"""Benchmark harness: one JSON line for the driver.

Headline metric (BASELINE.json): gauss n=2048 wall-clock, target = beat the
reference's best CPU result, OpenMP at 0.509428 s on a 72-core Xeon
(BASELINE.md "Gaussian elimination — parallel, internal input"). vs_baseline
is the speedup factor (baseline_seconds / our_seconds; > 1 means faster).

Measurement method: the TPU here sits behind a tunnel with ~70 ms RTT and
block_until_ready that can return early, so single-dispatch timing measures
the tunnel, not the chip. We time K-iteration chains (data-dependent, so XLA
cannot collapse them) fully on device for two values of K and take the slope
(t_K2 - t_K1) / (K2 - K1), which cancels the constant dispatch/fetch offset.
Each chained iteration is a full factor+solve of a fresh (perturbed) system.
"""

from __future__ import annotations

import json

import numpy as np

BASELINE_GAUSS_2048_S = 0.509428  # reference OpenMP best, node2x18a
N = 2048
K_SMALL, K_LARGE = 4, 16
ROUNDS = 5  # interleaved timing rounds per K (see _measure_slope)


def _chained_solver(a, b, k: int, panel: int):
    import jax
    import jax.numpy as jnp
    from jax import lax

    from gauss_tpu.core import blocked

    @jax.jit
    def run(x0):
        def body(_, x):
            # Data-dependent perturbation defeats CSE while keeping the
            # system well-conditioned (the internal matrix is SPD-like).
            a_i = a + x[0] * jnp.asarray(1e-6, a.dtype)
            fac = blocked.lu_factor_blocked_unrolled(a_i, panel=panel)
            return blocked.lu_solve(fac, b)

        x = lax.fori_loop(0, k, body, x0)
        return jnp.sum(x)  # scalar fetch: completion signal without bandwidth

    return run


def _measure_slope(a, b, panel: int) -> float:
    """Per-solve seconds via the two-chain slope, hardened against tunnel noise.

    Tunnel latency is noisy in epochs (cold compile caches, background
    transfers): a burst that lands on all of one K's reps but not the other's
    skews the slope badly (observed 20x once). Defense: compile and warm BOTH
    chains first, then INTERLEAVE the timed reps across several rounds so both
    K values sample the same epochs, and take the best (minimum) time per K —
    noise only ever adds time, so min is the right estimator.
    """
    from gauss_tpu.utils.timing import timed_fetch

    fns = {k: _chained_solver(a, b, k, panel) for k in (K_SMALL, K_LARGE)}
    for fn in fns.values():  # compile + settle before any timing (untimed)
        np.asarray(fn(b))
        np.asarray(fn(b))
    best = {k: float("inf") for k in fns}
    for _ in range(ROUNDS):
        for k, fn in fns.items():
            t, _ = timed_fetch(fn, b, warmup=0, reps=1)
            best[k] = min(best[k], t)
    slope = (best[K_LARGE] - best[K_SMALL]) / (K_LARGE - K_SMALL)
    if slope <= 0:
        # Noise swamped the slope. Fall back to the whole-chain mean, which
        # still includes the constant dispatch/fetch offset — a conservative
        # overestimate, never a fabricated speedup.
        return best[K_LARGE] / K_LARGE
    return slope


def main() -> None:
    import jax.numpy as jnp

    from gauss_tpu.core.blocked import solve_refined
    from gauss_tpu.io import synthetic
    from gauss_tpu.verify import checks

    a64 = synthetic.internal_matrix(N)
    b64 = synthetic.internal_rhs(N)
    a = jnp.asarray(a64, jnp.float32)
    b = jnp.asarray(b64, jnp.float32)
    # panel=256 beats 128 since the transposed panel kernel (2 full-tile
    # passes/step): fewer XLA glue steps now outweigh the extra VPU work.
    panel = 256

    per_solve = _measure_slope(a, b, panel)

    # Correctness gate: the refined solve must meet the 1e-4 residual bar.
    x, _ = solve_refined(a64, b64, panel=panel, iters=2)
    residual = checks.residual_norm(a64, x, b64)
    pattern_ok = checks.internal_pattern_ok(x, atol=1e-4)

    print(json.dumps({
        "metric": "gauss_n2048_wallclock",
        "value": round(per_solve, 6),
        "unit": "s",
        "vs_baseline": round(BASELINE_GAUSS_2048_S / per_solve, 2),
        "residual": float(f"{residual:.3e}"),
        "residual_ok": bool(residual < 1e-4),
        "pattern_ok": bool(pattern_ok),
        "baseline_s": BASELINE_GAUSS_2048_S,
        "method": (f"slope of K={K_SMALL} vs K={K_LARGE} on-device chains, "
                   f"interleaved best of {ROUNDS}"),
    }))


if __name__ == "__main__":
    import sys
    import traceback

    try:
        main()
    except Exception:
        # Transient tunnel/device failures have been observed; one retry
        # protects the driver's single once-per-round invocation.
        traceback.print_exc(file=sys.stderr)
        print("bench: transient failure, retrying once", file=sys.stderr)
        main()
