"""Benchmark harness: one JSON line for the driver.

Headline metric (BASELINE.json): gauss n=2048 wall-clock, target = beat the
reference's best CPU result, OpenMP at 0.509428 s on a 72-core Xeon
(BASELINE.md "Gaussian elimination — parallel, internal input"). vs_baseline
is the speedup factor (baseline_seconds / our_seconds; > 1 means faster).

Measurement method: the TPU here sits behind a tunnel with ~70 ms RTT and
block_until_ready that can return early, so single-dispatch timing measures
the tunnel, not the chip. We time K-iteration chains (data-dependent, so XLA
cannot collapse them) fully on device for two values of K and take the slope
(t_K2 - t_K1) / (K2 - K1), which cancels the constant dispatch/fetch offset.
Each chained iteration is a full factor+solve of a fresh (perturbed) system.
"""

from __future__ import annotations

import json

import numpy as np

BASELINE_GAUSS_2048_S = 0.509428  # reference OpenMP best, node2x18a
N = 2048


def _measure_slope(a, b, panel: int):
    """(per-solve seconds, k_small, k_large, is_slope) via the two-chain
    slope (see gauss_tpu.bench.slope for the method and its noise
    hardening); the K pair is the one actually measured after any
    jitter-floor escalation, and is_slope=False marks the chain-mean
    fallback (drives the FALLBACK method label below)."""
    from gauss_tpu.bench import slope

    make_chain, args = slope.gauss_chain(a, b, panel)
    return slope.measure_slope_info(make_chain, args)


def best_prior_headline() -> float | None:
    """Best (smallest) headline seconds across the committed BENCH_r*.json
    driver records, or None when none parse. The 49% r3->r4 swing went
    unnoticed because bench.py knew nothing of prior rounds (VERDICT r4
    next #8); the emitted "regression_vs_best" field makes any future swing
    loud in the one artifact the driver records."""
    import glob
    import os

    here = os.path.dirname(os.path.abspath(__file__))
    best = None
    for path in sorted(glob.glob(os.path.join(here, "BENCH_r*.json"))):
        try:
            with open(path) as f:
                value = (json.load(f).get("parsed") or {}).get("value")
        except (OSError, ValueError):
            continue
        if isinstance(value, (int, float)) and value > 0:
            best = value if best is None else min(best, value)
    return best


def best_prior_record() -> dict | None:
    """The full best-headline committed BENCH_r*.json record (the round
    behind :func:`best_prior_headline`'s value), preferring one that
    carries a ``phases_s`` breakdown — the prior side of the auto-
    attribution diff a failed ``--regress`` gate prints. None when no
    record parses."""
    import glob
    import os

    here = os.path.dirname(os.path.abspath(__file__))
    best = best_phased = None

    def _value(doc):
        v = (doc.get("parsed") or doc).get("value")
        return v if isinstance(v, (int, float)) and v > 0 else None

    for path in sorted(glob.glob(os.path.join(here, "BENCH_r*.json"))):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        v = _value(doc) if isinstance(doc, dict) else None
        if v is None:
            continue
        doc = dict(doc.get("parsed") or doc, _path=os.path.basename(path))
        if best is None or v < _value(best):
            best = doc
        if isinstance(doc.get("phases_s"), dict) and (
                best_phased is None or v < _value(best_phased)):
            best_phased = doc
    return best_phased or best


def main(metrics_out: str | None = None, tuned: bool = False,
         tune_compare: bool = False) -> dict:
    from gauss_tpu import obs

    with obs.run(metrics_out=metrics_out, tool="bench", n=N) as rec:
        return _bench(rec, tuned=tuned, tune_compare=tune_compare)


def _bench(rec, tuned: bool = False, tune_compare: bool = False) -> None:
    import jax.numpy as jnp

    from gauss_tpu import obs
    from gauss_tpu.core import blocked as _blocked
    from gauss_tpu.io import synthetic
    from gauss_tpu.tune import apply as tune_apply
    from gauss_tpu.utils.profiling import PhaseTimer
    from gauss_tpu.verify import checks

    pt = PhaseTimer()
    with pt.phase("prepare_inputs"):
        a64 = synthetic.internal_matrix(N)
        b64 = synthetic.internal_rhs(N)
        a = jnp.asarray(a64, jnp.float32)
        b = jnp.asarray(b64, jnp.float32)
    # panel=256 beats 128 since the transposed panel kernel (2 full-tile
    # passes/step): fewer XLA glue steps now outweigh the extra VPU work.
    # This is the headline's SEED config; --tuned swaps in the offline
    # sweep's winner for this hardware when a store exists (gauss_tpu.tune)
    # and --tune-compare measures both side by side.
    seed_panel = 256
    tuned_panel = tune_apply.override("lu_factor", N, "panel")
    tuned_panel = int(tuned_panel) if tuned_panel else None
    panel = (tuned_panel if (tuned or tune_compare) and tuned_panel
             else seed_panel)

    with pt.phase("headline_slope"):
        per_solve, k_small, k_large, is_slope = _measure_slope(a, b, panel)
    compare = None
    if tune_compare:
        if tuned_panel is None:
            compare = {"note": "no tuned store on disk — run gauss-tune "
                               "first; headline measured at the seed "
                               "config only"}
        else:
            with pt.phase("seed_slope"):
                seed_s, _, _, _ = _measure_slope(a, b, seed_panel)
            compare = {"seed_params": {"panel": seed_panel},
                       "seed_s": round(seed_s, 6),
                       "best_params": {"panel": tuned_panel},
                       "best_s": round(per_solve, 6),
                       "improvement": round(seed_s / per_solve, 4)}
    best_prior = best_prior_headline()

    # Correctness gate on EXACTLY the timed configuration (one f32 blocked
    # factor+solve, no refinement — it solves the internal system exactly;
    # solve_refined exists for systems that need the mixed-precision path).
    from gauss_tpu.bench.slope import gauss_solve_once

    with pt.phase("verify"):
        x = np.asarray(gauss_solve_once(a, b, panel), np.float64)
        residual = checks.residual_norm(a64, x, b64)
        pattern_ok = checks.internal_pattern_ok(x, atol=1e-4)
    obs.record_solve_health(a=a64, x=x, b=b64, backend="tpu",
                            pattern_ok=pattern_ok)

    from gauss_tpu.bench.slope import ROUNDS

    # The ds-refined chain alongside the happy-path headline (VERDICT r3
    # weak #7): the internal system is exact in one f32 solve (residual
    # 0.0), but a skeptic should also see the price of the full
    # mixed-precision configuration the external suite runs — measured
    # here, not quoted from an older sweep.
    from gauss_tpu.bench import slope as _slope
    from gauss_tpu.core import dsfloat

    with pt.phase("ds_stage"):
        at_ds = dsfloat.to_ds(a64.T)
        b_ds = dsfloat.to_ds(b64)
    with pt.phase("ds_verify"):
        x_ds = dsfloat.ds_to_f64(_slope.gauss_solve_once_ds(
            a, at_ds, b_ds, panel, dsfloat.DS_REFINE_STEPS))
        refined_residual = checks.residual_norm(a64, x_ds, b64)
    with pt.phase("refined_slope"):
        mk, ar = _slope.ds_solver_chain(a, at_ds, b_ds, panel,
                                        dsfloat.DS_REFINE_STEPS)
        refined_s, _, _, refined_is_slope = _slope.measure_slope_info(mk, ar)

    obs.emit("reported_time", name="gauss_n2048_wallclock",
             seconds=per_solve)
    record = {
        # Telemetry: the slope run's identity + its phase breakdown, so a
        # headline swing (the unexplained 49% r3->r4 move) is attributable
        # from the BENCH record alone — and, with --metrics-out, from the
        # full JSONL event stream keyed by the same run_id.
        "run_id": rec.run_id,
        "phases_s": {k: round(v, 6) for k, v in pt.seconds.items()},
        "metric": "gauss_n2048_wallclock",
        "value": round(per_solve, 6),
        "unit": "s",
        "vs_baseline": round(BASELINE_GAUSS_2048_S / per_solve, 2),
        "residual": float(f"{residual:.3e}"),
        "residual_ok": bool(residual < 1e-4),
        "pattern_ok": bool(pattern_ok),
        "baseline_s": BASELINE_GAUSS_2048_S,
        "method": ((f"slope of K={k_small} vs K={k_large} on-device chains, "
                    f"interleaved best of {ROUNDS}") if is_slope else
                   (f"FALLBACK chain mean at K={k_large} (slope delta never "
                    f"cleared the jitter floor; includes dispatch offset)")),
        "refined_value": round(refined_s, 6),
        "refined_residual": float(f"{refined_residual:.3e}"),
        "refined_method": (f"f32 factor + {dsfloat.DS_REFINE_STEPS} "
                           f"double-single on-device refinement steps, same "
                           f"slope protocol"
                           + ("" if refined_is_slope else " (FALLBACK mean)")),
        "refined_vs_baseline": round(BASELINE_GAUSS_2048_S / refined_s, 2),
        # > 1 means this round is SLOWER than the best committed round —
        # a value near 1.5 is a real regression, not jitter (the slope
        # protocol's round-to-round spread is ~±10%, see docs/REPORT).
        "regression_vs_best": (round(per_solve / best_prior, 3)
                               if best_prior else None),
        "best_prior_s": best_prior,
        "panel": panel,
        "tune_source": ("store" if panel == tuned_panel and tuned_panel
                        else "seed"),
        # PR-10 provenance: which reclaim machinery the measured route
        # actually engages on THIS backend/size. "fused" is the auto
        # resolution of the panel+trailing kernel (True on TPU while the
        # fused working set fits VMEM — kernels.panel_fused_pallas; always
        # False on CPU, where the plain path never routes through
        # interpret-mode kernels); "donated" is whether the one-shot solve
        # entry points donate the factor operand at this shape (they do
        # whenever n is a panel multiple — resolve_factor(donate=True)).
        "fused": bool(_blocked._use_fused("auto", N, panel,
                                          -(-N // panel) * panel)),
        "donated": bool(N % panel == 0),
        # ISSUE-11 provenance: the measured configuration's precision
        # axis, next to the PR-10 routing fields — the headline chain is
        # f32 storage with NO refinement (the internal system is exact in
        # one f32 solve), the refined leg runs DS_REFINE_STEPS
        # double-single rounds; mixed-precision epochs (the lowered path,
        # bench.throughput --dtype, grid --dtype cells) carry their own
        # dtype so history rows never mix precision classes silently.
        "dtype": "float32",
        "refine_steps": 0,
        "refined_steps": dsfloat.DS_REFINE_STEPS,
    }
    if compare is not None:
        record["tune_compare"] = compare
    print(json.dumps(record))
    return record


def tune_sweep_doc(record: dict) -> dict | None:
    """The regress-ingestable ``kind: tune_sweep`` doc from a
    --tune-compare run's record (None when the compare had no store)."""
    compare = record.get("tune_compare")
    if not compare or "best_s" not in compare:
        return None
    point = {"op": "gauss_headline", "n": N, "n_bucket": N,
             "dtype": "float32", "engine": "blocked",
             "key": f"gauss_headline/n{N}/float32/blocked",
             "candidates": 2, "pruned": 0, **compare}
    return {"kind": "tune_sweep", "ops": ["gauss_headline"], "ns": [N],
            "dtype": "float32", "engine": "blocked",
            "run_id": record.get("run_id"), "points": [point]}


if __name__ == "__main__":
    import argparse
    import sys
    import traceback

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--metrics-out", default=None, metavar="PATH",
                    help="append the run's telemetry (phase spans, health, "
                         "run id) as JSONL to PATH")
    ap.add_argument("--tuned", action="store_true",
                    help="measure the headline at the tuned store's "
                         "winning config for this hardware (gauss-tune) "
                         "instead of the hand-picked seed; no store -> "
                         "seed config, unchanged")
    ap.add_argument("--tune-compare", nargs="?", const="", default=None,
                    metavar="PATH",
                    help="measure tuned AND seed configs side by side; "
                         "optionally write the regress-ingestable "
                         "kind=tune_sweep summary to PATH")
    ap.add_argument("--regress", action="store_true",
                    help="after the run, gate the fresh headline against "
                         "reports/history.jsonl (obs.regress median + "
                         "epoch-noise band); exit 1 when out of band")
    ap.add_argument("--regress-history", default=None, metavar="PATH",
                    help="history file for --regress (default: the "
                         "committed reports/history.jsonl)")
    cli = ap.parse_args()
    kwargs = dict(metrics_out=cli.metrics_out, tuned=cli.tuned,
                  tune_compare=cli.tune_compare is not None)
    try:
        record = main(**kwargs)
    except Exception:
        # Transient tunnel/device failures have been observed; one retry
        # protects the driver's single once-per-round invocation.
        traceback.print_exc(file=sys.stderr)
        print("bench: transient failure, retrying once", file=sys.stderr)
        record = main(**kwargs)
    if cli.tune_compare is not None:
        doc = tune_sweep_doc(record)
        if doc is None:
            print("bench: --tune-compare had no tuned store to compare "
                  "against (run gauss-tune first)", file=sys.stderr)
        else:
            point = doc["points"][0]
            print(f"bench: tune-compare seed {point['seed_params']} "
                  f"{point['seed_s']:.6f} s vs tuned "
                  f"{point['best_params']} {point['best_s']:.6f} s "
                  f"({point['improvement']:.2f}x)", file=sys.stderr)
            if cli.tune_compare:
                with open(cli.tune_compare, "w") as f:
                    json.dump(doc, f, indent=1, sort_keys=True)
                    f.write("\n")
                print(f"bench: tune-compare summary -> {cli.tune_compare}",
                      file=sys.stderr)
    if cli.regress:
        from gauss_tpu.obs import regress

        history = regress.load_history(
            cli.regress_history or regress.default_history_path())
        verdicts = [regress.evaluate(record["metric"], record["value"],
                                     history)]
        # The hard ratchet: the fresh headline also gates against the
        # committed best-prior record (1.476 ms, BENCH_r03) — the median
        # band tolerates a slow NORM, the ratchet refuses one.
        ratchet = regress.evaluate_ratchet(record["metric"],
                                           record["value"])
        if ratchet is not None:
            verdicts.append(ratchet)
        if record.get("refined_value"):
            verdicts.append(regress.evaluate(
                f"{record['metric']}:refined", record["refined_value"],
                history))
            refined_ratchet = regress.evaluate_ratchet(
                f"{record['metric']}:refined", record["refined_value"])
            if refined_ratchet is not None:
                verdicts.append(refined_ratchet)
        print(regress.format_verdicts(verdicts), file=sys.stderr)
        if any(v["status"] == "out-of-band" for v in verdicts):
            # Auto-attribution (obs.doctor): before failing, diff this
            # run's phase breakdown against the best committed prior
            # epoch's and NAME the guilty phase — the triage the r3->r4
            # swing needed a manual bisection for. Prior records without
            # phases_s (pre-attribution rounds) degrade to printing the
            # fresh breakdown alone.
            prior = best_prior_record() or {}
            attribution = regress.attribute_phases(
                record.get("phases_s") or {}, prior.get("phases_s") or {},
                fresh_label="this run",
                prior_label=prior.get("_path", "best-prior"))
            if attribution:
                print("bench: gate FAILED — phase attribution vs "
                      f"{prior.get('_path', 'best prior')}:",
                      file=sys.stderr)
                print(attribution, file=sys.stderr)
            elif record.get("phases_s"):
                phases = sorted(record["phases_s"].items(),
                                key=lambda kv: -kv[1])
                print("bench: gate FAILED — best prior record has no "
                      "phases_s to diff against; this run's phases: "
                      + ", ".join(f"{k}={v:.6f}s" for k, v in phases),
                      file=sys.stderr)
            sys.exit(1)
