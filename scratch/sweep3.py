import sys; sys.path.insert(0, "/root/repo")
import numpy as np, jax, jax.numpy as jnp
from gauss_tpu.bench import slope
from gauss_tpu.io import synthetic

n = 2048
a = jnp.asarray(synthetic.internal_matrix(n), jnp.float32)
b = jnp.asarray(synthetic.internal_rhs(n), jnp.float32)
for panel in (128, 192, 256, 320):
    make, args = slope.gauss_chain(a, b, panel)
    print(f"panel={panel:4d}: {slope.measure_slope(make, args)*1e3:7.3f} ms")
