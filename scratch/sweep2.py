import sys; sys.path.insert(0, "/root/repo")
import numpy as np, jax, jax.numpy as jnp
from jax import lax
from gauss_tpu.bench import slope
from gauss_tpu.io import synthetic
from gauss_tpu.utils.timing import timed_fetch

n = 2048
a = jnp.asarray(synthetic.internal_matrix(n), jnp.float32)
b = jnp.asarray(synthetic.internal_rhs(n), jnp.float32)
make, args = slope.gauss_chain(a, b, 256)
print(f"factor+solve n=2048: {slope.measure_slope(make, args)*1e3:7.3f} ms")
