import sys; sys.path.insert(0, "/root/repo")
"""Ablation: factor cost breakdown (panel kernel / permute / trisolve / GEMM)."""
import numpy as np, jax, jax.numpy as jnp
from jax import lax
from functools import partial
from gauss_tpu.core import blocked
from gauss_tpu.kernels.panel_pallas import panel_factor_pallas
from gauss_tpu.io import synthetic
from gauss_tpu.utils.timing import timed_fetch
from gauss_tpu.kernels.matmul_pallas import resolve_precision

n, panel = 2048, 256
a64, b64 = synthetic.internal_matrix(n), synthetic.internal_rhs(n)
A = jnp.asarray(a64, jnp.float32)
B = jnp.asarray(b64, jnp.float32)

def factor_ablate(a, *, do_perm=True, do_tri=True, do_gemm=True, do_solve=False):
    m = a
    npad = m.shape[0]
    dtype = m.dtype
    perm = jnp.arange(npad)
    gemm_prec = resolve_precision("highest")
    for kb in range(0, npad, panel):
        tail = npad - kb
        p = m[kb:, kb:kb + panel]
        p, ipiv, perm_local, mp = panel_factor_pallas(p, 0)
        if do_perm:
            live = m[kb:][perm_local]
            perm = perm.at[kb:].set(perm[kb:][perm_local])
        else:
            live = m[kb:]
        live = live.at[:, kb:kb + panel].set(p)
        if kb + panel < npad:
            l11 = live[:panel, kb:kb + panel]
            if do_tri:
                u12 = lax.linalg.triangular_solve(
                    l11, live[:panel, kb + panel:],
                    left_side=True, lower=True, unit_diagonal=True)
                live = live.at[:panel, kb + panel:].set(u12)
            else:
                u12 = live[:panel, kb + panel:]
            if do_gemm:
                l21 = live[panel:, kb:kb + panel]
                trail = live[panel:, kb + panel:]
                live = live.at[panel:, kb + panel:].set(
                    trail - jnp.dot(l21, u12, precision=gemm_prec))
        m = m.at[kb:].set(live)
    if do_solve:
        fac = blocked.BlockedLU(m=m, perm=perm, min_abs_pivot=jnp.asarray(1.0, dtype))
        return blocked.lu_solve(fac, B)
    return m[:, 0]

def chain(k, **kw):
    @jax.jit
    def run(a, x0):
        def body(_, x):
            a_i = a + x[0] * jnp.asarray(1e-6, a.dtype)
            return factor_ablate(a_i, **kw)[:x0.shape[0]]
        x = lax.fori_loop(0, k, body, x0)
        return jnp.sum(x)
    return run

def slope(**kw):
    fns = {k: chain(k, **kw) for k in (3, 11)}
    x0 = B
    for f in fns.values():
        np.asarray(f(A, x0)); np.asarray(f(A, x0))
    best = {k: float("inf") for k in fns}
    for _ in range(4):
        for k, f in fns.items():
            t,_ = timed_fetch(f, A, x0, warmup=0, reps=1)
            best[k] = min(best[k], t)
    return (best[11]-best[3])/8

full = slope(do_perm=True, do_tri=True, do_gemm=True, do_solve=True)
fac  = slope(do_perm=True, do_tri=True, do_gemm=True)
noperm = slope(do_perm=False, do_tri=True, do_gemm=True)
notri = slope(do_perm=True, do_tri=False, do_gemm=True)
nogemm = slope(do_perm=True, do_tri=True, do_gemm=False)
kern_only = slope(do_perm=False, do_tri=False, do_gemm=False)
print(f"full factor+solve {full*1e3:7.3f} ms")
print(f"factor only       {fac*1e3:7.3f} ms  (solve = {(full-fac)*1e3:.3f})")
print(f"  no permute      {noperm*1e3:7.3f} ms  (permute = {(fac-noperm)*1e3:.3f})")
print(f"  no trisolve     {notri*1e3:7.3f} ms  (trisolve = {(fac-notri)*1e3:.3f})")
print(f"  no gemm         {nogemm*1e3:7.3f} ms  (gemm = {(fac-nogemm)*1e3:.3f})")
print(f"  kernels only    {kern_only*1e3:7.3f} ms")
