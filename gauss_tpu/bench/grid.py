"""The benchmark grid: the reference reports' timing tables, regenerated.

The reference's evaluation is three grids (BASELINE.md): gauss internal-input
over n in {128..2048} x engines, gauss external-input over the dataset library
x engines, and matmul over n in {1001, 1024, 2001, 2048} x engines. This
module sweeps the same axes over this framework's backends and prints
BASELINE.md-format markdown tables with a vs-reference column, plus optional
machine-readable JSON.

Usage::

    python -m gauss_tpu.bench.grid --suite gauss-internal \
        --keys 512,1024,2048 --backends tpu,seq,omp --json out.json

Timing semantics per suite match the corresponding reference program
(see gauss_tpu/cli/_common.py docstring); every cell is verified (residual /
manufactured-solution error / epsilon comparator) before it is reported —
an unverified time is printed as FAILED, never as a number.
"""

from __future__ import annotations

import argparse
import json
import sys
from dataclasses import asdict, dataclass, replace
from typing import List, Optional, Sequence

import numpy as np

from gauss_tpu import obs
from gauss_tpu.bench import baselines
from gauss_tpu.cli import _common
from gauss_tpu.verify import checks

SUITES = ("gauss-internal", "gauss-external", "matmul")
# The distributed suite is opt-in (not part of --suite all): it sweeps the
# SHARD count over a forced virtual CPU mesh — scaling shape + correctness,
# explicitly NOT an ICI measurement (VERDICT round 1 #7).
EXTRA_SUITES = ("gauss-dist",)
RESIDUAL_BAR = 1e-4  # BASELINE.json acceptance bar

DIST_BACKENDS = ("tpu-dist", "tpu-dist2d", "tpu-dist-blocked",
                 "tpu-dist-blocked2d")
DIST_SHARD_SWEEP = (2, 4, 8)   # reference sweep is mpirun -np {2,16,32,70}
DIST_NOTE = "virtual CPU mesh (scaling shape + correctness; NOT ICI)"
# --dist-device default: build dist meshes from jax.devices() instead of
# the forced CPU pool — a 1-chip mesh on the real TPU proves the shard_map
# programs lower and run on actual TPU hardware, not only under the CPU
# backend (VERDICT r4 next #7; the reference validated MPI on its real
# cluster, OpenMP_and_MPI/README.txt:39-48). Wall-clock here includes the
# ~0.1-0.7 s tunnel dispatch span, so these cells prove lowering +
# verification, not per-op speed (the note says which device ran).
DIST_DEVICE = "cpu"

#: --dtype: the gauss device-span cells' storage dtype (ISSUE 11 — the
#: lowered bf16/bf16x3 paths refined back to the 1e-4 bar). Module-global
#: like DIST_DEVICE; "float32" is the pre-existing path exactly.
GRID_DTYPE = "float32"


@dataclass
class Cell:
    suite: str
    key: str          # size or dataset name
    backend: str
    seconds: float
    verified: bool
    error: float      # residual (internal) / max rel error (external) / max abs diff (matmul)
    reference_s: Optional[float]
    span: str = "reference"   # "reference" parity span or "device" slope span
    note: str = ""            # provenance, e.g. external dataset source
    #: storage dtype of the timed configuration (the --dtype column):
    #: rides into the JSON cells, the obs ``cell`` events, and the
    #: history metric name (obs.regress._cell_metric appends "@<dtype>"
    #: for lowered cells), so mixed-precision epochs are distinguishable
    #: in history.jsonl and can never pollute an f32 baseline.
    dtype: str = "float32"

    @property
    def speedup(self) -> Optional[float]:
        if self.reference_s is None or self.seconds <= 0:
            return None
        return self.reference_s / self.seconds


def _prep_gauss_internal(n: int):
    import time

    from gauss_tpu.io import synthetic

    t0 = time.perf_counter()
    a, b = synthetic.internal_matrix(n), synthetic.internal_rhs(n)
    return a, b, time.perf_counter() - t0


def _gauss_device_cell(a64, b64, refine_steps: int, backend: str = "tpu"):
    """Slope-timed per-solve seconds for a device gauss engine (operands
    device-resident, dispatch/fetch offset cancelled; see bench.slope),
    plus the float64 solution of EXACTLY the timed configuration — the
    cell's verification must check what the slope measured, not some other
    (e.g. host-refined) solve."""
    import jax.numpy as jnp

    from gauss_tpu.bench import slope
    from gauss_tpu.core.blocked import auto_panel

    a = jnp.asarray(a64, jnp.float32)
    b = jnp.asarray(b64, jnp.float32)
    if backend == "tpu-rowelim":
        # The batched form (k pivot steps per launch) — same pivoting and
        # verification as the per-step kernel, n/k matrix passes instead of
        # n (VERDICT r1 #5: the per-step form is HBM-bound at 62 ms/2048).
        from gauss_tpu.kernels.rowelim_pallas import \
            gauss_solve_rowelim_batched

        solve_once = gauss_solve_rowelim_batched
    elif backend == "tpu-rowelim-step":
        from gauss_tpu.kernels.rowelim_pallas import gauss_solve_rowelim

        solve_once = gauss_solve_rowelim
    elif backend == "jax-linalg":
        import jax.scipy.linalg as jsl

        def solve_once(a_, b_):
            return jsl.solve(a_, b_)
    else:
        panel = auto_panel(a.shape[0])

        def solve_once(a_, b_):
            return slope.gauss_solve_once(a_, b_, panel, refine_steps)

    x = np.asarray(solve_once(a, b), np.float64)
    make_chain, args = slope.solver_chain(a, b, solve_once)
    return slope.measure_slope(make_chain, args), x


def _gauss_device_cell_ds(a64, b64, refine_steps: int | None = None,
                          gemm_precision: str = "highest",
                          factor_dtype: str | None = None):
    """Device-span external cell: f32 factor + double-single on-device
    refinement (core.dsfloat), slope-timed; returns
    (seconds, x_float64, (k_small, k_large, is_slope)) of exactly the timed
    configuration. The single measurement recipe shared with
    bench.precision — the K policy must not fork.

    ``factor_dtype``: the --dtype column — a lowered storage name
    ("bfloat16" / "bf16x3", core.lowered) threads through the SAME timed
    chain (dsfloat.solve_once_ds casts the factor operand / swaps the
    split-GEMM), so a lowered cell is measured and verified under the
    identical slope protocol as the f32 ones."""
    import jax.numpy as jnp

    from gauss_tpu.bench import slope
    from gauss_tpu.core import dsfloat
    from gauss_tpu.core.blocked import auto_panel

    if refine_steps is None:
        refine_steps = dsfloat.DS_REFINE_STEPS
    a64 = np.asarray(a64, np.float64)
    a = jnp.asarray(a64, jnp.float32)
    at_ds = dsfloat.to_ds(a64.T)
    b_ds = dsfloat.to_ds(b64)
    n = a.shape[0]
    panel = auto_panel(n)
    x = dsfloat.ds_to_f64(
        slope.gauss_solve_once_ds(a, at_ds, b_ds, panel, refine_steps,
                                  gemm_precision=gemm_precision,
                                  factor_dtype=factor_dtype))
    make_chain, args = slope.ds_solver_chain(a, at_ds, b_ds, panel,
                                             refine_steps,
                                             gemm_precision=gemm_precision,
                                             factor_dtype=factor_dtype)
    # Very large systems: per-solve seconds dwarf the jitter floor, so a
    # K=(1,2) pair keeps full slope validity while holding the chain's
    # compile payload and run count down (the memplus lesson, r2 -> r3).
    # With only one (K1, K2) pair a single outlier run would contaminate
    # the slope directly, so the interleaved rounds count rises to keep
    # per-K minima meaningful (ADVICE r3: cheap relative to per-solve
    # seconds at this size).
    if n >= 8192:
        ks, kl, rounds = 1, 2, 2 * slope.ROUNDS
    else:
        ks, kl, rounds = slope.K_SMALL, slope.K_LARGE, slope.ROUNDS
    seconds, ks, kl, is_slope = slope.measure_slope_info(
        make_chain, args, k_small=ks, k_large=kl, rounds=rounds)
    return seconds, x, (ks, kl, is_slope)


# Per-suite device-span eligibility. tpu-rowelim has no refinement path
# (nothing to reuse across solves), so it cannot meet the external suite's
# 1e-4 bar in f32 and is internal-only there. "jax-linalg" is the
# stock-library baseline column (VERDICT r3 next #4: jax.scipy.linalg.solve,
# slope-timed with the identical chain) — the framework must beat the
# library it could have been a thin wrapper over, not just a 2022 Xeon.
DEVICE_SPAN_GAUSS = ("tpu", "tpu-rowelim", "tpu-rowelim-step", "jax-linalg")
DEVICE_SPAN_GAUSS_EXTERNAL = ("tpu",)
# tpu-dist rides the device span too (VERDICT r3 missing #2: no dist-matmul
# device cell existed): on the single-chip bench it runs the sharded
# program over a 1-device mesh — the capability and its dispatch overhead,
# honestly labeled by the backend name.
DEVICE_SPAN_MATMUL = ("tpu", "tpu-pallas", "tpu-pallas-v1", "tpu-dist")


def _no_device_span_notice(suite, key, backend, reason):
    print(f"bench-grid: {suite}/{key}/{backend}: {reason}; cell keeps the "
          f"reference span", file=sys.stderr)


def _run_gauss_internal(ctx, n: int, backend: str, nthreads: int,
                        span: str = "reference") -> Cell:
    # Reference "Application time" = init + elimination
    # (gauss_internal_input.c:278-290); init is measured once in prep and
    # charged to every backend's cell so the vs-reference column compares
    # like spans.
    a, b, init_s = ctx
    if backend == "jax-linalg" and span != "device":
        raise ValueError("jax-linalg is a device-span-only baseline column "
                         "(stock jax.scipy.linalg.solve, slope-timed); run "
                         "with --span device")
    if (span == "device" and backend.startswith("tpu")
            and backend not in DEVICE_SPAN_GAUSS):
        _no_device_span_notice("gauss-internal", n, backend,
                               "no device-span implementation")
    if span == "device" and backend in DEVICE_SPAN_GAUSS:
        if GRID_DTYPE != "float32" and backend == "tpu":
            # The --dtype column: the lowered factor (bf16 storage /
            # bf16x3 split-GEMM) is NOT exact on the internal system the
            # way f32 is, so the timed chain includes the double-single
            # refinement that brings it back to the bar — the honest
            # price of the lowered configuration, slope-timed and
            # verified as one unit.
            seconds, x_dev, _ = _gauss_device_cell_ds(
                a, b, factor_dtype=GRID_DTYPE)
            res_dev = checks.residual_norm(a, x_dev, b)
            return Cell("gauss-internal", str(n), backend, seconds,
                        res_dev < RESIDUAL_BAR, res_dev,
                        baselines.reference_seconds("gauss-internal", n,
                                                    backend),
                        span="device", dtype=GRID_DTYPE)
        # The internal system solves exactly in one f32 factor+solve
        # (measured residual 0.0 at every reference size), so the timed
        # chain runs no refinement — and is verified as-is. The
        # reference-span solve is skipped entirely; the device cell
        # verifies its own configuration.
        seconds, x_dev = _gauss_device_cell(a, b, refine_steps=0,
                                            backend=backend)
        res_dev = checks.residual_norm(a, x_dev, b)
        return Cell("gauss-internal", str(n), backend, seconds,
                    res_dev < RESIDUAL_BAR, res_dev,
                    baselines.reference_seconds("gauss-internal", n, backend),
                    span="device")
    # refine_iters=2: the internal synthetic system solves exactly in one
    # f32 factor+solve (measured residual 0.0 at every reference size), so
    # the tol exits refinement immediately — the default budget of 8 would
    # route through the fixed-iteration ds chain and pay 8 pointless
    # on-device iterations per solve (measured 2x on this column). The
    # external suite keeps the big budget; its matrices need it.
    x, elapsed = _common.solve_with_backend(a, b, backend, nthreads=nthreads,
                                            refine_iters=2)
    res = checks.residual_norm(a, x, b)  # absolute, the BASELINE.json bar
    return Cell("gauss-internal", str(n), backend, init_s + elapsed,
                res < RESIDUAL_BAR, res,
                baselines.reference_seconds("gauss-internal", n, backend))


def _prep_gauss_external(name: str):
    from gauss_tpu.io import datasets

    # The REAL reference matrix when a checkout is present — the reference's
    # external tables (BASELINE.md) are defined on these exact files, so only
    # then is the vs-reference column apples-to-apples. Falls back to the
    # deterministic stand-in elsewhere; every cell records which one ran.
    source = datasets.resolve_source(name, "auto")
    a = datasets.dataset_dense(name, source=source)
    x_true = np.arange(1, a.shape[0] + 1, dtype=np.float64)  # X__[i] = i+1
    return a, a @ x_true, x_true, source                     # R = A . X__


def _run_gauss_external(ctx, name: str, backend: str, nthreads: int,
                        span: str = "reference") -> Cell:
    a, b, x_true, source = ctx
    note = f"source={source}"
    if backend == "jax-linalg":
        raise ValueError("the jax-linalg baseline column exists only in the "
                         "gauss-internal suite (it has no refinement path "
                         "for the external suite's 1e-4 bar)")
    if (span == "device" and backend.startswith("tpu")
            and backend not in DEVICE_SPAN_GAUSS_EXTERNAL):
        _no_device_span_notice(
            "gauss-external", name, backend,
            "no device span for this suite" + (
                " (no refinement path, cannot meet the 1e-4 bar)"
                if backend in DEVICE_SPAN_GAUSS else ""))
    if span == "device" and backend in DEVICE_SPAN_GAUSS_EXTERNAL:
        # External datasets need on-device refinement to meet the 1e-4 bar;
        # residuals run in double-single (two-float32) so even the
        # ill-conditioned real matrices (saylr4, memplus) converge fully on
        # device — plain f32 residuals floor at ~1e-7 relative and fail them
        # (VERDICT round 1 weak #2). The timed chain includes the refinement
        # steps, and the cell verifies that exact configuration — no
        # reference-span solve runs.
        fdt = None if GRID_DTYPE == "float32" else GRID_DTYPE
        seconds, x_dev, _ = _gauss_device_cell_ds(a, b, factor_dtype=fdt)
        err_dev = checks.max_rel_error(x_dev, x_true)
        return Cell("gauss-external", name, backend, seconds,
                    err_dev < RESIDUAL_BAR, err_dev,
                    baselines.reference_seconds("gauss-external", name,
                                                backend), span="device",
                    note=note, dtype=GRID_DTYPE)
    # The external flavor's policy is partial pivoting
    # (gauss_external_input.c:125-150) on EVERY backend — without the
    # explicit argument, resolve_pivoting would hand tpu-unblocked the
    # internal flavor's swap-on-zero default, which blows up on the real
    # ill-conditioned matrices.
    x, elapsed = _common.solve_with_backend(a, b, backend, nthreads=nthreads,
                                            pivoting="partial")
    err = checks.max_rel_error(x, x_true)
    return Cell("gauss-external", name, backend, elapsed,
                err < RESIDUAL_BAR, err,
                baselines.reference_seconds("gauss-external", name, backend),
                note=note)


# Above this size the full float64 host truth is unaffordable on the bench
# host (n=16384 is ~9e12 FLOPs on the single visible core — hours); cells
# verify against an exact truth on a fixed seeded row sample instead, and
# only the device span is offered (the reference span would also time a
# multi-GB D2H fetch through the tunnel). The sample is labeled in the
# cell note — a partially-verified cell must say so.
MATMUL_SAMPLE_N = 12288
MATMUL_SAMPLE_ROWS = 64


def _prep_matmul(n: int):
    from gauss_tpu.cli.matmul import _inputs

    a, b = _inputs(n)
    if n >= MATMUL_SAMPLE_N:
        rng = np.random.default_rng(n)
        rows = np.sort(rng.choice(n, size=MATMUL_SAMPLE_ROWS,
                                  replace=False))
        truth = a[rows] @ b  # exact f64 truth on the sampled rows
        return a, b, truth, float(np.abs(truth).max()), rows
    truth = a @ b  # float64 host truth, computed once per size
    return a, b, truth, float(np.abs(truth).max()), None


def _matmul_device_seconds(a64, b64, backend: str) -> float:
    import jax.numpy as jnp

    from gauss_tpu.bench import slope

    if backend == "tpu-dist":
        # The one-shot engine stages host operands per call (device_put),
        # which cannot appear inside the traced K-chain; the staged form
        # shards once and chains the pure sharded dot.
        from gauss_tpu.dist.matmul_dist import matmul_dist_staged

        a_dev, b_dev, c0, mm = matmul_dist_staged(
            np.asarray(a64, np.float32), np.asarray(b64, np.float32))
        make_chain, args = slope.matmul_chain(a_dev, b_dev, mm, c0=c0)
        return slope.measure_slope(make_chain, args)

    from gauss_tpu.cli.matmul import _tpu_engine_fn

    a = jnp.asarray(a64, jnp.float32)
    b = jnp.asarray(b64, jnp.float32)
    make_chain, args = slope.matmul_chain(a, b, _tpu_engine_fn(backend))
    return slope.measure_slope(make_chain, args)


def _run_matmul(ctx, n: int, backend: str, nthreads: int,
                span: str = "reference") -> Cell:
    from gauss_tpu.cli.matmul import _run_native, _run_tpu

    a, b, truth, scale, rows = ctx
    if rows is not None:
        # Sampled-verification regime (n >= MATMUL_SAMPLE_N): device span
        # only — the engine's full product stays on device; only the
        # sampled rows are fetched for the comparator.
        import jax.numpy as jnp

        from gauss_tpu.cli.matmul import _tpu_engine_fn

        if span != "device" or backend not in DEVICE_SPAN_MATMUL:
            raise ValueError(
                f"n={n} >= {MATMUL_SAMPLE_N} verifies on a "
                f"{MATMUL_SAMPLE_ROWS}-row sample and offers only the "
                f"device span for device engines {DEVICE_SPAN_MATMUL}; "
                f"got span={span!r} backend={backend!r}")
        mm = _tpu_engine_fn(backend)
        c = mm(jnp.asarray(a, jnp.float32), jnp.asarray(b, jnp.float32))
        c_rows = np.asarray(c[jnp.asarray(rows)], np.float64)
        del c
        diff = float(np.max(np.abs(c_rows - truth))) / scale
        return Cell("matmul", str(n), backend,
                    _matmul_device_seconds(a, b, backend),
                    diff <= checks.EPSILON, diff,
                    baselines.reference_seconds("matmul", n, backend),
                    span="device",
                    note=f"verify={MATMUL_SAMPLE_ROWS}-row sample")
    if backend.startswith("tpu"):
        c, elapsed = _run_tpu(a, b, backend)
    else:
        c, elapsed = _run_native(a, b, backend, nthreads)
    diff = float(np.max(np.abs(c - truth))) / scale
    if (span == "device" and backend.startswith("tpu")
            and backend not in DEVICE_SPAN_MATMUL):
        _no_device_span_notice("matmul", n, backend,
                               "no device-span implementation")
    if span == "device" and backend in DEVICE_SPAN_MATMUL:
        return Cell("matmul", str(n), backend,
                    _matmul_device_seconds(a, b, backend),
                    diff <= checks.EPSILON, diff,
                    baselines.reference_seconds("matmul", n, backend),
                    span="device")
    return Cell("matmul", str(n), backend, elapsed,
                diff <= checks.EPSILON, diff,
                baselines.reference_seconds("matmul", n, backend))


def _cpu_mesh_devices(k: int):
    """k virtual CPU devices for the distributed suite, independent of the
    default platform (the tunneled single TPU cannot host a shard sweep)."""
    from gauss_tpu.utils.env import force_host_device_count

    flag_ok = force_host_device_count(k)
    import jax

    devs = list(jax.devices("cpu"))
    if len(devs) < k:
        hint = ("a pre-existing XLA_FLAGS --xla_force_host_platform_"
                "device_count requests fewer devices" if not flag_ok else
                "the CPU backend initialized before the forced device count "
                "could apply — run --suite gauss-dist in its own process")
        raise RuntimeError(f"need {k} CPU devices, have {len(devs)}; {hint}")
    return devs[:k]


def _prep_gauss_dist(n: int):
    from gauss_tpu.io import synthetic

    a64 = synthetic.internal_matrix(n)
    b64 = synthetic.internal_rhs(n)
    return a64.astype(np.float32), b64.astype(np.float32), a64, b64


def _run_gauss_dist(ctx, n: int, backend: str, shards: int,
                    span: str = "reference") -> Cell:
    """One (size, engine, shard-count) cell on the virtual CPU mesh.

    Timing is plain best-of-3 wall-clock around solve+fetch with staging
    outside the span (no tunnel between host and the CPU mesh, so the slope
    method is unnecessary); every cell verifies the 1e-4 residual bar. The
    reference comparator is the best Distributed-MPI cell for the size
    (BASELINE.md node01-06 table) — different hardware on both sides, kept
    only to anchor the scale."""
    from gauss_tpu.utils.timing import timed_fetch

    a32, b32, a64, b64 = ctx
    shards = shards or DIST_SHARD_SWEEP[-1]
    if DIST_DEVICE == "default":
        import jax

        devs = list(jax.devices())
        if len(devs) < shards:
            raise RuntimeError(
                f"--dist-device default: need {shards} devices, have "
                f"{len(devs)} on platform {devs[0].platform}; pass -t "
                f"{len(devs)} (a 1-chip mesh still proves real-TPU lowering)")
        devs = devs[:shards]
        note = (f"real {devs[0].platform} mesh={shards} (lowering + "
                f"verification; span includes tunnel dispatch)")
    else:
        devs = _cpu_mesh_devices(shards)
        note = DIST_NOTE
    if backend == "tpu-dist":
        from gauss_tpu.dist import gauss_dist as eng
        from gauss_tpu.dist.mesh import make_mesh

        mesh = make_mesh(shards, devices=devs)
        staged = eng.prepare_dist(a32, b32, mesh)
        solve = lambda: eng.solve_dist_staged(staged, mesh)  # noqa: E731
    elif backend == "tpu-dist2d":
        from gauss_tpu.dist import gauss_dist2d as eng
        from gauss_tpu.dist.mesh import make_mesh_2d_auto

        mesh = make_mesh_2d_auto(shards, devices=devs)
        staged = eng.prepare_dist2d(a32, b32, mesh)
        solve = lambda: eng.solve_dist2d_staged(staged, mesh)  # noqa: E731
    elif backend == "tpu-dist-blocked":
        from gauss_tpu.dist import gauss_dist_blocked as eng
        from gauss_tpu.dist.mesh import make_mesh

        mesh = make_mesh(shards, devices=devs)
        staged = eng.prepare_dist_blocked(a32, b32, mesh)
        solve = lambda: eng.solve_dist_blocked_staged(staged, mesh)  # noqa: E731
    elif backend == "tpu-dist-blocked2d":
        from gauss_tpu.dist import gauss_dist_blocked2d as eng
        from gauss_tpu.dist.mesh import make_mesh_2d_auto

        mesh = make_mesh_2d_auto(shards, devices=devs)
        staged = eng.prepare_dist_blocked2d(a32, b32, mesh)
        solve = lambda: eng.solve_dist_blocked2d_staged(staged, mesh)  # noqa: E731
    else:
        raise ValueError(f"backend {backend!r} is not a distributed engine; "
                         f"options: {DIST_BACKENDS}")
    seconds, x = timed_fetch(solve, warmup=1, reps=3)
    res = checks.residual_norm(a64, np.asarray(x, np.float64), b64)
    return Cell("gauss-dist", str(n), backend, seconds, res < RESIDUAL_BAR,
                res, baselines.reference_seconds("gauss-dist", n, backend),
                note=note)


_SUITE_FNS = {
    "gauss-internal": (_prep_gauss_internal, _run_gauss_internal),
    "gauss-external": (_prep_gauss_external, _run_gauss_external),
    "matmul": (_prep_matmul, _run_matmul),
    "gauss-dist": (_prep_gauss_dist, _run_gauss_dist),
}

# Which backends actually get the device slope span per suite — used both to
# run cells and to label FAILED cells, so a failed device-span cell renders
# in the marked [device-span] column, never the unmarked reference column.
_DEVICE_ELIGIBLE = {
    "gauss-internal": DEVICE_SPAN_GAUSS,
    "gauss-external": DEVICE_SPAN_GAUSS_EXTERNAL,
    "matmul": DEVICE_SPAN_MATMUL,
    "gauss-dist": (),  # CPU-mesh wall-clock; slope spans do not apply
}


def _cell_span(suite: str, backend: str, span: str) -> str:
    return ("device" if span == "device"
            and backend in _DEVICE_ELIGIBLE[suite] else "reference")


def _failure_note(stage: str, e: Exception, limit: int = 500) -> str:
    """One-line provenance for a FAILED cell: exception type + (truncated)
    message. Cells are the only artifact a later reader has; 'seconds 0.0,
    verified false, error null' with no cause is undiagnosable. Terminal
    escape codes and trailing device-daemon log lines (timestamped) are
    stripped — they bloat the note with noise that renders as garbage in
    the REPORT tables."""
    import re

    msg = " ".join(str(e).split())
    msg = re.sub(r"\x1b\[[0-9;]*[A-Za-z]", "", msg)  # any CSI, not just SGR
    # Remote-compile failures bury the actionable cause ("Ran out of
    # memory...") inside timestamped daemon log lines; keep the head plus
    # the salient error fragment and drop the transport noise between. If
    # no fragment looks salient, keep the tail — dropping it could discard
    # the cause (truncation below bounds the size either way).
    parts = re.split(r"\s\d{4}-\d{2}-\d{2}T\d{2}:\d{2}:\d{2}\S*\s", msg)
    if len(parts) > 1:
        salient = [p for p in parts[1:]
                   if re.search(r"error|Error|out of memory|OOM", p)]
        # ALL salient fragments, not just the longest: a second,
        # complementary cause in a different post-timestamp part (or one
        # phrased without these markers) must survive into the note
        # (ADVICE r4 #3); the truncation below bounds the size.
        frag = " | ".join(salient) if salient else " ".join(parts[1:])
        frag = re.sub(r"^\s*\[?\w*ERROR\]?\s*", "", frag)
        msg = f"{parts[0]} | {frag}"
    if len(msg) > limit:
        msg = msg[:limit] + "..."
    return f"{stage}: {type(e).__name__}: {msg}"


def _infra_retryable(e: Exception) -> bool:
    """Is this failure INFRA-class — transport/daemon/device-runtime noise
    rather than a deterministic bug? The classifier keys on the same
    signals :func:`_failure_note` already strips for readability:
    timestamped device-daemon log lines buried in the message, plus the
    canonical gRPC/runtime markers (UNAVAILABLE, DEADLINE_EXCEEDED, socket
    resets, tunnel drops). Shape/value/assertion failures replay the same
    bug on a retry and are never classified infra."""
    import re

    if isinstance(e, (ValueError, TypeError, AssertionError)):
        return False
    msg = " ".join(str(e).split())
    if re.search(r"\d{4}-\d{2}-\d{2}T\d{2}:\d{2}:\d{2}", msg):
        return True  # device-daemon log lines ride only transport failures
    return bool(re.search(
        r"UNAVAILABLE|DEADLINE_EXCEEDED|ABORTED|Socket closed"
        r"|[Cc]onnection (?:reset|refused|closed|aborted)"
        r"|tunnel|[Hh]eartbeat", msg))


def _utc_stamp() -> str:
    import datetime

    return datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%SZ")


def _ctx_note(suite: str, ctx) -> str:
    """Provenance note carried by every cell of a prepared key — including
    cells whose run() later fails (the source is known the moment prep
    succeeds)."""
    return f"source={ctx[3]}" if suite == "gauss-external" else ""


def _is_device_backend(backend: str) -> bool:
    """Backends whose parallelism is the device/mesh, not a thread pool —
    they have no thread axis. Includes the stock-library baseline column
    (jax-linalg), which is device-resident but not tpu-prefixed."""
    return backend.startswith("tpu") or backend == "jax-linalg"


def _sweep_skip(suite: str, backend: str, t, sweep) -> bool:
    """Device engines have no thread axis (the mesh, not a thread pool, is
    their parallelism): in a thread sweep they run once, at the first entry.
    EXCEPT in the distributed suite, where the sweep axis IS the mesh's
    shard count."""
    if suite == "gauss-dist":
        return False
    return t is not None and _is_device_backend(backend) and t != sweep[0]


def _sweep_label(suite: str, key, backend: str, t) -> str:
    """Cell key within a sweep; device engines keep the bare size so scaling
    fits and tables stay honest, and distributed cells key on shards."""
    if suite == "gauss-dist":
        return f"{key} @{t}sh" if t is not None else str(key)
    return (str(key) if t is None or _is_device_backend(backend)
            else f"{key} @{t}t")


def run_suite(suite: str, keys: Sequence, backends: Sequence[str],
              nthreads: int = 0, span: str = "reference",
              thread_sweep: Optional[Sequence[int]] = None) -> List[Cell]:
    """Run one grid; returns the verified/timed cells in sweep order.

    Inputs (and the host truth) are prepared once per key and shared across
    the backend sweep — at n=2048 the float64 truth product alone is worth
    not recomputing per backend.

    ``thread_sweep``: the reference reports' second axis — each of its main
    tables sweeps the thread/rank count at fixed n (BASELINE.md "parallel,
    internal input" columns 1-72 t). When given, every (key, backend) cell
    is run once per thread count, keyed "<key> @<T>t". Device engines ignore
    the thread count (the mesh, not a thread pool, is their parallelism), so
    they are swept only once, at the first entry.
    """
    if suite not in SUITES + EXTRA_SUITES:
        raise ValueError(f"unknown suite {suite!r}; options: "
                         f"{SUITES + EXTRA_SUITES}")
    if span not in ("reference", "device"):
        raise ValueError(f"unknown span {span!r}; options: "
                         "('reference', 'device')")
    prep, run = _SUITE_FNS[suite]
    if suite == "gauss-dist":
        if not thread_sweep:
            # An explicit -t is honored as a single-point sweep (as the
            # other suites honor it); otherwise the default shard sweep.
            thread_sweep = [nthreads] if nthreads else DIST_SHARD_SWEEP
        # Force the LARGEST shard count before the CPU backend initializes:
        # the forced-device-count flag is latched at first backend init, so
        # asking for 2 first would cap the whole sweep at 2. (Not when the
        # meshes come from the default platform's real devices.)
        if DIST_DEVICE != "default":
            _cpu_mesh_devices(max(thread_sweep))
    sweep = list(thread_sweep) if thread_sweep else [None]
    cells = []
    for key in keys:
        try:
            ctx = prep(key)
        except Exception as e:  # bad key: fail its cells, keep the sweep
            print(f"bench-grid: {suite}/{key} setup failed: {e}",
                  file=sys.stderr)
            for t in sweep:
                for backend in backends:
                    if _sweep_skip(suite, backend, t, sweep):
                        continue
                    cells.append(Cell(suite,
                                      _sweep_label(suite, key, backend, t),
                                      backend, 0.0, False, float("nan"),
                                      baselines.reference_seconds(
                                          suite, key, backend),
                                      span=_cell_span(suite, backend, span),
                                      note=_failure_note("setup failed", e)))
            continue
        for t in sweep:
            run_t = nthreads if t is None else t
            for backend in backends:
                if _sweep_skip(suite, backend, t, sweep):
                    continue
                key_label = _sweep_label(suite, key, backend, t)
                # Progress to stderr per cell: sweeps run for minutes behind
                # slow device dispatch, and a silent hang is
                # indistinguishable from work without this.
                print(f"bench-grid: running {suite}/{key_label}/{backend} ...",
                      file=sys.stderr, flush=True)
                try:
                    with obs.span(f"cell:{suite}/{key_label}/{backend}",
                                  suite=suite, key=key_label,
                                  backend=backend):
                        cell = run(ctx, key, backend, run_t, span=span)
                except Exception as e:  # keep the sweep on backend failure
                    print(f"bench-grid: {suite}/{key_label}/{backend} "
                          f"failed: {e}", file=sys.stderr)
                    t_fail = _utc_stamp()
                    first_fail = _failure_note("failed", e)
                    cell = None
                    if _infra_retryable(e):
                        # ONE bounded retry, infra-class failures only: a
                        # daemon hiccup mid-sweep costs a whole cell (and
                        # on long device sweeps, the rerun costs hours).
                        # The retried cell records BOTH timestamps — the
                        # note must show the cell is a second attempt, not
                        # a clean first run.
                        print(f"bench-grid: {suite}/{key_label}/{backend} "
                              f"infra-class failure; retrying once",
                              file=sys.stderr, flush=True)
                        obs.emit("cell_retry", suite=suite, key=key_label,
                                 backend=backend, error=first_fail[:200])
                        try:
                            with obs.span(
                                    f"cell:{suite}/{key_label}/{backend}"
                                    f"/retry", suite=suite, key=key_label,
                                    backend=backend, retry=True):
                                cell = run(ctx, key, backend, run_t,
                                           span=span)
                        except Exception as e2:
                            # Reproduced: stays FAILED honestly, carrying
                            # both attempts' evidence.
                            print(f"bench-grid: {suite}/{key_label}/"
                                  f"{backend} retry failed: {e2}",
                                  file=sys.stderr)
                            first_fail = (
                                f"{first_fail} [at {t_fail}]; retry "
                                f"reproduced at {_utc_stamp()}: "
                                f"{_failure_note('failed', e2)}")
                        else:
                            retry_note = (f"retried: infra-class failure "
                                          f"at {t_fail} -> succeeded at "
                                          f"{_utc_stamp()}; first: "
                                          f"{first_fail}")
                            cell = replace(
                                cell, note=(f"{cell.note}; {retry_note}"
                                            if cell.note else retry_note))
                            print(f"bench-grid: {suite}/{key_label}/"
                                  f"{backend} retry -> "
                                  f"{cell.seconds:.6f}s "
                                  f"verified={cell.verified}",
                                  file=sys.stderr, flush=True)
                    if cell is None:
                        # The exception text rides in the cell's note: a
                        # FAILED cell must be diagnosable from the JSON
                        # alone (VERDICT round 2 weak #2 — a crash that
                        # records nothing is indistinguishable from a
                        # verification failure).
                        note = _ctx_note(suite, ctx)
                        cell = Cell(suite, str(key), backend, 0.0, False,
                                    float("nan"),
                                    baselines.reference_seconds(suite, key,
                                                                backend),
                                    span=_cell_span(suite, backend, span),
                                    note=(f"{note}; {first_fail}"
                                          if note else first_fail))
                else:
                    print(f"bench-grid: {suite}/{key_label}/{backend} -> "
                          f"{cell.seconds:.6f}s verified={cell.verified}",
                          file=sys.stderr, flush=True)
                if cell.key != key_label:
                    cell = replace(cell, key=key_label)
                obs.emit("cell", suite=cell.suite, key=cell.key,
                         backend=cell.backend, seconds=cell.seconds,
                         verified=cell.verified, span=cell.span,
                         note=cell.note, dtype=cell.dtype)
                cells.append(cell)
    return cells


DEVICE_SPAN_MARK = " [device-span]"  # shared with bench.report's tables


def _span_label(c: Cell) -> str:
    """Backend column label; device-span cells are explicitly marked so the
    two timing spans are never silently mixed in one table."""
    return c.backend + DEVICE_SPAN_MARK if c.span == "device" else c.backend


def format_table(cells: List[Cell]) -> str:
    """One BASELINE.md-style markdown table per suite, keys as rows."""
    out = []
    for suite in dict.fromkeys(c.suite for c in cells):
        suite_cells = [c for c in cells if c.suite == suite]
        backends = list(dict.fromkeys(_span_label(c) for c in suite_cells))
        keys = list(dict.fromkeys(c.key for c in suite_cells))
        label = {"gauss-internal": "n", "gauss-external": "matrix",
                 "matmul": "n", "gauss-dist": "n"}.get(suite, "key")
        out.append(f"## {suite} (seconds; xR = speedup vs reference cell)\n")
        out.append("| " + label + " | " + " | ".join(backends) + " |")
        out.append("|" + "---|" * (len(backends) + 1))
        index = {(c.key, _span_label(c)): c for c in suite_cells}
        for key in keys:
            row = [key]
            for backend in backends:
                c = index.get((key, backend))
                if c is None:
                    row.append("—")
                elif not c.verified:
                    row.append(f"FAILED (err {c.error:.2e})")
                else:
                    s = f"{c.seconds:.6f}"
                    if c.speedup is not None:
                        s += f" ({c.speedup:.1f}xR)"
                    row.append(s)
            out.append("| " + " | ".join(row) + " |")
        # Keyed per (row, backend): two backends of the same key may carry
        # different notes (e.g. one failure cause + one provenance), and a
        # later cell must not silently overwrite an earlier one's.
        notes = {(c.key, _span_label(c)): c.note
                 for c in suite_cells if c.note}
        if notes:
            vals = set(notes.values())
            if len(vals) == 1:
                out.append(f"\nAll rows: {vals.pop()}.")
            else:
                out.append("\n" + "; ".join(
                    f"{k}/{bk}: {v}" for (k, bk), v in notes.items()) + ".")
        out.append("")
    return "\n".join(out)


def main(argv=None) -> int:
    from gauss_tpu.utils.env import honor_jax_platforms

    honor_jax_platforms()  # an explicit JAX_PLATFORMS beats the image's pin
    p = argparse.ArgumentParser(
        prog="bench-grid",
        description="Reproduce the reference reports' benchmark grids.")
    p.add_argument("--suite", choices=SUITES + EXTRA_SUITES + ("all",),
                   default="all",
                   help="'all' runs the three reference suites; gauss-dist "
                        "(shard sweep on a virtual CPU mesh) is opt-in")
    p.add_argument("--keys", default="",
                   help="comma-separated sizes / dataset names "
                        "(default: the reference reports' sweep)")
    p.add_argument("--backends", default="tpu,seq,omp",
                   help=f"comma-separated; gauss: {_common.GAUSS_BACKENDS}; "
                        f"matmul: {_common.MATMUL_BACKENDS}")
    p.add_argument("-t", "--threads", type=int, default=0)
    p.add_argument("--thread-sweep", default=None, metavar="T1,T2,...",
                   help="sweep native-engine thread counts at each size "
                        "(the reference tables' second axis); cells are "
                        "keyed '<n> @<T>t'")
    p.add_argument("--span", choices=("reference", "device"),
                   default="reference",
                   help="timing span for device engines: 'reference' keeps "
                        "the reference programs' transfer-inclusive spans "
                        "(tunnel dispatch dominates here); 'device' measures "
                        "per-op seconds by the K-chain slope method with "
                        "operands device-resident (bench.slope)")
    p.add_argument("--dtype", choices=("float32", "bfloat16", "bf16x3"),
                   default="float32",
                   help="storage dtype for the gauss device-span tpu cells "
                        "(the mixed-precision column, core.lowered): "
                        "lowered cells run the SAME slope protocol with "
                        "the double-single refinement that brings the "
                        "lowered factor back to the 1e-4 bar included in "
                        "the timed chain; cells are stamped with the "
                        "dtype (JSON + obs events) and enter history as "
                        "distinct '...@<dtype>' metrics, so "
                        "mixed-precision epochs never pollute an f32 "
                        "baseline (requires --span device)")
    p.add_argument("--json", dest="json_path", default=None,
                   help="also write cells as a JSON array to this path")
    p.add_argument("--metrics-out", metavar="PATH", default=None,
                   help="append the sweep's telemetry (per-cell spans and "
                        "results, solver health, compile accounting) as "
                        "JSONL to PATH")
    p.add_argument("--regress-check", action="store_true",
                   help="gate every verified cell against the committed "
                        "per-cell baselines (obs.regress median + "
                        "epoch-noise band over reports/history.jsonl); "
                        "out-of-band cells fail the run")
    p.add_argument("--regress-history", metavar="PATH", default=None,
                   help="history file for --regress-check (default: the "
                        "committed reports/history.jsonl)")
    p.add_argument("--tuned", action="store_true",
                   help="report the tuned-store resolution for this sweep "
                        "and stamp tune provenance into the JSON cells. "
                        "The device cells' auto config ALWAYS consults the "
                        "store when one exists (gauss_tpu.tune) — this "
                        "flag makes which config actually ran visible in "
                        "the artifacts")
    p.add_argument("--dist-device", choices=("cpu", "default"),
                   default="cpu",
                   help="gauss-dist mesh devices: 'cpu' = the forced "
                        "virtual CPU pool (shard-sweep scaling); 'default' "
                        "= jax.devices() of the default platform — on one "
                        "real TPU, pass -t 1 to prove the shard_map "
                        "programs lower and run on actual hardware")
    args = p.parse_args(argv)
    global DIST_DEVICE, GRID_DTYPE
    DIST_DEVICE = args.dist_device
    if args.dtype != "float32" and args.span != "device":
        p.error("--dtype lowers the gauss device-span tpu cells; add "
                "--span device (the reference span has no lowered path)")
    GRID_DTYPE = args.dtype

    if args.keys and args.suite == "all":
        p.error("--keys requires a single --suite (sizes and dataset names "
                "do not apply across suites)")
    suites = list(SUITES) if args.suite == "all" else [args.suite]
    backends = [b.strip() for b in args.backends.split(",") if b.strip()]
    if (args.suite == "gauss-dist"
            and args.backends == p.get_default("backends")):
        # Only the untouched default is rewritten; an explicit non-dist
        # request falls through to the per-suite validity filter and its
        # "no requested backend applies" notice.
        backends = list(DIST_BACKENDS)
    # "jax-linalg" is bench-only (the stock-library baseline column), not a
    # CLI solve backend — known here, not in _common.GAUSS_BACKENDS.
    known = (set(_common.GAUSS_BACKENDS) | set(_common.MATMUL_BACKENDS)
             | {"jax-linalg"})
    unknown = [b for b in backends if b not in known]
    if unknown:
        p.error(f"unknown backend(s) {unknown}; gauss: "
                f"{_common.GAUSS_BACKENDS} + jax-linalg (device span only); "
                f"matmul: {_common.MATMUL_BACKENDS}")
    if "jax-linalg" in backends and args.span != "device":
        # Statically-detectable misuse gets a parse-time error, not a sweep
        # of per-cell run-time failures.
        p.error("jax-linalg is a device-span-only baseline column; add "
                "--span device")
    sweep = None
    if args.thread_sweep:
        raw = [x.strip() for x in args.thread_sweep.split(",") if x.strip()]
        bad = [x for x in raw if not x.isdigit() or int(x) < 1]
        if bad or not raw:
            p.error(f"--thread-sweep must be positive integers, got {bad or args.thread_sweep!r}")
        sweep = [int(x) for x in raw]
    tune_status = None
    if args.tuned:
        from gauss_tpu.tune import apply as tune_apply

        tune_status = tune_apply.store_status()
        state = (f"usable, {tune_status['configs']} config(s)"
                 if tune_status["usable"] else tune_status["reason"])
        print(f"bench-grid: tuned store {tune_status['path']}: {state}",
              file=sys.stderr)
    all_cells: List[Cell] = []
    with obs.run(metrics_out=args.metrics_out, tool="bench_grid") as rec:
        rc = _run_suites(p, args, suites, backends, sweep, all_cells)
    if rc is not None:
        return rc
    print(format_table(all_cells))
    if args.metrics_out:
        print(f"bench-grid: metrics run {rec.run_id} appended to "
              f"{args.metrics_out}", file=sys.stderr)
    if args.json_path:
        # NaN (failed-cell error) is not valid JSON; emit null instead.
        # Every cell carries the sweep's telemetry run id, so a table row
        # links back to its full event stream in --metrics-out.
        payload = [dict(asdict(c), speedup=c.speedup, run_id=rec.run_id,
                        error=c.error if np.isfinite(c.error) else None,
                        **({"tune_store": tune_status}
                           if tune_status is not None else {}))
                   for c in all_cells]
        with open(args.json_path, "w") as f:
            json.dump(payload, f, indent=1)
        print(f"wrote {len(payload)} cells to {args.json_path}", file=sys.stderr)
    rc = 0 if all(c.verified for c in all_cells) else 1
    if args.regress_check:
        # Per-cell regression gate: each verified cell checks against its
        # own committed baseline (metric "cell:<suite>/<key>/<backend>").
        # Cells with no history yet report no-baseline and do not gate —
        # run `obs.regress ingest` on this sweep's --json output to seed
        # them.
        from gauss_tpu.obs import regress

        history = regress.load_history(
            args.regress_history or regress.default_history_path())
        verdicts = [
            regress.evaluate(regress._cell_metric(
                {"suite": c.suite, "key": c.key, "backend": c.backend,
                 "span": c.span, "dtype": c.dtype}), c.seconds, history)
            for c in all_cells if c.verified]
        print(regress.format_verdicts(verdicts))
        if any(v["status"] == "out-of-band" for v in verdicts):
            rc = rc or 1
    return rc


def _run_suites(p, args, suites, backends, sweep, all_cells):
    for suite in suites:
        if args.keys:
            raw = [k.strip() for k in args.keys.split(",") if k.strip()]
            if suite == "gauss-external":
                keys = raw
            else:
                bad = [k for k in raw if not k.isdigit()]
                if bad:
                    p.error(f"--keys for {suite} must be integer sizes; "
                            f"got {bad}")
                keys = [int(k) for k in raw]
        else:
            keys = list(baselines.suite_keys(suite))
        if suite == "matmul":
            valid = _common.MATMUL_BACKENDS
        elif suite == "gauss-dist":
            valid = DIST_BACKENDS
        elif suite == "gauss-internal":
            # + the bench-only stock-library baseline column (device span).
            valid = _common.GAUSS_BACKENDS + ("jax-linalg",)
        else:
            valid = _common.GAUSS_BACKENDS
        suite_backends = [b for b in backends if b in valid]
        if not suite_backends:
            print(f"bench-grid: no requested backend applies to {suite}; "
                  f"valid: {valid}", file=sys.stderr)
            continue
        all_cells += run_suite(suite, keys, suite_backends, args.threads,
                               span=args.span, thread_sweep=sweep)
    if not all_cells:
        print("bench-grid: nothing ran (no valid suite/backend combination)",
              file=sys.stderr)
        return 1
    return None


if __name__ == "__main__":
    sys.exit(main())
