"""MXU precision sweep: HIGHEST (6-pass f32 emulation) vs HIGH (bf16x3)
trailing GEMMs at large n (VERDICT round 2 next #3).

The blocked factorization's O(n^3) lands in trailing GEMMs whose MXU
precision is selectable (core.blocked gemm_precision). Round 2 measured
"high" saving only ~4% at n=2048 — where the panel factorization, not the
GEMM, dominates — and never measured n >= 8192, where bf16x3's ~2x MXU
throughput should actually show. This sweep times BOTH precisions through
the same double-single-refined pipeline (refinement absorbs bf16x3's
accuracy loss; the cell verifies the refined solution against the 1e-4
residual bar), so the comparison is end-to-end honest: if bf16x3's GEMM
win survives its extra refinement cost, the number shows it.

Usage::

    python -m gauss_tpu.bench.precision --sizes 2048,4096,8192 \
        --json reports/cells_precision.json

Cells carry the same schema as bench.grid (suite "gauss-precision",
backend "tpu[<precision>]", device span) so bench.report folds them in.
"""

from __future__ import annotations

import argparse
import json
import sys
from dataclasses import asdict

import numpy as np

from gauss_tpu.bench.grid import RESIDUAL_BAR, Cell, format_table

PRECISIONS = ("highest", "high")
DEFAULT_SIZES = (2048, 4096, 8192)
DS_ITERS = 3  # refinement steps inside the timed chain (both precisions)


def measure_cell(n: int, precision: str, refine_steps: int = DS_ITERS) -> Cell:
    """One slope-timed, ds-refined, verified cell at (n, gemm_precision) —
    the measurement recipe (K policy included) is grid's
    _gauss_device_cell_ds, not a copy of it."""
    from gauss_tpu.bench.grid import _gauss_device_cell_ds
    from gauss_tpu.io import synthetic
    from gauss_tpu.verify import checks

    a64 = synthetic.internal_matrix(n)
    b64 = synthetic.internal_rhs(n)
    seconds, x, (ks, kl, is_slope) = _gauss_device_cell_ds(
        a64, b64, refine_steps=refine_steps, gemm_precision=precision)
    res = checks.residual_norm(a64, x, b64)
    note = (f"gemm_precision={precision}, ds-refine x{refine_steps}, "
            f"K=({ks},{kl}){'' if is_slope else ', NOT A SLOPE'}; "
            f"{2 * n ** 3 / 3 / seconds / 1e12:.2f} TF/s useful")
    return Cell("gauss-precision", str(n), f"tpu[{precision}]", seconds,
                res < RESIDUAL_BAR, res, None, span="device", note=note)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="bench-precision",
        description="HIGHEST vs HIGH (bf16x3) GEMM sweep, ds-refined.")
    p.add_argument("--sizes", default=",".join(map(str, DEFAULT_SIZES)))
    p.add_argument("--precisions", default=",".join(PRECISIONS))
    p.add_argument("--json", dest="json_path", default=None)
    args = p.parse_args(argv)

    sizes = [int(s) for s in args.sizes.split(",") if s.strip()]
    precisions = [s.strip() for s in args.precisions.split(",") if s.strip()]
    cells = []
    for n in sizes:
        for prec in precisions:
            print(f"bench-precision: n={n} {prec} ...", file=sys.stderr,
                  flush=True)
            try:
                cell = measure_cell(n, prec)
            except Exception as e:
                from gauss_tpu.bench.grid import _failure_note

                cell = Cell("gauss-precision", str(n), f"tpu[{prec}]", 0.0,
                            False, float("nan"), None, span="device",
                            note=_failure_note("failed", e))
            print(f"bench-precision: n={n} {prec} -> {cell.seconds:.6f}s "
                  f"verified={cell.verified} ({cell.note})", file=sys.stderr,
                  flush=True)
            cells.append(cell)

    print(format_table(cells))
    if args.json_path:
        payload = [dict(asdict(c), speedup=c.speedup,
                        error=c.error if np.isfinite(c.error) else None)
                   for c in cells]
        with open(args.json_path, "w") as f:
            json.dump(payload, f, indent=1)
        print(f"wrote {len(payload)} cells to {args.json_path}",
              file=sys.stderr)
    return 0 if all(c.verified for c in cells) else 1


if __name__ == "__main__":
    sys.exit(main())
