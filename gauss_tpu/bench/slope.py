"""Slope-based device timing: per-op seconds from K-iteration chains.

The single real chip in this environment sits behind a tunnel with ~70 ms
dispatch+fetch RTT, so a one-shot span measures the tunnel, not the chip
(and ``block_until_ready`` alone can return early on the tunneled platform).
The honest per-op number is the *slope* of K-iteration on-device chains:
time chains of K1 and K2 data-dependent iterations (XLA cannot collapse
them), fetch only a scalar, and take (t_K2 - t_K1) / (K2 - K1) — the
constant dispatch/fetch offset cancels exactly. Used by bench.py (the
headline metric) and by ``bench.grid --span device``.

Noise hardening (measured, see bench.py history): tunnel latency is noisy in
epochs, and a burst landing on all of one K's reps skews the slope badly
(20x observed once). Both chains are compiled and warmed first, the timed
reps are INTERLEAVED across rounds so both K values sample the same epochs,
and the estimator is the per-K minimum — noise only ever adds time.
"""

from __future__ import annotations

from typing import Callable, Sequence, Tuple

import numpy as np

K_SMALL, K_LARGE = 4, 16
ROUNDS = 5

# Data-dependent perturbation scale: defeats CSE across chained iterations
# while keeping the system numerically unchanged for verification purposes.
PERTURB = 1e-6


# The chain-time DIFFERENCE must clear the tunnel's jitter floor or the
# slope is noise: sub-0.1 ms ops at K=4/16 leave ~1 ms of signal against
# several ms of jitter, and the fallback then reports the ~0.1 s dispatch
# offset as if it were compute (observed 100x overstatements). Escalate K
# until the delta clears this floor.
MIN_DELTA_S = 0.004
MAX_K = 1024


def measure_slope_info(make_chain: Callable[[int], Callable],
                       args: Sequence = (), k_small: int = K_SMALL,
                       k_large: int = K_LARGE, rounds: int = ROUNDS
                       ) -> Tuple[float, int, int, bool]:
    """(seconds-per-iteration, k_small, k_large, is_slope): the two-chain
    slope plus the K pair that was ACTUALLY measured (the pair escalates
    when the chain delta is under the jitter floor, so reporting the
    requested pair would misstate the measurement configuration — ADVICE
    round 1). ``is_slope`` is False when the measurement fell back to the
    whole-chain mean (non-positive delta at MAX_K) — that number still
    contains the dispatch offset and must not be labeled a slope.

    ``make_chain(k)`` must return a jitted callable running k data-dependent
    iterations on device and returning a SMALL result (scalar fetch — the
    completion signal must not measure tunnel bandwidth). If the measured
    chain-time delta is below the jitter floor, the K pair escalates (x4)
    and remeasures. At MAX_K a positive sub-floor delta is still returned
    as the slope (the best available estimate); only a non-positive delta
    falls back to the whole-chain mean — a conservative overestimate that
    still contains the dispatch offset.
    """
    from gauss_tpu.utils.timing import timed_fetch

    while True:
        fns = {k: make_chain(k) for k in (k_small, k_large)}
        for fn in fns.values():  # compile + settle before any timing
            np.asarray(fn(*args))
            np.asarray(fn(*args))
        best = {k: float("inf") for k in fns}
        for _ in range(rounds):
            for k, fn in fns.items():
                t, _ = timed_fetch(fn, *args, warmup=0, reps=1)
                best[k] = min(best[k], t)
        delta = best[k_large] - best[k_small]
        if delta >= MIN_DELTA_S or k_large * 4 > MAX_K:
            break
        k_small, k_large = k_small * 4, k_large * 4
    if delta <= 0:
        return best[k_large] / k_large, k_small, k_large, False
    return delta / (k_large - k_small), k_small, k_large, True


def measure_slope(make_chain: Callable[[int], Callable], args: Sequence = (),
                  k_small: int = K_SMALL, k_large: int = K_LARGE,
                  rounds: int = ROUNDS) -> float:
    """:func:`measure_slope_info` without the configuration bookkeeping."""
    return measure_slope_info(make_chain, args, k_small, k_large, rounds)[0]


def gauss_solve_once(a, b, panel: int, refine_steps: int = 0,
                     unroll="auto", gemm_precision: str = "highest"):
    """One iteration of exactly the configuration :func:`gauss_chain` times:
    blocked f32 factor + solve (+ optional on-device f32 refinement steps).
    Exposed so callers can VERIFY the very computation the slope measures —
    a timed cell whose verification ran on a different configuration would
    be meaningless. The factorization policy (core.blocked.resolve_factor)
    keeps chain compile payloads bounded: a K=16 chain of 32+ fully unrolled
    panel programs exceeded the tunneled remote-compile limit (HTTP 413 at
    n=8192); the chunked form caps traced programs per group."""
    import jax.numpy as jnp
    from jax import lax

    from gauss_tpu.core import blocked

    factor = blocked.resolve_factor(a.shape[0], unroll)
    fac = factor(a, panel=panel, gemm_precision=gemm_precision)
    x = blocked.lu_solve(fac, b)
    for _ in range(refine_steps):
        r = b - jnp.dot(a, x, precision=lax.Precision.HIGHEST)
        x = x + blocked.lu_solve(fac, r)
    return x


def gauss_solve_once_ds(a, at_ds, b_ds, panel: int, refine_steps: int,
                        unroll="auto", gemm_precision: str = "highest",
                        factor_dtype: "str | None" = None):
    """One factor + solve + double-single on-device refinement — the
    external-suite device-span configuration (VERDICT round 1 #3: the f32
    refinement floor failed memplus; double-single residuals clear the 1e-4
    bar fully on device). Thin timing-chain wrapper over the single
    assembly point, core.dsfloat.solve_once_ds. ``factor_dtype``: the
    lowered storage axis (bfloat16 / bf16x3 — the grid --dtype column);
    None is the f32 path, unchanged."""
    from gauss_tpu.core import dsfloat

    x, _ = dsfloat.solve_once_ds(a, at_ds, b_ds, panel, iters=refine_steps,
                                 unroll=unroll,
                                 gemm_precision=gemm_precision,
                                 factor_dtype=factor_dtype)
    return x


def ds_solver_chain(a, at_ds, b_ds, panel: int, refine_steps: int,
                    unroll="auto", gemm_precision: str = "highest",
                    factor_dtype: "str | None" = None
                    ) -> Tuple[Callable[[int], Callable], tuple]:
    """Chain factory for the ds-refined solve. The factor operand is
    perturbed per iteration (defeats CSE); the residual operands stay fixed,
    so every iteration converges to the same (verified) solution — the
    correction operator tolerates a 1e-6-perturbed factorization exactly the
    way refinement tolerates its f32 rounding."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from gauss_tpu.core.dsfloat import DS

    def make_chain(k: int):
        @jax.jit
        def run(a_, at_hi, at_lo, b_hi, b_lo, x0):
            def body(_, xc):
                a_i = a_ + xc[0] * jnp.asarray(PERTURB, a_.dtype)
                x = gauss_solve_once_ds(a_i, DS(at_hi, at_lo),
                                        DS(b_hi, b_lo), panel, refine_steps,
                                        unroll, gemm_precision,
                                        factor_dtype)
                return x.hi + x.lo

            x = lax.fori_loop(0, k, body, x0)
            return jnp.sum(x)

        return run

    return make_chain, (a, at_ds.hi, at_ds.lo, b_ds.hi, b_ds.lo, b_ds.hi)


def solver_chain(a, b, solve_once: Callable
                 ) -> Tuple[Callable[[int], Callable], tuple]:
    """Chain factory for ANY jittable gauss solver ``solve_once(a, b) -> x``:
    each iteration solves a freshly perturbed system. Returns
    (make_chain, args)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    def make_chain(k: int):
        @jax.jit
        def run(a_, b_, x0):
            # a/b enter as ARGUMENTS, not closure captures: captured arrays
            # ride along with the compile payload, which breaks tunneled
            # remote compilation at large n (HTTP 413 at n=8192, 268 MB).
            def body(_, x):
                a_i = a_ + x[0] * jnp.asarray(PERTURB, a_.dtype)
                return solve_once(a_i, b_)

            x = lax.fori_loop(0, k, body, x0)
            return jnp.sum(x)  # scalar fetch: completion without bandwidth

        return run

    return make_chain, (a, b, b)


def gauss_chain(a, b, panel: int, refine_steps: int = 0, unroll="auto",
                gemm_precision: str = "highest"
                ) -> Tuple[Callable[[int], Callable], tuple]:
    """Chain factory for the blocked gauss solve (+ refine_steps on-device
    f32 refinement iterations — each one matvec + triangular solves, O(n^2)
    against the O(n^3) factor). Returns (make_chain, args)."""

    def solve_once(a_, b_):
        return gauss_solve_once(a_, b_, panel, refine_steps, unroll,
                                gemm_precision)

    return solver_chain(a, b, solve_once)


def matmul_chain(a, b, mm: Callable,
                 c0=None) -> Tuple[Callable[[int], Callable], tuple]:
    """Chain factory for a device matmul engine ``mm(a, b) -> c``.

    ``mm`` must be pure traced computation (no host staging — the body runs
    under one jit); distributed engines pass their staged form
    (dist/matmul_dist.matmul_dist_staged) along with a ``c0`` carry whose
    sharding matches the engine output, so the loop carry is
    sharding-stable on a multi-device mesh."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    def make_chain(k: int):
        @jax.jit
        def run(a_, b_, c0):
            def body(_, c):
                return mm(a_ + c[0, 0] * jnp.asarray(PERTURB, a_.dtype), b_)

            c = lax.fori_loop(0, k, body, c0)
            return c[0, 0]

        return run

    if c0 is None:
        c0 = jnp.zeros((a.shape[0], b.shape[1]), a.dtype)
    return make_chain, (a, b, c0)
