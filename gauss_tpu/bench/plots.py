"""Result graphs from bench-grid JSON (reference C11 analog: graphs/*.jpg).

The reference ships three result plots (SURVEY.md §2 C11: gauss_seq.jpg,
pthreads-mpi-openmp.jpg, mm_seq-openmp-cuda.jpg). This module regenerates the
same three views from measured grid cells:

    gauss_scaling.png   gauss-internal wall-clock vs n, one line per engine
    gauss_engines.png   n=2048 engine comparison, ours vs reference bests
    matmul_scaling.png  matmul wall-clock vs n, one line per engine

Usage: python -m gauss_tpu.bench.plots cells.json [more.json ...] --outdir graphs

Colors are a fixed-order CVD-validated categorical palette (adjacent-pair
CVD deltaE >= 8); reference-baseline context is drawn in neutral gray dashes,
never a series hue. Time axes are log-scaled (the data spans decades), which
is also why the engine comparison is a dot plot, not bars — bar length is
meaningless on a log axis.
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import defaultdict
from pathlib import Path

# Validated categorical palette, fixed slot order (dataviz reference palette;
# worst adjacent CVD deltaE 9.1 on light surfaces).
PALETTE = ["#2a78d6", "#eb6834", "#1baf7a", "#eda100",
           "#e87ba4", "#008300", "#4a3aa7", "#e34948"]
GRAY = "#767571"
TEXT = "#1a1a19"

# Fixed engine -> (slot, linestyle). There are more engines than palette
# slots, so identity is color + linestyle: device engines solid, native CPU
# engines dashed (a group-level secondary encoding), and no two engines share
# the same (slot, style) pair. Unknown engines fold to gray, never a
# generated hue.
ENGINE_STYLE = {"tpu": (0, "-"), "tpu-unblocked": (1, "-"),
                "tpu-rowelim": (2, "-"), "tpu-dist": (3, "-"),
                "tpu-dist2d": (4, "-"),
                "tpu-pallas": (5, "-"), "tpu-pallas-v1": (6, "-"),
                "seq": (7, "--"), "omp": (0, "--"), "threads": (1, "--"),
                "forkjoin": (2, "--"), "tiled": (3, "--"),
                "tpu-rowelim-step": (2, ":"), "tpu-dist-blocked": (5, "-.")}


def _color(engine: str) -> str:
    style = ENGINE_STYLE.get(engine)
    return GRAY if style is None else PALETTE[style[0]]


def _linestyle(engine: str) -> str:
    return ENGINE_STYLE.get(engine, (0, "-"))[1]


def _style_axes(ax):
    for side in ("top", "right"):
        ax.spines[side].set_visible(False)
    ax.grid(True, which="major", axis="both", color="#e8e6dc", linewidth=0.8)
    ax.set_axisbelow(True)
    ax.tick_params(colors=TEXT, labelsize=9)


def _load_cells(paths):
    cells = []
    for p in paths:
        cells += json.loads(Path(p).read_text())
    return [c for c in cells if c.get("verified")]


def _scaling_plot(ax, cells, suite, title):
    series = defaultdict(list)
    for c in cells:
        if c["suite"] == suite and c["key"].isdigit():
            series[c["backend"]].append((int(c["key"]), c["seconds"]))
    order = {b: i for i, b in enumerate(ENGINE_STYLE)}
    for backend in sorted(series, key=lambda b: order.get(b, 99)):
        pts = sorted(series[backend])
        ax.plot([n for n, _ in pts], [s for _, s in pts], marker="o",
                markersize=4, linewidth=2, label=backend,
                color=_color(backend), linestyle=_linestyle(backend))
    ax.set_xscale("log", base=2)
    ax.set_yscale("log")
    ax.set_xlabel("matrix size n", color=TEXT)
    ax.set_ylabel("wall-clock (s)", color=TEXT)
    ax.set_title(title, color=TEXT, fontsize=11)
    if len(series) >= 2:
        ax.legend(frameon=False, fontsize=9)
    _style_axes(ax)
    return bool(series)


def _engines_plot(ax, cells):
    from gauss_tpu.bench import baselines

    ours = {c["backend"]: c["seconds"] for c in cells
            if c["suite"] == "gauss-internal" and c["key"] == "2048"}
    if not ours:
        return False
    ref = dict(baselines.GAUSS_2048_BEST,
               **{"sequential": baselines.GAUSS_SEQ[2048]})
    rows = ([(f"ref {k}", v, True) for k, v in sorted(ref.items(),
                                                      key=lambda kv: -kv[1])] +
            [(k, v, False) for k, v in sorted(ours.items(),
                                              key=lambda kv: -kv[1])])
    ys = range(len(rows))
    for y, (label, secs, is_ref) in zip(ys, rows):
        color = GRAY if is_ref else _color(label)
        ax.plot([secs], [y], "o", markersize=9, color=color,
                markeredgecolor="white", markeredgewidth=1.5)
        ax.annotate(f" {secs:.3g}s", (secs, y), fontsize=8, color=TEXT,
                    va="center", xytext=(6, 0), textcoords="offset points")
    ax.set_yticks(list(ys), [r[0] for r in rows], fontsize=9)
    ax.set_xscale("log")
    ax.set_xlabel("wall-clock (s), n=2048 — log scale", color=TEXT)
    ax.set_title("Gauss n=2048: this framework vs reference best cells",
                 color=TEXT, fontsize=11)
    _style_axes(ax)
    ax.grid(axis="y", visible=False)
    return True


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="bench-plots",
        description="Render the three reference-analog result graphs.")
    p.add_argument("json_files", nargs="+")
    p.add_argument("--outdir", default="graphs")
    args = p.parse_args(argv)

    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    cells = _load_cells(args.json_files)
    if not cells:
        print("bench-plots: no verified cells in input", file=sys.stderr)
        return 1
    outdir = Path(args.outdir)
    outdir.mkdir(parents=True, exist_ok=True)

    written = []
    jobs = [
        ("gauss_scaling.png",
         lambda ax: _scaling_plot(ax, cells, "gauss-internal",
                                  "Gaussian elimination scaling (internal input)")),
        ("gauss_engines.png", lambda ax: _engines_plot(ax, cells)),
        ("matmul_scaling.png",
         lambda ax: _scaling_plot(ax, cells, "matmul", "Matmul scaling")),
    ]
    for name, draw in jobs:
        fig, ax = plt.subplots(figsize=(7, 4.5), dpi=120)
        fig.patch.set_facecolor("white")
        if draw(ax):
            fig.tight_layout()
            path = outdir / name
            fig.savefig(path)
            written.append(str(path))
        plt.close(fig)
    print("\n".join(written) or "bench-plots: no plots produced (wrong suites?)")
    return 0 if written else 1


if __name__ == "__main__":
    sys.exit(main())
