"""Benchmark grid harness (SURVEY.md §7.7).

Reproduces the reference reports' timing grids — gauss internal-input size
sweep, gauss external-input dataset sweep, matmul size sweep — across this
framework's engines, and emits tables in the BASELINE.md format with
reference-baseline comparison columns. ``python -m gauss_tpu.bench.grid -h``.
"""

from gauss_tpu.bench.baselines import reference_seconds  # noqa: F401
from gauss_tpu.bench.grid import run_suite  # noqa: F401
