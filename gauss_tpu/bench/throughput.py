"""Batched solves-per-second — the THROUGHPUT record (ISSUE 11).

The latency record (``bench.py``) measures ONE solve as fast as the chip
can run it; the serving fleet's economics are the other axis — how many
independent systems per second one chip sustains when they arrive as
batches. This leg measures exactly that, on exactly the machinery that
serves them: a ``vmap``-batched blocked factor+solve executable from the
serve :class:`~gauss_tpu.serve.cache.ExecutableCache` (the MAGMA-batched
execution shape, host-f64 refinement rounds included — the number a
capacity planner can divide traffic by), at n ∈ {256, 1024, 2048}.

Protocol: the executable is built (and compiled) through the cache —
compile lands in the build span, never the timed window — then one
untimed warm dispatch, then ``reps`` timed dispatches of the SAME seeded
batch with the best-of taken (noise only ever adds time; the tuner's
discipline). Every member solution is verified at the 1e-4 relative
gate; a leg with ANY unverified member reports ``verified: false`` and
is excluded from history — a fast wrong answer must never become a
baseline.

Records enter ``reports/history.jsonl`` as
``tput:<dtype>/n<N>/b<B>/s_per_solve`` (throughput inverted, so the
regression sentinel's slow-side gate applies) and ratchet via
``obs.regress.RATCHET_BASELINES`` / ``RATCHET_CEILINGS`` exactly like
the latency record — from this PR on, BOTH records are regress-gated.
The ``--dtype`` axis runs the same protocol over the lowered executables
(``bfloat16`` / ``bf16x3`` — core.lowered), making the mixed-precision
throughput claim a measured, gated artifact rather than a datasheet
multiplication.

CLI (one epoch per invocation; commit 3 seeded epochs for a baseline)::

    JAX_PLATFORMS=cpu python -m gauss_tpu.bench.throughput \
        --ns 256,1024,2048 --batch 8 --history --regress-check
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

DEFAULT_NS = (256, 1024, 2048)
DEFAULT_BATCH = 8
DEFAULT_REPS = 3
VERIFY_GATE = 1e-4


def _batch_systems(n: int, batch: int, seed: int,
                   ) -> Tuple[np.ndarray, np.ndarray]:
    """A deterministic batch of DISTINCT diagonally-dominant systems
    (one seeded generator per member — a batch of copies would let a
    pathological cache effect flatter the number)."""
    a = np.empty((batch, n, n), dtype=np.float64)
    b = np.empty((batch, n, 1), dtype=np.float64)
    for i in range(batch):
        rng = np.random.default_rng(seed + 7919 * i + n)
        a[i] = rng.standard_normal((n, n))
        a[i, np.arange(n), np.arange(n)] += float(n)
        b[i] = rng.standard_normal((n, 1))
    return a, b


def measure_throughput(ns: Sequence[int] = DEFAULT_NS,
                       batch: int = DEFAULT_BATCH,
                       dtype: str = "float32", refine_steps: int = 1,
                       reps: int = DEFAULT_REPS, seed: int = 258458,
                       lanes: int = 0,
                       run_id: Optional[str] = None) -> Dict:
    """Run the batched-throughput legs; returns the ``throughput_bench``
    summary (regress-ingestable).

    ``lanes > 0`` runs the MULTI-LANE record leg instead (ISSUE 14): the
    mesh-serving dispatch shape — ``lanes`` concurrent threads, each
    pinned to its own device of the visible mesh via the serve
    executable's ``placement=``, all sharing ONE cached executable
    (compiles once; each lane's backend specialization lands in its
    untimed warm dispatch). The metric is the aggregate wall over all
    lanes' timed dispatches, inverted to seconds per solve — on the
    1-core CPU proxy this measures dispatch-pipelining efficiency, not
    MXU scaling (the devices share the host's cores)."""
    from gauss_tpu import obs
    from gauss_tpu.serve.cache import CacheKey, ExecutableCache
    from gauss_tpu.verify import checks

    cache = ExecutableCache(capacity=max(8, len(ns)))
    legs: List[Dict] = []
    for n in ns:
        key = CacheKey(bucket_n=int(n), nrhs=1, batch=int(batch),
                       dtype=dtype, engine="blocked",
                       refine_steps=int(refine_steps))
        with obs.span("tput_build", n=int(n), batch=int(batch),
                      dtype=dtype, lanes=int(lanes)):
            exe = cache.get(key)  # compile inside the build span
        if lanes:
            leg = _multilane_leg(exe, int(n), int(batch), dtype,
                                 int(refine_steps), max(1, reps),
                                 int(seed), int(lanes), checks)
        else:
            a, b = _batch_systems(int(n), int(batch), seed)
            x = exe.solve(a, b)  # warm dispatch, untimed
            rel_max = max(
                checks.residual_norm(a[i], x[i], b[i], relative=True)
                for i in range(int(batch)))
            times = []
            for _ in range(max(1, reps)):
                t0 = time.perf_counter()
                exe.solve(a, b)
                times.append(time.perf_counter() - t0)
            best = min(times)
            leg = {
                "n": int(n), "batch": int(batch), "dtype": dtype,
                "refine_steps": int(refine_steps), "reps": int(reps),
                "batch_s": round(best, 6),
                "s_per_solve": round(best / batch, 6),
                "solves_per_s": round(batch / best, 4),
                "rel_residual_max": float(f"{rel_max:.3e}"),
                "verified": bool(rel_max <= VERIFY_GATE),
            }
        obs.emit("tput_leg", **leg)
        obs.gauge(f"tput.n{n}.solves_per_s", leg["solves_per_s"])
        legs.append(leg)
    return {"kind": "throughput_bench", "ns": [int(n) for n in ns],
            "batch": int(batch), "dtype": dtype, "lanes": int(lanes),
            "refine_steps": int(refine_steps), "reps": int(reps),
            "seed": int(seed), "legs": legs, "run_id": run_id,
            "verify_gate": VERIFY_GATE}


def _multilane_leg(exe, n: int, batch: int, dtype: str, refine_steps: int,
                   reps: int, seed: int, lanes: int, checks) -> Dict:
    """One multi-lane leg: per-lane distinct seeded batches, per-lane
    device placement, a start barrier, aggregate wall across lanes."""
    import jax

    devices = jax.devices()
    work = []
    rel_max = 0.0
    for li in range(lanes):
        a, b = _batch_systems(n, batch, seed + 104729 * li)
        dev = devices[li % len(devices)]
        x = exe.solve(a, b, placement=dev)  # warm (this lane's compile)
        rel_max = max(rel_max, max(
            checks.residual_norm(a[i], x[i], b[i], relative=True)
            for i in range(batch)))
        work.append((a, b, dev))
    barrier = threading.Barrier(lanes)
    spans: List[Optional[Tuple[float, float]]] = [None] * lanes

    def _lane(li: int) -> None:
        a, b, dev = work[li]
        barrier.wait()
        t0 = time.perf_counter()
        for _ in range(reps):
            exe.solve(a, b, placement=dev)
        spans[li] = (t0, time.perf_counter())

    threads = [threading.Thread(target=_lane, args=(li,))
               for li in range(lanes)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = (max(s[1] for s in spans if s)
            - min(s[0] for s in spans if s))
    solves = lanes * reps * batch
    return {
        "n": n, "batch": batch, "dtype": dtype, "lanes": lanes,
        "refine_steps": refine_steps, "reps": reps,
        "wall_s": round(wall, 6),
        "s_per_solve": round(wall / solves, 6),
        "solves_per_s": round(solves / wall, 4),
        "rel_residual_max": float(f"{rel_max:.3e}"),
        "verified": bool(rel_max <= VERIFY_GATE),
    }


def history_records(summary: Dict) -> List[Tuple[str, float, str]]:
    """The (metric, value, unit) records a throughput summary contributes
    to the regression history — VERIFIED legs only, throughput inverted
    to seconds-per-solve so the sentinel (and the ratchet) gate the slow
    side. Metric names carry dtype, n, AND batch: a batch-4 epoch must
    never pollute a batch-8 baseline."""
    out = []
    for leg in summary.get("legs", []):
        if not leg.get("verified"):
            continue
        v = leg.get("s_per_solve")
        if isinstance(v, (int, float)) and v > 0:
            # Multi-lane legs carry /l<L> so a mesh epoch can never drag
            # the single-lane record's baseline (or vice versa).
            lane_part = (f"/l{leg['lanes']}" if leg.get("lanes") else "")
            out.append((f"tput:{leg['dtype']}/n{leg['n']}/b{leg['batch']}"
                        f"{lane_part}/s_per_solve", v, "s"))
    return out


def format_summary(summary: Dict) -> str:
    lanes = summary.get("lanes")
    lines = [f"throughput bench [{summary['dtype']}] batch="
             f"{summary['batch']} refine_steps={summary['refine_steps']} "
             + (f"lanes={lanes} (aggregate wall)" if lanes
                else f"(best of {summary['reps']})")]
    for leg in summary["legs"]:
        state = ("ok" if leg["verified"]
                 else f"UNVERIFIED (rel {leg['rel_residual_max']:.1e})")
        window = leg.get("batch_s", leg.get("wall_s", 0.0))
        lines.append(
            f"  n={leg['n']:5d}: {leg['solves_per_s']:10.2f} solves/s "
            f"({leg['s_per_solve'] * 1e3:.3f} ms/solve, "
            f"{'wall' if leg.get('lanes') else 'batch'} "
            f"{window:.4f} s) [{state}]")
    return "\n".join(lines)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m gauss_tpu.bench.throughput",
        description="Batched solves/sec record through the serve "
                    "executables; regress- and ratchet-gated like the "
                    "latency headline.")
    p.add_argument("--ns", default=",".join(str(n) for n in DEFAULT_NS),
                   help=f"comma-separated sizes (default "
                        f"{','.join(str(n) for n in DEFAULT_NS)})")
    p.add_argument("--batch", type=int, default=DEFAULT_BATCH,
                   help=f"systems per dispatch (default {DEFAULT_BATCH})")
    p.add_argument("--dtype", choices=("float32", "bfloat16", "bf16x3"),
                   default="float32",
                   help="executable storage dtype (the lowered lanes; "
                        "default float32)")
    p.add_argument("--refine-steps", type=int, default=1,
                   help="host-f64 refinement rounds per dispatch "
                        "(default 1 — the serve default)")
    p.add_argument("--lanes", type=int, default=0,
                   help="multi-lane record leg: N concurrent dispatch "
                        "threads, one device each (mesh-serving shape; "
                        "metric carries /l<N>; honest note: the 1-core "
                        "CPU proxy measures dispatch pipelining, not MXU "
                        "scaling). 0 = the single-lane record")
    p.add_argument("--reps", type=int, default=DEFAULT_REPS,
                   help=f"timed dispatches, best-of (default "
                        f"{DEFAULT_REPS})")
    p.add_argument("--seed", type=int, default=258458)
    p.add_argument("--metrics-out", default=None, metavar="PATH")
    p.add_argument("--summary-json", default=None, metavar="PATH",
                   help="write the summary (regress-ingestable: "
                        "kind=throughput_bench)")
    p.add_argument("--history", nargs="?", const="", default=None,
                   metavar="PATH",
                   help="append verified s_per_solve records to the "
                        "regression history (default "
                        "reports/history.jsonl)")
    p.add_argument("--regress-check", action="store_true",
                   help="gate this run against the history baselines AND "
                        "the committed throughput ratchet "
                        "(RATCHET_BASELINES/RATCHET_CEILINGS; exit 1 "
                        "when out of band)")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from gauss_tpu.utils.env import force_host_device_count, honor_jax_platforms

    if args.lanes:
        # One virtual device per lane (before jax initializes); with
        # fewer devices than lanes the placement cycles — still valid,
        # just oversubscribed.
        force_host_device_count(max(8, args.lanes))
    honor_jax_platforms()
    from gauss_tpu import obs

    ns = [int(n) for n in args.ns.split(",") if n]
    with obs.run(metrics_out=args.metrics_out, tool="gauss_tput",
                 ns=args.ns, batch=args.batch, dtype=args.dtype,
                 lanes=args.lanes) as rec:
        summary = measure_throughput(ns, batch=args.batch,
                                     dtype=args.dtype,
                                     refine_steps=args.refine_steps,
                                     reps=args.reps, seed=args.seed,
                                     lanes=args.lanes,
                                     run_id=rec.run_id)
    print(format_summary(summary))

    if args.summary_json:
        parent = os.path.dirname(args.summary_json)
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(args.summary_json, "w") as f:
            json.dump(summary, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"summary: {args.summary_json}")

    rc = 0
    if any(not leg["verified"] for leg in summary["legs"]):
        print("throughput: UNVERIFIED leg(s) — excluded from history",
              file=sys.stderr)
        rc = 2
    from gauss_tpu.obs import regress

    records = [{"metric": m, "value": v, "unit": u,
                "source": f"tput:{summary.get('run_id')}", "kind": "tput"}
               for m, v, u in history_records(summary)]
    if args.regress_check and records:
        history_path = args.history or regress.default_history_path()
        verdicts = regress.check_records(
            records, regress.load_history(history_path))
        for r in records:
            rv = regress.evaluate_ratchet(r["metric"], r["value"])
            if rv is not None:
                verdicts.append(rv)
        print(regress.format_verdicts(verdicts))
        if any(v["status"] == "out-of-band" for v in verdicts):
            rc = max(rc, 1)
    if args.history is not None and records and rc == 0:
        history_path = args.history or regress.default_history_path()
        added = regress.append_history(records, history_path)
        print(f"history: {added} record(s) appended to {history_path}")
    return rc


if __name__ == "__main__":
    sys.exit(main())
