"""Reference baseline numbers as data (transcribed in BASELINE.md).

Sources: Pthreads/report.pdf, OpenMP_and_MPI/Report.pdf,
CUDA_and_OpenMP/Report.pdf of the reference (tables quoted by title in
BASELINE.md, which carries the full provenance). Keys are (suite, key,
engine-class); values are seconds. Used by the grid harness to print
vs-reference columns next to measured numbers.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

# Gauss internal input, sequential, node2x14a (the machine the reference
# README derives its headline speedups from) — "Sequential Performances".
GAUSS_SEQ: Dict[int, float] = {
    128: 0.00411,
    256: 0.030433,
    512: 0.374293,
    1024: 1.310601,
    2048: 10.977564,
}

# Gauss internal input, n=2048, best cell per engine across thread counts
# ("Gaussian elimination — parallel, internal input" table).
GAUSS_2048_BEST: Dict[str, float] = {
    "pthreads-v1": 2.36825,     # 32 t, node2x18a
    "pthreads-v2": 2.970117,    # 16 t, node2x18a
    "pthreads-v3": 1.377353,    # 16 t + affinity, node2x18a
    "openmp": 0.509428,         # 70 t, node2x18a (the reference's best CPU)
    "mpi": 10.32634,            # 16 ranks single node, node2x18a
}

# Gauss external input, best-across-threads per engine, node2x18a
# ("Best Performances Cross Comparison").
GAUSS_EXTERNAL_BEST: Dict[str, Dict[str, float]] = {
    "jpwh_991": {"seq": 1.102551, "pthreads": 0.233257, "mpi": 1.221907,
                 "openmp": 0.084672},
    "orsreg_1": {"seq": 12.009902, "pthreads": 1.696003, "mpi": 9.948886,
                 "openmp": 0.600996},
    "sherman5": {"seq": 41.651507, "pthreads": 4.581856, "mpi": 31.15757,
                 "openmp": 1.957547},
    "saylr4": {"seq": 51.446487, "pthreads": 5.584708, "mpi": 38.58076,
               "openmp": 2.956282},
    "sherman3": {"seq": 143.196348, "pthreads": 14.846271, "mpi": 121.7746,
                 "openmp": 11.584218},
}

# Gauss internal input, MPI over the real 6-node Ethernet cluster
# ("Results on node01 to node06 (Distributed MPI Program)") — the
# reference's ONLY multi-node data; columns are mpirun -np rank counts.
GAUSS_DIST_MPI: Dict[int, Dict[int, float]] = {
    128: {2: 1.29592, 16: 0.167949, 32: 0.127643, 70: 0.162209},
    256: {2: 7.218069, 16: 0.763665, 32: 0.638781, 70: 0.720387},
    512: {2: 31.57587, 16: 3.805018, 32: 3.65404, 70: 3.889204},
    1024: {2: 154.7341, 16: 24.26487, 32: 23.72897, 70: 28.7057},
}

# Matmul, gpu-node1 (GTX 1080 / i7-7700K), "Performance Comparisons" time table.
MATMUL: Dict[str, Dict[int, float]] = {
    "seq": {1001: 1.02894, 1024: 1.39945, 2001: 22.3342, 2048: 66.4837},
    "openmp": {1001: 0.247864, 1024: 0.411193, 2001: 2.60929, 2048: 21.4269},
    "cuda-v1": {1001: 0.08397, 1024: 0.081569, 2001: 0.258896, 2048: 0.22632},
    "cuda-v2": {1001: 0.096222, 1024: 0.089706, 2001: 0.198037, 2048: 0.114906},
}

# Which reference engine class each of our backends competes with, per task.
# Device engines compete with the reference's overall best for that task:
# OpenMP for gauss (no CUDA gauss exists), CUDA V2 for matmul.
BACKEND_CLASS: Dict[str, str] = {
    "seq": "seq",
    "omp": "openmp",
    "threads": "pthreads-v3",
    "forkjoin": "pthreads-v1",
    "tiled": "pthreads-v2",
    "tpu-dist": "mpi",
    "tpu-dist2d": "mpi",
    "tpu-dist-blocked": "mpi",
    "tpu-dist-blocked2d": "mpi",
    "tpu": "openmp",
    "tpu-unblocked": "seq",
    "tpu-rowelim": "openmp",
    "tpu-rowelim-step": "openmp",
}

_MATMUL_CLASS: Dict[str, str] = {
    "seq": "seq",
    "omp": "openmp",
    "tpu": "cuda-v2",
    "tpu-pallas": "cuda-v2",
    "tpu-pallas-v1": "cuda-v1",
}

# The external-input report collapses the three pthreads versions into one
# "Pthreads" column; derive from BACKEND_CLASS so new backends stay in sync.
_EXTERNAL_CLASS = {k: ("pthreads" if v.startswith("pthreads") else v)
                   for k, v in BACKEND_CLASS.items()}


def reference_seconds(suite: str, key, backend: str) -> Optional[float]:
    """Reference wall-clock this (suite, size-or-matrix, backend) competes
    with, or None when the reports have no comparable cell."""
    if suite == "gauss-internal":
        cls = BACKEND_CLASS.get(backend)
        if key == 2048 and cls in GAUSS_2048_BEST:
            return GAUSS_2048_BEST[cls]
        if cls == "seq" or backend.startswith("tpu"):
            # Size sweep exists only for the sequential engine; device
            # engines fall back to it below 2048 (conservative comparator).
            return GAUSS_SEQ.get(key)
        return None
    if suite == "gauss-external":
        table = GAUSS_EXTERNAL_BEST.get(key)
        cls = _EXTERNAL_CLASS.get(backend)
        return table.get(cls) if table and cls else None
    if suite == "matmul":
        cls = _MATMUL_CLASS.get(backend)
        table = MATMUL.get(cls) if cls else None
        return table.get(key) if table else None
    if suite == "gauss-dist":
        # Best across rank counts for the size — the reference's strongest
        # distributed result is the anchor (hardware differs on both sides).
        table = GAUSS_DIST_MPI.get(key)
        return min(table.values()) if table else None
    raise ValueError(f"unknown suite {suite!r}")


def suite_keys(suite: str) -> Tuple:
    """The reference reports' sweep axis for a suite."""
    if suite == "gauss-internal":
        return tuple(GAUSS_SEQ)
    if suite == "gauss-external":
        return tuple(GAUSS_EXTERNAL_BEST)
    if suite == "matmul":
        return (1001, 1024, 2001, 2048)
    if suite == "gauss-dist":
        return tuple(GAUSS_DIST_MPI)
    raise ValueError(f"unknown suite {suite!r}")
