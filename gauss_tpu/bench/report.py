"""Benchmark report composer — the reference's three PDF reports, regenerated.

The reference documents its evaluation as three PDF reports (SURVEY.md §2
C11: `Pthreads/report.pdf`, `OpenMP_and_MPI/Report.pdf`,
`CUDA_and_OpenMP/Report.pdf`), each with the same anatomy: a hardware
banner, per-size timing tables, speedup tables against the sequential
baseline, a "Verification of Correctness" section, gprof hot-spot profiling,
and a closing "Inferences" narrative. This module composes the same report
from measured bench-grid cells (gauss_tpu.bench.grid), so the document is
always regenerated from verified numbers — never hand-edited.

Usage::

    python -m gauss_tpu.bench.grid --suite gauss-internal --json cells.json ...
    python -m gauss_tpu.bench.report cells.json more.json \
        --title "gauss-tpu report" --out reports/REPORT.md [--profile 1024]

Every number in the output comes from a Cell that passed its verification
check; unverified cells render as FAILED (same contract as the grid tables).
The "Inferences" section is computed from the data (best engine, scaling
exponents, reference deltas) — the narrative equivalent of the reference
reports' hand-written inference lists.
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import defaultdict
from typing import Dict, List, Optional, Sequence

SUITE_TITLES = {
    "gauss-internal": "Gaussian elimination — internal (synthetic) input",
    "gauss-external": "Gaussian elimination — external (.dat file) input",
    "matmul": "Dense matrix multiplication",
    "gauss-dist": "Gaussian elimination — distributed engines "
                  "(shard sweep, virtual CPU mesh — NOT ICI)",
    "gauss-precision": "Gaussian elimination — MXU GEMM precision sweep "
                       "(HIGHEST f32-emulation vs HIGH bf16x3, ds-refined)",
}

# Verification semantics per suite (the reference's scattered checks,
# SURVEY.md §4, unified in verify/checks.py).
SUITE_CHECKS = {
    "gauss-internal": "absolute residual ||Ax - b||_2 < 1e-4",
    "gauss-external": "manufactured-solution max relative error < 1e-4 "
                      "(X__[i] = i+1, R = A.X__)",
    "matmul": "scaled elementwise epsilon comparison vs float64 host truth, "
              "eps = 1e-4",
    "gauss-dist": "absolute residual ||Ax - b||_2 < 1e-4 (cells run on a "
                  "forced virtual CPU mesh: scaling shape and correctness, "
                  "NOT an ICI measurement; the reference comparator is the "
                  "best 6-node Distributed-MPI cell per size)",
    "gauss-precision": "absolute residual ||Ax - b||_2 < 1e-4 of the "
                       "double-single-refined solution (refinement inside "
                       "the timed chain for BOTH precisions)",
}

# The one-host interpretation that must ride WITH the dist numbers
# (VERDICT round 2 weak #5): without it, the sweep's inverse scaling reads
# as the engines failing to scale.
DIST_CAVEAT = (
    "**Reading this table:** all shards of the virtual mesh share ONE "
    "host's cores and memory bus, and XLA emulates collectives as local "
    "copies, so wall-clock GROWS with shard count by construction — more "
    "shards just means more copies through the same silicon. These cells "
    "validate correctness, collective structure, and relative engine cost "
    "at identical shard counts; they are NOT an ICI scaling measurement. "
    "The per-chip traffic/latency model for real meshes, with the "
    "jaxpr-counted collective budgets, is docs/SCALING.md.")


def _parse_sweep_key(key: str):
    """'1024 @4sh' -> (1024, 4); plain keys -> (key, None)."""
    base, _, tail = str(key).partition(" @")
    if tail.endswith("sh") and tail[:-2].isdigit() and base.isdigit():
        return int(base), int(tail[:-2])
    return key, None


def _dist_efficiency_table(cells: Sequence[dict]) -> Optional[List[str]]:
    """Per (size, engine): seconds at each shard count + parallel efficiency
    vs that engine's own smallest-shard cell (eff = t_s0 * s0 / (t_s * s)).
    On one host efficiency is expected to fall well below 100% — the table
    makes the shape explicit instead of leaving readers to infer it."""
    sweeps: Dict[tuple, Dict[int, dict]] = defaultdict(dict)
    for c in cells:
        n, shards = _parse_sweep_key(c["key"])
        if shards is None or not c["verified"]:
            continue
        sweeps[(n, c["backend"])][shards] = c
    if not sweeps:
        return None
    all_shards = sorted({s for v in sweeps.values() for s in v})
    head = ("| size | engine | " +
            " | ".join(f"{s} shards" for s in all_shards) + " |")
    lines = [head, "|---|---|" + "---|" * len(all_shards)]
    for (n, backend), by_shards in sweeps.items():
        s0 = min(by_shards)
        t0 = by_shards[s0]["seconds"]
        row = []
        for s in all_shards:
            c = by_shards.get(s)
            if c is None:
                row.append("—")
            elif s == s0:
                row.append(f"{_fmt_s(c['seconds'])} (base)")
            else:
                eff = t0 * s0 / (c["seconds"] * s) * 100.0
                row.append(f"{_fmt_s(c['seconds'])} ({eff:.0f}% eff)")
        lines.append(f"| {n} | {backend} | " + " | ".join(row) + " |")
    return lines


def _fmt_s(seconds: float) -> str:
    return f"{seconds:.6f}" if seconds < 100 else f"{seconds:.2f}"


def _cell_text(cell: dict) -> str:
    return _fmt_s(cell["seconds"]) if cell.get("verified") else "FAILED"


def _by_suite(cells: Sequence[dict]) -> Dict[str, List[dict]]:
    suites: Dict[str, List[dict]] = defaultdict(list)
    for c in cells:
        suites[c["suite"]].append(c)
    return suites


def _keys_in_order(cells: Sequence[dict]) -> List[str]:
    seen: List[str] = []
    for c in cells:
        if c["key"] not in seen:
            seen.append(c["key"])
    return seen


def _backends_in_order(cells: Sequence[dict]) -> List[str]:
    seen: List[str] = []
    for c in cells:
        if c["backend"] not in seen:
            seen.append(c["backend"])
    return seen


def _grid(cells: Sequence[dict]) -> Dict[str, Dict[str, dict]]:
    g: Dict[str, Dict[str, dict]] = defaultdict(dict)
    for c in cells:
        g[c["key"]][c["backend"]] = c
    return g


def _time_table(cells: Sequence[dict]) -> List[str]:
    keys, backends, grid = (_keys_in_order(cells), _backends_in_order(cells),
                            _grid(cells))
    lines = ["| size | " + " | ".join(backends) + " |",
             "|---|" + "---|" * len(backends)]
    for k in keys:
        row = [_cell_text(grid[k][b]) if b in grid[k] else "—"
               for b in backends]
        lines.append(f"| {k} | " + " | ".join(row) + " |")
    return lines


def _speedup_table(cells: Sequence[dict], base_backend: str = "seq"
                   ) -> Optional[List[str]]:
    """Speedup vs the sequential engine, the reference reports' main table.

    Rendered as the reference's percentage convention (README.md:5 quotes
    "2054% speedup" for 21.5x): percent = (t_seq / t - 1) * 100.
    """
    keys, backends, grid = (_keys_in_order(cells), _backends_in_order(cells),
                            _grid(cells))
    if not any(base_backend in grid[k] and grid[k][base_backend]["verified"]
               for k in keys):
        return None
    others = [b for b in backends if b != base_backend]
    lines = [f"| size | " + " | ".join(f"{b} speedup" for b in others) + " |",
             "|---|" + "---|" * len(others)]
    for k in keys:
        base = grid[k].get(base_backend)
        row = []
        for b in others:
            c = grid[k].get(b)
            if (base is None or c is None or not base["verified"]
                    or not c["verified"] or c["seconds"] <= 0):
                row.append("—")
            else:
                pct = (base["seconds"] / c["seconds"] - 1.0) * 100.0
                row.append(f"{pct:+.0f}%")
        lines.append(f"| {k} | " + " | ".join(row) + " |")
    return lines


def _base_key(key: str) -> str:
    """Thread-sweep labels '<n> @Tt' fold back to their base size."""
    return str(key).split(" @")[0]


def _reference_table(cells: Sequence[dict]) -> Optional[List[str]]:
    """Best verified engine per size vs the reference's best recorded time.

    Thread-sweep rows ('<n> @Tt') fold into their base size so every
    engine's best — including sweep-only native cells — competes in one
    row per size."""
    grid: Dict[str, List[dict]] = defaultdict(list)
    keys: List[str] = []
    for c in cells:
        k = _base_key(c["key"])
        if k not in keys:
            keys.append(k)
        grid[k].append(c)
    rows = []
    for k in keys:
        verified = [c for c in grid[k] if c["verified"]]
        with_ref = [c for c in grid[k]
                    if c.get("reference_s") is not None]
        if not verified or not with_ref:
            continue
        best = min(verified, key=lambda c: c["seconds"])
        ref_best = min(c["reference_s"] for c in with_ref)
        rows.append(
            f"| {k} | {_fmt_s(ref_best)} | {_fmt_s(best['seconds'])} "
            f"({best['backend']}) | {ref_best / best['seconds']:.1f}x |")
    if not rows:
        return None
    return (["| size | reference best (s) | this framework best (s) | "
             "speedup |", "|---|---|---|---|"] + rows)


def _scaling_exponent(cells: Sequence[dict],
                      backend: str) -> Optional[tuple]:
    """(fitted exponent p of t ~ n^p, n0, n1) over this backend's verified
    cells, or None when no adequately-separated size pair exists."""
    import math

    best: Dict[float, float] = {}
    for c in cells:
        if (c["backend"] == backend and c["verified"]
                and str(c["key"]).isdigit() and c["seconds"] > 0):
            nval = float(c["key"])
            best[nval] = min(best.get(nval, float("inf")), c["seconds"])
    if len(best) < 2:
        return None
    # Fit over the two LARGEST distinct sizes at least 1.5x apart (best
    # time per size — merged cell files can repeat a size): small sizes
    # sit on the dispatch/launch latency floor and would drag the exponent
    # toward 0 for engines that are genuinely cubic at scale, and
    # NEAR-ADJACENT sizes (2001 vs 2048, the padding-edge pair) amplify
    # timing noise into absurd exponents (n^33 was printed in an earlier
    # draft) — log(n1/n0) in the denominator needs a real gap.
    pairs = sorted(best.items())
    n1, t1 = pairs[-1]
    for n0, t0 in reversed(pairs[:-1]):
        if n1 / n0 >= 1.5:
            return (math.log(t1 / t0) / math.log(n1 / n0), n0, n1)
    return None


def _largest_key(keys: List[str]) -> Optional[str]:
    """The largest NUMERIC size, falling back to input order for named keys
    (dataset names, '@Tt' thread-sweep labels must not win by position)."""
    numeric = [k for k in keys if str(k).isdigit()]
    if numeric:
        return max(numeric, key=int)
    return keys[-1] if keys else None


def _inferences(suite: str, cells: Sequence[dict]) -> List[str]:
    """Data-derived bullets — the analog of the reports' 'Inferences'."""
    out: List[str] = []
    keys, grid = _keys_in_order(cells), _grid(cells)
    largest = _largest_key(keys)
    if largest and grid[largest]:
        verified = [c for c in grid[largest].values() if c["verified"]]
        if verified:
            best = min(verified, key=lambda c: c["seconds"])
            out.append(
                f"At the largest size ({largest}), the fastest verified "
                f"engine is **{best['backend']}** at "
                f"{_fmt_s(best['seconds'])} s.")
            seq = grid[largest].get("seq")
            if seq and seq["verified"] and best["backend"] != "seq":
                out.append(
                    f"Best-engine speedup over the sequential C++ baseline "
                    f"at {largest}: "
                    f"{seq['seconds'] / best['seconds']:.1f}x "
                    f"({(seq['seconds'] / best['seconds'] - 1) * 100:.0f}% "
                    f"in the reference reports' convention).")
            refs = [c["reference_s"] for c in grid[largest].values()
                    if c.get("reference_s") is not None]
            if refs:
                ref_best = min(refs)
                out.append(
                    f"Against the reference's best recorded time at "
                    f"{largest} ({_fmt_s(ref_best)} s on its hardware, "
                    f"BASELINE.md), the margin is "
                    f"{ref_best / best['seconds']:.1f}x.")
    for backend in _backends_in_order(cells):
        fit = _scaling_exponent(cells, backend)
        if fit is not None and backend.startswith("tpu"):
            p, n0, n1 = fit
            note = ("dispatch/latency-dominated below the cubic-work regime"
                    if p < 2.0 else "approaching the cubic-FLOP regime")
            out.append(f"`{backend}` scales as ~n^{p:.1f} across "
                       f"n={n0:g}->{n1:g} — {note}.")
    failed = [c for c in cells if not c["verified"]]
    if failed:
        out.append(f"{len(failed)} cell(s) FAILED verification and report "
                   "no time (see tables).")
    return out


def compose_report(cells: Sequence[dict], title: str, hardware: str,
                   profile_sections: Optional[Dict[str, str]] = None) -> str:
    """Compose the markdown report from grid-cell dicts (asdict(Cell))."""
    lines = [f"# {title}", "",
             "Regenerated from measured, verified benchmark-grid cells "
             "(`gauss_tpu.bench.grid` -> `gauss_tpu.bench.report`); the "
             "reference analog is its three report PDFs (SURVEY.md §2 C11).",
             "", f"**Hardware:** {hardware}", ""]
    # Two timing spans can coexist (grid --span): the reference programs'
    # transfer-inclusive span, and the device span (operands resident,
    # per-op seconds by the K-chain slope; bench/slope.py). Label device-span
    # engines so the columns are never silently mixed.
    from gauss_tpu.bench.grid import DEVICE_SPAN_MARK

    cells = [dict(c, backend=c["backend"] + DEVICE_SPAN_MARK)
             if c.get("span") == "device" else c for c in cells]
    if any(DEVICE_SPAN_MARK in c["backend"] for c in cells):
        lines += ["Engines marked `[device-span]` are timed by the on-device "
                  "K-chain slope method (dispatch/transfer offsets cancelled; "
                  "`gauss_tpu/bench/slope.py`); unmarked engines keep the "
                  "corresponding reference program's span, which on this "
                  "tunneled dev chip includes ~0.1-0.7 s of host-link "
                  "latency for device engines.", ""]
    suites = _by_suite(cells)
    for suite, suite_cells in suites.items():
        lines += [f"## {SUITE_TITLES.get(suite, suite)}", "",
                  "### Performance (seconds)", ""]
        lines += _time_table(suite_cells)
        if suite == "gauss-dist":
            eff = _dist_efficiency_table(suite_cells)
            if eff:
                lines += ["", "### Shard-sweep efficiency (one-host mesh)",
                          "", DIST_CAVEAT, ""]
                lines += eff
        if suite == "gauss-precision":
            notes = [f"- {c['key']}/{c['backend']}: {c['note']}"
                     for c in suite_cells if c.get("note")]
            if notes:
                lines += ["", "Measurement configuration per cell:", ""]
                lines += notes
        speedup = _speedup_table(suite_cells)
        if speedup:
            lines += ["", "### Speedup over the sequential engine", ""]
            lines += speedup
        ref = _reference_table(suite_cells)
        if ref:
            lines += ["", "### Comparison with the reference", ""]
            lines += ref
        lines += ["", "### Verification of correctness", "",
                  f"Every timed cell above passed: {SUITE_CHECKS[suite]}. "
                  "Unverified cells render as FAILED, never as a number."]
        failed = [c for c in suite_cells if not c["verified"]]
        if failed:
            lines += ["", "Failed cells: " + ", ".join(
                f"{c['key']}/{c['backend']}"
                + (f" — {c['note']}" if c.get("note") else "")
                for c in failed) + "."]
        inferences = _inferences(suite, suite_cells)
        if inferences:
            lines += ["", "### Inferences", ""]
            lines += [f"{i + 1}. {text}" for i, text in enumerate(inferences)]
        lines.append("")
    if profile_sections:
        lines += ["## Profiling of the algorithm", "",
                  "Per-phase wall-clock spans (the gprof analog; "
                  "`gauss_tpu.utils.profiling.PhaseTimer`):", ""]
        for label, table in profile_sections.items():
            lines += [f"### {label}", "", "```", table.rstrip(), "```", ""]
    return "\n".join(lines).rstrip() + "\n"


def hardware_banner() -> str:
    """Device + host description, the analog of the reports' machine specs."""
    parts = []
    try:
        import jax

        d = jax.devices()[0]
        parts.append(f"{d.device_kind} ({jax.device_count()} visible, "
                     f"platform {d.platform})")
    except Exception as e:  # no device: still produce a report
        parts.append(f"no accelerator ({e.__class__.__name__})")
    try:
        import os

        with open("/proc/cpuinfo") as f:
            models = [ln.split(":", 1)[1].strip() for ln in f
                      if ln.startswith("model name")]
        if models:
            parts.append(f"host CPU {models[0]} x{len(models)}")
        parts.append(f"{os.cpu_count()} host cores visible")
    except OSError:
        pass
    return "; ".join(parts)


def _profile_gauss(n: int, backend: str) -> str:
    """Run one profiled internal-input solve; returns the phase table."""
    from gauss_tpu.cli import _common
    from gauss_tpu.io import synthetic
    from gauss_tpu.utils.profiling import PhaseTimer

    timer = PhaseTimer()
    with timer.phase("initMatrix"):
        a, b = synthetic.internal_matrix(n), synthetic.internal_rhs(n)
    # refine_iters=2 matches the internal suite's configuration (the
    # synthetic system is exact in one f32 solve; see grid._run_gauss_internal).
    if backend.startswith("tpu"):
        # Steady-state profile (the gprof analog): jit compilation happens
        # once per program lifetime, not per solve — warm it outside the span.
        _common.solve_with_backend(a, b, backend, refine_iters=2)
    with timer.phase("computeGauss"):
        x, _ = _common.solve_with_backend(a, b, backend, refine_iters=2)
    with timer.phase("solveGauss (verify)"):
        from gauss_tpu.verify import checks

        checks.residual_norm(a, x, b)
    return timer.report()


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m gauss_tpu.bench.report", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("cells", nargs="+", help="bench-grid JSON files")
    ap.add_argument("--title", default="gauss-tpu benchmark report")
    ap.add_argument("--out", default=None,
                    help="output markdown path (default: stdout)")
    ap.add_argument("--profile", type=int, default=None, metavar="N",
                    help="also run a profiled n=N internal solve per backend")
    ap.add_argument("--profile-backends", default="tpu,seq",
                    help="backends for --profile (comma-separated)")
    args = ap.parse_args(argv)

    cells: List[dict] = []
    for path in args.cells:
        with open(path) as f:
            cells.extend(json.load(f))
    if not cells:
        print("report: no cells in input", file=sys.stderr)
        return 2

    profile_sections = None
    if args.profile:
        profile_sections = {}
        for backend in args.profile_backends.split(","):
            backend = backend.strip()
            label = f"gauss internal n={args.profile}, backend {backend}"
            try:
                profile_sections[label] = _profile_gauss(args.profile, backend)
            except Exception as e:
                profile_sections[label] = f"profiling failed: {e}"

    text = compose_report(cells, args.title, hardware_banner(),
                          profile_sections)
    if args.out:
        import os

        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            f.write(text)
        print(f"report: wrote {args.out} ({len(cells)} cells)")
    else:
        sys.stdout.write(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
