"""gauss-tpu: a TPU-native framework for parallel dense Gaussian elimination and
matrix multiplication.

Re-implements, TPU-first (JAX / XLA / Pallas / pjit), the capabilities of the
reference repo svdeepak99/Gaussian_Elimination-CUDA-OpenMP-MPI-Pthreads: the
reference ships 12 standalone C/CUDA programs that each duplicate one ~230-line
algorithmic skeleton (see reference Pthreads/Version-1/gauss_internal_input.c)
with a different parallel engine spliced into ``computeGauss``. This package
de-duplicates that into one algorithmic core with pluggable execution backends:

- ``gauss_tpu.io``      — .dat coordinate-format I/O + synthetic initializers
                          (reference matrices_dense/matrix_gen.cc:13-22 format)
- ``gauss_tpu.core``    — pure-JAX oracle implementations (sequential-C analog)
- ``gauss_tpu.kernels`` — Pallas TPU kernels (CUDA Version-1/2 analog)
- ``gauss_tpu.dist``    — shard_map/pjit multi-chip engines (MPI gauss_mpi analog)
- ``gauss_tpu.native``  — C++ host-side runtime: matrix generator, fast .dat
                          parser, seq/OpenMP/std::thread CPU baseline engines
- ``gauss_tpu.cli``     — drivers with reference-parity flags and output
- ``gauss_tpu.verify``  — manufactured-solution / residual / cross-backend checks
- ``gauss_tpu.obs``     — unified telemetry: run metrics, solver-phase spans,
                          numerical-health monitors, compile/memory accounting
                          (the persistent equivalent of the reference's
                          gettimeofday spans + gprof profiles)
- ``gauss_tpu.resilience`` — fault injection behind named hook points,
                          health-gated recovery ladders (solve_resilient),
                          checkpoint/resume for long factorizations, and the
                          chaos campaign runner (the reference aborts on a
                          bad pivot; this layer recovers or fails TYPED)
- ``gauss_tpu.structure`` — structure-aware solves: SPD/banded/block-diagonal
                          detection (straight off the .dat coordinate
                          stream) + blocked Cholesky, scan-Thomas/band-LU,
                          and vmap-batched block engines behind one
                          ``solve_auto`` router with recovery-ladder
                          demotion (the reference densifies everything)
"""

__version__ = "0.1.0"

from gauss_tpu import obs  # noqa: F401
from gauss_tpu.core.gauss import (  # noqa: F401
    eliminate,
    back_substitute,
    gauss_solve,
)
from gauss_tpu.core.matmul import matmul  # noqa: F401
