"""Double-single (two-float32) arithmetic for on-device residuals.

TPUs are f32-native; the reference's gauss programs compute residual-free in
f64 on the host CPU (every engine, e.g. gauss_external_input.c:304-315 checks
the solve in the same double precision it ran in). Round 1 computed
iterative-refinement residuals either in f64 on host (accurate, but a
host<->device round trip per iteration) or in plain f32 on device (stays on
device, but the matvec's own rounding noise floors refinement around 1e-7
relative — the memplus device-span cell FAILED the 1e-4 bar, VERDICT weak #2).

This module closes that gap with classical double-single arithmetic: a value
is an unevaluated pair ``hi + lo`` of float32s (~48 mantissa bits), built from
error-free transformations — Knuth's TwoSum and Dekker's split/TwoProd, which
need only IEEE add/sub/mul (no FMA primitive required, which JAX does not
expose). XLA preserves IEEE semantics for these ops (no unsafe reassociation),
so the transformations hold on TPU, CPU, and under the test meshes alike.

The one consumer-facing op is :func:`ds_residual`: ``r = b - A @ x`` with A,
b, x all double-single — every elementwise product error is captured, so the
result is accurate to ~2^-47 relative, far below what refinement against the
1e-4 bar needs even on the ill-conditioned reference matrices (saylr4's
effective condition ~1e6 amplifies residual noise into the solution; plain
f32 residuals stall it at ~3e-2 max-rel-err, double-single takes it below
1e-5 — see tests/test_dsfloat.py).

**Compiler constraint (hard-won):** XLA duplicates cheap ops into whichever
fusions consume them, and LLVM contracts a duplicated multiply with a
neighboring subtract into an FMA — so a Dekker-style error term can measure
against an infinitely-precise copy of ``a * b`` while the caller keeps the
rounded one, silently degrading results to plain-f32 accuracy (~1e-8
relative; measured on XLA:CPU, reproduced at will with broadcast operands;
``optimization_barrier`` is elided too early to help). The primitives here
are therefore built to be REWRITE-IMMUNE rather than rewrite-protected: the
operand split runs in the integer domain (:func:`_split`), and every float
multiply in :func:`_two_prod` is exact by construction, so any contracted
or duplicated copy has the same value. tests/test_dsfloat.py's tight
tolerances are the regression guard.

Cost model: O(n^2) vectorized VPU work against the O(n^3) factorization it
refines; A rides transposed so the reduction walks contiguous row groups,
not strided column gathers across (8, 128) tiles.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
import numpy as np

# Integer-domain split masks for float32: round the low 12 fraction bits
# away (half-up via the integer add, carry propagating into the exponent
# correctly), keeping 12 significant bits in hi so all hi/lo cross products
# are exact in f32.
_ROUND_HALF = 0x800
_TRUNC_MASK = 0xFFFFF000


class DS(NamedTuple):
    """A double-single array: value = hi + lo, |lo| <= ulp(hi)/2."""

    hi: jax.Array
    lo: jax.Array


def to_ds(a, dtype=jnp.float32) -> DS:
    """Split a float64 host array into a double-single device pair.

    hi = f32(a) captures the leading 24 bits, lo = f32(a - hi) the next 24 —
    together they carry the f64 value to ~2^-48 relative, enough that the
    original external-input matrices (parsed in f64) lose nothing that a
    1e-4 verification bar could see.

    Precondition: |a| must be comfortably inside float32 range
    (|a| < ~3.4e38, and in practice < ~1.7e38 so :func:`_split`'s
    round-half-up integer add cannot carry the exponent to inf). Outside it
    hi overflows to inf and lo to -inf, NaN-poisoning every downstream
    combination. Asserted here on the host operand — none of the reference
    matrices comes near the bound, but this module is general-purpose and a
    silent NaN residual would masquerade as a refinement failure.
    """
    a = np.asarray(a, np.float64)
    if a.size and float(np.max(np.abs(a))) >= 1.7e38:
        raise ValueError(
            "to_ds operand exceeds the double-single representable range "
            f"(max |a| = {float(np.max(np.abs(a))):.3e} >= 1.7e38); the f32 "
            "hi part would overflow to inf and NaN-poison residuals")
    hi = a.astype(np.float32)
    lo = (a - hi.astype(np.float64)).astype(np.float32)
    return DS(jnp.asarray(hi, dtype), jnp.asarray(lo, dtype))


def ds_to_f64(x: DS) -> np.ndarray:
    """Exact host read-back: hi and lo are both representable in f64."""
    return np.asarray(x.hi, np.float64) + np.asarray(x.lo, np.float64)


def _two_sum(a, b):
    """Knuth TwoSum: s + e == a + b exactly, s = fl(a + b).

    Compiler-safety: the expression uses only adds/subtracts of values that
    are either loop carries or EXACT products (see :func:`_two_prod`), so
    XLA op duplication and LLVM FMA contraction cannot produce a second,
    differently-rounded copy of any operand — every rewrite is
    value-preserving. (The classic Dekker formulation with ``p = a * b`` of
    full-mantissa operands is NOT safe: XLA duplicates the cheap multiply
    into the error-term fusion, LLVM contracts it with the neighboring
    subtract into an FMA, and the error term then measures against an
    infinitely-precise product while the caller keeps the rounded one —
    measured f32-level corruption on XLA:CPU; ``optimization_barrier`` is
    elided too early to prevent it.)
    """
    s = a + b
    bb = s - a
    e = (a - (s - bb)) + (b - bb)
    return s, e


def _quick_two_sum(a, b):
    """Fast TwoSum, valid when |a| >= |b| (renormalization step)."""
    s = a + b
    return s, b - (s - a)


def _split(a):
    """Round-to-12-significant-bits split: a == hi + lo, products of any two
    hi/lo parts exact in f32.

    Done in the INTEGER domain — add half of the dropped ulp (carry rides
    into the exponent correctly, round-half-up) and mask the low 12 fraction
    bits — so no float identity is involved and no compiler rewrite can
    change the result. ``lo = a - hi`` is exact (Sterbenz: hi is within an
    ulp12 of a), with at most 12 significant bits itself.
    """
    bits = lax.bitcast_convert_type(a, jnp.uint32)
    hi_bits = (bits + jnp.uint32(_ROUND_HALF)) & jnp.uint32(_TRUNC_MASK)
    hi = lax.bitcast_convert_type(hi_bits, a.dtype)
    return hi, a - hi


def _two_prod(a, b):
    """TwoProd from exact partial products: p + e == a * b to ~2^-58.

    With 12-bit splits, ah*bh, ah*bl, al*bh, al*bl are all EXACT f32
    products; the pair (p, e) is assembled with TwoSums, so the only
    uncaptured rounding is on the e-channel combination (~2^-58 relative).
    Unlike Dekker's formulation there is no full-mantissa ``a * b`` whose
    rounded value the error term must agree with — the scheme is immune to
    FMA contraction and op duplication by construction (every multiply is
    exact, so every contracted or duplicated copy has the same value).
    """
    ah, al = _split(a)
    bh, bl = _split(b)
    s1, e1 = _two_sum(ah * bh, ah * bl)
    s2, e2 = _two_sum(s1, al * bh)
    e = e1 + e2 + al * bl
    return s2, e


def ds_add(x: DS, y: DS) -> DS:
    """Double-single addition with renormalization."""
    s, e = _two_sum(x.hi, y.hi)
    e = e + (x.lo + y.lo)
    return DS(*_quick_two_sum(s, e))


def ds_neg(x: DS) -> DS:
    return DS(-x.hi, -x.lo)


def ds_from_f32(a) -> DS:
    return DS(a, jnp.zeros_like(a))


_GROUP = 8    # sublane-aligned row group; tree-reduced before the fori loop
_STRIP = 512  # rows per product strip: bounds live product/error buffers to
              # O(_STRIP * m) instead of O(n * m) (memplus would otherwise
              # hold two extra ~1.26 GB matrices inside the timed chain)


def _accumulate_strip(rows: DS, x_strip: DS, acc):
    """Fold one (S, m) strip of transposed-A rows into the ds accumulator:
    vectorized exact products, 8-row tree reduction (three ds_add levels),
    then a compensated adds-only fori over the S/8 group partials. S must be
    a multiple of _GROUP."""
    s_rows, m = rows.hi.shape
    P, E = _two_prod(rows.hi, x_strip.hi[:, None])
    E = E + (rows.hi * x_strip.lo[:, None] + rows.lo * x_strip.hi[:, None])
    P = P.reshape(s_rows // _GROUP, _GROUP, m)
    E = E.reshape(s_rows // _GROUP, _GROUP, m)
    g = _GROUP
    while g > 1:
        h = g // 2
        a = ds_add(DS(P[:, :h], E[:, :h]), DS(P[:, h:g], E[:, h:g]))
        P, E = a.hi, a.lo
        g = h
    P = P[:, 0]
    E = E[:, 0]

    def body(j, acc):
        acc_hi, acc_lo = acc
        p = lax.dynamic_index_in_dim(P, j, 0, keepdims=False)
        pe = lax.dynamic_index_in_dim(E, j, 0, keepdims=False)
        s, e2 = _two_sum(acc_hi, p)
        lo = acc_lo + (e2 + pe)
        return _quick_two_sum(s, lo)

    return lax.fori_loop(0, s_rows // _GROUP, body, acc)


@jax.jit
def ds_matvec(at: DS, x: DS) -> DS:
    """Double-single ``A @ x`` where ``at`` is A TRANSPOSED, shape (n, m).

    result[i] = sum_j A[i, j] * x[j] = sum_j at[j, i] * x[j], computed
    strip by strip (_STRIP rows at a time, so peak extra memory is
    O(_STRIP * m), not O(n * m)): each strip's elementwise products are
    vectorized with exact TwoProd error capture (the ds-cross terms hi*lo
    ride in the error channel; lo*lo is below 2^-48 and dropped) — the
    rewrite-immune primitives are the correctness mechanism, see the module
    docstring — then tree-reduced per 8-row group and folded into the
    (hi, lo) accumulator with adds-only TwoSum compensation.

    Result error ~n * 2^-47 * |A||x| — residual-grade accuracy without f64
    emulation or a host round trip.
    """
    n, m = at.hi.shape
    dtype = at.hi.dtype
    zero = jnp.zeros((m,), dtype)
    acc = (zero, zero)

    n_full = (n // _STRIP) * _STRIP
    if n_full:
        def strip_body(k, acc):
            start = k * _STRIP
            rows = DS(lax.dynamic_slice(at.hi, (start, 0), (_STRIP, m)),
                      lax.dynamic_slice(at.lo, (start, 0), (_STRIP, m)))
            xs = DS(lax.dynamic_slice(x.hi, (start,), (_STRIP,)),
                    lax.dynamic_slice(x.lo, (start,), (_STRIP,)))
            return _accumulate_strip(rows, xs, acc)

        acc = lax.fori_loop(0, n_full // _STRIP, strip_body, acc)
    if n_full != n:  # tail strip, zero-padded to a group multiple (zeros
        tail = n - n_full  # are TwoSum identities)
        tpad = -(-tail // _GROUP) * _GROUP - tail
        rows = DS(jnp.pad(at.hi[n_full:], ((0, tpad), (0, 0))),
                  jnp.pad(at.lo[n_full:], ((0, tpad), (0, 0))))
        xs = DS(jnp.pad(x.hi[n_full:], (0, tpad)),
                jnp.pad(x.lo[n_full:], (0, tpad)))
        acc = _accumulate_strip(rows, xs, acc)
    return DS(*acc)


@jax.jit
def ds_residual(at: DS, x: DS, b: DS) -> DS:
    """``b - A @ x`` in double-single (``at`` = A transposed)."""
    ax = ds_matvec(at, x)
    return ds_add(b, ds_neg(ax))


@partial(jax.jit, static_argnames=("iters", "solve_fn", "tol",
                                   "return_iters"), donate_argnums=(3,))
def refine_ds(fac, at: DS, b: DS, x0, iters: int = 3, solve_fn=None,
              tol: float = 0.0, return_iters: bool = False):
    """On-device iterative refinement with double-single residuals.

    fac: a :class:`gauss_tpu.core.blocked.BlockedLU` of A — f32, or a
    LOWERED (bfloat16 / bf16x3-updated) factor: the correction solve runs
    in the factor's accumulate dtype (``blocked.lu_solve`` precision
    contract), so one refinement implementation serves every storage
    dtype on the demotion ladder.
    at:  A transposed, double-single (from :func:`to_ds` of the f64 matrix).
    b:   right-hand side, double-single.
    x0:  initial f32 solve ``lu_solve(fac, b.hi)``.
    solve_fn: the correction solver ``(fac, r) -> d`` (static; default
    ``blocked.lu_solve``). The structure engines thread their own — e.g.
    ``structure.cholesky.cholesky_solve`` — so every factorization family
    shares ONE double-single refinement implementation.

    ``tol`` (static): when > 0, an iteration whose double-single residual
    already satisfies ``||r||_2 <= tol * ||b||_2`` applies NO update (the
    masked form of early exit — the compiled program still runs ``iters``
    bodies, but a converged carry stops changing and the iteration count
    stops advancing). ``return_iters=True`` returns ``(x, used)`` with
    ``used`` the number of iterations that actually updated — the
    surfaced count the tuner's refine-steps-vs-dtype measurement needs
    (gauss_tpu.tune, op "lowered"); with the defaults the return value
    and the traced program are exactly the pre-existing ones, so every
    existing caller is unchanged.

    ``x0``'s buffer is DONATED (it seeds the solution carry and is dead in
    the caller by contract — every call site passes the fresh initial
    solve); on backends that honor donation the refine loop reuses it
    instead of allocating a new carry per entry. Inline-traced calls (the
    bench chains) are unaffected — donation only applies at top level.
    Each iteration: r = b - A x (double-single), d = solve_fn(fac, r.hi +
    r.lo collapsed to f32 — the correction only needs f32 relative
    accuracy), and a double-single solution update. The whole loop compiles
    into the caller's program; nothing touches the host.
    """
    if solve_fn is None:
        from gauss_tpu.core.blocked import lu_solve as solve_fn

    x = ds_from_f32(x0)
    if tol <= 0.0 and not return_iters:
        # The pre-existing trace, bit for bit.
        for _ in range(iters):
            r = ds_residual(at, x, b)
            d = solve_fn(fac, r.hi + r.lo)
            x = ds_add(x, ds_from_f32(d))
        return x

    thresh = jnp.asarray(tol, jnp.float32) * jnp.sqrt(
        jnp.sum(jnp.square(b.hi.astype(jnp.float32))))
    used = jnp.asarray(0, jnp.int32)
    active = jnp.asarray(True)
    for _ in range(iters):
        r = ds_residual(at, x, b)
        rc = r.hi + r.lo
        rnorm = jnp.sqrt(jnp.sum(jnp.square(rc.astype(jnp.float32))))
        step = active & (rnorm > thresh) if tol > 0.0 else active
        d = solve_fn(fac, rc)
        xn = ds_add(x, ds_from_f32(d))
        x = DS(jnp.where(step, xn.hi, x.hi), jnp.where(step, xn.lo, x.lo))
        used = used + step.astype(jnp.int32)
        active = step
    return (x, used) if return_iters else x


# Default refinement step count: enough for the worst-conditioned reference
# matrix (saylr4, effective condition ~1e6, contraction ~0.15/step) with
# margin. The single source for solve_ds, bench.slope, and bench.grid.
DS_REFINE_STEPS = 6


def solve_once_ds(a, at_ds: DS, b_ds: DS, panel: int | None,
                  iters: int = DS_REFINE_STEPS, unroll="auto",
                  gemm_precision: str = "highest",
                  donate: bool = False,
                  factor_dtype: "str | None" = None) -> "tuple[DS, object]":
    """One jittable f32 factor + solve + double-single refinement pass.

    ``a`` is the f32 matrix (factor operand); ``at_ds``/``b_ds`` the
    double-single transposed matrix and RHS (residual operands). Returns
    ``(x_ds, factors)`` — the refined double-single solution and the
    :class:`gauss_tpu.core.blocked.BlockedLU` it solved through, so callers
    can reuse the factorization for further solves. The single assembly
    point shared by :func:`solve_ds` and the bench timing chain
    (bench.slope.gauss_solve_once_ds) — what gets timed is exactly what
    gets verified.

    ``donate=True`` hands ``a``'s buffer to the factorization
    (resolve_factor's donating twin) — only for callers that own it;
    :func:`solve_ds` opts in for the operand it stages itself, the bench
    chains (where the call is traced inline and donation is moot) and the
    staged-operand timing paths do not.

    ``factor_dtype``: an optional LOWERED storage name from
    ``gauss_tpu.core.lowered.LOWERED_DTYPES`` — "bfloat16" casts the
    factor operand down (the refinement residual operands stay
    double-single f32), "bf16x3" keeps f32 storage but runs the trailing
    updates through the explicit split-GEMM. None/"float32" is the
    pre-existing path, unchanged. This is the timing-chain hook the
    bench grid's ``--dtype`` column rides (the timed chain IS the
    verified configuration).
    """
    from gauss_tpu.core import blocked

    if factor_dtype not in (None, "float32"):
        if factor_dtype == "bf16x3":
            gemm_precision = "bf16x3"
        else:
            a = jnp.asarray(a).astype(jnp.dtype(factor_dtype))
    factor = blocked.resolve_factor(a.shape[0], unroll, donate=donate)
    fac = factor(a, panel=panel, gemm_precision=gemm_precision)
    x0 = blocked.lu_solve(fac, b_ds.hi)
    return refine_ds(fac, at_ds, b_ds, x0, iters=iters), fac


def solve_ds(a, b, iters: int = DS_REFINE_STEPS, panel: int | None = None,
             unroll="auto"):
    """Fully on-device mixed-precision solve: f32 blocked factorization +
    double-single refinement; returns (x_float64, factors).

    The device-resident sibling of :func:`gauss_tpu.core.blocked.
    solve_refined` — same contract, but residuals never leave the device
    (no host f64 matvec, no per-iteration H2D/D2H round trip), so it belongs
    in jitted pipelines and honest device-span timing. Each refinement step
    is O(n^2) against the O(n^3) factorization.
    """
    a64 = np.asarray(a, np.float64)
    b64 = np.asarray(b, np.float64)
    n = len(b64)
    from gauss_tpu.core.blocked import _resolve_panel

    # The f32 factor operand is staged HERE and dead after the factor —
    # donate it (unpadded shapes only; a padded donation is unusable).
    donate = n % _resolve_panel(n, panel) == 0
    x, fac = solve_once_ds(jnp.asarray(a64, jnp.float32), to_ds(a64.T),
                           to_ds(b64), panel, iters=iters, unroll=unroll,
                           donate=donate)
    return ds_to_f64(x), fac
