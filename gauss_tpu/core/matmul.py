"""Dense matrix multiplication core.

The reference implements matmul three ways in one binary — ``seq_matmul``,
``omp_matmul``, and the CUDA ``gpu_matmul`` kernels (reference
CUDA_and_OpenMP/Version-1/cuda_matmul.cu:28-103). On TPU the idiomatic
equivalent of all three is a single ``jnp.dot`` under jit: XLA tiles it onto
the 128x128 MXU systolic array, which is precisely the role the CUDA grids
play on the GTX 1080. A hand-written Pallas tile kernel (the CUDA Version-2
analog) lives in :mod:`gauss_tpu.kernels.matmul_pallas`.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

PRECISIONS = {
    # float32 inputs on MXU: "highest" is the 6-pass f32 emulation (26.5
    # TFLOP/s on v5e), "high" the bf16x3 scheme (51 TFLOP/s), "default" a
    # single bf16 pass (157 TFLOP/s). The reference verifies at eps=1e-4
    # (cuda_matmul.cu:13,61-72): single-pass bf16 fails that at n >= 512,
    # but "high" passes with ~10x margin on both the reference inputs and
    # random matrices at every report size (measured scaled max diff
    # <= 1.2e-5 at n=2048) — so "high" is the default and "highest" remains
    # one flag away.
    "highest": jax.lax.Precision.HIGHEST,
    "high": jax.lax.Precision.HIGH,
    "default": jax.lax.Precision.DEFAULT,
}


def resolve_precision(name: str):
    """Shared precision-name resolution for every matmul engine and the
    blocked LU (single source; kernels.matmul_pallas re-exports it)."""
    try:
        return PRECISIONS[name]
    except KeyError:
        raise ValueError(f"unknown precision {name!r}; "
                         f"options: {tuple(PRECISIONS)}") from None


@partial(jax.jit, static_argnames=("precision",))
def matmul(a: jax.Array, b: jax.Array, precision: str = "high") -> jax.Array:
    """C = A @ B on the MXU. Shapes (m, k) x (k, n) -> (m, n)."""
    return jnp.dot(a, b, precision=resolve_precision(precision))
