"""Dense matrix multiplication core.

The reference implements matmul three ways in one binary — ``seq_matmul``,
``omp_matmul``, and the CUDA ``gpu_matmul`` kernels (reference
CUDA_and_OpenMP/Version-1/cuda_matmul.cu:28-103). On TPU the idiomatic
equivalent of all three is a single ``jnp.dot`` under jit: XLA tiles it onto
the 128x128 MXU systolic array, which is precisely the role the CUDA grids
play on the GTX 1080. A hand-written Pallas tile kernel (the CUDA Version-2
analog) lives in :mod:`gauss_tpu.kernels.matmul_pallas`.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

_PRECISIONS = {
    # float32 inputs on MXU: "highest" runs the 6-pass f32 emulation, "default"
    # allows bf16x3/bf16 passes. We default to highest: the reference computes
    # in double (gauss) / float (matmul) and verifies at eps=1e-4
    # (cuda_matmul.cu:13,61-72), which bf16 single-pass would not meet at n=2048.
    "highest": jax.lax.Precision.HIGHEST,
    "default": jax.lax.Precision.DEFAULT,
}


@partial(jax.jit, static_argnames=("precision",))
def matmul(a: jax.Array, b: jax.Array, precision: str = "highest") -> jax.Array:
    """C = A @ B on the MXU. Shapes (m, k) x (k, n) -> (m, n)."""
    return jnp.dot(a, b, precision=_PRECISIONS[precision])
