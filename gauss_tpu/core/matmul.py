"""Dense matrix multiplication core.

The reference implements matmul three ways in one binary — ``seq_matmul``,
``omp_matmul``, and the CUDA ``gpu_matmul`` kernels (reference
CUDA_and_OpenMP/Version-1/cuda_matmul.cu:28-103). On TPU the idiomatic
equivalent of all three is a single ``jnp.dot`` under jit: XLA tiles it onto
the 128x128 MXU systolic array, which is precisely the role the CUDA grids
play on the GTX 1080. A hand-written Pallas tile kernel (the CUDA Version-2
analog) lives in :mod:`gauss_tpu.kernels.matmul_pallas`.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

PRECISIONS = {
    # float32 inputs on MXU: "highest" is the 6-pass f32 emulation (26.5
    # TFLOP/s on v5e), "high" the bf16x3 scheme (51 TFLOP/s), "default" a
    # single bf16 pass (157 TFLOP/s). The reference verifies at eps=1e-4
    # (cuda_matmul.cu:13,61-72): single-pass bf16 fails that at n >= 512,
    # but "high" passes with ~10x margin on both the reference inputs and
    # random matrices at every report size (measured scaled max diff
    # <= 1.2e-5 at n=2048) — so "high" is the default and "highest" remains
    # one flag away.
    "highest": jax.lax.Precision.HIGHEST,
    "high": jax.lax.Precision.HIGH,
    "default": jax.lax.Precision.DEFAULT,
}

#: The EXPLICIT Ootomo-style split-GEMM precision name (ISSUE 11): f32
#: operands split into bf16 hi/lo pairs and multiplied in THREE bf16 GEMMs
#: with f32 accumulation (:func:`dot_bf16x3`). On a real TPU
#: ``lax.Precision.HIGH`` lowers f32 dots to the same three-pass scheme in
#: hardware; this software form has DEFINED semantics on every backend
#: (the CPU proxy included), so the mixed-precision ladder's middle rung
#: is testable and bit-stable anywhere. Only the call sites that opt in
#: (``resolve_precision(..., allow_split=True)`` — the blocked LU's
#: trailing updates and :func:`matmul`) accept it; everywhere else it
#: stays a typed ValueError rather than a raw trace error.
BF16X3 = "bf16x3"


def resolve_precision(name: str, allow_split: bool = False):
    """Shared precision-name resolution for every matmul engine and the
    blocked LU (single source; kernels.matmul_pallas re-exports it).

    ``allow_split=True`` additionally admits :data:`BF16X3`, returned as
    the sentinel string — the caller routes it to :func:`dot_bf16x3`
    instead of passing it to ``jnp.dot``."""
    if name == BF16X3:
        if allow_split:
            return BF16X3
        raise ValueError(
            f"precision {BF16X3!r} (the explicit split-GEMM) is only "
            f"supported by the blocked-LU trailing updates and matmul; "
            f"options here: {tuple(PRECISIONS)}")
    try:
        return PRECISIONS[name]
    except KeyError:
        raise ValueError(f"unknown precision {name!r}; "
                         f"options: {tuple(PRECISIONS) + (BF16X3,)}") from None


def split_bf16(x: jax.Array):
    """Two-way Ootomo split: ``x ≈ hi + lo`` with both parts bfloat16.

    ``hi`` keeps the leading 8 mantissa bits, ``lo`` the next 8 (the
    rounding residual re-rounded to bf16) — together ~16 of f32's 24
    bits. Products of two 8-bit-mantissa operands need 16 bits, so every
    partial product is EXACT inside an f32-accumulating MXU pass."""
    hi = x.astype(jnp.bfloat16)
    lo = (x - hi.astype(x.dtype)).astype(jnp.bfloat16)
    return hi, lo


def dot_bf16x3(x: jax.Array, y: jax.Array) -> jax.Array:
    """``x @ y`` in float32, emulated as THREE bf16 GEMMs (Ootomo-style).

    With 2-way splits ``x = xh + xl``, ``y = yh + yl`` the product is
    ``xh·yh + xh·yl + xl·yh`` (the dropped ``xl·yl`` term is ~2^-32
    relative); each pass multiplies bf16 operands with
    ``preferred_element_type=float32`` accumulation — the MXU's native
    mode. Result error ~1e-5 relative on the report sizes (the same
    fidelity class as ``lax.Precision.HIGH`` on TPU; measured in
    tests/test_lowered.py), i.e. ~100x tighter than a plain bf16 pass —
    the middle rung of the precision-demotion ladder."""
    xh, xl = split_bf16(x)
    yh, yl = split_bf16(y)

    def p(u, v):
        return jnp.dot(u, v, preferred_element_type=jnp.float32)

    return p(xh, yh) + (p(xh, yl) + p(xl, yh))


@partial(jax.jit, static_argnames=("precision",))
def matmul(a: jax.Array, b: jax.Array, precision: str = "high") -> jax.Array:
    """C = A @ B on the MXU. Shapes (m, k) x (k, n) -> (m, n).

    ``precision="bf16x3"`` runs the explicit split-GEMM
    (:func:`dot_bf16x3`) instead of a precision-flagged ``jnp.dot``."""
    prec = resolve_precision(precision, allow_split=True)
    if prec == BF16X3:
        return dot_bf16x3(a, b)
    return jnp.dot(a, b, precision=prec)
