"""Lowered-precision factorization refined back to the 1e-4 gate.

The MXU's native low-precision modes are the one substrate PR 10's record
path did not exploit: bfloat16 halves itemsize — `panel_fits_vmem` /
`fused_fits_vmem` admit ~2x the working set and every HBM/VMEM stream
moves half the bytes — and a single bf16 MXU pass runs ~6x the f32
(HIGHEST) rate on v5e. This module packages that as a SOLVE with the same
1e-4 guarantee everything else in the repo carries, in the spirit of
mixed-precision iterative-refinement LU (Haidar et al.'s tensor-core
solvers) and Ootomo-style bf16x3 emulated-f32 GEMM:

- **The dtype ladder** (:data:`LOWERED_DTYPES`, cheapest first):
  ``bfloat16`` (bf16 storage, f32-accumulate trailing updates — the
  precision contract in ``core.blocked``), ``bf16x3`` (f32 storage, the
  explicit three-bf16-pass split-GEMM trailing update,
  ``core.matmul.dot_bf16x3`` — for systems whose conditioning makes plain
  bf16 refinement too slow or divergent), ``float32`` (the pre-existing
  path, always the terminal rung).
- **Refinement back to the gate.** Every lowered factor is refined by the
  EXISTING double-single machinery (``dsfloat.refine_ds`` — residuals in
  ~2^-47 arithmetic, corrections through the lowered factor's f32-accuracy
  solves), with the surfaced iteration count as the convergence
  measurement. A solve that cannot reach the gate at its refine budget
  raises the typed :class:`PrecisionNotConvergedError`.
- **Deterministic demotion.** :func:`solve_lowered_auto` walks the ladder
  from the tuned starting dtype down to float32 — the same demotion shape
  as structure mistags (``structure.router``): typed failure, next rung,
  never a silent wrong answer. The (dtype, refine_steps) starting point is
  a TUNED axis (``tune.space`` op ``"lowered"``): the seed is float32 —
  zero behavior change without a store — and an offline ``gauss-tune
  --ops lowered`` sweep records the cheapest converging pair per
  (n-bucket, device), which ``solve_auto`` and the serve layer then pick
  up.

Contraction intuition (why the ladder is shaped this way): one refinement
step contracts the error by ~(factor relative error) x (condition
number). bf16 storage rounds the factor at ~4e-3, so well-conditioned
systems converge in 2-3 steps and cond >~ 1e2 systems stall; bf16x3
updates land at ~1e-5 — roughly ``lax.Precision.HIGH``'s class — covering
the mid-conditioned band; float32 + double-single remains the backstop
that clears the reference's worst matrices (saylr4, cond ~1e6).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from gauss_tpu import obs
from gauss_tpu.verify import checks

#: the demotion ladder, cheapest first; float32 is always the terminal rung.
LOWERED_DTYPES = ("bfloat16", "bf16x3", "float32")

#: the acceptance bar every rung refines back to (the reference EPSILON).
DEFAULT_GATE = 1e-4

#: refine_ds stops updating once the DS residual is under
#: ``gate * margin * ||b||`` — comfortably inside the gate, so the
#: surfaced iteration count measures convergence TO the contract, not to
#: the last representable bit.
REFINE_TOL_MARGIN = 0.1

#: default refinement budget per dtype (trace-time cap; the masked early
#: exit stops updating — and counting — once converged). bf16's ~4e-3
#: factor error needs more headroom than bf16x3's ~1e-5; float32 keeps
#: the dsfloat default that clears saylr4.
DEFAULT_REFINE_STEPS = {"bfloat16": 8, "bf16x3": 4, "float32": 6}


class PrecisionNotConvergedError(RuntimeError):
    """A lowered solve could not refine back to the gate at its budget.

    The typed demotion signal: :func:`solve_lowered_auto` catches it and
    drops one rung down the dtype ladder; the recovery ladder
    (``resilience.recover``) records it as ``exception:...`` and
    escalates — either way the caller ends verified or typed, never
    silently wrong."""

    def __init__(self, dtype: str, refine_steps: int, rel_residual: float,
                 gate: float):
        super().__init__(
            f"lowered dtype {dtype!r} did not reach the {gate:.0e} gate "
            f"after {refine_steps} refinement step(s) (relative residual "
            f"{rel_residual:.3e}); demote down LOWERED_DTYPES")
        self.dtype = dtype
        self.refine_steps = refine_steps
        self.rel_residual = rel_residual
        self.gate = gate


def _storage_and_precision(dtype: str):
    """(jnp storage dtype, gemm_precision) for a ladder dtype name."""
    import jax.numpy as jnp

    if dtype == "bfloat16":
        return jnp.bfloat16, "highest"
    if dtype == "bf16x3":
        return jnp.float32, "bf16x3"
    if dtype == "float32":
        return jnp.float32, "highest"
    raise ValueError(f"unknown lowered dtype {dtype!r}; options: "
                     f"{LOWERED_DTYPES}")


def default_refine_steps(dtype: str) -> int:
    try:
        return DEFAULT_REFINE_STEPS[dtype]
    except KeyError:
        raise ValueError(f"unknown lowered dtype {dtype!r}; options: "
                         f"{LOWERED_DTYPES}") from None


def solve_lowered(a, b, dtype: str = "bfloat16",
                  refine_steps: Optional[int] = None,
                  panel: Optional[int] = None, unroll="auto",
                  gate: float = DEFAULT_GATE,
                  ) -> Tuple[np.ndarray, object, dict]:
    """One lowered factor + double-single refinement pass, gated.

    Returns ``(x_float64, factors, info)`` — ``info`` carries the dtype,
    the MEASURED refinement count (how many steps actually updated before
    the masked early exit), and the final relative residual; these are the
    provenance fields bench records and the tuner's refine-steps
    measurement consume. Raises :class:`PrecisionNotConvergedError` when
    the budget was not enough — demotion is the CALLER's move
    (:func:`solve_lowered_auto` / the recovery ladder), so a direct call
    stays an honest single-configuration measurement.
    """
    import jax.numpy as jnp

    from gauss_tpu.core import blocked, dsfloat

    a64 = np.asarray(a, np.float64)
    b64 = np.asarray(b, np.float64)
    n = len(b64)
    storage, gemm_precision = _storage_and_precision(dtype)
    if refine_steps is None:
        refine_steps = default_refine_steps(dtype)
    itemsize = jnp.dtype(storage).itemsize
    # The staged operand is owned here and dead after the factor: donate
    # (panel-multiple shapes only — a padded donation is unusable).
    donate = n % blocked._resolve_panel(n, panel, itemsize) == 0
    a_dev = jnp.asarray(a64, storage)
    factor = blocked.resolve_factor(n, unroll, donate=donate)
    fac = factor(a_dev, panel=panel, gemm_precision=gemm_precision)
    at_ds = dsfloat.to_ds(a64.T)
    b_ds = dsfloat.to_ds(b64)
    x0 = blocked.lu_solve(fac, b_ds.hi)
    x, used = dsfloat.refine_ds(fac, at_ds, b_ds, x0, iters=refine_steps,
                                tol=gate * REFINE_TOL_MARGIN,
                                return_iters=True)
    x64 = dsfloat.ds_to_f64(x)
    used = int(used)
    rel = checks.residual_norm(a64, x64, b64, relative=True)
    obs.emit("precision", dtype=dtype, n=n, refine_steps=used,
             budget=refine_steps, rel_residual=float(f"{rel:.3e}"),
             converged=bool(rel <= gate))
    if not rel <= gate:
        obs.counter("precision.not_converged")
        raise PrecisionNotConvergedError(dtype, used, rel, gate)
    return x64, fac, {"dtype": dtype, "refine_steps": used,
                      "rel_residual": rel}


def lowered_params(n: int) -> Tuple[str, Optional[int]]:
    """The tuned (dtype, refine_steps) starting point for size ``n`` —
    the ``tune.space`` op ``"lowered"`` consult. The declared seed is
    ("float32", None): an untuned checkout keeps today's f32 path
    exactly; only an offline sweep that MEASURED a converging lowered
    pair on this hardware moves the start down the ladder."""
    from gauss_tpu.tune import apply as _tune

    p = _tune.params_for("lowered", n)
    dtype = str(p.get("dtype") or "float32")
    steps = p.get("refine_steps")
    return dtype, (int(steps) if steps else None)


def lowered_enabled(n: int) -> bool:
    """Whether the tuned store starts this size below float32 — the
    routing consult ``solve_auto`` / the recovery ladder use."""
    return lowered_params(n)[0] != "float32"


def solve_lowered_auto(a, b, panel: Optional[int] = None, unroll="auto",
                       gate: float = DEFAULT_GATE,
                       ) -> Tuple[np.ndarray, object, dict]:
    """The ladder walk: start at the tuned (dtype, refine_steps) pair and
    demote DETERMINISTICALLY down :data:`LOWERED_DTYPES` on every typed
    convergence failure — the same demotion shape as structure mistags.
    Returns ``(x_float64, factors, info)`` with ``info["demoted"]`` set
    when the serving dtype is below the requested start; re-raises the
    last :class:`PrecisionNotConvergedError` only when even float32 +
    double-single missed the gate (the recovery ladder's cue to escalate
    to its own deeper rungs)."""
    tuned_dtype, tuned_steps = lowered_params(np.shape(a)[0])
    start = (LOWERED_DTYPES.index(tuned_dtype)
             if tuned_dtype in LOWERED_DTYPES else len(LOWERED_DTYPES) - 1)
    last_err: Optional[PrecisionNotConvergedError] = None
    for dt in LOWERED_DTYPES[start:]:
        steps = tuned_steps if dt == tuned_dtype else None
        try:
            x64, fac, info = solve_lowered(a, b, dtype=dt,
                                           refine_steps=steps, panel=panel,
                                           unroll=unroll, gate=gate)
        except PrecisionNotConvergedError as e:
            last_err = e
            obs.counter("precision.demotions")
            obs.emit("precision", event="demote", from_dtype=dt,
                     rel_residual=float(f"{e.rel_residual:.3e}"))
            continue
        info["demoted"] = dt != tuned_dtype
        if info["demoted"]:
            obs.counter("precision.served_demoted")
        return x64, fac, info
    assert last_err is not None
    raise last_err
