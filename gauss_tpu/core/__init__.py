"""Pure-JAX algorithmic core — the oracle layer.

Equivalent in role to the sequential C skeleton that the reference duplicates
into all 10 gauss programs (reference Pthreads/Version-1/gauss_internal_input.c:29-227):
allocate/init/pivot/eliminate/back-substitute, plus dense matmul
(reference CUDA_and_OpenMP/Version-1/cuda_matmul.cu:28-39). Everything here is
jittable, static-shaped, and dtype-polymorphic (f32 on TPU, f64 for oracle tests).
"""

from gauss_tpu.core.gauss import (  # noqa: F401
    EliminationResult,
    eliminate,
    back_substitute,
    gauss_solve,
)
from gauss_tpu.core.matmul import matmul  # noqa: F401
