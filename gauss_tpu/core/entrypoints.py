"""The declared registry of fast-path solve entry points.

Three load-bearing contracts in this tree are promises about TRACED
PROGRAMS, not about any particular test size: the PR-10 fast-path contract
(``resolve_factor`` with keyword defaults is ONE fully-jitted program with
no host callsites), the PR-11 precision contract (every trailing dot on
bf16 operands accumulates f32), and the donation contract (declared
donations survive to the executable's input/output aliasing — CPU honors
donation in this container, so a silently-dropped alias is invisible to
behavioral tests). The test suite samples them at a few sizes; the static
auditor (``gauss_tpu.analysis.jaxpr_audit`` / ``gauss-lint``) re-derives
them from the closed jaxpr of EVERY registered entry point.

This module is the single source of what "every registered entry point"
means:

- :data:`ENTRY_POINTS` — one :class:`EntryPoint` per audited program
  form: a ``trace()`` builder returning ``(callable, args, kwargs)`` for
  ``jax.make_jaxpr``, flags for the host-stepped routes (callbacks
  allowed) and refinement sites (f64 allowed), and an optional
  ``lower()`` builder for entries that declare buffer donation.
- :data:`REGISTERED_FUNCS` — the public functions those entries cover.
- :data:`EXEMPT_FUNCS` — public solve entry points deliberately NOT
  traced, each with the reason (host drivers/routers over registered
  engines, mesh-requiring dist forms). The registry-completeness rule
  (and tests/test_analysis.py) asserts every discovered public solve
  entry point is in exactly one of the two sets, so a new solve API
  cannot ship unaudited by accident.

Adding a fast-path entry: append an :class:`EntryPoint` to
:func:`entry_points` AND its function name to :data:`REGISTERED_FUNCS`
(docs/ANALYSIS.md walks through it). Keep trace sizes small (n=64,
panel=16): tracing never executes the program, so the audit stays
seconds, not minutes.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Tuple

#: audit trace geometry: small enough that make_jaxpr is milliseconds,
#: large enough that every panel/group code path appears in the trace.
AUDIT_N = 64
AUDIT_PANEL = 16


@dataclasses.dataclass(frozen=True)
class EntryPoint:
    """One audited fast-path entry: how to trace it and what it may do."""

    name: str
    #: ``() -> (callable, args, kwargs)`` handed to ``jax.make_jaxpr``;
    #: None for host-stepped entries (registered for completeness and the
    #: callback exemption, but there is no single program to trace).
    trace: Optional[Callable[[], Tuple[Callable, tuple, dict]]] = None
    #: the ONLY entries allowed host callbacks (checkpoint / out-of-core /
    #: ABFT replay runners — their per-group host step is the feature).
    host_stepped: bool = False
    #: declared refinement site: f64 ops allowed in the traced program.
    refinement: bool = False
    #: ``() -> jax Lowered`` for entries that declare buffer donation; the
    #: auditor asserts the lowering carries the input/output alias.
    lower_donating: Optional[Callable[[], object]] = None
    #: additionally compile ``lower_donating`` and assert the alias
    #: survives to the executable (one entry is enough to pin backend
    #: behavior; compiles cost ~a second each on the CPU proxy).
    compile_check: bool = False
    note: str = ""
    #: (repo-relative path, line) findings anchor to; None = the
    #: registry itself (extra entries — tests, selftest — point home).
    where: Optional[Tuple[str, int]] = None


def _system(n: int = AUDIT_N, dtype="float32"):
    """A deterministic well-conditioned audit operand (never executed —
    tracing only needs shapes/dtypes, but concrete operands keep host-side
    numpy preludes in wrapped entries working)."""
    import numpy as np

    rng = np.random.default_rng(0)
    a = rng.standard_normal((n, n)).astype(np.float64)
    a += n * np.eye(n)
    b = rng.standard_normal(n).astype(np.float64)
    if dtype == "float64":
        return a, b
    import jax.numpy as jnp

    return jnp.asarray(a, dtype=dtype), jnp.asarray(b, dtype=dtype)


def _factor_entry(unroll, **kw):
    def build():
        from gauss_tpu.core import blocked

        a, _ = _system()
        factor = blocked.resolve_factor(AUDIT_N, unroll, **kw)
        return (lambda m: factor(m, panel=AUDIT_PANEL)), (a,), {}
    return build


def _bf16_factor_entry(fn_name):
    def build():
        from gauss_tpu.core import blocked

        a, _ = _system(dtype="bfloat16")
        fn = getattr(blocked, fn_name)
        return (lambda m: fn(m, panel=AUDIT_PANEL)), (a,), {}
    return build


def _bf16x3_factor_entry():
    def build():
        from gauss_tpu.core import blocked

        a, _ = _system()
        return (lambda m: blocked.lu_factor_blocked(
            m, panel=AUDIT_PANEL, gemm_precision="bf16x3")), (a,), {}
    return build


def _lu_solve_entry(dtype="float32"):
    def build():
        from gauss_tpu.core import blocked

        a, b = _system(dtype=dtype)
        def fn(m, rhs):
            fac = blocked.lu_factor_blocked(m, panel=AUDIT_PANEL)
            return blocked.lu_solve(fac, rhs)
        return fn, (a, b), {}
    return build


def _gauss_solve_entry():
    def build():
        from gauss_tpu.core import gauss

        a, b = _system()
        return gauss.gauss_solve, (a, b), {}
    return build


def _gauss_solve_blocked_entry():
    def build():
        from gauss_tpu.core import blocked

        a, b = _system()
        return (lambda m, rhs: blocked.gauss_solve_blocked(
            m, rhs, panel=AUDIT_PANEL)), (a, b), {}
    return build


def _refine_ds_entry():
    def build():
        import numpy as np

        from gauss_tpu.core import blocked, dsfloat

        a, b = _system()
        a64 = np.asarray(a, np.float64)
        fac = blocked.lu_factor_blocked(a, panel=AUDIT_PANEL)
        at_ds = dsfloat.to_ds(a64.T)
        b_ds = dsfloat.to_ds(np.asarray(b, np.float64))
        x0 = blocked.lu_solve(fac, b_ds.hi)
        return (lambda x: dsfloat.refine_ds(fac, at_ds, b_ds, x,
                                            iters=2)), (x0,), {}
    return build


def _chol_entry(solve: bool):
    def build():
        import numpy as np

        from gauss_tpu.structure import cholesky

        a, b = _system(dtype="float64")
        spd = np.asarray(a @ a.T + AUDIT_N * np.eye(AUDIT_N), np.float32)
        rhs = np.asarray(b, np.float32)
        if solve:
            def fn(m, r):
                fac = cholesky.cholesky_factor_blocked(m, panel=AUDIT_PANEL)
                return cholesky.cholesky_solve(fac, r)
            return fn, (spd, rhs), {}
        return (lambda m: cholesky.cholesky_factor_blocked(
            m, panel=AUDIT_PANEL)), (spd,), {}
    return build


def _tridiag_entry():
    def build():
        import numpy as np

        from gauss_tpu.structure import banded

        rng = np.random.default_rng(1)
        n = AUDIT_N
        d = (4.0 + rng.random(n)).astype(np.float32)
        dl = rng.random(n).astype(np.float32)   # dl[0] ignored
        du = rng.random(n).astype(np.float32)   # du[-1] ignored
        b = rng.random(n).astype(np.float32)
        return banded.solve_tridiag, (dl, d, du, b), {}
    return build


def _band_blocklu_entry():
    def build():
        import numpy as np

        from gauss_tpu.structure import banded

        rng = np.random.default_rng(2)
        n, bw = AUDIT_N, 4
        a = np.zeros((n, n), np.float64)
        for k in range(-bw, bw + 1):
            a += np.diag(rng.random(n - abs(k)), k)
        a += 4.0 * (2 * bw + 1) * np.eye(n)
        a32 = a.astype(np.float32)
        b32 = rng.random(n).astype(np.float32)
        # solve_band_blocklu stages its block diagonals on host (numpy);
        # the program it dispatches is the jitted two-scan form — trace
        # exactly that, on the staged operands.
        D, E, F, npad = banded._block_diagonals(a32, bw)
        B = b32.reshape(-1, 1)
        Bp = np.zeros((npad, 1), np.float32)
        Bp[:n] = B
        Bp = Bp.reshape(D.shape[0], bw, 1)
        return banded._band_run_jit(), (D, E, F, Bp), {}
    return build


def _sparse_system():
    """A small certified-SPD sparse operand in ELL staging plus an RHS —
    shared by the sparse SpMV/Krylov trace builders."""
    import numpy as np

    from gauss_tpu.io import synthetic
    from gauss_tpu.sparse.csr import CsrMatrix

    rows, cols, vals = synthetic.sparse_coords(AUDIT_N, nnz_per_row=5,
                                               seed=3)
    a = CsrMatrix.from_coords(AUDIT_N, rows, cols, vals)
    rng = np.random.default_rng(4)
    b = rng.standard_normal(AUDIT_N).astype(np.float32)
    ecols, evals = a.ell()
    return ecols, evals.astype(np.float32), b


def _spmv_entry(pallas: bool = False):
    def build():
        from gauss_tpu.sparse import spmv

        cols, vals, x = _sparse_system()
        if pallas:
            return (lambda c, v, u: spmv.spmv_ell_pallas(c, v, u, bm=32)), \
                (cols, vals, x), {}
        return spmv.spmv_ell, (cols, vals, x), {}
    return build


def _spmv_coo_entry():
    def build():
        from gauss_tpu.sparse.csr import CsrMatrix
        from gauss_tpu.io import synthetic
        from gauss_tpu.sparse import spmv
        import numpy as np

        rows, cols, vals = synthetic.sparse_coords(AUDIT_N, nnz_per_row=5,
                                                   seed=3)
        a = CsrMatrix.from_coords(AUDIT_N, rows, cols, vals)
        r, c, v = a.coo()
        rng = np.random.default_rng(4)
        x = rng.standard_normal(AUDIT_N).astype(np.float32)
        return (lambda rr, cc, vv, u: spmv.spmv_coo(rr, cc, vv, u,
                                                    n=AUDIT_N)), \
            (r, c, v.astype(np.float32), x), {}
    return build


def _krylov_entry(method: str):
    """Trace one Krylov while_loop core (unpreconditioned form — the
    preconditioner pytree only adds the registered tridiag/scan programs).
    Traced at f32; the host wrappers run the same program under
    enable_x64, hence the refinement flag on these entries."""
    def build():
        from gauss_tpu.sparse import krylov

        cols, vals, b = _sparse_system()
        x0 = b * 0.0
        tol = 1e-4
        if method == "cg":
            fn = lambda c, v, rhs, x: krylov.cg_run(  # noqa: E731
                c, v, rhs, x, None, tol, maxiter=8)
        elif method == "gmres":
            fn = lambda c, v, rhs, x: krylov.gmres_run(  # noqa: E731
                c, v, rhs, x, None, tol, restart=4, maxcycles=2)
        else:
            fn = lambda c, v, rhs, x: krylov.bicgstab_run(  # noqa: E731
                c, v, rhs, x, None, tol, maxiter=8)
        return fn, (cols, vals, b, x0), {}
    return build


def _serve_exe(dtype: str):
    from gauss_tpu.serve.cache import BatchedExecutable, CacheKey

    key = CacheKey(bucket_n=32, nrhs=1, batch=2, dtype=dtype,
                   engine="blocked", refine_steps=1)
    return BatchedExecutable(key, panel=AUDIT_PANEL)


def _serve_entry(dtype: str, solve: bool):
    def build():
        import numpy as np

        from gauss_tpu.serve.cache import storage_dtype

        exe = _serve_exe(dtype)
        sd = storage_dtype(dtype)
        a = np.stack([np.eye(32, dtype=sd)] * 2)
        if not solve:
            return (lambda m: exe._factor(m)), (a,), {}
        fac = exe._factor(a.copy())
        b = np.zeros((2, 32, 1), dtype=sd)
        return (lambda f, r: exe._solve(f, r)), (fac, b), {}
    return build


def _lower_factor_donating():
    import jax.numpy as jnp

    from gauss_tpu.core import blocked

    a, _ = _system()
    return blocked.lu_factor_blocked_donating.lower(jnp.asarray(a),
                                                    panel=AUDIT_PANEL)


def _lower_serve_solve_donating():
    import numpy as np

    exe = _serve_exe("float32")
    a = np.stack([np.eye(32, dtype=np.float32)] * 2)
    fac = exe._factor(a)
    return exe._solve.lower(fac, np.zeros((2, 32, 1), np.float32))


def entry_points() -> List[EntryPoint]:
    """The audited registry (rebuilt per call: entries capture live
    callables, and the serve entries build/warm real executables)."""
    return [
        # resolve_factor across every unroll policy — the PR-10 contract.
        EntryPoint("factor/auto", _factor_entry("auto")),
        EntryPoint("factor/unrolled", _factor_entry(True)),
        EntryPoint("factor/flat", _factor_entry(False)),
        EntryPoint("factor/chunked", _factor_entry("chunked")),
        # the checksum-carrying single program: still callback-free.
        EntryPoint("factor/abft", _factor_entry("auto", abft=True)),
        # donation: declared on the twin, must survive lowering+compile.
        EntryPoint("factor/donating", _factor_entry("auto", donate=True),
                   lower_donating=_lower_factor_donating,
                   compile_check=True),
        # the lowered/bf16 forms — the PR-11 precision contract surface.
        EntryPoint("factor/bf16", _bf16_factor_entry("lu_factor_blocked")),
        EntryPoint("factor/bf16/chunked",
                   _bf16_factor_entry("lu_factor_blocked_chunked")),
        EntryPoint("factor/bf16x3", _bf16x3_factor_entry(),
                   note="f32 storage; split-GEMM trailing updates"),
        EntryPoint("lu_solve", _lu_solve_entry()),
        EntryPoint("lu_solve/bf16", _lu_solve_entry(dtype="bfloat16"),
                   note="f32-accuracy solves against bf16 factors"),
        EntryPoint("gauss_solve", _gauss_solve_entry()),
        EntryPoint("gauss_solve_blocked", _gauss_solve_blocked_entry()),
        # the double-single refinement loop — the declared f64/refinement
        # site every refined solver shares.
        EntryPoint("refine_ds", _refine_ds_entry(), refinement=True),
        # structured engines.
        EntryPoint("chol/factor", _chol_entry(solve=False)),
        EntryPoint("chol/solve", _chol_entry(solve=True)),
        EntryPoint("banded/thomas", _tridiag_entry()),
        EntryPoint("banded/blocklu", _band_blocklu_entry()),
        # the sparse plane: SpMV staging forms + the Krylov while_loop
        # cores (refinement: the host wrappers run these f64 under
        # enable_x64 — iterating TO the gate is the design, not a
        # precision accident).
        EntryPoint("sparse/spmv", _spmv_entry()),
        EntryPoint("sparse/spmv/pallas", _spmv_entry(pallas=True)),
        EntryPoint("sparse/spmv/coo", _spmv_coo_entry()),
        EntryPoint("sparse/cg", _krylov_entry("cg"), refinement=True),
        EntryPoint("sparse/gmres", _krylov_entry("gmres"), refinement=True),
        EntryPoint("sparse/bicgstab", _krylov_entry("bicgstab"),
                   refinement=True),
        # the serve plane's compiled lanes (vmap-batched factor+solve).
        EntryPoint("serve/factor", _serve_entry("float32", solve=False)),
        EntryPoint("serve/solve", _serve_entry("float32", solve=True),
                   lower_donating=_lower_serve_solve_donating),
        EntryPoint("serve/factor/bf16",
                   _serve_entry("bfloat16", solve=False)),
        EntryPoint("serve/solve/bf16", _serve_entry("bfloat16", solve=True)),
        # host-stepped routes: registered so the callback exemption is a
        # DECLARED property, not a scan hole; there is no single jaxpr.
        EntryPoint("factor/checkpointed", host_stepped=True,
                   note="resilience.checkpoint — the only host-stepped "
                        "resolve_factor route"),
        EntryPoint("outofcore", host_stepped=True,
                   note="gauss_tpu.outofcore — host-streamed by design"),
        EntryPoint("abft/replay", host_stepped=True,
                   note="resilience.abft runners — per-group host "
                        "verify/replay is the feature"),
    ]


#: public functions the registry's entries cover (module:function).
REGISTERED_FUNCS = {
    "gauss_tpu.core.gauss:gauss_solve",
    "gauss_tpu.core.blocked:lu_factor_blocked",
    "gauss_tpu.core.blocked:lu_factor_blocked_unrolled",
    "gauss_tpu.core.blocked:lu_factor_blocked_chunked",
    "gauss_tpu.core.blocked:lu_factor_blocked_donating",
    "gauss_tpu.core.blocked:lu_factor_blocked_unrolled_donating",
    "gauss_tpu.core.blocked:lu_factor_blocked_chunked_donating",
    "gauss_tpu.core.blocked:lu_solve",
    "gauss_tpu.core.blocked:gauss_solve_blocked",
    "gauss_tpu.core.blocked:resolve_factor",
    "gauss_tpu.core.dsfloat:refine_ds",
    "gauss_tpu.structure.cholesky:cholesky_factor_blocked",
    "gauss_tpu.structure.cholesky:cholesky_factor_blocked_unrolled",
    "gauss_tpu.structure.cholesky:cholesky_solve",
    "gauss_tpu.structure.cholesky:resolve_chol_factor",
    "gauss_tpu.structure.banded:solve_tridiag",
    "gauss_tpu.structure.banded:solve_band_blocklu",
    "gauss_tpu.sparse.spmv:spmv_ell",
    "gauss_tpu.sparse.spmv:spmv_ell_pallas",
    "gauss_tpu.sparse.spmv:spmv_coo",
    "gauss_tpu.sparse.krylov:cg_run",
    "gauss_tpu.sparse.krylov:gmres_run",
    "gauss_tpu.sparse.krylov:bicgstab_run",
    "gauss_tpu.outofcore.stream:lu_factor_outofcore",
    "gauss_tpu.outofcore.stream:lu_solve_outofcore",
    "gauss_tpu.outofcore.stream:solve_outofcore",
    "gauss_tpu.resilience.checkpoint:lu_factor_blocked_chunked_checkpointed",
    "gauss_tpu.resilience.abft:lu_factor_abft",
    "gauss_tpu.resilience.abft:solve_lu_abft",
    "gauss_tpu.resilience.abft:cholesky_factor_abft",
    "gauss_tpu.resilience.abft:solve_chol_abft",
}

#: public solve entry points deliberately NOT traced, with the reason —
#: host drivers/routers over registered engines, or forms whose program
#: shape needs an environment the auditor does not stand up (meshes).
EXEMPT_FUNCS: Dict[str, str] = {
    "gauss_tpu.core.blocked:solve_refined":
        "host driver: numpy f64 residual loop around the registered "
        "factor/solve programs",
    "gauss_tpu.core.blocked:solve_handoff":
        "host router over registered engines (single_chip/dist/outofcore); "
        "its routing decision is audited dynamically via route events",
    "gauss_tpu.core.blocked:lu_factor_blocked_phased":
        "host-stepped diagnostic path (--phase-profile), never on the "
        "fast path",
    "gauss_tpu.core.dsfloat:solve_ds":
        "host staging around refine_ds (registered)",
    "gauss_tpu.core.dsfloat:solve_once_ds":
        "host staging around refine_ds (registered); bench slope chain",
    "gauss_tpu.core.lowered:solve_lowered":
        "host ladder driver over the registered bf16/bf16x3 factor forms",
    "gauss_tpu.core.lowered:solve_lowered_auto":
        "host demotion ladder over solve_lowered",
    "gauss_tpu.structure.cholesky:cholesky_factor":
        "host entry: NotSPD witness check around the registered "
        "chol/factor program",
    "gauss_tpu.structure.cholesky:solve_spd":
        "host entry over cholesky_factor + cholesky_solve (both "
        "registered)",
    "gauss_tpu.structure.cholesky:solve_spd_refined":
        "host refinement driver over chol/factor + chol/solve",
    "gauss_tpu.structure.cholesky:solve_spd_ds":
        "host staging around refine_ds(solve_fn=cholesky_solve)",
    "gauss_tpu.structure.banded:solve_banded":
        "host bandwidth-measuring router over the registered banded "
        "engines",
    "gauss_tpu.structure.banded:solve_banded_refined":
        "host refinement driver over solve_banded",
    "gauss_tpu.structure.blockdiag:solve_blockdiag":
        "host-orchestrated vmap batching through the serve executable "
        "cache (serve/factor + serve/solve are the traced programs)",
    "gauss_tpu.structure.router:solve_auto":
        "host detect->route->recovery-ladder driver",
    "gauss_tpu.resilience.recover:solve_resilient":
        "host recovery ladder over registered/exempt rungs",
    "gauss_tpu.sparse.krylov:solve_cg":
        "host wrapper: Gershgorin certification + f64 staging + the "
        "1e-4 true-residual verify around the registered sparse/cg core",
    "gauss_tpu.sparse.krylov:solve_gmres":
        "host wrapper around the registered sparse/gmres core",
    "gauss_tpu.sparse.krylov:solve_bicgstab":
        "host wrapper around the registered sparse/bicgstab core",
    "gauss_tpu.sparse.solve:solve_sparse":
        "host method router (certify -> cg | gmres -> bicgstab) over the "
        "registered Krylov cores; emits sparse_solve events",
}

#: modules the completeness rule scans for public solve entry points.
AUDIT_MODULES = (
    "gauss_tpu.core.gauss",
    "gauss_tpu.core.blocked",
    "gauss_tpu.core.dsfloat",
    "gauss_tpu.core.lowered",
    "gauss_tpu.structure.cholesky",
    "gauss_tpu.structure.banded",
    "gauss_tpu.structure.blockdiag",
    "gauss_tpu.structure.router",
    "gauss_tpu.outofcore.stream",
    "gauss_tpu.resilience.recover",
    "gauss_tpu.resilience.abft",
    "gauss_tpu.resilience.checkpoint",
    "gauss_tpu.sparse.spmv",
    "gauss_tpu.sparse.krylov",
    "gauss_tpu.sparse.solve",
)

#: a public callable with one of these prefixes is a solve entry point.
_SOLVE_PREFIXES = ("solve_", "gauss_solve", "lu_factor", "lu_solve",
                   "cholesky_factor", "cholesky_solve", "resolve_")


def stale_declarations() -> List[str]:
    """Registered/exempt names that no longer resolve to a module
    attribute — a renamed entry point must update the registry, not
    silently fall out of the audit."""
    import importlib

    out: List[str] = []
    for qual in sorted(REGISTERED_FUNCS | set(EXEMPT_FUNCS)):
        modname, name = qual.split(":")
        try:
            mod = importlib.import_module(modname)
        except Exception:
            out.append(qual)
            continue
        if not hasattr(mod, name):
            out.append(qual)
    return out


def discover_public_solvers() -> List[str]:
    """Every public solve entry point in :data:`AUDIT_MODULES`
    (``module:function`` strings) — what the completeness rule compares
    against REGISTERED_FUNCS | EXEMPT_FUNCS."""
    import importlib

    found: List[str] = []
    for modname in AUDIT_MODULES:
        mod = importlib.import_module(modname)
        for name in sorted(vars(mod)):
            if name.startswith("_") or not name.startswith(_SOLVE_PREFIXES):
                continue
            obj = getattr(mod, name)
            if not callable(obj):
                continue
            owner = getattr(obj, "__module__", modname)
            # jit/wrapper objects may not carry __module__; treat names
            # whose wrapped function came from elsewhere as re-exports.
            if owner is not None and owner != modname:
                continue
            found.append(f"{modname}:{name}")
    return found
