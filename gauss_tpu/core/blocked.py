"""Blocked (panel) Gaussian elimination — the MXU performance path.

The reference's engines all perform n dependent rank-1 eliminations over the
full matrix (reference Pthreads/Version-1/gauss_internal_input.c:170-206 and
every sibling); that formulation is bandwidth-bound on any hardware. The
TPU-first redesign is a right-looking blocked factorization: the O(n^3) work
lands in panel-wide GEMMs that XLA tiles onto the 128x128 MXU, and only the
O(n^2 * panel) panel factorization remains rank-1/VPU work. This is the same
transformation the reference's Version-2 "row-wise blocking" gestures at with
its block_size=16 cache tiling (Pthreads/Version-2/gauss_internal_input.c:18,
162-173) — taken to its logical conclusion for a systolic-array machine.

Everything runs under one ``lax.fori_loop`` over panels with static shapes:
the active trailing submatrix never shrinks; instead row/column masks zero out
the finished region, trading ~2x redundant-but-free MXU FLOPs for a single
compiled program (SURVEY.md §7 "hard parts" (a)/(b)).

Pivoting is partial (max-|column|), the reference external-input policy —
upgraded to be the default everywhere per SURVEY.md §7 hard part (c). Row
permutations are tracked and returned; the factor stores L's multipliers in
the strictly-lower triangle and U on/above the diagonal (LAPACK getrf layout),
so one factorization serves many right-hand sides and iterative refinement.
"""

from __future__ import annotations

import functools
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from gauss_tpu.resilience import inject as _inject
from gauss_tpu.tune import space as _tspace

DEFAULT_PANEL = 128  # one MXU tile wide; also the f32 lane count
# Panels per chunked group. The VALUE lives in tune.space (the autotuner's
# seed default — single source, so tuner output and this code default
# cannot drift); re-exported here under its historical name.
CHUNK_DEFAULT = _tspace.CHUNK_SEED
GROUP_UPDATE_STRIP = 2048  # rows per deferred-trailing-GEMM strip: bounds
# the chunked form's group-end transients to O(strip * n) so the route
# reaches the HBM ceiling (the unstripped form OOMed at n=32768)
GROUP_UPDATE_UNSTRIPPED_MAX_BYTES = 16 * 20480 * 20480  # ~6.7 GB: up to
# here the group-end update runs as ONE gather + GEMM instead of strips.
# Transients peak ~3 copies of the first group's (n-w)^2 trailing block
# plus the matrix — ~4 * npad^2 * itemsize bytes total — vs 16 GB HBM.
# The bound is in BYTES, not rows, so f64 inputs halve the admitted n
# (ADVICE r4 #1: a rows bound calibrated for f32 would admit ~13.4 GB of
# f64 transients). At f32 it equals the measured n=20480 limit; the strip
# loop's extra serialized gathers cost +2.3 ms at n=8192 (sweep_strip r4).

# Round 5: the panel kernel's transposed input is ALIASED into its output
# buffer, so its scoped working set is ONE (panel, npad) block plus per-row
# bookkeeping (inv/chosen (h,1) outputs, the done-mask scratch and mask
# temporaries) — the round-4 two-buffer model and its 43-800 B/row
# pipeline-copy overheads (commit 7e6cfc4) no longer apply. The residual
# overhead is context-dependent (the chip reported 153 B/row for
# (128, 24576) in one chunk width and ~210 B/row for (128, ~22.5k) in
# another — the enclosing group width changes which temporaries the
# scheduler keeps live), so the table below rounds the WORST observation
# per width up for margin; a borderline group that false-approves costs a
# whole route its compile. Ceilings: 256 -> ~12.4k, 128 -> ~21.1k,
# 64 -> ~34.7k — in-kernel pivoting covers the single-chip HBM ceiling
# (~34k), where the kernel measures 1.9-3.3x faster than the stock-JAX
# panel it previously handed tall groups to (VERDICT r4 next #5).
PANEL_VMEM_BUDGET = _tspace.PANEL_VMEM_BUDGET_SEED  # tune.space seed
PANEL_VMEM_ROW_OVERHEAD = {64: 190, 128: 220, 256: 220}

# The aliasing holds only when the kernel operand stays a standalone
# buffer. Slicing a 64-wide panel out of a group block NARROWER than 2048
# columns fuses the slice+transpose INTO the aliased call and the block
# double-counts in scoped VMEM (25.5 M at (64, 24576) with W=1024 groups;
# every probed W=2048 config compiles, n in 24576..34048). Slices from
# full-width arrays (the rowelim engine's augmented matrix) are immune —
# compile-probed at 24576/32768.
PANEL64_MIN_SLICE_W = 2048

# The deferred (two-level) kernel form additionally materializes large
# transposition transients in its boundary dots (the h=4096/panel=256
# chip OOM, kernels.panel_pallas DEFER_WORKSET_FACTOR); defer_seg budgets
# those against the same physical scoped limit via its own workset rule —
# aliased to the panel budget so a future recalibration cannot drift.
DEFER_VMEM_BUDGET = PANEL_VMEM_BUDGET


def panel_fits_vmem(n: int, panel: int, itemsize: int = 4) -> bool:
    """Whether the Pallas panel kernel's VMEM working set fits the scoped
    limit: npad * (panel * itemsize + per-width row overhead)."""
    npad = -(-n // panel) * panel
    # Unmeasured widths at or above the narrowest rung keep the widest
    # measured overhead; BELOW it the per-row overhead grows ~1/panel
    # (round-4 data), so narrow widths extrapolate conservatively instead of
    # false-approving a launch that dies with a raw Mosaic error (ADVICE r5).
    # The narrow-width floor formula is single-sourced in tune.space.
    overhead = PANEL_VMEM_ROW_OVERHEAD.get(
        panel, 220 if panel >= 64 else _tspace.narrow_panel_overhead(panel))
    est = npad * (panel * itemsize + overhead)
    # A tuned store can recalibrate the scoped budget per hardware epoch
    # (v5p's usable scoped VMEM differs from the v5e-measured seed); the
    # module global stays the seed so tests can monkeypatch it.
    from gauss_tpu.tune import apply as _tune

    budget = int(_tune.override("panel_kernel", n, "vmem_budget")
                 or PANEL_VMEM_BUDGET)
    fits = est <= budget
    from gauss_tpu.obs import compile as _obs_compile

    _obs_compile.record_vmem_estimate(
        "panel_kernel", n=n, panel=panel, itemsize=itemsize, bytes=est,
        budget=budget, fits=fits)
    return fits


def fused_fits_vmem(h: int, panel: int, ct: int | None = None,
                    itemsize: int = 4) -> bool:
    """Whether the FUSED panel+trailing kernel's working set fits scoped
    VMEM for an (h, panel) panel step: the pipeline keeps
    ``FUSED_WORKSET_TILES`` trailing (h, ct) tiles live next to the
    aliased transposed panel and its (panel, h) multiplier/pivot scratch
    pair (``FUSED_WORKSET_PANELS`` panel-width blocks), plus the classic
    kernel's per-row bookkeeping overhead. Both the budget and the tile
    width consult the tuned store (op ``panel_fused``) like the classic
    panel budget does."""
    from gauss_tpu.tune import apply as _tune

    npad = -(-h // panel) * panel
    if ct is None:
        ct = int(_tune.override("panel_fused", h, "ct")
                 or _tspace.FUSED_CT_SEED)
    ct = max(panel, (ct // panel) * panel)
    overhead = PANEL_VMEM_ROW_OVERHEAD.get(
        panel, 220 if panel >= 64 else _tspace.narrow_panel_overhead(panel))
    est = npad * ((_tspace.FUSED_WORKSET_TILES * ct
                   + _tspace.FUSED_WORKSET_PANELS * panel) * itemsize
                  + overhead)
    budget = int(_tune.override("panel_fused", h, "vmem_budget")
                 or PANEL_VMEM_BUDGET)
    fits = est <= budget
    from gauss_tpu.obs import compile as _obs_compile

    _obs_compile.record_vmem_estimate(
        "panel_fused", n=h, panel=panel, ct=ct, itemsize=itemsize,
        bytes=est, budget=budget, fits=fits)
    return fits


def _use_fused(panel_impl: str, h: int, panel: int, wtot: int,
               itemsize: int = 4, carried: bool = False,
               zero_pivot_safe: bool = False) -> bool:
    """Whether a panel step runs the fused panel+trailing kernel
    (kernels.panel_fused_pallas). ``panel_impl='fused'`` forces it (with
    the explicit-request sizing contract: a clear ValueError on a real TPU
    when the working set cannot fit — never a raw Mosaic error);
    ``'auto'`` selects it on TPU when :func:`fused_fits_vmem` approves;
    ``'jax'``/``'pallas'`` never do.

    ``carried=True`` (an ABFT checksum rider is active) deterministically
    falls back to the UNFUSED pair: the fused kernel does not thread the
    carry, and the checksum verification is defined against the unfused
    trailing math — the fallback keeps ``abft=True`` factors bit-identical
    to the unfused forms the invariant was validated on (the explicit
    fused-vs-ABFT contract; tested). ``zero_pivot_safe`` likewise pins the
    stock-JAX panel (only it implements the guarded division)."""
    if panel_impl not in ("auto", "fused"):
        return False
    if zero_pivot_safe or carried or wtot <= panel:
        return False
    if panel_impl == "fused":
        if (jax.default_backend() == "tpu"
                and not fused_fits_vmem(h, panel, itemsize=itemsize)):
            raise ValueError(
                f"panel_impl='fused' requested but the (h={h}, "
                f"panel={panel}) fused working set exceeds the VMEM "
                f"budget; use panel_impl='auto' (unfused pair there), a "
                f"narrower trailing tile (tune.space panel_fused/ct), or "
                f"a narrower panel")
        return True
    return (jax.default_backend() == "tpu" and panel >= 64
            and fused_fits_vmem(h, panel, itemsize=itemsize))


def auto_panel(n: int, itemsize: int = 4) -> int:
    """Measured-best panel width: 256 while its kernel block fits the
    scoped budget (~12.4k — the end-to-end winner there: fewer XLA glue
    steps), 128 everywhere beyond. The full (n, 128) block stops fitting
    at ~21.1k, but that does NOT route the width away from 128: the
    chunked route resolves the panel impl PER GROUP, so only the first
    (tallest) groups run the stock-JAX panel and every later group runs
    the kernel — measured at n=24576 this mixed-128 route beats the
    all-in-kernel panel-64 route 0.79 vs 1.02 s (the narrower kernel's
    extra serial steps cost more than the few stock-JAX panels save).
    Every factorization entry point resolves panel=None through this.

    A tuned store (gauss_tpu.tune) SHORT-CIRCUITS the heuristic: when an
    offline sweep on this hardware recorded a winning panel width for this
    n-bucket, that width wins — the rules below are the seed policy the
    sweep measures against. Zero behavior change when no store exists.
    """
    from gauss_tpu.tune import apply as _tune

    tuned = _tune.override("lu_factor", n, "panel")
    if tuned:
        return int(tuned)
    if n < 1024:
        return DEFAULT_PANEL  # crossover heuristic; VMEM is never binding
    if panel_fits_vmem(n, 256, itemsize):
        return 256
    return 128


def _resolve_panel(n: int, panel, itemsize: int = 4) -> int:
    return auto_panel(n, itemsize) if panel is None else panel


class BlockedLU(NamedTuple):
    """P @ A = L @ U factorization state (padded to a panel multiple).

    m:    (npad, npad) array; strictly-lower = L multipliers, upper = U.
    perm: (npad,) gather indices; row k of ``m`` is original row ``perm[k]``.
    min_abs_pivot: min over steps of |pivot|; 0 means singular input.
    linv/uinv: optional (nb, panel, panel) stacked explicit inverses of the
    diagonal blocks of L (unit-lower) and U (upper) — produced by BOTH
    factorization paths so the in-factor U12 computation and
    :func:`lu_solve` become GEMMs instead of latency-bound substitution
    chains (the TRTRI+GEMM scheme GPU LU libraries use; measured 0.52 ms
    of trisolve + 0.42 ms of solve at n=2048 on v5e with the chain form).
    None only for hand-constructed instances; lu_solve then substitutes.
    abft_err: only set by the ``abft=True`` checksum-carrying forms — the
    per-panel-group column-checksum mismatch magnitudes (one entry per
    group plus a final whole-factor ``e^T PA = (e^T L) U`` identity check,
    see the ABFT block below). Near-zero on a healthy run; a large entry
    localizes silent data corruption to the group that produced it.
    """

    m: jax.Array
    perm: jax.Array
    min_abs_pivot: jax.Array
    linv: jax.Array | None = None
    uinv: jax.Array | None = None
    abft_err: jax.Array | None = None


# --- The mixed-precision contract (ISSUE 11) ------------------------------
#
# A factorization may run with LOWERED storage: bfloat16 operands halve
# itemsize (panel_fits_vmem / fused_fits_vmem admit ~2x the working set,
# and every HBM stream moves half the bytes), or f32 storage with the
# explicit bf16x3 split-GEMM trailing update (core.matmul.dot_bf16x3 — the
# three-bf16-pass middle rung). The contract that keeps lowered factors
# refinable back to the 1e-4 gate:
#
# - **f32 accumulation.** Every trailing-update GEMM on bf16 operands
#   accumulates in float32 (``preferred_element_type`` — the MXU's native
#   bf16-in/f32-out mode) and rounds ONCE on store, so products never lose
#   the exponent range and the factor's error stays at storage rounding
#   (~2^-8 relative), not accumulated-dot rounding.
# - **f32 diagonal-block inverses.** linv/uinv are computed and STORED in
#   the accumulate dtype: they are O(nb * panel^2) — memory-negligible —
#   and both lu_solve substitutions and the in-factor U12 solves hinge on
#   them, so bf16 inverses would square the storage error for free.
# - **f32 solves.** ``lu_solve`` against a lowered factor computes in the
#   accumulate dtype and returns float32: refinement corrections only need
#   f32 relative accuracy, and the substitution chain must not re-round
#   per block.
#
# The float32 path is BIT-IDENTICAL to the pre-contract code: the
# accumulate dtype of f32 is f32, every ``astype`` is an identity, and
# ``_gdot`` emits the exact pre-existing ``jnp.dot`` (tests/test_fused.py's
# bit-identity grid still passes unchanged). Refinement back to 1e-4, the
# demotion ladder, and the tuned (dtype, refine_steps) axis live in
# gauss_tpu.core.lowered.

_BF16 = jnp.dtype("bfloat16")


def accum_dtype(dtype):
    """The accumulate dtype of the precision contract: bfloat16 storage
    accumulates (and stores its diagonal-block inverses) in float32;
    everything else accumulates in itself."""
    return jnp.float32 if jnp.dtype(dtype) == _BF16 else jnp.dtype(dtype)


def _gdot(x, y, prec, dtype):
    """One trailing-update GEMM under the precision contract. ``prec`` is
    a resolved ``lax.Precision`` — or the ``core.matmul.BF16X3`` sentinel,
    which routes to the explicit three-pass split-GEMM (f32 storage).
    bf16 storage accumulates in f32 and rounds once to ``dtype`` on the
    way out; the f32 path is the exact pre-existing ``jnp.dot``."""
    from gauss_tpu.core.matmul import BF16X3, dot_bf16x3

    if prec == BF16X3:
        return dot_bf16x3(x, y)
    if jnp.dtype(dtype) == _BF16:
        return jnp.dot(x, y, precision=prec,
                       preferred_element_type=jnp.float32).astype(dtype)
    return jnp.dot(x, y, precision=prec)


def _check_lowered_support(dtype, gemm_prec, abft: bool) -> None:
    """Typed rejection of the unsupported corners: the ABFT checksum
    rider's tolerances and verification dots are defined against f32
    HIGHEST math — a bf16 rider would alarm on storage rounding (and a
    bf16x3 rider would thread the split through checksum dots it was
    never validated on). The demotion ladder (core.lowered) never builds
    these combinations; explicit requests get the clear error."""
    from gauss_tpu.core.matmul import BF16X3

    if abft and (jnp.dtype(dtype) == _BF16 or gemm_prec == BF16X3):
        raise ValueError(
            "abft=True requires float32 storage with a lax.Precision gemm "
            "(the checksum invariant's tolerances are calibrated against "
            "f32 HIGHEST math); run the lowered dtype without the rider, "
            "or the rider at float32")


TRI_INV_BASE = 64  # base-case size for the recursive triangular inversions


def unit_lower_inv(l: jax.Array, precision=lax.Precision.HIGHEST) -> jax.Array:
    """Inverse of a unit-lower-triangular block by recursive 2x2 partition:
    inv([[A,0],[C,B]]) = [[Ai,0],[-Bi C Ai, Bi]]. log2(p/base) GEMM levels
    replace a p-step substitution chain; with partial pivoting |L| <= 1, the
    growth behavior matches what cuBLAS TRTRI-based getrs relies on."""
    p = l.shape[0]
    if p <= TRI_INV_BASE:
        return lax.linalg.triangular_solve(
            l, jnp.eye(p, dtype=l.dtype), left_side=True, lower=True,
            unit_diagonal=True)
    h = p // 2
    ai = unit_lower_inv(l[:h, :h], precision)
    bi = unit_lower_inv(l[h:, h:], precision)
    c = jnp.dot(jnp.dot(bi, l[h:, :h], precision=precision), ai,
                precision=precision)
    top = jnp.concatenate([ai, jnp.zeros((h, p - h), l.dtype)], axis=1)
    bot = jnp.concatenate([-c, bi], axis=1)
    return jnp.concatenate([top, bot], axis=0)


def upper_inv(u: jax.Array, precision=lax.Precision.HIGHEST) -> jax.Array:
    """Inverse of an upper-triangular block, same recursive scheme:
    inv([[A,C],[0,B]]) = [[Ai, -Ai C Bi],[0, Bi]]."""
    p = u.shape[0]
    if p <= TRI_INV_BASE:
        return lax.linalg.triangular_solve(
            u, jnp.eye(p, dtype=u.dtype), left_side=True, lower=False,
            unit_diagonal=False)
    h = p // 2
    ai = upper_inv(u[:h, :h], precision)
    bi = upper_inv(u[h:, h:], precision)
    c = jnp.dot(jnp.dot(ai, u[:h, h:], precision=precision), bi,
                precision=precision)
    top = jnp.concatenate([ai, -c], axis=1)
    bot = jnp.concatenate([jnp.zeros((p - h, h), u.dtype), bi], axis=1)
    return jnp.concatenate([top, bot], axis=0)


def _strict_lower_mask(panel: int):
    rows_p = jnp.arange(panel)
    return rows_p[:, None] > rows_p[None, :]


def _diag_block_linv(d: jax.Array, panel: int, dtype):
    """Inverse of the unit-lower part of one factored diagonal block ``d``
    (getrf layout: multipliers strictly below, U on/above). Computed and
    returned in the ACCUMULATE dtype (f32 for bf16 storage — the
    precision contract above; identity at f32)."""
    acc = accum_dtype(dtype)
    d = d.astype(acc)
    l11 = jnp.where(_strict_lower_mask(panel), d, jnp.zeros((), acc))
    return unit_lower_inv(l11 + jnp.eye(panel, dtype=acc))


def _diag_block_uinv(d: jax.Array, panel: int, dtype):
    """Inverse of the upper part of one factored diagonal block ``d``
    (accumulate dtype, like :func:`_diag_block_linv`)."""
    acc = accum_dtype(dtype)
    d = d.astype(acc)
    return upper_inv(jnp.where(~_strict_lower_mask(panel), d,
                               jnp.zeros((), acc)))


def _diag_block_invs(d: jax.Array, panel: int, dtype):
    """(linv, uinv) of one factored diagonal block ``d``. Single source for
    every factorization path — they must stay in lockstep; the unrolled
    path calls the two halves separately (linv inside its loop, uinv
    batched after it) but through these same helpers."""
    return (_diag_block_linv(d, panel, dtype),
            _diag_block_uinv(d, panel, dtype))


def _pad_to_panel(a: jax.Array, panel: int) -> jax.Array:
    """Embed a in the top-left of an identity-padded panel-multiple array.

    The identity pad keeps the factorization well-posed: padded columns have a
    1 on their own diagonal and zeros elsewhere, padded rows can never win a
    partial-pivot contest in a real column, and the padded block stays exactly
    the identity through every update.
    """
    n = a.shape[0]
    npad = -(-n // panel) * panel
    if npad == n:
        return a
    out = jnp.zeros((npad, npad), dtype=a.dtype)
    out = out.at[:n, :n].set(a)
    return out.at[jnp.arange(n, npad), jnp.arange(n, npad)].set(jnp.asarray(1.0, a.dtype))


def _panel_factor_jax(p: jax.Array, kb, zero_pivot_safe: bool = False):
    """Unblocked partial-pivot elimination of one (h, panel) column block whose
    diagonal lives at row offset ``kb`` within the block (stock-JAX analog of
    kernels.panel_pallas; single source of the pivot/NaN-as-singular policy).

    The rank-1 inner loop over the panel's columns — the analog of the
    reference's subtractElim hot loop (gauss_internal_input.c:155-162) —
    restricted to a VMEM-friendly panel width. Returns (factored_panel,
    ipiv, min_abs_pivot); ipiv indices are rows of ``p``.

    ``zero_pivot_safe``: guard the multiplier division so a zero pivot
    eliminates nothing (mult = 0) instead of NaN-poisoning every remaining
    row. The factorization proper never wants this — a zero pivot means
    singular, min_abs_pivot records 0 either way — but tournament-pivoting
    CANDIDATE ELECTION (dist.gauss_dist_blocked2d) runs this factorizer on
    routinely rank-deficient blocks (duplicate rows across shards), where
    an unguarded NaN would corrupt the argmax and silently drop rows that
    carry the remaining rank.
    """
    h, panel = p.shape
    rows = jnp.arange(h)
    pcols = jnp.arange(panel)
    dtype = p.dtype

    def step(j, carry):
        p, ipiv, min_piv = carry
        c = kb + j  # row of this panel column's diagonal
        col = p[:, j]
        cand = jnp.where(rows >= c, jnp.abs(col), -jnp.inf)
        piv_row = jnp.argmax(cand)
        ipiv = ipiv.at[j].set(piv_row.astype(ipiv.dtype))
        # Swap rows c <-> piv_row of the panel.
        rc, rp = p[c], p[piv_row]
        p = p.at[c].set(rp).at[piv_row].set(rc)
        piv = p[c, j]
        # A NaN pivot means a zero pivot already poisoned the trailing
        # rows; report it as singular (0), not NaN.
        apiv = jnp.abs(piv)
        min_piv = jnp.minimum(min_piv, jnp.where(jnp.isnan(apiv), 0.0, apiv))
        # Multipliers below the diagonal, stored in place (getrf layout).
        if zero_pivot_safe:
            inv_piv = jnp.where(apiv > 0, 1.0 / piv, jnp.zeros((), dtype))
            mult = jnp.where(rows > c, p[:, j] * inv_piv,
                             jnp.zeros((), dtype))
        else:
            mult = jnp.where(rows > c, p[:, j] / piv, jnp.zeros((), dtype))
        p = p.at[:, j].set(jnp.where(rows > c, mult, p[:, j]))
        # Rank-1 update of the panel columns right of j.
        urow = jnp.where(pcols > j, p[c], jnp.zeros((), dtype))
        p = p - mult[:, None] * urow[None, :]
        return p, ipiv, min_piv

    # Carry inits inherit p's varying-manual-axes type (shard_map vma), so
    # this factorizer can run replicated inside a sharded solver
    # (dist.gauss_dist_blocked) — a compiled no-op everywhere else. The
    # NaN-proof zero: cast to int first (integer x * 0 is always 0).
    vma0 = p[0, 0].astype(jnp.int32) * 0
    ipiv0 = jnp.zeros((panel,), dtype=jnp.int32) + vma0
    minpiv0 = jnp.asarray(jnp.inf, dtype) + vma0.astype(dtype)
    return lax.fori_loop(0, panel, step, (p, ipiv0, minpiv0))


def _looks_like_scoped_vmem_error(e: BaseException) -> bool:
    """Mosaic scoped-VMEM compile failures, as they surface through jit:
    'Ran out of memory in memory space vmem' / 'exceeds available scoped
    vmem' wrapped in XlaRuntimeError or Mosaic's own exception text."""
    msg = str(e).lower()
    return "vmem" in msg and ("ran out of memory" in msg or "scoped" in msg
                              or "exceed" in msg)


def _reraise_scoped_vmem(fn):
    """Hold the explicit-pallas clear-error contract (ADVICE r3) where the
    VMEM probe table is incomplete (ADVICE r5): the guards in
    :func:`_resolve_panel_impl` and the chunked group loop encode a
    whole-program-context-dependent Mosaic fusion decision from a finite set
    of compile probes, so an explicit ``panel_impl='pallas'`` outside the
    auto envelope can still reach the Mosaic compiler and die there. This
    wrapper catches that raw failure at the entry point and re-raises it as
    the documented sizing ValueError (original error chained). Auto-mode
    routes never request the kernel past the table, so only explicit
    requests pay the except path."""
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        if _inject.enabled() and args:
            # Fault-injection hook point "core.blocked.factor": corrupt the
            # operand of a host-level factor/solve entry (tracer operands —
            # calls inside an enclosing jit trace — pass through untouched).
            # One attribute check when a plan is installed, one `is None`
            # read otherwise; see gauss_tpu.resilience.inject.
            args = (_inject.corrupt_operand("core.blocked.factor", args[0]),
                    ) + args[1:]
        try:
            return fn(*args, **kwargs)
        except ValueError:
            raise
        except Exception as e:
            if (kwargs.get("panel_impl") in ("pallas", "fused")
                    and _looks_like_scoped_vmem_error(e)):
                raise ValueError(
                    f"panel_impl={kwargs.get('panel_impl')!r}: Mosaic ran "
                    "out of scoped VMEM "
                    "compiling the panel kernel — this (h, panel, group "
                    "width) context is outside the measured probe table "
                    "(PANEL_VMEM_ROW_OVERHEAD / PANEL64_MIN_SLICE_W). Use "
                    "panel_impl='auto' (stock-JAX panel for these groups), "
                    "a narrower panel, or a different chunk") from e
            raise
    # The AOT surface of the wrapped jit function stays reachable for
    # cost accounting (obs.compile) and tests.
    wrapper.lower = getattr(fn, "lower", None)
    return wrapper


def _resolve_panel_impl(panel_impl, n: int | None = None,
                        panel: int | None = None, itemsize: int = 4):
    if panel_impl == "fused":
        # The fused panel+trailing selection happens upstream (_use_fused);
        # paths that reach THIS resolver with "fused" either fell back
        # (ABFT carry, VMEM reject in auto mode) or never integrated the
        # fused kernel (the phased diagnostic factorizer) — they resolve
        # the remaining panel-factor choice as "auto".
        panel_impl = "auto"
    if panel_impl == "auto":
        # The Pallas VMEM-resident panel kernel uses TPU-only Mosaic
        # features; it is the fast path on real TPUs — when its block fits
        # VMEM — and stock JAX everywhere else (CPU test mesh, GPU) and
        # beyond the kernel budget (slower per panel, but unlimited).
        if jax.default_backend() != "tpu":
            return "jax"
        if (n is not None and panel is not None
                and not panel_fits_vmem(n, panel, itemsize)):
            return "jax"
        return "pallas"
    if panel_impl not in ("jax", "pallas"):
        raise ValueError(f"unknown panel_impl {panel_impl!r}")
    if (panel_impl == "pallas" and jax.default_backend() == "tpu"
            and n is not None and panel is not None
            and not panel_fits_vmem(n, panel, itemsize)):
        # An EXPLICIT pallas request past the ceiling must fail with a
        # sizing error, not a Mosaic scoped-VMEM error (ADVICE r3) — on a
        # real TPU only; everywhere else the kernel runs in interpret mode,
        # which has no VMEM limit.
        raise ValueError(
            f"panel_impl='pallas' requested but the (h={n}, panel={panel}) "
            f"panel block exceeds the VMEM budget; use panel_impl='auto' "
            f"(stock-JAX panel there) or a narrower panel")
    return panel_impl


def _factor_panel(sub, kb, h: int, panel: int, panel_impl: str,
                  zero_pivot_safe: bool = False):
    """Slice and factor the (h, panel) column block of ``sub`` whose diagonal
    sits at row offset ``kb``. Returns (p, ipiv, perm_local_or_None, mp).
    Single source for every blocked-factorization loop.

    ``zero_pivot_safe`` guards the multiplier division (see
    :func:`_panel_factor_jax`) — the recovery ladder's re-factor rung; only
    the stock-JAX panel implements it, so callers must resolve
    ``panel_impl='jax'`` when requesting it."""
    p = lax.dynamic_slice(sub, (0, kb), (h, panel))
    if panel_impl == "pallas":
        from gauss_tpu.kernels.panel_pallas import panel_factor_pallas

        p, ipiv, perm_local, mp = panel_factor_pallas(p, kb)
        return p, ipiv, perm_local, mp
    p, ipiv, mp = _panel_factor_jax(p, kb, zero_pivot_safe=zero_pivot_safe)
    return p, ipiv, None, mp


def _fold_transpositions(ipiv, kb, h: int, panel: int):
    """Fold a jax-panel transposition sequence into one gather permutation."""
    def fold(j, pl):
        x, y = pl[kb + j], pl[ipiv[j]]
        return pl.at[kb + j].set(y).at[ipiv[j]].set(x)

    # Init inherits ipiv's varying-manual-axes type (see _panel_factor_jax).
    return lax.fori_loop(0, panel, fold, jnp.arange(h) + ipiv[0] * 0)


def _install_and_update(sub, kb, h: int, panel: int, p, gemm_prec, dtype,
                        w: int | None = None):
    """Install the factored panel at column kb of the (row-permuted) ``sub``,
    compute the diagonal-block inverses, apply U12 = L11^-1 A12, and the
    masked trailing GEMM. Returns (sub, linv_k, uinv_k). Shared by the
    fori_loop and chunked factorizations — they must stay in numerical
    lockstep.

    ``sub`` may be rectangular (h, w): the chunked factorization passes only
    the group's own column block (w = chunk*panel), deferring the update of
    the columns right of the group to one big GEMM per group (see
    lu_factor_blocked_chunked) — the per-panel update then touches O(h*w)
    instead of O(h^2)."""
    w = h if w is None else w
    rows = jnp.arange(h)
    cols = jnp.arange(w)
    sub = lax.dynamic_update_slice(sub, p, (0, kb))

    # Diagonal-block inverses (TRTRI+GEMM): U12 and lu_solve become GEMMs
    # instead of substitution chains. Accumulate dtype (f32 for bf16
    # storage — the precision contract).
    d = lax.dynamic_slice(sub, (kb, kb), (panel, panel))
    linv_k, uinv_k = _diag_block_invs(d, panel, dtype)

    # Block row of U: U12 = L11^-1 A12, masked so finished columns
    # (multipliers left of the panel, the panel itself) stay untouched.
    # _gdot rounds the f32-accumulated solve once back to storage.
    block_row = lax.dynamic_slice(sub, (kb, 0), (panel, w))
    solved = _gdot(linv_k, block_row, gemm_prec, dtype)
    right = cols >= kb + panel
    block_row = jnp.where(right[None, :], solved, block_row)
    sub = lax.dynamic_update_slice(sub, block_row, (kb, 0))

    # Trailing GEMM on the MXU: A22 -= L21 @ U12, masked operands — the
    # finished region multiplies by zero and stays bit-identical.
    l21 = jnp.where((rows >= kb + panel)[:, None],
                    lax.dynamic_slice(sub, (0, kb), (h, panel)),
                    jnp.zeros((), dtype))
    u12 = jnp.where(right[None, :], block_row, jnp.zeros((), dtype))
    sub = sub - _gdot(l21, u12, gemm_prec, dtype)
    return sub, linv_k, uinv_k


# --- ABFT: checksum-carrying factorization (Huang & Abraham 1984) ---------
#
# A column-checksum row c = e^T A, carried as a separate (1, npad) array,
# is an invariant of blocked LU with partial pivoting: row swaps permute
# rows WITHIN the active trailing set (column sums over it are unchanged),
# and the group update A22' = A22 - L21 @ U12 maps the checksum to
# c2' = c2 - (c1 @ Ugroup^-1) @ U12 = e^T A22' (the checksum row is just
# one more eliminated row that never wins a pivot contest). Verifying
# c2' == colsums(A22') after each panel group detects silent data
# corruption WITHIN the group that produced it — an O(n * trailing)
# reduction against the group's O(n^2 * w) GEMM FLOPs — and the final
# e^T PA = (e^T L) @ U identity covers the already-factored region the
# group checks no longer watch. All helpers are traced only when
# ``abft=True``; the off path compiles to the exact pre-ABFT program.


def _csum_init(m: jax.Array) -> jax.Array:
    """The initial column-checksum row ``e^T m`` of the padded operand."""
    return jnp.sum(m, axis=0, keepdims=True)


def _csum_group_solve(c1, grp, uinvs, gpanels: int, panel: int, prec):
    """``Lc = c1 @ Ugroup^-1``: blockwise right-substitution against the
    factored group's (w, w) upper triangle, through the stored per-panel
    ``uinv`` diagonal-block inverses (the checksum row's multipliers — the
    same quantity ``e^T [L11; L21]`` the elimination would have produced
    row-operation by row-operation)."""
    xs = []
    for j in range(gpanels):
        r = c1[:, j * panel:(j + 1) * panel]
        for i in range(j):
            r = r - jnp.dot(xs[i], grp[i * panel:(i + 1) * panel,
                                       j * panel:(j + 1) * panel],
                            precision=prec)
        xs.append(jnp.dot(r, uinvs[j], precision=prec))
    return jnp.concatenate(xs, axis=1)


def _csum_group_col_err(block, u, c1, w: int):
    """The group-column identity ``c1 == (e^T L_group) @ Ugroup`` over the
    group's own ``w`` columns: ``block`` is the (h, w) factored column
    trapezoid (L multipliers strictly below the diagonal, whose row index
    equals the column index within the group), ``u`` its (w, w) top block.
    This is the whole-factor identity restricted to the group — EXACT in
    the corruption (a flip of magnitude d in the group block shows as a
    ~d mismatch), where the trailing-block check only sees group-column
    corruption through U^-1-attenuated propagation (~d/n for diagonally
    dominant systems). Returns (max mismatch, argmax column within the
    group); NaN folds to +inf."""
    h = block.shape[0]
    rows = jnp.arange(h)[:, None]
    cols = jnp.arange(w)[None, :]
    one = jnp.ones((), block.dtype)
    el = jnp.sum(jnp.where(rows > cols, block, jnp.zeros((), block.dtype)),
                 axis=0) + one  # unit diagonal of L
    rw = jnp.arange(w)
    ug = jnp.where(rw[:, None] <= rw[None, :], u,
                   jnp.zeros((), block.dtype))
    pred = jnp.dot(el[None, :], ug, precision=lax.Precision.HIGHEST)
    diff = pred[0] - c1[0]
    diff = jnp.where(jnp.isnan(diff), jnp.inf, jnp.abs(diff))
    return jnp.max(diff), jnp.argmax(diff)


def _csum_trailing_err(m, crow, split):
    """``(max |colsums(trailing) - crow|, argmax column)`` over the
    trailing block at rows/cols >= ``split`` (which may be traced; masked
    form). NaN mismatches fold to +inf so a NaN-poisoning corruption is
    DETECTED rather than comparing false."""
    npad = m.shape[0]
    live = jnp.arange(npad) >= split
    colsum = jnp.sum(jnp.where(live[:, None], m, jnp.zeros((), m.dtype)),
                     axis=0)
    diff = jnp.where(live, colsum - crow[0], jnp.zeros((), m.dtype))
    diff = jnp.where(jnp.isnan(diff), jnp.inf, jnp.abs(diff))
    return jnp.max(diff), jnp.argmax(diff)


def _csum_final_err_lu(m, crow0):
    """The post-factor identity ``e^T P A = (e^T L) @ U``: column sums are
    invariant under the row permutation, so the initial checksum row must
    equal the L-column-sum-weighted combination of U's rows. O(n^2) total;
    covers the factored L/U region the per-group trailing checks stop
    watching once a group retires (including the final group, whose
    trailing block is empty)."""
    npad = m.shape[0]
    rows = jnp.arange(npad)
    strict_lower = rows[:, None] > rows[None, :]
    one = jnp.ones((), m.dtype)
    el = jnp.sum(jnp.where(strict_lower, m, jnp.zeros((), m.dtype)),
                 axis=0) + one  # unit diagonal of L
    u = jnp.where(~strict_lower, m, jnp.zeros((), m.dtype))
    pred = jnp.dot(el[None, :], u, precision=lax.Precision.HIGHEST)
    diff = pred[0] - crow0[0]
    diff = jnp.where(jnp.isnan(diff), jnp.inf, jnp.abs(diff))
    return jnp.max(diff), jnp.argmax(diff)


def _lu_factor_blocked(a: jax.Array, panel: int | None = DEFAULT_PANEL,
                       panel_impl: str = "auto",
                       gemm_precision: str = "highest",
                       swap_impl: str = "gather",
                       zero_pivot_safe: bool = False,
                       abft: bool = False) -> BlockedLU:
    """Blocked LU with partial pivoting; one fori_loop over column panels.

    panel_impl: "jax" (stock fori_loop rank-1 updates), "pallas" (the
    VMEM-resident kernel from kernels.panel_pallas), "fused" (the
    panel+trailing kernel from kernels.panel_fused_pallas — factor and
    trailing update in ONE launch), or "auto" (fused on TPU while its
    working set fits VMEM, then pallas, then jax).
    gemm_precision: MXU precision for the trailing updates. Default "highest"
    (6-pass f32 emulation): measured on v5e, "high" (bf16x3) saves only ~4%
    wall-clock but costs ~50x residual accuracy on random matrices and stalls
    iterative refinement at ~1e-7 relative residual.
    swap_impl: how the jax panel path applies pivot swaps to the rest of the
    matrix — "gather" (one folded permutation, default) or "loop" (two-row
    exchanges, kept for comparison). The Pallas panel kernel emits a folded
    permutation directly (its ipiv is the pivot-choice sequence, not swap
    partners), so with panel_impl "pallas" — the "auto" resolution on TPU —
    swaps always go through the gather path and "loop" has no effect.
    zero_pivot_safe: guard the panel multiplier division so an exactly-zero
    pivot eliminates nothing instead of NaN-poisoning the trailing rows
    (``min_abs_pivot`` still records 0). The recovery ladder's re-factor
    rung (gauss_tpu.resilience.recover): a near-singular or corrupted
    system factors to a FINITE factor the residual gate can judge, instead
    of a NaN factor nothing downstream can use. Only the stock-JAX panel
    implements the guard, so the panel impl is pinned to "jax".
    abft: carry the Huang-Abraham column-checksum row and verify it against
    the trailing block after every panel (plus the final ``(e^T L) U``
    identity); mismatch magnitudes return in ``BlockedLU.abft_err``
    ((nb + 1,)). The factor arrays m/perm/linv/uinv are BIT-IDENTICAL to
    ``abft=False`` — the checksum is a rider, never an operand — and with
    ``abft=False`` (the default) none of it is traced: zero cost, same
    compiled program as before the option existed.
    """
    from gauss_tpu.kernels.matmul_pallas import resolve_precision

    gemm_prec = resolve_precision(gemm_precision, allow_split=True)
    if swap_impl not in ("gather", "loop"):
        raise ValueError(f"unknown swap_impl {swap_impl!r}; options: ('gather', 'loop')")
    a = jnp.asarray(a)
    n = a.shape[0]
    if a.shape != (n, n):
        raise ValueError(f"expected square matrix, got {a.shape}")
    itemsize = jnp.dtype(a.dtype).itemsize
    _check_lowered_support(a.dtype, gemm_prec, abft)
    panel = _resolve_panel(n, panel, itemsize)
    if zero_pivot_safe:
        panel_impl = "jax"
        use_fused = False
    else:
        use_fused = _use_fused(panel_impl, n, panel,
                               -(-n // panel) * panel, itemsize,
                               carried=abft)
        panel_impl = _resolve_panel_impl(panel_impl, n, panel, itemsize)
    m = _pad_to_panel(a, panel)
    npad = m.shape[0]
    nb = npad // panel
    dtype = m.dtype
    inv_dt = accum_dtype(dtype)  # linv/uinv storage (precision contract)

    def outer_fused(k, carry):
        """The fused step: factor + trailing update in one kernel launch
        (never traced with the ABFT rider — _use_fused falls back). The
        pivot rows come back holding U12 and the live rows the updated
        trailing block, so only the permutation gather, the panel install,
        and the lu_solve diagonal-block inverses remain at XLA level."""
        from gauss_tpu.kernels.panel_fused_pallas import \
            panel_trailing_fused_pallas

        m, perm, min_piv, linvs, uinvs = carry
        kb = k * panel
        p, ipiv, perm_local, mp, m_upd = panel_trailing_fused_pallas(
            m, kb, kb, panel=panel)
        min_piv = jnp.minimum(min_piv, mp)
        m = m_upd[perm_local]
        perm = perm[perm_local]
        m = lax.dynamic_update_slice(m, p, (0, kb))
        d = lax.dynamic_slice(m, (kb, kb), (panel, panel))
        linv_k, uinv_k = _diag_block_invs(d, panel, dtype)
        linvs = lax.dynamic_update_slice(linvs, linv_k[None], (k, 0, 0))
        uinvs = lax.dynamic_update_slice(uinvs, uinv_k[None], (k, 0, 0))
        return m, perm, min_piv, linvs, uinvs

    def outer(k, carry):
        if abft:
            m, perm, min_piv, linvs, uinvs, crow, errs = carry
        else:
            m, perm, min_piv, linvs, uinvs = carry
        kb = k * panel
        p, ipiv, perm_local, mp = _factor_panel(m, kb, npad, panel,
                                                panel_impl,
                                                zero_pivot_safe=zero_pivot_safe)
        min_piv = jnp.minimum(min_piv, mp)

        # Apply the panel's pivot permutation to the rest of the matrix. Two
        # equivalent implementations (the panel itself already has it):
        # "gather" folds it into one permutation and gathers the whole
        # matrix — O(n^2) traffic but one fused op, measured ~2.5x faster on
        # v5e than "loop", which exchanges two rows per step (O(panel * n)
        # traffic but `panel` serialized tiny dispatches). The Pallas panel
        # kernel builds the permutation in-kernel (see panel_pallas docstring:
        # the XLA-level fold loop was 6.3 ms of an 11 ms n=2048 factorization)
        # and its ipiv is a pivot-choice sequence, not swap partners, so the
        # "loop" transposition replay only applies to the jax panel path.
        if swap_impl == "loop" and perm_local is None:
            def swapj(j, state):
                m, perm = state
                r1, r2 = kb + j, ipiv[j]
                row1, row2 = m[r1], m[r2]
                m = m.at[r1].set(row2).at[r2].set(row1)
                p1, p2 = perm[r1], perm[r2]
                perm = perm.at[r1].set(p2).at[r2].set(p1)
                return m, perm

            m, perm = lax.fori_loop(0, panel, swapj, (m, perm))
        else:
            if perm_local is None:
                perm_local = _fold_transpositions(ipiv, kb, npad, panel)
            m = m[perm_local]
            perm = perm[perm_local]

        m, linv_k, uinv_k = _install_and_update(m, kb, npad, panel, p,
                                                gemm_prec, dtype)
        linvs = lax.dynamic_update_slice(linvs, linv_k[None], (k, 0, 0))
        uinvs = lax.dynamic_update_slice(uinvs, uinv_k[None], (k, 0, 0))
        if abft:
            # The checksum row is one more eliminated row: its multipliers
            # are Lc = c1 @ U11^-1, its trailing entries get the same
            # L @ U12 subtraction the real rows got, and the trailing
            # block's column sums must then still match it.
            c1 = lax.dynamic_slice(crow, (0, kb), (1, panel))
            lc = jnp.dot(c1, uinv_k, precision=gemm_prec)
            cols_ge = jnp.arange(npad) >= kb + panel
            u12 = jnp.where(cols_ge[None, :],
                            lax.dynamic_slice(m, (kb, 0), (panel, npad)),
                            jnp.zeros((), dtype))
            crow = crow - jnp.dot(lc, u12, precision=gemm_prec)
            ev, _ = _csum_trailing_err(m, crow, kb + panel)
            # Panel-column identity (exact in the corruption; cf.
            # _csum_group_col_err — inlined because the flat form's panel
            # block spans all rows with a traced diagonal offset).
            rr = jnp.arange(npad)[:, None]
            cc = jnp.arange(panel)[None, :]
            blk = lax.dynamic_slice(m, (0, kb), (npad, panel))
            el = jnp.sum(jnp.where(rr > kb + cc, blk,
                                   jnp.zeros((), dtype)),
                         axis=0) + jnp.ones((), dtype)
            d = lax.dynamic_slice(m, (kb, kb), (panel, panel))
            rp = jnp.arange(panel)
            u11 = jnp.where(rp[:, None] <= rp[None, :], d,
                            jnp.zeros((), dtype))
            pred = jnp.dot(el[None, :], u11,
                           precision=lax.Precision.HIGHEST)
            gdiff = pred[0] - c1[0]
            gdiff = jnp.where(jnp.isnan(gdiff), jnp.inf, jnp.abs(gdiff))
            ev = jnp.maximum(ev, jnp.max(gdiff))
            errs = lax.dynamic_update_slice(errs, ev[None], (k,))
            return m, perm, min_piv, linvs, uinvs, crow, errs
        return m, perm, min_piv, linvs, uinvs

    init = (m, jnp.arange(npad), jnp.asarray(jnp.inf, dtype),
            jnp.zeros((nb, panel, panel), inv_dt),
            jnp.zeros((nb, panel, panel), inv_dt))
    if abft:
        crow0 = _csum_init(m)
        init = init + (crow0, jnp.zeros((nb,), dtype))
        m, perm, min_piv, linvs, uinvs, _, errs = lax.fori_loop(
            0, nb, outer, init)
        fe, _ = _csum_final_err_lu(m, crow0)
        return BlockedLU(m=m, perm=perm, min_abs_pivot=min_piv,
                         linv=linvs, uinv=uinvs,
                         abft_err=jnp.concatenate([errs, fe[None]]))
    m, perm, min_piv, linvs, uinvs = lax.fori_loop(
        0, nb, outer_fused if use_fused else outer, init)
    return BlockedLU(m=m, perm=perm, min_abs_pivot=min_piv,
                     linv=linvs, uinv=uinvs)


_LU_FACTOR_STATICS = ("panel", "panel_impl", "gemm_precision", "swap_impl",
                      "zero_pivot_safe", "abft")
lu_factor_blocked = _reraise_scoped_vmem(
    jax.jit(_lu_factor_blocked, static_argnames=_LU_FACTOR_STATICS))
#: The donating twin: same trace, ``a``'s buffer donated so XLA reuses it
#: for the factor instead of holding operand + factor + transients live
#: (one full matrix copy less on the hot path). Callers must OWN the
#: operand buffer (it is invalidated on backends that honor donation —
#: including CPU on jax >= 0.4.x); resolve_factor(donate=True) routes here.
lu_factor_blocked_donating = _reraise_scoped_vmem(
    jax.jit(_lu_factor_blocked, static_argnames=_LU_FACTOR_STATICS,
            donate_argnums=(0,)))


def _lu_factor_blocked_unrolled(a: jax.Array,
                                panel: int | None = DEFAULT_PANEL,
                                panel_impl: str = "auto",
                                gemm_precision: str = "highest") -> BlockedLU:
    """Blocked LU with the panel loop unrolled at trace time.

    Identical math and factor layout to :func:`lu_factor_blocked`, but the
    outer loop over column panels is a Python loop, so every slice bound is
    static and the trailing submatrix genuinely shrinks: the GEMM does the
    true triangular ~2/3*n^3 FLOPs instead of the masked full-size 2*n^3, the
    panel kernel factors (n - kb, panel) instead of (n, panel), and no
    row/column masks are needed anywhere. Costs one traced program per panel
    (nb GEMM shapes to compile) — the right trade for the repeated-solve
    benchmark sizes; the fori_loop version keeps compile time flat for
    one-shot or very large n.
    """
    from gauss_tpu.kernels.matmul_pallas import resolve_precision

    gemm_prec = resolve_precision(gemm_precision, allow_split=True)
    a = jnp.asarray(a)
    n = a.shape[0]
    if a.shape != (n, n):
        raise ValueError(f"expected square matrix, got {a.shape}")
    itemsize = jnp.dtype(a.dtype).itemsize
    panel = _resolve_panel(n, panel, itemsize)
    impl_req = panel_impl
    panel_impl = _resolve_panel_impl(panel_impl, n, panel, itemsize)
    m = _pad_to_panel(a, panel)
    npad = m.shape[0]
    dtype = m.dtype
    perm = jnp.arange(npad)
    min_piv = jnp.asarray(jnp.inf, dtype)
    linvs = []

    for kb in range(0, npad, panel):
        tail = npad - kb
        # Fused panel+trailing step, resolved PER PANEL on the shrinking
        # live height (like the chunked route's per-group resolution):
        # factor, U12, and the trailing update leave the kernel as one
        # launch; only the permutation gather and the panel install remain.
        if _use_fused(impl_req, tail, panel, npad - kb, itemsize):
            from gauss_tpu.kernels.panel_fused_pallas import \
                panel_trailing_fused_pallas

            live = m[kb:]
            p, ipiv, perm_local, mp, live_upd = panel_trailing_fused_pallas(
                live, kb, 0, panel=panel)
            min_piv = jnp.minimum(min_piv, mp)
            live = live_upd[perm_local]
            perm = perm.at[kb:].set(perm[kb:][perm_local])
            live = live.at[:, kb:kb + panel].set(p)
            linvs.append(_diag_block_linv(live[:panel, kb:kb + panel],
                                          panel, dtype))
            m = m.at[kb:].set(live)
            continue
        # The live column block: rows kb.. only — earlier rows are finished U.
        p = m[kb:, kb:kb + panel]
        if panel_impl == "pallas":
            from gauss_tpu.kernels.panel_pallas import panel_factor_pallas

            p, ipiv, perm_local, mp = panel_factor_pallas(p, 0)
        else:
            p, ipiv, mp = _panel_factor_jax(p, 0)

            def fold(j, pl, ipiv=ipiv):
                x, y = pl[j], pl[ipiv[j]]
                return pl.at[j].set(y).at[ipiv[j]].set(x)

            perm_local = lax.fori_loop(0, panel, fold, jnp.arange(tail))
        min_piv = jnp.minimum(min_piv, mp)

        # Permute the live rows (all columns: L multipliers left of the panel
        # move with their rows), install the factored panel, then update.
        live = m[kb:][perm_local]
        perm = perm.at[kb:].set(perm[kb:][perm_local])
        live = live.at[:, kb:kb + panel].set(p)
        # Explicit diagonal-block L inverse: U12 becomes a GEMM (log-depth)
        # instead of a panel-length substitution chain. The U inverses are
        # needed only by lu_solve, not inside this loop — they are computed
        # batched after it, off the serial critical path (measured ~0.06 ms
        # of the 2.0 ms n=2048 factor when computed per panel here).
        linv = _diag_block_linv(live[:panel, kb:kb + panel], panel, dtype)
        linvs.append(linv)
        if kb + panel < npad:
            u12 = _gdot(linv, live[:panel, kb + panel:], gemm_prec, dtype)
            live = live.at[:panel, kb + panel:].set(u12)
            l21 = live[panel:, kb:kb + panel]
            trail = live[panel:, kb + panel:]
            live = live.at[panel:, kb + panel:].set(
                trail - _gdot(l21, u12, gemm_prec, dtype))
        m = m.at[kb:].set(live)

    # Batched U diagonal-block inverses: one vmapped TRTRI over the nb
    # finished diagonal blocks (parallel MXU work) instead of nb serial
    # per-panel inversions inside the loop above.
    diags = jnp.stack([m[kb:kb + panel, kb:kb + panel]
                       for kb in range(0, npad, panel)])
    uinvs = jax.vmap(lambda d: _diag_block_uinv(d, panel, dtype))(diags)
    return BlockedLU(m=m, perm=perm, min_abs_pivot=min_piv,
                     linv=jnp.stack(linvs), uinv=uinvs)


_UNROLLED_STATICS = ("panel", "panel_impl", "gemm_precision")
lu_factor_blocked_unrolled = _reraise_scoped_vmem(
    jax.jit(_lu_factor_blocked_unrolled, static_argnames=_UNROLLED_STATICS))
#: Donating twin (see lu_factor_blocked_donating).
lu_factor_blocked_unrolled_donating = _reraise_scoped_vmem(
    jax.jit(_lu_factor_blocked_unrolled, static_argnames=_UNROLLED_STATICS,
            donate_argnums=(0,)))


# Blockwise lu_solve trace form: unrolled below this many blocks (every
# dot shape static and fusable — the measured-fast small-n path), one
# lax.scan per direction at or above it. The unrolled form's payload is
# ~2*nb distinctly-shaped dots PER SOLVE; inside the ds-refined pipeline
# (7 solves) at n=17758 (nb=139) that is ~2000 traced ops, which the
# tunneled compiler did not finish in 33 minutes (round 3) — the scan form
# compiles two block-generic bodies regardless of nb.
LU_SOLVE_UNROLL_MAX_NB = 16


def _blockwise_substitution_scan(m, invs, rhs, lower: bool):
    """One lax.scan over the nb block rows of the factored matrix: per
    block, a (panel, npad) x (npad, k) dot folds in the already-solved
    blocks (the unsolved region of the running solution is zero), then the
    stored diagonal-block inverse finishes the block. Same math as the
    unrolled form; O(1) trace size in nb."""
    npad = m.shape[0]
    nb, panel, _ = invs.shape
    prec = lax.Precision.HIGHEST

    def step(x, i):
        rows = lax.dynamic_slice(m, (i * panel, 0), (panel, npad))
        r = lax.dynamic_slice(rhs, (i * panel, 0), (panel, rhs.shape[1]))
        r = r - jnp.dot(rows, x, precision=prec)
        xi = jnp.dot(invs[i], r, precision=prec)
        return lax.dynamic_update_slice(x, xi, (i * panel, 0)), i

    order = jnp.arange(nb) if lower else jnp.arange(nb - 1, -1, -1)
    x, _ = lax.scan(step, jnp.zeros_like(rhs), order)
    return x


@partial(jax.jit, static_argnames=("method",))
def lu_solve(factors: BlockedLU, b: jax.Array,
             method: str = "auto") -> jax.Array:
    """Solve A x = b given a BlockedLU of A: permute, L-solve, U-solve.

    With stored diagonal-block inverses (unrolled factorization), both
    substitutions run blockwise — per block one small-matvec against the
    off-diagonal strip plus one inverse multiply — an O(nb)-step chain of
    MXU ops instead of an O(n)-step scalar-recurrence chain (measured
    0.42 -> ~0.1 ms at n=2048 on v5e). Up to LU_SOLVE_UNROLL_MAX_NB blocks
    the chain is unrolled at trace time; beyond it the same math runs as
    one lax.scan per direction so the trace stays O(1) in nb (the compile
    payload at n=17758 otherwise defeated the tunneled compiler, round 3).

    ``method``: "auto" uses the stored inverses when present, else
    substitution; "substitution" forces ``lax.linalg.triangular_solve``
    even when inverses exist. The trade-off (ADVICE round 1): explicit
    TRTRI+GEMM inverses trade substitution's backward stability for speed —
    unit-lower inverses can grow up to 2^(panel-1) on Wilkinson-type
    adversarial matrices, and an ill-conditioned U diagonal block loses
    accuracy its substitution would keep. Partial pivoting keeps |L| <= 1
    so real inputs sit far from the bound (every verified report cell
    passes the 1e-4 gate, and solve_refined's refinement absorbs the
    difference), but callers with adversarial or very ill-conditioned
    systems should pass method="substitution".

    ``b`` may be a single right-hand side (n,) or a block of them (n, k) —
    one factorization serves many solves (the getrf/getrs split the
    reference's monolithic programs lack); every dot below is already
    GEMM-shaped, so the k axis rides along for free."""
    if method not in ("auto", "substitution"):
        raise ValueError(f"unknown method {method!r}; options: "
                         "('auto', 'substitution')")
    m, perm = factors.m, factors.perm
    npad = m.shape[0]
    # Solves run in the ACCUMULATE dtype (f32 against a bf16 factor, and
    # returned in it — refinement corrections only need f32 relative
    # accuracy, and the substitution chain must not re-round per block;
    # the precision contract at the top of this module). Identity at f32.
    cdt = accum_dtype(m.dtype)
    b = jnp.asarray(b, dtype=cdt)
    was_vector = b.ndim == 1
    b2 = b[:, None] if was_vector else b
    if b2.ndim != 2:
        raise ValueError(f"b must be (n,) or (n, k), got {b.shape}")
    n, k = b2.shape
    bp = jnp.zeros((npad, k), dtype=cdt).at[:n].set(b2)[perm]
    if factors.linv is None or method == "substitution":
        ms = m.astype(cdt)
        y = lax.linalg.triangular_solve(
            ms, bp, left_side=True, lower=True, unit_diagonal=True)
        x = lax.linalg.triangular_solve(
            ms, y, left_side=True, lower=False, unit_diagonal=False)
        return x[:n, 0] if was_vector else x[:n]

    nb, panel, _ = factors.linv.shape
    if nb > LU_SOLVE_UNROLL_MAX_NB:
        # Scan form against the RAW factor, no masking needed: in each
        # pass the unsolved region of the running solution is zero, so the
        # full-width row dot picks up exactly the solved off-diagonal
        # terms — L's at the forward pass (U columns meet zeros), U's at
        # the backward pass (L columns meet zeros), and the diagonal
        # block's own columns meet its still-zero slot (same argument as
        # dist.gauss_dist_blocked._block_substitution).
        y = _blockwise_substitution_scan(m, factors.linv, bp, lower=True)
        x = _blockwise_substitution_scan(m, factors.uinv, y, lower=False)
        x = x[:n]
        return x[:, 0] if was_vector else x
    prec = lax.Precision.HIGHEST
    # Forward: y_i = Linv_ii (b_i - L_i,<i y_<i)
    yblocks = []
    for i in range(nb):
        r = bp[i * panel:(i + 1) * panel]
        if i:
            y_prev = jnp.concatenate(yblocks)
            r = r - jnp.dot(m[i * panel:(i + 1) * panel, :i * panel], y_prev,
                            precision=prec)
        yblocks.append(jnp.dot(factors.linv[i], r, precision=prec))
    y = jnp.concatenate(yblocks)
    # Backward: x_i = Uinv_ii (y_i - U_i,>i x_>i)
    xblocks = [None] * nb
    for i in range(nb - 1, -1, -1):
        r = y[i * panel:(i + 1) * panel]
        if i < nb - 1:
            x_next = jnp.concatenate(xblocks[i + 1:])
            r = r - jnp.dot(m[i * panel:(i + 1) * panel, (i + 1) * panel:],
                            x_next, precision=prec)
        xblocks[i] = jnp.dot(factors.uinv[i], r, precision=prec)
    x = jnp.concatenate(xblocks)[:n]
    return x[:, 0] if was_vector else x


def _lu_factor_blocked_chunked(a: jax.Array,
                               panel: int | None = DEFAULT_PANEL,
                               chunk: int = CHUNK_DEFAULT,
                               panel_impl: str = "auto",
                               gemm_precision: str = "highest",
                               abft: bool = False) -> BlockedLU:
    """Blocked LU with the panel loop unrolled in GROUPS of ``chunk`` panels.

    The middle point between :func:`lu_factor_blocked` (one fori_loop, flat
    compile time, but full-size masked work every panel) and
    :func:`lu_factor_blocked_unrolled` (true triangular work, but one traced
    program per panel — compile payload grows with n/panel and breaks
    tunneled remote compilation around n=8192). Groups are unrolled at trace
    time with STATIC shrinking bounds; panels within a group run under one
    fori_loop over the group's (gh, gh) trailing submatrix. Work is
    triangular at group granularity (overhead ~ (1 + panel*chunk/n)x), and
    the compile payload scales with n/(panel*chunk), not n/panel.

    The group's left L-multiplier columns are realigned ONCE per group after
    its local permutations compose — per-panel realignment measured slower
    (gathers are per-op latency-bound), per-group is chunk x fewer ops.

    Round 4 restructure (VERDICT r3 next #1, the lookahead form): panels
    inside a group factor and update ONLY the group's own (gh, W=chunk*panel)
    column block — each next panel is factored from columns the narrow
    update already brought current, before any of the right-of-group
    trailing matrix is touched. The columns right of the group then receive
    ONE composed-permutation gather, one blockwise L^-1 solve (lax.scan over
    the group's chunk block rows), and one big unmasked (gh-W, W) x (W, rt)
    MXU GEMM per group. The per-panel full-width masked GEMM + full
    submatrix gather of the round-3 form did ~chunk x more HBM traffic for
    the same FLOPs; measured at n=16384 chunk-8 this restructure took the
    factorization 0.59 s -> ~0.2 s class (see reports). This completes the
    reference Version-2's cache-blocking idea
    (Pthreads/Version-2/gauss_internal_input.c:162-173) at MXU scale.
    """
    from gauss_tpu.core.matmul import resolve_precision

    gemm_prec = resolve_precision(gemm_precision, allow_split=True)
    if chunk < 1:
        raise ValueError(f"chunk must be >= 1, got {chunk}")
    a = jnp.asarray(a)
    n = a.shape[0]
    if a.shape != (n, n):
        raise ValueError(f"expected square matrix, got {a.shape}")
    itemsize = jnp.dtype(a.dtype).itemsize
    _check_lowered_support(a.dtype, gemm_prec, abft)
    panel = _resolve_panel(n, panel, itemsize)
    m = _pad_to_panel(a, panel)
    npad = m.shape[0]
    nb = npad // panel
    dtype = m.dtype
    perm = jnp.arange(npad)
    min_piv = jnp.asarray(jnp.inf, dtype)
    linvs_all, uinvs_all = [], []

    crow0 = crow = _csum_init(m) if abft else None
    errs = []
    for g0 in range(0, nb, chunk):
        if abft:
            m, perm, min_piv, linvs, uinvs, crow, err, _ = _factor_group(
                m, perm, min_piv, g0, panel, chunk, panel_impl, gemm_prec,
                crow=crow)
            errs.append(err)
        else:
            m, perm, min_piv, linvs, uinvs = _factor_group(
                m, perm, min_piv, g0, panel, chunk, panel_impl, gemm_prec)
        linvs_all.append(linvs)
        uinvs_all.append(uinvs)

    abft_err = None
    if abft:
        fe, _ = _csum_final_err_lu(m, crow0)
        abft_err = jnp.stack(errs + [fe])
    return BlockedLU(m=m, perm=perm, min_abs_pivot=min_piv,
                     linv=jnp.concatenate(linvs_all),
                     uinv=jnp.concatenate(uinvs_all),
                     abft_err=abft_err)


_CHUNKED_STATICS = ("panel", "chunk", "panel_impl", "gemm_precision", "abft")
lu_factor_blocked_chunked = _reraise_scoped_vmem(
    jax.jit(_lu_factor_blocked_chunked, static_argnames=_CHUNKED_STATICS))
#: Donating twin (see lu_factor_blocked_donating).
lu_factor_blocked_chunked_donating = _reraise_scoped_vmem(
    jax.jit(_lu_factor_blocked_chunked, static_argnames=_CHUNKED_STATICS,
            donate_argnums=(0,)))


def _factor_group(m, perm, min_piv, g0: int, panel: int, chunk: int,
                  panel_impl: str, gemm_prec, crow=None):
    """One group of the chunked factorization: factor (up to) ``chunk``
    panels starting at panel index ``g0``, apply the group's composed
    permutation, and run the deferred right-of-group update. Returns
    ``(m, perm, min_piv, linvs, uinvs)`` with the group's (gpanels, panel,
    panel) diagonal-block inverses.

    ``crow``: an optional (1, ncols) ABFT column-checksum row (see the
    checksum helpers above). When given, it receives the group's
    ``Lc @ U12`` update and the trailing block is verified against it; the
    return grows to ``(..., crow', err, err_col)`` — the mismatch
    magnitude and the global column index it localizes to. ``None`` (the
    default) traces exactly the pre-ABFT program; the checkpointed path
    (gauss_tpu.resilience.checkpoint), the ABFT group runner
    (gauss_tpu.resilience.abft), and the host-streamed out-of-core engine
    (gauss_tpu.outofcore) share this one function, so checkpointed, ABFT,
    and out-of-core factorizations cannot drift numerically.

    ``m`` may be RECTANGULAR: the trailing width is derived from
    ``m.shape[1]``, not the height, so the out-of-core engine can pass the
    group's own (gh, w) column block alone (``gs=0``, trailing width 0 —
    the in-core last-group trace) and stream the right-of-group tiles
    through its own windowed update. Square callers are unchanged:
    ``m.shape[1] == npad`` reproduces the exact pre-existing bounds, same
    trace, bit-identical program.

    Single source for :func:`lu_factor_blocked_chunked` (which unrolls every
    group into one traced program) and
    :mod:`gauss_tpu.resilience.checkpoint` (which jits and runs groups one
    at a time at host level, serializing this function's carry between
    groups — the checkpoint IS this signature). All group-shape arguments
    are trace-time statics; ``gemm_prec`` is an already-resolved
    ``lax.Precision``.
    """
    npad = m.shape[0]
    nb = npad // panel
    dtype = m.dtype
    itemsize = jnp.dtype(dtype).itemsize
    gs = g0 * panel              # group start row/col (static)
    gh = npad - gs               # static trailing size
    gpanels = min(chunk, nb - g0)
    w = gpanels * panel          # group block width (static)
    # Right-of-group trailing width, derived from the WIDTH so a
    # rectangular (gh, w) group-only buffer (the out-of-core step) gets
    # rt=0; square callers get exactly the old gh - w.
    rt = m.shape[1] - gs - w
    grp = m[gs:, gs:gs + w]      # (gh, w) group column block
    # Fused panel+trailing resolution is PER GROUP too: within a group the
    # panel's trailing update covers the group's own (gh, w) column block,
    # so the fused kernel's working set is the group height times the
    # trailing tile — the right-of-group deferred GEMM below is untouched.
    # An active ABFT rider (crow) deterministically falls back to the
    # unfused pair (see _use_fused), keeping the checksum math — and the
    # abft=True bit-identity contract — on the path it was validated on.
    fused_g = _use_fused(panel_impl, gh, panel, w, itemsize,
                         carried=crow is not None)
    # Panel-impl resolution is PER GROUP on the group height; explicit
    # "jax"/"pallas" requests stay global. Narrow panel-64 groups
    # additionally drop to the stock-JAX panel in auto mode: slicing
    # the panel from a group block under PANEL64_MIN_SLICE_W columns
    # fuses into the aliased kernel call and double-counts its block
    # in scoped VMEM (the round-5 compile probes) — resolve_factor
    # never produces such a config, but explicit chunk/panel
    # combinations can.
    impl_g = _resolve_panel_impl(panel_impl, gh, panel, itemsize)
    # Two group-width contexts degrade the kernel's aliasing into a
    # full block double-count (round-5 compile probes): panel-64
    # slices from groups NARROWER than PANEL64_MIN_SLICE_W, and
    # panel-128 slices from groups EXACTLY 2048 columns wide (W=1024
    # and W=4096 alias fine at 128; the fusion decision is
    # whole-program-context dependent — the same (128, 14336) shape
    # compiled inside n=24576 and double-counted inside n=32768, so
    # this guard is necessarily approximate and explicit
    # outside-the-auto-envelope configs can still hit raw Mosaic
    # scoped-VMEM errors). Auto mode drops guarded groups to the
    # stock-JAX panel; explicit pallas requests get the clear sizing
    # error (same contract as _resolve_panel_impl, ADVICE r3).
    narrow64 = panel <= 64 and w < PANEL64_MIN_SLICE_W
    wide128 = (panel == 128 and w == 2048
               and gh * (2 * panel * itemsize + 128) > PANEL_VMEM_BUDGET)
    if impl_g == "pallas" and (narrow64 or wide128) and not fused_g:
        if panel_impl in ("auto", "fused"):
            impl_g = "jax"
        elif jax.default_backend() == "tpu":
            raise ValueError(
                f"panel_impl='pallas': the (h={gh}, panel={panel}) "
                f"kernel block does not fit scoped VMEM in a "
                f"{w}-column group context; adjust chunk, or use "
                f"panel_impl='auto' (stock-JAX panel for these groups)")

    def body(j, carry, gh=gh, w=w, panel_impl=impl_g):
        grp, gperm, min_piv, linvs, uinvs = carry
        kb = j * panel           # panel offset WITHIN the group
        if fused_g:
            # One launch: factor + in-group trailing update (pivot rows
            # return holding U12); only the permutation gather, the panel
            # install, and the diagonal-block inverses remain here.
            from gauss_tpu.kernels.panel_fused_pallas import \
                panel_trailing_fused_pallas

            p, ipiv, perm_local, mp, grp_upd = panel_trailing_fused_pallas(
                grp, kb, kb, panel=panel)
            min_piv = jnp.minimum(min_piv, mp)
            grp = grp_upd[perm_local]
            gperm = gperm[perm_local]
            grp = lax.dynamic_update_slice(grp, p, (0, kb))
            d = lax.dynamic_slice(grp, (kb, kb), (panel, panel))
            linv_k, uinv_k = _diag_block_invs(d, panel, dtype)
            linvs = lax.dynamic_update_slice(linvs, linv_k[None], (j, 0, 0))
            uinvs = lax.dynamic_update_slice(uinvs, uinv_k[None], (j, 0, 0))
            return grp, gperm, min_piv, linvs, uinvs
        p, ipiv, perm_local, mp = _factor_panel(grp, kb, gh, panel,
                                                panel_impl)
        if perm_local is None:
            perm_local = _fold_transpositions(ipiv, kb, gh, panel)
        min_piv = jnp.minimum(min_piv, mp)
        grp = grp[perm_local]
        gperm = gperm[perm_local]

        grp, linv_k, uinv_k = _install_and_update(grp, kb, gh, panel, p,
                                                  gemm_prec, dtype, w=w)
        linvs = lax.dynamic_update_slice(linvs, linv_k[None], (j, 0, 0))
        uinvs = lax.dynamic_update_slice(uinvs, uinv_k[None], (j, 0, 0))
        return grp, gperm, min_piv, linvs, uinvs

    gperm0 = jnp.arange(gh)
    inv_dt = accum_dtype(dtype)  # precision contract: f32 invs at bf16
    linvs0 = jnp.zeros((gpanels, panel, panel), inv_dt)
    uinvs0 = jnp.zeros((gpanels, panel, panel), inv_dt)
    grp, gperm, min_piv, linvs, uinvs = lax.fori_loop(
        0, gpanels, body, (grp, gperm0, min_piv, linvs0, uinvs0))

    unstripped = (4 * npad * m.shape[1] * itemsize
                  <= GROUP_UPDATE_UNSTRIPPED_MAX_BYTES)
    # One fix-up per group: realign the L-multiplier columns written by
    # earlier groups (left of gs) with this group's composed
    # permutation. In the strip form (HBM-ceiling band) the SAME gather
    # realigns the right columns too: full rows, one gather, so the
    # strip updates below can run in place on row-aligned data — peak
    # HBM stays ~2 matrix copies. (Round 4 realigned left-only and
    # gathered permuted rows per strip into a full (gh-w, rt) `fresh`
    # accumulator; at n=34048 that schedule needed 19.7 GB and failed
    # to compile — a claim the round-4 report never actually backed.)
    if not unstripped:
        # Offset indices, not slice-then-gather: m[gs:][gperm] makes the
        # compiler materialize the (gh, npad) slice AND the gather
        # output (2 x 3.75 GB at n=32768, 70 MB over budget).
        m = m.at[gs:].set(m[gs + gperm])
    elif gs:
        left = m[gs:, :gs][gperm]
        m = m.at[gs:, :gs].set(left)
    m = m.at[gs:, gs:gs + w].set(grp)
    perm = perm.at[gs:].set(perm[gs:][gperm])

    if rt:
        # Deferred right-of-group update: the group's block rows of the
        # right columns (already row-permuted in the strip form; via a
        # composed-permutation gather otherwise), then
        # U12 = L_group^-1 A12 as a blockwise scan over the group's
        # chunk block rows (same zero-meets-U argument as
        # _blockwise_substitution_scan), then the whole group's
        # trailing contribution as one logical (gh-w, w) x (w, rt) MXU
        # GEMM — one pass in the unstripped form, bounded in-place ROW
        # STRIPS in the HBM-ceiling band.
        if unstripped:
            top = m[gs + gperm[:w]][:, gs + w:]  # (w, rt) block rows
        else:
            top = lax.dynamic_slice(m, (gs, gs + w), (w, rt))

        def usolve(x, i, grp=grp):
            rows = lax.dynamic_slice(grp, (i * panel, 0), (panel, w))
            r = lax.dynamic_slice(top, (i * panel, 0), (panel, rt))
            r = r - _gdot(rows, x, gemm_prec, dtype)
            xi = _gdot(linvs[i], r, gemm_prec, dtype)
            return lax.dynamic_update_slice(x, xi, (i * panel, 0)), i

        u12, _ = lax.scan(usolve, jnp.zeros((w, rt), dtype),
                          jnp.arange(gpanels))
        if crow is not None:
            # The checksum row's group-end update: its multipliers over the
            # group columns (c1 @ Ugroup^-1) times the group's U12 — the
            # exact rider of the big trailing GEMM below.
            lc = _csum_group_solve(crow[:, gs:gs + w], grp, uinvs, gpanels,
                                   panel, gemm_prec)
            crow = crow.at[:, gs + w:].add(
                -jnp.dot(lc, u12, precision=gemm_prec))

        if unstripped:
            # One gather + one GEMM; transients peak ~3 trailing-block
            # copies, fine while the byte gate holds.
            def a22_full(rows_idx, l21_full):
                old = m[gs + rows_idx][:, gs + w:]
                return old - _gdot(l21_full, u12, gemm_prec, dtype)

            fresh = a22_full(gperm[w:], grp[w:])
            # Writes come LAST: gperm[w:] can name original rows < w,
            # so the gather must read the right region's OLD data — the
            # u12 block-row write would clobber exactly those rows.
            m = lax.dynamic_update_slice(m, u12, (gs, gs + w))
            m = lax.dynamic_update_slice(m, fresh, (gs + w, gs + w))
        else:
            # Rows are already permutation-aligned: each strip reads
            # and writes only its own rows of m — in place, no
            # accumulator, no read-after-write hazard.
            m = lax.dynamic_update_slice(m, u12, (gs, gs + w))
            sw = min(GROUP_UPDATE_STRIP, gh - w)
            nfull = (gh - w) // sw

            def strip_body(s, m):
                r0 = w + s * sw
                old = lax.dynamic_slice(m, (gs + r0, gs + w), (sw, rt))
                l21 = lax.dynamic_slice(grp, (r0, 0), (sw, w))
                new = old - _gdot(l21, u12, gemm_prec, dtype)
                return lax.dynamic_update_slice(m, new, (gs + r0, gs + w))

            m = lax.fori_loop(0, nfull, strip_body, m)
            tail = (gh - w) - nfull * sw
            if tail:
                old = m[gs + w + nfull * sw:gs + gh, gs + w:]
                new = old - _gdot(grp[w + nfull * sw:], u12, gemm_prec,
                                  dtype)
                m = m.at[gs + w + nfull * sw:gs + gh, gs + w:].set(new)

    if crow is not None:
        # Two checks, two failure surfaces: the group-column identity is
        # EXACT for corruption landing in the group's own columns (where
        # the trailing check only sees it through U^-1-attenuated
        # propagation), the trailing-sum check is exact for corruption in
        # the deferred-update region. Together every active-region flip
        # shows at ~its own magnitude, in the group that produced it.
        g_err, g_col = _csum_group_col_err(grp, grp[:w, :w],
                                           crow[:, gs:gs + w], w)
        g_col = gs + g_col
        if rt:
            sub = m[gs + w:, gs + w:]
            diff = jnp.sum(sub, axis=0) - crow[0, gs + w:]
            diff = jnp.where(jnp.isnan(diff), jnp.inf, jnp.abs(diff))
            t_err, t_col = jnp.max(diff), gs + w + jnp.argmax(diff)
            err = jnp.maximum(g_err, t_err)
            err_col = jnp.where(g_err >= t_err, g_col, t_col)
        else:
            err, err_col = g_err, g_col
        return m, perm, min_piv, linvs, uinvs, crow, err, err_col
    return m, perm, min_piv, linvs, uinvs


def lu_factor_blocked_phased(a: jax.Array, panel: int | None = None,
                             panel_impl: str = "auto",
                             gemm_precision: str = "highest",
                             timer=None) -> BlockedLU:
    """Blocked LU with per-phase telemetry spans — the solver-phase profile.

    Same math, helpers, and factor layout as :func:`lu_factor_blocked`, but
    the panel loop runs at HOST level with a device-completion-bounded span
    around each phase (``panel_factor`` / ``pivot_apply`` /
    ``trailing_update``), reported through the PhaseTimer -> obs bridge —
    the TPU equivalent of the reference's per-phase ``gettimeofday``
    instrumentation, at the granularity its gprof profile resolved
    (computeGauss vs subtractElim). One dispatch per phase instead of one
    fused program: this is the diagnostic path (use the jitted
    factorizations for production numbers); the phase RATIOS are the
    payload — e.g. a trailing_update share far off ~O(n/panel) x the
    panel_factor share flags a mis-tiled GEMM.

    ``timer``: an optional :class:`gauss_tpu.utils.profiling.PhaseTimer` to
    accumulate into — pass your own to read the table afterwards (a private
    one is used otherwise). Spans land on the active obs recorder either
    way, via the PhaseTimer bridge.
    """
    from gauss_tpu.kernels.matmul_pallas import resolve_precision
    from gauss_tpu.utils.profiling import PhaseTimer

    gemm_prec = resolve_precision(gemm_precision, allow_split=True)
    pt = PhaseTimer() if timer is None else timer
    a = jnp.asarray(a)
    n = a.shape[0]
    if a.shape != (n, n):
        raise ValueError(f"expected square matrix, got {a.shape}")
    itemsize = jnp.dtype(a.dtype).itemsize
    panel = _resolve_panel(n, panel, itemsize)
    panel_impl = _resolve_panel_impl(panel_impl, n, panel, itemsize)
    with pt.phase("pad_stage"):
        m = jax.block_until_ready(_pad_to_panel(a, panel))
    npad = m.shape[0]
    nb = npad // panel
    dtype = m.dtype
    perm = jnp.arange(npad)
    min_piv = jnp.asarray(jnp.inf, dtype)
    linvs, uinvs = [], []

    for k in range(nb):
        kb = k * panel
        with pt.phase("panel_factor"):
            p, ipiv, perm_local, mp = _factor_panel(m, kb, npad, panel,
                                                    panel_impl)
            jax.block_until_ready(p)
        min_piv = jnp.minimum(min_piv, mp)
        with pt.phase("pivot_apply"):
            if perm_local is None:
                perm_local = _fold_transpositions(ipiv, kb, npad, panel)
            m = m[perm_local]
            perm = perm[perm_local]
            jax.block_until_ready(m)
        with pt.phase("trailing_update"):
            m, linv_k, uinv_k = _install_and_update(m, kb, npad, panel, p,
                                                   gemm_prec, dtype)
            jax.block_until_ready(m)
        linvs.append(linv_k)
        uinvs.append(uinv_k)

    return BlockedLU(m=m, perm=perm, min_abs_pivot=min_piv,
                     linv=jnp.stack(linvs), uinv=jnp.stack(uinvs))


UNROLL_MAX_N = 4096  # above this, full unroll costs too much compile payload
# Above this many trace-time GROUPS the chunked form's compile payload
# overwhelms the tunneled compiler (observed r2: 96 groups at n=24576,
# panel=64 never finished in 590 s; observed r3: 35 groups at n=17758
# inside the ds-refined solve did not compile within 49 MINUTES — the
# memplus device-span "crash" of VERDICT r2 missing #2). The payload
# scales with the group count, not the panel count, so resolve_factor
# first ESCALATES the chunk to bring the group count under this cap
# (n=16384 -> chunk 8, 16 groups, compiles in minutes and runs 2.3x
# faster than flat) and only routes to the flat one-traced-body program
# past chunk-16's reach.
MAX_CHUNK_GROUPS = 24


MAX_CHUNK = 32  # escalation ceiling: chunk-32 at panel 128 (the round-5
# auto width past ~12.4k) reaches 24 * 32 * 128 = 98k — far past the
# single-chip HBM ceiling (~34k), so the flat fori fallback is never the
# route below it (VERDICT r3 next #2). Group count, not group size, is
# what the tunneled compiler cannot absorb (see MAX_CHUNK_GROUPS); wider
# groups also make the one deferred trailing GEMM per group deeper
# (W = 4096 at panel 128, chunk 32).


def resolve_factor(n: int, unroll, *, donate: bool = False,
                   checkpoint_path=None, abft: bool = False):
    """The factorization for (size, unroll policy): "auto" picks fully
    unrolled up to UNROLL_MAX_N (true triangular work; measured
    6.1 -> 3.9 ms at n=2048 on v5e, and 1.43 -> 0.66 s on the CPU proxy —
    the PR-10 reclaim measurement: the flat form's masked full-size GEMMs
    cost ~2x the FLOPs, which a CPU pays linearly), group-chunked above it
    (triangular at group granularity, bounded compile payload;
    121 -> 59 ms at n=8192). Sub-1024 systems on non-TPU backends keep the
    flat one-traced-body form — at test-mesh sizes compile time dominates
    and the per-panel trace payload buys nothing.
    The chunked form's compile payload scales with its GROUP count (each
    group is one traced fori body at a distinct size; panels inside a group
    are a loop, not a trace), so when chunk=4 would exceed MAX_CHUNK_GROUPS
    the chunk ESCALATES (8, then 16) before falling back to the flat
    fori_loop — measured round 3: n=16384 runs 1.39 s on the flat route vs
    0.59 s chunked-8, memplus (17758) 1.91 s flat vs 0.82 s chunked-8.
    The flat fori_loop remains the route past chunk-16's reach and below
    n=1024 off-TPU. True/False force unrolled/fori; "chunked" forces the
    middle.

    A tuned store (gauss_tpu.tune) overrides the CHUNK starting point per
    n-bucket — the escalation cap still applies on top (a tuned chunk can
    never produce a group count the tunneled compiler is known to choke
    on); panel tuning rides through auto_panel.

    **The fast-path contract** (ROADMAP perf item, reclaimed in PR 10):
    with the keyword defaults — no checkpoint path, no ABFT carry — the
    returned callable is ONE fully-jitted program: no host-stepped group
    loop, no per-group device sync, and no hook callsites (io_callback /
    pure_callback or any other host primitive) anywhere in its traced
    jaxpr. Fault-injection and obs consults happen at trace/entry time
    only, so hooks cost nothing unless enabled (tested:
    tests/test_fused.py asserts the jaxpr is callback-free).

    ``donate=True`` selects the buffer-donating twin: the operand's buffer
    is handed to XLA for reuse (one matrix copy less live). Only for
    callers that OWN the operand — it is invalidated on backends that
    honor donation, including CPU. ``checkpoint_path`` routes to the
    host-stepped checkpointed factorization (the ONLY host-stepped route;
    its per-group steps donate their carry internally). ``abft=True``
    selects the checksum-carrying jitted form — still one program, with
    the rider verified on device; the host-stepped replay runner lives in
    resilience.abft. checkpoint_path and abft are mutually exclusive.
    """
    if checkpoint_path is not None:
        if abft:
            raise ValueError("checkpoint_path and abft are mutually "
                             "exclusive; the ABFT runner keeps its own "
                             "in-memory carry (resilience.abft)")
        from gauss_tpu.resilience.checkpoint import \
            lu_factor_blocked_chunked_checkpointed

        return partial(lu_factor_blocked_chunked_checkpointed,
                       path=checkpoint_path)

    def pick(fn):
        if abft:
            if fn is lu_factor_blocked_unrolled:
                # The unrolled form carries no checksum rider; the flat
                # fori form is the single-program checksum carrier at
                # unrolled sizes.
                fn = lu_factor_blocked
            base = partial(fn, abft=True)
            return base
        if donate:
            fn = _DONATING.get(fn, fn)
        return fn

    if unroll == "auto":
        if jax.default_backend() != "tpu" and n < 1024:
            # Tiny systems: one traced fori body; the unrolled form's
            # per-panel programs buy nothing at sizes where the whole
            # solve is microseconds (and the test meshes live here).
            return pick(lu_factor_blocked)
        if n > UNROLL_MAX_N:
            from gauss_tpu.tune import apply as _tune

            panel = auto_panel(n)
            nb = -(-n // panel)
            chunk = int(_tune.override("lu_factor", n, "chunk")
                        or CHUNK_DEFAULT)
            while -(-nb // chunk) > MAX_CHUNK_GROUPS and chunk < MAX_CHUNK:
                chunk *= 2
            if -(-nb // chunk) > MAX_CHUNK_GROUPS:
                return pick(lu_factor_blocked)
            # Panel-128 chunk-16 (W=2048 groups) inflates the aliased
            # kernel's scoped overhead at the top sizes (27.3 M at
            # n=34048, 16.3 M at 32768) and would push the tallest
            # kernel-eligible groups back onto the stock-JAX panel; chunk
            # 8 and chunk 32 both compile and measure faster everywhere
            # probed, so the escalation skips that rung. (auto_panel no
            # longer returns 64, so no narrow-group pin is needed here;
            # explicit panel-64 configs are guarded per group in
            # lu_factor_blocked_chunked.)
            if panel == 128 and chunk == 16:
                chunk = 32
            if chunk == CHUNK_DEFAULT:
                return pick(lu_factor_blocked_chunked)
            return partial(pick(lu_factor_blocked_chunked), chunk=chunk)
        return pick(lu_factor_blocked_unrolled)
    if unroll == "chunked":
        return pick(lu_factor_blocked_chunked)
    if isinstance(unroll, str):
        raise ValueError(f"unknown unroll {unroll!r}; options: "
                         "(True, False, 'auto', 'chunked')")
    return pick(lu_factor_blocked_unrolled if unroll else lu_factor_blocked)


#: non-donating entry point -> its buffer-donating twin (resolve_factor's
#: donate=True routing).
_DONATING = {
    lu_factor_blocked: lu_factor_blocked_donating,
    lu_factor_blocked_chunked: lu_factor_blocked_chunked_donating,
    lu_factor_blocked_unrolled: lu_factor_blocked_unrolled_donating,
}


@_reraise_scoped_vmem
@partial(jax.jit, static_argnames=("panel", "panel_impl", "unroll",
                                   "gemm_precision"))
def gauss_solve_blocked(a: jax.Array, b: jax.Array,
                        panel: int | None = None,
                        panel_impl: str = "auto",
                        unroll: bool | str = "auto",
                        gemm_precision: str = "highest") -> jax.Array:
    """Factor + solve in one jitted program (the fast single-chip solver)."""
    factor = resolve_factor(a.shape[0], unroll)
    return lu_solve(factor(a, panel=panel, panel_impl=panel_impl,
                           gemm_precision=gemm_precision), b)


def solve_refined(a: np.ndarray, b: np.ndarray, panel: int | None = None,
                  iters: int = 2, dtype=jnp.float32, panel_impl: str = "auto",
                  a_dev: jax.Array | None = None,
                  b_dev: jax.Array | None = None,
                  tol: float = 0.0, unroll: bool | str = "auto"):
    """Mixed-precision solve: f32 blocked factorization + f64 residual refinement.

    TPUs are f32-native; the reference's gauss programs compute in f64. To meet
    the BASELINE.json residual bar (||Ax - b|| < 1e-4) at n=2048 with an f32
    factorization, we run classical iterative refinement: residuals in f64 on
    host (one O(n^2) matvec per iteration — microseconds against the O(n^3)
    factorization), corrections through the already-computed f32 factors.
    Returns (x, factors) with x float64.

    ``a_dev``/``b_dev``: optionally the already-device-resident ``dtype`` casts
    of a/b, so timed callers can stage the H2D transfer outside their span
    (the reference's timed regions likewise start with the matrix already in
    memory, gauss_internal_input.c:278-284); a/b remain the f64 host operands
    used for residuals.

    ``tol``: stop refining once ``||Ax - b||_2 <= tol * min(1, ||b||_2)``
    (the residual is already in hand each iteration, so the check is free and
    each skipped iteration saves a host->device->host correction round trip).
    The ``min(1, ||b||)`` scaling is never looser than the absolute ``tol``
    (the internal flavor's acceptance bar is absolute) and tightens
    proportionally for small-magnitude systems (the external flavor's bar is
    relative). 0.0 (the default) runs exactly ``iters`` iterations.
    """
    a64 = np.asarray(a, dtype=np.float64)
    b64 = np.asarray(b, dtype=np.float64)
    n = len(b64)
    created_a = a_dev is None
    if created_a:
        a_dev = jnp.asarray(a64, dtype=dtype)
    if b_dev is None:
        b_dev = jnp.asarray(b64, dtype=dtype)
    # Donate the factor operand when WE created it this call (a caller-
    # staged a_dev may be reused across that caller's reps) and the shape
    # is already a panel multiple (a padded donation is unusable and would
    # warn) — one full matrix copy less live inside the factorization.
    donate = created_a and n % _resolve_panel(
        n, panel, jnp.dtype(dtype).itemsize) == 0
    factor = resolve_factor(n, unroll, donate=donate)
    fac = factor(a_dev, panel=panel, panel_impl=panel_impl)
    x = np.asarray(lu_solve(fac, b_dev), dtype=np.float64)
    tol_eff = tol * min(1.0, float(np.linalg.norm(b64))) if tol > 0.0 else 0.0
    for _ in range(iters):
        r = b64 - a64 @ x
        if tol > 0.0 and float(np.linalg.norm(r)) <= tol_eff:
            break
        d = np.asarray(lu_solve(fac, jnp.asarray(r, dtype=dtype)), dtype=np.float64)
        x = x + d
    return x, fac


# Conservative usable HBM per chip when the runtime cannot report it
# (v5e ships 16 GiB; the runtime, compiled executables, and transients
# take a slice).
DEFAULT_CHIP_BYTES = 13 * 2**30


def device_memory_budget() -> int:
    """Usable bytes on the first visible device (runtime-reported when
    available, conservative v5e-class constant otherwise)."""
    try:
        stats = jax.devices()[0].memory_stats()
        if stats and stats.get("bytes_limit"):
            return int(0.85 * stats["bytes_limit"])
    except Exception:
        pass
    return DEFAULT_CHIP_BYTES


def fits_single_chip(n: int, itemsize: int = 4,
                     budget: int | None = None) -> bool:
    """Whether a blocked factorization's working set fits one device.

    Peak residency ~3 matrix copies (operand, factor-in-progress with its
    donated double-buffer, and slice/update transients); the diagonal-block
    inverses are nb * panel^2, negligible beside them.
    """
    budget = device_memory_budget() if budget is None else budget
    est = 3 * n * n * itemsize
    fits = est <= budget
    from gauss_tpu.obs import compile as _obs_compile

    _obs_compile.record_vmem_estimate("single_chip_hbm", n=n,
                                      itemsize=itemsize, bytes=est,
                                      budget=budget, fits=fits)
    return fits


def _handoff_itemsize(a, single_chip_kwargs: dict) -> int:
    """The DEVICE-STORAGE itemsize a handoff solve would actually occupy —
    the routing satellite of ISSUE 13. A requested ``dtype`` (what
    :func:`solve_refined` stages the operands at) wins; otherwise an
    operand that is ALREADY lowered-storage (f32/bf16/f16) keeps its own
    itemsize; f64 host operands count as 4 bytes because the refined path
    stages them at the float32 default. PR 11 plumbed bf16 storage through
    every factorization — with the old hardcoded ``itemsize=4`` a bf16
    request near the budget was routed OFF the single chip its working set
    actually fits."""
    req = single_chip_kwargs.get("dtype")
    if req is not None:
        return jnp.dtype(req).itemsize
    dt = getattr(a, "dtype", None)
    if dt is not None:
        dt = np.dtype(dt)
        # ml_dtypes floats (bfloat16 et al.) register as kind 'V'; both
        # count as already-lowered storage below 8 bytes.
        if dt.kind in ("f", "V") and dt.itemsize < 8:
            return dt.itemsize
    return 4


#: engines solve_handoff understands; None = size-routed.
HANDOFF_ENGINES = (None, "single_chip", "dist", "outofcore")


def solve_handoff(a, b, budget: int | None = None, mesh=None,
                  panel: int | None = None, iters: int = 2, tol: float = 0.0,
                  engine: str | None = None, **single_chip_kwargs):
    """Size-routed solve (VERDICT round 1 #8): the single-chip refined path
    while the working set fits one device, the sharded blocked engine
    (dist.gauss_dist_blocked) over the mesh beyond it, and — new in
    ISSUE 13 — the host-streamed out-of-core engine (gauss_tpu.outofcore)
    when the request is oversized but no multi-device mesh is visible:
    that case used to be an explicit error, not a capability. Returns x
    float64, refined on ALL routes.

    ``engine`` forces a lane: ``"single_chip"`` / ``"dist"`` /
    ``"outofcore"`` (None = size-routed). The working-set estimate is
    DTYPE-AWARE: itemsize derives from the requested ``dtype`` (or an
    already-lowered operand's own dtype — see :func:`_handoff_itemsize`),
    so a bfloat16 request near the budget routes single-chip where the old
    hardcoded f32 estimate would have pushed it off-chip; the itemsize is
    stamped into the ``route`` obs event.

    ``panel``/``iters``/``tol`` are honored on every route;
    ``single_chip_kwargs`` (panel_impl, unroll, dtype, a_dev/b_dev — see
    :func:`solve_refined`) only apply below the budget, and passing any
    that a chosen route cannot honor raises rather than silently ignoring
    the request (the out-of-core route honors ``dtype``).

    The single-chip ceiling this lifts: the f32 blocked path fits one v5e
    chip to n ~ 34k (HBM-bound). Past the budget the solve either needs the
    sharded engine's aggregate memory (preferred when a multi-device mesh
    is visible — the working set stays device-resident) or the streamed
    engine's host memory (single device: only the active panel group plus
    a bounded tile window live on device). Only when the HOST cannot hold
    the matrix either is an oversized request an error.
    """
    from gauss_tpu import obs

    if engine not in HANDOFF_ENGINES:
        raise ValueError(f"unknown handoff engine {engine!r}; options: "
                         f"{HANDOFF_ENGINES}")
    n = np.shape(a)[0]
    eff_budget = budget if budget is not None else device_memory_budget()
    itemsize = _handoff_itemsize(a, single_chip_kwargs)
    est_bytes = 3 * n * n * itemsize

    def _outofcore_route():
        from gauss_tpu import outofcore

        bad = sorted(set(single_chip_kwargs) - {"dtype"})
        if bad:
            raise ValueError(
                f"n={n} routes to the out-of-core engine and these options "
                f"do not apply to it: {bad}")
        obs.emit("route", tool="solve_handoff", n=n, lane="outofcore",
                 est_bytes=est_bytes, budget=eff_budget, itemsize=itemsize)
        return outofcore.solve_outofcore(a, b, panel=panel, iters=iters,
                                         tol=tol, **single_chip_kwargs)

    if engine == "outofcore":
        return _outofcore_route()
    if engine == "single_chip" or (
            engine is None
            and fits_single_chip(n, itemsize=itemsize, budget=budget)):
        # The routing decision as data (serve-lane traces show WHY a request
        # took a lane): estimated working set vs the budget that admitted it.
        obs.emit("route", tool="solve_handoff", n=n, lane="single_chip",
                 est_bytes=est_bytes, budget=eff_budget, itemsize=itemsize)
        return solve_refined(a, b, panel=panel, iters=iters, tol=tol,
                             **single_chip_kwargs)[0]
    from gauss_tpu.dist.gauss_dist_blocked import \
        gauss_solve_dist_blocked_refined
    from gauss_tpu.dist.mesh import make_mesh

    if mesh is None:
        mesh = make_mesh()
    if mesh.devices.size < 2:
        if engine is None:
            # No mesh to shard over: stream from host memory instead of
            # raising (the ISSUE 13 capability). Admission still applies —
            # a matrix the host cannot hold stays a typed error below.
            from gauss_tpu import outofcore

            if outofcore.outofcore_fits(n, itemsize=itemsize):
                return _outofcore_route()
        raise ValueError(
            f"n={n} exceeds the single-chip budget (needs ~{est_bytes} "
            f"bytes at itemsize {itemsize}, budget {eff_budget}) and only "
            f"{mesh.devices.size} device is visible; provide a multi-device "
            f"mesh (the sharded blocked engine splits the working set "
            f"across chips) — and the host-streamed out-of-core engine "
            f"cannot admit it either (gauss_tpu.outofcore.outofcore_fits)")
    if single_chip_kwargs:
        raise ValueError(
            f"n={n} exceeds the single-chip budget and these options do not "
            f"apply to the distributed route: {sorted(single_chip_kwargs)}")
    obs.emit("route", tool="solve_handoff", n=n, lane="dist",
             est_bytes=est_bytes, budget=eff_budget, itemsize=itemsize,
             devices=int(mesh.devices.size))
    return gauss_solve_dist_blocked_refined(a, b, mesh=mesh, panel=panel,
                                            iters=iters, tol=tol)
