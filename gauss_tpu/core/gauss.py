"""Gaussian elimination core: pivot, eliminate, back-substitute.

TPU-first re-expression of the reference's sequential skeleton
(reference Pthreads/Version-1/gauss_internal_input.c:75-227 for the internal
flavor; Pthreads/Version-1/gauss_external_input.c:125-278 for the external
flavor). XLA requires static shapes, so instead of the C code's shrinking
``j = i+1..n`` loop bounds, every pivot step performs a full-width masked
rank-1 update under a single compiled ``lax.fori_loop`` — the whole O(n^3)
elimination is one XLA program, not n kernel launches.

Pivoting policies (both reference behaviors are reproduced):

- ``"partial"``       — max-|column| partial pivoting, as in the external-input
                        programs (gauss_external_input.c:125-150).
- ``"first_nonzero"`` — swap only when the diagonal is exactly zero, taking the
                        first nonzero row below, as in the internal-input
                        programs (gauss_internal_input.c:75-121). Unlike the
                        reference (which tracks swaps in ``swap[]`` but forgets
                        to apply them to the RHS / back-substitution — a
                        documented defect, SURVEY.md §2), we swap the RHS
                        consistently.
- ``"none"``          — no pivoting (useful for oracle comparisons).

The pivot row is scaled to unit diagonal before elimination, matching the
reference (getPivot scales in the internal flavor, computeGauss in the
external flavor — gauss_internal_input.c:109-120, gauss_external_input.c:219-227),
so the returned U has 1.0 on the diagonal.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from gauss_tpu.resilience import inject as _inject

PIVOT_POLICIES = ("partial", "first_nonzero", "none")


class EliminationResult(NamedTuple):
    """Outcome of forward elimination on the augmented system [A | b].

    u:    (n, n) upper-triangular with unit diagonal (pivot rows scaled).
    y:    (n,) transformed RHS (same row operations applied).
    perm: (n,) row permutation actually applied; ``perm[k]`` is the original
          index of the row now in position k (the reference's ``swap[]``,
          gauss_internal_input.c:105-108, but recorded consistently).
    min_abs_pivot: scalar; min over steps of |pivot| before scaling. Zero means
          the matrix is singular (the reference aborts in that case —
          gauss_internal_input.c:95-98; we surface it as data so the check can
          live outside the jitted region).
    """

    u: jax.Array
    y: jax.Array
    perm: jax.Array
    min_abs_pivot: jax.Array


def _select_pivot(col: jax.Array, i: jax.Array, idx: jax.Array, policy: str) -> jax.Array:
    """Choose the pivot row index for step i given the current column i."""
    if policy == "partial":
        cand = jnp.where(idx >= i, jnp.abs(col), -jnp.inf)
        return jnp.argmax(cand)
    if policy == "first_nonzero":
        eligible = (col != 0) & (idx >= i)
        # argmax of a boolean array returns the first True.
        first = jnp.argmax(eligible)
        has_any = jnp.any(eligible)
        diag_ok = col[i] != 0
        return jnp.where(diag_ok, i, jnp.where(has_any, first, i))
    if policy == "none":
        return i
    raise ValueError(f"unknown pivoting policy {policy!r}; expected one of {PIVOT_POLICIES}")


@partial(jax.jit, static_argnames=("pivoting",))
def eliminate(a: jax.Array, b: jax.Array, pivoting: str = "partial") -> EliminationResult:
    """Forward elimination of the dense system ``a @ x = b``.

    One fused ``fori_loop`` over n pivot steps; each step is (pivot select,
    two-row swap, pivot-row scale, masked rank-1 update). The rank-1 update
    touches the full n x n array — columns left of the pivot are exactly zero
    already, so the redundant FLOPs are nops numerically and the static shape
    lets XLA tile the update onto the VPU without re-compilation per step.
    """
    a = jnp.asarray(a)
    b = jnp.asarray(b, dtype=a.dtype)
    n = a.shape[0]
    if a.shape != (n, n) or b.shape != (n,):
        raise ValueError(f"expected square a and matching b; got {a.shape} and {b.shape}")
    idx = jnp.arange(n)
    big = jnp.asarray(jnp.inf, dtype=a.dtype)

    def step(i, carry):
        A, rhs, perm, min_piv = carry
        col = A[:, i]
        p = _select_pivot(col, i, idx, pivoting)

        # Swap rows i and p (a no-op gather when p == i).
        row_i, row_p = A[i], A[p]
        A = A.at[i].set(row_p).at[p].set(row_i)
        bi, bp = rhs[i], rhs[p]
        rhs = rhs.at[i].set(bp).at[p].set(bi)
        si, sp = perm[i], perm[p]
        perm = perm.at[i].set(sp).at[p].set(si)

        piv = A[i, i]
        # A NaN pivot means an earlier zero pivot already poisoned the
        # trailing rows; report it as singular (0), not NaN.
        apiv = jnp.abs(piv)
        min_piv = jnp.minimum(min_piv, jnp.where(jnp.isnan(apiv), jnp.zeros((), a.dtype), apiv))

        # Scale the pivot row to unit diagonal (reference getPivot semantics).
        # XLA may rewrite the division as reciprocal-multiply, so pin the
        # pivot element to exactly 1 — which in turn makes the eliminated
        # subdiagonal exactly zero.
        prow = (A[i] / piv).at[i].set(jnp.asarray(1.0, a.dtype))
        yi = rhs[i] / piv
        A = A.at[i].set(prow)
        rhs = rhs.at[i].set(yi)

        # Masked rank-1 elimination of every row below the pivot.
        factors = jnp.where(idx > i, A[:, i], jnp.zeros((), a.dtype))
        A = A - factors[:, None] * prow[None, :]
        rhs = rhs - factors * yi
        return A, rhs, perm, min_piv

    u, y, perm, min_piv = lax.fori_loop(0, n, step, (a, b, idx, big))
    return EliminationResult(u=u, y=y, perm=perm, min_abs_pivot=min_piv)


@jax.jit
def back_substitute(u: jax.Array, y: jax.Array) -> jax.Array:
    """Solve ``u @ x = y`` for upper-triangular u (general diagonal).

    The reference's ``solveGauss`` (gauss_internal_input.c:212-227) walks rows
    bottom-up accumulating the dot of the already-solved suffix; here each step
    is a full-row masked dot so the loop is a single compiled scan over n steps.
    Rows produced by :func:`eliminate` have exact zeros below the diagonal, so
    the unmasked part of the dot contributes nothing.
    """
    u = jnp.asarray(u)
    y = jnp.asarray(y, dtype=u.dtype)
    n = u.shape[0]

    def step(k, x):
        i = n - 1 - k
        # x[j] is zero for j <= i (not yet solved), so a full-row dot picks up
        # exactly the solved suffix sum_{j>i} u[i,j] * x[j].
        acc = u[i] @ x
        xi = (y[i] - acc) / u[i, i]
        return x.at[i].set(xi)

    return lax.fori_loop(0, n, step, jnp.zeros_like(y))


@partial(jax.jit, static_argnames=("pivoting",))
def _gauss_solve_jit(a: jax.Array, b: jax.Array,
                     pivoting: str = "partial") -> jax.Array:
    res = eliminate(a, b, pivoting=pivoting)
    return back_substitute(res.u, res.y)


def gauss_solve(a: jax.Array, b: jax.Array, pivoting: str = "partial") -> jax.Array:
    """Dense solve via forward elimination + back-substitution (oracle path).

    Equivalent end-to-end behavior to the reference's
    ``computeGauss`` + ``solveGauss`` pipeline (gauss_external_input.c:204-278).
    For the fast blocked/MXU path see :mod:`gauss_tpu.core.blocked`.

    The host shim around the jitted pipeline is the "core.gauss.solve"
    fault-injection hook point (gauss_tpu.resilience.inject) — one global
    check when no plan is installed; calls inside an enclosing jit trace
    pass through untouched, same contract as the blocked engine's hook.
    """
    if _inject.enabled():
        a = _inject.corrupt_operand("core.gauss.solve", a)
    return _gauss_solve_jit(a, b, pivoting=pivoting)
