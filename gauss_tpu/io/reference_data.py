"""Locate and load the reference's REAL dataset matrices, read in place.

The reference evaluates its external-input programs on seven Harwell-Boeing
matrices shipped as ``.dat`` files in five ``matrices_dense/`` directories
(SURVEY.md §2 C8; e.g. reference Pthreads/Version-1/matrices_dense/jpwh_991.dat).
Those files are third-party data we do not copy into this repo; instead this
module finds them in a read-only reference checkout (default ``/root/reference``,
override with ``GAUSS_TPU_REFERENCE_ROOT``) and parses them AT USE TIME with the
same :mod:`gauss_tpu.io.datfile` reader the external CLI uses — so golden tests,
cross-engine comparisons, and the external benchmark grid run against the exact
matrices behind the reference reports' external tables (BASELINE.md), not the
same-shape synthetic stand-ins from :mod:`gauss_tpu.io.datasets`.

When no reference checkout is present (any other machine), everything here
degrades gracefully: :func:`find_dat` returns None and callers fall back to the
stand-ins, which remain the deterministic, redistributable default.
"""

from __future__ import annotations

import functools
import os
from pathlib import Path
from typing import Optional

import numpy as np

ROOT_ENV = "GAUSS_TPU_REFERENCE_ROOT"
DEFAULT_ROOT = "/root/reference"

# The five replicated dataset directories, in lookup order (files are
# md5-identical across them per SURVEY.md §2 C7/C8; first hit wins).
_SEARCH_DIRS = (
    "Pthreads/Version-1/matrices_dense",
    "Pthreads/Version-2/matrices_dense",
    "Pthreads/Version-3/matrices_dense",
    "OpenMP_and_MPI/gauss_openmp/matrices_dense",
    "OpenMP_and_MPI/gauss_mpi/matrices_dense",
)

# The real files shipped by the reference (matrix_2000 is referenced by its
# README but stripped from the mirror — regenerated, never "real").
REAL_NAMES = ("matrix_10", "jpwh_991", "orsreg_1", "sherman5", "saylr4",
              "sherman3", "memplus")


def reference_root() -> Path:
    return Path(os.environ.get(ROOT_ENV, DEFAULT_ROOT))


def available() -> bool:
    """True when a reference checkout with at least one dataset dir exists."""
    root = reference_root()
    return any((root / d).is_dir() for d in _SEARCH_DIRS)


@functools.lru_cache(maxsize=None)
def _find_dat_under(root: str, name: str) -> Optional[str]:
    for d in _SEARCH_DIRS:
        p = Path(root) / d / f"{name}.dat"
        if p.is_file():
            return str(p)
    return None


def find_dat(name: str) -> Optional[str]:
    """Absolute path of the real ``<name>.dat``, or None if absent.

    Cached per (root, name): a checkout is read-only and immutable for a run,
    but the ``$GAUSS_TPU_REFERENCE_ROOT`` override is re-read on every call
    (a later env change must not be poisoned by an earlier miss).
    """
    return _find_dat_under(str(reference_root()), name)


def load_dense(name: str, dtype=np.float64) -> np.ndarray:
    """Densified REAL reference matrix (raises KeyError when not available).

    Parse semantics are exactly the external programs' initMatrix
    (gauss_external_input.c:34-86): 1-indexed coordinates, last duplicate
    wins, ``0 0 0`` terminator, densified to row-major n x n.
    """
    from gauss_tpu.io import datfile

    path = find_dat(name)
    if path is None:
        raise KeyError(
            f"real reference matrix {name!r} not found under "
            f"{reference_root()} (set ${ROOT_ENV} to a reference checkout)")
    return datfile.read_dat_dense(path, dtype=dtype)
