"""Synthetic matrix / RHS initializers reproducing the reference's generators.

Two generator families exist in the reference and both are reproduced here:

- ``internal_matrix``: the in-memory benchmark init used by every
  internal-input program — ``matrix[i][j] = j < i ? 2*(j+1) : 2*(i+1)`` with
  ``B[i] = i`` (reference Pthreads/Version-1/gauss_internal_input.c:59-69).
  That formula is ``2 * (min(i, j) + 1)`` — a symmetric positive-definite
  "min matrix" whose solution against B is the closed form
  (-0.5, 0, ..., 0, 0.5) (gauss_internal_input.c:54-57).

- ``generator_matrix``: the standalone tool's emission,
  ``value = row < col ? 2*row : 2*col`` over 1-indexed coordinates
  (matrix_gen.cc:15-19) — i.e. ``2 * min(row, col)`` 1-indexed, which is the
  same matrix as ``internal_matrix`` (min is symmetric; the survey's
  "transposed convention" collapses for a symmetric formula).

- ``manufactured_rhs``: the external-input programs' oracle: preset solution
  ``X__[i] = i + 1`` and ``R = A @ X__`` so the max relative error of a
  computed solution is checkable (gauss_external_input.c:88-108).
"""

from __future__ import annotations

import numpy as np


def internal_matrix(n: int, dtype=np.float64) -> np.ndarray:
    """A[i, j] = 2 * (min(i, j) + 1), the internal-input benchmark matrix."""
    i = np.arange(n)
    return (2.0 * (np.minimum.outer(i, i) + 1)).astype(dtype)


def internal_rhs(n: int, dtype=np.float64) -> np.ndarray:
    """B[i] = i (gauss_internal_input.c:68)."""
    return np.arange(n, dtype=dtype)


def internal_expected_solution(n: int, dtype=np.float64) -> np.ndarray:
    """Closed-form solution of the internal system: (-0.5, 0, ..., 0, 0.5)."""
    x = np.zeros(n, dtype=dtype)
    x[0] = -0.5
    x[-1] = 0.5
    return x


def generator_matrix(n: int, dtype=np.float64) -> np.ndarray:
    """The matrix matrix_gen.cc emits: value = 2 * min(row, col), 1-indexed."""
    i = np.arange(1, n + 1)
    return (2.0 * np.minimum.outer(i, i)).astype(dtype)


def manufactured_solution(n: int, dtype=np.float64) -> np.ndarray:
    """X__[i] = i + 1, the external-input preset solution."""
    return np.arange(1, n + 1, dtype=dtype)


def manufactured_rhs(a: np.ndarray, x_true: np.ndarray = None) -> np.ndarray:
    """R = A @ X__ computed in float64 (the external-input initRHS)."""
    a = np.asarray(a, dtype=np.float64)
    if x_true is None:
        x_true = manufactured_solution(a.shape[0])
    return a @ np.asarray(x_true, dtype=np.float64)


# -- structured generators (gauss_tpu.structure) ---------------------------
#
# Deterministic matrices for each structure class the router recognizes, so
# datasets, serving mixes, and the chaos campaign can exercise the
# structured engines end to end. All values round-trip exactly through the
# .dat writer's %.17g (matrix_gen CLI --structure).

def spd_matrix(n: int, rho: float = 0.25, dtype=np.float64) -> np.ndarray:
    """Symmetric positive-definite Kac-Murdock-Szego matrix
    ``a_ij = rho^|i-j|``: SPD for |rho| < 1, and for rho <= 1/3 every
    Gershgorin disc sits strictly in the positive half-line
    (off-diagonal row sums < 2*rho/(1-rho) <= 1 = diagonal), so the
    structure detector can CERTIFY it rather than guess."""
    i = np.arange(n)
    return (rho ** np.abs(np.subtract.outer(i, i))).astype(dtype)


def banded_matrix(n: int, bandwidth: int = 1, dtype=np.float64) -> np.ndarray:
    """Strictly diagonally dominant symmetric band: ``2*(b+1)`` on the
    diagonal, ``-1`` within the band — the structured analog of the
    internal benchmark matrix (tridiagonal at b=1)."""
    a = np.zeros((n, n), dtype=dtype)
    np.fill_diagonal(a, 2.0 * (bandwidth + 1))
    for k in range(1, min(bandwidth, n - 1) + 1):
        idx = np.arange(n - k)
        a[idx, idx + k] = -1.0
        a[idx + k, idx] = -1.0
    return a


def blockdiag_matrix(n: int, block: int = 32, dtype=np.float64) -> np.ndarray:
    """Block-diagonal matrix of SPD "min matrix" blocks (the internal
    benchmark formula per block, plus a per-block diagonal shift so blocks
    differ); the last block is ragged when ``block`` does not divide n."""
    a = np.zeros((n, n), dtype=dtype)
    for c, s in enumerate(range(0, n, block)):
        w = min(block, n - s)
        i = np.arange(w)
        blk = 2.0 * (np.minimum.outer(i, i) + 1) + np.eye(w) * (c % 7)
        a[s:s + w, s:s + w] = blk
    return a


def dense_matrix(n: int, rho: float = 0.25, dtype=np.float64) -> np.ndarray:
    """Deterministic NON-symmetric dense matrix (the general-LU class):
    the KMS matrix with its upper triangle scaled 1.5x. Still strictly
    diagonally dominant (off-diagonal row sums < 2.5*rho/(1-rho) < 1 for
    rho = 0.25), hence invertible — but symmetric it is not, so the
    detector must refuse the Cholesky route."""
    a = spd_matrix(n, rho=rho, dtype=np.float64)
    a += np.triu(0.5 * a, 1)
    return a.astype(dtype)


def sparse_coords(n: int, nnz_per_row: int = 8, seed: int = 0,
                  symmetric: bool = True):
    """Deterministic sparse coordinate system for the Krylov plane:
    0-indexed ``(rows, cols, vals)`` with on average at most
    ``nnz_per_row`` stored entries per row, STRICTLY diagonally dominant
    (``a_ii = 1 + sum_j |a_ij|``), never densified — O(nnz) memory at any
    n. Symmetric (the default) also carries the Gershgorin SPD
    certificate, so CG is licensed; ``symmetric=False`` keeps dominance
    (invertible) but routes the general-system solvers. All values are
    float64 and round-trip exactly through the ``.dat`` writer's %.17g.
    """
    if n <= 0:
        z = np.zeros(0)
        return z.astype(np.int64), z.astype(np.int64), z
    rng = np.random.default_rng(
        np.random.SeedSequence((seed, n, nnz_per_row, int(symmetric))))
    # k off-diagonal draws per row; the symmetric mirror doubles them, so
    # halve the budget there (diagonal always present).
    k = max(0, (nnz_per_row - 1) // (2 if symmetric else 1))
    if k and n > 1:
        rows = np.repeat(np.arange(n, dtype=np.int64), k)
        cols = rng.integers(0, n - 1, n * k)
        cols += cols >= rows  # skew past the diagonal
        vals = rng.uniform(-1.0, 1.0, n * k)
        if symmetric:
            # Canonicalize to the upper triangle, drop duplicate slots,
            # then mirror — exact value symmetry by construction.
            r = np.minimum(rows, cols)
            c = np.maximum(rows, cols)
            codes = r * n + c
            _, first = np.unique(codes, return_index=True)
            r, c, vals = r[first], c[first], vals[first]
            rows = np.concatenate([r, c])
            cols = np.concatenate([c, r])
            vals = np.concatenate([vals, vals])
        else:
            codes = rows * n + cols
            _, first = np.unique(codes, return_index=True)
            rows, cols, vals = rows[first], cols[first], vals[first]
    else:
        rows = np.zeros(0, dtype=np.int64)
        cols = np.zeros(0, dtype=np.int64)
        vals = np.zeros(0)
    offsum = np.zeros(n)
    np.add.at(offsum, rows, np.abs(vals))
    diag_rows = np.arange(n, dtype=np.int64)
    return (np.concatenate([rows, diag_rows]),
            np.concatenate([cols, diag_rows]),
            np.concatenate([vals, 1.0 + offsum]))


def sparse_matrix(n: int, nnz_per_row: int = 8, seed: int = 0,
                  symmetric: bool = True, dtype=np.float64) -> np.ndarray:
    """Dense materialization of :func:`sparse_coords` for the SMALL-n
    consumers that need an ndarray operand (loadgen mixes, tests); the
    coordinate form is the scalable interface."""
    if n > 4096:
        raise ValueError(
            f"sparse_matrix densifies (n={n} > 4096); use sparse_coords")
    rows, cols, vals = sparse_coords(n, nnz_per_row, seed=seed,
                                     symmetric=symmetric)
    a = np.zeros((n, n), dtype=np.float64)
    a[rows, cols] = vals
    return a.astype(dtype)
