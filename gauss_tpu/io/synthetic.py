"""Synthetic matrix / RHS initializers reproducing the reference's generators.

Two generator families exist in the reference and both are reproduced here:

- ``internal_matrix``: the in-memory benchmark init used by every
  internal-input program — ``matrix[i][j] = j < i ? 2*(j+1) : 2*(i+1)`` with
  ``B[i] = i`` (reference Pthreads/Version-1/gauss_internal_input.c:59-69).
  That formula is ``2 * (min(i, j) + 1)`` — a symmetric positive-definite
  "min matrix" whose solution against B is the closed form
  (-0.5, 0, ..., 0, 0.5) (gauss_internal_input.c:54-57).

- ``generator_matrix``: the standalone tool's emission,
  ``value = row < col ? 2*row : 2*col`` over 1-indexed coordinates
  (matrix_gen.cc:15-19) — i.e. ``2 * min(row, col)`` 1-indexed, which is the
  same matrix as ``internal_matrix`` (min is symmetric; the survey's
  "transposed convention" collapses for a symmetric formula).

- ``manufactured_rhs``: the external-input programs' oracle: preset solution
  ``X__[i] = i + 1`` and ``R = A @ X__`` so the max relative error of a
  computed solution is checkable (gauss_external_input.c:88-108).
"""

from __future__ import annotations

import numpy as np


def internal_matrix(n: int, dtype=np.float64) -> np.ndarray:
    """A[i, j] = 2 * (min(i, j) + 1), the internal-input benchmark matrix."""
    i = np.arange(n)
    return (2.0 * (np.minimum.outer(i, i) + 1)).astype(dtype)


def internal_rhs(n: int, dtype=np.float64) -> np.ndarray:
    """B[i] = i (gauss_internal_input.c:68)."""
    return np.arange(n, dtype=dtype)


def internal_expected_solution(n: int, dtype=np.float64) -> np.ndarray:
    """Closed-form solution of the internal system: (-0.5, 0, ..., 0, 0.5)."""
    x = np.zeros(n, dtype=dtype)
    x[0] = -0.5
    x[-1] = 0.5
    return x


def generator_matrix(n: int, dtype=np.float64) -> np.ndarray:
    """The matrix matrix_gen.cc emits: value = 2 * min(row, col), 1-indexed."""
    i = np.arange(1, n + 1)
    return (2.0 * np.minimum.outer(i, i)).astype(dtype)


def manufactured_solution(n: int, dtype=np.float64) -> np.ndarray:
    """X__[i] = i + 1, the external-input preset solution."""
    return np.arange(1, n + 1, dtype=dtype)


def manufactured_rhs(a: np.ndarray, x_true: np.ndarray = None) -> np.ndarray:
    """R = A @ X__ computed in float64 (the external-input initRHS)."""
    a = np.asarray(a, dtype=np.float64)
    if x_true is None:
        x_true = manufactured_solution(a.shape[0])
    return a @ np.asarray(x_true, dtype=np.float64)
