"""Reader/writer for the reference's ``.dat`` sparse-coordinate matrix format.

Format (reference Pthreads/Version-1/matrices_dense/matrix_gen.cc:13-22 and the
parser in gauss_external_input.c:34-86):

    line 1: ``n n nnz``            (rows, cols, number of entries)
    body:   ``row col value``     one entry per line, **1-indexed**
    end:    ``0 0 0``             terminator row (optional in some files)

Entries may appear in any order. By default (``strict=True``) the parser
REJECTS, with a typed :class:`DatFormatError` carrying the offending line
number, three classes of file the reference's fscanf loop silently accepts
into a bad matrix: non-finite values (a NaN/Inf entry poisons every solve
downstream), duplicate ``(row, col)`` coordinates (the reference's
densifying loop overwrites — two generators disagreeing about one entry is
a corrupt file, not a preference), and a missing ``0 0 0`` terminator (the
classic truncated-upload signature). ``strict=False`` restores the exact
reference semantics — last duplicate wins, EOF terminates — for bug-parity
experiments.

A faster C++ parser for large files is provided by :mod:`gauss_tpu.native`
(``read_dat_dense(..., engine="native")`` uses it when built). The native
parser does not run the strict per-line checks; ``read_dat_dense`` applies
a whole-matrix finite check to its output instead.

**Duplicate-coordinate semantics.** A ``.dat`` file may name the same
``(row, col)`` twice; the three consumers resolve that differently, on
purpose, and the differences are pinned by tests (tests/test_sparse.py):

- ``strict=True`` (every reader's default): duplicates are a CORRUPT
  file — two generators disagreeing about one entry — and parsing fails
  with a typed :class:`DatFormatError` naming both lines. No consumer
  downstream ever sees an ambiguous matrix.
- ``strict=False``, dense path (:func:`read_dat` + :func:`densify`): the
  reference's fscanf loop scatters entries in file order, so the LAST
  occurrence wins — bug-parity with gauss_external_input.c's initMatrix.
- ``strict=False``, sparse assembly
  (:meth:`gauss_tpu.sparse.csr.CsrMatrix.from_dat`): coordinates are
  SUMMED — the additive convention of finite-element/graph assembly,
  where duplicate ``(i, j)`` contributions are partial sums by design.

So a tolerant read of a duplicate-bearing file gives ``last-wins`` when
densified and ``summed`` when assembled sparse. That divergence is
inherent to the two traditions, which is exactly why ``strict=True``
refuses to guess.

:func:`iter_coords` is the streaming face of the same parser: the header
is read eagerly (``.n`` / ``.declared_nnz``), the body is yielded as
0-indexed ``(rows, cols, vals)`` numpy chunks, and every per-line strict
check of :func:`read_dat` runs as the stream advances — O(chunk) resident
text for an O(nnz) file, never an n x n buffer.
"""

from __future__ import annotations

import io as _io
import os
from typing import Optional, TextIO, Tuple, Union

import numpy as np

PathOrFile = Union[str, os.PathLike, TextIO]


class DatFormatError(ValueError):
    """A malformed .dat file, with the 1-indexed line of the offense when
    known (``.line``; the header is line 1). Subclasses ValueError so
    pre-existing ``except ValueError`` call sites keep working."""

    def __init__(self, message: str, line: Optional[int] = None):
        super().__init__(f"line {line}: {message}" if line is not None
                         else message)
        self.line = line


def _open_maybe(path_or_file: PathOrFile, mode: str):
    if hasattr(path_or_file, "read") or hasattr(path_or_file, "write"):
        return path_or_file, False
    return open(path_or_file, mode), True


def read_dat(path_or_file: PathOrFile, strict: bool = True,
             ) -> Tuple[int, np.ndarray, np.ndarray, np.ndarray]:
    """Parse a .dat file -> (n, rows, cols, vals) with 0-indexed coordinates.

    ``strict`` additionally rejects non-finite values, duplicate (row, col)
    coordinates, and a missing ``0 0 0`` terminator — each as a
    :class:`DatFormatError` with the offending line number — instead of
    silently building a bad matrix (reference fscanf behavior, available
    via ``strict=False``)."""
    f, close = _open_maybe(path_or_file, "r")
    try:
        header = f.readline().split()
        if len(header) < 3:
            raise DatFormatError("malformed .dat header; expected 'n n nnz'",
                                 line=1)
        try:
            n = int(header[0])
            n2 = int(header[1])
            nnz = int(header[2])
        except ValueError as e:
            raise DatFormatError(
                f"malformed .dat header: {' '.join(header[:3])!r}",
                line=1) from e
        if n != n2:
            raise DatFormatError(
                f"non-square matrix in .dat header: {n} x {n2}", line=1)
        if n < 0 or nnz < 0:
            raise DatFormatError(
                f"negative dimension in .dat header: n={n} nnz={nnz}", line=1)
        rows = np.empty(nnz, dtype=np.int64)
        cols = np.empty(nnz, dtype=np.int64)
        vals = np.empty(nnz, dtype=np.float64)
        lines = np.empty(nnz, dtype=np.int64)  # per-entry source line
        count = 0
        terminated = False
        lineno = 1
        for line in f:
            lineno += 1
            parts = line.split()
            if not parts:
                continue
            if len(parts) < 2 or (len(parts) < 3 and not (parts[0] == "0" and parts[1] == "0")):
                raise DatFormatError(
                    f"malformed .dat body line: {line.rstrip()!r}",
                    line=lineno)
            try:
                r, c = int(parts[0]), int(parts[1])
            except ValueError as e:
                raise DatFormatError(
                    f"malformed .dat body line: {line.rstrip()!r}",
                    line=lineno) from e
            if r == 0 and c == 0:  # `0 0 0` terminator
                terminated = True
                break
            if count >= nnz:
                raise DatFormatError(
                    ".dat body has more entries than header nnz",
                    line=lineno)
            if not (1 <= r <= n and 1 <= c <= n):
                raise DatFormatError(
                    f".dat entry ({r}, {c}) out of bounds for 1-indexed "
                    f"{n} x {n} matrix", line=lineno)
            try:
                v = float(parts[2])
            except ValueError as e:
                raise DatFormatError(
                    f"malformed .dat body line: {line.rstrip()!r}",
                    line=lineno) from e
            if strict and not np.isfinite(v):
                raise DatFormatError(
                    f"non-finite value {parts[2]!r} at entry ({r}, {c}); a "
                    f"NaN/Inf entry poisons every downstream solve",
                    line=lineno)
            rows[count] = r - 1
            cols[count] = c - 1
            vals[count] = v
            lines[count] = lineno
            count += 1
        if count != nnz:
            raise DatFormatError(
                f".dat body has {count} entries, header promised {nnz}",
                line=lineno)
        if strict and not terminated:
            raise DatFormatError(
                "missing '0 0 0' terminator (truncated file?); pass "
                "strict=False to accept EOF-terminated files", line=lineno)
        if strict and nnz:
            # Vectorized duplicate scan (a per-line set would cost O(nnz)
            # python-object memory on generator-format files).
            codes = rows * np.int64(n) + cols
            order = np.argsort(codes, kind="stable")
            dup = np.nonzero(np.diff(codes[order]) == 0)[0]
            if dup.size:
                i1, i2 = order[dup[0]], order[dup[0] + 1]
                raise DatFormatError(
                    f"duplicate .dat entry ({rows[i2] + 1}, {cols[i2] + 1}) "
                    f"(first at line {lines[i1]}); the reference's "
                    f"last-wins overwrite is available via strict=False",
                    line=int(lines[i2]))
        return n, rows, cols, vals
    finally:
        if close:
            f.close()


class CoordStream:
    """Streaming ``.dat`` reader: the header eagerly (``.n``,
    ``.declared_nnz``), the body lazily as 0-indexed ``(rows, cols,
    vals)`` numpy chunks of at most ``chunk`` entries. Iterate it once;
    :meth:`gauss_tpu.sparse.csr.CsrMatrix.from_coord_chunks` accepts it
    directly. All of :func:`read_dat`'s per-line validation (bounds,
    malformed lines, header/body count mismatch) runs as the stream
    advances; ``strict`` additionally rejects non-finite values,
    duplicate coordinates (detected by the same vectorized scan, at end
    of stream), and a missing ``0 0 0`` terminator."""

    def __init__(self, path_or_file: PathOrFile, strict: bool = True,
                 chunk: int = 65536):
        if chunk <= 0:
            raise ValueError(f"chunk must be positive, got {chunk}")
        self._f, self._close = _open_maybe(path_or_file, "r")
        self.strict = bool(strict)
        self.chunk = int(chunk)
        self._consumed = False
        header = self._f.readline().split()
        try:
            if len(header) < 3:
                raise DatFormatError(
                    "malformed .dat header; expected 'n n nnz'", line=1)
            try:
                n, n2, nnz = (int(header[0]), int(header[1]),
                              int(header[2]))
            except ValueError as e:
                raise DatFormatError(
                    f"malformed .dat header: {' '.join(header[:3])!r}",
                    line=1) from e
            if n != n2:
                raise DatFormatError(
                    f"non-square matrix in .dat header: {n} x {n2}", line=1)
            if n < 0 or nnz < 0:
                raise DatFormatError(
                    f"negative dimension in .dat header: n={n} nnz={nnz}",
                    line=1)
        except Exception:
            self._finish()
            raise
        #: matrix order from the header (available before any body I/O)
        self.n = n
        #: entry count the header promises (validated against the body)
        self.declared_nnz = nnz

    def _finish(self):
        if self._close and self._f is not None:
            self._f.close()
        self._f = None

    def __iter__(self):
        if self._consumed:
            raise RuntimeError(
                "CoordStream is single-pass; construct a new one to re-read")
        self._consumed = True
        return self._iterate()

    def _iterate(self):
        n, nnz, strict = self.n, self.declared_nnz, self.strict
        rs, cs, vs, ls = [], [], [], []
        codes_seen, lines_seen = [], []  # strict duplicate scan, per chunk
        count = 0
        terminated = False
        lineno = 1
        try:
            for line in self._f:
                lineno += 1
                parts = line.split()
                if not parts:
                    continue
                if len(parts) < 2 or (len(parts) < 3 and not (
                        parts[0] == "0" and parts[1] == "0")):
                    raise DatFormatError(
                        f"malformed .dat body line: {line.rstrip()!r}",
                        line=lineno)
                try:
                    r, c = int(parts[0]), int(parts[1])
                except ValueError as e:
                    raise DatFormatError(
                        f"malformed .dat body line: {line.rstrip()!r}",
                        line=lineno) from e
                if r == 0 and c == 0:
                    terminated = True
                    break
                if count >= nnz:
                    raise DatFormatError(
                        ".dat body has more entries than header nnz",
                        line=lineno)
                if not (1 <= r <= n and 1 <= c <= n):
                    raise DatFormatError(
                        f".dat entry ({r}, {c}) out of bounds for 1-indexed "
                        f"{n} x {n} matrix", line=lineno)
                try:
                    v = float(parts[2])
                except ValueError as e:
                    raise DatFormatError(
                        f"malformed .dat body line: {line.rstrip()!r}",
                        line=lineno) from e
                if strict and not np.isfinite(v):
                    raise DatFormatError(
                        f"non-finite value {parts[2]!r} at entry ({r}, {c});"
                        f" a NaN/Inf entry poisons every downstream solve",
                        line=lineno)
                rs.append(r - 1)
                cs.append(c - 1)
                vs.append(v)
                ls.append(lineno)
                count += 1
                if len(rs) >= self.chunk:
                    rows = np.asarray(rs, dtype=np.int64)
                    cols = np.asarray(cs, dtype=np.int64)
                    if strict:
                        codes_seen.append(rows * np.int64(n) + cols)
                        lines_seen.append(np.asarray(ls, dtype=np.int64))
                    yield rows, cols, np.asarray(vs, dtype=np.float64)
                    rs, cs, vs, ls = [], [], [], []
            if count != nnz:
                raise DatFormatError(
                    f".dat body has {count} entries, header promised {nnz}",
                    line=lineno)
            if strict and not terminated:
                raise DatFormatError(
                    "missing '0 0 0' terminator (truncated file?); pass "
                    "strict=False to accept EOF-terminated files",
                    line=lineno)
            if rs:
                rows = np.asarray(rs, dtype=np.int64)
                cols = np.asarray(cs, dtype=np.int64)
                if strict:
                    codes_seen.append(rows * np.int64(n) + cols)
                    lines_seen.append(np.asarray(ls, dtype=np.int64))
                yield rows, cols, np.asarray(vs, dtype=np.float64)
            if strict and codes_seen:
                # Same vectorized duplicate scan as read_dat, over the
                # accumulated codes (O(nnz) ints — the coordinates a
                # consumer holds anyway; never the file text or an n^2
                # buffer).
                codes = np.concatenate(codes_seen)
                srclines = np.concatenate(lines_seen)
                order = np.argsort(codes, kind="stable")
                dup = np.nonzero(np.diff(codes[order]) == 0)[0]
                if dup.size:
                    i1, i2 = order[dup[0]], order[dup[0] + 1]
                    code = int(codes[i2])
                    raise DatFormatError(
                        f"duplicate .dat entry ({code // n + 1}, "
                        f"{code % n + 1}) (first at line {srclines[i1]}); "
                        f"the reference's last-wins overwrite is available "
                        f"via strict=False", line=int(srclines[i2]))
        finally:
            self._finish()


def iter_coords(path_or_file: PathOrFile, strict: bool = True,
                chunk: int = 65536) -> CoordStream:
    """Open a ``.dat`` file for streaming: returns a :class:`CoordStream`
    whose ``.n`` / ``.declared_nnz`` come from the header immediately and
    whose iteration yields 0-indexed ``(rows, cols, vals)`` chunks with
    :func:`read_dat`'s validation applied line by line."""
    return CoordStream(path_or_file, strict=strict, chunk=chunk)


def densify(n: int, rows: np.ndarray, cols: np.ndarray, vals: np.ndarray,
            dtype=np.float64) -> np.ndarray:
    """Scatter coordinate entries into a dense row-major n x n array."""
    dense = np.zeros((n, n), dtype=dtype)
    dense[rows, cols] = vals
    return dense


def read_dat_dense(path_or_file: PathOrFile, dtype=np.float64,
                   engine: str = "auto", strict: bool = True) -> np.ndarray:
    """Parse + densify in one step (the external-input programs' initMatrix).

    engine: "python", "native" (C++ parser via ctypes), or "auto" — native
    when available, the input is a real file path, AND ``strict`` is off;
    python otherwise. The native parser has no per-line validation (its
    output gets only a whole-matrix finite check — no line numbers, no
    duplicate/terminator detection), so the strict default routes "auto"
    through the fully-checked python parser: safety by default, the
    unchecked fast path by explicit request (``engine="native"`` or
    ``strict=False``).
    """
    is_path = not (hasattr(path_or_file, "read"))
    if engine not in ("auto", "python", "native"):
        raise ValueError(f"unknown engine {engine!r}")
    if engine == "native" and not is_path:
        raise ValueError("engine='native' requires a file path, not a file object")
    if (engine == "native" or (engine == "auto" and not strict)) and is_path:
        try:
            from gauss_tpu import native

            if native.available() or engine == "native":
                dense = native.read_dat_dense(
                    os.fspath(path_or_file)).astype(dtype, copy=False)
                if strict and not np.isfinite(dense).all():
                    raise DatFormatError(
                        f"non-finite value(s) in {os.fspath(path_or_file)!r} "
                        f"(native parser; re-read with engine='python' for "
                        f"the offending line)")
                return dense
        except Exception:
            if engine == "native":
                raise
    n, rows, cols, vals = read_dat(path_or_file, strict=strict)
    return densify(n, rows, cols, vals, dtype=dtype)


def write_dat(path_or_file: PathOrFile, matrix: np.ndarray = None, *,
              n: int = None, rows=None, cols=None, vals=None,
              column_major: bool = True, terminator: bool = True,
              drop_zeros: bool = False) -> None:
    """Write a matrix in .dat coordinate format (1-indexed, `0 0 0` terminator).

    With a dense ``matrix``, every entry is emitted (optionally skipping exact
    zeros) in column-major order by default — matching matrix_gen.cc's emission
    order (matrix_gen.cc:15-19). Alternatively pass explicit coordinate arrays.
    """
    if matrix is not None:
        matrix = np.asarray(matrix)
        n = matrix.shape[0]
        if matrix.shape != (n, n):
            raise ValueError("write_dat expects a square matrix")
        if column_major:
            cc, rr = np.meshgrid(np.arange(n), np.arange(n), indexing="ij")
            rows, cols = rr.ravel(), cc.ravel()
        else:
            rr, cc = np.meshgrid(np.arange(n), np.arange(n), indexing="ij")
            rows, cols = rr.ravel(), cc.ravel()
        vals = matrix[rows, cols]
        if drop_zeros:
            keep = vals != 0
            rows, cols, vals = rows[keep], cols[keep], vals[keep]
    else:
        rows = np.asarray(rows)
        cols = np.asarray(cols)
        vals = np.asarray(vals)
        if n is None:
            raise ValueError("n is required when writing coordinate arrays")

    f, close = _open_maybe(path_or_file, "w")
    try:
        buf = _io.StringIO()
        buf.write(f"{n} {n} {len(vals)}\n")
        for r, c, v in zip(rows, cols, vals):
            # 17 significant digits: exact float64 round trip.
            buf.write(f"{int(r) + 1} {int(c) + 1} {v:.17g}\n")
        if terminator:
            buf.write("0 0 0\n")
        f.write(buf.getvalue())
    finally:
        if close:
            f.close()
