"""Reader/writer for the reference's ``.dat`` sparse-coordinate matrix format.

Format (reference Pthreads/Version-1/matrices_dense/matrix_gen.cc:13-22 and the
parser in gauss_external_input.c:34-86):

    line 1: ``n n nnz``            (rows, cols, number of entries)
    body:   ``row col value``     one entry per line, **1-indexed**
    end:    ``0 0 0``             terminator row (optional in some files)

Entries may appear in any order; duplicate coordinates take the last value
(matching the reference's densifying loop, which overwrites). Matrices are
densified to row-major n x n on load exactly as ``initMatrix`` does in the
external-input programs.

A faster C++ parser for large files is provided by :mod:`gauss_tpu.native`
(``read_dat_dense(..., engine="native")`` uses it when built).
"""

from __future__ import annotations

import io as _io
import os
from typing import TextIO, Tuple, Union

import numpy as np

PathOrFile = Union[str, os.PathLike, TextIO]


def _open_maybe(path_or_file: PathOrFile, mode: str):
    if hasattr(path_or_file, "read") or hasattr(path_or_file, "write"):
        return path_or_file, False
    return open(path_or_file, mode), True


def read_dat(path_or_file: PathOrFile) -> Tuple[int, np.ndarray, np.ndarray, np.ndarray]:
    """Parse a .dat file -> (n, rows, cols, vals) with 0-indexed coordinates."""
    f, close = _open_maybe(path_or_file, "r")
    try:
        header = f.readline().split()
        if len(header) < 3:
            raise ValueError("malformed .dat header; expected 'n n nnz'")
        n = int(header[0])
        n2 = int(header[1])
        nnz = int(header[2])
        if n != n2:
            raise ValueError(f"non-square matrix in .dat header: {n} x {n2}")
        rows = np.empty(nnz, dtype=np.int64)
        cols = np.empty(nnz, dtype=np.int64)
        vals = np.empty(nnz, dtype=np.float64)
        count = 0
        for line in f:
            parts = line.split()
            if not parts:
                continue
            if len(parts) < 2 or (len(parts) < 3 and not (parts[0] == "0" and parts[1] == "0")):
                raise ValueError(f"malformed .dat body line: {line.rstrip()!r}")
            try:
                r, c = int(parts[0]), int(parts[1])
            except ValueError as e:
                raise ValueError(f"malformed .dat body line: {line.rstrip()!r}") from e
            if r == 0 and c == 0:  # `0 0 0` terminator
                break
            if count >= nnz:
                raise ValueError(".dat body has more entries than header nnz")
            if not (1 <= r <= n and 1 <= c <= n):
                raise ValueError(
                    f".dat entry ({r}, {c}) out of bounds for 1-indexed {n} x {n} matrix")
            rows[count] = r - 1
            cols[count] = c - 1
            vals[count] = float(parts[2])
            count += 1
        if count != nnz:
            raise ValueError(f".dat body has {count} entries, header promised {nnz}")
        return n, rows, cols, vals
    finally:
        if close:
            f.close()


def densify(n: int, rows: np.ndarray, cols: np.ndarray, vals: np.ndarray,
            dtype=np.float64) -> np.ndarray:
    """Scatter coordinate entries into a dense row-major n x n array."""
    dense = np.zeros((n, n), dtype=dtype)
    dense[rows, cols] = vals
    return dense


def read_dat_dense(path_or_file: PathOrFile, dtype=np.float64,
                   engine: str = "auto") -> np.ndarray:
    """Parse + densify in one step (the external-input programs' initMatrix).

    engine: "python", "native" (C++ parser via ctypes), or "auto" (native when
    available and the input is a real file path, else python).
    """
    is_path = not (hasattr(path_or_file, "read"))
    if engine not in ("auto", "python", "native"):
        raise ValueError(f"unknown engine {engine!r}")
    if engine == "native" and not is_path:
        raise ValueError("engine='native' requires a file path, not a file object")
    if engine in ("auto", "native") and is_path:
        try:
            from gauss_tpu import native

            if native.available() or engine == "native":
                return native.read_dat_dense(os.fspath(path_or_file)).astype(dtype, copy=False)
        except Exception:
            if engine == "native":
                raise
    n, rows, cols, vals = read_dat(path_or_file)
    return densify(n, rows, cols, vals, dtype=dtype)


def write_dat(path_or_file: PathOrFile, matrix: np.ndarray = None, *,
              n: int = None, rows=None, cols=None, vals=None,
              column_major: bool = True, terminator: bool = True,
              drop_zeros: bool = False) -> None:
    """Write a matrix in .dat coordinate format (1-indexed, `0 0 0` terminator).

    With a dense ``matrix``, every entry is emitted (optionally skipping exact
    zeros) in column-major order by default — matching matrix_gen.cc's emission
    order (matrix_gen.cc:15-19). Alternatively pass explicit coordinate arrays.
    """
    if matrix is not None:
        matrix = np.asarray(matrix)
        n = matrix.shape[0]
        if matrix.shape != (n, n):
            raise ValueError("write_dat expects a square matrix")
        if column_major:
            cc, rr = np.meshgrid(np.arange(n), np.arange(n), indexing="ij")
            rows, cols = rr.ravel(), cc.ravel()
        else:
            rr, cc = np.meshgrid(np.arange(n), np.arange(n), indexing="ij")
            rows, cols = rr.ravel(), cc.ravel()
        vals = matrix[rows, cols]
        if drop_zeros:
            keep = vals != 0
            rows, cols, vals = rows[keep], cols[keep], vals[keep]
    else:
        rows = np.asarray(rows)
        cols = np.asarray(cols)
        vals = np.asarray(vals)
        if n is None:
            raise ValueError("n is required when writing coordinate arrays")

    f, close = _open_maybe(path_or_file, "w")
    try:
        buf = _io.StringIO()
        buf.write(f"{n} {n} {len(vals)}\n")
        for r, c, v in zip(rows, cols, vals):
            # 17 significant digits: exact float64 round trip.
            buf.write(f"{int(r) + 1} {int(c) + 1} {v:.17g}\n")
        if terminator:
            buf.write("0 0 0\n")
        f.write(buf.getvalue())
    finally:
        if close:
            f.close()
