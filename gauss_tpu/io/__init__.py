"""Matrix I/O: .dat coordinate-format files and synthetic initializers."""

from gauss_tpu.io.datfile import (  # noqa: F401
    DatFormatError,
    read_dat,
    read_dat_dense,
    write_dat,
)
from gauss_tpu.io.synthetic import (  # noqa: F401
    internal_matrix,
    internal_rhs,
    generator_matrix,
    manufactured_solution,
    manufactured_rhs,
)
