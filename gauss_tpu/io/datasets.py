"""Dataset registry: the reference's test-matrix library, regenerated.

The reference ships seven Harwell-Boeing-style sparse matrices in ``.dat``
coordinate form, replicated into five directories (SURVEY.md §2 C8):
matrix_10, jpwh_991, orsreg_1, sherman5, saylr4, sherman3, memplus, plus a
``matrix_2000`` that its README references but the mirror stripped (to be
regenerated with matrix_gen). Those files are third-party data we do not
copy; instead this module regenerates, deterministically, stand-in matrices
with the **same names, dimensions, and nonzero counts** (taken from each
reference file's header line), so every workflow that consumes the reference
dataset — external-input solves, cross-engine comparisons, the benchmark
grid — runs against the same shapes and sparsity budgets.

Stand-ins are strictly diagonally dominant (diag = 1 + sum |row off-diag|),
hence nonsingular and well-conditioned, with entries from a name-seeded
PCG64 stream — bitwise reproducible across runs and machines.

When a reference checkout is present (see :mod:`gauss_tpu.io.reference_data`),
:func:`dataset_dense` can read the REAL matrices in place instead
(``source="reference"`` or ``"auto"``) — the real Harwell-Boeing conditioning,
not the deliberately easy stand-ins, is what the external benchmark grid and
golden tests exercise on this machine.
"""

from __future__ import annotations

import zlib
from typing import Dict, Tuple

import numpy as np

from gauss_tpu.io import datfile, synthetic

# name -> (n, nnz) from the reference .dat headers (SURVEY.md §2 C8).
REGISTRY: Dict[str, Tuple[int, int]] = {
    "matrix_10": (10, 100),
    "jpwh_991": (991, 6027),
    "orsreg_1": (2205, 14133),
    "sherman5": (3312, 20793),
    "saylr4": (3564, 22316),
    "sherman3": (5005, 20033),
    "memplus": (17758, 126150),
    # README-referenced, stripped from the mirror; dense generator family.
    "matrix_2000": (2000, 4_000_000),
}


def dataset_names():
    return tuple(REGISTRY)


def dataset_coords(name: str):
    """(n, rows, cols, vals) for a registry matrix, deterministic by name."""
    if name not in REGISTRY:
        raise KeyError(f"unknown dataset {name!r}; options: {sorted(REGISTRY)}")
    n, nnz = REGISTRY[name]

    if name in ("matrix_10", "matrix_2000"):
        # Dense generator-family matrices: exactly the matrix_gen emission.
        dense = synthetic.generator_matrix(n)
        cc, rr = np.meshgrid(np.arange(n), np.arange(n), indexing="ij")
        return n, rr.ravel(), cc.ravel(), dense[rr.ravel(), cc.ravel()]

    rng = np.random.default_rng(zlib.crc32(name.encode()))
    n_off = nnz - n
    # Sample off-diagonal coordinates without replacement (rejection loop;
    # nnz << n^2 so a couple of rounds suffice).
    seen = set()
    rows = np.empty(n_off, dtype=np.int64)
    cols = np.empty(n_off, dtype=np.int64)
    filled = 0
    while filled < n_off:
        need = n_off - filled
        r = rng.integers(0, n, size=2 * need + 16)
        c = rng.integers(0, n, size=2 * need + 16)
        for ri, ci in zip(r, c):
            if ri == ci or (ri, ci) in seen:
                continue
            seen.add((ri, ci))
            rows[filled] = ri
            cols[filled] = ci
            filled += 1
            if filled == n_off:
                break
    vals = rng.uniform(-1.0, 1.0, size=n_off)

    # Strict diagonal dominance -> nonsingular, well-conditioned.
    diag = np.ones(n)
    np.add.at(diag, rows, np.abs(vals))
    all_rows = np.concatenate([rows, np.arange(n)])
    all_cols = np.concatenate([cols, np.arange(n)])
    all_vals = np.concatenate([vals, diag])
    order = np.lexsort((all_cols, all_rows))
    return n, all_rows[order], all_cols[order], all_vals[order]


def resolve_source(name: str, source: str = "standin") -> str:
    """Resolve a requested dataset source to the one that will be used.

    "standin"   — the deterministic regenerated matrix (always available).
    "reference" — the real reference .dat file, read in place (raises if the
                  reference checkout or the file is absent).
    "auto"      — "reference" when the real file exists, else "standin".
    """
    if source not in ("standin", "reference", "auto"):
        raise ValueError(f"unknown source {source!r}; options: "
                         "('standin', 'reference', 'auto')")
    if name not in REGISTRY:
        raise KeyError(f"unknown dataset {name!r}; options: {sorted(REGISTRY)}")
    if source == "standin":
        return "standin"
    from gauss_tpu.io import reference_data

    if reference_data.find_dat(name) is not None:
        return "reference"
    if source == "reference":
        detail = (f"checkout at {reference_data.reference_root()} does not "
                  f"ship {name}.dat" if reference_data.available() else
                  f"no reference checkout under "
                  f"{reference_data.reference_root()} "
                  f"(set ${reference_data.ROOT_ENV})")
        raise KeyError(f"real reference matrix {name!r} not available: {detail}")
    return "standin"


def dataset_dense(name: str, dtype=np.float64,
                  source: str = "standin") -> np.ndarray:
    """Densified registry matrix (memplus at f64 is ~2.5 GB — mind the RAM,
    exactly as with the reference's external-input programs).

    ``source``: see :func:`resolve_source`; "standin" (the default) keeps
    results bitwise reproducible on machines without a reference checkout.
    """
    if resolve_source(name, source) == "reference":
        from gauss_tpu.io import reference_data

        return reference_data.load_dense(name, dtype=dtype)
    n, rows, cols, vals = dataset_coords(name)
    return datfile.densify(n, rows, cols, vals, dtype=dtype)


def write_dataset(name: str, path) -> None:
    """Emit a registry matrix as a reference-format .dat file."""
    n, rows, cols, vals = dataset_coords(name)
    datfile.write_dat(path, n=n, rows=rows, cols=cols, vals=vals)
