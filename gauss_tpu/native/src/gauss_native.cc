// Native host-side runtime for gauss-tpu: CPU baseline engines + fast .dat I/O.
//
// The reference implements its CPU engines as 10 standalone C programs
// (reference Pthreads/Version-{1,2,3}/*.c, OpenMP_and_MPI/gauss_{openmp,mpi}/*.c);
// this library provides the same engine taxonomy behind one C ABI so the
// Python CLI can dispatch `--backend={seq,omp,threads}` to true native code:
//
//   seq     — sequential partial-pivot elimination (the reference's baseline,
//             upgraded from swap-on-zero to partial pivoting per SURVEY.md §7c)
//   omp     — OpenMP `parallel for` over elimination rows (reference C4)
//   threads — persistent std::thread workers, cyclic row striping, barrier
//             synchronization: the modern-C++ re-expression of reference C3's
//             persistent pthreads + hand-rolled condvar barrier (and of C1's
//             cyclic striping); threads are spawned once, not n*T times
//
// All engines operate in-place on caller-owned row-major float64 buffers and
// share one elimination step helper, de-duplicating what the reference copies
// into every translation unit. Return codes: 0 ok, -1 singular, -2 bad args.

#include <atomic>
#if defined(__has_include)
#if __has_include(<barrier>)
#include <barrier>
#define GT_HAVE_STD_BARRIER 1
#endif
#endif
#include <cmath>
#include <condition_variable>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <thread>
#ifdef __linux__
#include <pthread.h>
#include <sched.h>
#endif
#include <vector>

#ifdef _OPENMP
#include <omp.h>
#endif

namespace {

#ifdef GT_HAVE_STD_BARRIER
using Barrier = std::barrier<>;
#else
// libstdc++ < 11 ships C++20 without <barrier>; this condvar barrier has the
// same arrive_and_wait contract (and is exactly the hand-rolled barrier the
// reference C3 uses, Pthreads/Version-3/gauss_internal_input.c).
class Barrier {
 public:
  explicit Barrier(long count) : threshold_(count), count_(count) {}
  void arrive_and_wait() {
    std::unique_lock<std::mutex> lk(m_);
    const unsigned long gen = generation_;
    if (--count_ == 0) {
      ++generation_;
      count_ = threshold_;
      cv_.notify_all();
    } else {
      cv_.wait(lk, [&] { return generation_ != gen; });
    }
  }

 private:
  std::mutex m_;
  std::condition_variable cv_;
  const long threshold_;
  long count_;
  unsigned long generation_ = 0;
};
#endif

// Select the partial pivot for column i, swap rows of A and b, scale the
// pivot row to unit diagonal. Returns false if the column is exactly singular.
bool pivot_and_scale(double* A, double* b, long n, long i) {
  long best = i;
  double best_abs = std::fabs(A[i * n + i]);
  for (long r = i + 1; r < n; ++r) {
    double v = std::fabs(A[r * n + i]);
    if (v > best_abs) {
      best_abs = v;
      best = r;
    }
  }
  if (best_abs == 0.0) return false;
  if (best != i) {
    for (long k = 0; k < n; ++k) std::swap(A[i * n + k], A[best * n + k]);
    std::swap(b[i], b[best]);
  }
  const double piv = A[i * n + i];
  double* row = A + i * n;
  for (long k = i; k < n; ++k) row[k] /= piv;
  row[i] = 1.0;  // exact, mirroring the JAX core's pinned diagonal
  b[i] /= piv;
  return true;
}

// Eliminate one target row j against the scaled pivot row i.
inline void eliminate_row(double* A, double* b, long n, long i, long j) {
  double* tgt = A + j * n;
  const double* piv = A + i * n;
  const double f = tgt[i];
  if (f == 0.0) return;
  for (long k = i; k < n; ++k) tgt[k] -= f * piv[k];
  tgt[i] = 0.0;
  b[j] -= f * b[i];
}

void back_substitute(const double* A, const double* b, double* x, long n) {
  for (long i = n - 1; i >= 0; --i) {
    double acc = b[i];
    const double* row = A + i * n;
    for (long j = i + 1; j < n; ++j) acc -= row[j] * x[j];
    x[i] = acc / row[i];
  }
}

}  // namespace

extern "C" {

int gt_gauss_solve_seq(double* A, double* b, double* x, long n) {
  if (!A || !b || !x || n <= 0) return -2;
  for (long i = 0; i < n; ++i) {
    if (!pivot_and_scale(A, b, n, i)) return -1;
    for (long j = i + 1; j < n; ++j) eliminate_row(A, b, n, i, j);
  }
  back_substitute(A, b, x, n);
  return 0;
}

int gt_gauss_solve_omp(double* A, double* b, double* x, long n, int nthreads) {
  if (!A || !b || !x || n <= 0) return -2;
#ifdef _OPENMP
  if (nthreads > 0) omp_set_num_threads(nthreads);
  for (long i = 0; i < n; ++i) {
    if (!pivot_and_scale(A, b, n, i)) return -1;
#pragma omp parallel for schedule(static)
    for (long j = i + 1; j < n; ++j) eliminate_row(A, b, n, i, j);
  }
  back_substitute(A, b, x, n);
  return 0;
#else
  (void)nthreads;
  return gt_gauss_solve_seq(A, b, x, n);
#endif
}

// Fork-join engine (reference C1, Pthreads Version-1): threads are created
// and joined anew for EVERY pivot step — n*T thread spawns total. Kept for
// engine-taxonomy parity and as a benchmarkable demonstration of why the
// persistent-pool engine (gt_gauss_solve_threads) exists; the reference's own
// Version-3 draws the same conclusion.
int gt_gauss_solve_forkjoin(double* A, double* b, double* x, long n, int nthreads) {
  if (!A || !b || !x || n <= 0) return -2;
  if (nthreads < 1) nthreads = 1;
  for (long i = 0; i < n; ++i) {
    if (!pivot_and_scale(A, b, n, i)) return -1;
    std::vector<std::thread> pool;
    pool.reserve(nthreads);
    for (int t = 0; t < nthreads; ++t) {
      pool.emplace_back([&, t]() {
        for (long j = i + 1 + t; j < n; j += nthreads) eliminate_row(A, b, n, i, j);
      });
    }
    for (auto& th : pool) th.join();
  }
  back_substitute(A, b, x, n);
  return 0;
}

// Cache-tiled engine (reference C2, Pthreads Version-2): the elimination
// column range is processed in block_size chunks, all target rows visiting a
// chunk before advancing, keeping the pivot-row slice cache-resident
// (reference Version-2/gauss_internal_input.c:18,162-173 uses block_size=16;
// 64 doubles = one 512-byte prefetch-friendly run works better on modern
// cores). Persistent pool + barrier like the threads engine.
int gt_gauss_solve_tiled(double* A, double* b, double* x, long n, int nthreads) {
  if (!A || !b || !x || n <= 0) return -2;
  if (nthreads < 1) nthreads = 1;
  constexpr long kBlock = 64;

  std::atomic<bool> singular{false};
  Barrier sync(nthreads);

  auto worker = [&](int tid) {
    for (long i = 0; i < n; ++i) {
      if (tid == 0) {
        if (!pivot_and_scale(A, b, n, i)) singular.store(true);
      }
      sync.arrive_and_wait();
      if (singular.load()) return;
      const double* piv = A + i * n;
      // RHS update + multiplier capture first (the tiled passes destroy
      // column i last, mirroring the reference's deferred zeroing).
      for (long j = i + 1 + tid; j < n; j += nthreads) b[j] -= A[j * n + i] * b[i];
      for (long k0 = i; k0 < n; k0 += kBlock) {
        const long k1 = std::min(n, k0 + kBlock);
        for (long j = i + 1 + tid; j < n; j += nthreads) {
          double* tgt = A + j * n;
          const double f = tgt[i];
          if (f == 0.0) continue;
          for (long k = std::max(k0, i + 1); k < k1; ++k) tgt[k] -= f * piv[k];
        }
      }
      for (long j = i + 1 + tid; j < n; j += nthreads) A[j * n + i] = 0.0;
      sync.arrive_and_wait();
    }
  };

  std::vector<std::thread> pool;
  pool.reserve(nthreads);
  for (int t = 0; t < nthreads; ++t) pool.emplace_back(worker, t);
  for (auto& th : pool) th.join();
  if (singular.load()) return -1;
  back_substitute(A, b, x, n);
  return 0;
}

// CPU-affinity pinning for the persistent-pool engine, mirroring the
// reference C3's pthread_attr_setaffinity_np path: pin thread t to core t
// only when the pool fits the machine (Version-3/gauss_internal_input.c:
// 238,278-279,297-301). Linux-only; a no-op elsewhere.
static void pin_to_core(std::thread& th, int core, int nthreads) {
#ifdef __linux__
  // Respect the PROCESS affinity mask (taskset/cgroup cpusets), not raw
  // hardware_concurrency: pin thread t to the t-th ALLOWED core, and only
  // when the whole pool fits the allowed set — a partial pinning under a
  // restricted mask would skew measurements asymmetrically.
  cpu_set_t allowed;
  CPU_ZERO(&allowed);
  if (sched_getaffinity(0, sizeof(allowed), &allowed) != 0) return;
  std::vector<int> cores;
  for (int c = 0; c < CPU_SETSIZE; ++c)
    if (CPU_ISSET(c, &allowed)) cores.push_back(c);
  if (cores.empty() || nthreads > (int)cores.size()) return;
  cpu_set_t set;
  CPU_ZERO(&set);
  CPU_SET(cores[core % cores.size()], &set);
  pthread_setaffinity_np(th.native_handle(), sizeof(set), &set);
#else
  (void)th; (void)core; (void)nthreads;
#endif
}

int gt_gauss_solve_threads(double* A, double* b, double* x, long n, int nthreads) {
  if (!A || !b || !x || n <= 0) return -2;
  if (nthreads < 1) nthreads = 1;
  if (nthreads == 1) return gt_gauss_solve_seq(A, b, x, n);

  std::atomic<bool> singular{false};
  Barrier sync(nthreads);

  auto worker = [&](int tid) {
    for (long i = 0; i < n; ++i) {
      if (tid == 0) {
        if (!pivot_and_scale(A, b, n, i)) singular.store(true);
      }
      sync.arrive_and_wait();  // pivot row ready (or failure flagged)
      if (singular.load()) return;
      // Cyclic row striping, the reference C1/C3 load-balance scheme.
      for (long j = i + 1 + tid; j < n; j += nthreads) eliminate_row(A, b, n, i, j);
      sync.arrive_and_wait();  // all rows eliminated before the next pivot
    }
  };

  std::vector<std::thread> pool;
  pool.reserve(nthreads);
  for (int t = 0; t < nthreads; ++t) {
    pool.emplace_back(worker, t);
    pin_to_core(pool.back(), t, nthreads);
  }
  for (auto& th : pool) th.join();
  if (singular.load()) return -1;
  back_substitute(A, b, x, n);
  return 0;
}

void gt_matmul_seq(const double* A, const double* B, double* C, long n) {
  // i-k-j loop order: streams B rows, keeps C row hot — cache-friendly
  // without tiling (the reference's seq_matmul uses naive i-j-k).
  std::memset(C, 0, sizeof(double) * n * n);
  for (long i = 0; i < n; ++i) {
    double* crow = C + i * n;
    for (long k = 0; k < n; ++k) {
      const double a = A[i * n + k];
      const double* brow = B + k * n;
      for (long j = 0; j < n; ++j) crow[j] += a * brow[j];
    }
  }
}

void gt_matmul_omp(const double* A, const double* B, double* C, long n, int nthreads) {
#ifdef _OPENMP
  if (nthreads > 0) omp_set_num_threads(nthreads);
#pragma omp parallel for schedule(static)
  for (long i = 0; i < n; ++i) {
    double* crow = C + i * n;
    std::memset(crow, 0, sizeof(double) * n);
    for (long k = 0; k < n; ++k) {
      const double a = A[i * n + k];
      const double* brow = B + k * n;
      for (long j = 0; j < n; ++j) crow[j] += a * brow[j];
    }
  }
#else
  (void)nthreads;
  gt_matmul_seq(A, B, C, n);
#endif
}

// ---- .dat coordinate-format I/O ------------------------------------------
// Format (reference matrix_gen.cc:13-22): header "n n nnz", 1-indexed body
// lines "row col value", optional "0 0 0" terminator. Whole-file buffered
// parse with strtol/strtod — ~50x faster than line-by-line Python for the
// larger dataset matrices (memplus: 126k entries).

namespace {

struct FileBuf {
  char* data = nullptr;
  size_t size = 0;
  ~FileBuf() { std::free(data); }
  bool read(const char* path) {
    FILE* f = std::fopen(path, "rb");
    if (!f) return false;
    std::fseek(f, 0, SEEK_END);
    long sz = std::ftell(f);
    std::fseek(f, 0, SEEK_SET);
    if (sz < 0) {
      std::fclose(f);
      return false;
    }
    data = static_cast<char*>(std::malloc(sz + 1));
    if (!data) {
      std::fclose(f);
      return false;
    }
    size = std::fread(data, 1, sz, f);
    data[size] = '\0';
    std::fclose(f);
    return true;
  }
};

}  // namespace

int gt_dat_read_header(const char* path, long* n, long* nnz) {
  FILE* f = std::fopen(path, "r");
  if (!f) return -2;
  long a = 0, b = 0, c = 0;
  int got = std::fscanf(f, "%ld %ld %ld", &a, &b, &c);
  std::fclose(f);
  if (got != 3 || a != b || a <= 0 || c < 0) return -3;
  *n = a;
  *nnz = c;
  return 0;
}

// out must hold n*n doubles; it is zero-filled then scattered into.
int gt_dat_read_dense(const char* path, double* out, long n) {
  FileBuf buf;
  if (!buf.read(path)) return -2;
  char* p = buf.data;
  char* end;
  long hn = std::strtol(p, &end, 10);
  p = end;
  long hn2 = std::strtol(p, &end, 10);
  p = end;
  long nnz = std::strtol(p, &end, 10);
  p = end;
  if (hn != n || hn2 != n || nnz < 0) return -3;
  std::memset(out, 0, sizeof(double) * n * n);
  long count = 0;
  while (count < nnz) {
    long r = std::strtol(p, &end, 10);
    if (end == p) break;  // EOF / garbage
    p = end;
    long c = std::strtol(p, &end, 10);
    p = end;
    double v = std::strtod(p, &end);
    p = end;
    if (r == 0 && c == 0) break;  // terminator
    if (r < 1 || r > n || c < 1 || c > n) return -4;
    out[(r - 1) * n + (c - 1)] = v;
    ++count;
  }
  if (count != nnz) return -5;
  return 0;
}

}  // extern "C"
