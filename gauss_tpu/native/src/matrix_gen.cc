// Standalone synthetic-matrix generator tool (reference component C7).
//
// Emits to stdout, in the .dat coordinate format, the same matrix family the
// reference's generator produces (reference
// Pthreads/Version-1/matrices_dense/matrix_gen.cc:13-22): header "n n n*n",
// column-major body of 1-indexed entries with value 2*min(row, col), and the
// "0 0 0" terminator line. Usage: ./matrix_gen <n> [> file.dat]

#include <cstdio>
#include <cstdlib>
#include <cstring>

int main(int argc, char** argv) {
  if (argc != 2) {
    std::fprintf(stderr, "usage: %s <n>\n", argv[0]);
    return 1;
  }
  char* end = nullptr;
  long n = std::strtol(argv[1], &end, 10);
  if (end == argv[1] || *end != '\0' || n <= 0) {
    std::fprintf(stderr, "%s: n must be a positive integer, got '%s'\n", argv[0], argv[1]);
    return 1;
  }
  std::printf("%ld %ld %ld\n", n, n, n * n);
  for (long col = 1; col <= n; ++col)
    for (long row = 1; row <= n; ++row)
      std::printf("%ld %ld %ld\n", row, col, 2 * (row < col ? row : col));
  std::printf("0 0 0\n");
  return 0;
}
