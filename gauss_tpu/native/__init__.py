"""ctypes bindings to the native C++ runtime (build-on-demand).

Provides the host-side native components the reference keeps in C/C++
(SURVEY.md §2 C7/C9 and the CPU baseline engines): a fast .dat parser, the
``matrix_gen`` tool, and seq / OpenMP / std::thread Gaussian-elimination and
matmul engines. Falls back gracefully (``available() == False``) when no
toolchain is present; set ``GAUSS_TPU_NO_NATIVE=1`` to disable entirely.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path
from typing import Optional

import numpy as np

_SRC_DIR = Path(__file__).resolve().parent / "src"
_LIB_PATH = _SRC_DIR / "libgauss_native.so"
_GEN_PATH = _SRC_DIR / "matrix_gen"
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False

GAUSS_ENGINES = ("seq", "omp", "threads", "forkjoin", "tiled")
MATMUL_ENGINES = ("seq", "omp")


def _sources_newer_than(artifact: Path) -> bool:
    if not artifact.exists():
        return True
    amt = artifact.stat().st_mtime
    return any(src.stat().st_mtime > amt for src in _SRC_DIR.glob("*.cc"))


def ensure_built(force: bool = False) -> bool:
    """Build the .so + matrix_gen if missing or stale. Returns success."""
    global _build_failed
    if os.environ.get("GAUSS_TPU_NO_NATIVE"):
        return False
    with _lock:
        if not force and _build_failed:
            return False
        if force or _sources_newer_than(_LIB_PATH) or _sources_newer_than(_GEN_PATH):
            try:
                subprocess.run(
                    ["make", "-C", str(_SRC_DIR)],
                    check=True, capture_output=True, text=True, timeout=300)
            except (subprocess.CalledProcessError, subprocess.TimeoutExpired,
                    FileNotFoundError) as e:
                _build_failed = True
                detail = getattr(e, "stderr", "") or str(e)
                import warnings

                warnings.warn(f"native build failed; using fallbacks: {detail[-500:]}")
                return False
        return _LIB_PATH.exists()


def _load() -> Optional[ctypes.CDLL]:
    global _lib
    if _lib is not None:
        return _lib
    if not ensure_built():
        return None
    with _lock:
        if _lib is None:
            lib = ctypes.CDLL(str(_LIB_PATH))
            dp = ctypes.POINTER(ctypes.c_double)
            lib.gt_gauss_solve_seq.argtypes = [dp, dp, dp, ctypes.c_long]
            lib.gt_gauss_solve_seq.restype = ctypes.c_int
            lib.gt_gauss_solve_omp.argtypes = [dp, dp, dp, ctypes.c_long, ctypes.c_int]
            lib.gt_gauss_solve_omp.restype = ctypes.c_int
            lib.gt_gauss_solve_threads.argtypes = [dp, dp, dp, ctypes.c_long, ctypes.c_int]
            lib.gt_gauss_solve_threads.restype = ctypes.c_int
            lib.gt_gauss_solve_forkjoin.argtypes = [dp, dp, dp, ctypes.c_long, ctypes.c_int]
            lib.gt_gauss_solve_forkjoin.restype = ctypes.c_int
            lib.gt_gauss_solve_tiled.argtypes = [dp, dp, dp, ctypes.c_long, ctypes.c_int]
            lib.gt_gauss_solve_tiled.restype = ctypes.c_int
            lib.gt_matmul_seq.argtypes = [dp, dp, dp, ctypes.c_long]
            lib.gt_matmul_seq.restype = None
            lib.gt_matmul_omp.argtypes = [dp, dp, dp, ctypes.c_long, ctypes.c_int]
            lib.gt_matmul_omp.restype = None
            lib.gt_dat_read_header.argtypes = [
                ctypes.c_char_p, ctypes.POINTER(ctypes.c_long), ctypes.POINTER(ctypes.c_long)]
            lib.gt_dat_read_header.restype = ctypes.c_int
            lib.gt_dat_read_dense.argtypes = [ctypes.c_char_p, dp, ctypes.c_long]
            lib.gt_dat_read_dense.restype = ctypes.c_int
            _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


def _as_c(a: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(a, dtype=np.float64)


def gauss_solve(a: np.ndarray, b: np.ndarray, engine: str = "seq",
                nthreads: int = 0) -> np.ndarray:
    """Solve A x = b with a native CPU engine. A/b are not modified."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable (no toolchain or build failed)")
    if engine not in GAUSS_ENGINES:
        raise ValueError(f"unknown native gauss engine {engine!r}; options: {GAUSS_ENGINES}")
    a = _as_c(a).copy()
    b = _as_c(b).copy()
    n = a.shape[0]
    if a.shape != (n, n) or b.shape != (n,):
        raise ValueError(f"expected square a and matching b; got {a.shape} and {b.shape}")
    x = np.empty(n, dtype=np.float64)
    dp = ctypes.POINTER(ctypes.c_double)
    pa, pb, px = (arr.ctypes.data_as(dp) for arr in (a, b, x))
    nt = nthreads or (os.cpu_count() or 2)
    if engine == "seq":
        rc = lib.gt_gauss_solve_seq(pa, pb, px, n)
    elif engine == "omp":
        rc = lib.gt_gauss_solve_omp(pa, pb, px, n, nthreads)
    elif engine == "forkjoin":
        rc = lib.gt_gauss_solve_forkjoin(pa, pb, px, n, nt)
    elif engine == "tiled":
        rc = lib.gt_gauss_solve_tiled(pa, pb, px, n, nt)
    else:
        rc = lib.gt_gauss_solve_threads(pa, pb, px, n, nt)
    if rc == -1:
        raise np.linalg.LinAlgError("matrix is singular")
    if rc != 0:
        raise RuntimeError(f"native gauss engine failed with code {rc}")
    return x


def matmul(a: np.ndarray, b: np.ndarray, engine: str = "seq",
           nthreads: int = 0) -> np.ndarray:
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable (no toolchain or build failed)")
    if engine not in MATMUL_ENGINES:
        raise ValueError(f"unknown native matmul engine {engine!r}; options: {MATMUL_ENGINES}")
    a = _as_c(a)
    b = _as_c(b)
    n = a.shape[0]
    if a.shape != (n, n) or b.shape != (n, n):
        raise ValueError("native matmul expects square same-size matrices")
    c = np.empty((n, n), dtype=np.float64)
    dp = ctypes.POINTER(ctypes.c_double)
    pa, pb, pc = (arr.ctypes.data_as(dp) for arr in (a, b, c))
    if engine == "seq":
        lib.gt_matmul_seq(pa, pb, pc, n)
    else:
        lib.gt_matmul_omp(pa, pb, pc, n, nthreads)
    return c


def read_dat_header(path: str) -> tuple[int, int]:
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    n = ctypes.c_long()
    nnz = ctypes.c_long()
    rc = lib.gt_dat_read_header(os.fsencode(path), ctypes.byref(n), ctypes.byref(nnz))
    if rc != 0:
        raise ValueError(f"failed to parse .dat header of {path} (code {rc})")
    return n.value, nnz.value


def read_dat_dense(path: str) -> np.ndarray:
    """Fast native .dat parse + densify; same semantics as the Python parser."""
    n, _ = read_dat_header(path)
    out = np.empty((n, n), dtype=np.float64)
    lib = _load()
    rc = lib.gt_dat_read_dense(
        os.fsencode(path), out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), n)
    if rc != 0:
        raise ValueError(f"failed to parse .dat body of {path} (code {rc})")
    return out


def matrix_gen_path() -> str:
    """Path to the built matrix_gen binary (building if needed)."""
    if not ensure_built() or not _GEN_PATH.exists():
        raise RuntimeError("matrix_gen binary unavailable")
    return str(_GEN_PATH)
