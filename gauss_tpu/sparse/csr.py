"""CSR container assembled directly from the ``.dat`` coordinate stream.

``CsrMatrix`` is the sparse-plane operand: three flat arrays (row
pointers, column indices, values) holding O(nnz + n) bytes, assembled
from coordinates without ever materializing an n x n buffer.  Assembly
SUMS duplicate coordinates — the additive convention for sparse
assembly (finite-element style), documented against the dense path's
fscanf last-wins parity in ``io/datfile.py``.

Two staging forms feed the kernels in ``sparse/spmv.py``:

- ``coo()`` — sorted COO triplets for the ``segment_sum`` fallback;
- ``ell()`` — padded-row (ELLPACK) arrays ``(n, k)`` where
  ``k = max_row_nnz``; padding points at column 0 with value 0 so it
  contributes nothing to a matvec.  For the ≤ tens-of-nnz-per-row
  systems this plane targets, the ELL form is a small constant factor
  over CSR and vectorizes cleanly on both XLA and Pallas.

Everything here is host-side numpy; jax enters only in ``sparse/spmv.py``
and ``sparse/krylov.py``.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Tuple

import numpy as np

__all__ = ["CsrMatrix"]

#: ``to_dense`` refuses above this order: the sparse plane exists so that
#: n x n buffers are never allocated by accident; densifying is only for
#: tests and small diagnostics.
DENSIFY_LIMIT = 8192


def _sum_duplicates(
    n: int, rows: np.ndarray, cols: np.ndarray, vals: np.ndarray
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Lexsort coordinates by (row, col) and sum duplicate entries."""
    codes = rows.astype(np.int64) * np.int64(n) + cols.astype(np.int64)
    order = np.argsort(codes, kind="stable")
    codes = codes[order]
    vals = vals[order]
    uniq, start = np.unique(codes, return_index=True)
    summed = np.add.reduceat(vals, start) if vals.size else vals
    return (uniq // n).astype(np.int64), (uniq % n).astype(np.int32), summed


@dataclasses.dataclass(frozen=True)
class CsrMatrix:
    """Compressed-sparse-row matrix: ``indptr`` (n+1,), ``indices``/
    ``data`` (nnz,) with columns sorted within each row."""

    n: int
    indptr: np.ndarray
    indices: np.ndarray
    data: np.ndarray

    # -- assembly ----------------------------------------------------------

    @classmethod
    def from_coords(
        cls,
        n: int,
        rows: np.ndarray,
        cols: np.ndarray,
        vals: np.ndarray,
        *,
        drop_zeros: bool = True,
    ) -> "CsrMatrix":
        """Assemble from 0-indexed coordinates, SUMMING duplicates.

        Explicit zeros (and entries that cancel to zero when duplicates
        sum) are dropped by default so density reflects structural
        nonzeros, matching ``detect_structure_coords``.
        """
        rows = np.asarray(rows)
        cols = np.asarray(cols)
        vals = np.asarray(vals, dtype=np.float64)
        if not (rows.shape == cols.shape == vals.shape):
            raise ValueError("rows/cols/vals must have identical shapes")
        if rows.size and (
            rows.min() < 0 or rows.max() >= n or cols.min() < 0 or cols.max() >= n
        ):
            raise ValueError(f"coordinate out of range for n={n}")
        r, c, v = _sum_duplicates(n, rows, cols, vals)
        if drop_zeros:
            keep = v != 0.0
            r, c, v = r[keep], c[keep], v[keep]
        indptr = np.zeros(n + 1, dtype=np.int64)
        np.add.at(indptr, r + 1, 1)
        np.cumsum(indptr, out=indptr)
        return cls(n=n, indptr=indptr, indices=c, data=v)

    @classmethod
    def from_coord_chunks(
        cls,
        n: int,
        chunks: Iterable[Tuple[np.ndarray, np.ndarray, np.ndarray]],
        *,
        drop_zeros: bool = True,
    ) -> "CsrMatrix":
        """Assemble from an iterable of ``(rows, cols, vals)`` chunks —
        the shape ``io.datfile.iter_coords`` yields — accumulating
        O(nnz) coordinate arrays, never the file text."""
        rs, cs, vs = [], [], []
        for rows, cols, vals in chunks:
            rs.append(np.asarray(rows))
            cs.append(np.asarray(cols))
            vs.append(np.asarray(vals, dtype=np.float64))
        if not rs:
            rs, cs, vs = [np.zeros(0, np.int64)], [np.zeros(0, np.int64)], [
                np.zeros(0, np.float64)
            ]
        return cls.from_coords(
            n,
            np.concatenate(rs),
            np.concatenate(cs),
            np.concatenate(vs),
            drop_zeros=drop_zeros,
        )

    @classmethod
    def from_dat(cls, path_or_file, *, strict: bool = False) -> "CsrMatrix":
        """Stream a ``.dat`` coordinate file into CSR form.

        Non-strict (the default here, mirroring the reference's
        tolerant fscanf loop) SUMS duplicate coordinates; ``strict=True``
        rejects them with a typed ``DatFormatError`` before assembly —
        see the duplicate-semantics note in ``io/datfile.py``.
        """
        from gauss_tpu.io import datfile

        stream = datfile.iter_coords(path_or_file, strict=strict)
        return cls.from_coord_chunks(stream.n, stream)

    @classmethod
    def from_dense(cls, a: np.ndarray) -> "CsrMatrix":
        """Convert a small dense matrix (tests, recovery-ladder rungs
        whose operands are already dense)."""
        a = np.asarray(a, dtype=np.float64)
        if a.ndim != 2 or a.shape[0] != a.shape[1]:
            raise ValueError(f"expected square matrix, got {a.shape}")
        rows, cols = np.nonzero(a)
        return cls.from_coords(a.shape[0], rows, cols, a[rows, cols])

    # -- shape / structure -------------------------------------------------

    @property
    def nnz(self) -> int:
        return int(self.data.size)

    @property
    def density(self) -> float:
        return self.nnz / float(max(self.n, 1) ** 2)

    @property
    def max_row_nnz(self) -> int:
        return int(np.diff(self.indptr).max()) if self.n else 0

    def row_ids(self) -> np.ndarray:
        """COO row index per stored entry (sorted ascending)."""
        return np.repeat(
            np.arange(self.n, dtype=np.int64), np.diff(self.indptr)
        )

    def diagonal(self) -> np.ndarray:
        d = np.zeros(self.n, dtype=np.float64)
        rows = self.row_ids()
        on_diag = rows == self.indices
        d[rows[on_diag]] = self.data[on_diag]
        return d

    def is_symmetric(self) -> bool:
        """Exact pattern + value symmetry (same convention as
        ``structure.detect``: compares the (row, col) stream against its
        transpose after lexsort)."""
        rows = self.row_ids()
        tcodes = self.indices.astype(np.int64) * np.int64(self.n) + rows
        torder = np.argsort(tcodes, kind="stable")
        codes = rows * np.int64(self.n) + self.indices
        return bool(
            np.array_equal(codes, tcodes[torder])
            and np.array_equal(self.data, self.data[torder])
        )

    def gershgorin_spd(self) -> bool:
        """The same SPD certificate the structure tagger issues: symmetric,
        positive diagonal, and every Gershgorin disc strictly right of
        zero (``a_ii > sum_{j != i} |a_ij|``) — a proof of SPD, which is
        what licenses the CG head of the sparse ladder."""
        d = self.diagonal()
        if not (d > 0.0).all():
            return False
        off = np.zeros(self.n, dtype=np.float64)
        rows = self.row_ids()
        mask = rows != self.indices
        np.add.at(off, rows[mask], np.abs(self.data[mask]))
        if not (d > off).all():
            return False
        return self.is_symmetric()

    # -- staging forms -----------------------------------------------------

    def coo(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Row-sorted COO triplets for the ``segment_sum`` SpMV."""
        return self.row_ids(), self.indices, self.data

    def ell(self) -> Tuple[np.ndarray, np.ndarray]:
        """Padded-row (ELLPACK) staging: ``(cols, vals)`` of shape
        ``(n, max_row_nnz)``; padding is column 0 / value 0."""
        k = max(self.max_row_nnz, 1)
        counts = np.diff(self.indptr)
        cols = np.zeros((self.n, k), dtype=np.int32)
        vals = np.zeros((self.n, k), dtype=np.float64)
        slot = np.arange(self.data.size, dtype=np.int64) - np.repeat(
            self.indptr[:-1], counts
        )
        cols[self.row_ids(), slot] = self.indices
        vals[self.row_ids(), slot] = self.data
        return cols, vals

    # -- host reference ops ------------------------------------------------

    def matvec(self, x: np.ndarray) -> np.ndarray:
        """Host numpy reference ``A @ x`` (1-D or (n, k) x) — the
        independent check the verify gate runs against solver output."""
        x = np.asarray(x, dtype=np.float64)
        rows = self.row_ids()
        contrib = (
            self.data * x[self.indices]
            if x.ndim == 1
            else self.data[:, None] * x[self.indices]
        )
        y = np.zeros(x.shape, dtype=np.float64)
        np.add.at(y, rows, contrib)
        return y

    def to_dense(self, *, limit: int = DENSIFY_LIMIT) -> np.ndarray:
        """Materialize n x n — tests/diagnostics only; refuses above
        ``limit`` so the no-densify contract cannot be broken silently."""
        if self.n > limit:
            raise ValueError(
                f"refusing to densify n={self.n} (> {limit}): the sparse "
                "plane exists to avoid n^2 buffers"
            )
        a = np.zeros((self.n, self.n), dtype=np.float64)
        a[self.row_ids(), self.indices] = self.data
        return a
