"""Sparse engines: CSR assembly straight from the coordinate stream,
matrix-free Krylov solvers, and factor-based preconditioning.

The reference's native input format is already sparse — ``row col value``
coordinate ``.dat`` files — and ``detect_structure_coords`` classifies
structure on that stream without densifying.  This package closes the
remaining gap: the operand itself stays in CSR form (O(nnz + n) bytes),
SpMV runs as a padded-row (ELL) kernel with a Pallas TPU path behind the
usual size routing, and the solvers are matrix-free ``lax.while_loop``
Krylov programs (CG for Gershgorin-certified SPD systems, GMRES(restart)
and BiCGStab for general systems) gated by the same 1e-4 verify as every
dense engine.  Preconditioners reuse existing machinery: block-Jacobi
from block-diagonal partitions (factorability probed by the
``core/blocked.py`` panel step), tridiagonal factors from
``structure/banded.py``, and a zero-fill incomplete Cholesky/ILU whose
fill is confined to the block-tridiagonal pattern.

Routing: ``structure/detect.py`` tags a system ``"sparse"`` when its
density sits at or below ``SPARSE_MAX_DENSITY`` (sourced from
``tune.space.SPARSE_DENSITY_SEED``) at ``n >= SPARSE_MIN_N``; the
recovery ladder for that tag is cg -> gmres -> bicgstab -> dense chain,
with stagnation surfacing as the typed ``IterativeStagnationError``
(docs/STRUCTURE.md).
"""

from gauss_tpu.sparse.csr import CsrMatrix
from gauss_tpu.sparse.krylov import (
    IterativeStagnationError,
    SparseSolveResult,
    solve_bicgstab,
    solve_cg,
    solve_gmres,
)
from gauss_tpu.sparse.precond import Preconditioner, build_preconditioner
from gauss_tpu.sparse.solve import solve_sparse
from gauss_tpu.sparse.spmv import spmv_coo, spmv_ell, spmv_ell_pallas

__all__ = [
    "CsrMatrix",
    "IterativeStagnationError",
    "Preconditioner",
    "SparseSolveResult",
    "build_preconditioner",
    "solve_bicgstab",
    "solve_cg",
    "solve_gmres",
    "solve_sparse",
    "spmv_coo",
    "spmv_ell",
    "spmv_ell_pallas",
]
