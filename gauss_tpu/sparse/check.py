"""Sparse-plane smoke gate: ``python -m gauss_tpu.sparse.check``.

Two legs, both on the deterministic generator the matrix_gen CLI ships
(``io.synthetic.sparse_coords``):

- **smoke** (n ~ 640): the coordinate stream classifies ``sparse``
  (detect_structure_coords), ``solve_auto`` routes it to the CG rung
  without demotion, and each Krylov method — CG, GMRES, BiCGStab — solves
  the same system to the 1e-4 relative-residual gate (verified here with
  a TRUE residual, independently of the solvers' own convergence tests).

- **giant** (n = 100,000, ~20 nnz/row): the headline of the sparse plane
  — the system is assembled, preconditioned, and CG-solved to 1e-4
  WITHOUT ever allocating an n x n buffer. Enforced, not asserted by
  inspection: the process peak RSS (``resource.getrusage``) must stay
  under a budget that the dense matrix alone (8 n^2 bytes = 80 GB)
  exceeds tenfold. A future change that quietly densifies anywhere on
  the path cannot pass this leg.

The summary (``--summary-json``) is regress-ingestable
(``kind: sparse_solve``): per-method seconds-per-solve and iteration
counts plus the giant leg's wall time and peak bytes, slow-side-gated so
a convergence regression — a preconditioner losing its bite, iteration
counts creeping — gates in CI exactly like a perf regression.
``make sparse-check`` runs the CPU configuration CI gates on.

Exit status: 2 when any leg fails verification/routing/memory, 1 when
``--regress-check`` finds an out-of-band metric, 0 otherwise.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, List, Tuple

import numpy as np

from gauss_tpu.utils.env import honor_jax_platforms

#: giant-leg peak-RSS budget (bytes). The point is the ORDER: the dense
#: operand alone costs 8 n^2 = 80 GB at n = 100,000 — at least 10x this
#: budget (asserted) — so fitting under it proves no densification.
PEAK_BUDGET_BYTES = 4 << 30


def _peak_rss_bytes() -> int:
    """Lifetime peak RSS of this process. ru_maxrss is KiB on Linux,
    bytes on macOS."""
    import resource

    peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    return int(peak) if sys.platform == "darwin" else int(peak) * 1024


def run_smoke(n: int, nnz_per_row: int, seed: int, gate: float,
              repeats: int) -> Tuple[Dict, Dict[str, Dict]]:
    """The small-n leg: coordinate classification + routing + all three
    Krylov methods at the gate. Returns (routed_row, per_method_rows)."""
    from gauss_tpu.sparse import solve_sparse
    from gauss_tpu.sparse.csr import CsrMatrix
    from gauss_tpu.io import synthetic
    from gauss_tpu.structure import solve_auto
    from gauss_tpu.structure.detect import detect_structure_coords
    from gauss_tpu.verify import checks

    rows, cols, vals = synthetic.sparse_coords(n, nnz_per_row, seed=seed)
    a = CsrMatrix.from_coords(n, rows, cols, vals)
    rng = np.random.default_rng(np.random.SeedSequence((seed, n)))
    b = rng.standard_normal(n)

    info = detect_structure_coords(n, rows, cols, vals)
    dense = a.to_dense()
    res = solve_auto(dense, b, info=info, gate=gate)
    rel = checks.residual_norm(dense, res.x, b, relative=True)
    routed = {
        "n": n, "nnz": a.nnz, "detected": info.kind, "engine": res.rung,
        "demoted": bool(res.rung_index > 0),
        "rel_residual": float(rel),
        "verified": bool(np.isfinite(rel) and rel <= gate),
        "routed_ok": info.kind == "sparse" and res.rung == "cg",
    }

    methods: Dict[str, Dict] = {}
    for method in ("cg", "gmres", "bicgstab"):
        best = None
        out = None
        for _ in range(max(1, repeats)):
            t0 = time.perf_counter()
            out = solve_sparse(a, b, method=method, gate=gate)
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        true_rel = float(np.linalg.norm(a.matvec(out.x) - b)
                         / np.linalg.norm(b))
        methods[method] = {
            "n": n, "nnz": a.nnz, "precond": out.precond,
            "iterations": int(out.iterations),
            "s_per_solve": round(best, 6),
            "rel_residual": true_rel,
            "verified": bool(np.isfinite(true_rel) and true_rel <= gate),
        }
    return routed, methods


def run_giant(n: int, nnz_per_row: int, seed: int, gate: float) -> Dict:
    """The no-densify leg: assemble + CG-solve an n = 100k system to the
    gate with the process peak RSS held under PEAK_BUDGET_BYTES."""
    from gauss_tpu.sparse import solve_sparse
    from gauss_tpu.sparse.csr import CsrMatrix
    from gauss_tpu.io import synthetic

    t0 = time.perf_counter()
    rows, cols, vals = synthetic.sparse_coords(n, nnz_per_row, seed=seed)
    a = CsrMatrix.from_coords(n, rows, cols, vals)
    rng = np.random.default_rng(np.random.SeedSequence((seed, n)))
    b = rng.standard_normal(n)
    out = solve_sparse(a, b, method="cg", precond="jacobi", gate=gate)
    wall = time.perf_counter() - t0
    true_rel = float(np.linalg.norm(a.matvec(out.x) - b)
                     / np.linalg.norm(b))
    peak = _peak_rss_bytes()
    dense_bytes = 8 * n * n
    return {
        "n": n, "nnz": a.nnz, "density": a.density,
        "method": out.method, "precond": out.precond,
        "iterations": int(out.iterations),
        "s_per_solve": round(wall, 6),
        "rel_residual": true_rel,
        "verified": bool(np.isfinite(true_rel) and true_rel <= gate),
        "peak_rss_bytes": peak,
        "peak_budget_bytes": PEAK_BUDGET_BYTES,
        "dense_bytes": dense_bytes,
        # The leg's whole point, as data: the budget held AND the budget
        # is small against the densified operand (>= 10x margin).
        "no_densify_ok": bool(peak <= PEAK_BUDGET_BYTES
                              and dense_bytes >= 10 * PEAK_BUDGET_BYTES),
    }


def history_records(summary: Dict) -> List[Tuple[str, float, str]]:
    """(metric, value, unit) records for the regression history —
    per-method seconds-per-solve and iteration counts, plus the giant
    leg's wall time and peak bytes. All slow-side-gated: convergence
    regressions raise iterations and seconds; densification raises peak
    bytes by an order of magnitude."""
    out: List[Tuple[str, float, str]] = []
    for method, row in (summary.get("methods") or {}).items():
        if isinstance(row.get("s_per_solve"), (int, float)):
            out.append((f"sparse:{method}/s_per_solve",
                        row["s_per_solve"], "s"))
        if isinstance(row.get("iterations"), (int, float)):
            out.append((f"sparse:{method}/iterations",
                        float(row["iterations"]), "count"))
    giant = summary.get("giant") or {}
    if isinstance(giant.get("s_per_solve"), (int, float)):
        out.append(("sparse:giant/s_per_solve",
                    giant["s_per_solve"], "s"))
    if isinstance(giant.get("peak_rss_bytes"), (int, float)):
        out.append(("sparse:giant/peak_rss_bytes",
                    float(giant["peak_rss_bytes"]), "bytes"))
    return out


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m gauss_tpu.sparse.check",
        description="Sparse-plane smoke gate: coordinate classification, "
                    "Krylov routing, CG/GMRES/BiCGStab at the 1e-4 gate, "
                    "and the n=100k no-densify leg (the make sparse-check "
                    "CI configuration).")
    p.add_argument("--smoke-n", type=int, default=640)
    p.add_argument("--giant-n", type=int, default=100_000)
    p.add_argument("--nnz-per-row", type=int, default=6,
                   help="stored entries per row for the smoke leg")
    p.add_argument("--giant-nnz-per-row", type=int, default=20,
                   help="stored entries per row for the giant leg")
    p.add_argument("--skip-giant", action="store_true",
                   help="smoke legs only (developer loop; CI runs both)")
    p.add_argument("--repeats", type=int, default=3,
                   help="timed solves per method (best-of; the first rep "
                        "pays the jit compile, so >= 2 is meaningful)")
    p.add_argument("--seed", type=int, default=258458)
    p.add_argument("--gate", type=float, default=1e-4)
    p.add_argument("--metrics-out", default=None, metavar="PATH",
                   help="append the run's obs JSONL stream here")
    p.add_argument("--summary-json", default=None, metavar="PATH",
                   help="write the regress-ingestable summary "
                        "(kind=sparse_solve)")
    p.add_argument("--history", nargs="?", const="", default=None,
                   metavar="PATH",
                   help="append this run's records to the regression "
                        "history (default reports/history.jsonl)")
    p.add_argument("--regress-check", action="store_true",
                   help="gate against the history baselines (exit 1 when "
                        "out of band)")
    p.add_argument("--band", type=float, default=1.5,
                   help="slow-side noise band for --regress-check "
                        "(default 1.5: millisecond-scale CPU timings are "
                        "jittery, while the regressions this gate exists "
                        "for — densification, a preconditioner losing its "
                        "bite — move the metrics by orders of magnitude)")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    honor_jax_platforms()

    from gauss_tpu import obs
    from gauss_tpu.obs import regress

    t0 = time.perf_counter()
    with obs.run(metrics_out=args.metrics_out, tool="sparse_check",
                 seed=args.seed) as rec:
        with obs.span("sparse_check_smoke", n=args.smoke_n):
            routed, methods = run_smoke(args.smoke_n, args.nnz_per_row,
                                        args.seed, args.gate, args.repeats)
        giant = None
        if not args.skip_giant:
            with obs.span("sparse_check_giant", n=args.giant_n):
                giant = run_giant(args.giant_n, args.giant_nnz_per_row,
                                  args.seed, args.gate)
    wall = round(time.perf_counter() - t0, 3)

    bad: List[str] = []
    if not (routed["verified"] and routed["routed_ok"]):
        bad.append("routed")
    bad.extend(m for m, row in methods.items() if not row["verified"])
    if giant is not None and not (giant["verified"]
                                  and giant["no_densify_ok"]):
        bad.append("giant")
    summary = {"kind": "sparse_solve", "seed": args.seed,
               "gate": args.gate, "routed": routed, "methods": methods,
               "giant": giant, "wall_s": wall, "ok": not bad}

    print(f"sparse-check [routed   ] n={routed['n']:6d} detected="
          f"{routed['detected']:7s} engine={routed['engine']:9s} "
          f"rel_residual={routed['rel_residual']:.2e} "
          f"{'OK' if routed['verified'] and routed['routed_ok'] else 'FAIL'}")
    for method, row in methods.items():
        print(f"sparse-check [{method:9s}] n={row['n']:6d} "
              f"precond={row['precond']:7s} iters={row['iterations']:4d} "
              f"s_per_solve={row['s_per_solve']:.4f} "
              f"rel_residual={row['rel_residual']:.2e} "
              f"{'OK' if row['verified'] else 'FAIL'}")
    if giant is not None:
        print(f"sparse-check [giant    ] n={giant['n']:6d} "
              f"nnz={giant['nnz']} iters={giant['iterations']:4d} "
              f"s_per_solve={giant['s_per_solve']:.4f} "
              f"rel_residual={giant['rel_residual']:.2e} "
              f"peak_rss={giant['peak_rss_bytes'] / 2**30:.2f} GiB "
              f"(budget {giant['peak_budget_bytes'] / 2**30:.0f} GiB, "
              f"dense would be {giant['dense_bytes'] / 2**30:.0f} GiB) "
              f"{'OK' if giant['verified'] and giant['no_densify_ok'] else 'FAIL'}")
    print(f"sparse-check: {len(methods) + 1 + (giant is not None)} leg(s) "
          f"in {wall} s"
          + (f"; FAILED: {bad}" if bad else "; all verified at the "
             f"{args.gate:.0e} gate"))

    if args.summary_json:
        parent = os.path.dirname(args.summary_json)
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(args.summary_json, "w") as f:
            json.dump(summary, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"summary: {args.summary_json}")

    rc = 0
    # Run-id-tagged sources (cf. structure-check): identical values from
    # DISTINCT epochs — iteration counts are deterministic — must
    # accumulate as separate baseline samples, not dedup into one.
    records = [{"metric": m, "value": v, "unit": u,
                "source": f"sparse-{rec.run_id}",
                "kind": "sparse"} for m, v, u in history_records(summary)]
    if args.regress_check and records:
        history_path = args.history or regress.default_history_path()
        verdicts = regress.check_records(
            records, regress.load_history(history_path), band=args.band)
        print(regress.format_verdicts(verdicts))
        if any(v["status"] == "out-of-band" for v in verdicts):
            rc = 1
    if args.history is not None and records and rc == 0 and not bad:
        history_path = args.history or regress.default_history_path()
        added = regress.append_history(records, history_path)
        print(f"history: {added} record(s) appended to {history_path}")

    if bad:
        return 2
    return rc


if __name__ == "__main__":
    sys.exit(main())
