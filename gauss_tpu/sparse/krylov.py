"""Matrix-free Krylov solvers as ``lax.while_loop`` programs.

Three cores, each a single jit-clean traced program (registered in
``core/entrypoints.py`` as ``sparse/cg``, ``sparse/gmres``,
``sparse/bicgstab`` — refinement sites, since the host wrappers run them
in f64 under ``jax.experimental.enable_x64()``):

- :func:`cg_run` — preconditioned conjugate gradients.  The host wrapper
  :func:`solve_cg` demands the Gershgorin SPD certificate
  (``CsrMatrix.gershgorin_spd``, the same proof the structure tagger
  issues) before running it: CG's convergence theory needs SPD, and an
  uncertified operand raises typed ``NotSPDError`` so the recovery
  ladder demotes to the general-system rungs instead of iterating
  blindly.
- :func:`gmres_run` — GMRES(restart) with a CGS2 (classical
  Gram-Schmidt, one reorthogonalization pass) Arnoldi inner loop: fully
  vectorized over the basis, numerically on par with MGS for the
  restart lengths this plane sweeps.  Peak memory is the acceptance
  bound: O(nnz + n * restart) for the resident basis.
- :func:`bicgstab_run` — BiCGStab with breakdown-guarded denominators;
  a breakdown stalls the residual and surfaces as stagnation.

Every wrapper verifies the TRUE residual ``||b - A x|| / ||b||`` on the
host via the CSR matvec — the same 1e-4 gate as every dense engine — and
raises the typed :class:`IterativeStagnationError` when the budget runs
out above it, which the recovery ladder catches
(``exception:IterativeStagnationError``) to demote toward the dense
chain.  Each result carries the iteration count and the residual curve
for the ``sparse_solve`` observability event.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from gauss_tpu.sparse.csr import CsrMatrix
from gauss_tpu.sparse.precond import apply_precond, build_preconditioner
from gauss_tpu.sparse.spmv import spmv_ell

# Seed restart length for GMRES (tune.space "sparse" op sweeps it).
from gauss_tpu.tune.space import SPARSE_RESTART_SEED

__all__ = [
    "IterativeStagnationError",
    "SparseSolveResult",
    "bicgstab_run",
    "cg_run",
    "gmres_run",
    "solve_bicgstab",
    "solve_cg",
    "solve_gmres",
]

#: Same residual gate as the dense engines (resilience.recover.DEFAULT_GATE);
#: duplicated here only as a keyword default — callers route the live gate.
DEFAULT_TOL = 1e-4

#: Default total matvec budget for the host wrappers.
DEFAULT_MAXITER = 400

_TINY = 1e-300


class IterativeStagnationError(RuntimeError):
    """A Krylov solver exhausted its iteration budget (or broke down)
    above the residual gate.  Typed so the recovery ladder can demote to
    the dense chain (``exception:IterativeStagnationError`` trigger)
    instead of shipping an unverified answer.  ``result`` carries the
    partial :class:`SparseSolveResult` for diagnostics."""

    def __init__(self, message, *, method=None, iterations=None,
                 rel_residual=None, result=None):
        super().__init__(message)
        self.method = method
        self.iterations = iterations
        self.rel_residual = rel_residual
        self.result = result


@dataclasses.dataclass(frozen=True)
class SparseSolveResult:
    """Solver outcome: ``x`` (float64, shape of ``b``), the method and
    preconditioner that produced it, the matvec/iteration count, the
    residual curve (relative, one entry per recorded step), and the TRUE
    host-verified relative residual."""

    x: np.ndarray
    method: str
    precond: str
    iterations: int
    residuals: np.ndarray
    converged: bool
    rel_residual: float


def _safe_div(num, den):
    import jax.numpy as jnp

    return num / jnp.where(jnp.abs(den) > _TINY, den, jnp.where(den < 0, -_TINY, _TINY))


def cg_run(cols, vals, b, x0, prec, tol, *, maxiter):
    """Preconditioned CG core — see module docstring.  Returns
    ``(x, iterations, curve, rel)``; ``curve`` is (maxiter+1,) with
    unreached entries zero."""
    import jax.numpy as jnp
    from jax import lax

    mv = lambda u: spmv_ell(cols, vals, u)  # noqa: E731
    bnorm = jnp.maximum(jnp.linalg.norm(b), _TINY)
    r0 = b - mv(x0)
    z0 = apply_precond(prec, r0)
    rz0 = r0 @ z0
    rel0 = jnp.linalg.norm(r0) / bnorm
    curve0 = jnp.zeros(maxiter + 1, b.dtype).at[0].set(rel0)

    def cond(state):
        k, _, _, _, _, _, _, rel = state
        return (k < maxiter) & (rel > tol)

    def body(state):
        k, x, r, z, p, rz, curve, _ = state
        q = mv(p)
        alpha = _safe_div(rz, p @ q)
        x = x + alpha * p
        r = r - alpha * q
        z = apply_precond(prec, r)
        rz_new = r @ z
        p = z + _safe_div(rz_new, rz) * p
        rel = jnp.linalg.norm(r) / bnorm
        curve = curve.at[k + 1].set(rel)
        return k + 1, x, r, z, p, rz_new, curve, rel

    k, x, _, _, _, _, curve, rel = lax.while_loop(
        cond, body, (0, x0, r0, z0, z0, rz0, curve0, rel0)
    )
    return x, k, curve, rel


def gmres_run(cols, vals, b, x0, prec, tol, *, restart, maxcycles):
    """Left-preconditioned GMRES(restart) core.  Returns
    ``(x, cycles, curve, rel)``; ``curve`` holds the TRUE relative
    residual once per restart cycle, shaped (maxcycles+1,).  Peak state
    is the (restart+1, n) basis — the O(n * restart) acceptance bound."""
    import jax.numpy as jnp
    from jax import lax

    n = b.shape[0]
    mv = lambda u: spmv_ell(cols, vals, u)  # noqa: E731
    bnorm = jnp.maximum(jnp.linalg.norm(b), _TINY)
    rel0 = jnp.linalg.norm(b - mv(x0)) / bnorm
    curve0 = jnp.zeros(maxcycles + 1, b.dtype).at[0].set(rel0)

    def arnoldi(j, carry):
        V, H = carry
        w = apply_precond(prec, mv(V[j]))
        # CGS2: project against the whole basis twice; unfilled rows of V
        # are zero so they contribute nothing to either pass.
        h1 = V @ w
        w = w - V.T @ h1
        h2 = V @ w
        w = w - V.T @ h2
        hnorm = jnp.linalg.norm(w)
        V = V.at[j + 1].set(jnp.where(hnorm > _TINY, w / hnorm, 0.0))
        H = H.at[:, j].set(h1 + h2)
        H = H.at[j + 1, j].set(hnorm)
        return V, H

    def cycle(state):
        c, x, curve, _ = state
        r = b - mv(x)
        z = apply_precond(prec, r)
        beta = jnp.linalg.norm(z)
        V0 = jnp.zeros((restart + 1, n), b.dtype).at[0].set(
            z / jnp.maximum(beta, _TINY)
        )
        H0 = jnp.zeros((restart + 1, restart), b.dtype)
        V, H = lax.fori_loop(0, restart, arnoldi, (V0, H0))
        g = jnp.zeros(restart + 1, b.dtype).at[0].set(beta)
        y = jnp.linalg.lstsq(H, g)[0]
        x = x + V[:restart].T @ y
        rel = jnp.linalg.norm(b - mv(x)) / bnorm
        curve = curve.at[c + 1].set(rel)
        return c + 1, x, curve, rel

    def cond(state):
        c, _, _, rel = state
        return (c < maxcycles) & (rel > tol)

    c, x, curve, rel = lax.while_loop(cond, cycle, (0, x0, curve0, rel0))
    return x, c, curve, rel


def bicgstab_run(cols, vals, b, x0, prec, tol, *, maxiter):
    """Preconditioned BiCGStab core with breakdown-guarded denominators.
    Returns ``(x, iterations, curve, rel)``; ``curve`` (maxiter+1,)."""
    import jax.numpy as jnp
    from jax import lax

    mv = lambda u: spmv_ell(cols, vals, u)  # noqa: E731
    bnorm = jnp.maximum(jnp.linalg.norm(b), _TINY)
    r0 = b - mv(x0)
    rel0 = jnp.linalg.norm(r0) / bnorm
    curve0 = jnp.zeros(maxiter + 1, b.dtype).at[0].set(rel0)
    one = jnp.asarray(1.0, b.dtype)
    zeros = jnp.zeros_like(b)

    def cond(state):
        k, _, _, _, _, _, _, _, _, rel = state
        return (k < maxiter) & (rel > tol)

    def body(state):
        k, x, r, p, v, rho, alpha, omega, curve, _ = state
        rho_new = r0 @ r
        beta = _safe_div(rho_new, rho) * _safe_div(alpha, omega)
        p = r + beta * (p - omega * v)
        phat = apply_precond(prec, p)
        v = mv(phat)
        alpha = _safe_div(rho_new, r0 @ v)
        s = r - alpha * v
        shat = apply_precond(prec, s)
        t = mv(shat)
        omega = _safe_div(t @ s, t @ t)
        x = x + alpha * phat + omega * shat
        r = s - omega * t
        rel = jnp.linalg.norm(r) / bnorm
        curve = curve.at[k + 1].set(rel)
        return k + 1, x, r, p, v, rho_new, alpha, omega, curve, rel

    k, x, _, _, _, _, _, _, curve, rel = lax.while_loop(
        cond, body, (0, x0, r0, zeros, zeros, one, one, one, curve0, rel0)
    )
    return x, k, curve, rel


# ---------------------------------------------------------------------------
# Host wrappers: stage ELL arrays in f64, run the core under enable_x64,
# verify the TRUE residual, raise typed on stagnation.
# ---------------------------------------------------------------------------

_CORES = {}


def _core(method: str, static):
    import jax

    key = method
    if key not in _CORES:
        fn = {"cg": cg_run, "gmres": gmres_run, "bicgstab": bicgstab_run}[method]
        _CORES[key] = jax.jit(fn, static_argnames=static)
    return _CORES[key]


def _resolve_precond(a: CsrMatrix, precond, block):
    if precond is None:
        precond = "none"
    if isinstance(precond, str):
        return build_preconditioner(a, precond, block=block), precond
    return precond, precond.kind


def _run_columns(a, b, run_one):
    """Apply a single-RHS solver columnwise for (n, k) b; returns the
    stacked x plus the worst column's (iterations, curve, rel)."""
    b = np.asarray(b, dtype=np.float64)
    if b.ndim == 1:
        return run_one(b)
    xs, worst = [], None
    for j in range(b.shape[1]):
        x, iters, curve, rel = run_one(b[:, j])
        xs.append(x)
        if worst is None or rel > worst[2]:
            worst = (iters, curve, rel)
    return np.stack(xs, axis=1), worst[0], worst[1], worst[2]


def _finish(a, b, x, iters, curve, method, pname, tol, raise_on_stagnation):
    b = np.asarray(b, dtype=np.float64)
    true_res = np.linalg.norm(b - a.matvec(x))
    rel = float(true_res / max(np.linalg.norm(b), _TINY))
    curve = np.asarray(curve, dtype=np.float64)
    # Trim trailing unreached entries (zeros past the iteration count).
    curve = curve[: int(iters) + 1]
    res = SparseSolveResult(
        x=np.asarray(x, dtype=np.float64),
        method=method,
        precond=pname,
        iterations=int(iters),
        residuals=curve,
        converged=rel <= tol,
        rel_residual=rel,
    )
    if not res.converged and raise_on_stagnation:
        raise IterativeStagnationError(
            f"{method} stagnated: rel_residual={rel:.3e} > gate={tol:g} "
            f"after {res.iterations} iterations",
            method=method,
            iterations=res.iterations,
            rel_residual=rel,
            result=res,
        )
    return res


def _stage(a: CsrMatrix):
    import jax.numpy as jnp

    cols, vals = a.ell()
    return jnp.asarray(cols), jnp.asarray(vals, jnp.float64)


def solve_cg(
    a: CsrMatrix,
    b,
    *,
    precond="jacobi",
    block: Optional[int] = None,
    tol: float = DEFAULT_TOL,
    maxiter: int = DEFAULT_MAXITER,
    x0=None,
    raise_on_stagnation: bool = True,
) -> SparseSolveResult:
    """Conjugate gradients on a Gershgorin-CERTIFIED SPD CsrMatrix.
    Raises typed ``NotSPDError`` when the certificate fails (the ladder's
    demotion signal) and ``IterativeStagnationError`` on budget
    exhaustion above ``tol``."""
    import jax

    from gauss_tpu.structure.cholesky import NotSPDError

    if not a.gershgorin_spd():
        raise NotSPDError(
            "solve_cg requires the Gershgorin SPD certificate (symmetric, "
            "positive strictly dominant diagonal); route general systems "
            "to GMRES/BiCGStab"
        )
    with jax.experimental.enable_x64():
        prec, pname = _resolve_precond(a, precond, block)
        cols, vals = _stage(a)
        run = _core("cg", ("maxiter",))

        def run_one(b1):
            import jax.numpy as jnp

            x0j = (
                jnp.zeros(a.n, jnp.float64)
                if x0 is None
                else jnp.asarray(x0, jnp.float64)
            )
            x, it, curve, rel = run(
                cols, vals, jnp.asarray(b1, jnp.float64), x0j, prec,
                jnp.asarray(tol, jnp.float64), maxiter=maxiter,
            )
            return np.asarray(x), int(it), np.asarray(curve), float(rel)

        x, iters, curve, _ = _run_columns(a, b, run_one)
    return _finish(a, b, x, iters, curve, "cg", pname, tol, raise_on_stagnation)


def solve_gmres(
    a: CsrMatrix,
    b,
    *,
    precond="jacobi",
    block: Optional[int] = None,
    tol: float = DEFAULT_TOL,
    restart: int = SPARSE_RESTART_SEED,
    maxiter: int = DEFAULT_MAXITER,
    x0=None,
    raise_on_stagnation: bool = True,
) -> SparseSolveResult:
    """GMRES(restart) for general systems; ``maxiter`` bounds total inner
    iterations (cycles = ceil(maxiter / restart)).  Reported iterations
    count inner steps (cycles * restart)."""
    import jax

    restart = max(1, min(int(restart), a.n))
    maxcycles = max(1, -(-int(maxiter) // restart))
    with jax.experimental.enable_x64():
        prec, pname = _resolve_precond(a, precond, block)
        cols, vals = _stage(a)
        run = _core("gmres", ("restart", "maxcycles"))

        def run_one(b1):
            import jax.numpy as jnp

            x0j = (
                jnp.zeros(a.n, jnp.float64)
                if x0 is None
                else jnp.asarray(x0, jnp.float64)
            )
            x, cyc, curve, rel = run(
                cols, vals, jnp.asarray(b1, jnp.float64), x0j, prec,
                jnp.asarray(tol, jnp.float64), restart=restart,
                maxcycles=maxcycles,
            )
            return np.asarray(x), int(cyc) * restart, np.asarray(curve), float(rel)

        x, iters, curve, _ = _run_columns(a, b, run_one)
    # Curve rows are per-cycle; trim to cycles actually run.
    curve = np.asarray(curve)[: iters // restart + 1]
    return _finish(
        a, b, x, iters, curve, "gmres", pname, tol, raise_on_stagnation
    )


def solve_bicgstab(
    a: CsrMatrix,
    b,
    *,
    precond="jacobi",
    block: Optional[int] = None,
    tol: float = DEFAULT_TOL,
    maxiter: int = DEFAULT_MAXITER,
    x0=None,
    raise_on_stagnation: bool = True,
) -> SparseSolveResult:
    """BiCGStab for general systems (two matvecs per iteration)."""
    import jax

    with jax.experimental.enable_x64():
        prec, pname = _resolve_precond(a, precond, block)
        cols, vals = _stage(a)
        run = _core("bicgstab", ("maxiter",))

        def run_one(b1):
            import jax.numpy as jnp

            x0j = (
                jnp.zeros(a.n, jnp.float64)
                if x0 is None
                else jnp.asarray(x0, jnp.float64)
            )
            x, it, curve, rel = run(
                cols, vals, jnp.asarray(b1, jnp.float64), x0j, prec,
                jnp.asarray(tol, jnp.float64), maxiter=maxiter,
            )
            return np.asarray(x), int(it), np.asarray(curve), float(rel)

        x, iters, curve, _ = _run_columns(a, b, run_one)
    return _finish(
        a, b, x, iters, curve, "bicgstab", pname, tol, raise_on_stagnation
    )
