"""Sparse matrix-vector kernels over CSR staging forms.

Two jit-clean (callback-free) implementations back the Krylov solvers,
both registered in ``core/entrypoints.py`` for the gauss-lint jaxpr
audit:

- ``spmv_ell`` — padded-row (ELLPACK) form: a gather + row reduction
  over dense ``(n, k)`` arrays, which XLA vectorizes well and which the
  while_loop solver bodies can close over with static shapes.  Also
  accepts an ``(n, m)`` multivector for SpMM.
- ``spmv_coo`` — ``jax.ops.segment_sum`` over row-sorted COO triplets:
  the fallback when the padded-row form would waste memory (a few rows
  far denser than the rest).

``spmv_ell_pallas`` is the TPU row-block kernel behind the same
auto-interpret routing as every other Pallas engine here (interpret mode
everywhere that is not a real TPU): one program per block of ``bm``
rows, the operand vector resident in VMEM, the gather and row reduction
fused in-core.  Guide: /opt/skills/guides/pallas_guide.md.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

__all__ = ["spmv_coo", "spmv_ell", "spmv_ell_pallas", "PALLAS_MIN_N"]

#: Below this order the XLA forms win (kernel launch + padding overheads
#: dominate); ``spmv`` routing prefers the Pallas path at or above it on
#: real TPUs only.
PALLAS_MIN_N = 4096


@jax.jit
def spmv_ell(cols, vals, x):
    """``y = A @ x`` from padded-row staging ``cols``/``vals`` of shape
    ``(n, k)`` (padding: column 0, value 0).  ``x`` may be ``(n,)`` or an
    ``(n, m)`` multivector (SpMM)."""
    if x.ndim == 1:
        return (vals * x[cols]).sum(axis=1)
    return jnp.einsum("rk,rkm->rm", vals, x[cols])


@partial(jax.jit, static_argnames=("n",))
def spmv_coo(rows, cols, vals, x, *, n):
    """``y = A @ x`` from row-sorted COO triplets via ``segment_sum``.
    ``n`` is static (the output segment count)."""
    contrib = vals * x[cols] if x.ndim == 1 else vals[:, None] * x[cols]
    return jax.ops.segment_sum(
        contrib, rows, num_segments=n, indices_are_sorted=True
    )


def _auto_interpret(interpret):
    if interpret is None:
        # Same routing as kernels/matmul_pallas: anything that is not a
        # real TPU runs the Pallas interpreter.
        return jax.default_backend() != "tpu"
    return interpret


def _spmv_kernel(cols_ref, vals_ref, x_ref, o_ref):
    # One program per bm-row block: gather the operand entries for every
    # stored column in the block and reduce along the padded-row axis.
    # The padding (column 0, value 0) contributes exactly zero.
    o_ref[:] = jnp.sum(vals_ref[:] * x_ref[:][cols_ref[:]], axis=1)


@partial(jax.jit, static_argnames=("bm", "interpret"))
def spmv_ell_pallas(cols, vals, x, *, bm: int = 512, interpret=None):
    """Pallas row-block ELL SpMV: grid over ``ceil(n / bm)`` row blocks,
    ``x`` resident in VMEM (n * 4 bytes at f32 — well under the ~16 MB
    VMEM budget for every order this plane serves).  1-D ``x`` only."""
    n, k = vals.shape
    grid = (n + bm - 1) // bm
    npad = grid * bm - n
    if npad:
        cols = jnp.pad(cols, ((0, npad), (0, 0)))
        vals = jnp.pad(vals, ((0, npad), (0, 0)))
    y = pl.pallas_call(
        _spmv_kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((bm, k), lambda i: (i, 0)),
            pl.BlockSpec((bm, k), lambda i: (i, 0)),
            pl.BlockSpec((n,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((bm,), lambda i: (i,)),
        out_shape=jax.ShapeDtypeStruct((grid * bm,), vals.dtype),
        interpret=_auto_interpret(interpret),
    )(cols, vals, x)
    return y[:n]


def spmv(a, x, *, impl: str = "auto"):
    """Host convenience: ``A @ x`` for a ``CsrMatrix``, routing between
    the staging forms (``auto`` prefers ELL; the Pallas path engages only
    on a real TPU at ``n >= PALLAS_MIN_N``)."""
    import numpy as np

    if impl == "coo":
        rows, cols, vals = a.coo()
        return np.asarray(spmv_coo(rows, cols, vals, jnp.asarray(x), n=a.n))
    cols, vals = a.ell()
    xj = jnp.asarray(x)
    if impl == "pallas" or (
        impl == "auto"
        and jax.default_backend() == "tpu"
        and a.n >= PALLAS_MIN_N
        and xj.ndim == 1
    ):
        return np.asarray(spmv_ell_pallas(jnp.asarray(cols), jnp.asarray(vals), xj))
    return np.asarray(spmv_ell(jnp.asarray(cols), jnp.asarray(vals), xj))
