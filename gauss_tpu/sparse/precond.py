"""Preconditioners for the sparse Krylov plane, built from machinery the
dense engines already own.

Build (host, numpy — staging like ``structure/banded.py``) produces a
:class:`Preconditioner` pytree whose APPLY is jit-clean, so the Krylov
``lax.while_loop`` bodies can close over it without callbacks:

- ``jacobi``       — inverse diagonal; the safe default at any order.
- ``block_jacobi`` — the ``blockdiag`` partition idea applied as a
  preconditioner: the ``bs x bs`` diagonal blocks, factorability probed
  by the same ``core/blocked.py`` panel step every dense engine pivots
  with (vmapped ``_panel_factor_jax``; a vanishing ``min_abs_pivot``
  raises typed before the apply ever ships), then inverted explicitly so
  apply is one batched GEMV.
- ``tridiag``      — the ``structure/banded.py`` Thomas factor
  (``solve_tridiag``) over the |i-j| <= 1 crop: the band-factor
  preconditioner for matrices with a dominant tridiagonal core.
- ``ilu0`` / ``ic0`` — zero-fill incomplete LU / Cholesky with fill
  confined to the BLOCK-tridiagonal pattern (the blocked analog of
  scalar ILU(0)): crop to blocks |I - J| <= 1, compensate each dropped
  entry's magnitude onto the diagonal (keeps dominance, so the
  incomplete factor stays nonsingular on the certified inputs this
  plane routes), then run the block-tridiagonal Schur recurrence
  ``S_I = D_I - E_I S_{I-1}^{-1} F_{I-1}`` — each ``S_I`` probed by the
  ``core/blocked.py`` panel step exactly like ``block_jacobi``.  Apply
  is the block forward/back substitution as two ``lax.scan`` sweeps:
  O(n * bs) work and memory.  ``ic0`` is the symmetric-certified
  variant: it additionally demands the Gershgorin SPD certificate
  (typed ``StructureMismatchError`` otherwise), and its recurrence
  preserves symmetry because ``E_I = F_I^T``.

Block size defaults to ``tune.space.SPARSE_BLOCK_SEED``; the "sparse"
tune op sweeps it.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from gauss_tpu.sparse.csr import CsrMatrix
from gauss_tpu.structure.detect import StructureMismatchError

# Block size the block-Jacobi / block-incomplete factors partition on
# (gauss_tpu.tune.space seed; the "sparse" op sweeps it).
from gauss_tpu.tune.space import SPARSE_BLOCK_SEED

__all__ = ["Preconditioner", "build_preconditioner", "apply_precond",
           "PRECOND_KINDS"]

PRECOND_KINDS = ("none", "jacobi", "block_jacobi", "tridiag", "ilu0", "ic0")

_TINY = 1e-300


class Preconditioner:
    """``M^{-1}``-apply state: ``kind`` + static ``meta`` ints are pytree
    aux data (part of the jit cache key), ``arrays`` are traced leaves."""

    def __init__(self, kind: str, meta: Tuple[int, ...], arrays: tuple):
        self.kind = kind
        self.meta = meta
        self.arrays = arrays

    def tree_flatten(self):
        return self.arrays, (self.kind, self.meta)

    @classmethod
    def tree_unflatten(cls, aux, arrays):
        kind, meta = aux
        return cls(kind, meta, tuple(arrays))

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"Preconditioner(kind={self.kind!r}, meta={self.meta})"


def _register_pytree():
    import jax

    jax.tree_util.register_pytree_node_class(Preconditioner)


_register_pytree()


def _block_stacks(a: CsrMatrix, bs: int):
    """Crop the CSR stream to the block-tridiagonal pattern: returns
    (diag, sub, sup) stacks of shape (nb, bs, bs) plus the per-row
    absolute mass of the DROPPED entries (|I - J| >= 2) for diagonal
    compensation. Padding rows of the last partial block carry an
    identity diagonal."""
    nb = -(-a.n // bs)
    rows, cols, vals = a.coo()
    rb, cb = rows // bs, cols.astype(np.int64) // bs
    lr, lc = (rows - rb * bs).astype(np.int64), (cols.astype(np.int64) - cb * bs)

    diag = np.zeros((nb, bs, bs), dtype=np.float64)
    sub = np.zeros((nb, bs, bs), dtype=np.float64)   # sub[i] = block (i, i-1)
    sup = np.zeros((nb, bs, bs), dtype=np.float64)   # sup[i] = block (i, i+1)
    dropped = np.zeros(a.n, dtype=np.float64)

    on = rb == cb
    diag[rb[on], lr[on], lc[on]] = vals[on]
    lo = rb == cb + 1
    sub[rb[lo], lr[lo], lc[lo]] = vals[lo]
    hi = cb == rb + 1
    sup[rb[hi], lr[hi], lc[hi]] = vals[hi]
    far = np.abs(rb - cb) >= 2
    np.add.at(dropped, rows[far], np.abs(vals[far]))

    pad = nb * bs - a.n
    if pad:
        tail = np.arange(bs - pad, bs)
        diag[nb - 1, tail, tail] = 1.0
    return diag, sub, sup, dropped


def _panel_probe(blocks: np.ndarray, kind: str) -> None:
    """Certify every block factors: run the ``core/blocked.py`` panel
    step (single source of the pivot/NaN-as-singular policy) over the
    stack and raise typed on a vanishing pivot."""
    import jax
    import jax.numpy as jnp

    from gauss_tpu.core.blocked import _panel_factor_jax

    _, _, minpiv = jax.vmap(
        lambda blk: _panel_factor_jax(blk, 0, zero_pivot_safe=True)
    )(jnp.asarray(blocks))
    worst = float(np.asarray(minpiv).min())
    if not worst > 0.0:
        raise StructureMismatchError(
            f"{kind} preconditioner: a diagonal block is singular "
            f"(panel-step min |pivot| = {worst}); the operand does not "
            "support this partition"
        )


def build_preconditioner(
    a: CsrMatrix, kind: str = "jacobi", *, block: int | None = None
) -> Preconditioner:
    """Stage ``M^{-1}`` for ``a``. ``block`` sizes the block_jacobi /
    ilu0 / ic0 partitions (default ``SPARSE_BLOCK_SEED``)."""
    import jax.numpy as jnp

    if kind not in PRECOND_KINDS:
        raise ValueError(f"unknown preconditioner {kind!r}; one of {PRECOND_KINDS}")
    if kind == "none":
        return Preconditioner("none", (a.n,), ())

    if kind == "jacobi":
        d = a.diagonal()
        inv = np.where(np.abs(d) > _TINY, 1.0 / np.where(d == 0.0, 1.0, d), 1.0)
        return Preconditioner("jacobi", (a.n,), (jnp.asarray(inv),))

    if kind == "tridiag":
        rows, cols, vals = a.coo()
        dl = np.zeros(a.n)
        d = np.zeros(a.n)
        du = np.zeros(a.n)
        delta = cols.astype(np.int64) - rows
        d[rows[delta == 0]] = vals[delta == 0]
        dl[rows[delta == -1]] = vals[delta == -1]
        du[rows[delta == 1]] = vals[delta == 1]
        d = np.where(np.abs(d) > _TINY, d, 1.0)
        return Preconditioner(
            "tridiag", (a.n,), (jnp.asarray(dl), jnp.asarray(d), jnp.asarray(du))
        )

    bs = int(block or SPARSE_BLOCK_SEED)
    bs = max(1, min(bs, a.n))
    nb = -(-a.n // bs)
    diag, sub, sup, dropped = _block_stacks(a, bs)

    if kind == "block_jacobi":
        _panel_probe(diag, kind)
        sinv = np.linalg.inv(diag)
        return Preconditioner(
            "block_jacobi", (a.n, bs, nb), (jnp.asarray(sinv),)
        )

    # ilu0 / ic0: block-tridiagonal incomplete factorization.
    if kind == "ic0" and not a.gershgorin_spd():
        raise StructureMismatchError(
            "ic0 preconditioner requires the Gershgorin SPD certificate "
            "(symmetric + strictly dominant positive diagonal); use ilu0 "
            "for general systems"
        )
    # Dropped-entry compensation: fold each row's discarded off-pattern
    # magnitude onto its diagonal — dominance is preserved, so every
    # Schur block below stays invertible on certified inputs.
    comp = np.zeros(nb * bs, dtype=np.float64)
    comp[: a.n] = dropped
    idx = np.arange(bs)
    diag = diag.copy()
    dd = diag[:, idx, idx]
    # Push the diagonal AWAY from zero (sign-aware) so negative-diagonal
    # dominant rows keep their dominance too.
    diag[:, idx, idx] = dd + np.where(dd < 0.0, -1.0, 1.0) * comp.reshape(nb, bs)

    s = np.empty_like(diag)
    sinv = np.empty_like(diag)
    s[0] = diag[0]
    _panel_probe(s[0:1], kind)
    sinv[0] = np.linalg.inv(s[0])
    for i in range(1, nb):
        s[i] = diag[i] - sub[i] @ sinv[i - 1] @ sup[i - 1]
        sinv[i] = np.linalg.inv(s[i])
    _panel_probe(s, kind)
    if not np.isfinite(sinv).all():
        raise StructureMismatchError(
            f"{kind} preconditioner: non-finite incomplete factor"
        )
    return Preconditioner(
        kind, (a.n, bs, nb), (jnp.asarray(sinv), jnp.asarray(sub), jnp.asarray(sup))
    )


def apply_precond(prec, r):
    """``z = M^{-1} r`` — trace-time dispatch on the static ``kind`` so
    every branch lowers to a callback-free jaxpr. ``r`` is (n,)."""
    import jax.numpy as jnp
    from jax import lax

    if prec is None or prec.kind == "none":
        return r
    if prec.kind == "jacobi":
        (inv_d,) = prec.arrays
        return inv_d * r
    if prec.kind == "tridiag":
        from gauss_tpu.structure.banded import solve_tridiag

        dl, d, du = prec.arrays
        return solve_tridiag(dl, d, du, r)

    n, bs, nb = prec.meta
    pad = nb * bs - n
    rb = jnp.pad(r, (0, pad)).reshape(nb, bs) if pad else r.reshape(nb, bs)

    if prec.kind == "block_jacobi":
        (sinv,) = prec.arrays
        z = jnp.einsum("nij,nj->ni", sinv, rb).reshape(-1)
        return z[:n] if pad else z

    # ilu0 / ic0: block forward sweep y_I = S_I^{-1}(r_I - E_I y_{I-1}),
    # then back sweep z_I = y_I - S_I^{-1} F_I z_{I+1}.
    sinv, sub, sup = prec.arrays

    def fwd(y_prev, inp):
        sinv_i, e_i, r_i = inp
        y = sinv_i @ (r_i - e_i @ y_prev)
        return y, y

    _, ys = lax.scan(fwd, jnp.zeros(bs, rb.dtype), (sinv, sub, rb))

    def bwd(z_next, inp):
        sinv_i, f_i, y_i = inp
        z = y_i - sinv_i @ (f_i @ z_next)
        return z, z

    _, zs = lax.scan(
        bwd, jnp.zeros(bs, rb.dtype), (sinv, sup, ys), reverse=True
    )
    z = zs.reshape(-1)
    return z[:n] if pad else z
