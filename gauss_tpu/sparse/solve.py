"""``solve_sparse`` — the sparse plane's host driver: method selection,
the 1e-4 verify, and the ``sparse_solve`` observability event.

Method routing mirrors the dense router's certify-then-demote shape:

- ``method="auto"`` tries CG first iff the operand carries the
  Gershgorin SPD certificate (the proof, not a heuristic), then falls
  through to GMRES(restart) and BiCGStab on stagnation; the LAST typed
  :class:`~gauss_tpu.sparse.krylov.IterativeStagnationError` propagates
  when every method stalls — the recovery ladder's signal to densify.
- an explicit method runs exactly that solver (CG still demands the
  certificate — typed ``NotSPDError`` otherwise).

Every attempt emits a ``sparse_solve`` event (docs/OBSERVABILITY.md)
carrying the iteration count and a downsampled residual curve, which
``obs.summarize`` folds into the sparse section and
``gauss_tpu/sparse/check.py`` regress-feeds (``kind: sparse_solve``).
"""

from __future__ import annotations

import time
from typing import Optional, Sequence

import numpy as np

from gauss_tpu import obs
from gauss_tpu.sparse.csr import CsrMatrix
from gauss_tpu.sparse.krylov import (
    DEFAULT_MAXITER,
    DEFAULT_TOL,
    IterativeStagnationError,
    SparseSolveResult,
    solve_bicgstab,
    solve_cg,
    solve_gmres,
)

__all__ = ["solve_sparse"]

#: residual-curve points kept on the event (downsampled; full curves ride
#: the SparseSolveResult, not the telemetry stream).
_CURVE_POINTS = 33

_SOLVERS = {"cg": solve_cg, "gmres": solve_gmres, "bicgstab": solve_bicgstab}


def _downsample(curve: np.ndarray, points: int = _CURVE_POINTS) -> list:
    curve = np.asarray(curve, dtype=np.float64)
    if curve.size > points:
        idx = np.linspace(0, curve.size - 1, points).round().astype(int)
        curve = curve[np.unique(idx)]
    return [float(f"{v:.6g}") for v in curve]


def solve_sparse(
    a,
    b,
    *,
    method: str = "auto",
    precond: str = "auto",
    gate: float = DEFAULT_TOL,
    restart: Optional[int] = None,
    maxiter: int = DEFAULT_MAXITER,
    block: Optional[int] = None,
    x0=None,
) -> SparseSolveResult:
    """Solve ``a @ x = b`` iteratively; ``a`` is a :class:`CsrMatrix`
    (a small dense ndarray is converted — the recovery-ladder rungs pass
    dense operands).  Never allocates an n x n buffer for CSR input."""
    if not isinstance(a, CsrMatrix):
        a = CsrMatrix.from_dense(np.asarray(a))
    certified = a.gershgorin_spd()
    if precond == "auto":
        precond = "jacobi"
    if method == "auto":
        methods: Sequence[str] = (
            ("cg", "gmres", "bicgstab") if certified else ("gmres", "bicgstab")
        )
    else:
        if method not in _SOLVERS:
            raise ValueError(
                f"unknown sparse method {method!r}; one of "
                f"{sorted(_SOLVERS)} or 'auto'"
            )
        methods = (method,)

    last_err: Optional[IterativeStagnationError] = None
    for m in methods:
        kwargs = dict(
            precond=precond, block=block, tol=gate, maxiter=maxiter, x0=x0
        )
        if m == "gmres" and restart is not None:
            kwargs["restart"] = restart
        t0 = time.perf_counter()
        try:
            res = _SOLVERS[m](a, b, **kwargs)
        except IterativeStagnationError as e:
            last_err = e
            obs.counter("sparse.stagnations")
            _emit(a, m, precond, e.result, time.perf_counter() - t0,
                  certified)
            continue
        obs.counter("sparse.solves")
        _emit(a, m, precond, res, time.perf_counter() - t0, certified)
        return res
    assert last_err is not None
    raise last_err


def _emit(a, method, precond, res, wall_s, certified):
    if res is None:
        return
    obs.emit(
        "sparse_solve",
        n=a.n,
        nnz=a.nnz,
        density=round(a.density, 8),
        certified_spd=certified,
        method=method,
        precond=res.precond if res.precond else precond,
        iterations=res.iterations,
        converged=res.converged,
        rel_residual=float(res.rel_residual),
        residuals=_downsample(res.residuals),
        wall_s=round(wall_s, 6),
    )
