"""solve_resilient: health-gated solves with an explicit escalation ladder.

The obs health monitors (finite / min-pivot / residual) so far only record
trouble; this module ACTS on it. Every candidate solution is gated on the
same three monitors ``obs.health`` records, and a failed gate escalates
along an explicit recovery ladder instead of returning a wrong answer or
crashing:

    rung 0  primary engine        blocked f32 factor + host-f64 refinement
                                  (or the rank-1 oracle engine); with
                                  ``abft=True`` this is the CHECKSUM-
                                  CARRYING form (gauss_tpu.resilience
                                  .abft): silent data corruption is
                                  detected within one panel group and
                                  REPLAYED in place from the last-good
                                  carry (the localized replay rung —
                                  emitted as ``rung="abft_replay"``
                                  recovery events), and only a replay
                                  failure (persistent corruption, typed
                                  ``SDCUnrecoverableError``) escalates to
                                  the rungs below
    rung 1  pivot_safe            re-factor with ``zero_pivot_safe``
                                  pivoting (a corrupted or near-singular
                                  system factors to a FINITE factor the
                                  residual gate can judge) + refinement
    rung 2  ds_refine             double-single on-device refinement
                                  (core.dsfloat — the Carson & Higham-style
                                  mixed-precision rung, cf. PAPERS.md)
    rung 3  alternate engine      the other engine (blocked <-> rank-1):
                                  survives a fault pinned to one engine's
                                  code path
    rung 4  numpy_f64             host LAPACK in float64 — always available,
                                  the serving layer's degraded lane

Each escalation emits an obs ``recovery`` event (trigger, rung, attempt,
outcome), so the summarizer's resilience section and the chaos campaign
count recoveries from the stream. When the caller runs under an
``obs.trace_context`` (the serve worker wraps its recovery lane in the
request's trace), every rung event is additionally stamped with that
``trace`` id — the ladder shows up inside the request's span tree
(``gauss_tpu.obs.requesttrace``) with no parameter threading here. Only when every rung has failed does a
typed :class:`UnrecoverableSolveError` surface — the invariant the chaos
campaign asserts is exactly "verified solution or this error, never a
silent wrong answer".

A healthy solve pays one rung-0 solve plus the gate's O(n^2) host residual
(which the refined solvers compute anyway) and emits nothing.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from gauss_tpu import obs
from gauss_tpu.verify import checks

#: relative-residual acceptance bar (the reference EPSILON, BASELINE.json)
DEFAULT_GATE = 1e-4

ENGINES = ("blocked", "rank1")

def default_rungs(engine: str = "blocked",
                  abft: bool = False) -> Tuple[str, ...]:
    """The ladder's rung names in escalation order for a primary engine.

    ``abft=True`` swaps the blocked rung 0 for its checksum-carrying form
    (in-rung detect/localize/replay; see gauss_tpu.resilience.abft) —
    the full ladder below it is unchanged, so replay failure escalates
    through exactly the pre-existing chain."""
    if engine not in ENGINES:
        raise ValueError(f"unknown engine {engine!r}; options: {ENGINES}")
    alternate = "rank1" if engine == "blocked" else "blocked"
    base = (engine, "pivot_safe", "ds_refine", alternate, "numpy_f64")
    if abft:
        # PREPEND the checksum-carrying rung: replay failure (persistent
        # corruption) escalates to the EXISTING full ladder, unchanged.
        return ("abft",) + base
    return base


class UnrecoverableSolveError(RuntimeError):
    """The ladder is exhausted: every rung failed its gate or raised.

    ``trigger``: the last rung's failure reason; ``attempts``: the
    (rung, trigger) history — what the obs stream also recorded.
    """

    def __init__(self, message: str, trigger: Optional[str] = None,
                 attempts: Optional[List[Tuple[str, str]]] = None):
        super().__init__(message)
        self.trigger = trigger
        self.attempts = list(attempts or ())


class SingularSystemError(UnrecoverableSolveError):
    """The system is exactly singular (rank-deficient): a VERDICT about
    the operands, not a fault in any engine. Raised by the numpy_f64 rung
    when host LAPACK reports ``LinAlgError`` — the ground-truth rung has
    spoken, so the ladder re-raises immediately instead of burning the
    remaining rungs on a system no factorization can solve. The serving
    layer maps this to ``STATUS_POISON`` (a typed reject, not a failure)."""

    def __init__(self, message: str,
                 attempts: Optional[List[Tuple[str, str]]] = None):
        super().__init__(message, trigger="singular_matrix",
                         attempts=attempts)


@dataclasses.dataclass
class ResilientResult:
    """A gated solve: the solution plus how hard the ladder worked for it."""

    x: np.ndarray
    rung: str                  # the rung that produced the accepted solution
    rung_index: int            # 0 = healthy first try
    attempts: int              # rungs tried (1 = no escalation)
    rel_residual: float
    escalations: List[Tuple[str, str]]  # (rung, trigger) of each failure
    #: ABFT accounting when an abft rung ran (gauss_tpu.resilience.abft
    #: report as a dict: detections / replays / escalated / localization);
    #: None on non-ABFT ladders and on ladders whose abft rung never saw a
    #: checksum mismatch is still a populated dict with detections == 0.
    sdc: Optional[dict] = None

    @property
    def recovered(self) -> bool:
        return self.rung_index > 0

    @property
    def sdc_detected(self) -> bool:
        return bool(self.sdc and self.sdc.get("detections"))


def _gate(a64: np.ndarray, b64: np.ndarray, x, factors=None,
          gate: float = DEFAULT_GATE) -> Tuple[bool, str, float]:
    """The health monitors as an accept/reject decision: returns
    ``(ok, trigger, rel_residual)``. Order matters — a NaN solution must
    report ``nonfinite``, not a meaningless residual."""
    x = np.asarray(x, dtype=np.float64)
    if not np.isfinite(x).all():
        return False, "nonfinite_solution", float("inf")
    if factors is not None:
        mp = getattr(factors, "min_abs_pivot", None)
        if mp is not None:
            mp = float(np.asarray(mp))
            if not mp > 0.0:  # 0 (singular) and NaN both fail
                return False, "zero_pivot", float("inf")
    rel = checks.residual_norm(a64, x, b64, relative=True)
    if not rel <= gate:
        return False, "residual", rel
    return True, "", rel


def _refine_host(fac, a64, b64, x, iters: int):
    """Classical host-f64 iterative refinement through existing factors."""
    import jax.numpy as jnp

    from gauss_tpu.core import blocked

    x = np.asarray(x, dtype=np.float64)
    for _ in range(iters):
        r = b64 - a64 @ x
        d = np.asarray(blocked.lu_solve(fac, jnp.asarray(r, jnp.float32)),
                       dtype=np.float64)
        x = x + d
    return x


def _rung_blocked(a64, b64, panel, iters):
    from gauss_tpu.core import blocked

    x, fac = blocked.solve_refined(a64, b64, panel=panel, iters=iters)
    return x, fac


def _rung_lowered(a64, b64, panel, iters):
    """Mixed-precision rung 0 (core.lowered): the tuned (dtype,
    refine_steps) pair — bf16 or bf16x3 MXU storage refined back to the
    gate — with its OWN deterministic dtype demotion inside the rung; a
    ladder-visible failure (typed PrecisionNotConvergedError after even
    float32 missed) escalates to the pre-existing f32 chain below, the
    same shape as a structure mistag."""
    from gauss_tpu.core import lowered

    x, fac, _info = lowered.solve_lowered_auto(a64, b64, panel=panel)
    return x, fac


def _rung_pivot_safe(a64, b64, panel, iters):
    import jax.numpy as jnp

    from gauss_tpu.core import blocked

    fac = blocked.lu_factor_blocked(jnp.asarray(a64, jnp.float32),
                                    panel=panel, zero_pivot_safe=True)
    x = np.asarray(blocked.lu_solve(fac, jnp.asarray(b64, jnp.float32)),
                   dtype=np.float64)
    return _refine_host(fac, a64, b64, x, iters), fac


def _rung_ds(a64, b64, panel, iters):
    from gauss_tpu.core import dsfloat

    x, fac = dsfloat.solve_ds(a64, b64, panel=panel)
    return np.asarray(x, dtype=np.float64), fac


def _rung_rank1(a64, b64, panel, iters):
    import jax.numpy as jnp

    from gauss_tpu.core import gauss

    a32 = jnp.asarray(a64, jnp.float32)
    if b64.ndim == 1:
        x = np.asarray(gauss.gauss_solve(a32, jnp.asarray(b64, jnp.float32)),
                       dtype=np.float64)
    else:
        # The rank-1 oracle solves one RHS at a time; k is small in practice
        # (the serve ladder caps nrhs buckets) and this is a recovery rung,
        # not a hot path.
        cols = [np.asarray(gauss.gauss_solve(
            a32, jnp.asarray(b64[:, j], jnp.float32)), dtype=np.float64)
            for j in range(b64.shape[1])]
        x = np.stack(cols, axis=1)
    return x, None


def _rung_numpy(a64, b64, panel, iters):
    try:
        return np.linalg.solve(a64, b64), None
    except np.linalg.LinAlgError as e:
        # Host LAPACK is the ground-truth rung: its LinAlgError means the
        # system is EXACTLY singular, a verdict about the operands that no
        # other rung can overturn. Surface it typed so the ladder (and the
        # serving layer's STATUS_POISON mapping) can short-circuit instead
        # of exhausting into a generic unrecoverable error.
        raise SingularSystemError(
            f"exactly singular system: host LAPACK reports {e}") from e


def _rung_outofcore(a64, b64, panel, iters):
    """Host-streamed rung (gauss_tpu.outofcore): only the active panel
    group plus a bounded tile window live on device — the serving layer's
    giant-request lane. An ABFT-detected corruption or admission failure
    raises typed and the ladder escalates (numpy_f64 is the usual tail)."""
    from gauss_tpu import outofcore

    return outofcore.solve_outofcore(a64, b64, panel=panel,
                                     iters=max(2, iters)), None


def _rung_abft(a64, b64, panel, iters):
    """Checksum-carrying blocked LU with in-rung detect/localize/replay
    (gauss_tpu.resilience.abft). A transient mid-solve corruption never
    surfaces here at all — the replay repairs it inside the rung, bit-
    identical to an uninterrupted run; persistent corruption raises the
    typed SDCUnrecoverableError, which the ladder records as
    ``exception:SDCUnrecoverableError`` and escalates past."""
    from gauss_tpu.resilience import abft

    x, fac, _report = abft.solve_lu_abft(a64, b64, panel=panel, iters=iters)
    return x, fac


def _rung_abft_chol(a64, b64, panel, iters):
    """The SPD sibling: checksum-carrying blocked Cholesky with replay.
    Non-SPD input raises the same typed NotSPDError the plain cholesky
    rung does — the structured demotion contract is unchanged."""
    from gauss_tpu.resilience import abft

    x, fac, _report = abft.solve_chol_abft(a64, b64, panel=panel,
                                           iters=iters)
    return x, fac


def _rung_cholesky(a64, b64, panel, iters):
    """SPD rung: blocked Cholesky + host-f64 refinement. A non-SPD operand
    raises the typed NotSPDError, which the ladder records as
    ``exception:NotSPDError`` and escalates past — the structured ->
    general-LU demotion in action."""
    from gauss_tpu.structure import cholesky

    return cholesky.solve_spd_refined(a64, b64, panel=panel, iters=iters)


def _rung_banded(a64, b64, panel, iters):
    """Banded rung: O(n*b^2) band solve + refinement; a matrix whose true
    bandwidth busts the band limit raises StructureMismatchError and the
    ladder demotes."""
    from gauss_tpu.structure import banded

    return banded.solve_banded_refined(a64, b64, iters=iters), None


def _rung_blockdiag(a64, b64, panel, iters):
    """Block-diagonal rung: vmap-batched small-block solves; an
    unpartitionable matrix raises StructureMismatchError and the ladder
    demotes."""
    from gauss_tpu.structure import blockdiag

    return blockdiag.solve_blockdiag(a64, b64, refine_steps=iters), None


def _rung_cg(a64, b64, panel, iters):
    """Sparse head rung: conjugate gradients on the CSR form of the
    operand (gauss_tpu.sparse). An uncertified operand raises the typed
    NotSPDError before any iteration, stagnation raises the typed
    IterativeStagnationError — both demote to the general-system Krylov
    rungs below, then the dense chain."""
    from gauss_tpu.sparse import solve as _sparse

    return _sparse.solve_sparse(a64, b64, method="cg").x, None


def _rung_gmres(a64, b64, panel, iters):
    """General-system Krylov rung: GMRES(restart); stagnation raises
    typed and the ladder keeps demoting (bicgstab, then dense)."""
    from gauss_tpu.sparse import solve as _sparse

    return _sparse.solve_sparse(a64, b64, method="gmres").x, None


def _rung_bicgstab(a64, b64, panel, iters):
    """Last iterative rung before the dense chain: BiCGStab."""
    from gauss_tpu.sparse import solve as _sparse

    return _sparse.solve_sparse(a64, b64, method="bicgstab").x, None


_RUNG_FNS: Dict[str, Callable] = {
    "blocked": _rung_blocked,
    "lowered": _rung_lowered,
    "pivot_safe": _rung_pivot_safe,
    "ds_refine": _rung_ds,
    "rank1": _rung_rank1,
    "numpy_f64": _rung_numpy,
    "cholesky": _rung_cholesky,
    "banded": _rung_banded,
    "blockdiag": _rung_blockdiag,
    "abft": _rung_abft,
    "abft_chol": _rung_abft_chol,
    "outofcore": _rung_outofcore,
    "cg": _rung_cg,
    "gmres": _rung_gmres,
    "bicgstab": _rung_bicgstab,
}

#: rungs backed by the checksum-carrying factorizations — the ladder
#: clears/collects the ABFT report around these.
_ABFT_RUNGS = ("abft", "abft_chol")

#: ladder head per structure tag; every structured ladder then demotes
#: "blocked" (general LU) -> pivot_safe -> ds_refine -> numpy_f64, so a
#: MISCLASSIFIED matrix — wrong tag, near-SPD that fails the Cholesky
#: attempt, permuted "block-diagonal" — still ends 1e-4-verified or typed,
#: exactly like a corrupted dense solve.
_STRUCTURE_HEADS: Dict[str, Tuple[str, ...]] = {
    "spd": ("cholesky",),
    "banded": ("banded",),
    "blockdiag": ("blockdiag",),
    "dense": (),
    # The sparse ladder is three Krylov rungs deep before densifying:
    # CG (certified-SPD only — typed NotSPDError demotes instantly on
    # general systems), then GMRES(restart), then BiCGStab; stagnation
    # at each raises the typed IterativeStagnationError. Only past all
    # three does the operand densify into the dense chain — the route's
    # whole point is that rung 0-2 never allocate n^2.
    "sparse": ("cg", "gmres", "bicgstab"),
}


def structured_rungs(tag: str, abft: bool = False,
                     lowered: bool = False) -> Tuple[str, ...]:
    """The escalation ladder for a structure tag: the structured engine
    first, then the general-LU demotion rungs.

    ``abft=True`` PREPENDS the checksum-carrying engine form where one
    exists (``abft_chol`` ahead of the spd ladder, ``abft`` ahead of the
    others' general-LU rung) — the existing demotion chain is unchanged,
    so replay failure escalates through exactly the pre-ABFT ladder.

    ``lowered=True`` (dense tag only — the structured engines' cost
    profiles are the point of their routes, and the lowered path is an
    LU) prepends the mixed-precision rung (core.lowered): the tuned
    bf16/bf16x3 pair refined back to the gate, demoting typed to exactly
    the pre-existing f32 chain when refinement cannot converge — the
    router (``structure.router.solve_auto``) sets this from the tuned
    store consult, so an untuned checkout never changes ladders. The two
    heads are mutually exclusive by construction: the ABFT checksum rider
    is defined against f32 math (core.blocked), so ``abft`` wins and
    ``lowered`` is ignored when both are requested."""
    if tag not in _STRUCTURE_HEADS:
        raise ValueError(f"unknown structure tag {tag!r}; options: "
                         f"{sorted(_STRUCTURE_HEADS)}")
    head = _STRUCTURE_HEADS[tag]
    base = head + ("blocked", "pivot_safe", "ds_refine", "numpy_f64")
    if abft and tag == "spd":
        return ("abft_chol",) + base
    if abft and tag == "dense":
        return ("abft",) + base
    if lowered and tag == "dense":
        return ("lowered",) + base
    # banded / blockdiag engines have no checksum-carrying form; their
    # O(n*b^2) / batched-small-block cost profiles are the point of the
    # route, so an ABFT-LU head would defeat the routing — the structured
    # ladder stays as-is and the 1e-4 gate remains their backstop.
    return base


def solve_resilient(a, b, *, gate: float = DEFAULT_GATE,
                    engine: str = "blocked",
                    rungs: Optional[Sequence[str]] = None,
                    panel: Optional[int] = None,
                    refine_iters: int = 2,
                    abft: bool = False) -> ResilientResult:
    """Solve ``a @ x = b`` with health gating and ladder escalation.

    Returns a :class:`ResilientResult` (``.x`` float64, plus which rung
    served it). Raises :class:`UnrecoverableSolveError` when every rung
    fails — and immediately for non-finite INPUT operands, which no rung
    can recover — and plain ``ValueError`` for malformed requests (wrong
    shapes, unknown rung names): those are programming errors, not faults.

    ``rungs`` overrides the ladder (names from ``_RUNG_FNS``); the serving
    layer's degraded lane passes ``("numpy_f64", "rank1")`` — same gating,
    same events, same typed error, different rung order.

    ``abft=True`` protects the solve against SILENT DATA CORRUPTION
    mid-factorization: rung 0 becomes the checksum-carrying form
    (gauss_tpu.resilience.abft), which detects a mismatch within one
    panel group, localizes it, and replays just the affected group from
    the last verified carry — bit-identical to an uninterrupted run —
    before the ladder below is ever consulted. ``.sdc`` on the result
    carries the detection/replay accounting (``.sdc_detected`` is the
    per-request serving tag).
    """
    a64 = np.asarray(a, dtype=np.float64)
    b64 = np.asarray(b, dtype=np.float64)
    n = a64.shape[0]
    if a64.shape != (n, n):
        raise ValueError(f"expected square matrix, got {a64.shape}")
    if b64.shape[:1] != (n,) or b64.ndim > 2:
        raise ValueError(f"b must be (n,) or (n, k) with n={n}, "
                         f"got {b64.shape}")
    if not (np.isfinite(a64).all() and np.isfinite(b64).all()):
        # A non-finite operand is not a recoverable fault — there is no
        # well-posed system behind it for ANY rung to solve. Typed, so the
        # chaos invariant (recovered or typed error) holds for input
        # corruption too.
        obs.counter("resilience.unrecoverable")
        obs.emit("recovery", trigger="nonfinite_input", rung="input",
                 attempt=0, outcome="unrecoverable")
        raise UnrecoverableSolveError(
            "non-finite entries in the input operands (NaN/Inf); no "
            "recovery rung can restore a system that was never well-posed",
            trigger="nonfinite_input")
    ladder = (tuple(rungs) if rungs is not None
              else default_rungs(engine, abft=abft))
    unknown = [r for r in ladder if r not in _RUNG_FNS]
    if unknown:
        raise ValueError(f"unknown ladder rung(s) {unknown}; options: "
                         f"{sorted(_RUNG_FNS)}")
    has_abft = any(r in _ABFT_RUNGS for r in ladder)
    sdc_reports: List[dict] = []

    def _collect_sdc(rung: str) -> None:
        """Stash the just-finished abft rung's report — every later abft
        rung overwrites the module's thread-local, so the detections of a
        FAILED abft rung (the interesting ones) must be captured here."""
        if rung not in _ABFT_RUNGS:
            return
        from gauss_tpu.resilience import abft as _abft

        rep = _abft.last_report()
        if rep is not None:
            sdc_reports.append(rep.to_dict())
        _abft.clear_report()

    def _sdc_info() -> Optional[dict]:
        if not has_abft:
            return None
        if not sdc_reports:
            return None
        if len(sdc_reports) == 1:
            return sdc_reports[0]
        out = dict(sdc_reports[-1])
        out["engine"] = "+".join(r["engine"] for r in sdc_reports)
        for key in ("detections", "replays"):
            out[key] = sum(r[key] for r in sdc_reports)
        out["escalated"] = any(r["escalated"] for r in sdc_reports)
        out["max_err"] = max(r["max_err"] for r in sdc_reports)
        for key in ("detect_groups", "detect_cols", "detect_latency_s"):
            out[key] = [v for r in sdc_reports for v in r[key]]
        return out

    if has_abft:
        from gauss_tpu.resilience import abft as _abft

        _abft.clear_report()

    escalations: List[Tuple[str, str]] = []
    for i, rung in enumerate(ladder):
        try:
            x, fac = _RUNG_FNS[rung](a64, b64, panel, refine_iters)
            ok, trigger, rel = _gate(a64, b64, x, factors=fac, gate=gate)
            _collect_sdc(rung)
        except SingularSystemError as e:
            # A singular verdict from the ground-truth rung is terminal for
            # EVERY rung — the system itself is rank-deficient — so re-raise
            # immediately instead of burning the remaining ladder.
            _collect_sdc(rung)
            escalations.append((rung, "singular_matrix"))
            obs.counter("resilience.unrecoverable")
            obs.emit("recovery", trigger="singular_matrix", rung=rung,
                     rung_index=i, attempt=i + 1, outcome="unrecoverable")
            e.attempts = list(escalations)
            raise
        except Exception as e:  # noqa: BLE001 — a rung failing IS the signal
            ok, trigger, rel = False, f"exception:{type(e).__name__}", None
            _collect_sdc(rung)
        if ok:
            if i > 0:
                obs.counter("resilience.recovered")
                obs.emit("recovery", trigger=escalations[-1][1], rung=rung,
                         rung_index=i, attempt=i + 1, outcome="recovered",
                         rel_residual=rel)
            return ResilientResult(x=np.asarray(x, dtype=np.float64),
                                   rung=rung, rung_index=i, attempts=i + 1,
                                   rel_residual=rel,
                                   escalations=escalations,
                                   sdc=_sdc_info())
        escalations.append((rung, trigger))
        if "SDC" in trigger:
            # An SDCDetectedError escalating PAST its rung means repair
            # failed — freeze the flight ring into a post-mortem bundle
            # (no-op unless the serving process armed the trigger).
            try:
                from gauss_tpu.obs import postmortem as _postmortem

                _postmortem.trigger("sdc_detected", rung=rung,
                                    escalation=trigger)
            except Exception:  # pragma: no cover — capture is best-effort
                pass
        obs.counter("resilience.escalations")
        obs.emit("recovery", trigger=trigger, rung=rung, rung_index=i,
                 attempt=i + 1, outcome="escalate",
                 **({"rel_residual": rel} if rel is not None
                    and np.isfinite(rel) else {}))

    obs.counter("resilience.unrecoverable")
    obs.emit("recovery", trigger=escalations[-1][1], rung=ladder[-1],
             attempt=len(ladder), outcome="unrecoverable")
    raise UnrecoverableSolveError(
        f"recovery ladder exhausted after {len(ladder)} rung(s) "
        f"({', '.join(f'{r}: {t}' for r, t in escalations)})",
        trigger=escalations[-1][1], attempts=escalations)
