"""Seeded, deterministic fault injection behind named hook points.

The stack's failure handling (serve retries, the recovery ladder, checkpoint
resume) is only trustworthy if the failures it claims to survive can be
produced ON DEMAND, deterministically, in CI. This module is that switch: a
:class:`FaultPlan` names which hook **sites** misbehave, how (``kind``), how
often (``p``), and how many times (``max_triggers``); hook points threaded
through the stack poll the installed plan and act only when a spec fires.

Hook-site catalog (the call sites live in the named modules; full semantics
in docs/RESILIENCE.md):

    core.blocked.factor     corrupt the factor operand (nan / inf / bitflip
                            of a panel-sized column block, near_zero_pivot)
                            — gauss_tpu.core.blocked factor entry points
    core.gauss.solve        same corruption kinds — the rank-1 oracle engine
    serve.cache.compile     raise a simulated scoped-VMEM/compile failure on
                            executable build — gauss_tpu.serve.cache
    serve.worker.dispatch   delay the serve worker before dispatch (deadline
                            pressure) — gauss_tpu.serve.server
    serve.server.batch      kill the whole serving process (os._exit) at a
                            seeded batch BOUNDARY (kind ``server_kill``;
                            ``skip`` picks the batch) — the crash the
                            write-ahead request journal must recover from —
                            gauss_tpu.serve.server worker loop
    serve.journal.append    tear the journal's live segment MID-RECORD
                            (kind ``journal_torn_write``: a prefix of the
                            record is written, then the process dies —
                            ``param`` in (0,1) picks the tear fraction);
                            recovery must drop the torn tail by
                            construction — gauss_tpu.serve.durable
    dist.multihost.straggler  sleep ``param`` seconds in multihost
                            initialize — gauss_tpu.dist.multihost
    dist.multihost.worker   kill the worker process (os._exit) or stall it
                            forever (sleep until externally killed) after
                            multihost initialize — gauss_tpu.dist.multihost
    checkpoint.group        raise (simulated kill) or os._exit between
                            checkpointed factor groups —
                            gauss_tpu.resilience.checkpoint
    outofcore.group         raise / os._exit between streamed out-of-core
                            factor groups — gauss_tpu.outofcore.stream
    outofcore.tile          corrupt one trailing tile on its way to the
                            device (the abft=True rider's detection
                            surface) — gauss_tpu.outofcore.stream
    fleet.worker.group      kill / stall / raise a supervised fleet worker
                            between sharded-checkpoint groups (``skip``
                            picks the group) — gauss_tpu.resilience
                            .dcheckpoint
    structure.detect        force the structure router's routing tag to
                            ``STRUCTURE_KINDS[int(param)]`` (kind
                            ``mistag``) — proves a lying classifier
                            demotes to general LU instead of shipping a
                            wrong answer — gauss_tpu.structure.router
    abft.lu.group           flip one bit of one element of the ON-DEVICE
    abft.chol.group         factorization carry at a panel-group boundary
                            (kind ``sdc_bitflip``; ``skip`` picks the
                            group, ``param`` > 0 pins the bit index) —
                            the silent-data-corruption stand-in the ABFT
                            checksum invariant must detect, localize, and
                            repair — gauss_tpu.resilience.abft
    abft.matmul             same, against an ABFT matmul's on-device
                            output block (single-element GEMM errors are
                            corrected in place from the row x column
                            checksum intersection) —
                            gauss_tpu.resilience.abft.abft_matmul

Design rules:

- **Off by default, zero hot-path cost.** No plan installed -> every hook is
  one module-global ``is None`` check. Instrumented modules import this
  module at load (stdlib + numpy only — importing it can never pull jax).
- **Deterministic.** Each spec draws from its own ``np.random.Generator``
  seeded from ``(plan.seed, spec.seed, site)``; given the same plan and the
  same call sequence, the same calls trigger and the same bytes corrupt.
- **Observable.** Every trigger emits an obs ``fault`` event (site, kind,
  per-site trigger index) so the summarizer's resilience section and the
  chaos campaign count injections from the same stream everything else uses.
- **Trace-safe.** Corruption helpers act only on concrete host arrays; under
  a jit trace (tracer operands) they are no-ops, so a plan can stay
  installed around jitted pipelines without corrupting compile-time values.

Activation: ``inject.plan(...)`` as a context manager (tests, the chaos
runner), ``install()``/``uninstall()`` for long-lived processes, or the
``GAUSS_FAULTS`` environment variable — parsed and installed at import time,
which is how a *worker subprocess* (multihost, checkpoint kill tests)
inherits a fault plan it cannot be handed through an API. Accepted forms::

    GAUSS_FAULTS='{"seed": 7, "faults": [{"site": "core.blocked.factor",
                                          "kind": "nan", "p": 1.0,
                                          "max_triggers": 1}]}'
    GAUSS_FAULTS='core.blocked.factor=nan:p=0.5:max=2;serve.worker.dispatch=delay:param=0.05'
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

ENV_VAR = "GAUSS_FAULTS"

#: kinds that corrupt an operand array
CORRUPT_KINDS = ("nan", "inf", "bitflip", "near_zero_pivot")
#: kinds with dedicated action helpers; ``mistag`` forces the structure
#: router's routing tag to ``STRUCTURE_KINDS[int(param)]`` (see
#: gauss_tpu.structure.router.routed_tag) — the lying-classifier fault;
#: ``sdc_bitflip`` flips one bit of one ON-DEVICE array element at an ABFT
#: panel-group site (the corruption is applied by the owning runner via
#: :func:`poll_sdc` — this module never touches device arrays itself).
#: ``server_kill`` is the serving-process analog of ``kill`` (os._exit at
#: the serve worker's batch-boundary hook — a distinct name so a campaign
#: can aim at the SERVER without also arming worker/fleet kill sites);
#: ``journal_torn_write`` tears the live journal segment mid-record and
#: dies (applied by gauss_tpu.serve.durable via :func:`poll_torn_write` —
#: only the journal knows its own record boundaries).
ACTION_KINDS = ("raise", "compile_fail", "delay", "kill", "stall", "mistag",
                "sdc_bitflip", "server_kill", "journal_torn_write")
KINDS = CORRUPT_KINDS + ACTION_KINDS

#: exit status used by kind="kill" — distinctive, so a harness can tell an
#: injected kill from a real crash.
KILL_EXIT_CODE = 113


class SimulatedFaultError(RuntimeError):
    """An injected failure (kind="raise"). RuntimeError on purpose: the
    serve layer's transient-error heuristic must treat it as retryable,
    exactly like the device hiccups it stands in for."""


class SimulatedCompileError(SimulatedFaultError):
    """An injected executable-build failure (kind="compile_fail"), worded
    like the real Mosaic scoped-VMEM exhaustion it simulates."""


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One fault: where (site), what (kind), how often, how many times.

    ``p``: per-poll trigger probability (1.0 = every eligible poll).
    ``max_triggers``: stop firing after this many triggers (None = forever);
    the default 1 models a transient fault a retry heals.
    ``skip``: let this many eligible polls pass before the first trigger —
    "fail on the Nth visit" (e.g. kill at the second checkpoint group).
    ``param``: kind-specific knob — delay seconds for ``delay``, corruption
    scale for ``near_zero_pivot`` (default 1e-30).
    ``seed``: per-spec RNG stream offset (so two specs at one site differ).
    """

    site: str
    kind: str
    p: float = 1.0
    max_triggers: Optional[int] = 1
    skip: int = 0
    param: float = 0.0
    seed: int = 0

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; options: {KINDS}")
        if not (0.0 <= self.p <= 1.0):
            raise ValueError(f"fault p must be in [0, 1], got {self.p}")


class FaultPlan:
    """An immutable set of :class:`FaultSpec` plus the campaign seed."""

    def __init__(self, specs: Sequence[FaultSpec], seed: int = 0):
        self.specs: Tuple[FaultSpec, ...] = tuple(specs)
        self.seed = int(seed)

    def __repr__(self):
        return f"FaultPlan(seed={self.seed}, specs={list(self.specs)!r})"

    @classmethod
    def parse(cls, text: str) -> "FaultPlan":
        """Parse the JSON or compact ``site=kind:k=v:...;...`` forms."""
        text = text.strip()
        if not text:
            raise ValueError("empty fault plan")
        if text.startswith("{"):
            doc = json.loads(text)
            specs = [FaultSpec(**f) for f in doc.get("faults", ())]
            return cls(specs, seed=int(doc.get("seed", 0)))
        specs = []
        for i, token in enumerate(t for t in text.split(";") if t.strip()):
            head, *opts = token.strip().split(":")
            if "=" not in head:
                raise ValueError(f"fault token {token!r} needs site=kind")
            site, kind = head.split("=", 1)
            kw = dict(site=site.strip(), kind=kind.strip(), seed=i)
            names = {"p": "p", "max": "max_triggers", "skip": "skip",
                     "param": "param", "seed": "seed"}
            for opt in opts:
                if "=" not in opt:
                    raise ValueError(f"bad fault option {opt!r} in {token!r}")
                k, v = opt.split("=", 1)
                if k not in names:
                    raise ValueError(f"unknown fault option {k!r} in {token!r}")
                key = names[k]
                kw[key] = (int(v) if key in ("max_triggers", "skip", "seed")
                           else float(v))
            specs.append(FaultSpec(**kw))
        return cls(specs)

    @classmethod
    def from_env(cls, environ=os.environ) -> Optional["FaultPlan"]:
        text = environ.get(ENV_VAR)
        return cls.parse(text) if text else None


class ActivePlan:
    """Runtime state of an installed plan: per-spec trigger accounting and
    RNG streams. Thread-safe — the serve worker and client threads poll
    concurrently."""

    def __init__(self, plan: FaultPlan):
        self.plan = plan
        self._lock = threading.Lock()
        self._by_site: Dict[str, List[int]] = {}
        for i, sp in enumerate(plan.specs):
            self._by_site.setdefault(sp.site, []).append(i)
        self._rngs = [np.random.default_rng(
            np.random.SeedSequence((plan.seed, sp.seed, _site_key(sp.site))))
            for sp in plan.specs]
        self.polls: Dict[str, int] = {}           # guarded by: self._lock
        self.triggers: List[int] = [0] * len(plan.specs)  # guarded by: self._lock
        self._skips_left: List[int] = [sp.skip for sp in plan.specs]  # guarded by: self._lock

    def poll(self, site: str) -> Optional[FaultSpec]:
        """One hook-point visit: returns the spec that fires, or None. At
        most one spec fires per poll (first eligible in plan order)."""
        idxs = self._by_site.get(site)
        with self._lock:
            self.polls[site] = self.polls.get(site, 0) + 1
            if not idxs:
                return None
            for i in idxs:
                sp = self.plan.specs[i]
                if (sp.max_triggers is not None
                        and self.triggers[i] >= sp.max_triggers):
                    continue
                if sp.p < 1.0 and self._rngs[i].random() >= sp.p:
                    continue
                if self._skips_left[i] > 0:
                    self._skips_left[i] -= 1
                    continue
                self.triggers[i] += 1
                seq = self.triggers[i]
                break
            else:
                return None
        _emit_fault_event(site, sp.kind, seq)
        return sp

    def rng_for(self, spec: FaultSpec) -> np.random.Generator:
        return self._rngs[self.plan.specs.index(spec)]

    def stats(self) -> Dict[str, object]:
        with self._lock:
            by_site: Dict[str, int] = {}
            by_kind: Dict[str, int] = {}
            for sp, n in zip(self.plan.specs, self.triggers):
                if n:
                    by_site[sp.site] = by_site.get(sp.site, 0) + n
                    by_kind[sp.kind] = by_kind.get(sp.kind, 0) + n
            return {"triggered": sum(self.triggers),
                    "by_site": by_site, "by_kind": by_kind,
                    "polls": dict(self.polls)}


def _site_key(site: str) -> int:
    # Stable across processes (hash() is salted; this must not be).
    return int.from_bytes(site.encode()[:8].ljust(8, b"\0"), "big")


def _emit_fault_event(site: str, kind: str, seq: int) -> None:
    try:
        from gauss_tpu import obs

        obs.counter("resilience.faults_injected")
        obs.emit("fault", site=site, kind=kind, seq=seq)
    except Exception:  # pragma: no cover — telemetry must never mask a test
        pass


# The one module global every hook point checks. Installed plans nest via
# the context manager; GAUSS_FAULTS installs one at import (see bottom).
_ACTIVE: Optional[ActivePlan] = None
_install_lock = threading.Lock()


def enabled() -> bool:
    """True when a fault plan is installed (the zero-cost hook guard)."""
    return _ACTIVE is not None


def active() -> Optional[ActivePlan]:
    return _ACTIVE


def install(p: FaultPlan) -> ActivePlan:
    global _ACTIVE
    with _install_lock:
        if _ACTIVE is not None:
            raise RuntimeError("a FaultPlan is already installed; uninstall "
                               "it first (plans do not stack)")
        _ACTIVE = ActivePlan(p)
        return _ACTIVE


def uninstall() -> None:
    global _ACTIVE
    with _install_lock:
        _ACTIVE = None


@contextlib.contextmanager
def plan(p: FaultPlan):
    """Install ``p`` for the duration of the block; yields the ActivePlan
    (its ``stats()`` are how a campaign counts what actually fired)."""
    ap = install(p)
    try:
        yield ap
    finally:
        uninstall()


def poll(site: str) -> Optional[FaultSpec]:
    """Module-level hook: poll the installed plan (None when off)."""
    ap = _ACTIVE
    return ap.poll(site) if ap is not None else None


def _is_concrete(a) -> bool:
    """Concrete host-readable array vs a jit-trace tracer (corrupting a
    tracer is meaningless and would poison the compiled program)."""
    if isinstance(a, np.ndarray):
        return True
    try:
        import jax

        return not isinstance(a, jax.core.Tracer)
    except Exception:  # pragma: no cover
        return False


def corrupt_operand(site: str, a, panel: int = 128):
    """Poll ``site`` and, on trigger, return a corrupted COPY of ``a``
    (else ``a`` unchanged). The corruption kinds model device-memory faults
    at panel granularity:

    - ``nan`` / ``inf``: poison one panel-sized column block (the shape a
      corrupted factor panel would have).
    - ``bitflip``: flip one random bit of one element's mantissa/exponent.
    - ``near_zero_pivot``: scale one column's on-and-below-diagonal entries
      by ``param`` (default 1e-30), so that step's pivot contest can only
      find a vanishing pivot.

    Tracer operands and non-array sites are passed through untouched even
    when the spec fires (the trigger still counts — the fault "happened",
    the program just wasn't at a corruptible boundary).
    """
    ap = _ACTIVE
    if ap is None:
        return a
    if not _is_concrete(a):
        return a
    sp = ap.poll(site)
    if sp is None or sp.kind not in CORRUPT_KINDS:
        return a
    arr = np.array(a, copy=True)
    if arr.ndim < 2 or arr.shape[0] < 1:
        return a
    n = arr.shape[0]
    rng = ap.rng_for(sp)
    if sp.kind in ("nan", "inf"):
        w = min(n, panel)
        c0 = int(rng.integers(0, max(1, arr.shape[1] - w + 1)))
        arr[:, c0:c0 + w] = np.nan if sp.kind == "nan" else np.inf
    elif sp.kind == "bitflip":
        i = int(rng.integers(0, n))
        j = int(rng.integers(0, arr.shape[1]))
        itemsize = arr.dtype.itemsize
        uint = {2: np.uint16, 4: np.uint32, 8: np.uint64}[itemsize]
        bits = np.asarray(arr[i, j]).view(uint)
        bit = int(rng.integers(0, 8 * itemsize))
        arr[i, j] = (bits ^ uint(1 << bit)).view(arr.dtype)
    elif sp.kind == "near_zero_pivot":
        j = int(rng.integers(0, min(n, arr.shape[1])))
        scale = sp.param if sp.param else 1e-30
        arr[j:, j] = arr[j:, j] * scale
    return arr


def maybe_raise(site: str) -> None:
    """Poll ``site``; kinds ``raise``/``compile_fail`` raise their simulated
    error (other kinds at this site are ignored — wrong hook shape)."""
    sp = poll(site)
    if sp is None:
        return
    if sp.kind == "compile_fail":
        raise SimulatedCompileError(
            f"RESOURCE_EXHAUSTED: ran out of memory in memory space vmem "
            f"(simulated scoped-VMEM compile failure injected at {site})")
    if sp.kind == "raise":
        raise SimulatedFaultError(f"injected fault at {site}")


def poll_sdc(site: str):
    """Poll ``site`` for an on-device silent-data-corruption fault (kind
    ``sdc_bitflip``). Returns ``(spec, rng)`` when one fires — the caller
    owns the device array and applies the flip itself (jitted XOR on the
    bitcast element; see gauss_tpu.resilience.abft) — else None. Other
    kinds at the site are ignored (wrong hook shape), matching the other
    ``maybe_*`` helpers; the trigger still counts and emits its ``fault``
    event either way."""
    ap = _ACTIVE
    if ap is None:
        return None
    sp = ap.poll(site)
    if sp is None or sp.kind != "sdc_bitflip":
        return None
    return sp, ap.rng_for(sp)


def poll_torn_write(site: str):
    """Poll ``site`` for a torn journal write (kind ``journal_torn_write``).
    Returns the spec when one fires — the JOURNAL applies the tear itself
    (write a prefix of the record, then die: only it knows its record
    boundaries) — else None. Other kinds at the site are ignored (wrong
    hook shape); the trigger still counts and emits its ``fault`` event."""
    ap = _ACTIVE
    if ap is None:
        return None
    sp = ap.poll(site)
    if sp is None or sp.kind != "journal_torn_write":
        return None
    return sp


def maybe_delay(site: str) -> float:
    """Poll ``site``; kind ``delay`` sleeps ``param`` seconds (straggler /
    deadline-pressure injection). Returns the seconds slept."""
    sp = poll(site)
    if sp is not None and sp.kind == "delay" and sp.param > 0:
        time.sleep(sp.param)
        return sp.param
    return 0.0


def maybe_kill(site: str) -> None:
    """Poll ``site``; kind ``kill`` terminates the process immediately via
    ``os._exit`` (no cleanup, no atexit — the honest SIGKILL stand-in);
    kind ``stall`` sleeps FOREVER (the hung-not-dead worker: the process
    stays alive, its heartbeat goes stale, and only an external kill — the
    fleet supervisor's — ends it), distinct from ``kill`` so watchdog/
    stall-detection paths are testable separately from crash paths; kind
    ``raise`` throws SimulatedFaultError instead (the in-process variant
    tests use where a real exit would take the test runner down)."""
    sp = poll(site)
    if sp is None:
        return
    if sp.kind in ("kill", "server_kill"):
        os._exit(KILL_EXIT_CODE)
    if sp.kind == "stall":
        while True:  # pragma: no cover — only ends by external kill
            time.sleep(3600.0)
    if sp.kind == "raise":
        raise SimulatedFaultError(f"injected worker kill at {site}")


# Environment activation: a worker subprocess (multihost rank, checkpoint
# kill test) inherits its fault plan through GAUSS_FAULTS — installed here
# at import so every hook in the process sees it without any API call.
_env_plan = FaultPlan.from_env()
if _env_plan is not None and _env_plan.specs:
    install(_env_plan)
del _env_plan
