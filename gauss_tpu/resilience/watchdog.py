"""Collective watchdog: deadlines around blocking distributed steps.

A distributed solve blocks in two places: inside a compiled collective
(``jax.block_until_ready`` on a shard_map program whose psum/all_gather is
waiting for a peer) and at the fleet's host-level coordination barriers
(waiting for a peer's checkpoint shard or the coordinator's manifest). When
a peer process is dead or stalled, both waits are INFINITE by default — the
reference MPI engine has exactly this failure mode, and "the job hangs until
an operator notices" is the one outcome a supervised fleet must never allow.

This module turns those infinite waits into a typed
:class:`WorkerLostError` after a configurable deadline:

- :func:`guarded` runs a blocking callable (a compiled distributed solve)
  on a helper thread and bounds the wait. On timeout the caller gets the
  typed error immediately; the stuck computation cannot be cancelled from
  host Python (XLA owns it), so the helper thread is left to die with the
  process — the supervisor's restart, not this process, is the actual
  recovery. With no deadline configured the callable runs inline: zero
  threads, zero cost.
- :func:`wait_for` polls a host-side predicate (a shard file appearing, a
  manifest landing) with the same deadline semantics, invoking an optional
  ``on_tick`` each poll so a worker blocked on a PEER keeps writing its own
  heartbeat — being blocked is not being dead, and the supervisor must be
  able to tell the two apart.

The deadline comes from the ``GAUSS_WATCHDOG_S`` environment variable (how
fleet worker subprocesses inherit it), from the :func:`deadline` context
manager, or per call. Every timeout emits an obs ``watchdog`` event before
raising, so the summarizer's fleet section counts detections from the same
stream everything else uses.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Callable, Optional

ENV_VAR = "GAUSS_WATCHDOG_S"

#: default poll interval for host-side predicate waits
POLL_S = 0.05


class WorkerLostError(RuntimeError):
    """A peer did not show up within the deadline: the collective (or the
    coordination barrier standing in for one) can never complete from this
    process's point of view. ``site`` names the blocked operation;
    ``deadline_s`` is the bound that expired."""

    def __init__(self, message: str, site: str = "?",
                 deadline_s: Optional[float] = None):
        super().__init__(message)
        self.site = site
        self.deadline_s = deadline_s


# Process-wide configured deadline (None = watchdog off). Set once from the
# environment at import — fleet workers inherit it that way — and scoped by
# the deadline() context manager for in-process use.
_DEADLINE: Optional[float] = None
_lock = threading.Lock()


def _env_deadline() -> Optional[float]:
    raw = os.environ.get(ENV_VAR)
    if not raw:
        return None
    try:
        v = float(raw)
    except ValueError:
        return None
    return v if v > 0 else None


def configured_deadline() -> Optional[float]:
    """The active deadline in seconds, or None when the watchdog is off."""
    return _DEADLINE


def enabled() -> bool:
    return _DEADLINE is not None


@contextlib.contextmanager
def deadline(seconds: Optional[float]):
    """Scope a watchdog deadline (None disables) for the block."""
    global _DEADLINE
    with _lock:
        prev = _DEADLINE
        _DEADLINE = float(seconds) if seconds else None
    try:
        yield
    finally:
        with _lock:
            _DEADLINE = prev


def _emit_timeout(site: str, dl: float, kind: str) -> None:
    try:
        from gauss_tpu import obs

        obs.counter("resilience.watchdog_timeouts")
        obs.emit("watchdog", site=site, deadline_s=dl, kind=kind)
    except Exception:  # pragma: no cover — telemetry must never mask the error
        pass


def guarded(fn: Callable, *, site: str, deadline_s: Optional[float] = None):
    """Run a blocking callable under the watchdog deadline.

    No deadline configured -> ``fn()`` inline (the zero-cost default every
    unsupervised solve takes). With a deadline, ``fn`` runs on a daemon
    thread; if it does not finish in time a :class:`WorkerLostError` is
    raised — the hung collective itself cannot be interrupted from host
    Python, so the thread is abandoned and the caller (a fleet worker)
    exits for the supervisor to restart.
    """
    dl = deadline_s if deadline_s is not None else _DEADLINE
    if dl is None:
        return fn()
    box: dict = {}
    done = threading.Event()

    def run():
        try:
            box["value"] = fn()
        except BaseException as e:  # noqa: BLE001 — relayed to the caller
            box["error"] = e
        finally:
            done.set()

    t = threading.Thread(target=run, name=f"watchdog:{site}", daemon=True)
    t.start()
    if not done.wait(dl):
        _emit_timeout(site, dl, "collective")
        raise WorkerLostError(
            f"collective at {site!r} did not complete within {dl:.3g} s — "
            f"a peer process is dead or stalled", site=site, deadline_s=dl)
    if "error" in box:
        raise box["error"]
    return box.get("value")


def wait_for(predicate: Callable[[], object], *, site: str,
             deadline_s: Optional[float] = None,
             poll_s: float = POLL_S,
             on_tick: Optional[Callable[[], None]] = None):
    """Poll ``predicate`` until it returns a truthy value; that value is
    returned. ``on_tick`` runs every poll (a fleet worker's heartbeat — a
    worker BLOCKED on a peer is alive and must keep saying so). Past the
    deadline a :class:`WorkerLostError` is raised; with no deadline
    configured anywhere the wait is unbounded (plain coordination)."""
    dl = deadline_s if deadline_s is not None else _DEADLINE
    t0 = time.monotonic()
    while True:
        value = predicate()
        if value:
            return value
        if on_tick is not None:
            on_tick()
        if dl is not None and time.monotonic() - t0 > dl:
            _emit_timeout(site, dl, "barrier")
            raise WorkerLostError(
                f"barrier at {site!r} not satisfied within {dl:.3g} s — "
                f"a peer process is dead or stalled", site=site,
                deadline_s=dl)
        time.sleep(poll_s)


def guarded_device(fn: Callable, *, site: str):
    """The distributed engines' hook shape: with the watchdog OFF the
    callable runs inline and stays lazy (no forced device sync — timed
    spans keep their semantics); with a deadline configured the result is
    ``block_until_ready``-synced on the helper thread so a peer hung
    inside the compiled collective trips the deadline."""
    if _DEADLINE is None:
        return fn()
    import jax

    return guarded(lambda: jax.block_until_ready(fn()), site=site)


# Environment activation: fleet worker subprocesses inherit their collective
# deadline through GAUSS_WATCHDOG_S, installed here at import so every
# guarded call in the process sees it without API plumbing.
_DEADLINE = _env_deadline()
