"""Chaos campaign runner: ``python -m gauss_tpu.resilience.chaos``.

Sweeps seeded randomized fault plans across engines and hook points and
asserts the one invariant a solver service must never break:

    **every injected fault is either recovered — a solution the RUNNER
    independently verifies at the relative-residual gate — or surfaced as a
    typed error. Never a silent wrong answer.**

Five phases:

- **solver** (``--cases``): each case draws an engine (blocked / rank-1), a
  size, and a fault scenario from a seeded catalog — transient or
  persistent operand corruption (NaN / Inf / bit-flip / forced near-zero
  pivot) at the engine's hook point, corruption of BOTH engines (forces the
  ladder to the host-NumPy rung), or input corruption (expected outcome: a
  typed ``UnrecoverableSolveError``) — installs the plan, and runs
  :func:`gauss_tpu.resilience.recover.solve_resilient`.
- **serve** (``--serve-requests``): a live :class:`SolverServer` under
  injected executable-compile failures and worker-dispatch stalls (deadline
  pressure); every request must reach exactly one terminal status, and
  every ``ok`` solution is verified.
- **checkpoint**: a checkpointed chunked factorization killed mid-run (the
  ``checkpoint.group`` hook) must resume to a factorization bit-identical
  to an uninterrupted run.
- **fleet** (``--no-fleet`` to skip): supervised multi-worker solves with
  a worker KILLED (os._exit) and a worker STALLED (sleep-forever) at a
  seeded panel group; the supervisor must detect (lease heartbeats for the
  stall, exit status for the kill), restart-and-resume from the sharded
  checkpoint, and finish with a verified solution **bit-identical** to the
  unfaulted supervised run — or raise the typed ``FleetError``. Every wait
  is deadline-bounded: zero hangs, by construction.
- **structure** (``--no-structure`` to skip): structured solves
  (gauss_tpu.structure) under a LYING classifier — every engine x every
  wrong tag, forced through the ``structure.detect`` mis-tag hook; the
  router must demote down the recovery ladder to general LU and end with
  an independently verified solution or a typed error.
- **durable** (``--no-durable`` to skip): the serving plane killed and
  restarted against its write-ahead request journal
  (gauss_tpu.serve.durable) — one in-process case per crash kind (batch-
  boundary crash, torn terminal append, clean drain, resume-under-load);
  the invariant is the durability contract: every admitted request reaches
  exactly one journaled terminal (served results re-verified by the
  runner), and idempotent resubmission never re-solves. The case runner is
  shared with ``make durable-check`` (gauss_tpu.serve.durablecheck — the
  deep campaign, with REAL os._exit subprocess kills); this phase keeps
  the invariant inside the one chaos gate.
- **sdc** (``--sdc-cases``, 0 disables): ON-DEVICE silent data corruption
  — seeded ``sdc_bitflip`` faults at the ABFT panel-group sites of the
  checksum-carrying LU and Cholesky engines
  (gauss_tpu.resilience.abft); every corruption must be DETECTED by the
  checksum invariant before the final residual gate, localized to its
  panel group, and repaired by the localized replay rung (bit-identical
  to an uninterrupted ABFT run) or, for persistent corruption, by
  escalation through the full ladder. The case runner is shared with
  ``make abft-check`` (gauss_tpu.resilience.abftcheck — the deep
  campaign); this phase keeps the invariant inside the one chaos gate.

The summary (``--summary-json``) is regress-ingestable
(``kind: chaos_campaign``): recovery depth (``mean_rung``), typed-error
rate, and per-case wall-clock enter ``reports/history.jsonl`` so a
recovery-rate regression gates like a perf regression. Exit status: 2 when
the invariant is violated (silent wrong answer or untyped error), 1 when
``--regress-check`` finds an out-of-band metric, 0 otherwise.

``make faults-check`` runs the CPU smoke configuration CI gates on.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from gauss_tpu.utils.env import honor_jax_platforms

#: solver-phase scenario catalog: (name, weight). Weights keep the common
#: transient case dominant, like real fleets: most faults are one-shot.
SCENARIOS = (
    ("transient", 6),      # one-shot corruption at the primary engine
    ("persistent", 2),     # corruption on EVERY primary-engine call
    ("persistent_all", 1),  # both engines corrupted -> numpy rung
    ("input", 1),          # corrupt the input itself -> typed error
)
CORRUPT_KINDS = ("nan", "inf", "bitflip", "near_zero_pivot")

ENGINE_SITES = {"blocked": "core.blocked.factor",
                "rank1": "core.gauss.solve"}


def _system(rng: np.random.Generator, n: int):
    a = rng.standard_normal((n, n))
    a[np.arange(n), np.arange(n)] += float(n)  # diagonally dominant
    return a, rng.standard_normal(n)


def _solver_case(i: int, seed: int, engines, sizes, panel, gate):
    """Run one seeded solver case; returns its outcome record."""
    from gauss_tpu.resilience import inject, recover
    from gauss_tpu.verify import checks

    rng = np.random.default_rng(np.random.SeedSequence((seed, i)))
    engine = engines[i % len(engines)]
    n = int(sizes[int(rng.integers(0, len(sizes)))])
    names = [s for s, w in SCENARIOS for _ in range(w)]
    scenario = names[int(rng.integers(0, len(names)))]
    kind = CORRUPT_KINDS[int(rng.integers(0, len(CORRUPT_KINDS)))]
    a, b = _system(rng, n)

    specs = []
    if scenario == "transient":
        specs = [inject.FaultSpec(site=ENGINE_SITES[engine], kind=kind,
                                  max_triggers=1, seed=i)]
    elif scenario == "persistent":
        specs = [inject.FaultSpec(site=ENGINE_SITES[engine], kind=kind,
                                  max_triggers=None, seed=i)]
    elif scenario == "persistent_all":
        specs = [inject.FaultSpec(site=s, kind=kind, max_triggers=None,
                                  seed=i + j)
                 for j, s in enumerate(ENGINE_SITES.values())]
    else:  # input
        specs = [inject.FaultSpec(site="chaos.input",
                                  kind="nan" if kind == "bitflip" else kind,
                                  max_triggers=1, seed=i)]

    out = {"case": i, "engine": engine, "n": n, "scenario": scenario,
           "kind": kind}
    with inject.plan(inject.FaultPlan(specs, seed=seed)) as ap:
        if scenario == "input":
            a = inject.corrupt_operand("chaos.input", a)
        try:
            res = recover.solve_resilient(a, b, engine=engine, panel=panel,
                                          gate=gate)
            # The runner's OWN verification — the invariant must not trust
            # the ladder's gate to judge the ladder.
            rel = checks.residual_norm(a, res.x, b, relative=True)
            if np.isfinite(rel) and rel <= gate:
                out.update(outcome="recovered" if res.rung_index else "ok",
                           rung=res.rung, rung_index=res.rung_index,
                           rel_residual=rel)
            else:
                out.update(outcome="silent_wrong", rung=res.rung,
                           rel_residual=float(rel))
        except recover.UnrecoverableSolveError as e:
            out.update(outcome="typed_error", trigger=e.trigger)
        except Exception as e:  # noqa: BLE001 — an untyped escape IS the bug
            out.update(outcome="violation",
                       error=f"{type(e).__name__}: {e}"[:200])
        out["injected"] = ap.stats()
    return out


def run_solver_phase(cases: int, seed: int, engines, sizes, panel, gate,
                     log=print) -> Dict:
    from gauss_tpu import obs

    outcomes: List[Dict] = []
    t0 = time.perf_counter()
    with obs.span("chaos_solver_phase", cases=cases):
        for i in range(cases):
            outcomes.append(_solver_case(i, seed, engines, sizes, panel,
                                         gate))
            if (i + 1) % 50 == 0:
                log(f"  solver cases: {i + 1}/{cases}")
    phase_wall = round(time.perf_counter() - t0, 3)
    by_rung: Dict[str, int] = {}
    counts = {"ok": 0, "recovered": 0, "typed_error": 0, "silent_wrong": 0,
              "violation": 0}
    rung_depths = []
    inj_site: Dict[str, int] = {}
    inj_kind: Dict[str, int] = {}
    injected = 0
    for o in outcomes:
        counts[o["outcome"]] = counts.get(o["outcome"], 0) + 1
        if o["outcome"] in ("ok", "recovered"):
            by_rung[o["rung"]] = by_rung.get(o["rung"], 0) + 1
            rung_depths.append(o["rung_index"] + 1)
        st = o.get("injected", {})
        injected += st.get("triggered", 0)
        for k, v in st.get("by_site", {}).items():
            inj_site[k] = inj_site.get(k, 0) + v
        for k, v in st.get("by_kind", {}).items():
            inj_kind[k] = inj_kind.get(k, 0) + v
    return {
        "cases": cases, "counts": counts, "recovered_by_rung": by_rung,
        "mean_rung": (round(float(np.mean(rung_depths)), 4)
                      if rung_depths else None),
        "typed_error_rate": round(counts["typed_error"] / cases, 4)
        if cases else None,
        "injected": injected, "injected_by_site": inj_site,
        "injected_by_kind": inj_kind, "wall_s": phase_wall,
    }


def run_serve_phase(requests: int, seed: int, gate: float) -> Dict:
    from gauss_tpu import obs
    from gauss_tpu.resilience import inject
    from gauss_tpu.serve import ServeConfig, SolverServer
    from gauss_tpu.verify import checks

    cfg = ServeConfig(ladder=(32, 64), max_batch=4, panel=16, refine_steps=1,
                      verify_gate=gate, max_retries=2, retry_backoff_s=0.0,
                      unhealthy_after=2, device_probe_cooldown_s=0.05)
    plan = inject.FaultPlan([
        inject.FaultSpec(site="serve.cache.compile", kind="compile_fail",
                         p=0.35, max_triggers=None, seed=1),
        inject.FaultSpec(site="serve.worker.dispatch", kind="delay",
                         p=0.25, max_triggers=None, param=0.02, seed=2),
    ], seed=seed)
    rng = np.random.default_rng(np.random.SeedSequence((seed, 0x5e12e)))
    counts: Dict[str, int] = {}
    incorrect = 0
    unresolved = 0
    injected = {}
    with obs.span("chaos_serve_phase", requests=requests):
        with inject.plan(plan) as ap:
            with SolverServer(cfg) as srv:
                handles = []
                for i in range(requests):
                    n = int(rng.integers(8, 49))
                    a, b = _system(rng, n)
                    # every 5th request runs under deadline pressure
                    dl = 0.01 if i % 5 == 4 else None
                    handles.append((a, b, srv.submit(a, b, deadline_s=dl)))
                for a, b, h in handles:
                    try:
                        res = h.result(timeout=120)
                    except TimeoutError:
                        unresolved += 1
                        continue
                    counts[res.status] = counts.get(res.status, 0) + 1
                    if res.status == "ok":
                        rel = checks.residual_norm(a, res.x, b,
                                                   relative=True)
                        if not rel <= gate:
                            incorrect += 1
            injected = ap.stats()
    return {"requests": requests, "counts": counts, "incorrect": incorrect,
            "unresolved": unresolved, "injected": injected.get("triggered", 0),
            "injected_by_site": injected.get("by_site", {})}


def run_checkpoint_phase(tmpdir: str) -> Dict:
    import jax.numpy as jnp

    from gauss_tpu import obs
    from gauss_tpu.core import blocked
    from gauss_tpu.resilience import checkpoint as ckpt
    from gauss_tpu.resilience import inject

    rng = np.random.default_rng(2584580)
    n = 96
    a = (rng.standard_normal((n, n)) + np.diag([float(n)] * n)).astype(
        np.float32)
    kw = dict(panel=16, chunk=2)
    with obs.span("chaos_checkpoint_phase"):
        clean = ckpt.lu_factor_blocked_chunked_checkpointed(
            a, f"{tmpdir}/chaos_ck_clean.npz", **kw)
        path = f"{tmpdir}/chaos_ck_killed.npz"
        plan = inject.FaultPlan([inject.FaultSpec(
            site="checkpoint.group", kind="raise", max_triggers=1, skip=2)])
        killed = False
        with inject.plan(plan) as ap:
            try:
                ckpt.lu_factor_blocked_chunked_checkpointed(a, path, **kw)
            except inject.SimulatedFaultError:
                killed = True
            injected = ap.stats()["triggered"]
        resumed = ckpt.lu_factor_blocked_chunked_checkpointed(a, path, **kw)
        identical = all(
            np.array_equal(np.asarray(getattr(clean, f)),
                           np.asarray(getattr(resumed, f)))
            for f in ("m", "perm", "min_abs_pivot", "linv", "uinv"))
        # and the factor actually solves
        b = rng.standard_normal(n)
        x = np.asarray(blocked.lu_solve(resumed, jnp.asarray(b, jnp.float32)))
        from gauss_tpu.verify import checks

        rel = checks.residual_norm(a, x, b, relative=True)
    return {"ran": True, "killed": killed, "bit_identical": bool(identical),
            "injected": injected, "resumed_rel_residual": float(rel)}


def run_fleet_phase(seed: int, gate: float) -> Dict:
    """Supervised-multihost chaos: kill one fleet worker and stall another
    at a seeded panel group. Invariant: the supervised job completes with a
    verified solution — bit-identical to the unfaulted supervised run — or
    a typed FleetError; never a hang (every wait is deadline-bounded)."""
    import shutil
    import tempfile

    from gauss_tpu import obs
    from gauss_tpu.obs import debug as _gdebug
    from gauss_tpu.obs import postmortem as _postmortem
    from gauss_tpu.resilience import fleet

    rng = np.random.default_rng(np.random.SeedSequence((seed, 0xF1EE7)))
    n = 48
    a, b = _system(rng, n)
    kw = dict(workers=2, panel=16, chunk=1, gate=gate, stall_after_s=3.0,
              barrier_deadline_s=45.0, job_timeout_s=150.0)
    cases: List[Dict] = []
    with obs.span("chaos_fleet_phase"):
        clean = fleet.solve_supervised(a, b, **kw)
        group = 1 + int(rng.integers(0, 2))  # kill/stall at group 1 or 2
        for kind in ("kill", "stall"):
            case = {"kind": kind, "group": group}
            # Caller-owned jobdir: solve_supervised leaves it in place, so
            # the supervisor's post-mortem bundle (captured at detection)
            # can be asserted on after the solve.
            jobdir = tempfile.mkdtemp(prefix=f"gauss_chaos_fleet_{kind}_")
            try:
                res = fleet.solve_supervised(
                    a, b, jobdir=jobdir,
                    inject=f"fleet.worker.group={kind}:skip={group}",
                    inject_worker=1, **kw)
                case.update(
                    outcome="recovered" if res.recovered else "ok",
                    rung=res.rung, restarts=res.restarts,
                    stalls=res.stalls,
                    rel_residual=float(res.rel_residual),
                    resume_latency_s=res.resume_latency_s,
                    bit_identical=bool(np.array_equal(clean.x, res.x)))
            except fleet.FleetError as e:
                case.update(outcome="typed_error", error=str(e)[:200])
            except Exception as e:  # noqa: BLE001 — an untyped escape IS the bug
                case.update(outcome="violation",
                            error=f"{type(e).__name__}: {e}"[:200])
            # Flight-recorder contract: every injected kill/stall must leave
            # a post-mortem bundle that gauss-debug --check accepts. A fault
            # the supervisor survived but did not bundle is a violation too.
            bundle = _postmortem.latest_bundle(
                _postmortem.default_bundles_dir(
                    os.path.join(jobdir, "flight")))
            case["bundle_check_rc"] = (
                _gdebug.main([bundle, "--check"]) if bundle else None)
            case["postmortem_ok"] = (bundle is not None
                                     and case["bundle_check_rc"] == 0)
            shutil.rmtree(jobdir, ignore_errors=True)
            cases.append(case)
    violations = sum(
        1 for c in cases
        if c["outcome"] == "violation"
        or not c.get("postmortem_ok")
        or (c["outcome"] in ("ok", "recovered")
            and not c.get("bit_identical")))
    return {"ran": True, "cases": cases, "injected": len(cases),
            "clean_rel_residual": float(clean.rel_residual),
            "violations": violations}


def run_structure_phase(seed: int, gate: float) -> Dict:
    """Structured-solve chaos: force a WRONG structure tag (every engine x
    every wrong tag, via the ``structure.detect`` mis-tag hook) and assert
    the router's invariant — the recovery ladder demotes to general LU and
    the result is independently verified at the gate, or the error is
    typed. A lying classifier must never produce a silent wrong answer."""
    from gauss_tpu import obs
    from gauss_tpu.io import synthetic
    from gauss_tpu.resilience import inject, recover
    from gauss_tpu.structure import STRUCTURE_KINDS, solve_auto
    from gauss_tpu.verify import checks

    rng = np.random.default_rng(np.random.SeedSequence((seed, 0x5717)))
    n = 48
    systems = {
        "spd": synthetic.spd_matrix(n),
        "banded": synthetic.banded_matrix(n, 1),
        "blockdiag": synthetic.blockdiag_matrix(n, 8),
        "dense": synthetic.dense_matrix(n),
    }
    cases: List[Dict] = []
    injected = 0
    with obs.span("chaos_structure_phase"):
        for true_kind, a in systems.items():
            b = rng.standard_normal(n)
            for wrong_idx, wrong in enumerate(STRUCTURE_KINDS):
                if wrong == true_kind:
                    continue
                case = {"true": true_kind, "forced": wrong}
                plan = inject.FaultPlan([inject.FaultSpec(
                    site="structure.detect", kind="mistag",
                    param=float(wrong_idx), max_triggers=1)], seed=seed)
                with inject.plan(plan) as ap:
                    try:
                        res = solve_auto(a, b, gate=gate)
                        rel = checks.residual_norm(a, res.x, b,
                                                   relative=True)
                        if np.isfinite(rel) and rel <= gate:
                            case.update(outcome=("demoted"
                                                 if res.rung_index else "ok"),
                                        engine=res.rung,
                                        rel_residual=float(rel))
                        else:
                            case.update(outcome="silent_wrong",
                                        engine=res.rung,
                                        rel_residual=float(rel))
                    except recover.UnrecoverableSolveError as e:
                        case.update(outcome="typed_error", trigger=e.trigger)
                    except Exception as e:  # noqa: BLE001 — untyped IS the bug
                        case.update(outcome="violation",
                                    error=f"{type(e).__name__}: {e}"[:200])
                    injected += ap.stats()["triggered"]
                cases.append(case)
    violations = sum(1 for c in cases
                     if c["outcome"] in ("silent_wrong", "violation"))
    return {"ran": True, "cases": cases, "injected": injected,
            "demotions": sum(1 for c in cases if c["outcome"] == "demoted"),
            "violations": violations}


def run_sdc_phase(cases: int, seed: int, gate: float, log=print) -> Dict:
    """On-device SDC chaos: the abftcheck case runner under the campaign
    invariant (100% detection, replay-or-ladder recovery, bit-identity)."""
    from gauss_tpu import obs
    from gauss_tpu.resilience import abftcheck

    outcomes: List[Dict] = []
    clean_cache: Dict = {}
    by_site: Dict[str, int] = {}
    t0 = time.perf_counter()
    with obs.span("chaos_sdc_phase", cases=cases):
        for i in range(cases):
            o = abftcheck.run_sdc_case(i, seed, gate,
                                       clean_cache=clean_cache)
            outcomes.append(o)
            site = f"abft.{o['engine']}.group"
            by_site[site] = by_site.get(site, 0) + o.get("injected", 0)
    summ = abftcheck.summarize_sdc_cases(outcomes,
                                         time.perf_counter() - t0)
    summ["ran"] = True
    summ["injected_by_site"] = by_site
    return summ


def run_durable_phase(seed: int, gate: float, tmpdir: str) -> Dict:
    """Kill-the-server chaos: one in-process case per crash kind against
    the write-ahead request journal (the deep campaign with real
    subprocess kills is ``make durable-check``; the runner is shared)."""
    from gauss_tpu import obs
    from gauss_tpu.serve import durablecheck
    from gauss_tpu.serve.cache import ExecutableCache

    cache = ExecutableCache(32)
    ddir = os.path.join(tmpdir, "durable")
    os.makedirs(ddir, exist_ok=True)
    cases: List[Dict] = []
    with obs.span("chaos_durable_phase"):
        for i, kind in enumerate(durablecheck.CASE_KINDS):
            try:
                cases.append(durablecheck.run_recovery_case(
                    i, seed, gate, ddir, kind, cache=cache))
            except Exception as e:  # noqa: BLE001 — untyped escape IS the bug
                cases.append({"case": i, "kind": kind,
                              "outcome": "violation",
                              "error": f"{type(e).__name__}: {e}"[:200]})
    # NOTE: these crashes are driven by the server's _crash() chaos hook,
    # not the inject module — they are deliberately NOT counted in the
    # campaign's "injected" fault total (no ``fault`` events exist for
    # them in the stream; the resilience summary must keep reconciling
    # with the injected count).
    return {"ran": True, "cases": cases,
            "admitted": sum(c.get("audit", {}).get("admitted", 0)
                            for c in cases),
            "crashes": len(cases),
            "violations": sum(1 for c in cases
                              if c["outcome"] == "violation")}


def history_records(summary: Dict) -> List[Tuple[str, float, str]]:
    """(metric, value, unit) records a campaign contributes to the
    regression history. All slow-side-gated: recovery regressing shows as a
    DEEPER mean rung or a HIGHER typed-error rate; throughput regressing as
    more seconds per case."""
    out: List[Tuple[str, float, str]] = []
    sol = summary.get("solver") or {}
    if isinstance(sol.get("mean_rung"), (int, float)) and sol["mean_rung"] > 0:
        out.append(("chaos:solver/mean_rung", sol["mean_rung"], "rung"))
    ter = sol.get("typed_error_rate")
    if isinstance(ter, (int, float)) and ter > 0:
        out.append(("chaos:solver/typed_error_rate", ter, "ratio"))
    # Prefer the solver phase's OWN wall-clock (recorded since the fleet
    # phase joined the campaign — the CAMPAIGN wall would charge subprocess
    # fleet solves to the per-case metric); older summaries fall back to
    # the campaign wall, which for them was the same thing minus epsilon.
    wall = sol.get("wall_s", summary.get("wall_s"))
    cases = sol.get("cases")
    if isinstance(wall, (int, float)) and wall > 0 and cases:
        out.append(("chaos:solver/s_per_case", round(wall / cases, 6), "s"))
    return out


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m gauss_tpu.resilience.chaos",
        description="Seeded chaos campaign: inject faults across engines "
                    "and hook points; assert every fault is recovered "
                    "(verified) or a typed error — never a silent wrong "
                    "answer.")
    p.add_argument("--cases", type=int, default=200,
                   help="solver-phase fault cases (default 200)")
    p.add_argument("--seed", type=int, default=258458)
    p.add_argument("--engines", default="blocked,rank1",
                   help="comma-separated primary engines (default both)")
    p.add_argument("--sizes", default="24,32,48",
                   help="comma-separated system sizes (small: the campaign "
                        "is about fault paths, not FLOPs)")
    p.add_argument("--panel", type=int, default=16)
    p.add_argument("--gate", type=float, default=1e-4,
                   help="relative-residual verification bar (default 1e-4)")
    p.add_argument("--serve-requests", type=int, default=30,
                   help="serve-phase request count (0 disables the phase)")
    p.add_argument("--no-checkpoint", action="store_true",
                   help="skip the checkpoint kill/resume phase")
    p.add_argument("--no-fleet", action="store_true",
                   help="skip the supervised-fleet kill/stall phase "
                        "(subprocess workers; the slowest phase)")
    p.add_argument("--no-structure", action="store_true",
                   help="skip the structured-solve mis-tag phase")
    p.add_argument("--no-durable", action="store_true",
                   help="skip the kill-the-server journal-recovery phase")
    p.add_argument("--sdc-cases", type=int, default=12,
                   help="on-device sdc_bitflip cases against the ABFT "
                        "checksum engines (0 disables; the deep campaign "
                        "is `make abft-check`)")
    p.add_argument("--tmpdir", default="/tmp",
                   help="where the checkpoint phase writes its files")
    p.add_argument("--metrics-out", default=None, metavar="PATH",
                   help="append the campaign's obs JSONL stream (faults, "
                        "recovery events, serving events) here")
    p.add_argument("--summary-json", default=None, metavar="PATH",
                   help="write the campaign summary (regress-ingestable: "
                        "kind=chaos_campaign)")
    p.add_argument("--history", nargs="?", const="", default=None,
                   metavar="PATH",
                   help="append this campaign's records to the regression "
                        "history (default reports/history.jsonl)")
    p.add_argument("--regress-check", action="store_true",
                   help="gate this campaign against the history baselines "
                        "(exit 1 when out of band)")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    honor_jax_platforms()

    from gauss_tpu import obs
    from gauss_tpu.obs import regress

    engines = [e.strip() for e in args.engines.split(",") if e.strip()]
    sizes = [int(s) for s in args.sizes.split(",")]
    bad = [e for e in engines if e not in ENGINE_SITES]
    if bad:
        print(f"chaos: unknown engine(s) {bad}; options: "
              f"{sorted(ENGINE_SITES)}", file=sys.stderr)
        return 2

    t0 = time.perf_counter()
    with obs.run(metrics_out=args.metrics_out, tool="chaos_campaign",
                 cases=args.cases, seed=args.seed):
        solver = run_solver_phase(args.cases, args.seed, engines, sizes,
                                  args.panel, args.gate)
        serve = (run_serve_phase(args.serve_requests, args.seed, args.gate)
                 if args.serve_requests > 0 else {})
        ckpt = ({} if args.no_checkpoint
                else run_checkpoint_phase(args.tmpdir))
        flt = ({} if args.no_fleet
               else run_fleet_phase(args.seed, args.gate))
        struct = ({} if args.no_structure
                  else run_structure_phase(args.seed, args.gate))
        dur = ({} if args.no_durable
               else run_durable_phase(args.seed, args.gate, args.tmpdir))
        sdc = (run_sdc_phase(args.sdc_cases, args.seed, args.gate)
               if args.sdc_cases > 0 else {})
        wall = round(time.perf_counter() - t0, 3)

        violations = (solver["counts"]["silent_wrong"]
                      + solver["counts"]["violation"]
                      + (serve.get("incorrect", 0) if serve else 0)
                      + (serve.get("unresolved", 0) if serve else 0)
                      + (0 if not ckpt or ckpt["bit_identical"] else 1)
                      + (flt.get("violations", 0) if flt else 0)
                      + (struct.get("violations", 0) if struct else 0)
                      + (dur.get("violations", 0) if dur else 0)
                      + (sdc.get("violations", 0) if sdc else 0))
        injected = (solver["injected"] + (serve.get("injected", 0))
                    + (ckpt.get("injected", 0) if ckpt else 0)
                    + (flt.get("injected", 0) if flt else 0)
                    + (struct.get("injected", 0) if struct else 0)
                    + (sdc.get("injected", 0) if sdc else 0))
        sites = dict(solver["injected_by_site"])
        for k, v in (serve.get("injected_by_site") or {}).items():
            sites[k] = sites.get(k, 0) + v
        if ckpt.get("injected"):
            sites["checkpoint.group"] = (sites.get("checkpoint.group", 0)
                                         + ckpt["injected"])
        if flt.get("injected"):
            sites["fleet.worker.group"] = (sites.get("fleet.worker.group", 0)
                                           + flt["injected"])
        if struct.get("injected"):
            sites["structure.detect"] = (sites.get("structure.detect", 0)
                                         + struct["injected"])
        for k, v in (sdc.get("injected_by_site") or {}).items():
            sites[k] = sites.get(k, 0) + v
        summary = {
            "kind": "chaos_campaign", "seed": args.seed,
            "engines": engines, "sizes": sizes, "gate": args.gate,
            "injected": injected, "injected_by_site": sites,
            "solver": solver, "serve": serve, "checkpoint": ckpt,
            "fleet": flt, "structure": struct, "durable": dur, "sdc": sdc,
            "wall_s": wall, "invariant_ok": violations == 0,
        }
        obs.emit("chaos_campaign",
                 **{k: v for k, v in summary.items() if k != "kind"})

    c = solver["counts"]
    print(f"chaos campaign: {args.cases} solver case(s) over "
          f"{'+'.join(engines)} @ n={sizes}, {injected} fault(s) injected "
          f"across {len(sites)} site(s)")
    print(f"  solver: {c['ok']} clean, {c['recovered']} recovered "
          f"(by rung: {solver['recovered_by_rung']}), "
          f"{c['typed_error']} typed error(s), "
          f"{c['silent_wrong']} SILENT WRONG, {c['violation']} untyped")
    if serve:
        print(f"  serve: {serve['requests']} request(s) -> "
              f"{serve['counts']}, {serve['incorrect']} incorrect, "
              f"{serve['unresolved']} unresolved, "
              f"{serve['injected']} fault(s)")
    if ckpt:
        print(f"  checkpoint: killed={ckpt['killed']} "
              f"bit_identical={ckpt['bit_identical']} "
              f"rel_residual={ckpt['resumed_rel_residual']:.3e}")
    if flt:
        for c in flt["cases"]:
            print(f"  fleet[{c['kind']}@group{c['group']}]: "
                  f"{c['outcome']}"
                  + (f" rung={c.get('rung')} restarts={c.get('restarts')} "
                     f"stalls={c.get('stalls')} "
                     f"bit_identical={c.get('bit_identical')}"
                     if "rung" in c else f" ({c.get('error', '')[:80]})"))
    if struct:
        by_outcome: Dict[str, int] = {}
        for c in struct["cases"]:
            by_outcome[c["outcome"]] = by_outcome.get(c["outcome"], 0) + 1
        print(f"  structure: {len(struct['cases'])} mis-tag case(s) -> "
              f"{by_outcome}, {struct['demotions']} demotion(s), "
              f"{struct['violations']} violation(s)")
    if dur:
        by_outcome = {}
        for c in dur["cases"]:
            by_outcome[c["outcome"]] = by_outcome.get(c["outcome"], 0) + 1
        print(f"  durable: {dur['crashes']} kill/resume case(s) "
              f"({'+'.join(c['kind'] for c in dur['cases'])}) -> "
              f"{by_outcome}, {dur['admitted']} admitted, "
              f"{dur['violations']} violation(s)")
    if sdc:
        print(f"  sdc: {sdc['cases']} on-device case(s), "
              f"{sdc['injected']} bitflip(s) -> detect rate "
              f"{sdc['detect_rate']}, {sdc['replayed']} replay-recovered, "
              f"{sdc['escalated']} escalated, "
              f"{sdc['bit_identity_failures']} bit-identity failure(s), "
              f"{sdc['violations']} violation(s)")
    print(f"  invariant {'HOLDS' if violations == 0 else 'VIOLATED'} "
          f"({wall} s)")

    if args.summary_json:
        parent = os.path.dirname(args.summary_json)
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(args.summary_json, "w") as f:
            json.dump(summary, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"summary: {args.summary_json}")

    rc = 0
    records = [{"metric": m, "value": v, "unit": u, "source": "chaos",
                "kind": "chaos"} for m, v, u in history_records(summary)]
    if args.regress_check and records:
        history_path = args.history or regress.default_history_path()
        verdicts = regress.check_records(
            records, regress.load_history(history_path))
        print(regress.format_verdicts(verdicts))
        if any(v["status"] == "out-of-band" for v in verdicts):
            rc = 1
    if args.history is not None and records and rc == 0:
        history_path = args.history or regress.default_history_path()
        added = regress.append_history(records, history_path)
        print(f"history: {added} record(s) appended to {history_path}")

    if violations:
        print(f"chaos: INVARIANT VIOLATED ({violations} case(s))",
              file=sys.stderr)
        return 2
    return rc


if __name__ == "__main__":
    sys.exit(main())
