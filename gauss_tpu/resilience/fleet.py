"""gauss_tpu.resilience.fleet — supervised multi-worker solves.

The reference's MPI engine — and PR 4's single-process recovery ladder —
share a blind spot: when a WORKER PROCESS dies mid-factorization nothing
detects it, nothing preserves the distributed work, and nothing brings the
job back. This module is the missing supervisor, three mechanisms deep:

- **Lease-file heartbeats.** Every worker writes a small lease JSON
  (atomic replace) from its group loop and from every coordination-barrier
  poll (:func:`beat` — also called by the distributed engines' stage
  hooks). The supervisor watches process liveness AND lease freshness, so
  it can tell the three failure shapes apart: *dead* (process exited —
  preemption, crash, injected kill), *stalled* (process alive, lease
  stale — the hung worker ``kind="stall"`` injects), and *blocked on a
  peer* (the worker's own watchdog fired and it exited with
  :data:`PEER_LOST_EXIT`).
- **Restart-and-resume.** A replacement worker resumes from the newest
  verified generation of the sharded coordinated checkpoint
  (:mod:`gauss_tpu.resilience.dcheckpoint`) and — because every group step
  is deterministic over bit-identical carry — the supervised job finishes
  **bit-identical to an uninterrupted supervised run**.
- **Elastic degrade.** When the restart budget is spent the job is
  re-sharded onto the surviving mesh (world W -> W-1, the checkpoint layout
  is world-size independent), and at the last rung the supervisor itself
  finishes the factorization in-process (world 1) from the last good
  generation. The ladder is ``supervised -> restart -> shrink ->
  local_finish``; every rung ends in a solution verified at the 1e-4 gate
  or a typed :class:`FleetError` — never a hang (everything is
  deadline-bounded) and never a silent wrong answer.

Entry points: :func:`solve_supervised` (API) and ``gauss-fleet`` (CLI,
``python -m gauss_tpu.resilience.fleet``), which also hosts the internal
``--worker`` mode the supervisor spawns. The CLI emits a regress-ingestable
summary (``kind: fleet_solve``) so restart counts, resume latency, and the
rung reached gate in CI exactly like a perf metric.

On a real TPU fleet the workers would additionally join a
``jax.distributed`` coordination service (dist.multihost) and run the
shard_map engines; the CPU rehearsal keeps per-worker compute local (see
dcheckpoint's module docstring) — the supervision protocol under test is
identical either way.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time
from typing import Dict, List, Optional

import numpy as np

from gauss_tpu import obs
from gauss_tpu.resilience import inject as _inject

ENV_LEASE = "GAUSS_FLEET_LEASE"

#: a worker's own watchdog fired (peer dead/stalled): the worker is healthy
#: but cannot make progress; its respawn is free (bounded separately).
PEER_LOST_EXIT = 117
#: a child exited from a GRACEFUL drain (SIGTERM -> drain -> this code):
#: an operator-initiated shutdown, not a failure. Supervisors respawn it
#: WITHOUT charging the bounded restart budget — before this code existed
#: a rolling drain was indistinguishable from a crash loop and could
#: exhaust max_restarts (ISSUE 19 satellite).
DRAIN_EXIT = 116
#: unrecoverable configuration/checkpoint mismatch inside a worker.
CONFIG_EXIT = 115

RUNGS = ("supervised", "restart", "shrink", "local_finish")

#: death causes whose respawn does not consume the restart budget:
#: peer_lost is a secondary casualty (bounded separately), drained is an
#: operator-initiated graceful exit, quarantined is a poison-request
#: death whose blame evidence GREW (the journal-replay quarantine ladder
#: is converging — solo at K deaths, typed reject past K — so these
#: respawns are finite by construction and must not spend the budget).
FREE_RESPAWN_CAUSES = ("peer_lost", "drained", "quarantined")


def exit_cause(rc: Optional[int]) -> str:
    """Classify a supervised child's exit code into the shared cause
    vocabulary: ``"clean"`` (0), ``"killed"`` (the fault injector's
    os._exit), ``"drained"`` (graceful SIGTERM drain — :data:`DRAIN_EXIT`),
    ``"peer_lost"``, ``"config"``, or ``"crashed"`` (anything else,
    including signal deaths, where ``rc`` is negative). Both the fleet
    supervisor and the serve replica router classify deaths through this
    one function, so the drain-vs-crash accounting can never diverge
    between them."""
    if rc == 0:
        return "clean"
    return {_inject.KILL_EXIT_CODE: "killed",
            DRAIN_EXIT: "drained",
            PEER_LOST_EXIT: "peer_lost",
            CONFIG_EXIT: "config"}.get(rc, "crashed")


def counts_against_restart_budget(cause: str) -> bool:
    """Does a death with this :func:`exit_cause` consume the bounded
    restart budget? Real failures (crash / injected kill / stall-kill /
    config) do; graceful drains and peer-lost watchdog exits respawn
    free, so a rolling drain or one fault's secondary casualties cannot
    exhaust ``max_restarts``."""
    return cause not in FREE_RESPAWN_CAUSES and cause != "clean"

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_BEAT_SEQ = 0


class FleetError(RuntimeError):
    """The supervised job could not produce a verified solution — every
    rung of the elastic ladder failed or the result missed the residual
    gate. The typed terminal error of the fleet path (the chaos invariant:
    verified solution or THIS, never a hang)."""


# -- lease heartbeats ------------------------------------------------------

def active() -> bool:
    """Is this process a supervised fleet worker (lease env configured)?
    One environ lookup. The distributed engines consult this — together
    with ``watchdog.enabled()`` — ONCE per staged solve, so the
    heartbeat/watchdog hook plumbing is skipped entirely on the
    unsupervised hot path (the hooks are guarded where the solver is
    BUILT, not polled inside it)."""
    return bool(os.environ.get(ENV_LEASE))


def lease_path(jobdir, worker: int) -> str:
    return os.path.join(os.fspath(jobdir), "leases", f"w{worker}.json")


def beat(**fields) -> None:
    """Write this process's fleet lease (no-op outside a fleet worker —
    one environ lookup). Called from the worker group loop, from every
    barrier poll, and from the distributed engines' stage hooks, so a
    worker inside a long compiled solve still beats at stage boundaries."""
    path = os.environ.get(ENV_LEASE)
    if not path:
        return
    global _BEAT_SEQ
    _BEAT_SEQ += 1
    doc = {"pid": os.getpid(), "beat": _BEAT_SEQ,
           "time_unix": time.time(), **fields}
    parent = os.path.dirname(path)
    os.makedirs(parent, exist_ok=True)
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)


def read_lease(path) -> Optional[dict]:
    try:
        with open(os.fspath(path)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


# -- configuration / results ----------------------------------------------

@dataclasses.dataclass
class FleetConfig:
    """Tuning knobs for :func:`solve_supervised`."""

    workers: int = 2                 # initial world size
    panel: Optional[int] = None      # blocked-factor panel (None -> auto)
    chunk: int = 1                   # panels per group (= per checkpoint)
    refine_iters: int = 2            # host-f64 refinement rounds
    gate: float = 1e-4               # rel-residual verification bar
    stall_after_s: float = 10.0      # stale-lease threshold (alive process)
    startup_grace_s: float = 60.0    # stall allowance before the 1st beat
    poll_s: float = 0.05             # supervisor monitor cadence
    max_restarts: int = 2            # dead-worker respawn budget (global)
    max_peer_respawns: int = 8       # free respawns of PEER_LOST exits
    min_workers: int = 1             # elastic floor before local_finish
    barrier_deadline_s: float = 60.0  # worker-side watchdog (GAUSS_WATCHDOG_S)
    job_timeout_s: float = 600.0     # whole-job bound -> local_finish
    inject: Optional[str] = None     # GAUSS_FAULTS plan for first spawns
    inject_worker: Optional[int] = None  # target worker (None = all)
    keep: bool = False               # keep the job directory
    #: persistent XLA compile-cache dir, passed to every worker through the
    #: GAUSS_COMPILE_CACHE env channel (same pattern as GAUSS_FAULTS): a
    #: RESTARTED worker then resumes from cached executables instead of
    #: re-jitting its whole factorization — the dominant term of the
    #: detect->first-beat resume latency this module measures. None
    #: inherits whatever the supervisor's environment already carries.
    compile_cache_dir: Optional[str] = None


@dataclasses.dataclass
class FleetResult:
    """What a supervised solve produced and how hard the fleet worked."""

    x: np.ndarray
    rung: str                  # deepest elastic rung exercised
    rung_index: int            # 0 = clean supervised run
    restarts: int              # budgeted dead-worker respawns
    peer_respawns: int         # free respawns after PEER_LOST exits
    stalls: int                # stale-lease detections (worker killed)
    kills: int                 # dead-worker detections (incl. stalls)
    shrinks: int               # world-size reductions
    world: int                 # final world size (0 = local_finish)
    resume_latency_s: Optional[float]  # worst death->replacement-beat gap
    rel_residual: float
    wall_s: float

    @property
    def recovered(self) -> bool:
        return self.rung_index > 0


# -- worker subprocess management ------------------------------------------

class _Worker:
    def __init__(self, wid: int, proc, log, spawn_t: float):
        self.id = wid
        self.proc = proc
        self.log = log
        self.spawn_t = spawn_t          # monotonic clock
        self.spawn_unix = time.time()   # for lease-mtime freshness checks
        self.reaped = False


def _spawn_worker(jobdir: str, cfg: FleetConfig, wid: int, world: int,
                  run_id: str, attempt: int,
                  faults: Optional[str]) -> _Worker:
    env = {k: v for k, v in os.environ.items() if k != _inject.ENV_VAR}
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env[ENV_LEASE] = lease_path(jobdir, wid)
    env["GAUSS_OBS_RUN_ID"] = run_id
    env["GAUSS_WATCHDOG_S"] = str(cfg.barrier_deadline_s)
    # Crash-surviving telemetry: every worker appends its obs events to an
    # mmap flight ring under the jobdir, so when the supervisor detects it
    # dead/stalled the final seconds are still on disk to bundle
    # (gauss_tpu.obs.flight / obs.postmortem).
    from gauss_tpu.obs import flight as _flight

    env[_flight.ENV_VAR] = os.path.join(jobdir, "flight")
    if cfg.compile_cache_dir:
        # The warm-restart channel: workers (and their REPLACEMENTS) share
        # one persistent XLA compile cache, so a respawn resumes from
        # cached executables (gauss_tpu.tune.compilecache). Inherited from
        # os.environ above when the supervisor already runs with one.
        from gauss_tpu.tune import compilecache as _cc

        env[_cc.ENV_CACHE_DIR] = os.path.abspath(cfg.compile_cache_dir)
    if faults:
        env[_inject.ENV_VAR] = faults
    cmd = [sys.executable, "-m", "gauss_tpu.resilience.fleet", "--worker",
           "--jobdir", jobdir, "--worker-id", str(wid),
           "--num-workers", str(world), "--chunk", str(cfg.chunk),
           "--refine-iters", str(cfg.refine_iters)]
    if cfg.panel:
        cmd += ["--panel", str(cfg.panel)]
    logdir = os.path.join(jobdir, "logs")
    os.makedirs(logdir, exist_ok=True)
    log = open(os.path.join(logdir, f"w{wid}.{attempt}.log"), "ab")
    proc = subprocess.Popen(cmd, cwd=_REPO, env=env, stdout=log,
                            stderr=subprocess.STDOUT)
    return _Worker(wid, proc, log, time.monotonic())


def _reap(w: _Worker) -> None:
    if not w.reaped:
        try:
            w.log.close()
        except OSError:
            pass
        w.reaped = True


def _kill_worker(w: _Worker) -> None:
    if w.proc.poll() is None:
        w.proc.kill()
        try:
            w.proc.wait(timeout=30)
        except subprocess.TimeoutExpired:  # pragma: no cover
            pass
    _reap(w)


def _last_activity(jobdir: str, w: _Worker) -> float:
    """Monotonic-clock timestamp of the worker's most recent sign of life
    (its spawn, or its latest lease write)."""
    try:
        mtime_age = time.time() - os.path.getmtime(lease_path(jobdir, w.id))
    except OSError:
        return w.spawn_t
    return max(w.spawn_t, time.monotonic() - max(0.0, mtime_age))


def _has_lease(jobdir: str, w: _Worker) -> bool:
    try:
        return os.path.getmtime(lease_path(jobdir, w.id)) >= 0
    except OSError:
        return False


def _lease_fresh(jobdir: str, w: _Worker) -> bool:
    """Has THIS incarnation beaten yet? (A dead predecessor's lease file
    still exists; only a write after this worker's spawn counts.)"""
    try:
        return os.path.getmtime(lease_path(jobdir, w.id)) >= w.spawn_unix
    except OSError:
        return False


# -- results on disk -------------------------------------------------------

def _result_path(jobdir: str) -> str:
    return os.path.join(jobdir, "result.npz")


def _write_result(jobdir: str, x: np.ndarray) -> None:
    from gauss_tpu.resilience import dcheckpoint

    x = np.asarray(x, np.float64)
    dcheckpoint._atomic_write(
        _result_path(jobdir),
        lambda f: np.savez(f, x=x, digest=np.frombuffer(
            _x_digest(x).encode(), np.uint8)))


def _x_digest(x: np.ndarray) -> str:
    import hashlib

    return hashlib.sha256(np.ascontiguousarray(x).tobytes()).hexdigest()[:16]


def _read_result(jobdir: str) -> Optional[np.ndarray]:
    path = _result_path(jobdir)
    if not os.path.exists(path):
        return None
    try:
        with np.load(path) as z:
            x = np.array(z["x"])
            digest = bytes(np.array(z["digest"])).decode()
    except Exception:  # noqa: BLE001 — torn write: not ready yet
        return None
    return x if _x_digest(x) == digest else None


def _solve_refined(fac, a64: np.ndarray, b64: np.ndarray,
                   iters: int) -> np.ndarray:
    """Deterministic solve through an existing blocked factor: one f32
    device solve + fixed host-f64 refinement — identical on every rung, so
    the elastic ladder cannot change the bits of a recovered answer."""
    import jax.numpy as jnp

    from gauss_tpu.core import blocked
    from gauss_tpu.resilience.recover import _refine_host

    x = np.asarray(blocked.lu_solve(
        fac, jnp.asarray(b64.astype(np.float32))), np.float64)
    return _refine_host(fac, a64, b64, x, iters)


# -- the supervisor --------------------------------------------------------

def solve_supervised(a, b, *, config: Optional[FleetConfig] = None,
                     jobdir=None, **overrides) -> FleetResult:
    """Solve ``a @ x = b`` under fleet supervision; returns a
    :class:`FleetResult` with a 1e-4-verified float64 solution, or raises
    the typed :class:`FleetError`. ``overrides`` patch
    :class:`FleetConfig` fields (``workers=4``, ``inject="..."``, ...).

    The factorization runs in ``config.workers`` spawned worker processes
    over a sharded coordinated checkpoint in ``jobdir`` (a temp directory
    by default, removed on success unless ``keep``); the calling process
    only supervises — and, at the last elastic rung, finishes the job
    itself from the last good checkpoint generation.
    """
    cfg = dataclasses.replace(
        config if config is not None else FleetConfig(), **overrides)
    if cfg.workers < 1:
        raise ValueError(f"workers must be >= 1, got {cfg.workers}")
    a64 = np.asarray(a, np.float64)
    b64 = np.asarray(b, np.float64)
    n = a64.shape[0]
    if a64.shape != (n, n) or b64.shape != (n,):
        raise ValueError(f"expected (n, n) and (n,) operands, got "
                         f"{a64.shape} and {b64.shape}")
    own_jobdir = jobdir is None
    jobdir = os.fspath(jobdir) if jobdir else tempfile.mkdtemp(
        prefix="gauss_fleet_")
    os.makedirs(jobdir, exist_ok=True)
    np.save(os.path.join(jobdir, "a.npy"), a64)
    np.save(os.path.join(jobdir, "b.npy"), b64)
    t0 = time.monotonic()
    try:
        x, stats = _supervise(cfg, jobdir, a64, b64)
        from gauss_tpu.verify import checks

        rel = checks.residual_norm(a64, x, b64, relative=True)
        wall = time.monotonic() - t0
        if not (np.isfinite(rel) and rel <= cfg.gate):
            obs.emit("fleet", event="verify_failed", rel_residual=float(rel))
            raise FleetError(
                f"supervised solve finished but missed the verification "
                f"gate: relative residual {rel:.3e} > {cfg.gate:.0e} "
                f"(rung {stats['rung']})")
        result = FleetResult(x=x, rel_residual=float(rel),
                             wall_s=round(wall, 4), **stats)
        obs.emit("fleet", event="done", rung=result.rung,
                 restarts=result.restarts, stalls=result.stalls,
                 shrinks=result.shrinks, world=result.world,
                 resume_latency_s=result.resume_latency_s,
                 rel_residual=result.rel_residual, wall_s=result.wall_s)
        return result
    finally:
        if own_jobdir and not cfg.keep:
            shutil.rmtree(jobdir, ignore_errors=True)


def _supervise(cfg: FleetConfig, jobdir: str, a64, b64):
    run_id = os.environ.get("GAUSS_OBS_RUN_ID") or obs.new_run_id()
    world = cfg.workers
    restarts = peer_respawns = stalls = kills = shrinks = 0
    rung_index = 0
    resume_latencies: List[float] = []
    pending_detect: Dict[int, float] = {}   # worker id -> detection time
    attempts: Dict[int, int] = {}
    deadline = time.monotonic() + cfg.job_timeout_s

    def faults_for(wid: int) -> Optional[str]:
        # Fault plans model the ENVIRONMENT's one-shot misbehavior: only
        # first spawns inherit them — a replacement re-running the same
        # GAUSS_FAULTS would deterministically re-die forever.
        if cfg.inject and attempts.get(wid, 0) == 0 and (
                cfg.inject_worker is None or cfg.inject_worker == wid):
            return cfg.inject
        return None

    def spawn(wid: int) -> _Worker:
        w = _spawn_worker(jobdir, cfg, wid, world, run_id,
                          attempts.get(wid, 0), faults_for(wid))
        attempts[wid] = attempts.get(wid, 0) + 1
        return w

    flight_dir = os.path.join(jobdir, "flight")

    def capture(cause: str, w: _Worker, **detail) -> None:
        # Freeze the failed worker's flight ring into a post-mortem bundle
        # the moment the failure is detected — before a replacement spawns
        # and telemetry moves on. Best-effort: diagnostics never take the
        # supervised job down.
        try:
            from gauss_tpu.obs import postmortem as _postmortem

            _postmortem.capture_bundle(
                _postmortem.default_bundles_dir(flight_dir), cause,
                flight_dir=flight_dir,
                heartbeat_path=lease_path(jobdir, w.id),
                extra={"worker": w.id, **detail})
        except Exception:  # pragma: no cover
            pass

    obs.emit("fleet", event="launch", workers=world, n=int(a64.shape[0]),
             chunk=cfg.chunk, jobdir=os.path.basename(jobdir))
    workers = [spawn(w) for w in range(world)]
    beaten: Dict[int, bool] = {}

    def finish_stats(final_world: int):
        return {"rung": RUNGS[rung_index], "rung_index": rung_index,
                "restarts": restarts, "peer_respawns": peer_respawns,
                "stalls": stalls, "kills": kills, "shrinks": shrinks,
                "world": final_world,
                "resume_latency_s": (round(max(resume_latencies), 4)
                                     if resume_latencies else None)}

    def note_resume(w: _Worker):
        # resume latency: death detection -> the replacement's first beat
        if w.id in pending_detect and _lease_fresh(jobdir, w):
            resume_latencies.append(
                time.monotonic() - pending_detect.pop(w.id))

    try:
        while True:
            x = _read_result(jobdir)
            if x is not None:
                for w in workers:
                    _kill_worker(w)
                return x, finish_stats(world)
            if time.monotonic() > deadline:
                obs.emit("fleet", event="job_timeout",
                         timeout_s=cfg.job_timeout_s)
                break  # -> local_finish

            replace: List[_Worker] = []
            degrade = False
            obs.gauge("fleet.world", world)
            for w in workers:
                rc = w.proc.poll()
                if rc is None:
                    if not beaten.get(w.id) and _lease_fresh(jobdir, w):
                        beaten[w.id] = True
                        note_resume(w)
                    # Heartbeat age as a live gauge per worker: the
                    # supervisor's failure-detection input, scraped on
                    # /metrics when the live plane is on (gauss-fleet
                    # --live-port) so a stalling worker is visible before
                    # the stall threshold kills it.
                    obs.gauge(f"fleet.w{w.id}.heartbeat_age_s",
                              round(time.monotonic()
                                    - _last_activity(jobdir, w), 3))
                    # Freshness, not existence: a respawned worker still
                    # importing jax must get the startup grace even though
                    # its dead predecessor's lease file is present.
                    grace = (cfg.stall_after_s if _lease_fresh(jobdir, w)
                             else cfg.startup_grace_s)
                    if time.monotonic() - _last_activity(jobdir, w) > grace:
                        stalls += 1
                        kills += 1
                        obs.counter("fleet.stalls")
                        obs.emit("fleet", event="worker_stalled",
                                 worker=w.id,
                                 stale_s=round(time.monotonic()
                                               - _last_activity(jobdir, w),
                                               3))
                        capture("fleet_worker_stalled", w,
                                stale_s=round(time.monotonic()
                                              - _last_activity(jobdir, w),
                                              3))
                        _kill_worker(w)
                        pending_detect.setdefault(w.id, time.monotonic())
                        replace.append(w)
                    continue
                if rc == 0:
                    _reap(w)
                    continue
                _reap(w)
                cause = exit_cause(rc)
                if counts_against_restart_budget(cause):
                    # A peer_lost exit is a secondary casualty of a death
                    # already bundled — bundling it too would storm one
                    # bundle per surviving worker per fault. A drained
                    # exit is not a failure at all; neither gets a bundle.
                    capture("fleet_worker_dead", w, rc=rc, exit_cause=cause)
                if cause == "config":
                    raise FleetError(
                        f"worker {w.id} exited with a configuration/"
                        f"checkpoint mismatch (exit {rc}); see "
                        f"{jobdir}/logs/")
                kills += counts_against_restart_budget(cause)
                obs.counter("fleet.worker_deaths")
                obs.emit("fleet", event="worker_dead", worker=w.id, rc=rc,
                         cause=cause)
                pending_detect.setdefault(w.id, time.monotonic())
                replace.append(w)

            for w in replace:
                dead_cause = exit_cause(w.proc.returncode)
                if dead_cause == "peer_lost" \
                        and peer_respawns < cfg.max_peer_respawns:
                    peer_respawns += 1
                elif dead_cause == "drained":
                    # Graceful drain: the replacement is free — an
                    # operator rolling workers must not spend the crash
                    # budget (the stall path killed via SIGKILL, so a
                    # stalled worker still lands in the bounded branch).
                    pass
                elif restarts < cfg.max_restarts:
                    restarts += 1
                    rung_index = max(rung_index, 1)
                else:
                    degrade = True
                    continue
                beaten[w.id] = False
                nw = spawn(w.id)
                workers[workers.index(w)] = nw
                obs.counter("fleet.restarts")
                obs.emit("fleet", event="restart", worker=w.id,
                         attempt=attempts[w.id], world=world)

            if degrade:
                if world - 1 >= cfg.min_workers:
                    world -= 1
                    shrinks += 1
                    rung_index = max(rung_index, 2)
                    obs.counter("fleet.shrinks")
                    obs.emit("fleet", event="shrink", world=world)
                    for w in workers:
                        _kill_worker(w)
                    beaten.clear()
                    workers = [spawn(w) for w in range(world)]
                else:
                    break  # -> local_finish
            time.sleep(cfg.poll_s)
    finally:
        for w in workers:
            _kill_worker(w)

    # Last rung: the supervisor finishes the job itself, in-process, from
    # the newest good generation (world-size-independent assembly).
    rung_index = 3
    obs.counter("fleet.local_finish")
    obs.emit("fleet", event="local_finish")
    from gauss_tpu.resilience import dcheckpoint

    try:
        fac, _ = dcheckpoint.factor_sharded(
            a64.astype(np.float32), os.path.join(jobdir, "ckpt"), 0, 1,
            panel=cfg.panel, chunk=cfg.chunk,
            barrier_deadline_s=cfg.barrier_deadline_s)
        x = _solve_refined(fac, a64, b64, cfg.refine_iters)
    except Exception as e:  # noqa: BLE001 — the ladder's true bottom
        raise FleetError(
            f"local_finish rung failed after fleet supervision was "
            f"exhausted: {type(e).__name__}: {e}") from e
    return x, finish_stats(0)


# -- the worker subprocess entry -------------------------------------------

def _worker_main(args) -> int:
    from gauss_tpu.utils.env import honor_jax_platforms

    honor_jax_platforms()
    from gauss_tpu.tune import compilecache as _cc

    # Join the supervisor's persistent compile cache when the env channel
    # names one (no-op — and no extra jax config — otherwise).
    _cc.enable_from_env()
    # Flight recorder: when the supervisor handed us a flight dir, every
    # obs event also lands in an mmap ring that survives kill -9 — the
    # crash-telemetry the supervisor bundles on worker death/stall.
    from gauss_tpu.obs import flight as _flight

    _flight.install_from_env()
    jobdir = os.fspath(args.jobdir)
    wid, world = args.worker_id, args.num_workers
    a64 = np.load(os.path.join(jobdir, "a.npy"))
    b64 = np.load(os.path.join(jobdir, "b.npy"))
    stream = os.path.join(jobdir, "obs", f"fleet.p{wid}.jsonl")
    run_id = os.environ.get("GAUSS_OBS_RUN_ID")

    from gauss_tpu.resilience import dcheckpoint
    from gauss_tpu.resilience.checkpoint import CheckpointMismatchError
    from gauss_tpu.resilience.watchdog import WorkerLostError

    with obs.run(metrics_out=stream, run_id=run_id, tool="fleet_worker",
                 worker=wid, world=world):
        beat(phase="start")
        try:
            fac, stats = dcheckpoint.factor_sharded(
                a64.astype(np.float32), os.path.join(jobdir, "ckpt"),
                wid, world, panel=args.panel, chunk=args.chunk, beat=beat)
            if wid == 0:
                beat(phase="solve")
                x = _solve_refined(fac, a64, b64, args.refine_iters)
                _write_result(jobdir, x)
            beat(phase="done", resumed_from=stats["resumed_from"])
        except WorkerLostError as e:
            obs.emit("fleet", event="peer_lost", worker=wid, site=e.site)
            beat(phase="peer_lost")
            return PEER_LOST_EXIT
        except CheckpointMismatchError as e:
            print(f"fleet worker {wid}: {e}", file=sys.stderr)
            return CONFIG_EXIT
    return 0


# -- CLI -------------------------------------------------------------------

def history_records(summary: dict):
    """(metric, value, unit) records a fleet solve contributes to the
    regression history — all slow-side gated: recovery getting WORSE shows
    as a deeper rung, more restarts, or a longer resume."""
    out = []
    ri = summary.get("rung_index")
    if isinstance(ri, int):
        out.append(("fleet:rung_depth", ri + 1, "rung"))
    lat = summary.get("resume_latency_s")
    if isinstance(lat, (int, float)) and lat > 0:
        out.append(("fleet:resume_latency_s", lat, "s"))
    restarts = (summary.get("restarts") or 0) + (summary.get("stalls") or 0)
    if restarts > 0:
        out.append(("fleet:restarts", restarts, "count"))
    wall = summary.get("wall_s")
    if isinstance(wall, (int, float)) and wall > 0:
        out.append(("fleet:s_per_solve", round(wall, 4), "s"))
    return out


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="gauss-fleet",
        description="Supervised multi-worker solve: lease heartbeats, "
                    "sharded coordinated checkpoints, restart-and-resume, "
                    "elastic degrade. Finishes with a verified solution or "
                    "a typed error — never a hang.")
    p.add_argument("-s", "--size", type=int, default=96,
                   help="generate a seeded diagonally-dominant system of "
                        "this size (default 96)")
    p.add_argument("--seed", type=int, default=258458)
    p.add_argument("--a", dest="a_path", default=None, metavar="A.npy")
    p.add_argument("--b", dest="b_path", default=None, metavar="B.npy")
    p.add_argument("--workers", type=int, default=2)
    p.add_argument("--panel", type=int, default=None)
    p.add_argument("--chunk", type=int, default=1)
    p.add_argument("--stall-after", type=float, default=10.0,
                   help="seconds of stale lease before a live worker is "
                        "declared stalled and killed (default 10)")
    p.add_argument("--barrier-deadline", type=float, default=60.0,
                   help="worker-side watchdog deadline on coordination "
                        "barriers (default 60)")
    p.add_argument("--max-restarts", type=int, default=2)
    p.add_argument("--min-workers", type=int, default=1)
    p.add_argument("--job-timeout", type=float, default=600.0)
    p.add_argument("--inject", default=None, metavar="PLAN",
                   help="GAUSS_FAULTS plan for first-spawn workers (e.g. "
                        "'fleet.worker.group=kill:skip=1')")
    p.add_argument("--inject-worker", type=int, default=None,
                   help="restrict --inject to this worker id (default all)")
    p.add_argument("--jobdir", default=None)
    p.add_argument("--live-port", type=int, default=None, metavar="PORT",
                   help="embed the live telemetry endpoint on PORT "
                        "(0 = ephemeral): /metrics exposes per-worker "
                        "heartbeat ages, world size, restart/stall/shrink "
                        "counters while the supervised solve runs "
                        "(read with gauss-top)")
    p.add_argument("--compile-cache", default=None, metavar="DIR",
                   help="persistent XLA compile-cache dir shared by the "
                        "supervisor and every (re)spawned worker via the "
                        "GAUSS_COMPILE_CACHE env channel — restarted "
                        "workers resume with a warm cache; compare the "
                        "summary's resume_latency_s across a cold and a "
                        "warm run (also honored from the env)")
    p.add_argument("--keep", action="store_true",
                   help="keep the job directory (checkpoints, logs, leases)")
    p.add_argument("--metrics-out", default=None, metavar="PATH")
    p.add_argument("--summary-json", default=None, metavar="PATH",
                   help="write the regress-ingestable summary "
                        "(kind=fleet_solve)")
    p.add_argument("--history", nargs="?", const="", default=None,
                   metavar="PATH",
                   help="append fleet recovery metrics to the regression "
                        "history (default reports/history.jsonl)")
    p.add_argument("--regress-check", action="store_true")
    # internal worker mode (spawned by the supervisor)
    p.add_argument("--worker", action="store_true", help=argparse.SUPPRESS)
    p.add_argument("--worker-id", type=int, default=0,
                   help=argparse.SUPPRESS)
    p.add_argument("--num-workers", type=int, default=1,
                   help=argparse.SUPPRESS)
    p.add_argument("--refine-iters", type=int, default=2,
                   help=argparse.SUPPRESS)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.worker:
        return _worker_main(args)

    from gauss_tpu.utils.env import honor_jax_platforms

    honor_jax_platforms()
    if args.a_path:
        a = np.load(args.a_path)
        b = (np.load(args.b_path) if args.b_path
             else np.ones(a.shape[0]))
    else:
        rng = np.random.default_rng(args.seed)
        n = args.size
        a = rng.standard_normal((n, n))
        a[np.arange(n), np.arange(n)] += float(n)
        b = rng.standard_normal(n)

    from gauss_tpu.tune import compilecache as _cc

    # Enable on the supervisor too (the local_finish rung compiles here),
    # and export the env channel so workers inherit it.
    cache_dir = _cc.enable(args.compile_cache)
    cfg = FleetConfig(workers=args.workers, panel=args.panel,
                      chunk=args.chunk, stall_after_s=args.stall_after,
                      barrier_deadline_s=args.barrier_deadline,
                      max_restarts=args.max_restarts,
                      min_workers=args.min_workers,
                      job_timeout_s=args.job_timeout, inject=args.inject,
                      inject_worker=args.inject_worker, keep=args.keep,
                      compile_cache_dir=cache_dir)
    live_server = live_prev = None
    if args.live_port is not None:
        from gauss_tpu.obs import export as _export
        from gauss_tpu.obs import live as _live

        agg = _live.LiveAggregator()
        live_prev = _live.install(agg)
        live_server = _export.LiveServer(agg, port=args.live_port).start()
        print(f"live telemetry: {live_server.url}/metrics "
              f"(watch with: gauss-top --url {live_server.url})")

    t0 = time.monotonic()
    error = None
    try:
        with obs.run(metrics_out=args.metrics_out, tool="gauss_fleet",
                     n=int(a.shape[0]), workers=args.workers) as rec:
            run_id = rec.run_id
            try:
                res = solve_supervised(a, b, config=cfg, jobdir=args.jobdir)
            except (FleetError, ValueError) as e:
                error = e
    finally:
        if live_server is not None:
            live_server.stop()
            from gauss_tpu.obs import live as _live

            _live.uninstall(live_prev)

    if error is not None:
        print(f"gauss-fleet: FAILED (typed): {type(error).__name__}: "
              f"{error}", file=sys.stderr)
        return 2
    print(f"gauss-fleet: n={a.shape[0]} workers={args.workers} -> "
          f"rung={res.rung} restarts={res.restarts} stalls={res.stalls} "
          f"shrinks={res.shrinks} rel_residual={res.rel_residual:.3e} "
          f"({res.wall_s:.2f} s)")
    if res.resume_latency_s is not None:
        cache_note = (f"warm compile cache: {cache_dir}" if cache_dir
                      else "cold: no compile cache")
        print(f"  worst resume latency: {res.resume_latency_s:.3f} s "
              f"({cache_note})")

    summary = {"kind": "fleet_solve", "n": int(a.shape[0]),
               "workers": args.workers, "seed": args.seed,
               "rung": res.rung, "rung_index": res.rung_index,
               "restarts": res.restarts, "peer_respawns": res.peer_respawns,
               "stalls": res.stalls, "kills": res.kills,
               "shrinks": res.shrinks, "world": res.world,
               "resume_latency_s": res.resume_latency_s,
               "rel_residual": res.rel_residual, "verified": True,
               "wall_s": round(time.monotonic() - t0, 3),
               "inject": args.inject,
               # the resume-latency decode key: a cold run (None) vs a
               # warm-cache run (dir) — compare resume_latency_s across
               # the pair to see what the persistent cache buys a restart
               "compile_cache": cache_dir}
    if args.summary_json:
        parent = os.path.dirname(args.summary_json)
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(args.summary_json, "w") as f:
            json.dump(summary, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"summary: {args.summary_json}")

    rc = 0
    from gauss_tpu.obs import regress

    # Source carries the run id: epochs of a DISCRETE metric (rung_depth=2
    # every green run) must still accumulate as separate history samples —
    # append_history dedups on (metric, value, source).
    records = [{"metric": m, "value": v, "unit": u,
                "source": f"fleet:{run_id}", "kind": "fleet"}
               for m, v, u in history_records(summary)]
    if args.regress_check and records:
        history_path = args.history or regress.default_history_path()
        verdicts = regress.check_records(records,
                                         regress.load_history(history_path))
        print(regress.format_verdicts(verdicts))
        if any(v["status"] == "out-of-band" for v in verdicts):
            rc = 1
    if args.history is not None and records and rc == 0:
        history_path = args.history or regress.default_history_path()
        added = regress.append_history(records, history_path)
        print(f"history: {added} record(s) appended to {history_path}")
    return rc


if __name__ == "__main__":
    sys.exit(main())
