"""gauss_tpu.resilience — fault injection, recovery ladders, checkpointed solves.

The reference programs simply abort on a bad pivot or malformed input, and
the obs layer so far only *observes* trouble (health records min-pivot /
growth / residual but nothing acts on it). This package closes the loop, the
chaos-engineering way production serving stacks do:

- :mod:`gauss_tpu.resilience.inject` — a seeded, deterministic
  fault-injection framework. Named hook points threaded through core, serve,
  and dist (see docs/RESILIENCE.md for the catalog) poll an installed
  :class:`FaultPlan`; off by default with zero hot-path cost.
- :mod:`gauss_tpu.resilience.recover` — ``solve_resilient(a, b)``: every
  result gated on the health monitors (finite / min-pivot / 1e-4 relative
  residual), failures escalated along an explicit ladder (pivot-safe
  refactor -> double-single refinement -> alternate engine -> host NumPy
  f64), each step an obs ``recovery`` event, a typed
  :class:`UnrecoverableSolveError` only when the ladder is exhausted.
- :mod:`gauss_tpu.resilience.checkpoint` — panel-granular checkpoint/resume
  for the chunked blocked factorization: a killed long solve resumes from
  the last checkpoint, bit-identical to an uninterrupted run.
- :mod:`gauss_tpu.resilience.watchdog` — deadlines around blocking
  collectives and coordination barriers: a dead or stalled peer surfaces as
  a typed :class:`WorkerLostError`, never an infinite block.
- :mod:`gauss_tpu.resilience.dcheckpoint` — the SHARDED, coordinated form
  of the checkpoint for multi-worker solves: per-worker atomic carry
  shards, a digest-bearing coordinator manifest per generation, last-good
  retention, world-size-independent assembly.
- :mod:`gauss_tpu.resilience.fleet` — the supervisor (``gauss-fleet``):
  lease-file heartbeats, dead/stalled worker classification,
  restart-and-resume from the sharded checkpoint, and elastic degrade
  (shrink the world, or finish in-process) — a verified solution or a
  typed :class:`FleetError`, never a hang.
- :mod:`gauss_tpu.resilience.abft` — algorithm-based fault tolerance:
  checksum-carrying LU/Cholesky/matmul that DETECT silent data corruption
  within one panel group (Huang–Abraham column-checksum invariant,
  verified on-device per group), LOCALIZE it, and REPAIR it by replaying
  just the affected group from the last verified carry — bit-identical to
  an uninterrupted run — escalating (typed
  :class:`~gauss_tpu.resilience.abft.SDCUnrecoverableError`) to the full
  recovery ladder only when replay fails. ``abft=False`` paths stay
  bit-identical to the pre-ABFT solvers at zero cost.
- :mod:`gauss_tpu.resilience.chaos` — the campaign runner
  (``python -m gauss_tpu.resilience.chaos``): seeded randomized fault plans
  swept across engines and hook points, asserting the one invariant that
  matters — every injected fault is either recovered (verified solution) or
  surfaced as a typed error; never a silent wrong answer.
- :mod:`gauss_tpu.resilience.abftcheck` — the ABFT campaign
  (``make abft-check``): >= 100 seeded on-device ``sdc_bitflip`` faults
  across LU + Cholesky, 100% detection / localized-replay recovery /
  bit-identity asserted, with the abft-off zero-overhead contract pinned
  to the regression history.

``inject`` is imported eagerly (it is stdlib+numpy only and the hook points
in core/serve/dist reference it at module load); the other submodules import
the solver stack and load lazily via ``__getattr__`` to keep
``core -> inject`` dependency-cycle-free.
"""

from gauss_tpu.resilience.inject import (  # noqa: F401
    FaultPlan,
    FaultSpec,
    SimulatedCompileError,
    SimulatedFaultError,
)

_LAZY = ("recover", "checkpoint", "chaos", "inject", "watchdog",
         "dcheckpoint", "fleet")


def __getattr__(name):
    if name == "UnrecoverableSolveError":
        from gauss_tpu.resilience.recover import UnrecoverableSolveError

        return UnrecoverableSolveError
    if name == "solve_resilient":
        from gauss_tpu.resilience.recover import solve_resilient

        return solve_resilient
    if name == "WorkerLostError":
        from gauss_tpu.resilience.watchdog import WorkerLostError

        return WorkerLostError
    if name == "FleetError":
        from gauss_tpu.resilience.fleet import FleetError

        return FleetError
    if name == "solve_supervised":
        from gauss_tpu.resilience.fleet import solve_supervised

        return solve_supervised
    if name in _LAZY:
        import importlib

        return importlib.import_module(f"gauss_tpu.resilience.{name}")
    raise AttributeError(f"module 'gauss_tpu.resilience' has no attribute {name!r}")
