"""Sharded coordinated checkpointing for supervised multi-worker solves.

PR 4's :mod:`gauss_tpu.resilience.checkpoint` made ONE process's chunked
factorization killable: the outer-loop carry of ``blocked._factor_group``
is serialized between groups and a resume is bit-identical to an
uninterrupted run. A supervised FLEET (gauss_tpu.resilience.fleet) needs the
distributed form of the same promise, and this module provides it:

- **Sharded persistence.** Each worker atomically writes only its own
  checkpoint shard — the panel-block rows it owns under block-cyclic
  assignment (global panel block ``k`` belongs to worker ``k % W``, the same
  striping the distributed engines use for rows) plus its owned
  diagonal-block inverses; the tiny replicated carry pieces (``perm``,
  ``min_piv``) ride in every shard. No single worker ever writes — or needs
  to hold the write bandwidth for — the whole state.
- **Coordinated generations.** A generation is complete only when worker 0
  has observed every shard of it and published ``MANIFEST.json`` naming the
  per-shard SHA-256 digests. The manifest wait doubles as the per-group
  barrier: every worker advances group-lockstep, which is what makes a
  stale heartbeat unambiguous (a worker that stops beating is dead or
  stalled, not merely ahead). The wait runs under the collective watchdog,
  so a dead peer surfaces as a typed
  :class:`~gauss_tpu.resilience.watchdog.WorkerLostError`, never a hang.
- **Last-good retention.** The two most recent manifested generations are
  kept; a kill at ANY instant — mid shard write (tmp+rename+fsync), mid
  manifest publish — leaves a complete older generation to resume from.
  Corrupt or digest-mismatched shards disqualify their generation (typed,
  observable) and the previous one is used; a manifest from a DIFFERENT
  (operand, statics) factorization raises
  :class:`~gauss_tpu.resilience.checkpoint.CheckpointMismatchError`.
- **World-size-independent layout.** Shards name their world in the
  filename (``shard-03-of-08.npz``) and assembly walks global panel blocks,
  so a carry checkpointed by W workers restores onto W' workers — the
  mechanism behind the fleet's elastic degrade (re-shard onto the surviving
  mesh, or onto the supervisor itself as the last rung).

Compute per group is the SAME jitted ``blocked._factor_group`` step the
single-process checkpoint uses — every worker derives the identical carry,
the way the distributed blocked engines replicate their panel factorization
to buy pivot agreement without collectives (docs/SCALING.md). On a TPU pod
the group step would be the shard_map program and each process would
serialize its addressable shards; this CPU-rehearsable form keeps the
coordination protocol — the thing the fleet supervises and chaos-tests —
byte-for-byte identical while the per-worker compute stays local. Because
every group step is deterministic over bit-identical carry inputs,
kill -> restart -> resume (even onto a different world size) finishes
**bit-identical to an uninterrupted supervised run**.

Hook point ``fleet.worker.group`` fires between groups in every worker:
kind ``kill`` is the preempted-VM stand-in, ``stall`` the hung worker the
watchdog must catch, ``raise`` the in-process variant.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import shutil
import tempfile
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from gauss_tpu import obs
from gauss_tpu.resilience import inject as _inject
from gauss_tpu.resilience import watchdog
from gauss_tpu.resilience.checkpoint import (
    CheckpointMismatchError,
    SCHEMA,
    _digest,
    _group_step_jit,
    fsync_dir,
)

MANIFEST = "MANIFEST.json"
#: manifested generations kept on disk (current + last-good fallback)
KEEP_GENERATIONS = 2

_GEN_RE = re.compile(r"^gen-(\d+)$")


def owned_blocks(nb: int, worker: int, world: int) -> List[int]:
    """Global panel-block indices worker ``worker`` owns out of ``nb``
    (block-cyclic: block k -> worker k % world)."""
    return [k for k in range(nb) if k % world == worker]


def gen_dir(ckptdir: str, next_group: int) -> str:
    return os.path.join(ckptdir, f"gen-{next_group:05d}")


def shard_name(worker: int, world: int) -> str:
    """World size rides in the NAME so a partially-written generation from
    a differently-sized world (pre-shrink leftovers) can never satisfy the
    new world's barrier or be hashed into its manifest."""
    return f"shard-{worker:02d}-of-{world:02d}.npz"


def _file_digest(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()[:16]


def _atomic_write(path: str, write_fn) -> int:
    """tmp + fsync + rename + parent fsync; returns bytes written."""
    parent = os.path.dirname(path) or "."
    os.makedirs(parent, exist_ok=True)
    fd, tmp = tempfile.mkstemp(prefix=os.path.basename(path) + ".",
                               suffix=".tmp", dir=parent)
    try:
        with os.fdopen(fd, "wb") as f:
            write_fn(f)
            f.flush()
            os.fsync(f.fileno())
        nbytes = os.path.getsize(tmp)
        os.replace(tmp, path)
        fsync_dir(parent)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return nbytes


def write_shard(ckptdir: str, next_group: int, worker: int, world: int, *,
                meta: dict, m, perm, min_piv, linvs, uinvs,
                panel: int) -> str:
    """Atomically write worker ``worker``'s shard of the generation whose
    carry is about to process group ``next_group``. The shard holds ONLY
    the rows / diagonal-block inverses of the panel blocks this worker owns
    (plus the tiny replicated ``perm``/``min_piv``). Returns the shard
    path."""
    m = np.asarray(m)
    nb = m.shape[0] // panel
    blocks = owned_blocks(nb, worker, world)
    rows = np.concatenate([m[k * panel:(k + 1) * panel] for k in blocks]) \
        if blocks else np.empty((0, m.shape[1]), m.dtype)
    linvs = np.asarray(linvs)
    uinvs = np.asarray(uinvs)
    done = [k for k in blocks if k < linvs.shape[0]]
    path = os.path.join(gen_dir(ckptdir, next_group),
                        shard_name(worker, world))
    payload = {
        "meta": np.frombuffer(json.dumps(
            {**meta, "worker": worker, "world": world,
             "next_group": next_group}, sort_keys=True).encode(), np.uint8),
        "blocks": np.asarray(blocks, np.int64),
        "m_rows": rows,
        "perm": np.asarray(perm),
        "min_piv": np.asarray(min_piv),
        "done_blocks": np.asarray(done, np.int64),
        "linvs": linvs[done] if done else np.empty((0,) + linvs.shape[1:],
                                                   linvs.dtype),
        "uinvs": uinvs[done] if done else np.empty((0,) + uinvs.shape[1:],
                                                   uinvs.dtype),
    }
    _atomic_write(path, lambda f: np.savez(f, **payload))
    return path


def _load_shard(path: str) -> dict:
    try:
        with np.load(path) as z:
            out = {k: np.array(z[k]) for k in
                   ("blocks", "m_rows", "perm", "min_piv", "done_blocks",
                    "linvs", "uinvs")}
            out["meta"] = json.loads(bytes(z["meta"]).decode())
    except Exception as e:  # noqa: BLE001 — any parse failure means corrupt
        raise CheckpointMismatchError(
            f"checkpoint shard at {path} is truncated or corrupt "
            f"({type(e).__name__}: {e})") from e
    return out


def try_publish_manifest(ckptdir: str, next_group: int, world: int,
                         meta: dict) -> bool:
    """Coordinator step (worker 0): if every shard of this generation is
    present, hash them and atomically publish MANIFEST.json. Returns True
    once the manifest exists (already-published counts). The generation is
    resumable if and only if this file exists and its digests verify."""
    gdir = gen_dir(ckptdir, next_group)
    if os.path.exists(os.path.join(gdir, MANIFEST)):
        return True
    names = [shard_name(w, world) for w in range(world)]
    if not all(os.path.exists(os.path.join(gdir, nm)) for nm in names):
        return False
    doc = {"schema": SCHEMA, "meta": meta, "next_group": next_group,
           "world": world,
           "shards": {nm: _file_digest(os.path.join(gdir, nm))
                      for nm in names}}
    _atomic_write(os.path.join(gdir, MANIFEST),
                  lambda f: f.write(json.dumps(doc, sort_keys=True,
                                               indent=1).encode()))
    return True


def _generations(ckptdir: str) -> List[int]:
    if not os.path.isdir(ckptdir):
        return []
    gens = []
    for name in os.listdir(ckptdir):
        mm = _GEN_RE.match(name)
        if mm:
            gens.append(int(mm.group(1)))
    return sorted(gens)


def gc_generations(ckptdir: str, keep: int = KEEP_GENERATIONS) -> None:
    """Drop everything older than the ``keep`` newest manifested
    generations (unmanifested partials below them included). Best-effort —
    a racing reader that loses its generation falls back via last_good."""
    manifested = [g for g in _generations(ckptdir)
                  if os.path.exists(os.path.join(gen_dir(ckptdir, g),
                                                 MANIFEST))]
    if len(manifested) <= keep:
        return
    floor = manifested[-keep]
    for g in _generations(ckptdir):
        if g < floor:
            shutil.rmtree(gen_dir(ckptdir, g), ignore_errors=True)


def last_good(ckptdir: str, meta: dict) -> Optional[Tuple[int, dict]]:
    """Newest generation whose manifest verifies end to end: manifest
    parses, meta matches, every named shard exists with the recorded
    digest. Digest/corruption failures disqualify the generation (observed,
    typed internally) and the scan continues downward; a VALID manifest for
    a different (operand, statics) factorization raises — that is operator
    error, not a torn write. Returns ``(next_group, manifest)`` or None."""
    for g in reversed(_generations(ckptdir)):
        mpath = os.path.join(gen_dir(ckptdir, g), MANIFEST)
        if not os.path.exists(mpath):
            continue
        try:
            doc = json.loads(open(mpath).read())
            shards = doc["shards"]
        except Exception:  # noqa: BLE001 — torn manifest: not last-good
            obs.emit("checkpoint", event="corrupt", path=mpath)
            continue
        if doc.get("meta") != meta:
            raise CheckpointMismatchError(
                f"sharded checkpoint at {ckptdir} (generation {g}) does not "
                f"match this factorization: checkpoint {doc.get('meta')}, "
                f"requested {meta}")
        ok = True
        for nm, digest in shards.items():
            spath = os.path.join(gen_dir(ckptdir, g), nm)
            if not (os.path.exists(spath)
                    and _file_digest(spath) == digest):
                obs.counter("resilience.checkpoint.corrupt")
                obs.emit("checkpoint", event="corrupt", path=spath)
                ok = False
                break
        if ok:
            return g, doc
    return None


def load_carry(ckptdir: str, manifest: dict, *, panel: int,
               npad: int) -> dict:
    """Assemble the full factorization carry from a manifested generation,
    independent of the world size that wrote it (the elastic-degrade
    enabler). Returns ``{"m", "perm", "min_piv", "linvs", "uinvs",
    "next_group"}`` as host numpy arrays."""
    g = int(manifest["next_group"])
    gdir = gen_dir(ckptdir, g)
    shards = [_load_shard(os.path.join(gdir, nm))
              for nm in sorted(manifest["shards"])]
    nb = npad // panel
    m = np.empty((npad, npad), shards[0]["m_rows"].dtype)
    seen = np.zeros(nb, bool)
    done: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
    for sh in shards:
        for i, k in enumerate(sh["blocks"]):
            m[k * panel:(k + 1) * panel] = \
                sh["m_rows"][i * panel:(i + 1) * panel]
            seen[k] = True
        for i, k in enumerate(sh["done_blocks"]):
            done[int(k)] = (sh["linvs"][i], sh["uinvs"][i])
    if not seen.all():
        raise CheckpointMismatchError(
            f"sharded checkpoint generation {g} at {ckptdir} does not cover "
            f"all {nb} panel blocks (missing {np.flatnonzero(~seen)[:8]})")
    panels_done = min(g, nb)
    if sorted(done) != list(range(panels_done)):
        raise CheckpointMismatchError(
            f"sharded checkpoint generation {g} at {ckptdir}: diagonal "
            f"inverses incomplete ({sorted(done)[:8]}... vs "
            f"{panels_done} panels done)")
    dt = shards[0]["linvs"].dtype if panels_done else m.dtype
    linvs = (np.stack([done[k][0] for k in range(panels_done)])
             if panels_done else np.empty((0, panel, panel), dt))
    uinvs = (np.stack([done[k][1] for k in range(panels_done)])
             if panels_done else np.empty((0, panel, panel), dt))
    return {"m": m, "perm": shards[0]["perm"],
            "min_piv": shards[0]["min_piv"], "linvs": linvs, "uinvs": uinvs,
            "next_group": g}


def factor_sharded(a, ckptdir, worker: int, world: int, *,
                   panel: Optional[int] = None,
                   chunk: Optional[int] = None,
                   panel_impl: str = "auto",
                   gemm_precision: str = "highest",
                   beat: Optional[Callable[..., None]] = None,
                   barrier_deadline_s: Optional[float] = None,
                   barrier_poll_s: float = 0.02):
    """One fleet worker's group loop: factor ``a`` with per-group sharded
    checkpoints and a manifest barrier per generation.

    Resumes automatically from the newest verified generation in
    ``ckptdir`` (written by ANY world size). Worker 0 is the coordinator
    (publishes manifests, garbage-collects old generations); everyone else
    blocks on the manifest. Both waits run under the collective watchdog
    (``barrier_deadline_s``, else the process-wide deadline), so a dead or
    stalled peer raises :class:`watchdog.WorkerLostError` for the
    supervisor to act on instead of hanging the job. ``beat`` is invoked
    with progress fields every group AND every barrier poll — a worker
    waiting on a peer is alive and keeps saying so.

    Returns ``(BlockedLU, stats)``; the final generation (``next_group ==
    nb``) is always written and manifested, so a worker killed after
    factorization but before the solve resumes for free.
    """
    import jax
    import jax.numpy as jnp

    from gauss_tpu.core import blocked

    if not 0 <= worker < world:
        raise ValueError(f"worker must be in [0, {world}), got {worker}")
    a = np.asarray(a)
    n = a.shape[0]
    if a.shape != (n, n):
        raise ValueError(f"expected square matrix, got {a.shape}")
    panel = blocked._resolve_panel(n, panel, a.dtype.itemsize)
    chunk = blocked.CHUNK_DEFAULT if chunk is None else chunk
    if chunk < 1:
        raise ValueError(f"chunk must be >= 1, got {chunk}")
    ckptdir = os.fspath(ckptdir)
    beat = beat or (lambda **kw: None)
    meta = {"schema": SCHEMA, "n": n, "panel": panel, "chunk": chunk,
            "panel_impl": panel_impl, "gemm_precision": gemm_precision,
            "dtype": str(a.dtype), "digest": _digest(a)}

    m = blocked._pad_to_panel(jnp.asarray(a), panel)
    npad = m.shape[0]
    nb = npad // panel
    start_group = 0
    perm = jnp.arange(npad)
    min_piv = jnp.asarray(jnp.inf, m.dtype)
    linvs = np.empty((0, panel, panel), np.dtype(str(m.dtype)))
    uinvs = linvs.copy()

    good = last_good(ckptdir, meta)
    if good is not None:
        g, manifest = good
        carry = load_carry(ckptdir, manifest, panel=panel, npad=npad)
        m = jnp.asarray(carry["m"])
        perm = jnp.asarray(carry["perm"])
        min_piv = jnp.asarray(carry["min_piv"])
        linvs, uinvs = carry["linvs"], carry["uinvs"]
        start_group = int(carry["next_group"])
        obs.counter("resilience.checkpoint.resumes")
        obs.emit("checkpoint", event="resume", path=ckptdir,
                 next_group=start_group, worker=worker, world=world)

    step = _group_step_jit(panel, chunk, panel_impl, gemm_precision)
    stats = {"resumed_from": start_group if good else None,
             "gens_written": 0}

    def _barrier(next_group: int, phase: str):
        beat(phase=phase, group=next_group)
        if worker == 0:
            watchdog.wait_for(
                lambda: try_publish_manifest(ckptdir, next_group, world,
                                             meta),
                site="fleet.manifest.publish", deadline_s=barrier_deadline_s,
                poll_s=barrier_poll_s,
                on_tick=lambda: beat(phase=phase, group=next_group))
            gc_generations(ckptdir)
        else:
            watchdog.wait_for(
                lambda: os.path.exists(os.path.join(
                    gen_dir(ckptdir, next_group), MANIFEST)),
                site="fleet.manifest.wait", deadline_s=barrier_deadline_s,
                poll_s=barrier_poll_s,
                on_tick=lambda: beat(phase=phase, group=next_group))

    for g0 in range(start_group, nb, chunk):
        # Hook point "fleet.worker.group": preemption (kill), a hang
        # (stall), or the in-process stand-in (raise) BETWEEN groups —
        # the supervisor and watchdog must turn any of them into a
        # restart-and-resume, never a hang or a wrong answer.
        _inject.maybe_kill("fleet.worker.group")
        beat(phase="factor", group=g0)
        m, perm, min_piv, lg, ug = step(m, perm, min_piv, g0=g0)
        jax.block_until_ready(m)
        linvs = np.concatenate([linvs, np.asarray(lg)])
        uinvs = np.concatenate([uinvs, np.asarray(ug)])
        next_group = min(g0 + chunk, nb)
        write_shard(ckptdir, next_group, worker, world, meta=meta, m=m,
                    perm=perm, min_piv=min_piv, linvs=linvs, uinvs=uinvs,
                    panel=panel)
        stats["gens_written"] += 1
        obs.counter("resilience.checkpoint.saves")
        obs.emit("checkpoint", event="save", path=ckptdir,
                 next_group=next_group, worker=worker, world=world)
        _barrier(next_group, phase="barrier")

    if start_group >= nb and nb > 0:
        # Resumed past the last group (killed between factorization and
        # solve): the final generation already exists; nothing to compute.
        _barrier(nb, phase="barrier")

    fac = blocked.BlockedLU(m=m, perm=perm, min_abs_pivot=min_piv,
                            linv=jnp.asarray(linvs),
                            uinv=jnp.asarray(uinvs))
    return fac, stats
