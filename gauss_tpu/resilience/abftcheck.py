"""ABFT campaign runner: ``python -m gauss_tpu.resilience.abftcheck``.

Sweeps seeded on-device ``sdc_bitflip`` faults (gauss_tpu.resilience
.inject) across the checksum-carrying LU and Cholesky engines
(gauss_tpu.resilience.abft) and asserts the SDC invariant the chaos stack
now extends to silent data corruption:

    **every injected on-device corruption is DETECTED by the checksum
    invariant before the final residual gate, LOCALIZED to the panel group
    that produced it, and repaired — by the localized replay rung for
    transient faults (bit-identical to an uninterrupted ABFT run) or by
    escalation through the full recovery ladder for persistent ones — and
    the runner independently verifies every solution at the 1e-4 gate.
    Never a silent wrong answer, never a missed detection.**

Three phases:

- **sdc** (``--cases``): each case draws an engine (LU / Cholesky), a
  size, a panel group, and a transient-or-persistent scenario from a
  seeded catalog, installs an ``sdc_bitflip`` plan at the engine's ABFT
  group site, and runs the full ``recover.solve_resilient`` ladder with
  ABFT on. Replay-recovered solutions must be bit-identical to the
  unfaulted ABFT solve of the same system.
- **identity** (``--no-identity`` to skip): the zero-overhead contract —
  ``abft=False`` paths must be BIT-IDENTICAL to the checksum-carrying
  forms' factor output (the checksum is a rider, never an operand) across
  the flat, chunked, host-stepped-LU, and Cholesky forms, and the plain
  (abft off) solve's seconds-per-solve is recorded as the regression
  sentinel ``abft:plain_s_per_solve`` — checksum machinery creeping into
  the unprotected hot path gates like a perf regression.
- **matmul** (``--no-matmul`` to skip): single-element GEMM corruption
  must be localized to its row x column checksum intersection and
  corrected in place (to checksum precision); wider corruption must be
  repaired by recomputation.

The summary (``--summary-json``) is regress-ingestable
(``kind: abft_campaign``). Exit status: 2 when the invariant is violated
(missed detection, silent wrong answer, bit-identity failure), 1 when
``--regress-check`` finds an out-of-band metric, 0 otherwise.

``make abft-check`` runs the CPU smoke configuration CI gates on (>= 100
injected faults across both engines).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from gauss_tpu.utils.env import honor_jax_platforms

#: scenario catalog: transient dominates ~11:1 (real SDC is overwhelmingly
#: one-shot; the persistent slice exists to prove the escalate-to-ladder
#: path, and budgets the replay-recovery rate at ~92%).
SCENARIOS = (("transient", 11), ("persistent", 1))

#: default sweep sizes — chosen so the LU rung path (panel 16, the ladder's
#: CHUNK_DEFAULT grouping) has >= 2 panel groups to localize across.
LU_SIZES = (96, 128)
CHOL_SIZES = (64, 96)


def _lu_groups(n: int, panel: int) -> int:
    from gauss_tpu.core import blocked

    nb = -(-n // panel)
    return -(-nb // blocked.CHUNK_DEFAULT)


def _system_lu(rng: np.random.Generator, n: int):
    a = rng.standard_normal((n, n))
    a[np.arange(n), np.arange(n)] += float(n)
    return a, rng.standard_normal(n)


def _system_chol(rng: np.random.Generator, n: int):
    from gauss_tpu.io import synthetic

    return np.asarray(synthetic.spd_matrix(n)), rng.standard_normal(n)


def run_sdc_case(i: int, seed: int, gate: float, panel: int = 16,
                 lu_sizes=LU_SIZES, chol_sizes=CHOL_SIZES,
                 clean_cache: Optional[dict] = None) -> Dict:
    """One seeded on-device SDC case; returns its outcome record.

    Shared with the chaos campaign's sdc phase
    (gauss_tpu.resilience.chaos) — one case runner, two harnesses."""
    from gauss_tpu.resilience import abft, inject, recover
    from gauss_tpu.verify import checks

    rng = np.random.default_rng(np.random.SeedSequence((seed, 0xABF7, i)))
    engine = ("lu", "chol")[i % 2]
    names = [s for s, w in SCENARIOS for _ in range(w)]
    scenario = names[int(rng.integers(0, len(names)))]
    if engine == "lu":
        n = int(lu_sizes[int(rng.integers(0, len(lu_sizes)))])
        a, b = _system_lu(np.random.default_rng(
            np.random.SeedSequence((seed, 0, n))), n)
        groups = _lu_groups(n, panel)
        site = abft.SITE_LU
        rungs = None
    else:
        n = int(chol_sizes[int(rng.integers(0, len(chol_sizes)))])
        a, b = _system_chol(np.random.default_rng(
            np.random.SeedSequence((seed, 1, n))), n)
        groups = -(-n // panel)
        site = abft.SITE_CHOL
        rungs = recover.structured_rungs("spd", abft=True)
    group = int(rng.integers(0, groups))

    # The unfaulted ABFT solve of this exact system — the bit-identity
    # reference for replay recovery (cached per (engine, n): the systems
    # are deterministic per campaign seed).
    key = (engine, n)
    if clean_cache is None:
        clean_cache = {}
    if key not in clean_cache:
        if rungs is None:
            clean = recover.solve_resilient(a, b, gate=gate, panel=panel,
                                            abft=True)
        else:
            clean = recover.solve_resilient(a, b, gate=gate, panel=panel,
                                            rungs=rungs)
        clean_cache[key] = clean.x
    clean_x = clean_cache[key]

    spec = inject.FaultSpec(
        site=site, kind="sdc_bitflip", skip=group, seed=i,
        max_triggers=1 if scenario == "transient" else None)
    out = {"case": i, "engine": engine, "n": n, "scenario": scenario,
           "group": group}
    with inject.plan(inject.FaultPlan([spec], seed=seed)) as ap:
        try:
            if rungs is None:
                res = recover.solve_resilient(a, b, gate=gate, panel=panel,
                                              abft=True)
            else:
                res = recover.solve_resilient(a, b, gate=gate, panel=panel,
                                              rungs=rungs)
            rel = checks.residual_norm(a, res.x, b, relative=True)
            sdc = res.sdc or {}
            detected = bool(sdc.get("detections"))
            if not (np.isfinite(rel) and rel <= gate):
                out.update(outcome="silent_wrong", rung=res.rung,
                           rel_residual=float(rel), detected=detected)
            elif res.rung_index == 0 and detected:
                out.update(outcome="replayed", rung=res.rung,
                           detected=True, replays=sdc.get("replays"),
                           detect_groups=sdc.get("detect_groups"),
                           localized=group in (sdc.get("detect_groups")
                                               or []),
                           detect_latency_s=sdc.get("detect_latency_s"),
                           bit_identical=bool(np.array_equal(res.x,
                                                             clean_x)),
                           rel_residual=float(rel))
            elif res.rung_index > 0:
                out.update(outcome="escalated", rung=res.rung,
                           detected=detected, rel_residual=float(rel))
            else:
                out.update(outcome="missed" if ap.stats()["triggered"]
                           else "no_fault", rung=res.rung,
                           detected=detected, rel_residual=float(rel))
        except recover.UnrecoverableSolveError as e:
            out.update(outcome="typed_error", trigger=e.trigger,
                       detected=True)
        except Exception as e:  # noqa: BLE001 — an untyped escape IS the bug
            out.update(outcome="violation",
                       error=f"{type(e).__name__}: {e}"[:200])
        out["injected"] = ap.stats()["triggered"]
    return out


def summarize_sdc_cases(outcomes: List[Dict], wall_s: float) -> Dict:
    counts: Dict[str, int] = {}
    by_engine: Dict[str, int] = {}
    injected = 0
    missed = 0
    bit_fail = 0
    mislocalized = 0
    lats: List[float] = []
    for o in outcomes:
        counts[o["outcome"]] = counts.get(o["outcome"], 0) + 1
        injected += o.get("injected", 0)
        if o.get("injected") and not o.get("detected"):
            missed += 1
        if o["outcome"] == "replayed":
            by_engine[o["engine"]] = by_engine.get(o["engine"], 0) + 1
            if not o.get("bit_identical"):
                bit_fail += 1
            if not o.get("localized"):
                mislocalized += 1
            lats.extend(o.get("detect_latency_s") or [])
    replayed = counts.get("replayed", 0)
    escalated = counts.get("escalated", 0)
    faulted = sum(1 for o in outcomes if o.get("injected"))
    violations = (counts.get("silent_wrong", 0)
                  + counts.get("violation", 0) + missed + bit_fail)
    return {
        "cases": len(outcomes), "counts": counts, "injected": injected,
        "faulted_cases": faulted, "missed": missed,
        "detect_rate": round((faulted - missed) / faulted, 4)
        if faulted else None,
        "replayed": replayed, "escalated": escalated,
        "replay_rate": round(replayed / (replayed + escalated), 4)
        if replayed + escalated else None,
        "replayed_by_engine": by_engine,
        "bit_identity_failures": bit_fail,
        "mislocalized": mislocalized,
        "mean_detect_latency_s": round(float(np.mean(lats)), 6)
        if lats else None,
        "violations": violations, "wall_s": round(wall_s, 3),
    }


def run_sdc_phase(cases: int, seed: int, gate: float, panel: int = 16,
                  log=print) -> Dict:
    from gauss_tpu import obs

    outcomes: List[Dict] = []
    clean_cache: dict = {}
    t0 = time.perf_counter()
    with obs.span("abft_sdc_phase", cases=cases):
        for i in range(cases):
            outcomes.append(run_sdc_case(i, seed, gate, panel=panel,
                                         clean_cache=clean_cache))
            if (i + 1) % 25 == 0:
                log(f"  sdc cases: {i + 1}/{cases}")
    return summarize_sdc_cases(outcomes, time.perf_counter() - t0)


def run_identity_phase(seed: int, reps: int = 3) -> Dict:
    """The zero-overhead / bit-identity contract: abft=False output must
    equal the checksum-carrying forms' factor bit for bit, and the plain
    path's timing is the regression sentinel."""
    import jax
    import jax.numpy as jnp

    from gauss_tpu import obs
    from gauss_tpu.core import blocked
    from gauss_tpu.io import synthetic
    from gauss_tpu.resilience import abft
    from gauss_tpu.structure import cholesky

    rng = np.random.default_rng(np.random.SeedSequence((seed, 0x1DE47)))
    n = 96
    a, b = _system_lu(rng, n)
    a32 = jnp.asarray(a, jnp.float32)
    mismatches: List[str] = []

    def cmp(tag, f0, f1, fields):
        for f in fields:
            if not np.array_equal(np.asarray(getattr(f0, f)),
                                  np.asarray(getattr(f1, f))):
                mismatches.append(f"{tag}.{f}")

    with obs.span("abft_identity_phase"):
        lu_fields = ("m", "perm", "min_abs_pivot", "linv", "uinv")
        cmp("flat", blocked.lu_factor_blocked(a32, panel=16),
            blocked.lu_factor_blocked(a32, panel=16, abft=True), lu_fields)
        ck0 = blocked.lu_factor_blocked_chunked(a32, panel=16, chunk=2)
        cmp("chunked", ck0,
            blocked.lu_factor_blocked_chunked(a32, panel=16, chunk=2,
                                              abft=True), lu_fields)
        stepped, _ = abft.lu_factor_abft(a32, panel=16, chunk=2)
        cmp("stepped", ck0, stepped, lu_fields)
        aspd = jnp.asarray(synthetic.spd_matrix(n), jnp.float32)
        ch0 = cholesky.cholesky_factor_blocked(aspd, panel=16)
        cmp("chol_flat", ch0,
            cholesky.cholesky_factor_blocked(aspd, panel=16, abft=True),
            ("m", "linv", "min_diag"))
        ch_stepped, _ = abft.cholesky_factor_abft(aspd, panel=16)
        cmp("chol_stepped", ch0, ch_stepped, ("m", "linv", "min_diag"))

        # Plain-path timing (abft OFF) — the zero-overhead sentinel; and
        # the protected path's cost as the honest overhead record.
        def best_of(fn):
            fn()  # warmup / compile outside the timed reps
            best = float("inf")
            for _ in range(reps):
                t0 = time.perf_counter()
                jax.block_until_ready(fn())
                best = min(best, time.perf_counter() - t0)
            return best

        plain_s = best_of(
            lambda: blocked.lu_factor_blocked_chunked(a32, panel=16,
                                                      chunk=2).m)
        abft_s = best_of(lambda: abft.lu_factor_abft(a32, panel=16,
                                                     chunk=2)[0].m)
    return {
        "ran": True, "n": n, "bit_identical": not mismatches,
        "mismatches": mismatches,
        "plain_s_per_solve": round(plain_s, 6),
        "abft_s_per_solve": round(abft_s, 6),
        "overhead_ratio": round(abft_s / plain_s, 4) if plain_s else None,
    }


def run_matmul_phase(cases: int, seed: int) -> Dict:
    from gauss_tpu import obs
    from gauss_tpu.resilience import abft, inject

    rng = np.random.default_rng(np.random.SeedSequence((seed, 0x3A73)))
    corrected = recomputed = detections = 0
    max_dev = 0.0
    violations = 0
    with obs.span("abft_matmul_phase", cases=cases):
        for i in range(cases):
            mm, kk, nn = (int(rng.integers(24, 64)) for _ in range(3))
            a = rng.standard_normal((mm, kk)).astype(np.float32)
            b = rng.standard_normal((kk, nn)).astype(np.float32)
            clean, info0 = abft.abft_matmul(a, b)
            if info0["detections"]:
                violations += 1  # clean product must verify clean
                continue
            plan = inject.FaultPlan([inject.FaultSpec(
                site=abft.SITE_MATMUL, kind="sdc_bitflip",
                max_triggers=1, seed=i)], seed=seed)
            with inject.plan(plan) as ap:
                fixed, info = abft.abft_matmul(a, b)
            if not ap.stats()["triggered"]:
                continue
            detections += info["detections"]
            corrected += bool(info["corrected"])
            recomputed += bool(info["recomputed"])
            if not (info["corrected"] or info["recomputed"]):
                violations += 1
            dev = float(np.max(np.abs(np.asarray(fixed)
                                      - np.asarray(clean))))
            max_dev = max(max_dev, dev)
            if dev > info["tol"]:
                violations += 1
    return {"ran": True, "cases": cases, "detections": detections,
            "corrected": corrected, "recomputed": recomputed,
            "max_dev": max_dev, "violations": violations}


def history_records(summary: Dict) -> List[Tuple[str, float, str]]:
    """(metric, value, unit) records an ABFT campaign contributes to the
    regression history — all slow-side-gated: detection regressing shows
    as a higher escalation rate or latency, overhead regressing as more
    seconds per solve (the plain path is the zero-overhead sentinel)."""
    out: List[Tuple[str, float, str]] = []
    sdc = summary.get("sdc") or {}
    if sdc.get("wall_s") and sdc.get("cases"):
        out.append(("abft:s_per_case",
                    round(sdc["wall_s"] / sdc["cases"], 6), "s"))
    if sdc.get("mean_detect_latency_s"):
        out.append(("abft:detect_latency_s",
                    sdc["mean_detect_latency_s"], "s"))
    esc = sdc.get("escalated")
    if isinstance(esc, int) and esc > 0 and sdc.get("cases"):
        out.append(("abft:escalation_rate",
                    round(esc / sdc["cases"], 4), "ratio"))
    ident = summary.get("identity") or {}
    if ident.get("plain_s_per_solve"):
        out.append(("abft:plain_s_per_solve", ident["plain_s_per_solve"],
                    "s"))
    if ident.get("overhead_ratio"):
        out.append(("abft:overhead_ratio", ident["overhead_ratio"], "x"))
    return out


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m gauss_tpu.resilience.abftcheck",
        description="Seeded ABFT campaign: inject on-device sdc_bitflip "
                    "faults at panel-group boundaries of the checksum-"
                    "carrying LU/Cholesky engines; assert 100%% detection, "
                    "localized replay recovery (bit-identical), ladder "
                    "escalation for persistent faults, and the abft-off "
                    "zero-overhead/bit-identity contract.")
    p.add_argument("--cases", type=int, default=110,
                   help="sdc-phase fault cases (default 110: >= 100 "
                        "injected faults across LU + Cholesky)")
    p.add_argument("--seed", type=int, default=258458)
    p.add_argument("--panel", type=int, default=16)
    p.add_argument("--gate", type=float, default=1e-4)
    p.add_argument("--matmul-cases", type=int, default=8)
    p.add_argument("--no-identity", action="store_true",
                   help="skip the bit-identity / zero-overhead phase")
    p.add_argument("--no-matmul", action="store_true",
                   help="skip the GEMM single-element-correction phase")
    p.add_argument("--metrics-out", default=None, metavar="PATH")
    p.add_argument("--summary-json", default=None, metavar="PATH",
                   help="write the campaign summary (regress-ingestable: "
                        "kind=abft_campaign)")
    p.add_argument("--history", nargs="?", const="", default=None,
                   metavar="PATH",
                   help="append this campaign's records to the regression "
                        "history (default reports/history.jsonl)")
    p.add_argument("--regress-check", action="store_true",
                   help="gate this campaign against the history baselines "
                        "(exit 1 when out of band)")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    honor_jax_platforms()

    from gauss_tpu import obs
    from gauss_tpu.obs import regress

    t0 = time.perf_counter()
    with obs.run(metrics_out=args.metrics_out, tool="abft_campaign",
                 cases=args.cases, seed=args.seed):
        sdc = run_sdc_phase(args.cases, args.seed, args.gate,
                            panel=args.panel)
        ident = {} if args.no_identity else run_identity_phase(args.seed)
        mat = ({} if args.no_matmul
               else run_matmul_phase(args.matmul_cases, args.seed))
        wall = round(time.perf_counter() - t0, 3)
        violations = (sdc["violations"]
                      + (0 if not ident or ident["bit_identical"] else 1)
                      + (mat.get("violations", 0) if mat else 0))
        summary = {
            "kind": "abft_campaign", "seed": args.seed,
            "gate": args.gate, "panel": args.panel,
            "sdc": sdc, "identity": ident, "matmul": mat,
            "wall_s": wall, "invariant_ok": violations == 0,
        }
        obs.emit("abft_campaign",
                 **{k: v for k, v in summary.items() if k != "kind"})

    c = sdc["counts"]
    print(f"abft campaign: {sdc['cases']} sdc case(s), {sdc['injected']} "
          f"on-device fault(s) injected ({sdc['faulted_cases']} faulted "
          f"case(s))")
    print(f"  detection: rate={sdc['detect_rate']}, {sdc['missed']} "
          f"missed; replay-recovered {sdc['replayed']} "
          f"(rate {sdc['replay_rate']}, by engine "
          f"{sdc['replayed_by_engine']}, {sdc['bit_identity_failures']} "
          f"bit-identity failure(s), {sdc['mislocalized']} mislocalized), "
          f"{sdc['escalated']} ladder escalation(s), "
          f"{c.get('silent_wrong', 0)} SILENT WRONG, "
          f"{c.get('violation', 0)} untyped")
    if ident:
        print(f"  identity: bit_identical={ident['bit_identical']}"
              + (f" MISMATCHES={ident['mismatches']}"
                 if ident["mismatches"] else "")
              + f", plain {ident['plain_s_per_solve']} s/solve, abft "
                f"{ident['abft_s_per_solve']} s/solve "
                f"({ident['overhead_ratio']}x)")
    if mat:
        print(f"  matmul: {mat['detections']} detection(s) -> "
              f"{mat['corrected']} corrected in place, "
              f"{mat['recomputed']} recomputed, max deviation "
              f"{mat['max_dev']:.2e}, {mat['violations']} violation(s)")
    print(f"  invariant {'HOLDS' if violations == 0 else 'VIOLATED'} "
          f"({wall} s)")

    if args.summary_json:
        parent = os.path.dirname(args.summary_json)
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(args.summary_json, "w") as f:
            json.dump(summary, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"summary: {args.summary_json}")

    rc = 0
    records = [{"metric": m, "value": v, "unit": u, "source": "abft",
                "kind": "abft"} for m, v, u in history_records(summary)]
    if args.regress_check and records:
        history_path = args.history or regress.default_history_path()
        verdicts = regress.check_records(
            records, regress.load_history(history_path))
        print(regress.format_verdicts(verdicts))
        if any(v["status"] == "out-of-band" for v in verdicts):
            rc = 1
    if args.history is not None and records and rc == 0:
        history_path = args.history or regress.default_history_path()
        added = regress.append_history(records, history_path)
        print(f"history: {added} record(s) appended to {history_path}")

    if violations:
        print(f"abftcheck: INVARIANT VIOLATED ({violations} case(s))",
              file=sys.stderr)
        return 2
    return rc


if __name__ == "__main__":
    sys.exit(main())
