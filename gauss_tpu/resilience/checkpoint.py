"""Panel-granular checkpoint/resume for the chunked blocked factorization.

A long factorization on preemptible hardware (the multihost story's spot
workers, the serve layer's long handoff solves) dies with ALL its work today:
``lu_factor_blocked_chunked`` is one device program. This module runs the
SAME math group by group at host level — the per-group step is
:func:`gauss_tpu.core.blocked._factor_group`, jitted per group exactly as the
one-shot form traces it — and serializes the outer-loop carry
``(m, perm, min_piv, linvs, uinvs, next_group)`` to disk every K panels. A
killed run resumes from the last checkpoint and, because every group step is
a deterministic compiled program over bit-identical carry inputs, finishes
**bit-identical to an uninterrupted checkpointed run** (asserted in
tests/test_resilience.py).

Cost model: one host round-trip per group (the phased factorizer's trade,
amortized over ``chunk`` panels, not paid per panel) plus one
O(npad^2 * itemsize) file write per checkpoint interval. The checkpoint
carries a digest of the input operand, so resuming against a DIFFERENT
matrix — or different panel/chunk/precision statics, which would change the
math — is a typed :class:`CheckpointMismatchError`, never a silently wrong
factor.

Hook point ``checkpoint.group`` (gauss_tpu.resilience.inject) fires between
groups: kind ``kill`` is a real ``os._exit`` (subprocess tests), kind
``raise`` the in-process stand-in.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
from functools import partial
from typing import Optional

import numpy as np

from gauss_tpu import obs
from gauss_tpu.resilience import inject as _inject

SCHEMA = 1


class CheckpointMismatchError(RuntimeError):
    """The checkpoint on disk does not belong to this (operand, statics)
    factorization — or is truncated/corrupt and cannot be trusted at all.
    Either way, resuming from it would risk a silently wrong factor."""


def _digest(a: np.ndarray) -> str:
    h = hashlib.sha256()
    h.update(str((a.shape, str(a.dtype))).encode())
    h.update(np.ascontiguousarray(a).tobytes())
    return h.hexdigest()[:16]


def prev_path(path) -> str:
    """Where :func:`save_state` keeps the PREVIOUS checkpoint generation."""
    return os.fspath(path) + ".prev"


def fsync_dir(parent: str) -> None:
    """fsync a directory so a just-renamed file's entry survives a crash
    (the rename itself is atomic, but durability of the new entry needs the
    parent flushed). Best-effort — not every filesystem supports it."""
    try:
        fd = os.open(parent, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _group_step_jit(panel: int, chunk: int, panel_impl: str,
                    gemm_precision: str):
    """The jitted per-group step, cached by jax.jit on its statics — the
    same trace :func:`lu_factor_blocked_chunked` embeds for this group.

    The carry (m, perm, min_piv) is DONATED: every caller rebinds it to
    the step's outputs (this module's group loop, dcheckpoint's sharded
    loop — shards are serialized from the NEW carry), so XLA updates the
    factor in place instead of materializing a fresh npad^2 copy per
    group — the host-stepped route's copy-per-step that the doctor diff
    (reports/doctor_r3_vs_r5.json) charges to ``host_group_step``. The
    ABFT runner keeps its own UNdonated step (resilience.abft): replay
    re-runs a group from the held carry, which donation would invalidate.
    """
    import jax

    from gauss_tpu.core import blocked
    from gauss_tpu.core.matmul import resolve_precision

    @partial(jax.jit, static_argnames=("g0",), donate_argnums=(0, 1, 2))
    def step(m, perm, min_piv, g0):
        return blocked._factor_group(m, perm, min_piv, g0, panel, chunk,
                                     panel_impl, resolve_precision(gemm_precision))

    return step


def save_state(path, *, meta: dict, m, perm, min_piv, linvs, uinvs) -> int:
    """Durably write one checkpoint; returns bytes written.

    tmp + fsync + rename + parent-dir fsync, and the checkpoint that was at
    ``path`` is KEPT as ``path.prev`` (one previous generation): a process
    killed at ANY instant of writing generation K leaves either K intact or
    K−1 intact — never zero resumable checkpoints. (Without the file fsync,
    a crash shortly after the rename could surface a truncated K with K−1
    already gone; :func:`load_state` types that corruption, and the resume
    path falls back to ``.prev``.)"""
    path = os.fspath(path)
    parent = os.path.dirname(path) or "."
    os.makedirs(parent, exist_ok=True)
    fd, tmp = tempfile.mkstemp(prefix=os.path.basename(path) + ".",
                               suffix=".tmp", dir=parent)
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, meta=np.frombuffer(
                json.dumps(meta, sort_keys=True).encode(), dtype=np.uint8),
                m=np.asarray(m), perm=np.asarray(perm),
                min_piv=np.asarray(min_piv), linvs=np.asarray(linvs),
                uinvs=np.asarray(uinvs))
            f.flush()
            os.fsync(f.fileno())
        nbytes = os.path.getsize(tmp)
        if os.path.exists(path):
            os.replace(path, prev_path(path))
        os.replace(tmp, path)
        fsync_dir(parent)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return nbytes


def load_state(path) -> dict:
    """Load one checkpoint. A file that cannot be parsed end to end — a
    torn write, a truncated npz, mangled meta — raises a typed
    :class:`CheckpointMismatchError` instead of leaking a raw zipfile/json/
    numpy error, so callers can fall back to the previous generation."""
    path = os.fspath(path)
    try:
        with np.load(path) as z:
            out = {k: np.array(z[k])
                   for k in ("m", "perm", "min_piv", "linvs", "uinvs")}
            out["meta"] = json.loads(bytes(z["meta"]).decode())
    except CheckpointMismatchError:
        raise
    except Exception as e:  # noqa: BLE001 — any parse failure means corrupt
        raise CheckpointMismatchError(
            f"checkpoint at {path} is truncated or corrupt "
            f"({type(e).__name__}: {e})") from e
    return out


def _load_resume_state(path, meta: dict):
    """Resolve the resumable state for ``meta``: the current checkpoint at
    ``path``, falling back to the kept previous generation at ``path.prev``
    when the current file is truncated/corrupt (a kill mid-write of K
    resumes from K−1, never fails the job). Returns None when neither file
    exists. A VALID checkpoint whose meta does not match stays a hard
    :class:`CheckpointMismatchError` — that is a different factorization,
    not a torn write, and falling back would silently mix systems."""
    candidates = [path, prev_path(path)]
    corrupt = None
    for cand in candidates:
        if not os.path.exists(cand):
            continue
        try:
            state = load_state(cand)
        except CheckpointMismatchError as e:
            corrupt = e
            obs.counter("resilience.checkpoint.corrupt")
            obs.emit("checkpoint", event="corrupt", path=cand,
                     error=str(e)[:200])
            continue
        disk = dict(state["meta"])
        disk.pop("next_group", None)
        disk.pop("panels_done", None)
        if disk != meta or "next_group" not in state["meta"]:
            raise CheckpointMismatchError(
                f"checkpoint at {cand} does not match this factorization: "
                f"checkpoint {disk}, requested {meta}")
        if cand != path:
            obs.emit("checkpoint", event="fallback_prev", path=cand)
        return state
    if corrupt is not None:
        # Both generations unusable: surface the typed corruption rather
        # than silently recomputing — the caller decides (resume=False).
        raise corrupt
    return None


def lu_factor_blocked_chunked_checkpointed(
        a, path, *, panel: Optional[int] = None, chunk: Optional[int] = None,
        panel_impl: str = "auto", gemm_precision: str = "highest",
        every_panels: Optional[int] = None, resume: bool = True,
        keep: bool = False):
    """Chunked blocked LU with a checkpoint file at ``path``.

    Identical factor layout to :func:`gauss_tpu.core.blocked.
    lu_factor_blocked_chunked` (same per-group math through the shared
    ``_factor_group``), stepped at host level so the carry can be saved
    every ``every_panels`` factored panels (default: every group, i.e.
    ``chunk`` panels). When ``resume`` and ``path`` holds a checkpoint for
    this exact (operand, statics) pair, factorization continues from its
    ``next_group``; a mismatched checkpoint raises
    :class:`CheckpointMismatchError`. On success the checkpoint is removed
    unless ``keep``.

    ``path=None`` DISABLES checkpointing at trace time: the call delegates
    to the fully-jitted one-program ``lu_factor_blocked_chunked`` — no
    host-stepped group split, no per-group device sync, no hook polls —
    so callers can thread one entry point and pay the checkpoint machinery
    only when they actually configured a checkpoint (ROADMAP perf item:
    hooks compiled out unless enabled).

    Returns a :class:`gauss_tpu.core.blocked.BlockedLU`.
    """
    import jax
    import jax.numpy as jnp

    from gauss_tpu.core import blocked

    if path is None:
        return blocked.lu_factor_blocked_chunked(
            jnp.asarray(a), panel=panel,
            chunk=blocked.CHUNK_DEFAULT if chunk is None else chunk,
            panel_impl=panel_impl, gemm_precision=gemm_precision)
    a = np.asarray(a)
    n = a.shape[0]
    if a.shape != (n, n):
        raise ValueError(f"expected square matrix, got {a.shape}")
    itemsize = a.dtype.itemsize
    panel = blocked._resolve_panel(n, panel, itemsize)
    chunk = blocked.CHUNK_DEFAULT if chunk is None else chunk
    if chunk < 1:
        raise ValueError(f"chunk must be >= 1, got {chunk}")
    every = chunk if every_panels is None else max(1, int(every_panels))
    path = os.fspath(path)

    meta = {"schema": SCHEMA, "n": n, "panel": panel, "chunk": chunk,
            "panel_impl": panel_impl, "gemm_precision": gemm_precision,
            "dtype": str(a.dtype), "digest": _digest(a)}

    m = blocked._pad_to_panel(jnp.asarray(a), panel)
    npad = m.shape[0]
    nb = npad // panel
    start_group = 0
    perm = jnp.arange(npad)
    min_piv = jnp.asarray(jnp.inf, m.dtype)
    linv_parts, uinv_parts = [], []

    state = _load_resume_state(path, meta) if resume else None
    if state is not None:
        disk = dict(state["meta"])
        next_group = disk.pop("next_group")
        panels_done = disk.pop("panels_done", 0)
        m = jnp.asarray(state["m"])
        perm = jnp.asarray(state["perm"])
        min_piv = jnp.asarray(state["min_piv"])
        if state["linvs"].size:
            linv_parts = [state["linvs"]]
            uinv_parts = [state["uinvs"]]
        start_group = int(next_group)
        obs.counter("resilience.checkpoint.resumes")
        obs.emit("checkpoint", event="resume", path=path,
                 next_group=start_group, panels_done=int(panels_done))

    step = _group_step_jit(panel, chunk, panel_impl, gemm_precision)
    unsaved = 0
    for g0 in range(start_group, nb, chunk):
        # Hook point "checkpoint.group": a kill here models preemption
        # BETWEEN groups — everything since the last save is lost, the
        # saved carry is intact (the write below is atomic).
        _inject.maybe_kill("checkpoint.group")
        m, perm, min_piv, linvs, uinvs = step(m, perm, min_piv, g0=g0)
        jax.block_until_ready(m)
        linv_parts.append(np.asarray(linvs))
        uinv_parts.append(np.asarray(uinvs))
        gpanels = min(chunk, nb - g0)
        unsaved += gpanels
        next_group = g0 + chunk
        if unsaved >= every and next_group < nb:
            nbytes = save_state(
                path,
                meta={**meta, "next_group": next_group,
                      "panels_done": next_group},
                m=m, perm=perm, min_piv=min_piv,
                linvs=np.concatenate(linv_parts),
                uinvs=np.concatenate(uinv_parts))
            unsaved = 0
            obs.counter("resilience.checkpoint.saves")
            obs.emit("checkpoint", event="save", path=path,
                     next_group=next_group, panels_done=int(next_group),
                     bytes=int(nbytes))

    if not keep:
        for stale in (path, prev_path(path)):
            try:
                os.unlink(stale)
            except OSError:
                pass
    obs.emit("checkpoint", event="complete", path=path, groups=-(-nb // chunk))
    return blocked.BlockedLU(m=m, perm=perm, min_abs_pivot=min_piv,
                             linv=jnp.concatenate(
                                 [jnp.asarray(p) for p in linv_parts]),
                             uinv=jnp.concatenate(
                                 [jnp.asarray(p) for p in uinv_parts]))
