"""Algorithm-based fault tolerance: checksum-carrying solves that detect,
localize, and repair silent data corruption MID-solve.

The stack's verification so far is end-of-job: the 1e-4 residual gate (and
the recovery ladder behind it) notices a corrupted solve only after ALL the
O(n^3) work is spent, and recovery redoes everything. Fleets see silent
data corruption from flaky cores as a matter of course (Dixit et al.,
"Silent Data Corruptions at Scale", 2021); the classic answer is Huang &
Abraham's algorithm-based fault tolerance (checksum-augmented matrix
factorizations, IEEE ToC 1984; blocked-factorization form per Du, Bosilca
& Dongarra, PPoPP'12): carry a column-checksum row through the
factorization — it is an invariant of every panel factor and trailing GEMM
(see the ABFT block in :mod:`gauss_tpu.core.blocked`) — and verify it
on-device after each panel group, a cheap reduction against the group's
GEMM FLOPs.

This module is the host-stepped runner that turns the invariant into
repair:

- :func:`lu_factor_abft` / :func:`cholesky_factor_abft` run the SAME group
  math as the checkpointed factorizations (``blocked._factor_group`` /
  ``cholesky._chol_panel_step`` — shared code, numerical lockstep),
  holding the last VERIFIED carry in memory exactly like a PR-4
  checkpoint. On a checksum mismatch the fault is localized to the
  offending panel group (and the argmax column), an obs ``sdc`` event +
  health gauge fires, and the group is REPLAYED from the last-good carry
  — a deterministic compiled program over bit-identical inputs, so a
  repaired run is bit-identical to an uninterrupted one (the fleet
  recovery guarantee, asserted by ``make abft-check``). Replay exhaustion
  (persistent corruption) raises the typed :class:`SDCUnrecoverableError`
  so the recovery ladder (gauss_tpu.resilience.recover, rungs ``abft`` /
  ``abft_chol``) escalates to the full pre-existing ladder.
- A final whole-factor identity (``e^T PA = (e^T L) U``, resp.
  ``e^T A = (e^T L) L^T``) covers the factored region the per-group
  trailing checks stop watching — including the last group, whose
  trailing block is empty.
- :func:`abft_matmul` is the standalone GEMM form: column-checksum row on
  A and row-checksum column on B give full output checksums; a
  single-element error is localized to its (row, column) intersection and
  corrected IN PLACE (to checksum precision); anything wider is repaired
  by recomputation. Never a silent wrong product.

Fault injection (gauss_tpu.resilience.inject, kind ``sdc_bitflip`` at
sites ``abft.lu.group`` / ``abft.chol.group`` / ``abft.matmul``) flips one
bit of one element of the ON-DEVICE carry at a panel-group boundary — the
first on-device corruption channel in the chaos stack (the PR-4 bitflips
corrupt host operands before launch). Default bits are drawn from the
sign/exponent/high-mantissa range: a low-order mantissa flip perturbs the
result below the f32 checksum rounding floor and below the 1e-4 gate —
numerically invisible corruption is not a detectable (or meaningful)
fault class for an f32 pipeline, and docs/RESILIENCE.md says so honestly.

Detection threshold: ``tol = scale * max(64 * npad * eps, 1e-6)`` with
``scale = max |initial column sums|`` — comfortably above the checksum's
accumulated rounding noise (measured ~2e-7 relative at n=96..2048) and far
below any high-bit flip's perturbation. NaN mismatches fold to +inf inside
the on-device check, so NaN-poisoning corruption is always detected.
"""

from __future__ import annotations

import dataclasses
import functools
import threading
import time
from typing import List, Optional

import numpy as np

from gauss_tpu import obs
from gauss_tpu.resilience import inject as _inject

#: fault-injection hook sites (inject kind ``sdc_bitflip``)
SITE_LU = "abft.lu.group"
SITE_CHOL = "abft.chol.group"
SITE_MATMUL = "abft.matmul"

#: the final whole-factor identity accumulates rounding across all groups;
#: its acceptance band is this many group-check tolerances wide.
FINAL_TOL_FACTOR = 4.0

#: default replay budget per factorization — a transient fault heals on
#: the first replay; two failed replays of the same group mean the
#: corruption reproduces (sick core, poisoned input) and the ladder is
#: the right tool.
DEFAULT_MAX_REPLAYS = 2


class SDCDetectedError(RuntimeError):
    """A checksum mismatch the runner could not (or was not asked to)
    repair in place. Carries the localization: engine, panel group,
    column, and mismatch magnitude."""

    def __init__(self, message: str, engine: str = "", group: int = -1,
                 col: int = -1, magnitude: float = 0.0):
        super().__init__(message)
        self.engine = engine
        self.group = group
        self.col = col
        self.magnitude = magnitude


class SDCUnrecoverableError(SDCDetectedError):
    """Replay exhausted: the same panel group failed its checksum
    ``max_replays + 1`` times — persistent corruption, not a transient
    flip. Typed so the recovery ladder escalates to the full pre-existing
    rung chain (pivot-safe refactor -> ds refine -> alternate engine ->
    host NumPy) instead of surfacing an untyped crash."""


@dataclasses.dataclass
class AbftReport:
    """What the checksum machinery saw during one factorization."""

    engine: str
    groups: int
    tol: float
    detections: int = 0
    replays: int = 0
    escalated: bool = False
    max_err: float = 0.0
    detect_groups: List[int] = dataclasses.field(default_factory=list)
    detect_cols: List[int] = dataclasses.field(default_factory=list)
    detect_latency_s: List[float] = dataclasses.field(default_factory=list)

    @property
    def repaired(self) -> bool:
        return self.detections > 0 and not self.escalated

    def to_dict(self) -> dict:
        return {"engine": self.engine, "groups": self.groups,
                "detections": self.detections, "replays": self.replays,
                "escalated": self.escalated,
                "max_err": float(self.max_err), "tol": float(self.tol),
                "detect_groups": list(self.detect_groups),
                "detect_cols": list(self.detect_cols),
                "detect_latency_s": [round(v, 6)
                                     for v in self.detect_latency_s]}


# The last factorization's report, per thread — how the recovery ladder
# (which only sees a rung's (x, factors) return) attaches SDC accounting
# to its ResilientResult without changing every rung's signature.
_tls = threading.local()


def last_report() -> Optional[AbftReport]:
    return getattr(_tls, "report", None)


def clear_report() -> None:
    _tls.report = None


def default_tol(npad: int, dtype, scale: float) -> float:
    """Detection threshold for an (npad, npad) factorization at checksum
    magnitude ``scale`` — above the accumulated checksum rounding noise,
    far below any high-bit flip's perturbation."""
    eps = float(np.finfo(np.dtype(dtype)).eps)
    return max(float(scale), 1.0) * max(64.0 * npad * eps, 1e-6)


# -- on-device bit flip (the corruption primitive AND the test substrate) --

_UINT = {2: "uint16", 4: "uint32", 8: "uint64"}
_JITS: dict = {}


def flip_bit(m, i: int, j: int, bit: int):
    """Flip bit ``bit`` of element (i, j) of the device array ``m`` — a
    jitted bitcast-XOR, so the corruption happens ON DEVICE against the
    live carry (never a host round-trip of the matrix)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    fn = _JITS.get("flip")
    if fn is None:
        def impl(m, i, j, bit):
            uint = jnp.dtype(_UINT[m.dtype.itemsize])
            v = lax.dynamic_slice(m, (i, j), (1, 1))
            u = lax.bitcast_convert_type(v, uint)
            u = u ^ (jnp.ones((), uint) << bit.astype(uint))
            return lax.dynamic_update_slice(
                m, lax.bitcast_convert_type(u, m.dtype), (i, j))

        fn = jax.jit(impl)
        _JITS["flip"] = fn
    return fn(m, jnp.asarray(i, jnp.int32), jnp.asarray(j, jnp.int32),
              jnp.asarray(bit, jnp.int32))


def _flipped_host(v: float, bit: int, np_dtype) -> float:
    """What flipping ``bit`` of ``v`` yields, computed host-side (used to
    pre-qualify an injection as detectable)."""
    uint = np.dtype(_UINT[np.dtype(np_dtype).itemsize])
    u = np.asarray(v, np_dtype).view(uint)
    return float(np.asarray(u ^ uint.type(1 << bit)).view(np_dtype))


def _poll_sdc_corrupt(site: str, m, lo: int, engine: str, group: int,
                      tol: float = 0.0, lower_only: bool = False):
    """Poll ``site``; on an ``sdc_bitflip`` trigger, flip one seeded bit of
    one seeded element of the ACTIVE region (rows/cols >= ``lo``) of the
    on-device carry. Returns (m, fired).

    The seeded draw prefers (element, bit) pairs whose flip perturbs the
    value by more than the detection tolerance: a flip of a near-zero
    element (or a low-order mantissa bit) perturbs the result below the
    f32 checksum rounding floor AND below the final residual gate —
    numerically invisible corruption is not a meaningful fault class for
    an f32 pipeline (docs/RESILIENCE.md). ``spec.param`` > 0 pins the bit
    index verbatim, bypassing the qualification (tests use it to exercise
    the sub-noise case deliberately).

    ``lower_only``: draw (i, j) with i >= j — the Cholesky fault model:
    the factorization never reads the strict upper triangle, so a flip
    there is corruption of DEAD memory (harmless and, correctly,
    invisible to a checksum over the computation's inputs/outputs)."""
    if not _inject.enabled():
        return m, False
    hit = _inject.poll_sdc(site)
    if hit is None:
        return m, False
    sp, rng = hit
    npad = m.shape[0]
    np_dtype = np.dtype(str(m.dtype))
    nbits = np_dtype.itemsize * 8
    mant = {2: 10, 4: 23, 8: 52}[np_dtype.itemsize]
    def draw_ij():
        i = lo + int(rng.integers(0, max(1, npad - lo)))
        j = lo + int(rng.integers(0, max(1, npad - lo)))
        return (max(i, j), min(i, j)) if lower_only else (i, j)

    i = j = bit = None
    if sp.param and sp.param > 0:
        i, j = draw_ij()
        bit = int(sp.param) % nbits
    else:
        floor = max(4.0 * tol, 1e-3)
        for _ in range(16):
            i, j = draw_ij()
            v = float(np.asarray(m[i, j]))
            for b in rng.permutation(np.arange(mant - 3, nbits)):
                nv = _flipped_host(v, int(b), np_dtype)
                delta = abs(nv - v)
                if not np.isfinite(delta) or delta > floor:
                    bit = int(b)
                    break
            if bit is not None:
                break
        if bit is None:
            bit = nbits - 2  # top exponent bit: always catastrophic
    obs.emit("sdc_inject", site=site, engine=engine, group=group,
             row=i, col=j, bit=bit)
    return flip_bit(m, i, j, bit), True


def _record_detection(report: AbftReport, engine: str, group: int,
                      col: int, err: float, lat: float,
                      action: str) -> None:
    report.detections += 1
    report.max_err = max(report.max_err, err)
    report.detect_groups.append(group)
    report.detect_cols.append(col)
    report.detect_latency_s.append(lat)
    obs.counter("abft.sdc_detected")
    obs.histogram("abft.detect_latency_s", lat)
    obs.gauge("abft.last_sdc_group", float(group))
    obs.emit("sdc", engine=engine, group=group, col=col,
             magnitude=float(err), latency_s=round(lat, 6), action=action)
    # The PR-1 health plane (and through it the live gauges: health events
    # auto-gauge as health.* in obs.live) sees every detection too.
    obs.emit("health", sdc_detected=1.0, sdc_magnitude=float(err),
             sdc_group=group)


def _emit_repair(report: AbftReport, replays: int, group: int) -> None:
    report.replays += replays
    obs.counter("abft.replays", replays)
    obs.counter("abft.sdc_repaired")
    obs.emit("recovery", trigger="sdc", rung="abft_replay", rung_index=0,
             attempt=replays, outcome="recovered", group=group)


def _escalate(report: AbftReport, engine: str, group: int, col: int,
              err: float) -> "SDCUnrecoverableError":
    report.escalated = True
    _tls.report = report
    obs.counter("abft.sdc_escalated")
    obs.emit("recovery", trigger="sdc", rung="abft_replay", rung_index=0,
             attempt=report.replays + 1, outcome="escalate", group=group)
    return SDCUnrecoverableError(
        f"{engine} ABFT: panel group {group} failed its checksum after "
        f"{report.replays} replay(s) (|mismatch| {err:.3e} > tol "
        f"{report.tol:.3e} at column {col}); corruption is persistent — "
        f"escalate to the full recovery ladder", engine=engine,
        group=group, col=col, magnitude=err)


# -- checksum-carrying blocked LU (host-stepped groups + replay) -----------

@functools.lru_cache(maxsize=32)
def _lu_step_jit(panel: int, chunk: int, panel_impl: str,
                 gemm_precision: str):
    """The jitted per-group ABFT step — ``blocked._factor_group`` with the
    checksum row riding, cached by jax.jit on its statics (the same trace
    discipline as resilience.checkpoint._group_step_jit)."""
    import jax

    from functools import partial

    from gauss_tpu.core import blocked
    from gauss_tpu.core.matmul import resolve_precision

    @partial(jax.jit, static_argnames=("g0",))
    def step(m, perm, min_piv, crow, g0):
        return blocked._factor_group(
            m, perm, min_piv, g0, panel, chunk, panel_impl,
            resolve_precision(gemm_precision), crow=crow)

    return step


def lu_factor_abft(a, *, panel: Optional[int] = None,
                   chunk: Optional[int] = None, panel_impl: str = "auto",
                   gemm_precision: str = "highest",
                   max_replays: int = DEFAULT_MAX_REPLAYS,
                   tol: Optional[float] = None):
    """Checksum-carrying chunked blocked LU with detect -> localize ->
    replay. Returns ``(BlockedLU, AbftReport)``; the factor is
    bit-identical to ``blocked.lu_factor_blocked_chunked`` at the same
    statics (the checksum is a rider, never an operand), faulted-and-
    replayed runs are bit-identical to uninterrupted ones, and persistent
    corruption raises the typed :class:`SDCUnrecoverableError`."""
    import jax
    import jax.numpy as jnp

    from gauss_tpu.core import blocked

    a = jnp.asarray(a)
    n = a.shape[0]
    if a.shape != (n, n):
        raise ValueError(f"expected square matrix, got {a.shape}")
    itemsize = jnp.dtype(a.dtype).itemsize
    panel = blocked._resolve_panel(n, panel, itemsize)
    chunk = blocked.CHUNK_DEFAULT if chunk is None else chunk
    if chunk < 1:
        raise ValueError(f"chunk must be >= 1, got {chunk}")
    m = blocked._pad_to_panel(a, panel)
    npad = m.shape[0]
    nb = npad // panel
    ngroups = -(-nb // chunk)
    dtype = m.dtype
    crow0 = blocked._csum_init(m)
    scale = float(jnp.max(jnp.abs(crow0)))
    tol = default_tol(npad, dtype, scale) if tol is None else float(tol)
    report = AbftReport(engine="lu", groups=ngroups, tol=tol)
    _tls.report = report

    step = _lu_step_jit(panel, chunk, panel_impl, gemm_precision)
    carry = (m, jnp.arange(npad), jnp.asarray(jnp.inf, dtype), crow0)
    carry_before = carry   # the last group's rollback point
    linv_parts, uinv_parts = [], []
    errs = []

    def run_group(gi: int, g0: int, carry):
        """One verified group: corrupt-hook poll, step, on-device checksum
        verdict, bounded replay from the (unchanged) input carry."""
        replays = 0
        while True:
            t0 = time.perf_counter()
            m_in, perm_in, piv_in, crow_in = carry
            m_try, _ = _poll_sdc_corrupt(SITE_LU, m_in, g0 * panel, "lu",
                                         gi, tol=tol)
            m2, perm2, piv2, linvs, uinvs, crow2, err, col = step(
                m_try, perm_in, piv_in, crow_in, g0=g0)
            err_f = float(jax.block_until_ready(err))
            if not err_f > tol:   # NaN already folded to inf on device
                if replays:
                    _emit_repair(report, replays, gi)
                return ((m2, perm2, piv2, crow2), np.asarray(linvs),
                        np.asarray(uinvs), err_f)
            lat = time.perf_counter() - t0
            col_i = int(col)
            _record_detection(report, "lu", gi, col_i, err_f, lat,
                              "replay" if replays < max_replays
                              else "escalate")
            if replays >= max_replays:
                raise _escalate(report, "lu", gi, col_i, err_f)
            replays += 1

    for gi, g0 in enumerate(range(0, nb, chunk)):
        carry_before = carry
        carry, linv_g, uinv_g, err_f = run_group(gi, g0, carry)
        linv_parts.append(linv_g)
        uinv_parts.append(uinv_g)
        errs.append(err_f)

    # The whole-factor identity covers the factored region (and the last
    # group, whose trailing block is empty). A mismatch that localizes to
    # the final group replays from the held rollback point; anything
    # earlier is beyond the carry we kept — typed escalation.
    fcheck = _JITS.get("final_lu")
    if fcheck is None:
        fcheck = jax.jit(blocked._csum_final_err_lu)
        _JITS["final_lu"] = fcheck
    final_tol = tol * FINAL_TOL_FACTOR
    last_gi, last_g0 = ngroups - 1, (ngroups - 1) * chunk
    for attempt in range(max_replays + 1):
        fe, fcol = fcheck(carry[0], crow0)
        fe_f = float(jax.block_until_ready(fe))
        if not fe_f > final_tol:
            break
        col_i = int(fcol)
        group_i = min(col_i // (panel * chunk), last_gi)
        _record_detection(report, "lu", group_i, col_i, fe_f, 0.0,
                          "replay" if (group_i == last_gi
                                       and attempt < max_replays)
                          else "escalate")
        if group_i != last_gi or attempt >= max_replays:
            raise _escalate(report, "lu", group_i, col_i, fe_f)
        carry, linv_parts[-1], uinv_parts[-1], errs[-1] = run_group(
            last_gi, last_g0, carry_before)
        _emit_repair(report, 1, last_gi)

    m, perm, min_piv, _ = carry
    errs.append(fe_f)
    fac = blocked.BlockedLU(
        m=m, perm=perm, min_abs_pivot=min_piv,
        linv=jnp.concatenate([jnp.asarray(p) for p in linv_parts]),
        uinv=jnp.concatenate([jnp.asarray(p) for p in uinv_parts]),
        abft_err=jnp.asarray(np.asarray(errs, np.float64).astype(
            np.dtype(str(dtype)))))
    _tls.report = report
    return fac, report


def solve_lu_abft(a, b, *, panel: Optional[int] = None,
                  chunk: Optional[int] = None, iters: int = 2,
                  max_replays: int = DEFAULT_MAX_REPLAYS,
                  tol: Optional[float] = None):
    """ABFT-protected LU solve: f32 checksum-carrying factorization (with
    replay repair) + host-f64 iterative refinement — the contract of
    ``blocked.solve_refined`` with mid-solve SDC detection added. Returns
    ``(x float64, factors, AbftReport)``."""
    import jax.numpy as jnp

    from gauss_tpu.core import blocked

    a64 = np.asarray(a, np.float64)
    b64 = np.asarray(b, np.float64)
    fac, report = lu_factor_abft(jnp.asarray(a64, jnp.float32), panel=panel,
                                 chunk=chunk, max_replays=max_replays,
                                 tol=tol)
    x = np.asarray(blocked.lu_solve(fac, jnp.asarray(b64, jnp.float32)),
                   dtype=np.float64)
    for _ in range(iters):
        r = b64 - a64 @ x
        d = np.asarray(blocked.lu_solve(fac, jnp.asarray(r, jnp.float32)),
                       dtype=np.float64)
        x = x + d
    return x, fac, report


# -- checksum-carrying blocked Cholesky (per-panel groups) -----------------

@functools.lru_cache(maxsize=32)
def _chol_step_jit(panel: int, gemm_precision: str):
    import jax

    from functools import partial

    from gauss_tpu.core.matmul import resolve_precision
    from gauss_tpu.structure import cholesky

    @partial(jax.jit, static_argnames=("kb",))
    def step(m, min_diag, crow, kb):
        return cholesky._chol_panel_step(
            m, min_diag, kb, panel, resolve_precision(gemm_precision),
            crow=crow)

    return step


def cholesky_factor_abft(a, *, panel: Optional[int] = None,
                         gemm_precision: str = "highest",
                         max_replays: int = DEFAULT_MAX_REPLAYS,
                         tol: Optional[float] = None):
    """Checksum-carrying blocked Cholesky with detect -> localize ->
    replay; the SPD sibling of :func:`lu_factor_abft` (panel-granular
    groups — Cholesky has no chunked form to mirror). Returns
    ``(BlockedCholesky, AbftReport)``; never raises on non-SPD input —
    check ``min_diag`` (the solve wrapper does, preserving the
    :class:`~gauss_tpu.structure.cholesky.NotSPDError` contract)."""
    import jax
    import jax.numpy as jnp

    from gauss_tpu.core import blocked
    from gauss_tpu.structure import cholesky

    a = jnp.asarray(a)
    n = a.shape[0]
    if a.shape != (n, n):
        raise ValueError(f"expected square matrix, got {a.shape}")
    itemsize = jnp.dtype(a.dtype).itemsize
    panel = blocked._resolve_panel(n, panel, itemsize)
    m = blocked._pad_to_panel(a, panel)
    npad = m.shape[0]
    nb = npad // panel
    dtype = m.dtype
    crow0 = cholesky._csum_sym_init(m)
    scale = float(jnp.max(jnp.abs(crow0)))
    tol = default_tol(npad, dtype, scale) if tol is None else float(tol)
    report = AbftReport(engine="chol", groups=nb, tol=tol)
    _tls.report = report

    step = _chol_step_jit(panel, gemm_precision)
    carry = (m, jnp.asarray(jnp.inf, dtype), crow0)
    carry_before = carry
    linv_parts = []
    errs = []

    def run_group(k: int, carry):
        replays = 0
        kb = k * panel
        while True:
            t0 = time.perf_counter()
            m_in, mind_in, crow_in = carry
            m_try, _ = _poll_sdc_corrupt(SITE_CHOL, m_in, kb, "chol", k,
                                         tol=tol, lower_only=True)
            m2, mind2, linv, crow2, err = step(m_try, mind_in, crow_in,
                                               kb=kb)
            err_f = float(jax.block_until_ready(err))
            if not err_f > tol:
                if replays:
                    _emit_repair(report, replays, k)
                return (m2, mind2, crow2), np.asarray(linv), err_f
            lat = time.perf_counter() - t0
            # The masked check's argmax is internal to the step here; the
            # panel index IS the localization for per-panel groups.
            _record_detection(report, "chol", k, kb, err_f, lat,
                              "replay" if replays < max_replays
                              else "escalate")
            if replays >= max_replays:
                # A checksum that keeps failing with a non-positive
                # min-diagonal witness is the NOT-SPD signature, not SDC:
                # the NaN-as-0 fold makes an indefinite operand's "factor"
                # garbage by design, so A = L L^T cannot hold. (A
                # corrupted-to-indefinite carry lands here too — the
                # typed demotion to general LU is right either way; a
                # TRANSIENT flip never reaches this branch, its first
                # replay heals it.)
                mind_f = float(np.asarray(mind_in))
                if not mind_f > 0.0 or not float(np.asarray(mind2)) > 0.0:
                    report.escalated = True
                    _tls.report = report
                    from gauss_tpu.structure import cholesky as _chol

                    raise _chol.NotSPDError(
                        f"matrix is not positive definite (Cholesky "
                        f"min diagonal <= 0 with a persistent checksum "
                        f"mismatch at panel {k}); route to general LU",
                        min_diag=min(mind_f,
                                     float(np.asarray(mind2))))
                raise _escalate(report, "chol", k, kb, err_f)
            replays += 1

    for k in range(nb):
        carry_before = carry
        carry, linv_k, err_f = run_group(k, carry)
        linv_parts.append(linv_k)
        errs.append(err_f)

    fcheck = _JITS.get("final_chol")
    if fcheck is None:
        fcheck = jax.jit(cholesky._csum_final_err_chol)
        _JITS["final_chol"] = fcheck
    final_tol = tol * FINAL_TOL_FACTOR
    for attempt in range(max_replays + 1):
        fe, fcol = fcheck(carry[0], crow0)
        fe_f = float(jax.block_until_ready(fe))
        if not fe_f > final_tol:
            break
        col_i = int(fcol)
        group_i = min(col_i // panel, nb - 1)
        _record_detection(report, "chol", group_i, col_i, fe_f, 0.0,
                          "replay" if (group_i == nb - 1
                                       and attempt < max_replays)
                          else "escalate")
        if group_i != nb - 1 or attempt >= max_replays:
            raise _escalate(report, "chol", group_i, col_i, fe_f)
        carry, linv_parts[-1], errs[-1] = run_group(nb - 1, carry_before)
        _emit_repair(report, 1, nb - 1)

    m, min_diag, _ = carry
    errs.append(fe_f)
    fac = cholesky.BlockedCholesky(
        m=m, linv=jnp.stack([jnp.asarray(p) for p in linv_parts]),
        min_diag=min_diag,
        abft_err=jnp.asarray(np.asarray(errs, np.float64).astype(
            np.dtype(str(dtype)))))
    _tls.report = report
    return fac, report


def solve_chol_abft(a, b, *, panel: Optional[int] = None, iters: int = 2,
                    max_replays: int = DEFAULT_MAX_REPLAYS,
                    tol: Optional[float] = None):
    """ABFT-protected SPD solve: checksum-carrying Cholesky (with replay
    repair) + host-f64 refinement — ``cholesky.solve_spd_refined``'s
    contract with mid-solve SDC detection. Returns
    ``(x float64, factors, AbftReport)``; raises
    :class:`~gauss_tpu.structure.cholesky.NotSPDError` on non-SPD input
    (the router's demotion signal, unchanged)."""
    import jax.numpy as jnp

    from gauss_tpu.structure import cholesky

    a64 = np.asarray(a, np.float64)
    b64 = np.asarray(b, np.float64)
    fac, report = cholesky_factor_abft(
        jnp.asarray(a64, jnp.float32), panel=panel,
        max_replays=max_replays, tol=tol)
    mind = float(np.asarray(fac.min_diag))
    if not mind > 0.0:
        raise cholesky.NotSPDError(
            f"matrix is not positive definite (Cholesky min diagonal "
            f"{mind:g}); route to general LU", min_diag=mind)
    x = np.asarray(cholesky.cholesky_solve(fac, jnp.asarray(b64,
                                                            jnp.float32)),
                   dtype=np.float64)
    for _ in range(iters):
        r = b64 - a64 @ x
        d = np.asarray(cholesky.cholesky_solve(
            fac, jnp.asarray(r, jnp.float32)), dtype=np.float64)
        x = x + d
    return x, fac, report


# -- ABFT matmul: detect + correct single-element GEMM errors --------------

def abft_matmul(a, b, *, precision: str = "highest", correct: bool = True,
                tol: Optional[float] = None):
    """``C = A @ B`` with full Huang-Abraham checksums: the column-checksum
    row ``(e^T A) B`` and the row-checksum column ``A (B e)`` predict C's
    column and row sums. A single corrupted element is localized to the
    intersection of the one mismatching row and one mismatching column and
    corrected IN PLACE from the column-sum excess (to checksum precision);
    multi-element corruption is repaired by recomputation. Returns
    ``(c, info)`` with ``info = {detections, corrected, recomputed,
    row, col, magnitude}``.

    Hook site ``abft.matmul`` (kind ``sdc_bitflip``) corrupts the
    on-device product between compute and verification."""
    import jax
    import jax.numpy as jnp

    from gauss_tpu.kernels.matmul_pallas import resolve_precision

    prec = resolve_precision(precision)
    a = jnp.asarray(a)
    b = jnp.asarray(b)

    mm = _JITS.get(("mm", precision))
    if mm is None:
        def impl(a, b):
            return jnp.dot(a, b, precision=prec)

        mm = jax.jit(impl)
        _JITS[("mm", precision)] = mm
    chk = _JITS.get(("mmchk", precision))
    if chk is None:
        def chk_impl(a, b, c):
            ccol = jnp.dot(jnp.sum(a, axis=0, keepdims=True), b,
                           precision=prec)
            crow = jnp.dot(a, jnp.sum(b, axis=1, keepdims=True),
                           precision=prec)
            dcol = jnp.sum(c, axis=0) - ccol[0]
            drow = jnp.sum(c, axis=1) - crow[:, 0]
            fold = lambda d: jnp.where(jnp.isnan(d), jnp.inf, jnp.abs(d))
            return fold(dcol), fold(drow), dcol

        chk = jax.jit(chk_impl)
        _JITS[("mmchk", precision)] = chk

    c = mm(a, b)
    c, _ = _poll_sdc_corrupt(SITE_MATMUL, c, 0, "matmul", 0)
    k = a.shape[1]
    if tol is None:
        eps = float(np.finfo(np.dtype(str(c.dtype))).eps)
        scale = max(1.0, float(jnp.max(jnp.abs(a)))
                    * float(jnp.max(jnp.abs(b))) * k)
        tol = scale * max(64.0 * max(a.shape[0], b.shape[1], k) * eps, 1e-6)
    info = {"detections": 0, "corrected": False, "recomputed": False,
            "row": None, "col": None, "magnitude": 0.0, "tol": float(tol)}
    dcol_a, drow_a, dcol = chk(a, b, c)
    bad_cols = np.nonzero(np.asarray(dcol_a) > tol)[0]
    bad_rows = np.nonzero(np.asarray(drow_a) > tol)[0]
    if not len(bad_cols) and not len(bad_rows):
        return c, info
    info["detections"] = 1
    mag = float(max(np.max(np.asarray(dcol_a)[bad_cols], initial=0.0),
                    np.max(np.asarray(drow_a)[bad_rows], initial=0.0)))
    info["magnitude"] = mag
    obs.counter("abft.sdc_detected")
    if correct and len(bad_cols) == 1 and len(bad_rows) == 1:
        i, j = int(bad_rows[0]), int(bad_cols[0])
        delta = float(np.asarray(dcol)[j])
        if np.isfinite(delta):
            c2 = c.at[i, j].add(jnp.asarray(-delta, c.dtype))
            # Re-verify: a very large corrupted value inflates the f32
            # column sum's ulp past the true terms, leaving the correction
            # delta imprecise — if the repaired product still fails its
            # checksums, fall through to recomputation instead of
            # shipping an almost-corrected element.
            d2c, d2r, _ = chk(a, b, c2)
            if (float(np.max(np.asarray(d2c))) <= tol
                    and float(np.max(np.asarray(d2r))) <= tol):
                info.update(corrected=True, row=i, col=j)
                obs.counter("abft.sdc_corrected")
                obs.emit("sdc", engine="matmul", group=0, col=j, row=i,
                         magnitude=mag, action="correct")
                return c2, info
    # Wider (or non-finite) corruption: recompute — GEMM replay is the
    # whole-operation rollback, cheap at O(mnk) once.
    c = mm(a, b)
    info["recomputed"] = True
    obs.counter("abft.replays")
    obs.emit("sdc", engine="matmul", group=0,
             col=int(bad_cols[0]) if len(bad_cols) else -1,
             magnitude=mag, action="recompute")
    return c, info
