"""2-D cyclic-sharded distributed Gaussian elimination (BASELINE config 5).

The 1-D row-cyclic engine (:mod:`gauss_tpu.dist.gauss_dist`) re-expresses the
reference's MPI master-worker row distribution (reference
OpenMP_and_MPI/gauss_mpi/gauss_internal_input.c:124-255). At pod scale the 1-D
layout stops scaling: every shard holds full n-wide rows, so the per-step
pivot-row broadcast moves O(n) per chip regardless of the chip count. This
module is the 2-D generalization — the ScaLAPACK block-cyclic layout rebuilt
on the JAX sharding model for meshes like the v5p-64 of BASELINE.json's
config 5 ("gauss with partial pivoting N=16384, 2D-sharded"):

- **Layout**: global element (g, j) lives on mesh tile (g % R, j % C), i.e.
  cyclic in both dimensions — late pivot steps still touch every tile (the
  same load-balance argument as the reference's cyclic row striping,
  Pthreads/Version-1/gauss_internal_input.c:155, applied to both axes).
- **Pivot search** runs only in the mesh column that owns matrix column i:
  local masked argmax, an ``all_gather`` of (value, row) candidates along the
  ``rows`` axis, then a scalar ``psum`` along ``cols`` to tell everyone the
  winner — SURVEY.md §7 hard part (d)'s latency-critical piece costs R+1
  small collectives, never O(n) data.
- **Row swap + pivot-row broadcast** fuse into one (2, mc+1) ``psum`` along
  ``rows``: each shard contributes its column-slice of the two rows being
  swapped, and the summed result *is* the broadcast pivot row — per-step
  traffic is O(n/C) per chip, vs O(n) for 1-D and O(n^2) for the reference's
  ship-all-rows MPI scheme.
- **Multiplier column** is one (mr,) ``psum`` along ``cols``.
- Elimination and back-substitution are then local FMAs; SPMD program order
  replaces every MPI_Barrier.

The whole solve compiles to a single XLA program per (n, mesh, dtype).
"""

from __future__ import annotations

import math
from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from gauss_tpu.dist.gauss_dist import _cyclic_perm, _host_dtype
from gauss_tpu.dist.mesh import make_mesh_2d_auto
from gauss_tpu.resilience import fleet as _fleet
from gauss_tpu.resilience import watchdog as _watchdog
from gauss_tpu.utils import compat


@lru_cache(maxsize=32)
def _build_solver_2d(mesh: jax.sharding.Mesh, npad: int, dtype_name: str):
    rax, cax = mesh.axis_names
    R, C = mesh.devices.shape
    mr, mc = npad // R, npad // C
    dtype = jnp.dtype(dtype_name)

    def shard_fn(a_loc, b_loc):
        """a_loc: (mr, mc) cyclic tile; b_loc: (mr,) row-sharded, col-replicated."""
        dr = lax.axis_index(rax)
        dc = lax.axis_index(cax)
        g_rows = jnp.arange(mr) * R + dr  # global row of each local row
        g_cols = jnp.arange(mc) * C + dc  # global col of each local col
        zero = jnp.zeros((), dtype)
        # b arrives replicated over cols; the loop body makes it vary there
        # (it mixes in col-psum'd terms), so widen its varying set up front.
        b_loc = compat.pcast_varying(b_loc, (cax,))

        def elim_step(i, carry):
            A, rhs = carry
            l_i, m_i = i // R, i // C
            own_ri = dr == i % R   # this mesh row holds global row i
            own_ci = dc == i % C   # this mesh col holds global col i

            # --- distributed partial pivot, owner mesh-column only ---
            col = A[:, m_i]
            cand = jnp.where(own_ci & (g_rows >= i), jnp.abs(col), -jnp.inf)
            lbest = jnp.argmax(cand)
            vals = lax.all_gather(cand[lbest], rax)        # (R,)
            gidxs = lax.all_gather(g_rows[lbest], rax)     # (R,)
            gpiv_local = gidxs[jnp.argmax(vals)]           # valid where own_ci
            gpiv = lax.psum(jnp.where(own_ci, gpiv_local, 0), cax)
            l_p = gpiv // R
            own_rp = dr == gpiv % R

            # --- swap rows i <-> gpiv and broadcast both, one psum over rows ---
            contrib = jnp.zeros((2, mc + 1), dtype)
            contrib = contrib.at[0, :mc].set(jnp.where(own_ri, A[l_i], zero))
            contrib = contrib.at[0, mc].set(jnp.where(own_ri, rhs[l_i], zero))
            contrib = contrib.at[1, :mc].set(jnp.where(own_rp, A[l_p], zero))
            contrib = contrib.at[1, mc].set(jnp.where(own_rp, rhs[l_p], zero))
            both = lax.psum(contrib, rax)
            row_i, b_i = both[0, :mc], both[0, mc]
            row_p, b_p = both[1, :mc], both[1, mc]

            # Pivot value lives at local column m_i of the owner mesh column.
            piv = lax.psum(jnp.where(own_ci, row_p[m_i], zero), cax)

            # Scaled pivot row slice (diagonal pinned to exactly 1, as in
            # core.gauss) — already resident everywhere after the swap psum.
            prow = jnp.where(g_cols == i, jnp.asarray(1.0, dtype), row_p / piv)
            y_i = b_p / piv

            # Slot of gpiv receives old row i; slot of i the scaled pivot row.
            # Write order makes gpiv == i come out right.
            A = A.at[l_p].set(jnp.where(own_rp, row_i, A[l_p]))
            rhs = rhs.at[l_p].set(jnp.where(own_rp, b_i, rhs[l_p]))
            A = A.at[l_i].set(jnp.where(own_ri, prow, A[l_i]))
            rhs = rhs.at[l_i].set(jnp.where(own_ri, y_i, rhs[l_i]))

            # --- multiplier column: one (mr,) psum over the cols axis ---
            f_local = jnp.where(own_ci, A[:, m_i], zero)
            f = lax.psum(f_local, cax)
            f = jnp.where(g_rows > i, f, zero)

            # --- local rank-1 elimination ---
            A = A - f[:, None] * prow[None, :]
            rhs = rhs - f * y_i
            return A, rhs

        A, rhs = lax.fori_loop(0, npad, elim_step, (a_loc, b_loc))

        # --- back-substitution: x kept column-sharded (mc,), row-replicated ---
        def back_step(k, x_loc):
            i = npad - 1 - k
            l_i = i // R
            own_ri = dr == i % R
            # Unsolved entries of x are 0 and U has unit diagonal, so the
            # full-slice dot picks up exactly the solved suffix.
            part = jnp.where(own_ri, A[l_i] @ x_loc, zero)
            acc = lax.psum(part, cax)                      # full row dot
            xi = lax.psum(jnp.where(own_ri, rhs[l_i] - acc, zero), rax)
            return jnp.where(g_cols == i, xi, x_loc)

        # xi is row-invariant (it ends in a psum over rows), so x stays
        # varying over cols only — matching the P(cols) out_spec.
        x0 = compat.pcast_varying(jnp.zeros((mc,), dtype), (cax,))
        x_loc = lax.fori_loop(0, npad, back_step, x0)
        return x_loc

    mapped = compat.shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(rax, cax), P(rax)),
        out_specs=P(cax))
    return jax.jit(mapped)


def _prepare_2d(a, b, mesh: jax.sharding.Mesh):
    """Identity-pad to a multiple of lcm(R, C), apply the cyclic permutation
    to rows and columns, and stage the tiles DIRECTLY onto the mesh's devices
    (host-side numpy prep + one explicit device_put per operand; the default
    jax backend is never touched — see gauss_dist._prepare).
    Returns (a_c, b_c, npad, col_perm)."""
    R, C = mesh.devices.shape
    rax, cax = mesh.axis_names
    dtype = _host_dtype(a)
    a = np.asarray(a, dtype)
    b = np.asarray(b, dtype)
    n = a.shape[0]
    blk = math.lcm(R, C)
    npad = -(-n // blk) * blk
    if npad != n:
        ap = np.zeros((npad, npad), dtype)
        ap[:n, :n] = a
        ap[np.arange(n, npad), np.arange(n, npad)] = 1.0
        bp = np.zeros((npad,), dtype)
        bp[:n] = b
    else:
        ap, bp = a, b
    rperm = _cyclic_perm(npad, R)
    cperm = _cyclic_perm(npad, C)
    a_c = jax.device_put(ap[rperm][:, cperm], NamedSharding(mesh, P(rax, cax)))
    b_c = jax.device_put(bp[rperm], NamedSharding(mesh, P(rax)))
    return a_c, b_c, npad, cperm


def prepare_dist2d(a, b, mesh: jax.sharding.Mesh):
    """Stage a system onto a 2-D mesh; handle for :func:`solve_dist2d_staged`
    (same staging/solve split rationale as gauss_dist.prepare_dist)."""
    if mesh.devices.ndim != 2:
        raise ValueError(f"gauss_solve_dist2d needs a 2-D mesh; got shape "
                         f"{mesh.devices.shape} (use gauss_solve_dist for 1-D)")
    n = np.shape(a)[0]
    a_c, b_c, npad, cperm = _prepare_2d(a, b, mesh)
    return (a_c, b_c, n, npad, cperm)


def solve_dist2d_staged(staged, mesh: jax.sharding.Mesh) -> jax.Array:
    """Solve a system previously staged by :func:`prepare_dist2d`."""
    from gauss_tpu import obs

    a_c, b_c, n, npad, cperm = staged
    solver = _build_solver_2d(mesh, npad, str(a_c.dtype))
    obs.record_collective_budget("gauss_dist2d", solver, a_c, b_c,
                                 n=n, npad=npad,
                                 mesh_shape=list(mesh.devices.shape))
    # Fleet hooks (see gauss_dist.solve_dist_staged): heartbeat + optional
    # collective watchdog deadline for supervised workers; compiled out of
    # the unsupervised path at solver-build time.
    if _fleet.active() or _watchdog.enabled():
        _fleet.beat(phase="dist_factor_solve", engine="gauss_dist2d", n=n)
        x_cyc = _watchdog.guarded_device(lambda: solver(a_c, b_c),
                                         site="dist.gauss_dist2d.solve")
    else:
        x_cyc = solver(a_c, b_c)
    # x_cyc[k] = x[cperm[k]]; undo (gather runs on the mesh's backend).
    inv = np.empty(npad, dtype=np.int64)
    inv[cperm] = np.arange(npad)
    return x_cyc[inv][:n]


def gauss_solve_dist2d(a, b, mesh: jax.sharding.Mesh = None) -> jax.Array:
    """Distributed dense solve over a 2-D mesh; returns x in natural order.

    The solver's output is column-cyclic-ordered (it comes back sharded along
    the mesh's cols axis); the inverse permutation is undone before returning.
    """
    if mesh is None:
        mesh = make_mesh_2d_auto()
    return solve_dist2d_staged(prepare_dist2d(a, b, mesh), mesh)
