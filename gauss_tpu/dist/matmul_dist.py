"""Sharded dense matmul via pjit sharding annotations.

The CUDA matmul engines (reference CUDA_and_OpenMP/Version-{1,2}/cuda_matmul.cu)
are single-GPU; the reference has no distributed matmul. The TPU framework
gets one for free from the sharding model: annotate operand shardings over the
mesh and let XLA insert the collectives (SURVEY.md §5 "distributed
communication backend"). Two layouts:

- 1-D: A row-sharded, B replicated -> C row-sharded. No communication in the
  matmul itself; the all_gather (if the caller wants C replicated) rides ICI.
- 2-D: A sharded (rows, None), B sharded (None, cols) -> C sharded
  (rows, cols) — the classic SUMMA-style layout, collectives inserted by XLA.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from gauss_tpu.dist.mesh import make_mesh


def _prepare_operands(a, b, mesh, replicate_out: bool):
    """Shared host-side prep: dtype/pad/sharding resolution for both the
    one-shot and the staged entry points. Returns
    (a_np, b_np, in_shardings, out_spec, m, n, vec_rhs)."""
    from gauss_tpu.dist.gauss_dist import _input_dtype

    # Host-side prep + explicit device_put in the callers: the default
    # backend is never touched (see gauss_tpu.dist.gauss_dist._prepare).
    # Unlike gauss, matmul keeps the input dtype (integer products stay
    # exact).
    dtype = _input_dtype(a)
    a = np.asarray(a, dtype)
    b = np.asarray(b, dtype)
    vec_rhs = b.ndim == 1  # matrix-vector: lift to (k, 1), squeeze at the end
    if vec_rhs:
        b = b[:, None]
    m, n = a.shape[0], b.shape[1]

    def _pad(x, mult0, mult1):
        """Zero-pad each dim up to the next multiple (sharding divisibility)."""
        p0 = -(-x.shape[0] // mult0) * mult0
        p1 = -(-x.shape[1] // mult1) * mult1
        if (p0, p1) == x.shape:
            return x
        xp = np.zeros((p0, p1), x.dtype)
        xp[: x.shape[0], : x.shape[1]] = x
        return xp

    if mesh.devices.ndim == 1:
        axis = mesh.axis_names[0]
        (nrows,) = mesh.devices.shape
        a, b = _pad(a, nrows, 1), b
        in_shardings = (NamedSharding(mesh, P(axis, None)),
                        NamedSharding(mesh, P()))
        out_spec = P() if replicate_out else P(axis, None)
    else:
        r, c = mesh.axis_names
        R, C = mesh.devices.shape
        a, b = _pad(a, R, 1), _pad(b, 1, C)
        in_shardings = (NamedSharding(mesh, P(r, None)),
                        NamedSharding(mesh, P(None, c)))
        out_spec = P() if replicate_out else P(r, c)
    return a, b, in_shardings, out_spec, m, n, vec_rhs


def matmul_dist(a, b, mesh: jax.sharding.Mesh = None, *,
                precision: str = "high", replicate_out: bool = True):
    """C = A @ B with operands sharded over the mesh."""
    if mesh is None:
        mesh = make_mesh()
    from gauss_tpu.core.matmul import resolve_precision

    prec = resolve_precision(precision)
    a, b, in_shardings, out_spec, m, n, vec_rhs = _prepare_operands(
        a, b, mesh, replicate_out)

    @jax.jit
    def run(a, b):
        c = jnp.dot(a, b, precision=prec)
        return lax.with_sharding_constraint(c, NamedSharding(mesh, out_spec))

    a = jax.device_put(a, in_shardings[0])
    b = jax.device_put(b, in_shardings[1])
    from gauss_tpu import obs

    obs.record_collective_budget("matmul_dist", run, a, b, via="hlo",
                                 m=m, n=n,
                                 mesh_shape=list(mesh.devices.shape))
    out = run(a, b)
    if out.shape != (m, n):
        out = out[:m, :n]
    if vec_rhs:
        out = out[:, 0]
    return out


def matmul_dist_staged(a, b, mesh: jax.sharding.Mesh = None, *,
                       precision: str = "high"):
    """Stage operands for a device-resident sharded-matmul chain.

    ``matmul_dist`` stages host arrays per call (np.asarray + device_put),
    which cannot appear inside a traced K-chain — the bench's device-span
    timing wraps the engine in one jitted ``lax.fori_loop``
    (bench/slope.matmul_chain). This entry point does the staging ONCE and
    returns ``(a_dev, b_dev, c0_dev, mm)`` where ``mm(a_, b_) -> c`` is pure
    traced computation (the sharded dot + replicated output constraint), and
    ``c0_dev`` is a replicated zero of the product shape for the chain
    carry. Matrix operands only (the chain perturbs ``a_dev`` elementwise).
    """
    if np.ndim(b) == 1:
        raise ValueError("matmul_dist_staged stages matrix operands only")
    if mesh is None:
        mesh = make_mesh()
    from gauss_tpu.core.matmul import resolve_precision

    prec = resolve_precision(precision)
    a, b, in_shardings, _out_spec, m, n, _vec = _prepare_operands(
        a, b, mesh, replicate_out=True)  # out replicated (P()) by construction

    def mm(a_, b_):
        c = jnp.dot(a_, b_, precision=prec)
        return lax.with_sharding_constraint(c, NamedSharding(mesh, P()))

    a_dev = jax.device_put(a, in_shardings[0])
    b_dev = jax.device_put(b, in_shardings[1])
    # Zero carry created device-side with its sharding (a host np.zeros +
    # device_put would ship the whole buffer through the tunnel; the
    # explicit sharding keeps the default backend untouched).
    c0 = jnp.zeros((a.shape[0], b.shape[1]), a.dtype,
                   device=NamedSharding(mesh, P()))
    return a_dev, b_dev, c0, mm
