"""Blocked distributed factorization: collectives per PANEL, not per row.

VERDICT round 1 #4 / docs/SCALING.md: the per-step engines (gauss_dist,
gauss_dist2d) faithfully re-express the reference's per-pivot-step MPI
protocol (reference OpenMP_and_MPI/gauss_mpi/gauss_internal_input.c:124-206
— barrier + bcast + scatter/gather EVERY step) with ~3-4 collectives per
pivot step x n steps; latency-bound on any interconnect. This engine is the
formulation that actually scales: right-looking blocked LU where the O(n^3)
work is local MXU GEMMs and the interconnect carries O(panel)-amortized
messages:

- **Layout**: panel-block-cyclic rows — global row block k (rows
  k*panel..(k+1)*panel) lives on shard k % P, so late panels still touch
  every shard (the reference's cyclic striping argument at block granularity).
- **Panel factorization is replicated, not negotiated**: each shard
  all-gathers the (npad, panel) column strip (ONE collective) and factors it
  redundantly with the same partial-pivoting panel kernel the single-chip
  blocked path uses (core.blocked._panel_factor_jax). Every shard derives
  identical pivots — cross-shard pivot agreement costs ZERO collectives,
  where ScaLAPACK's pdgetf2 pays one amax-reduction per column. The
  redundant flops are sum_k npad*panel^2 = n^2*panel total, ~100x below the
  2/3 n^3 GEMM work at the BASELINE config-5 scale.
- **Row swaps route in ONE psum per panel**: the panel's folded permutation
  touches at most 2*panel rows (incoming pivot rows + displaced diagonal
  block); both sets ride a single (2*panel, npad+1) psum, and every shard
  rewrites only the rows it owns. The reference ships the whole O(n^2)
  working set per step; the per-step engines ship O(n); this ships
  O(panel * n / panel) = O(n) per PANEL.
- **Trailing update is a local GEMM** per shard: A_own -= L21_own @ U12,
  with U12 = L11^{-1} (post-swap block row) computed redundantly from the
  replicated panel factor. The RHS rides as an augmented column through the
  same GEMM.
- **Back-substitution is blockwise**: the owner of block k solves the
  (panel, panel) upper-triangular system locally and one psum broadcasts
  x_k — n/panel collectives, vs n for the per-step engines.

Collective budget per solve: 3 per panel (all_gather + routing psum +
back-sub psum) x n/panel, vs ~4 x n for gauss_dist — a panel-width (~128x)
reduction, asserted from the compiled jaxpr in tests/test_dist_blocked.py.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from gauss_tpu.core.blocked import (_fold_transpositions, _panel_factor_jax,
                                    unit_lower_inv)
from gauss_tpu.dist.gauss_dist import _host_dtype
from gauss_tpu.dist.mesh import make_mesh
from gauss_tpu.resilience import fleet as _fleet
from gauss_tpu.resilience import watchdog as _watchdog
from gauss_tpu.utils import compat

DEFAULT_PANEL_DIST = 128


def auto_panel_dist(n: int, nshards: int,
                    panel_max: int = DEFAULT_PANEL_DIST) -> int:
    """Widest power-of-two panel (<= panel_max, >= 8) with panel * P <= n,
    so small systems are not identity-padded to panel * P (a n=128 solve on
    8 shards at panel=128 would pad 8x and spend 87% of its time on
    padding)."""
    p = panel_max
    while p > 8 and p * nshards > n:
        p //= 2
    return p


def _block_cyclic_perm(npad: int, nshards: int, panel: int) -> np.ndarray:
    """perm[d * m + l] = global row of shard d's local row l under
    panel-block-cyclic layout: local block lb is global block lb * P + d."""
    m = npad // nshards
    perm = np.empty(npad, dtype=np.int64)
    for d in range(nshards):
        for l in range(m):
            g = ((l // panel) * nshards + d) * panel + (l % panel)
            perm[d * m + l] = g
    return perm


@lru_cache(maxsize=32)
def _gather_order(npad: int, nshards: int, panel: int) -> np.ndarray:
    """Static index array reordering an all-gathered (P*m, panel) strip into
    global row order: ORDER[g] = d(g) * m + l(g). Plain numpy — it traces
    into the jitted shard_fn as a constant; an eager jnp array here would
    touch the DEFAULT backend at build time, which this module must never do
    (a broken default platform must not poison an explicit-mesh solve)."""
    m = npad // nshards
    g = np.arange(npad)
    blk = g // panel
    d = blk % nshards
    l = (blk // nshards) * panel + (g % panel)
    return d * m + l


@lru_cache(maxsize=32)
def _build_solver_blocked(mesh: jax.sharding.Mesh, npad: int, panel: int,
                          dtype_name: str, abft: bool = False):
    """``abft=True`` additionally carries a REPLICATED Huang-Abraham
    column-checksum row (covering the augmented RHS column too — it rides
    the same trailing GEMM) and verifies the trailing block's column sums
    against it after every panel: the partial column sums ride one extra
    psum per panel next to the three the protocol already pays, and the
    per-panel mismatch magnitudes return as an extra (nblocks,) output
    (replicated, like min_piv). The ``abft=False`` trace is unchanged."""
    axis = mesh.axis_names[0]
    nshards = mesh.devices.shape[0]
    m = npad // nshards
    nblocks = npad // panel
    w = npad + 1  # augmented: RHS rides as the last column
    dtype = jnp.dtype(dtype_name)
    order = _gather_order(npad, nshards, panel)

    def shard_fn(a_loc):
        """a_loc: (m, npad+1) — this shard's block-cyclic rows, augmented."""
        d = lax.axis_index(axis)
        l = jnp.arange(m)
        g_loc = ((l // panel) * nshards + d) * panel + (l % panel)
        zero = jnp.zeros((), dtype)

        def panel_step(carry, k):
            if abft:
                A, min_piv, gperm, crow = carry
            else:
                A, min_piv, gperm = carry
            kb = k * panel
            own_k = (k % nshards) == d          # owner of diagonal block k
            lb = (k // nshards) * panel         # its local row offset there

            # --- ONE all_gather: the global (npad, panel) column strip ---
            strip_loc = lax.dynamic_slice(A, (0, kb), (m, panel))
            strip = lax.all_gather(strip_loc, axis)          # (P, m, panel)
            strip = strip.reshape(nshards * m, panel)[order]  # global order

            # --- replicated panel factorization: identical on every shard,
            # so pivot agreement needs no communication at all ---
            pfac, ipiv, mp = _panel_factor_jax(strip, kb)
            min_piv = jnp.minimum(min_piv, mp)
            perm_g = _fold_transpositions(ipiv, kb, npad, panel)
            # Composed P of PA = LU (replicated — every shard derives the
            # same pivots), returned so factored solves can permute new
            # right-hand sides.
            gperm = gperm[perm_g]
            src = lax.dynamic_slice(perm_g, (kb,), (panel,))  # incoming rows

            # --- ONE routing psum: incoming pivot rows + displaced diagonal
            # block, each shard contributing the rows it owns ---
            src_blk = src // panel
            src_mine = (src_blk % nshards) == d
            src_li = (src_blk // nshards) * panel + (src % panel)
            incoming = jnp.where(src_mine[:, None], A[src_li], zero)
            outgoing = jnp.where(own_k,
                                 lax.dynamic_slice(A, (lb, 0), (panel, w)),
                                 zero)
            buf = lax.psum(jnp.concatenate([incoming, outgoing]), axis)
            new_diag = buf[:panel]   # post-swap diagonal block rows (pre-elim)
            old_diag = buf[panel:]   # the rows they displaced

            # --- each shard rewrites only the rows it owns ---
            tau = perm_g[g_loc]                    # where my new content lives
            moved = tau != g_loc
            is_diag = (g_loc >= kb) & (g_loc < kb + panel)
            diag_off = jnp.clip(g_loc - kb, 0, panel - 1)
            disp_off = jnp.clip(tau - kb, 0, panel - 1)
            A = jnp.where(is_diag[:, None], new_diag[diag_off], A)
            A = jnp.where((moved & ~is_diag)[:, None], old_diag[disp_off], A)

            # Panel columns from the replicated factor (multipliers below the
            # diagonal, U11 on/above; rows < kb pass through unchanged).
            strip_mine = pfac[g_loc]               # (m, panel)
            A = lax.dynamic_update_slice(A, strip_mine, (0, kb))

            # --- U12 (replicated small GEMM) + local trailing GEMM ---
            dblk = lax.dynamic_slice(pfac, (kb, 0), (panel, panel))
            rows_p = jnp.arange(panel)
            lmask = rows_p[:, None] > rows_p[None, :]
            l11 = jnp.where(lmask, dblk, zero) + jnp.eye(panel, dtype=dtype)
            linv = unit_lower_inv(l11)
            cols = jnp.arange(w)
            right = cols >= kb + panel             # trailing cols + RHS col
            u12 = jnp.where(right[None, :],
                            jnp.dot(linv, new_diag,
                                    precision=lax.Precision.HIGHEST),
                            zero)
            # Owner installs the eliminated block row's trailing columns.
            A = jnp.where((is_diag & own_k)[:, None],
                          jnp.where(right[None, :], u12[diag_off], A), A)
            # Everyone eliminates its rows below the block: one MXU GEMM.
            below = g_loc >= kb + panel
            f_own = jnp.where(below[:, None], strip_mine, zero)
            A = A - jnp.dot(f_own, u12, precision=lax.Precision.HIGHEST)
            if not abft:
                return (A, min_piv, gperm), k
            # ABFT rider: the checksum row's multipliers over the panel
            # columns are Lc = c1 @ U11^-1 (replicated small solve), its
            # trailing update the same Lc @ U12 GEMM the rows got, and the
            # verification psums each shard's partial trailing column sums
            # — one extra collective riding next to the three above.
            u11 = jnp.where(~lmask, dblk, zero)
            c1 = lax.dynamic_slice(crow, (kb,), (panel,))
            lc = lax.linalg.triangular_solve(
                u11, c1[None, :], left_side=False, lower=False)
            crow = crow - jnp.dot(lc, u12,
                                  precision=lax.Precision.HIGHEST)[0]
            colsum = lax.psum(
                jnp.sum(jnp.where(below[:, None], A, zero), axis=0), axis)
            diff = jnp.where(right, colsum - crow, zero)
            diff = jnp.where(jnp.isnan(diff), jnp.inf, jnp.abs(diff))
            return (A, min_piv, gperm, crow), jnp.max(diff)

        # min_piv init inherits a_loc's varying type (shard_map vma);
        # NaN-proof zero via the integer domain (int x * 0 is always 0).
        vma0i = a_loc[0, 0].astype(jnp.int32) * 0
        vma0 = vma0i.astype(dtype)
        init = (a_loc, jnp.asarray(jnp.inf, dtype) + vma0,
                jnp.arange(npad) + vma0i)
        if abft:
            # Replicated initial checksum row: global column sums of the
            # augmented matrix, one psum of each shard's local row sums.
            crow0 = lax.psum(jnp.sum(a_loc, axis=0), axis)
            (A, min_piv, gperm, _), errs = lax.scan(
                panel_step, init + (crow0,), jnp.arange(nblocks))
        else:
            (A, min_piv, gperm), _ = lax.scan(
                panel_step, init, jnp.arange(nblocks))

        # --- blockwise back-substitution: one psum per block. The RHS was
        # eliminated in place as the augmented column (L already applied),
        # so only the U substitution remains. ---
        x = _block_substitution(A, lambda rows, kb: rows[:, npad],
                                axis, d, npad, panel, nshards, lower=False)
        # min_piv and gperm are numerically identical on every shard
        # (replicated panel factorization) but typed varying; a pmin makes
        # the replication provable for out_specs.
        out = (x, A, lax.pmin(gperm, axis), lax.pmin(min_piv, axis))
        if abft:
            out = out + (lax.pmin(errs, axis),)
        return out

    out_specs = (P(None), P(axis, None), P(None), P())
    if abft:
        out_specs = out_specs + (P(None),)
    mapped = compat.shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(axis, None),),
        out_specs=out_specs)
    return jax.jit(mapped)


def _block_substitution(A_loc, rhs_block, axis, d, npad: int, panel: int,
                        nshards: int, lower: bool):
    """Blockwise triangular substitution over the distributed getrf factor:
    per block, the full-width dot folds in the already-solved blocks (the
    unsolved suffix/prefix multiplies zeros), the owner solves its
    (panel, panel) diagonal system, and one psum broadcasts the block.
    ``rhs_block(rows, kb)`` supplies the block's right-hand side — the spent
    augmented column at factor time, a fresh vector at re-solve time — so
    factor-time and resolve-time substitution cannot drift apart.
    ``lower`` selects L (unit-diagonal, ascending) vs U (descending)."""
    w = npad + 1
    dtype = A_loc.dtype
    zero = jnp.zeros((), dtype)
    rows_p = jnp.arange(panel)
    nblocks = npad // panel

    def step(x, k):
        kb = k * panel
        own_k = (k % nshards) == d
        lb = (k // nshards) * panel
        rows = lax.dynamic_slice(A_loc, (lb, 0), (panel, w))
        r_k = rhs_block(rows, kb) - rows[:, :npad] @ x
        dkk = lax.dynamic_slice(rows, (0, kb), (panel, panel))
        if lower:
            # unit_diagonal=True ignores the stored diagonal (U's), so only
            # the strictly-lower multipliers need keeping.
            dkk = jnp.where(rows_p[:, None] > rows_p[None, :], dkk, zero)
            xk = lax.linalg.triangular_solve(
                dkk, r_k[:, None], left_side=True, lower=True,
                unit_diagonal=True)[:, 0]
        else:
            dkk = jnp.where(rows_p[:, None] <= rows_p[None, :], dkk, zero)
            xk = lax.linalg.triangular_solve(
                dkk, r_k[:, None], left_side=True, lower=False)[:, 0]
        xk = lax.psum(jnp.where(own_k, xk, zero), axis)
        return lax.dynamic_update_slice(x, xk, (kb,)), k

    order = (jnp.arange(nblocks) if lower
             else jnp.arange(nblocks - 1, -1, -1))
    x, _ = lax.scan(step, jnp.zeros((npad,), dtype), order)
    return x


@lru_cache(maxsize=32)
def _build_resolver_blocked(mesh: jax.sharding.Mesh, npad: int, panel: int,
                            dtype_name: str):
    """Distributed solve from an already-factored system: given the factored
    block-cyclic local rows (L multipliers below the diagonal, U on/above —
    getrf layout, plus the spent RHS column which is ignored), the composed
    row permutation, and a NEW right-hand side, run blockwise forward and
    back substitution with one psum per block each way. O(n^2) work and
    2 * n/panel collectives per solve — the cheap correction step that lets
    iterative refinement run against ONE distributed factorization (ADVICE
    round 2: the handoff's distributed route must refine too)."""
    axis = mesh.axis_names[0]
    nshards = mesh.devices.shape[0]

    def shard_fn(a_loc, perm, r):
        d = lax.axis_index(axis)
        rp = r[perm]
        # Forward: y = L^-1 (P r); y is nonzero only for solved prefix
        # blocks, so the full-width dot picks up exactly the L_{k,<k} term.
        y = _block_substitution(
            a_loc, lambda rows, kb: lax.dynamic_slice(rp, (kb,), (panel,)),
            axis, d, npad, panel, nshards, lower=True)
        # Backward: x = U^-1 y.
        return _block_substitution(
            a_loc, lambda rows, kb: lax.dynamic_slice(y, (kb,), (panel,)),
            axis, d, npad, panel, nshards, lower=False)

    mapped = compat.shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(axis, None), P(None), P(None)),
        out_specs=P(None))
    return jax.jit(mapped)


def _prepare_blocked(a, b, mesh: jax.sharding.Mesh, panel: int):
    """Identity-pad to a multiple of panel*P, apply the panel-block-cyclic
    row permutation, augment with the RHS column, and stage the shards
    DIRECTLY onto the mesh's devices (host numpy + one explicit device_put;
    the default backend is never touched — same rule as gauss_dist)."""
    nshards = mesh.devices.shape[0]
    axis = mesh.axis_names[0]
    dtype = _host_dtype(a)
    a = np.asarray(a, dtype)
    b = np.asarray(b, dtype)
    n = a.shape[0]
    blk = panel * nshards
    npad = -(-n // blk) * blk
    aug = np.zeros((npad, npad + 1), dtype)
    aug[:n, :n] = a
    aug[np.arange(n, npad), np.arange(n, npad)] = 1.0
    aug[:n, npad] = b
    perm = _block_cyclic_perm(npad, nshards, panel)
    a_c = jax.device_put(aug[perm], NamedSharding(mesh, P(axis, None)))
    return a_c, npad


def prepare_dist_blocked(a, b, mesh: jax.sharding.Mesh,
                         panel: int | None = None):
    """Stage a system; returns an opaque handle for
    :func:`solve_dist_blocked_staged` (staging/solve split as in gauss_dist).
    panel=None resolves through :func:`auto_panel_dist`."""
    from gauss_tpu import obs

    n = np.shape(a)[0]
    if panel is None:
        panel = auto_panel_dist(n, mesh.devices.shape[0])
    with obs.span("dist_host_staging", n=n, panel=panel,
                  shards=int(mesh.devices.size)):
        a_c, npad = _prepare_blocked(a, b, mesh, panel)
        jax.block_until_ready(a_c)
    return (a_c, n, npad, panel)


def solve_dist_blocked_staged(staged, mesh: jax.sharding.Mesh) -> jax.Array:
    from gauss_tpu import obs

    a_c, n, npad, panel = staged
    solver = _build_solver_blocked(mesh, npad, panel, str(a_c.dtype))
    obs.record_collective_budget("gauss_dist_blocked", solver, a_c,
                                 n=n, npad=npad, panel=panel,
                                 nblocks=npad // panel,
                                 shards=int(mesh.devices.size))
    # Fleet hooks: heartbeat at the stage boundary; supervised workers
    # additionally get a watchdog deadline so a peer hung inside the
    # per-panel psum/all_gather protocol surfaces as WorkerLostError.
    # Guarded at solver-build time: the unsupervised path carries zero
    # hook plumbing.
    hooks = _fleet.active() or _watchdog.enabled()
    if hooks:
        _fleet.beat(phase="dist_factor_solve", engine="gauss_dist_blocked",
                    n=n)
    with obs.span("dist_factor_solve", n=n, panel=panel):
        if hooks:
            x, *_ = _watchdog.guarded_device(
                lambda: jax.block_until_ready(solver(a_c)),
                site="dist.gauss_dist_blocked.solve")
        else:
            x, *_ = jax.block_until_ready(solver(a_c))
    return x[:n]


class DistBlockedLU:
    """A factored distributed system: the sharded getrf-layout rows, the
    composed row permutation, and the geometry needed to solve against it.
    Produced by :func:`factor_solve_dist_blocked_staged`; consumed by
    :func:`lu_solve_dist_blocked` — one distributed factorization, many
    O(n^2) solves (the same getrf/getrs split the single-chip path has)."""

    def __init__(self, a_fac, perm, min_piv, n, npad, panel, mesh,
                 abft_err=None):
        self.a_fac, self.perm, self.min_piv = a_fac, perm, min_piv
        self.n, self.npad, self.panel, self.mesh = n, npad, panel, mesh
        #: (nblocks,) per-panel ABFT checksum mismatch magnitudes when the
        #: factorization carried the checksum row; None otherwise.
        self.abft_err = abft_err


def factor_solve_dist_blocked_staged(staged, mesh: jax.sharding.Mesh,
                                     abft: bool = False):
    """Factor + solve a staged system; returns (x, DistBlockedLU).

    ``abft=True`` builds the checksum-carrying solver (see
    :func:`_build_solver_blocked`); the per-panel mismatch magnitudes land
    on ``DistBlockedLU.abft_err`` for the caller to judge (the refined
    entry below raises the typed SDC error past the tolerance)."""
    a_c, n, npad, panel = staged
    solver = _build_solver_blocked(mesh, npad, panel, str(a_c.dtype),
                                   abft=abft)
    if _fleet.active() or _watchdog.enabled():
        _fleet.beat(phase="dist_factor_solve", engine="gauss_dist_blocked",
                    n=n)
        out = _watchdog.guarded_device(
            lambda: solver(a_c), site="dist.gauss_dist_blocked.factor")
    else:
        out = solver(a_c)
    x, a_fac, perm, min_piv = out[:4]
    errs = out[4] if abft else None
    return x[:n], DistBlockedLU(a_fac, perm, min_piv, n, npad, panel, mesh,
                                abft_err=errs)


def lu_solve_dist_blocked(fac: DistBlockedLU, r) -> jax.Array:
    """Solve A d = r against an existing distributed factorization: blockwise
    forward + back substitution, 2 psums per block, O(n^2) work."""
    mesh = fac.mesh
    axis = mesh.axis_names[0]
    dtype = np.dtype(str(fac.a_fac.dtype))
    rpad = np.zeros(fac.npad, dtype)
    rpad[:fac.n] = np.asarray(r, dtype)
    r_dev = jax.device_put(rpad, NamedSharding(mesh, P(None)))
    resolver = _build_resolver_blocked(mesh, fac.npad, fac.panel,
                                       str(fac.a_fac.dtype))
    return resolver(fac.a_fac, fac.perm, r_dev)[:fac.n]


def host_refine(a64, b64, x0, lu_solve_fn, iters: int,
                tol: float) -> np.ndarray:
    """The shared host-f64 refinement loop for every distributed engine:
    per iteration an O(n^2) f64 residual on host and an O(n^2) correction
    through ``lu_solve_fn`` (a solve against EXISTING factors — no
    refactorization). Same tol contract as core.blocked.solve_refined:
    stop once ||Ax - b||_2 <= tol * min(1, ||b||_2); tol=0 runs exactly
    ``iters``."""
    from gauss_tpu import obs

    x = np.asarray(x0, np.float64)
    tol_eff = tol * min(1.0, float(np.linalg.norm(b64))) if tol > 0.0 else 0.0
    for _ in range(iters):
        with obs.span("refine_residual"):
            r = b64 - a64 @ x
        if tol > 0.0 and float(np.linalg.norm(r)) <= tol_eff:
            break
        with obs.span("refine_correction"):
            x = x + np.asarray(lu_solve_fn(r), np.float64)
    return x


def gauss_solve_dist_blocked_refined(a, b, mesh: jax.sharding.Mesh = None,
                                     panel: int | None = None,
                                     iters: int = 2,
                                     tol: float = 0.0,
                                     abft: bool = False) -> np.ndarray:
    """Distributed blocked solve + host-f64 iterative refinement; returns
    x float64.

    The distributed sibling of core.blocked.solve_refined (ADVICE round 2:
    solve_handoff's past-the-budget route must not silently drop refinement):
    one f32 distributed factorization, then per iteration an O(n^2) host-f64
    residual and an O(n^2) distributed correction solve through the SAME
    factors (:func:`lu_solve_dist_blocked`) — no refactorization.

    ``tol``: same early-stop contract as solve_refined — stop once
    ``||Ax - b||_2 <= tol * min(1, ||b||_2)``; 0.0 runs exactly ``iters``.

    ``abft=True``: the factorization carries the replicated checksum row
    (one extra psum per panel) and every panel's trailing block is
    verified on-device; a mismatch past the tolerance emits an obs ``sdc``
    event localizing the panel and raises the typed
    :class:`~gauss_tpu.resilience.abft.SDCDetectedError` — the
    distributed engine has no in-place replay (no host-stepped carry to
    roll back to), so detection escalates to the caller's recovery ladder
    instead of refining a corrupted factor into a wrong-but-plausible
    answer.
    """
    from gauss_tpu import obs

    if mesh is None:
        mesh = make_mesh()
    a64 = np.asarray(a, np.float64)
    b64 = np.asarray(b, np.float64)
    staged = prepare_dist_blocked(a64.astype(np.float32),
                                  b64.astype(np.float32), mesh, panel=panel)
    x0, fac = factor_solve_dist_blocked_staged(staged, mesh, abft=abft)
    if abft:
        from gauss_tpu.resilience import abft as _abft

        errs = np.asarray(fac.abft_err, np.float64)
        scale = float(max(1.0, np.max(np.abs(a64).sum(axis=0))))
        sdc_tol = _abft.default_tol(fac.npad, np.float32, scale)
        worst = int(np.argmax(np.where(np.isnan(errs), np.inf, errs)))
        worst_err = float(errs[worst]) if np.isfinite(errs[worst]) \
            else float("inf")
        if not worst_err <= sdc_tol:
            obs.counter("abft.sdc_detected")
            obs.emit("sdc", engine="dist_blocked", group=worst,
                     col=worst * fac.panel, magnitude=worst_err,
                     action="escalate")
            raise _abft.SDCDetectedError(
                f"dist_blocked ABFT: panel {worst} failed its checksum "
                f"(|mismatch| {worst_err:.3e} > tol {sdc_tol:.3e}); the "
                f"distributed engine escalates instead of replaying",
                engine="dist_blocked", group=worst, col=worst * fac.panel,
                magnitude=worst_err)
    return host_refine(a64, b64, x0,
                       lambda r: lu_solve_dist_blocked(fac, r), iters, tol)


def gauss_solve_dist_blocked(a, b, mesh: jax.sharding.Mesh = None,
                             panel: int | None = None) -> jax.Array:
    """Distributed blocked dense solve; returns x replicated on every shard.

    The performance formulation of the distributed axis (the per-step
    gauss_dist stays as the reference-shape parity engine). Columns are
    never permuted, so x returns in natural order.
    """
    if mesh is None:
        mesh = make_mesh()
    return solve_dist_blocked_staged(
        prepare_dist_blocked(a, b, mesh, panel=panel), mesh)
