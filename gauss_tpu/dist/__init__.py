"""Multi-chip execution engines over a jax.sharding.Mesh (SURVEY.md §7.4).

The reference's only distributed path is the MPI master–worker gauss
(reference OpenMP_and_MPI/gauss_mpi/gauss_internal_input.c:124-255): rank 0
owns the matrix and, per pivot step, broadcasts the pivot row and ships row
blocks out and back over the network — the documented bottleneck (its own
report ranks MPI slowest). The TPU-native re-expression keeps data
device-resident and sharded permanently: rows live row-cyclically across the
mesh, the pivot row rides a psum over ICI instead of MPI_Bcast + Isend/Irecv,
and the SPMD program order replaces MPI_Barrier.
"""

from gauss_tpu.dist.mesh import make_mesh, make_mesh_2d  # noqa: F401
from gauss_tpu.dist.gauss_dist import gauss_solve_dist, eliminate_dist  # noqa: F401
from gauss_tpu.dist.gauss_dist2d import gauss_solve_dist2d  # noqa: F401
from gauss_tpu.dist.gauss_dist_blocked import (  # noqa: F401
    gauss_solve_dist_blocked, gauss_solve_dist_blocked_refined)
from gauss_tpu.dist.gauss_dist_blocked2d import (  # noqa: F401
    gauss_solve_dist_blocked2d, gauss_solve_dist_blocked2d_refined)
from gauss_tpu.dist.matmul_dist import matmul_dist  # noqa: F401

# Measured engine crossover (reports/cells_gauss_dist.json, n=128..4096
# x {2,4,8} shards): the 2-D tournament engine's fixed per-step cost (its
# compile-scheduled two-stage election) buys strip traffic that shrinks
# with BOTH mesh axes, so it loses below n=1024 and wins at and above it —
# at every swept shard count, with a lead that grows with n (2048 @8sh:
# 1.52 s vs 5.07 s 1-D). This constant states that as a routing rule
# instead of leaving the tables to be eyeballed (VERDICT r3 weak #6).
DIST_2D_CROSSOVER_N = 1024


def recommend_engine(n: int, ndev: int | None = None):
    """The measured-best distributed gauss engine for a size: the 1-D
    panel-blocked engine below DIST_2D_CROSSOVER_N, the 2-D
    tournament-pivoting engine at or above it. ``ndev`` is accepted for
    symmetry but does not change the answer on the swept range (2-8
    shards); both engines' refined entries share the same contract."""
    if n < DIST_2D_CROSSOVER_N:
        return gauss_solve_dist_blocked_refined
    return gauss_solve_dist_blocked2d_refined
