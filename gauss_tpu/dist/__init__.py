"""Multi-chip execution engines over a jax.sharding.Mesh (SURVEY.md §7.4).

The reference's only distributed path is the MPI master–worker gauss
(reference OpenMP_and_MPI/gauss_mpi/gauss_internal_input.c:124-255): rank 0
owns the matrix and, per pivot step, broadcasts the pivot row and ships row
blocks out and back over the network — the documented bottleneck (its own
report ranks MPI slowest). The TPU-native re-expression keeps data
device-resident and sharded permanently: rows live row-cyclically across the
mesh, the pivot row rides a psum over ICI instead of MPI_Bcast + Isend/Irecv,
and the SPMD program order replaces MPI_Barrier.
"""

from gauss_tpu.dist.mesh import make_mesh, make_mesh_2d  # noqa: F401
from gauss_tpu.dist.gauss_dist import gauss_solve_dist, eliminate_dist  # noqa: F401
from gauss_tpu.dist.gauss_dist2d import gauss_solve_dist2d  # noqa: F401
from gauss_tpu.dist.gauss_dist_blocked import (  # noqa: F401
    gauss_solve_dist_blocked, gauss_solve_dist_blocked_refined)
from gauss_tpu.dist.gauss_dist_blocked2d import (  # noqa: F401
    gauss_solve_dist_blocked2d, gauss_solve_dist_blocked2d_refined)
from gauss_tpu.dist.matmul_dist import matmul_dist  # noqa: F401
