"""2-D panel-blocked distributed LU: the pod-scale factorization shape.

VERDICT round 2 missing #3: the 1-D blocked engine
(:mod:`gauss_tpu.dist.gauss_dist_blocked`) all-gathers the full (npad, panel)
column strip to EVERY shard and factors it redundantly — per-chip strip
traffic is O(n^2) per solve regardless of the chip count, which caps scaling
exactly where BASELINE config 5 (n=16384, 2-D-sharded, v5p-64) starts. This
module is the ScaLAPACK-pdgetrf-shaped engine rebuilt for the JAX sharding
model, with the panel itself handled by **tournament pivoting** (the
communication-avoiding LU scheme of Grigori/Demmel/Xiang's CALU): the strip
is never replicated — each mesh row elects ``panel`` local candidate pivot
rows by local partial pivoting, one ``all_gather`` of the (panel, panel)
candidate blocks along the row axis stages a replicated playoff, and GEPP on
that (R*panel, panel) stack both picks the panel's global pivot rows and
factors their (panel, panel) block in place. Per-panel communication:

- ONE ``psum`` along the **cols** axis routes the owning mesh column's
  (mr, panel) strip slice to every shard of its mesh row — O(n/R * panel);
- ONE ``all_gather`` along the **rows** axis of the candidate blocks —
  O(R * panel^2), independent of n;
- ONE ``psum`` along the **rows** axis routes the swapped rows (their full
  local column slices + strip slices) — O((n/C + panel) * panel).

Per-chip traffic per solve is therefore O(n^2/R + n^2/C + n*panel*R), versus
the 1-D engine's O(n^2): the strip cost now scales DOWN with the mesh, the
ScaLAPACK property the round-2 verdict asked for. The trailing update is one
local (mr, panel) x (panel, mc) MXU GEMM on every shard — sharded over BOTH
axes — with U12 computed redundantly per mesh column from the replicated
tournament factor (no broadcast needed) and L21 = A21 @ U11^-1 computed
locally from the routed strip.

Pivot-quality note: tournament pivoting is weaker than global partial
pivoting in the worst case (growth bound 2^(panel*log2 R) vs 2^panel) but is
the established practical trade for exactly this communication pattern; the
engine tracks min |U11 diagonal| as its singularity witness the same way the
other engines track min |pivot|, and the refined entry point restores
f64-grade accuracy through the factored solve.

Reference lineage: the reference's only multi-node engine ships the whole
O(n^2) working set through rank 0 every pivot step
(reference OpenMP_and_MPI/gauss_mpi/gauss_internal_input.c:124-206); its 2-D
analog here keeps every byte device-resident, moves O(panel)-amortized
messages, and does the O(n^3) on the MXU.
"""

from __future__ import annotations

import math
from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from gauss_tpu.core.blocked import (_fold_transpositions, _panel_factor_jax,
                                    unit_lower_inv, upper_inv)
from gauss_tpu.dist.gauss_dist import _host_dtype
from gauss_tpu.dist.gauss_dist_blocked import (DEFAULT_PANEL_DIST,
                                               _block_cyclic_perm,
                                               auto_panel_dist)
from gauss_tpu.dist.mesh import make_mesh_2d_auto
from gauss_tpu.resilience import fleet as _fleet
from gauss_tpu.resilience import watchdog as _watchdog
from gauss_tpu.utils import compat


def auto_panel_dist2d(n: int, R: int, C: int,
                      panel_max: int = DEFAULT_PANEL_DIST) -> int:
    """Widest power-of-two panel (<= panel_max, >= 8) whose padded size
    panel * lcm(R, C) does not dwarf n — the 1-D anti-padding rule with
    lcm(R, C) standing in for the shard count (one policy, one place)."""
    return auto_panel_dist(n, math.lcm(R, C), panel_max)


# One layout rule for both engines and both axes of this one.
_block_cyclic_perm_2d = _block_cyclic_perm


def _perm_from_winners(winners, kb: int, npad: int, panel: int):
    """Fold the tournament's winner rows into one global swap permutation:
    sequentially swap position kb+j with the CURRENT position of winner j,
    tracking the inverse permutation so later winners are found wherever
    earlier swaps moved them. Returns perm with new[i] = old[perm[i]]."""
    def fold(j, state):
        p, invp = state
        w = winners[j]
        pos_w = invp[w]
        a_, b_ = p[kb + j], p[pos_w]
        p = p.at[kb + j].set(b_).at[pos_w].set(a_)
        invp = invp.at[b_].set(kb + j).at[a_].set(pos_w)
        return p, invp

    init = jnp.arange(npad) + winners[0] * 0  # inherit vma type
    p, _ = lax.fori_loop(0, panel, fold, (init, init))
    return p


class DistBlocked2DLU:
    """A 2-D-factored distributed system: the sharded getrf-layout tiles,
    the composed row permutation, the replicated per-panel diagonal-block
    inverses, and the geometry to solve against it."""

    def __init__(self, a_fac, perm, linvs, uinvs, min_piv, n, npad, panel,
                 mesh):
        self.a_fac, self.perm = a_fac, perm
        self.linvs, self.uinvs, self.min_piv = linvs, uinvs, min_piv
        self.n, self.npad, self.panel, self.mesh = n, npad, panel, mesh


@lru_cache(maxsize=32)
def _build_factor_2d(mesh: jax.sharding.Mesh, npad: int, panel: int,
                     dtype_name: str):
    rax, cax = mesh.axis_names
    R, C = mesh.devices.shape
    mr, mc = npad // R, npad // C
    nblocks = npad // panel
    dtype = jnp.dtype(dtype_name)

    def shard_fn(a_loc):
        """a_loc: (mr, mc) panel-block-cyclic tile (rows over R, cols over C)."""
        dr = lax.axis_index(rax)
        dc = lax.axis_index(cax)
        lrows = jnp.arange(mr)
        lcols = jnp.arange(mc)
        g_rows = ((lrows // panel) * R + dr) * panel + (lrows % panel)
        g_cols = ((lcols // panel) * C + dc) * panel + (lcols % panel)
        zero = jnp.zeros((), dtype)

        def panel_step(carry, k):
            A, min_piv, gperm, linvs, uinvs = carry
            kb = k * panel
            own_col = (k % C) == dc
            own_row = (k % R) == dr
            lc = (k // C) * panel       # local col offset in the owning col
            lr = (k // R) * panel       # local row offset in the owning row

            # --- [psum over cols] the owning column's strip slice reaches
            # every shard of its mesh row: (mr, panel), O(n/R * panel) ---
            strip_loc = jnp.where(own_col,
                                  lax.dynamic_slice(A, (0, lc), (mr, panel)),
                                  zero)
            strip = lax.psum(strip_loc, cax)

            # --- local candidate election: GEPP over the ELIGIBLE local
            # rows (finished rows are zeroed so they cannot win) ---
            elig = g_rows >= kb
            sel = jnp.where(elig[:, None], strip, zero)
            # zero_pivot_safe: a shard's eligible rows are ROUTINELY
            # rank-deficient here (duplicate rows, or fewer eligible rows
            # than panel); the guard keeps the election's argmax sound.
            _, ipiv_loc, _ = _panel_factor_jax(sel, 0, zero_pivot_safe=True)
            perm_loc = _fold_transpositions(ipiv_loc, 0, mr, panel)
            chosen = perm_loc[:panel]
            cand_vals = sel[chosen]           # original values, zeros if
            cand_gidx = g_rows[chosen]        # ineligible (cannot win)

            # --- [all_gather over rows] the tournament: O(R * panel^2),
            # independent of n. GEPP on the stacked candidates both elects
            # the global pivot rows and factors their block in place. The
            # candidate row indices ride as one extra float column (exact
            # below 2^24 — asserted at staging time) so the panel costs ONE
            # gather, not two. ---
            cand = jnp.concatenate(
                [cand_vals, cand_gidx.astype(dtype)[:, None]], axis=1)
            stack = lax.all_gather(cand, rax).reshape(R * panel, panel + 1)
            stack_vals = stack[:, :panel]
            stack_gidx = stack[:, panel].astype(jnp.int32)
            tfac, tipiv, tmin = _panel_factor_jax(stack_vals, 0,
                                                  zero_pivot_safe=True)
            min_piv = jnp.minimum(min_piv, tmin)
            tperm = _fold_transpositions(tipiv, 0, R * panel, panel)
            winners = stack_gidx[tperm[:panel]]
            top = tfac[:panel]                 # L11\U11, getrf layout

            # Diagonal-block inverses (replicated): U12 and the factored
            # solves become GEMMs, exactly as in core.blocked.
            jj = jnp.arange(panel)
            lmask = jj[:, None] > jj[None, :]
            linv = unit_lower_inv(jnp.where(lmask, top, zero)
                                  + jnp.eye(panel, dtype=dtype))
            uinv = upper_inv(jnp.where(~lmask, top, zero))
            linvs = lax.dynamic_update_slice(linvs, linv[None], (k, 0, 0))
            uinvs = lax.dynamic_update_slice(uinvs, uinv[None], (k, 0, 0))

            # --- the panel's swap permutation, composed into P ---
            perm_g = _perm_from_winners(winners, kb, npad, panel)
            gperm = gperm[perm_g]

            # --- [psum over rows] route swapped rows: each shard
            # contributes its local column slice AND strip slice of the
            # rows it owns; O((n/C + panel) * panel) ---
            src = lax.dynamic_slice(perm_g, (kb,), (panel,))
            src_blk = src // panel
            src_own = (src_blk % R) == dr
            src_lr = (src_blk // R) * panel + (src % panel)
            inc_A = jnp.where(src_own[:, None], A[src_lr], zero)
            inc_S = jnp.where(src_own[:, None], strip[src_lr], zero)
            out_A = jnp.where(own_row,
                              lax.dynamic_slice(A, (lr, 0), (panel, mc)),
                              zero)
            out_S = jnp.where(own_row,
                              lax.dynamic_slice(strip, (lr, 0),
                                                (panel, panel)),
                              zero)
            buf = lax.psum(
                jnp.concatenate([inc_A, inc_S, out_A, out_S], axis=1), rax)
            new_diag_A = buf[:, :mc]                  # post-swap block rows
            new_diag_S = buf[:, mc:mc + panel]
            old_diag_A = buf[:, mc + panel:2 * mc + panel]  # displaced rows
            old_diag_S = buf[:, 2 * mc + panel:]

            # --- each shard rewrites only the rows it owns (content moves
            # exclusively between block slots and winner slots) ---
            tau = perm_g[g_rows]
            moved = tau != g_rows
            is_diag = (g_rows >= kb) & (g_rows < kb + panel)
            diag_off = jnp.clip(g_rows - kb, 0, panel - 1)
            disp_off = jnp.clip(tau - kb, 0, panel - 1)
            A = jnp.where(is_diag[:, None], new_diag_A[diag_off], A)
            A = jnp.where((moved & ~is_diag)[:, None], old_diag_A[disp_off],
                          A)
            strip = jnp.where(is_diag[:, None], new_diag_S[diag_off], strip)
            strip = jnp.where((moved & ~is_diag)[:, None],
                              old_diag_S[disp_off], strip)

            # --- L21 = A21 @ U11^-1: local, from the routed strip ---
            below = g_rows >= kb + panel
            l21 = jnp.dot(jnp.where(below[:, None], strip, zero), uinv,
                          precision=lax.Precision.HIGHEST)

            # --- U12 = L11^-1 @ (post-swap block rows): local per mesh
            # column from the replicated tournament factor ---
            u12 = jnp.dot(linv, new_diag_A, precision=lax.Precision.HIGHEST)
            right = g_cols >= kb + panel
            u12_masked = jnp.where(right[None, :], u12, zero)

            # Block rows: trailing columns become U12; earlier columns (the
            # rows' L history) arrived with the routing and stay.
            A = jnp.where(is_diag[:, None] & right[None, :], u12[diag_off],
                          A)

            # --- trailing update: ONE local MXU GEMM, sharded both ways ---
            f = jnp.where(below[:, None], l21, zero)
            A = A - jnp.dot(f, u12_masked, precision=lax.Precision.HIGHEST)

            # Owning column installs the panel columns: L21 below, the
            # factored L11\U11 block rows, finished rows unchanged.
            pan = jnp.where(below[:, None], l21, strip)
            pan = jnp.where(is_diag[:, None], top[diag_off], pan)
            A_pan = lax.dynamic_update_slice(A, pan, (0, lc))
            A = jnp.where(own_col, A_pan, A)

            return (A, min_piv, gperm, linvs, uinvs), k

        # Carry inits inherit a_loc's varying-manual-axes type (the vma0
        # trick from the 1-D engine); NaN-proof zero via the int domain.
        vma0i = a_loc[0, 0].astype(jnp.int32) * 0
        vma0 = vma0i.astype(dtype)
        (A, min_piv, gperm, linvs, uinvs), _ = lax.scan(
            panel_step,
            (a_loc, jnp.asarray(jnp.inf, dtype) + vma0,
             jnp.arange(npad) + vma0i,
             jnp.zeros((nblocks, panel, panel), dtype) + vma0,
             jnp.zeros((nblocks, panel, panel), dtype) + vma0),
            jnp.arange(nblocks))

        # Replicated outputs proved replicated for out_specs: one pmin per
        # axis pair (values are bit-identical on every shard already).
        pm = lambda t: lax.pmin(lax.pmin(t, rax), cax)  # noqa: E731
        return A, pm(gperm), pm(linvs), pm(uinvs), pm(min_piv)

    mapped = compat.shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(rax, cax),),
        out_specs=(P(rax, cax), P(None), P(None), P(None), P()))
    return jax.jit(mapped)


@lru_cache(maxsize=32)
def _build_solver_2d(mesh: jax.sharding.Mesh, npad: int, panel: int,
                     dtype_name: str):
    """Blockwise substitution against the 2-D factor: per block one psum
    along cols (the row-dot partial sums) and one psum along rows (the
    solved block broadcast) — 4 * n/panel collectives per solve, O(n^2)
    work. The diagonal solves ride the replicated tournament inverses."""
    rax, cax = mesh.axis_names
    R, C = mesh.devices.shape
    mr, mc = npad // R, npad // C
    nblocks = npad // panel
    dtype = jnp.dtype(dtype_name)

    def shard_fn(a_loc, perm, linvs, uinvs, b):
        dr = lax.axis_index(rax)
        dc = lax.axis_index(cax)
        lcols = jnp.arange(mc)
        g_cols = ((lcols // panel) * C + dc) * panel + (lcols % panel)
        zero = jnp.zeros((), dtype)
        rp = b[perm]

        def substep(x, k, inv_stack, rhs):
            """One block of either substitution: the unsolved part of x is
            zero, so the full local row-dot picks up exactly the solved
            terms; owner row solves via the replicated inverse."""
            kb = k * panel
            own_row = (k % R) == dr
            lr = (k // R) * panel
            rows = lax.dynamic_slice(a_loc, (lr, 0), (panel, mc))
            part = lax.psum(rows @ x[g_cols], cax)
            r_k = lax.dynamic_slice(rhs, (kb,), (panel,)) - part
            xk = jnp.dot(inv_stack[k], r_k, precision=lax.Precision.HIGHEST)
            xk = lax.psum(jnp.where(own_row, xk, zero), rax)
            return lax.dynamic_update_slice(x, xk, (kb,))

        # Forward: y = L^-1 P b (unit-lower; linv already embeds the unit
        # diagonal). The dot's L_kk y_k and U y_suffix terms are zero.
        y, _ = lax.scan(
            lambda x, k: (substep(x, k, linvs, rp), k),
            jnp.zeros((npad,), dtype) + rp[0] * 0, jnp.arange(nblocks))
        # Backward: x = U^-1 y.
        x, _ = lax.scan(
            lambda x, k: (substep(x, k, uinvs, y), k),
            jnp.zeros((npad,), dtype) + y[0] * 0,
            jnp.arange(nblocks - 1, -1, -1))
        return lax.pmin(lax.pmin(x, rax), cax)

    mapped = compat.shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(rax, cax), P(None), P(None), P(None), P(None)),
        out_specs=P(None))
    return jax.jit(mapped)


def _resolve_mesh_panel(a, mesh, panel):
    if mesh is None:
        mesh = make_mesh_2d_auto()
    if mesh.devices.ndim != 2:
        raise ValueError(f"gauss_dist_blocked2d needs a 2-D mesh; got shape "
                         f"{mesh.devices.shape} (use gauss_dist_blocked "
                         f"for 1-D)")
    if panel is None:
        panel = auto_panel_dist2d(np.shape(a)[0], *mesh.devices.shape)
    return mesh, panel


def prepare_dist_blocked2d(a, b, mesh: jax.sharding.Mesh,
                           panel: int | None = None):
    """Identity-pad to a multiple of panel * lcm(R, C), apply the
    panel-block-cyclic permutation to rows AND columns, and stage the tiles
    directly onto the mesh (explicit device_put; the default backend is
    never touched — same rule as every dist engine here). The column
    permutation is pure data layout: shard_fn addresses columns by their
    global indices, so x returns in natural order."""
    mesh, panel = _resolve_mesh_panel(a, mesh, panel)
    R, C = mesh.devices.shape
    rax, cax = mesh.axis_names
    dtype = _host_dtype(a)
    a = np.asarray(a, dtype)
    b = np.asarray(b, dtype)
    n = a.shape[0]
    blk = panel * math.lcm(R, C)
    npad = -(-n // blk) * blk
    if npad >= 2 ** 24:
        raise ValueError(
            f"npad={npad} >= 2^24: global row indices would no longer be "
            f"exact in the tournament's float index column")
    ap = np.zeros((npad, npad), dtype)
    ap[:n, :n] = a
    ap[np.arange(n, npad), np.arange(n, npad)] = 1.0
    bp = np.zeros((npad,), dtype)
    bp[:n] = b
    rperm = _block_cyclic_perm_2d(npad, R, panel)
    cperm = _block_cyclic_perm_2d(npad, C, panel)
    a_c = jax.device_put(ap[rperm][:, cperm],
                         NamedSharding(mesh, P(rax, cax)))
    b_c = jax.device_put(bp, NamedSharding(mesh, P(None)))
    return (a_c, b_c, n, npad, panel)


def factor_dist_blocked2d(staged, mesh: jax.sharding.Mesh) -> DistBlocked2DLU:
    from gauss_tpu import obs

    a_c, _, n, npad, panel = staged
    fac_fn = _build_factor_2d(mesh, npad, panel, str(a_c.dtype))
    obs.record_collective_budget("gauss_dist_blocked2d", fac_fn, a_c,
                                 n=n, npad=npad, panel=panel,
                                 nblocks=npad // panel,
                                 mesh_shape=list(mesh.devices.shape))
    # Fleet hooks (see gauss_dist.solve_dist_staged): heartbeat + optional
    # collective watchdog deadline for supervised workers; compiled out of
    # the unsupervised path at solver-build time.
    if _fleet.active() or _watchdog.enabled():
        _fleet.beat(phase="dist_factor_solve", engine="gauss_dist_blocked2d",
                    n=n)
        a_fac, perm, linvs, uinvs, min_piv = _watchdog.guarded_device(
            lambda: fac_fn(a_c), site="dist.gauss_dist_blocked2d.factor")
    else:
        a_fac, perm, linvs, uinvs, min_piv = fac_fn(a_c)
    return DistBlocked2DLU(a_fac, perm, linvs, uinvs, min_piv, n, npad,
                           panel, mesh)


def lu_solve_dist_blocked2d(fac: DistBlocked2DLU, r) -> jax.Array:
    """Solve A d = r against an existing 2-D distributed factorization."""
    mesh = fac.mesh
    dtype = np.dtype(str(fac.a_fac.dtype))
    rpad = np.zeros(fac.npad, dtype)
    rpad[:fac.n] = np.asarray(r, dtype)
    r_dev = jax.device_put(rpad, NamedSharding(mesh, P(None)))
    solver = _build_solver_2d(mesh, fac.npad, fac.panel, str(fac.a_fac.dtype))
    return solver(fac.a_fac, fac.perm, fac.linvs, fac.uinvs, r_dev)[:fac.n]


def factor_solve_dist_blocked2d_staged(staged, mesh: jax.sharding.Mesh):
    """Factor + solve a staged system; returns (x, DistBlocked2DLU) — the
    single plumbing point for both the staged solve and the refined entry
    (mirrors the 1-D engine's factor_solve_dist_blocked_staged)."""
    a_c, b_c, n, npad, panel = staged
    fac = factor_dist_blocked2d(staged, mesh)
    solver = _build_solver_2d(mesh, npad, panel, str(a_c.dtype))
    return solver(fac.a_fac, fac.perm, fac.linvs, fac.uinvs, b_c)[:n], fac


def solve_dist_blocked2d_staged(staged, mesh: jax.sharding.Mesh) -> jax.Array:
    return factor_solve_dist_blocked2d_staged(staged, mesh)[0]


def _check_not_singular(fac: DistBlocked2DLU) -> None:
    """Raise on a zero tournament pivot (ADVICE r3: on an all-zero candidate
    column the tournament argmax can elect a finished row and the swap would
    silently corrupt the factor — min_piv == 0 is the witness; surfacing it
    matches the reference's singular-matrix abort,
    gauss_internal_input.c:95-98). One scalar D2H fetch; the staged/timed
    entry points stay unchecked so timed spans never host-sync."""
    if float(np.min(np.asarray(fac.min_piv))) == 0.0:
        raise np.linalg.LinAlgError(
            "matrix is singular (zero tournament pivot in the 2-D blocked "
            "factorization)")


def gauss_solve_dist_blocked2d(a, b, mesh: jax.sharding.Mesh = None,
                               panel: int | None = None) -> jax.Array:
    """2-D panel-blocked distributed dense solve; x replicated, natural
    order. The pod-scale formulation (see module docstring); the 1-D
    blocked engine remains the small-mesh default. Raises LinAlgError on a
    singular input (zero tournament pivot)."""
    mesh, panel = _resolve_mesh_panel(a, mesh, panel)
    staged = prepare_dist_blocked2d(a, b, mesh, panel=panel)
    x, fac = factor_solve_dist_blocked2d_staged(staged, mesh)
    _check_not_singular(fac)
    return x


def gauss_solve_dist_blocked2d_refined(a, b, mesh: jax.sharding.Mesh = None,
                                       panel: int | None = None,
                                       iters: int = 2,
                                       tol: float = 0.0) -> np.ndarray:
    """2-D distributed solve + host-f64 iterative refinement through the
    SAME factors (tournament pivoting's weaker growth bound makes the
    refined entry point the recommended one for f32 meshes); returns x
    float64."""
    from gauss_tpu.dist.gauss_dist_blocked import host_refine

    mesh, panel = _resolve_mesh_panel(a, mesh, panel)
    a64 = np.asarray(a, np.float64)
    b64 = np.asarray(b, np.float64)
    staged = prepare_dist_blocked2d(a64.astype(np.float32),
                                    b64.astype(np.float32), mesh, panel=panel)
    x0, fac = factor_solve_dist_blocked2d_staged(staged, mesh)
    _check_not_singular(fac)  # a refined f64 answer must not look
    # authoritative when the underlying factor silently lost rank
    return host_refine(a64, b64, x0,
                       lambda r: lu_solve_dist_blocked2d(fac, r), iters, tol)
