"""Row-cyclic distributed Gaussian elimination under shard_map.

TPU-first re-expression of the reference's MPI master–worker engine
(reference OpenMP_and_MPI/gauss_mpi/gauss_internal_input.c:124-255), redesigned
per SURVEY.md §5/§7.4:

- **Row-cyclic ownership** replaces the master's per-step row-block scatter:
  global row g lives permanently on shard ``g % P`` (the load-balance trick of
  the reference's Pthreads cyclic striping, Version-1 gauss_internal_input.c:155,
  now applied across chips) — late pivot steps still touch every shard.
- **Pivot-row broadcast** is one ``psum`` of a masked contribution over ICI,
  replacing MPI_Bcast of the pivot row tail + tagged Isend/Irecv of row blocks
  (the reference ships the full O(n^2) working set over the network per step;
  here only the pivot row and a handful of scalars move).
- **Cross-shard partial pivoting**: local masked argmax, then an ``all_gather``
  of (value, global-index) candidates — the distributed upgrade of the
  reference's rank-0-serial getPivot, which SURVEY.md §7 hard part (d) calls
  out as the latency-critical piece.
- **Barriers are implicit**: SPMD program order replaces MPI_Barrier, and
  there are no shutdown/no-work sentinels (bs=-1 / i=-1) because control flow
  is compiled, not message-driven.

The whole n-step elimination plus distributed back-substitution compiles to a
single XLA program per (n, P, dtype).
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from gauss_tpu.dist.mesh import ROWS_AXIS, make_mesh
from gauss_tpu.resilience import fleet as _fleet
from gauss_tpu.resilience import watchdog as _watchdog
from gauss_tpu.utils import compat


def _cyclic_perm(npad: int, nshards: int) -> np.ndarray:
    """Row permutation placing global row l*P + d at shard d, local slot l.

    perm[d * m + l] = l * P + d; applying ``a[perm]`` then sharding the leading
    axis contiguously gives each shard exactly its cyclic row set.
    """
    m = npad // nshards
    return np.arange(npad).reshape(m, nshards).T.reshape(-1)


@lru_cache(maxsize=32)
def _build_solver(mesh: jax.sharding.Mesh, npad: int, dtype_name: str):
    axis = mesh.axis_names[0]
    nshards = mesh.devices.shape[0]
    m = npad // nshards
    dtype = jnp.dtype(dtype_name)

    def shard_fn(a_loc, b_loc):
        """Runs on every shard: a_loc (m, npad) cyclic rows, b_loc (m,)."""
        d = lax.axis_index(axis)
        local_g = jnp.arange(m) * nshards + d  # global index of each local row

        def elim_step(i, carry):
            A, rhs = carry
            l_i = i // nshards
            d_i = i % nshards
            own_i = d == d_i

            # --- distributed partial pivot (getPivot across shards) ---
            col = A[:, i]
            cand = jnp.where(local_g >= i, jnp.abs(col), -jnp.inf)
            lbest = jnp.argmax(cand)
            vals = lax.all_gather(cand[lbest], axis)          # (P,)
            gidxs = lax.all_gather(local_g[lbest], axis)      # (P,)
            gpiv = gidxs[jnp.argmax(vals)]
            l_p = gpiv // nshards
            d_p = gpiv % nshards
            own_p = d == d_p

            # --- broadcast both swap rows (+rhs) in ONE psum over ICI ---
            zero = jnp.zeros((), dtype)
            contrib = jnp.zeros((2, npad + 1), dtype)
            contrib = contrib.at[0, :npad].set(jnp.where(own_i, A[l_i], zero))
            contrib = contrib.at[0, npad].set(jnp.where(own_i, rhs[l_i], zero))
            contrib = contrib.at[1, :npad].set(jnp.where(own_p, A[l_p], zero))
            contrib = contrib.at[1, npad].set(jnp.where(own_p, rhs[l_p], zero))
            both = lax.psum(contrib, axis)
            row_i, b_i = both[0, :npad], both[0, npad]
            row_p, b_p = both[1, :npad], both[1, npad]

            # Scale the pivot row (reference getPivot semantics, diag pinned).
            piv = row_p[i]
            prow = (row_p / piv).at[i].set(jnp.asarray(1.0, dtype))
            y_i = b_p / piv

            # Swap: slot of gpiv receives old row i; slot of i receives the
            # scaled pivot row. Write order makes gpiv == i come out right.
            A = A.at[l_p].set(jnp.where(own_p, row_i, A[l_p]))
            rhs = rhs.at[l_p].set(jnp.where(own_p, b_i, rhs[l_p]))
            A = A.at[l_i].set(jnp.where(own_i, prow, A[l_i]))
            rhs = rhs.at[l_i].set(jnp.where(own_i, y_i, rhs[l_i]))

            # --- local elimination of owned rows below the pivot ---
            factors = jnp.where(local_g > i, A[:, i], zero)
            A = A - factors[:, None] * prow[None, :]
            rhs = rhs - factors * y_i
            return A, rhs

        A, rhs = lax.fori_loop(0, npad, elim_step, (a_loc, b_loc))

        # --- distributed back-substitution: owner solves, psum broadcasts ---
        def back_step(k, x):
            i = npad - 1 - k
            l_i = i // nshards
            own = d == (i % nshards)
            # Unsolved entries of x are 0 and U has unit diagonal, so the
            # full-row dot picks up exactly the solved suffix.
            acc = A[l_i] @ x
            xi = lax.psum(jnp.where(own, rhs[l_i] - acc, jnp.zeros((), dtype)), axis)
            return x.at[i].set(xi)

        x = lax.fori_loop(0, npad, back_step, jnp.zeros((npad,), dtype))
        return x

    mapped = compat.shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(axis, None), P(axis)),
        out_specs=P(None))
    return jax.jit(mapped)


def _input_dtype(a) -> np.dtype:
    """Canonical dtype of an array-like WITHOUT materializing it (respects
    jax x64 mode)."""
    dt = getattr(a, "dtype", None)
    # np.result_type misreads nested lists as dtype specs; materialize only
    # when there is no dtype attribute (plain lists/tuples — cheap, host-side).
    dt = np.dtype(dt) if dt is not None else np.asarray(a).dtype
    return np.dtype(jax.dtypes.canonicalize_dtype(dt))


def _host_dtype(a) -> np.dtype:
    """Canonical FLOAT dtype for staging a linear system (gauss divides by
    pivots, so integer inputs are promoted to float32)."""
    dt = _input_dtype(a)
    if not np.issubdtype(dt, np.floating):
        dt = np.dtype(jax.dtypes.canonicalize_dtype(np.float32))
    return dt


def _prepare(a, b, mesh: jax.sharding.Mesh):
    """Pad to a shard multiple (identity pad, as in core.blocked), apply the
    cyclic row permutation, and stage the shards DIRECTLY onto the mesh's
    devices.

    All preparation is host-side numpy followed by one explicit
    ``device_put`` per operand with the mesh's NamedSharding — the default
    jax backend is never touched, so a present-but-broken default platform
    (e.g. a tunneled TPU client with a libtpu version mismatch) cannot poison
    a CPU-mesh run. This mirrors the reference's staging model, where rank 0
    holds host memory and ships shards out explicitly
    (OpenMP_and_MPI/gauss_mpi/gauss_internal_input.c:149-155) — except here
    the placement happens once, not per pivot step.
    """
    nshards = mesh.devices.shape[0]
    axis = mesh.axis_names[0]
    dtype = _host_dtype(a)
    a = np.asarray(a, dtype)
    b = np.asarray(b, dtype)
    n = a.shape[0]
    npad = -(-n // nshards) * nshards
    if npad != n:
        ap = np.zeros((npad, npad), dtype)
        ap[:n, :n] = a
        ap[np.arange(n, npad), np.arange(n, npad)] = 1.0
        bp = np.zeros((npad,), dtype)
        bp[:n] = b
    else:
        ap, bp = a, b
    perm = _cyclic_perm(npad, nshards)
    a_c = jax.device_put(ap[perm], NamedSharding(mesh, P(axis, None)))
    b_c = jax.device_put(bp[perm], NamedSharding(mesh, P(axis)))
    return a_c, b_c, npad


def prepare_dist(a, b, mesh: jax.sharding.Mesh):
    """Stage a system onto the mesh (pad + cyclic-permute + shard) and return
    an opaque handle for :func:`solve_dist_staged`.

    Splitting staging from solving lets callers time the solve alone — the
    reference's external flavor likewise times computeGauss only, after
    parse/init (gauss_external_input.c:300-302).
    """
    n = np.shape(a)[0]
    a_c, b_c, npad = _prepare(a, b, mesh)
    return (a_c, b_c, n, npad)


def solve_dist_staged(staged, mesh: jax.sharding.Mesh) -> jax.Array:
    """Solve a system previously staged by :func:`prepare_dist`."""
    from gauss_tpu import obs

    a_c, b_c, n, npad = staged
    solver = _build_solver(mesh, npad, str(a_c.dtype))
    obs.record_collective_budget("gauss_dist", solver, a_c, b_c,
                                 n=n, npad=npad,
                                 shards=int(mesh.devices.size))
    # Fleet hooks: heartbeat at the stage boundary, and — only when a
    # watchdog deadline is configured (a supervised worker) — a deadline
    # around the blocking collective program, so a dead peer becomes a
    # typed WorkerLostError instead of an infinite block. Guarded at
    # solver-build time (one predicate), so the unsupervised hot path
    # carries zero hook plumbing (ROADMAP perf item / ISSUE 6).
    if _fleet.active() or _watchdog.enabled():
        _fleet.beat(phase="dist_factor_solve", engine="gauss_dist", n=n)
        return _watchdog.guarded_device(lambda: solver(a_c, b_c),
                                        site="dist.gauss_dist.solve")[:n]
    return solver(a_c, b_c)[:n]


def gauss_solve_dist(a, b, mesh: jax.sharding.Mesh = None) -> jax.Array:
    """Distributed dense solve; returns x replicated on every shard.

    Columns are never permuted, so x comes back in natural order. The
    reference equivalent is `mpirun -np P gauss_internal_input` with the
    matrix resident only on rank 0; here it is sharded the whole time.
    """
    if mesh is None:
        mesh = make_mesh()
    return solve_dist_staged(prepare_dist(a, b, mesh), mesh)


def eliminate_dist(a, b, mesh: jax.sharding.Mesh = None):
    """Forward elimination + back-substitution, exposed for tests/benchmarks
    (same signature family as core.gauss.gauss_solve)."""
    return gauss_solve_dist(a, b, mesh=mesh)
