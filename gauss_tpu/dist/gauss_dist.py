"""Row-cyclic distributed Gaussian elimination under shard_map.

TPU-first re-expression of the reference's MPI master–worker engine
(reference OpenMP_and_MPI/gauss_mpi/gauss_internal_input.c:124-255), redesigned
per SURVEY.md §5/§7.4:

- **Row-cyclic ownership** replaces the master's per-step row-block scatter:
  global row g lives permanently on shard ``g % P`` (the load-balance trick of
  the reference's Pthreads cyclic striping, Version-1 gauss_internal_input.c:155,
  now applied across chips) — late pivot steps still touch every shard.
- **Pivot-row broadcast** is one ``psum`` of a masked contribution over ICI,
  replacing MPI_Bcast of the pivot row tail + tagged Isend/Irecv of row blocks
  (the reference ships the full O(n^2) working set over the network per step;
  here only the pivot row and a handful of scalars move).
- **Cross-shard partial pivoting**: local masked argmax, then an ``all_gather``
  of (value, global-index) candidates — the distributed upgrade of the
  reference's rank-0-serial getPivot, which SURVEY.md §7 hard part (d) calls
  out as the latency-critical piece.
- **Barriers are implicit**: SPMD program order replaces MPI_Barrier, and
  there are no shutdown/no-work sentinels (bs=-1 / i=-1) because control flow
  is compiled, not message-driven.

The whole n-step elimination plus distributed back-substitution compiles to a
single XLA program per (n, P, dtype).
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from gauss_tpu.dist.mesh import ROWS_AXIS, make_mesh


def _cyclic_perm(npad: int, nshards: int) -> np.ndarray:
    """Row permutation placing global row l*P + d at shard d, local slot l.

    perm[d * m + l] = l * P + d; applying ``a[perm]`` then sharding the leading
    axis contiguously gives each shard exactly its cyclic row set.
    """
    m = npad // nshards
    return np.arange(npad).reshape(m, nshards).T.reshape(-1)


@lru_cache(maxsize=32)
def _build_solver(mesh: jax.sharding.Mesh, npad: int, dtype_name: str):
    axis = mesh.axis_names[0]
    nshards = mesh.devices.shape[0]
    m = npad // nshards
    dtype = jnp.dtype(dtype_name)

    def shard_fn(a_loc, b_loc):
        """Runs on every shard: a_loc (m, npad) cyclic rows, b_loc (m,)."""
        d = lax.axis_index(axis)
        local_g = jnp.arange(m) * nshards + d  # global index of each local row

        def elim_step(i, carry):
            A, rhs = carry
            l_i = i // nshards
            d_i = i % nshards
            own_i = d == d_i

            # --- distributed partial pivot (getPivot across shards) ---
            col = A[:, i]
            cand = jnp.where(local_g >= i, jnp.abs(col), -jnp.inf)
            lbest = jnp.argmax(cand)
            vals = lax.all_gather(cand[lbest], axis)          # (P,)
            gidxs = lax.all_gather(local_g[lbest], axis)      # (P,)
            gpiv = gidxs[jnp.argmax(vals)]
            l_p = gpiv // nshards
            d_p = gpiv % nshards
            own_p = d == d_p

            # --- broadcast both swap rows (+rhs) in ONE psum over ICI ---
            zero = jnp.zeros((), dtype)
            contrib = jnp.zeros((2, npad + 1), dtype)
            contrib = contrib.at[0, :npad].set(jnp.where(own_i, A[l_i], zero))
            contrib = contrib.at[0, npad].set(jnp.where(own_i, rhs[l_i], zero))
            contrib = contrib.at[1, :npad].set(jnp.where(own_p, A[l_p], zero))
            contrib = contrib.at[1, npad].set(jnp.where(own_p, rhs[l_p], zero))
            both = lax.psum(contrib, axis)
            row_i, b_i = both[0, :npad], both[0, npad]
            row_p, b_p = both[1, :npad], both[1, npad]

            # Scale the pivot row (reference getPivot semantics, diag pinned).
            piv = row_p[i]
            prow = (row_p / piv).at[i].set(jnp.asarray(1.0, dtype))
            y_i = b_p / piv

            # Swap: slot of gpiv receives old row i; slot of i receives the
            # scaled pivot row. Write order makes gpiv == i come out right.
            A = A.at[l_p].set(jnp.where(own_p, row_i, A[l_p]))
            rhs = rhs.at[l_p].set(jnp.where(own_p, b_i, rhs[l_p]))
            A = A.at[l_i].set(jnp.where(own_i, prow, A[l_i]))
            rhs = rhs.at[l_i].set(jnp.where(own_i, y_i, rhs[l_i]))

            # --- local elimination of owned rows below the pivot ---
            factors = jnp.where(local_g > i, A[:, i], zero)
            A = A - factors[:, None] * prow[None, :]
            rhs = rhs - factors * y_i
            return A, rhs

        A, rhs = lax.fori_loop(0, npad, elim_step, (a_loc, b_loc))

        # --- distributed back-substitution: owner solves, psum broadcasts ---
        def back_step(k, x):
            i = npad - 1 - k
            l_i = i // nshards
            own = d == (i % nshards)
            # Unsolved entries of x are 0 and U has unit diagonal, so the
            # full-row dot picks up exactly the solved suffix.
            acc = A[l_i] @ x
            xi = lax.psum(jnp.where(own, rhs[l_i] - acc, jnp.zeros((), dtype)), axis)
            return x.at[i].set(xi)

        x = lax.fori_loop(0, npad, back_step, jnp.zeros((npad,), dtype))
        return x

    mapped = jax.shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(axis, None), P(axis)),
        out_specs=P(None))
    return jax.jit(mapped)


def _prepare(a, b, nshards: int):
    """Pad to a shard multiple (identity pad, as in core.blocked) and apply
    the cyclic row permutation to both the matrix and the RHS."""
    a = jnp.asarray(a)
    n = a.shape[0]
    b = jnp.asarray(b, dtype=a.dtype)
    npad = -(-n // nshards) * nshards
    if npad != n:
        ap = jnp.zeros((npad, npad), a.dtype).at[:n, :n].set(a)
        ap = ap.at[jnp.arange(n, npad), jnp.arange(n, npad)].set(
            jnp.asarray(1.0, a.dtype))
        bp = jnp.zeros((npad,), a.dtype).at[:n].set(b)
    else:
        ap, bp = a, b
    perm = _cyclic_perm(npad, nshards)
    return ap[perm], bp[perm], npad


def gauss_solve_dist(a, b, mesh: jax.sharding.Mesh = None) -> jax.Array:
    """Distributed dense solve; returns x replicated on every shard.

    Columns are never permuted, so x comes back in natural order. The
    reference equivalent is `mpirun -np P gauss_internal_input` with the
    matrix resident only on rank 0; here it is sharded the whole time.
    """
    if mesh is None:
        mesh = make_mesh()
    nshards = mesh.devices.shape[0]
    a_c, b_c, npad = _prepare(a, b, nshards)
    n = jnp.asarray(a).shape[0]
    solver = _build_solver(mesh, npad, str(a_c.dtype))
    x = solver(a_c, b_c)
    return x[:n]


def eliminate_dist(a, b, mesh: jax.sharding.Mesh = None):
    """Forward elimination + back-substitution, exposed for tests/benchmarks
    (same signature family as core.gauss.gauss_solve)."""
    return gauss_solve_dist(a, b, mesh=mesh)
