"""Device-mesh construction helpers."""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np

ROWS_AXIS = "rows"


def make_mesh(n_shards: Optional[int] = None, axis: str = ROWS_AXIS,
              devices: Optional[Sequence] = None) -> jax.sharding.Mesh:
    """A 1-D mesh over the first ``n_shards`` devices (default: all).

    The reference pins its distributed size with ``mpirun -np N`` and a
    hostfile (OpenMP_and_MPI/README.txt:39-48); here the mesh is the cluster
    and the axis name is the address space collectives run over.
    """
    devs = list(devices if devices is not None else jax.devices())
    if n_shards is not None:
        if n_shards > len(devs):
            raise ValueError(f"requested {n_shards} shards but only "
                             f"{len(devs)} devices are visible")
        devs = devs[:n_shards]
    return jax.sharding.Mesh(np.array(devs), (axis,))


def make_mesh_2d(rows: int, cols: int, axes=("rows", "cols"),
                 devices: Optional[Sequence] = None) -> jax.sharding.Mesh:
    """A rows x cols 2-D mesh (for the 2-D-sharded gauss / matmul variants)."""
    devs = list(devices if devices is not None else jax.devices())
    if rows * cols > len(devs):
        raise ValueError(f"requested {rows}x{cols} mesh but only "
                         f"{len(devs)} devices are visible")
    grid = np.array(devs[: rows * cols]).reshape(rows, cols)
    return jax.sharding.Mesh(grid, axes)


def squarest_factors(n: int) -> tuple[int, int]:
    """Factor n into the squarest (rows, cols) grid with rows >= cols."""
    import math

    cols = max(d for d in range(1, int(math.isqrt(n)) + 1) if n % d == 0)
    return n // cols, cols


def make_mesh_2d_auto(n_devices: Optional[int] = None,
                      devices: Optional[Sequence] = None) -> jax.sharding.Mesh:
    """A 2-D mesh over n_devices (default: all visible), squarest grid."""
    devs = list(devices if devices is not None else jax.devices())
    total = n_devices if n_devices is not None else len(devs)
    rows, cols = squarest_factors(total)
    return make_mesh_2d(rows, cols, devices=devs)


BATCH_AXIS = "batch"


def lane_slices(devices: Optional[Sequence] = None,
                width: int = 1) -> list:
    """Partition the visible devices into contiguous ``width``-device
    slices — the mesh-serving placement (gauss_tpu.serve.lanes): one async
    dispatch lane per slice. ``width=1`` is one lane per device (the
    common case); a wider slice gives one lane a sub-mesh that GSPMD
    shards the BATCH axis of oversized bucket executables over (see
    :func:`lane_mesh`). Tail devices that do not fill a whole slice are
    left unused rather than forming a ragged lane."""
    devs = list(devices if devices is not None else jax.devices())
    width = max(1, int(width))
    if width > len(devs):
        raise ValueError(f"lane width {width} exceeds the {len(devs)} "
                         f"visible devices")
    return [tuple(devs[i:i + width])
            for i in range(0, len(devs) - width + 1, width)]


def lane_mesh(devices: Sequence, axis: str = BATCH_AXIS) -> jax.sharding.Mesh:
    """A 1-D mesh over one lane's device slice, axis-named for batch
    sharding: the serve layer device_puts its (B, n, n) operand stacks
    with ``NamedSharding(lane_mesh(devs), P("batch"))`` and jit/GSPMD
    partitions the vmapped factor+solve across the slice — the SNIPPETS
    [2] pattern (sharding is data placement; application code unchanged)."""
    return jax.sharding.Mesh(np.array(list(devices)), (axis,))
