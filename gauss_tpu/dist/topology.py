"""Meshfile: declarative device-mesh configuration (reference C10 analog).

The reference pins its distributed runs with an MPI hostfile naming six
cluster nodes plus ``mpirun -np N -hostfile hosts`` (reference
OpenMP_and_MPI/gauss_mpi/hosts:1-6, OpenMP_and_MPI/README.txt:39-48). The TPU
equivalent of "which machines, how many ranks" is "which mesh axes, how many
devices per axis" — captured in a meshfile::

    # comments and blank lines ignored
    axis rows 4
    axis cols 2

Axes are laid out over the visible devices in declaration order (row-major).
A single axis gives a 1-D mesh; two axes give the 2-D meshes the 2-D-sharded
engines use. Device count must not exceed the visible pool, mirroring
mpirun's rank check.
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence, Tuple

import jax
import numpy as np


def parse_meshfile(text: str) -> List[Tuple[str, int]]:
    axes: List[Tuple[str, int]] = []
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.split("#", 1)[0].strip()
        if not line:
            continue
        parts = line.split()
        if len(parts) != 3 or parts[0] != "axis":
            raise ValueError(f"meshfile line {lineno}: expected 'axis NAME SIZE', "
                             f"got {raw.rstrip()!r}")
        name, size_s = parts[1], parts[2]
        try:
            size = int(size_s)
        except ValueError:
            raise ValueError(f"meshfile line {lineno}: size {size_s!r} is not an int")
        if size <= 0:
            raise ValueError(f"meshfile line {lineno}: axis size must be positive")
        if any(n == name for n, _ in axes):
            raise ValueError(f"meshfile line {lineno}: duplicate axis {name!r}")
        axes.append((name, size))
    if not axes:
        raise ValueError("meshfile defines no axes")
    return axes


def load_meshfile(path: os.PathLike, devices: Optional[Sequence] = None
                  ) -> jax.sharding.Mesh:
    """Build a Mesh from a meshfile over the visible (or given) devices."""
    with open(path) as f:
        axes = parse_meshfile(f.read())
    devs = list(devices if devices is not None else jax.devices())
    total = int(np.prod([s for _, s in axes]))
    if total > len(devs):
        raise ValueError(f"meshfile requests {total} devices "
                         f"({'x'.join(str(s) for _, s in axes)}) but only "
                         f"{len(devs)} are visible")
    grid = np.array(devs[:total]).reshape([s for _, s in axes])
    return jax.sharding.Mesh(grid, tuple(n for n, _ in axes))
