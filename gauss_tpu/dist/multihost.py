"""Multi-host bootstrap: the mpirun + hostfile axis, re-expressed for JAX.

The reference scales past one machine with MPI: an ssh-key bootstrap, a
hostfile naming the nodes, and ``mpirun -np N -hostfile hosts`` starting one
rank per slot (reference OpenMP_and_MPI/README.txt:39-48,
OpenMP_and_MPI/gauss_mpi/hosts:1-6). Ranks then talk through
MPI_Bcast/Isend/Irecv over TCP.

The JAX equivalent is SPMD over a *global* device pool: every host runs the
same program, calls :func:`initialize` once (the MPI_Init analog — a gRPC
coordination service replaces the ssh/hostfile plumbing), and afterwards
``jax.devices()`` spans all hosts. The distributed engines in this package
(dist.gauss_dist / gauss_dist2d / matmul_dist) need no changes: they build
their mesh over the global pool, XLA partitions the one program, and the
pivot-row broadcast rides ICI within a slice and DCN across slices — there
is no per-step host messaging to port, which is precisely the reference
MPI engine's documented bottleneck (SURVEY.md §3.3).

Launch parity table:

    mpirun -np N -hostfile hosts ./gauss -s 8192
        == on each host:
    python -m gauss_tpu.cli.gauss_internal -s 8192 --backend tpu-dist \
        --coordinator host0:8476 --num-processes N --process-id <i>

On Cloud TPU pods the three coordinates are discovered from the metadata
server and plain ``initialize()`` (no arguments) suffices; the explicit
flags exist for manual clusters and for CPU-backend rehearsal, which
tests/test_multihost.py exercises with two real localhost processes.
"""

from __future__ import annotations

import os
from typing import Optional

from gauss_tpu.resilience import inject as _inject

# None until initialize() succeeds, then the (coordinator, num_processes,
# process_id) topology it was called with (for the idempotence check).
_INITIALIZED = None


def initialize(coordinator: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """MPI_Init analog: join this process into the global JAX runtime.

    Arguments fall back to the standard environment variables
    (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID), then to
    JAX's own cluster auto-detection (TPU pod metadata, SLURM, ...).
    Idempotent for an identical topology (a repeated identical call is a
    no-op, like MPI_Initialized-guarded MPI_Init); raises on
    re-initialization with DIFFERENT topology, which jax.distributed cannot
    honor within one process.
    """
    global _INITIALIZED
    import jax

    coordinator = coordinator or os.environ.get("JAX_COORDINATOR_ADDRESS")
    if num_processes is None and "JAX_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and "JAX_PROCESS_ID" in os.environ:
        process_id = int(os.environ["JAX_PROCESS_ID"])
    requested = (coordinator, num_processes, process_id)
    if _INITIALIZED is not None:
        if requested == _INITIALIZED:
            return
        raise RuntimeError(
            f"multihost.initialize() already called with topology "
            f"{_INITIALIZED}; cannot re-initialize as {requested}")
    if _inject.enabled():
        # Hook point "dist.multihost.straggler": a worker that shows up
        # late to the rendezvous (the plan's ``param`` is the delay in
        # seconds) — the gRPC coordination service, like mpirun, must
        # either absorb the skew or fail the launch loudly.
        _inject.maybe_delay("dist.multihost.straggler")
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)
    _INITIALIZED = requested
    if _inject.enabled():
        # Hook point "dist.multihost.worker": kill THIS worker right after
        # it joined (kind="kill" is a real os._exit — the preempted-VM
        # stand-in) or stall it forever (kind="stall" sleeps until an
        # external kill — the hung-not-dead worker whose lease goes stale
        # while its process lives). Surviving ranks must surface a
        # collective failure or a watchdog timeout, never a silent wrong
        # answer. Workers inherit the plan through the GAUSS_FAULTS
        # environment variable.
        _inject.maybe_kill("dist.multihost.worker")


def is_multihost() -> bool:
    import jax

    return jax.process_count() > 1


def process_banner() -> str:
    """One-line rank banner, the analog of the reference's per-rank prints
    (gauss_mpi/gauss_internal_input.c:319-327)."""
    import jax

    return (f"process {jax.process_index()}/{jax.process_count()}: "
            f"{jax.local_device_count()} local / "
            f"{jax.device_count()} global devices")


def maybe_initialize_from_args(args) -> bool:
    """CLI hook: initialize when any multihost flag/env coordinate is set.

    Returns True when running multihost. Drivers call this before touching
    any device so the global pool is established first (jax.distributed must
    initialize before the backend)."""
    explicit = any(getattr(args, k, None) is not None
                   for k in ("coordinator", "num_processes", "process_id"))
    env = any(k in os.environ for k in
              ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
               "JAX_PROCESS_ID"))
    if not (explicit or env):
        return False
    initialize(getattr(args, "coordinator", None),
               getattr(args, "num_processes", None),
               getattr(args, "process_id", None))
    return True


def resolve_metrics_stream(metrics_out, coordinator=None, process_id=None):
    """Per-process telemetry coordinates for a (possibly) multihost launch:
    returns ``(stream_path, run_id)`` for ``obs.run``.

    The reference's MPI engine interleaves every rank's prints on rank 0's
    terminal; the JSONL analog must NOT share one file — two processes
    appending concurrently interleave partial lines. Instead each process
    writes ``<base>.p<process_id><ext>`` and all of them stamp ONE shared
    run id, so ``python -m gauss_tpu.obs.aggregate base.p*.jsonl`` merges
    the streams back into a single run.

    The shared id comes from GAUSS_OBS_RUN_ID when the launcher exported
    one, else it is derived deterministically from the coordination address
    (identical on every process of a launch; ephemeral coordinator ports
    make it unique per launch — a launcher reusing a fixed port should
    export GAUSS_OBS_RUN_ID instead). Pure host-side string work: callable
    before jax.distributed.initialize, never touches a backend.

    Single-process runs (no coordinates anywhere) pass through unchanged:
    ``(metrics_out, None)``.
    """
    coordinator = coordinator or os.environ.get("JAX_COORDINATOR_ADDRESS")
    if process_id is None and "JAX_PROCESS_ID" in os.environ:
        process_id = int(os.environ["JAX_PROCESS_ID"])
    run_id = os.environ.get("GAUSS_OBS_RUN_ID")
    if coordinator is None and process_id is None:
        return metrics_out, run_id
    if run_id is None and coordinator is not None:
        import hashlib

        run_id = hashlib.sha1(
            f"multihost:{coordinator}".encode()).hexdigest()[:12]
    if metrics_out and process_id is not None:
        root, ext = os.path.splitext(os.fspath(metrics_out))
        metrics_out = f"{root}.p{process_id}{ext}"
    return metrics_out, run_id


def add_multihost_args(parser) -> None:
    """Attach the three launch coordinates to a CLI parser (mpirun parity)."""
    g = parser.add_argument_group(
        "multihost", "multi-process launch coordinates (the mpirun "
        "-np/-hostfile analog; omit on TPU pods for auto-detection)")
    g.add_argument("--coordinator", default=None, metavar="HOST:PORT",
                   help="coordination service address (process 0's)")
    g.add_argument("--num-processes", type=int, default=None)
    g.add_argument("--process-id", type=int, default=None)
