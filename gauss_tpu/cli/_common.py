"""Shared CLI machinery: backend dispatch and reference-parity timing spans.

Backend taxonomy (maps the reference's 12-binary grid onto one flag):

    tpu           blocked MXU factorization, f32 + iterative refinement
                  (the headline engine; reference CUDA/OpenMP analog)
    tpu-unblocked pure-JAX rank-1 fori_loop elimination (reference sequential
                  semantics on device; oracle path)
    tpu-rowelim   Pallas row-elimination kernel engine (the BASELINE.json
                  north-star kernel; subtractElim analog), batched form —
                  k pivot steps per launch, rank-k MXU update
    tpu-rowelim-step  the same engine one pivot step per launch (the
                  reference's exact algorithmic shape; HBM-bound, didactic)
    tpu-dist      row-cyclic shard_map over the device mesh (reference MPI
                  gauss_mpi analog, per-pivot-step protocol); -t selects the
                  shard count
    tpu-dist2d    2-D block-cyclic shard_map (ScaLAPACK layout; BASELINE
                  config 5); -t selects the total device count, factored
                  into the squarest R x C grid
    tpu-dist-blocked  panel-blocked distributed factorization (collectives
                  per panel, local MXU trailing GEMMs — the formulation
                  that scales; dist.gauss_dist_blocked); -t as tpu-dist
    tpu-dist-blocked2d  2-D panel-blocked factorization (tournament
                  pivoting, per-chip strip traffic O(n^2/R + n^2/C) — the
                  pod-scale shape; dist.gauss_dist_blocked2d); -t as
                  tpu-dist2d
    seq|omp|threads|forkjoin|tiled  native C++ host engines (reference CPU
                  baselines: sequential, OpenMP C4, persistent-pool C3,
                  fork-join-per-step C1, cache-tiled C2)

Timing semantics follow the reference per flavor (SURVEY.md §1 table): the
internal flavor times init + elimination (gauss_internal_input.c:278-290), the
external flavor times elimination only (gauss_external_input.c:300-302). For
gauss device backends the system is staged to the device (f32 cast + H2D)
*before* the span opens — the reference's timed regions likewise begin with
the matrix already resident in the memory attached to the compute — and the
span is bounded by a host fetch of the solution vector. Matmul keeps H2D
inside the span, matching CUDA's cudaMalloc/Memcpy-inclusive timing
(cuda_matmul.cu:135-167; see cli/matmul.py). JIT compilation is excluded via
a warmup run at the same shape; the reference's binaries are likewise compiled
ahead of the timed region.
"""

from __future__ import annotations

import contextlib

import numpy as np

from gauss_tpu import obs
from gauss_tpu.utils.timing import timed_fetch


@contextlib.contextmanager
def metrics_run(args, tool: str):
    """The drivers' ``obs.run`` wrapper, multihost-aware: on a multi-process
    launch each process writes its OWN JSONL stream (``<base>.pN<ext>``) and
    all processes stamp one shared run id, so ``obs.aggregate`` can merge
    them back into a single run with per-process lanes (see
    :func:`gauss_tpu.dist.multihost.resolve_metrics_stream`). Single-process
    runs behave exactly as before. Yields ``(recorder, stream_path)`` —
    print the PATH from the yield, not ``args.metrics_out``, so the banner
    names the file that actually exists."""
    from gauss_tpu.dist import multihost

    path, run_id = multihost.resolve_metrics_stream(
        getattr(args, "metrics_out", None),
        coordinator=getattr(args, "coordinator", None),
        process_id=getattr(args, "process_id", None))
    with obs.run(metrics_out=path, run_id=run_id, tool=tool) as rec:
        yield rec, path

GAUSS_BACKENDS = ("tpu", "tpu-unblocked", "tpu-rowelim", "tpu-rowelim-step",
                  "tpu-dist", "tpu-dist2d", "tpu-dist-blocked",
                  "tpu-dist-blocked2d", "seq", "omp", "threads", "forkjoin",
                  "tiled")
MATMUL_BACKENDS = ("tpu", "tpu-pallas", "tpu-pallas-v1", "tpu-dist", "seq", "omp")

# Backends that implement the reference internal flavor's swap-on-zero
# pivot policy (gauss_internal_input.c:75-121). Every other engine pivots
# partially (max-|column|, the external flavor's policy,
# gauss_external_input.c:125-150) — upgraded to the default everywhere per
# SURVEY.md §7 hard part (c).
FIRST_NONZERO_BACKENDS = ("tpu-unblocked",)

# Minimum size for the tpu backend's on-device ds refinement route (see
# _solve_tpu_blocked): below it the chain's extra dispatch/fetch round
# trips dominate anything it saves over host-refined-with-early-exit.
DS_ROUTE_MIN_N = 512


def resolve_pivoting(pivoting: str | None, backend: str) -> str:
    """Resolve the pivot policy for a backend; never silently ignore a flag.

    ``None`` (the CLI default) resolves to the reference-faithful policy the
    backend actually implements: first_nonzero on FIRST_NONZERO_BACKENDS,
    partial everywhere else. An EXPLICIT first_nonzero request on a
    partial-only backend prints a notice and runs partial — partial pivoting
    subsumes swap-on-zero (it never divides by zero when swap-on-zero
    wouldn't, and the solution is identical up to roundoff), so honoring the
    spirit of the request while stating the substitution beats either
    silence (VERDICT r3 missing #3) or a hard error.
    """
    if pivoting is None:
        return ("first_nonzero" if backend in FIRST_NONZERO_BACKENDS
                else "partial")
    if pivoting == "first_nonzero" and backend not in FIRST_NONZERO_BACKENDS:
        import sys

        print(f"Note: backend '{backend}' always uses partial pivoting "
              f"(max-|column|); --pivoting first_nonzero is honored by: "
              f"{', '.join(FIRST_NONZERO_BACKENDS)}.", file=sys.stderr)
        return "partial"
    return pivoting


def _stage(*arrays):
    """Upload f32 casts to the default device; returns them ready (blocked).

    Deliberately uncommitted (jnp.asarray, not device_put): the warmup calls
    compile with uncommitted operands, and a committed operand would change
    the jit cache key and force a recompile inside the timed span.
    """
    import jax
    import jax.numpy as jnp

    from gauss_tpu.utils.timing import fetch_staged

    with obs.span("host_staging"):
        staged = [jnp.asarray(a, jnp.float32) for a in arrays]
        jax.block_until_ready(staged)
        # block_until_ready can return before tunneled uploads finish; bound
        # each staged buffer with a scalar fetch so the H2D cannot bill to
        # the caller's timed span (see timing.fetch_staged).
        fetch_staged(*staged)
    return staged


def _solve_tpu_blocked(a64, b64, nthreads, refine_iters, panel, refine_tol):
    from gauss_tpu.core import blocked

    n = len(b64)
    if refine_iters > 2 and n >= DS_ROUTE_MIN_N:
        # Host-driven refinement pays a tunnel round trip per iteration
        # (f64 residual on host, correction solve on device); past a couple
        # of iterations the on-device double-single chain wins outright —
        # VERDICT r3 weak #5: saylr4 at ~8 host iterations ran 8.5x slower
        # than the native sequential engine; measured round 4: saylr4
        # 5.94 -> 0.21 s host-span. The ds chain runs the whole budget on
        # device (extra iterations are O(n^2) VPU work, no round trips);
        # refine_tol does not apply on this path (no host residual to
        # test — the fixed budget subsumes it, see DS_REFINE_STEPS). Below
        # n=512 the ds chain's extra dispatch/fetch round trips dominate
        # anything it saves (matrix_10 measured 0.11 s host-refined vs
        # 1.6 s ds) and the tol-early-exit host path stays the route.
        from gauss_tpu.core import dsfloat

        import jax

        a64c = np.asarray(a64, np.float64)
        b64c = np.asarray(b64, np.float64)
        eye = np.eye(n)
        # jit warmup at shape — BLOCKED on: the TPU executes enqueued
        # programs in order, so an un-fetched warmup would still be running
        # when the timed span below opens and would be billed to it.
        with obs.compile_span("tpu_ds_warmup", n=n):
            jax.block_until_ready(
                dsfloat.solve_once_ds(_stage(eye)[0], dsfloat.to_ds(eye.T),
                                      dsfloat.to_ds(np.zeros(n)), panel,
                                      iters=refine_iters))

        from gauss_tpu.utils.timing import fetch_staged

        with obs.span("host_staging_ds"):
            a_dev = _stage(a64c)[0]
            at_ds = jax.block_until_ready(dsfloat.to_ds(a64c.T))
            b_ds = jax.block_until_ready(dsfloat.to_ds(b64c))
            # The ds operand pair is ~2.5 GB over a ~21 MB/s tunnel; without
            # the completion fetches the in-flight upload bills to the timed
            # span below (measured 86-100 s around a 0.4 s solve).
            fetch_staged(at_ds, b_ds)
        holder = {}

        def _solve_ds():
            x_ds, fac = dsfloat.solve_once_ds(a_dev, at_ds, b_ds, panel,
                                              iters=refine_iters)
            holder["fac"] = fac
            return dsfloat.ds_to_f64(x_ds)

        elapsed, x = timed_fetch(_solve_ds, warmup=0, reps=1)
        with obs.span("health_monitors"):
            obs.record_solve_health(a=a64c, x=x, b=b64c,
                                    factors=holder.get("fac"), n=n,
                                    backend="tpu[ds]")
        return x, elapsed

    # Warm up compile at the target shape through solve_refined itself: the
    # jit cache keys on the call-site kwarg signature, so warming the inner
    # functions directly with a different kwarg set would still recompile
    # (measured: +1.7 s) inside the timed span. The warmup passes STAGED
    # a_dev/b_dev exactly like the timed call below — a caller-staged
    # operand selects the NON-donating factorization (solve_refined only
    # donates operands it created itself), and warming the donating twin
    # would leave the timed route cold.
    with obs.compile_span("tpu_blocked_warmup", n=n):
        w_a, w_b = np.eye(n), np.zeros(n)
        blocked.solve_refined(w_a, w_b, panel=panel, iters=refine_iters,
                              a_dev=_stage(w_a)[0], b_dev=_stage(w_b)[0])

    a_dev, b_dev = _stage(a64, b64)
    if obs.active() is not None:
        # FLOPs/bytes accounting for the factorization the solve runs
        # (lowering-level estimate — no second backend compile).
        with obs.span("cost_analysis"):
            obs.record_cost("lu_factor", blocked.resolve_factor(n, "auto"),
                            a_dev, panel=panel, allow_compile=False)
    # Return only x from the span: fetching the factors too would time the
    # D2H of the whole 16 MB factor matrix, not the solve. The factors stay
    # device-resident in the holder for the health monitors below.
    holder = {}

    def _solve():
        x, fac = blocked.solve_refined(a64, b64, panel=panel,
                                       iters=refine_iters, a_dev=a_dev,
                                       b_dev=b_dev, tol=refine_tol)
        holder["fac"] = fac
        return x

    elapsed, x = timed_fetch(_solve, warmup=0, reps=1)
    with obs.span("health_monitors"):
        obs.record_solve_health(a=a64, x=x, b=b64, factors=holder.get("fac"),
                                n=n, backend="tpu")
    return x, elapsed


def _solve_tpu_unblocked(a64, b64, pivoting):
    import jax.numpy as jnp

    from gauss_tpu.core.gauss import gauss_solve

    n = len(b64)
    # Warmup at shape with identity to exclude compile time.
    np.asarray(gauss_solve(jnp.eye(n, dtype=jnp.float32),
                           jnp.zeros(n, dtype=jnp.float32), pivoting=pivoting))
    a_dev, b_dev = _stage(a64, b64)
    elapsed, x = timed_fetch(
        lambda: gauss_solve(a_dev, b_dev, pivoting=pivoting),
        warmup=0, reps=1)
    return np.asarray(x, np.float64), elapsed


def _solve_dist_generic(a64, b64, prepare_fn, solve_fn):
    """Shared distributed-engine timing protocol: warm up the jit cache with
    a staged identity (same cache key as the timed call), free the warmup
    shards, stage the real system OUTSIDE the timed span (like _stage for
    the single-chip engines), then time solve+fetch alone."""
    n = len(b64)
    with obs.compile_span("dist_warmup", n=n):
        warm = prepare_fn(np.eye(n, dtype=np.float32),
                          np.zeros(n, dtype=np.float32))
        np.asarray(solve_fn(warm))
    del warm  # free the warmup shards before staging the real system
    with obs.span("host_staging_dist"):
        staged = prepare_fn(a64.astype(np.float32), b64.astype(np.float32))
    elapsed, x = timed_fetch(lambda: solve_fn(staged), warmup=0, reps=1)
    return np.asarray(x, np.float64), elapsed


def _dist_device_count(nthreads: int) -> int:
    import jax

    ndev = len(jax.devices())
    return max(1, min(nthreads or ndev, ndev))


def _solve_tpu_dist(a64, b64, nthreads):
    from gauss_tpu.dist import gauss_dist

    mesh = gauss_dist.make_mesh(_dist_device_count(nthreads))
    return _solve_dist_generic(
        a64, b64,
        lambda a, b: gauss_dist.prepare_dist(a, b, mesh),
        lambda staged: gauss_dist.solve_dist_staged(staged, mesh))


def _solve_tpu_dist2d(a64, b64, nthreads):
    from gauss_tpu.dist import gauss_dist2d
    from gauss_tpu.dist.mesh import make_mesh_2d_auto

    mesh = make_mesh_2d_auto(_dist_device_count(nthreads))
    return _solve_dist_generic(
        a64, b64,
        lambda a, b: gauss_dist2d.prepare_dist2d(a, b, mesh),
        lambda staged: gauss_dist2d.solve_dist2d_staged(staged, mesh))


def _solve_tpu_dist_blocked(a64, b64, nthreads):
    from gauss_tpu.dist import gauss_dist_blocked as gdb

    mesh = gdb.make_mesh(_dist_device_count(nthreads))
    return _solve_dist_generic(
        a64, b64,
        lambda a, b: gdb.prepare_dist_blocked(a, b, mesh),
        lambda staged: gdb.solve_dist_blocked_staged(staged, mesh))


def _solve_tpu_dist_blocked2d(a64, b64, nthreads):
    from gauss_tpu.dist import gauss_dist_blocked2d as g2d
    from gauss_tpu.dist.mesh import make_mesh_2d_auto

    mesh = make_mesh_2d_auto(_dist_device_count(nthreads))
    return _solve_dist_generic(
        a64, b64,
        lambda a, b: g2d.prepare_dist_blocked2d(a, b, mesh),
        lambda staged: g2d.solve_dist_blocked2d_staged(staged, mesh))


def _solve_tpu_rowelim(a64, b64, batched: bool = True):
    import jax.numpy as jnp

    from gauss_tpu.kernels import rowelim_pallas

    solve = (rowelim_pallas.gauss_solve_rowelim_batched if batched
             else rowelim_pallas.gauss_solve_rowelim)
    n = len(b64)
    np.asarray(solve(jnp.eye(n, dtype=jnp.float32),
                     jnp.zeros(n, dtype=jnp.float32)))  # warmup
    a_dev, b_dev = _stage(a64, b64)
    elapsed, x = timed_fetch(lambda: solve(a_dev, b_dev), warmup=0, reps=1)
    return np.asarray(x, np.float64), elapsed


def _solve_native(a64, b64, backend, nthreads):
    from gauss_tpu import native

    elapsed, x = timed_fetch(
        native.gauss_solve, a64, b64, engine=backend, nthreads=nthreads,
        warmup=0, reps=1)
    return x, elapsed


def solve_with_backend(a64: np.ndarray, b64: np.ndarray, backend: str,
                       nthreads: int = 0, pivoting: str | None = None,
                       refine_iters: int = 8, panel: int | None = None,
                       refine_tol: float = 1e-5):
    """Dispatch a solve; returns (x_float64, elapsed_seconds).

    ``pivoting``: None resolves per backend (see :func:`resolve_pivoting`);
    an explicit first_nonzero on a partial-only backend prints a notice.
    ``refine_iters``/``refine_tol``: the tpu backend has two refinement
    routes. With ``refine_iters <= 2`` — or ``n < DS_ROUTE_MIN_N``, where
    the on-device chain's extra round trips cost more than they save — it
    refines host-side (f64 residual per iteration, one tunnel round trip
    each) and ``refine_tol`` stops it early once
    ``||Ax-b|| <= refine_tol * min(1, ||b||)``. With a larger budget at or
    above the gate it runs the whole chain ON DEVICE with double-single
    residuals
    (core.dsfloat) — no round trips, so the full ``refine_iters`` budget
    always runs and ``refine_tol`` does not apply there: the tol's purpose
    (skipping costly host iterations) is moot when an extra iteration is
    O(n^2) VPU work inside the same program. The default budget of 8
    covers the worst real matrix (saylr4, effective condition ~1e6,
    contraction ~0.15/step — 2 host iterations were not enough, VERDICT r1
    weak #3; 8 HOST iterations made saylr4 8.5x slower than the native CPU
    engine, VERDICT r3 weak #5 — hence the on-device route).
    """
    pivoting = resolve_pivoting(pivoting, backend)
    if backend == "tpu":
        x, elapsed = _solve_tpu_blocked(a64, b64, nthreads, refine_iters,
                                        panel, refine_tol)
    elif backend == "tpu-unblocked":
        x, elapsed = _solve_tpu_unblocked(a64, b64, pivoting)
    elif backend == "tpu-dist":
        x, elapsed = _solve_tpu_dist(a64, b64, nthreads)
    elif backend == "tpu-dist2d":
        x, elapsed = _solve_tpu_dist2d(a64, b64, nthreads)
    elif backend == "tpu-dist-blocked":
        x, elapsed = _solve_tpu_dist_blocked(a64, b64, nthreads)
    elif backend == "tpu-dist-blocked2d":
        x, elapsed = _solve_tpu_dist_blocked2d(a64, b64, nthreads)
    elif backend == "tpu-rowelim":
        x, elapsed = _solve_tpu_rowelim(a64, b64)
    elif backend == "tpu-rowelim-step":
        x, elapsed = _solve_tpu_rowelim(a64, b64, batched=False)
    elif backend in ("seq", "omp", "threads", "forkjoin", "tiled"):
        x, elapsed = _solve_native(a64, b64, backend, nthreads)
    else:
        raise ValueError(
            f"unknown backend {backend!r}; options: {GAUSS_BACKENDS}")
    # Telemetry: the solve span (externally measured by each backend's
    # protocol above) and, for backends whose path did not already record
    # factor-level monitors, the generic solution-health event.
    obs.record_span("computeGauss", elapsed, backend=backend)
    if backend != "tpu" and obs.active() is not None:
        with obs.span("health_monitors"):
            obs.record_solve_health(a=a64, x=x, b=b64, backend=backend)
    return x, elapsed
