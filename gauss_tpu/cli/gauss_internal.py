"""Internal-input gauss driver: synthetic benchmark system, self-timed.

Reference surface (Pthreads/Version-1/gauss_internal_input.c:230-298):
``./gauss_internal_input -s <n> -t <threads> [-h]``, defaults n=2048 / 32
threads, prints ``Application time: %f Secs`` over init + elimination. The
compile-time ``#define VERIFY`` gate becomes the runtime ``--verify`` flag
(SURVEY.md §4 implication), and ``--backend`` selects the execution engine.
Invalid -s/-t values fall back to the defaults with a notice, matching the
reference's forgiving getopt loop (gauss_internal_input.c:243-268).
"""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np

from gauss_tpu.cli import _common
from gauss_tpu.io import synthetic
from gauss_tpu.verify import checks

DEFAULT_N = 2048  # reference NSIZE (gauss_internal_input.c:16)
DEFAULT_THREADS = 32  # reference task_num (gauss_internal_input.c:25)


def positive_int_or_default(value: str, default: int, what: str) -> int:
    try:
        v = int(value)
        if v > 0:
            return v
    except ValueError:
        pass
    print(f"Invalid {what} '{value}'; using default {default}.")
    return default


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="gauss_internal",
        description="Gaussian elimination on the synthetic benchmark system "
                    "(TPU-native port of the reference's *_internal_input programs).")
    p.add_argument("-s", metavar="N", default=str(DEFAULT_N),
                   help=f"matrix dimension (default {DEFAULT_N})")
    p.add_argument("-t", metavar="T", default=str(DEFAULT_THREADS),
                   help=f"threads / shards, backend-dependent (default {DEFAULT_THREADS})")
    p.add_argument("--backend", choices=_common.GAUSS_BACKENDS, default="tpu")
    p.add_argument("--pivoting", choices=("partial", "first_nonzero"),
                   default=None,
                   help="pivot policy; default: first_nonzero (the reference "
                        "internal flavor's swap-on-zero) on backends that "
                        "implement it, partial elsewhere — explicitly "
                        "requesting first_nonzero on a partial-only backend "
                        "prints a notice and runs partial")
    p.add_argument("--verify", action="store_true",
                   help="check the closed-form solution pattern and residual "
                        "(the reference's compile-time VERIFY, now a flag)")
    p.add_argument("--refine", type=int, default=2, metavar="K",
                   help="iterative-refinement budget for the f32 tpu "
                        "backend; K <= 2 (or n < "
                        f"{_common.DS_ROUTE_MIN_N}) refines host-side with "
                        "early exit at --refine-tol, larger budgets run "
                        "fully on device with double-single residuals")
    p.add_argument("--refine-tol", type=float, default=1e-5, metavar="TOL",
                   help="host-side refinement only: stop once "
                        "||Ax-b|| <= TOL*min(1, ||b||); 0 always runs "
                        "exactly --refine steps (default 1e-5)")
    p.add_argument("--panel", type=int, default=None,
                   help="panel width for the blocked tpu backend "
                        "(default: auto — VMEM-aware)")
    p.add_argument("--trace", "--trace-dir", dest="trace", metavar="DIR",
                   default=None,
                   help="capture a jax.profiler device trace into DIR "
                        "(the gprof analog; view in TensorBoard/Perfetto)")
    p.add_argument("--metrics-out", metavar="PATH", default=None,
                   help="append this run's telemetry (spans, numerical "
                        "health, compile/memory accounting) as JSONL to "
                        "PATH; render with `python -m "
                        "gauss_tpu.obs.summarize PATH`")
    p.add_argument("--profile", action="store_true",
                   help="print a gprof-style per-phase wall-clock table")
    p.add_argument("--phase-profile", action="store_true",
                   help="tpu backend only: additionally run the "
                        "phase-instrumented blocked factorization (panel "
                        "factor / pivot apply / trailing update spans, one "
                        "device dispatch per phase) and print its table")
    from gauss_tpu.dist.multihost import add_multihost_args

    add_multihost_args(p)
    return p


def _run(args) -> int:
    from gauss_tpu import obs

    with obs.span("setup_env"):
        from gauss_tpu.utils.env import honor_jax_platforms

        honor_jax_platforms()  # explicit JAX_PLATFORMS beats the image's pin
        from gauss_tpu.dist import multihost

        if multihost.maybe_initialize_from_args(args):
            print(multihost.process_banner())
    n = positive_int_or_default(args.s, DEFAULT_N, "matrix size")
    t = positive_int_or_default(args.t, DEFAULT_THREADS, "thread count")
    obs.emit("config", tool="gauss_internal", n=n, threads=t,
             backend=args.backend)

    print(f"Computing Gaussian elimination: size {n} x {n}, "
          f"backend {args.backend}, threads/shards {t}")

    # Timed region = init + elimination, matching the internal flavor
    # (gauss_internal_input.c:278-284). Init is the synthetic fill; device
    # backends stage the system to the device before their span opens
    # (see _common's module docstring for the timing semantics).
    from gauss_tpu.utils import profiling

    pt = profiling.PhaseTimer()
    with pt.phase("initMatrix"):
        a = synthetic.internal_matrix(n)
        b = synthetic.internal_rhs(n)
    init_elapsed = pt.seconds["initMatrix"]

    t0 = time.perf_counter()
    try:
        with profiling.trace(args.trace):
            x, solve_elapsed = _common.solve_with_backend(
                a, b, args.backend, nthreads=t, pivoting=args.pivoting,
                refine_iters=args.refine, panel=args.panel,
                refine_tol=args.refine_tol)
    except np.linalg.LinAlgError:
        # Native engines raise on a zero pivot; the reference's abort
        # message (gauss_internal_input.c:96).
        print("The matrix is singular")
        return 1
    # solve_with_backend's span excludes the JIT warmup; attribute the rest
    # of the wrapper time to compilation so the profile matches the printed
    # Application time instead of blaming compile time on the compute phase.
    # (computeGauss and the warmup are already recorded as obs spans inside
    # solve_with_backend, so neither is re-emitted here.)
    pt.seconds["computeGauss"] = solve_elapsed
    pt.seconds["jit compile+warmup"] = max(
        0.0, time.perf_counter() - t0 - solve_elapsed)

    print(f"Application time: {init_elapsed + solve_elapsed:f} Secs")
    obs.emit("reported_time", name="Application time",
             seconds=init_elapsed + solve_elapsed)
    if args.profile:
        print(pt.report())
    if args.phase_profile and args.backend == "tpu":
        # The solver-phase profile: re-factor with one device dispatch per
        # phase (diagnostic path — core.blocked.lu_factor_blocked_phased),
        # spans recorded on the run and the table printed like --profile.
        import jax.numpy as jnp

        from gauss_tpu.core import blocked

        with obs.span("phase_profile"):
            ppt = profiling.PhaseTimer()
            blocked.lu_factor_blocked_phased(
                jnp.asarray(a, jnp.float32), panel=args.panel, timer=ppt)
        print("Solver phase profile (instrumented re-factorization):")
        print(ppt.report())
    elif args.phase_profile:
        print(f"Note: --phase-profile applies to the tpu backend only "
              f"(got '{args.backend}')", file=sys.stderr)
    if args.trace:
        print(f"Device trace written to {args.trace}")

    if args.verify:
        with obs.span("verify"):
            ok = checks.internal_pattern_ok(x, atol=1e-4)
            res = checks.residual_norm(a, x, b)
        print(f"Verification: solution pattern (-0.5, 0...0, 0.5) "
              f"{'OK' if ok else 'FAILED'}")
        print(f"Residual ||Ax-b||: {res:e}")
        if not ok or not np.isfinite(res):
            return 1
    return 0


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    with _common.metrics_run(args, "gauss_internal") as (rec, stream):
        rc = _run(args)
    if stream:
        print(f"Metrics: run {rec.run_id} appended to {stream}")
    return rc


if __name__ == "__main__":
    sys.exit(main())
