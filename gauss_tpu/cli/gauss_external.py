"""External-input gauss driver: .dat file, manufactured-solution oracle.

Reference surface (Pthreads/Version-1/gauss_external_input.c:280-318):
``./gauss_external_input <matrixfile> [threads]`` — parse + densify the
coordinate file, manufacture the RHS from the preset solution X__[i] = i+1,
time the elimination only, back-substitute, print::

    Time: %f seconds
    Error: %e

where Error is the max relative error vs X__ (always-on verification,
gauss_external_input.c:304-315).
"""

from __future__ import annotations

import argparse
import sys

import numpy as np

from gauss_tpu.cli import _common
from gauss_tpu.io import datfile, synthetic
from gauss_tpu.verify import checks


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="gauss_external",
        description="Gaussian elimination on a .dat coordinate-format matrix "
                    "(TPU-native port of the reference's *_external_input programs).")
    p.add_argument("matrixfile", help="path to the .dat matrix file")
    p.add_argument("threads", nargs="?", type=int, default=0,
                   help="threads / shards (backend-dependent; default: auto)")
    p.add_argument("--backend", choices=_common.GAUSS_BACKENDS, default="tpu")
    p.add_argument("--refine", type=int, default=2, metavar="K",
                   help="iterative-refinement budget for the tpu backend; "
                        "K <= 2 (or n < "
                        f"{_common.DS_ROUTE_MIN_N}) refines host-side with "
                        "early exit at --refine-tol, larger budgets run "
                        "fully on device with double-single residuals")
    p.add_argument("--refine-tol", type=float, default=1e-5, metavar="TOL",
                   help="host-side refinement only: stop once "
                        "||Ax-b|| <= TOL*min(1, ||b||); 0 always runs "
                        "exactly --refine steps")
    p.add_argument("--panel", type=int, default=None,
                   help="panel width for the blocked tpu backend "
                        "(default: auto — VMEM-aware)")
    p.add_argument("--trace", "--trace-dir", dest="trace", metavar="DIR",
                   default=None,
                   help="capture a jax.profiler device trace into DIR")
    p.add_argument("--metrics-out", metavar="PATH", default=None,
                   help="append this run's telemetry (spans, numerical "
                        "health, compile/memory accounting) as JSONL to "
                        "PATH; render with `python -m "
                        "gauss_tpu.obs.summarize PATH`")
    p.add_argument("--debug", action="store_true",
                   help="print parse and pivot diagnostics (the reference's "
                        "compile-time DEBUG define, gauss_external_input.c:17, "
                        "as a runtime flag)")
    from gauss_tpu.dist.multihost import add_multihost_args

    add_multihost_args(p)
    return p


def _run(args) -> int:
    from gauss_tpu import obs

    with obs.span("setup_env"):
        from gauss_tpu.utils.env import honor_jax_platforms

        honor_jax_platforms()  # explicit JAX_PLATFORMS beats the image's pin
        from gauss_tpu.dist import multihost

        if multihost.maybe_initialize_from_args(args):
            print(multihost.process_banner())
    try:
        with obs.span("parse_dat"):
            if args.debug:
                n_hdr, rows, cols, vals = datfile.read_dat(args.matrixfile)
                if len(vals):
                    stats = (f"coord range rows [{rows.min()},{rows.max()}] "
                             f"cols [{cols.min()},{cols.max()}], |value| in "
                             f"[{abs(vals).min():.3e},{abs(vals).max():.3e}]")
                else:
                    stats = "no nonzeros (zero matrix)"
                print(f"DEBUG: parsed header n={n_hdr}, nnz={len(vals)}, "
                      f"{stats}")
                a = datfile.densify(n_hdr, rows, cols, vals)
            else:
                a = datfile.read_dat_dense(args.matrixfile)
    except (OSError, ValueError) as e:
        print(f"gauss_external: cannot read '{args.matrixfile}': {e}", file=sys.stderr)
        return 1
    n = a.shape[0]
    with obs.span("manufacture_rhs"):
        x_true = synthetic.manufactured_solution(n)
        b = synthetic.manufactured_rhs(a, x_true)
    obs.emit("config", tool="gauss_external", n=n, backend=args.backend,
             matrixfile=str(args.matrixfile))

    print(f"Matrix {args.matrixfile}: {n} x {n}, backend {args.backend}")

    # Timed region = elimination only (gauss_external_input.c:300-302); the
    # solve span includes back-substitution, which is O(n^2) noise against it.
    from gauss_tpu.utils import profiling

    try:
        with profiling.trace(args.trace):
            x, elapsed = _common.solve_with_backend(
                a, b, args.backend, nthreads=args.threads,
                pivoting="partial", refine_iters=args.refine, panel=args.panel,
                refine_tol=args.refine_tol)
    except np.linalg.LinAlgError:
        # Native engines raise on a zero pivot; the reference's abort
        # message (gauss_external_input.c:137 prints to stderr).
        print("The matrix is singular", file=sys.stderr)
        return 1

    if args.debug and args.backend == "tpu":
        # Pivot diagnostics (the reference's DEBUG pivot logs print the
        # chosen row per step): an explicit blocked-LU analysis pass —
        # costs one extra factorization, only for the exact backend whose
        # solver is this factorization, and only on process 0 under
        # multihost. min |pivot| reads the real U diagonal (first n
        # entries), not min_abs_pivot, which the identity padding clamps
        # to <= 1 when n is not a panel multiple.
        import jax

        if jax.process_index() == 0:
            from gauss_tpu.core.blocked import resolve_factor

            fac = resolve_factor(n, "auto")(
                np.asarray(a, np.float32), panel=args.panel)
            perm = np.asarray(fac.perm)[:n]
            moved = int((perm != np.arange(n)).sum())
            pivots = np.abs(np.diagonal(np.asarray(fac.m)))[:n]
            print(f"DEBUG: partial pivoting moved {moved}/{n} rows; "
                  f"min |pivot| = {pivots.min():.6e}")

    print(f"Time: {elapsed:f} seconds")
    obs.emit("reported_time", name="Time", seconds=elapsed)
    with obs.span("verify"):
        err = checks.max_rel_error(x, x_true)
    obs.emit("health", backend=args.backend, max_rel_error=err)
    print(f"Error: {err:e}")
    if not np.isfinite(err):
        # Device engines signal a zero pivot through a NaN solution
        # (min_abs_pivot == 0 inside jit; SURVEY.md §2 C12 error paths).
        # A solution that overflowed f32 without NaN is a range problem,
        # not singularity — do not misdiagnose it.
        if np.isnan(np.asarray(x, np.float64)).any():
            print("The matrix is singular", file=sys.stderr)
        else:
            print("Solve overflowed float32 range (matrix scaling problem, "
                  "not singularity)", file=sys.stderr)
        return 1
    return 0


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    with _common.metrics_run(args, "gauss_external") as (rec, stream):
        rc = _run(args)
    if stream:
        print(f"Metrics: run {rec.run_id} appended to {stream}")
    return rc


if __name__ == "__main__":
    sys.exit(main())
