"""CLI drivers reproducing the reference's program surfaces (SURVEY.md §1 L3).

- ``python -m gauss_tpu.cli.gauss_internal -s N -t T``   (internal-input flavor)
- ``python -m gauss_tpu.cli.gauss_external FILE [T]``    (external-input flavor)
- ``python -m gauss_tpu.cli.matmul N``                   (cuda_matmul flavor)
- ``python -m gauss_tpu.cli.matrix_gen N``               (generator tool)

Each driver adds ``--backend`` to select the execution engine — the pluggable
axis the reference encodes as 12 separate binaries.
"""
