"""Matrix generator CLI: emits the synthetic matrix in .dat format to stdout.

Reference surface (matrices_dense/matrix_gen.cc + Makefile): ``./matrix_gen <n>``.
Dispatches to the native C++ tool when built (identical output); otherwise
falls back to the Python writer.
"""

from __future__ import annotations

import argparse
import subprocess
import sys

from gauss_tpu.io import datfile, synthetic


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="matrix_gen",
        description="Emit the synthetic benchmark matrix in .dat coordinate format.")
    p.add_argument("n", type=int, help="matrix dimension")
    p.add_argument("--python", action="store_true",
                   help="force the Python writer (skip the native tool)")
    args = p.parse_args(argv)
    if args.n <= 0:
        print("matrix_gen: n must be positive", file=sys.stderr)
        return 1

    if not args.python:
        try:
            from gauss_tpu import native

            rc = subprocess.run([native.matrix_gen_path(), str(args.n)],
                                stdout=sys.stdout)
            return rc.returncode
        except Exception:
            pass  # fall back to Python below

    # Values are small integers; the .17g format prints them exactly.
    datfile.write_dat(sys.stdout, synthetic.generator_matrix(args.n))
    return 0


if __name__ == "__main__":
    sys.exit(main())
