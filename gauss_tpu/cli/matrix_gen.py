"""Matrix generator CLI: emits a synthetic matrix in .dat format to stdout.

Reference surface (matrices_dense/matrix_gen.cc + Makefile): ``./matrix_gen <n>``.
Dispatches to the native C++ tool when built (identical output); otherwise
falls back to the Python writer.

``--structure`` extends the reference surface with the structure classes the
router (:mod:`gauss_tpu.structure`) recognizes — ``spd``, ``banded:<b>``,
``blockdiag:<k>``, ``dense``, ``sparse:<nnz_per_row>`` — in the SAME
reference-compatible ``.dat`` coordinate format (sparse classes drop exact
zeros, which is exactly what a coordinate format is for), so datasets,
serving loadgen mixes, and the chaos campaign can exercise the structured
engines end to end. The ``sparse`` mode emits its coordinates DIRECTLY
(io.synthetic.sparse_coords -> write_dat): no n x n buffer exists at any
point, so ``gauss-matrix-gen 1000000 --structure sparse:8`` is an O(nnz)
operation end to end.
"""

from __future__ import annotations

import argparse
import subprocess
import sys

from gauss_tpu.io import datfile, synthetic


def structured_matrix(n: int, structure: str):
    """Build the operand for a ``--structure`` spec; returns
    ``(matrix, drop_zeros)`` where ``matrix`` is a dense ndarray for the
    dense-backed classes and a ``(rows, cols, vals)`` coordinate triple for
    ``sparse`` (which is never densified). Specs: ``spd``, ``banded:<b>``
    (default b=1), ``blockdiag:<k>`` (block size, default max(1, n // 8)),
    ``dense``, ``sparse:<nnz_per_row>`` (default 8)."""
    kind, _, arg = structure.partition(":")
    if kind == "spd":
        return synthetic.spd_matrix(n), False
    if kind == "banded":
        return synthetic.banded_matrix(n, int(arg) if arg else 1), True
    if kind == "blockdiag":
        block = int(arg) if arg else max(1, n // 8)
        return synthetic.blockdiag_matrix(n, block), True
    if kind == "dense":
        return synthetic.dense_matrix(n), False
    if kind == "sparse":
        nnz_per_row = int(arg) if arg else 8
        if nnz_per_row < 1:
            raise ValueError(
                f"sparse:<nnz_per_row> must be >= 1, got {nnz_per_row}")
        return synthetic.sparse_coords(n, nnz_per_row=nnz_per_row), True
    raise ValueError(
        f"unknown --structure {structure!r}; options: spd, banded:<b>, "
        f"blockdiag:<k>, dense, sparse:<nnz_per_row>")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="matrix_gen",
        description="Emit a synthetic benchmark matrix in .dat coordinate format.")
    p.add_argument("n", type=int, help="matrix dimension")
    p.add_argument("--structure", default=None, metavar="SPEC",
                   help="structured generation mode: spd | banded:<b> | "
                        "blockdiag:<k> | dense (default: the reference "
                        "matrix_gen.cc min-matrix)")
    p.add_argument("--python", action="store_true",
                   help="force the Python writer (skip the native tool)")
    p.add_argument("--metrics-out", metavar="PATH", default=None,
                   help="append generation telemetry as JSONL to PATH")
    args = p.parse_args(argv)
    if args.n <= 0:
        print("matrix_gen: n must be positive", file=sys.stderr)
        return 1
    if args.structure is not None:
        try:
            matrix, drop_zeros = structured_matrix(args.n, args.structure)
        except ValueError as e:
            print(f"matrix_gen: {e}", file=sys.stderr)
            return 1
    else:
        matrix, drop_zeros = None, False

    from gauss_tpu import obs

    with obs.run(metrics_out=args.metrics_out, tool="matrix_gen") as rec:
        obs.emit("config", tool="matrix_gen", n=args.n,
                 structure=args.structure)
        rc = None
        if not args.python and matrix is None:
            # The native C++ tool only knows the reference min-matrix;
            # structured modes always take the Python writer.
            try:
                from gauss_tpu import native

                with obs.span("generate_native"):
                    rc = subprocess.run(
                        [native.matrix_gen_path(), str(args.n)],
                        stdout=sys.stdout).returncode
            except Exception:
                rc = None  # fall back to Python below
        if rc is None:
            # Values are small integers, exact powers of rho, or float64
            # draws; .17g prints them with an exact round trip either way.
            with obs.span("generate_python"):
                if isinstance(matrix, tuple):
                    # The sparse class: coordinates straight to the
                    # writer — no n x n buffer at any n.
                    rows, cols, vals = matrix
                    datfile.write_dat(sys.stdout, n=args.n, rows=rows,
                                      cols=cols, vals=vals)
                else:
                    datfile.write_dat(
                        sys.stdout,
                        matrix if matrix is not None
                        else synthetic.generator_matrix(args.n),
                        drop_zeros=drop_zeros)
            rc = 0
    if args.metrics_out:
        print(f"Metrics: run {rec.run_id} appended to {args.metrics_out}",
              file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main())
