"""Matrix generator CLI: emits the synthetic matrix in .dat format to stdout.

Reference surface (matrices_dense/matrix_gen.cc + Makefile): ``./matrix_gen <n>``.
Dispatches to the native C++ tool when built (identical output); otherwise
falls back to the Python writer.
"""

from __future__ import annotations

import argparse
import subprocess
import sys

from gauss_tpu.io import datfile, synthetic


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="matrix_gen",
        description="Emit the synthetic benchmark matrix in .dat coordinate format.")
    p.add_argument("n", type=int, help="matrix dimension")
    p.add_argument("--python", action="store_true",
                   help="force the Python writer (skip the native tool)")
    p.add_argument("--metrics-out", metavar="PATH", default=None,
                   help="append generation telemetry as JSONL to PATH")
    args = p.parse_args(argv)
    if args.n <= 0:
        print("matrix_gen: n must be positive", file=sys.stderr)
        return 1

    from gauss_tpu import obs

    with obs.run(metrics_out=args.metrics_out, tool="matrix_gen") as rec:
        obs.emit("config", tool="matrix_gen", n=args.n)
        rc = None
        if not args.python:
            try:
                from gauss_tpu import native

                with obs.span("generate_native"):
                    rc = subprocess.run(
                        [native.matrix_gen_path(), str(args.n)],
                        stdout=sys.stdout).returncode
            except Exception:
                rc = None  # fall back to Python below
        if rc is None:
            # Values are small integers; .17g prints them exactly.
            with obs.span("generate_python"):
                datfile.write_dat(sys.stdout,
                                  synthetic.generator_matrix(args.n))
            rc = 0
    if args.metrics_out:
        print(f"Metrics: run {rec.run_id} appended to {args.metrics_out}",
              file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main())
