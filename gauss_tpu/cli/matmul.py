"""Matmul driver: runs the device engine plus CPU baselines back-to-back.

Reference surface (CUDA_and_OpenMP/Version-2/cuda_matmul.cu:104-187):
``./cuda_matmul <nsize>`` — fills A[idx] = idx+1, B[idx] = 1/(idx+1), then
runs GPU, sequential, and OpenMP engines in one invocation, printing each
time. Differences from the reference, deliberate (SURVEY.md §2 C6 defects):

- the epsilon comparator (``verify()``, eps=1e-4) is actually invoked here —
  the reference defines it but never calls it, and silently overwrites C
  between engines;
- each engine writes its own output array, and every engine is compared
  against the float64 truth;
- ``--engines`` selects a subset (the n=2048 sequential baseline takes ~a
  minute, as the reference's own tables show).

Device timing includes H2D/D2H transfer, matching the reference's span
(cuda_matmul.cu:135-167).
"""

from __future__ import annotations

import argparse
import sys

import numpy as np

from gauss_tpu.cli import _common
from gauss_tpu.verify import checks

DEFAULT_N = 1024  # reference default nsize (cuda_matmul.cu:16,105-111)


def _inputs(n: int):
    idx = np.arange(n * n, dtype=np.float64)
    a = (idx + 1.0).reshape(n, n)
    b = (1.0 / (idx + 1.0)).reshape(n, n)
    return a, b


def _tpu_engine_fn(engine: str, precision: str = None):
    """The device matmul callable behind a tpu* engine name.

    ``precision`` None keeps each engine's default — "high" (bf16x3)
    everywhere: the XLA engine via lax.Precision.HIGH, the Pallas kernels
    via the manual in-kernel split scheme (Mosaic rejects HIGH as a dot
    precision, so the kernels build it by hand; kernels.matmul_pallas).
    """
    from functools import partial as _partial

    if engine == "tpu-dist":
        from gauss_tpu.dist.matmul_dist import matmul_dist

        if precision is None:
            return matmul_dist
        return _partial(matmul_dist, precision=precision)
    if engine in ("tpu-pallas", "tpu-pallas-v1"):
        if engine == "tpu-pallas":
            from gauss_tpu.kernels.matmul_pallas import matmul_pallas as mm
        else:
            from gauss_tpu.kernels.matmul_pallas import (
                matmul_pallas_stripe as mm)
        return mm if precision is None else _partial(mm, precision=precision)
    from gauss_tpu.core.matmul import matmul as mm
    return mm if precision is None else _partial(mm, precision=precision)


def _run_tpu(a, b, engine: str, precision: str = None):
    import jax.numpy as jnp

    from gauss_tpu import obs

    mm = _tpu_engine_fn(engine, precision)
    from gauss_tpu.utils.timing import timed_fetch

    with obs.compile_span(f"matmul_warmup:{engine}", n=a.shape[0]):
        np.asarray(mm(jnp.asarray(a, jnp.float32),
                      jnp.asarray(b, jnp.float32)))  # compile
    if obs.active() is not None:
        with obs.span("cost_analysis"):
            obs.record_cost(f"matmul:{engine}", mm,
                            jnp.asarray(a, jnp.float32),
                            jnp.asarray(b, jnp.float32),
                            allow_compile=False)
    elapsed, c = timed_fetch(
        lambda: mm(jnp.asarray(a, jnp.float32), jnp.asarray(b, jnp.float32)),
        warmup=0, reps=1)
    return np.asarray(c, np.float64), elapsed


def _run_native(a, b, engine, nthreads):
    from gauss_tpu import native
    from gauss_tpu.utils.timing import timed_fetch

    elapsed, c = timed_fetch(native.matmul, a, b, engine=engine,
                             nthreads=nthreads, warmup=0, reps=1)
    return c, elapsed


def main(argv=None) -> int:
    from gauss_tpu.utils.env import honor_jax_platforms

    honor_jax_platforms()  # an explicit JAX_PLATFORMS beats the image's pin
    p = argparse.ArgumentParser(
        prog="matmul",
        description="Dense matmul benchmark (TPU-native port of cuda_matmul).")
    p.add_argument("nsize", nargs="?", type=int, default=DEFAULT_N)
    p.add_argument("--engines", default="tpu,seq,omp",
                   help="comma-separated subset of: tpu, tpu-pallas, "
                        "tpu-pallas-v1, tpu-dist, seq, omp")
    p.add_argument("-t", "--threads", type=int, default=0,
                   help="threads for the omp engine (default: all)")
    p.add_argument("--precision", choices=("highest", "high", "default"),
                   default=None,
                   help="MXU precision for device engines (default 'high' "
                        "bf16x3 everywhere; the Pallas kernels implement it "
                        "in-kernel by manual operand splitting)")
    p.add_argument("--trace", "--trace-dir", dest="trace", metavar="DIR",
                   default=None,
                   help="capture a jax.profiler device trace into DIR")
    p.add_argument("--metrics-out", metavar="PATH", default=None,
                   help="append this run's telemetry as JSONL to PATH; "
                        "render with `python -m gauss_tpu.obs.summarize`")
    args = p.parse_args(argv)
    n = args.nsize
    if n <= 0:
        print("matmul: nsize must be positive", file=sys.stderr)
        return 1
    engines = [e.strip() for e in args.engines.split(",") if e.strip()]
    bad = set(engines) - set(_common.MATMUL_BACKENDS)
    if bad:
        print(f"matmul: unknown engines {sorted(bad)}; "
              f"options: {_common.MATMUL_BACKENDS}", file=sys.stderr)
        return 1

    from gauss_tpu import obs
    from gauss_tpu.utils import profiling

    with obs.run(metrics_out=args.metrics_out, tool="matmul") as rec:
        obs.emit("config", tool="matmul", n=n, engines=",".join(engines))
        with obs.span("prepare_inputs"):
            a, b = _inputs(n)
            truth = a @ b  # float64 host truth for the epsilon comparator
            scale = float(np.abs(truth).max())
        labels = {"tpu": "TPU", "tpu-pallas": "TPU-Pallas",
                  "tpu-pallas-v1": "TPU-Pallas-V1",
                  "tpu-dist": "TPU-Dist (sharded)",
                  "seq": "Sequential", "omp": "OpenMP"}

        failed = False
        with profiling.trace(args.trace):
            for engine in engines:
                if engine.startswith("tpu"):
                    c, elapsed = _run_tpu(a, b, engine, args.precision)
                else:
                    c, elapsed = _run_native(a, b, engine, args.threads)
                with obs.span("verify"):
                    ok = checks.elementwise_match(
                        c, truth, epsilon=checks.EPSILON * scale)
                    diff = float(np.max(np.abs(c - truth))) / scale
                obs.record_span(f"matmul:{engine}", elapsed, backend=engine)
                obs.emit("reported_time", name=f"{labels[engine]} time",
                         seconds=elapsed)
                obs.emit("health", backend=engine, max_rel_diff=diff,
                         verified=ok)
                gflops = 2.0 * n ** 3 / elapsed / 1e9
                print(f"{labels[engine]} time: {elapsed:f} seconds "
                      f"({gflops:.1f} GFLOP/s) "
                      f"verify: {'OK' if ok else 'MISMATCH'}")
                failed |= not ok
    if args.metrics_out:
        print(f"Metrics: run {rec.run_id} appended to {args.metrics_out}")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
