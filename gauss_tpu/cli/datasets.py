"""Dataset CLI: regenerate the reference's test-matrix library as .dat files.

Usage: ``python -m gauss_tpu.cli.datasets [names...] [--out DIR] [--list]``.
With no names, writes every registry matrix except the two largest (memplus,
matrix_2000), which are opt-in by name.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from gauss_tpu.io import datasets

_LARGE = ("memplus", "matrix_2000")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="datasets",
        description="Regenerate the reference dataset matrices in .dat format.")
    p.add_argument("names", nargs="*", help="registry names (default: all small)")
    p.add_argument("--out", default="matrices_dense", help="output directory")
    p.add_argument("--list", action="store_true", help="list the registry and exit")
    p.add_argument("--metrics-out", metavar="PATH", default=None,
                   help="append per-dataset write telemetry as JSONL to PATH")
    args = p.parse_args(argv)

    if args.list:
        for name in datasets.dataset_names():
            n, nnz = datasets.REGISTRY[name]
            print(f"{name}: n={n} nnz={nnz}")
        return 0

    names = args.names or [n for n in datasets.dataset_names() if n not in _LARGE]
    bad = [n for n in names if n not in datasets.REGISTRY]
    if bad:
        print(f"datasets: unknown names {bad}; use --list", file=sys.stderr)
        return 1

    from gauss_tpu import obs

    out = Path(args.out)
    out.mkdir(parents=True, exist_ok=True)
    with obs.run(metrics_out=args.metrics_out, tool="datasets") as rec:
        obs.emit("config", tool="datasets", names=",".join(names),
                 out=str(out))
        for name in names:
            path = out / f"{name}.dat"
            with obs.span("write_dataset", dataset=name):
                datasets.write_dataset(name, path)
            n, nnz = datasets.REGISTRY[name]
            obs.counter("datasets_written")
            print(f"wrote {path} (n={n}, nnz={nnz})")
    if args.metrics_out:
        print(f"Metrics: run {rec.run_id} appended to {args.metrics_out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
