"""``gauss-tune`` — the offline sweep that fills the store.

Per (op, n-bucket, dtype, engine) point the runner measures every candidate
config in the declared space (:mod:`gauss_tpu.tune.space`) on a seeded
synthetic system, using the same device-completion timing discipline the
bench stack uses (warmup excluded via ``obs.compile_span``, spans bounded
by ``block_until_ready``), and records the WINNER — plus the seed config's
own time, so every store entry carries its measured improvement and the
``tune_sweep`` summary is regress-ingestable (a later sweep whose winner
is slower than history's is a tuning regression, gated like any other).

Determinism: operands come from the seeded generators
(:mod:`gauss_tpu.io.synthetic`-style diagonally-dominant systems), the
candidate order is the declared order, and timing noise is bounded by
taking the best of ``reps`` repetitions. Early pruning: a candidate whose
FIRST repetition already exceeds ``prune_ratio`` x the best-so-far is
abandoned without spending its remaining reps (the sweep's cost is
dominated by losers — most of the grid — so this is where the time goes).

The sweep never runs inside a serving process: it is offline by design
(compiles dozens of programs); processes CONSULT its output through
:mod:`gauss_tpu.tune.apply`.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

import numpy as np

from gauss_tpu import obs
from gauss_tpu.tune import space as _space
from gauss_tpu.tune import store as _store

DEFAULT_REPS = 3
DEFAULT_PRUNE_RATIO = 1.5


def _seeded_system(n: int, seed: int) -> Tuple[np.ndarray, np.ndarray]:
    """Deterministic well-conditioned dense system (diagonally dominant —
    the same shape the fleet/chaos smokes use, so a sweep measures the
    factorization, not recovery ladders)."""
    rng = np.random.default_rng(seed + n)
    a = rng.standard_normal((n, n))
    a[np.arange(n), np.arange(n)] += float(n)
    return a, rng.standard_normal(n)


def _candidate_grid(op: str, axes: Optional[Dict[str, Iterable]] = None,
                    sweep_all: bool = False) -> List[Dict[str, Any]]:
    """The cross product of candidate values over the op's swept axes,
    seed config first. ``axes`` overrides candidate lists per axis
    (the CLI's ``--axes panel=64,128``); non-default axes join only when
    explicitly overridden or with ``sweep_all``."""
    space = _space.space_for(op)
    names, values = [], []
    for ax in space:
        if axes and ax.name in axes:
            vals = tuple(axes[ax.name])
        elif ax.sweep_default or sweep_all:
            vals = ax.values()
        else:
            continue
        names.append(ax.name)
        values.append(vals)
    grid: List[Dict[str, Any]] = [{}]
    for name, vals in zip(names, values):
        grid = [{**g, name: v} for g in grid for v in vals]
    seeds = {ax.name: ax.seed for ax in space}
    seed_pt = {n: seeds[n] for n in names}
    # Seed first (it is the baseline every candidate is judged against);
    # preserve declared order for the rest, minus the seed duplicate.
    return [seed_pt] + [g for g in grid if g != seed_pt]


def _measure_lu_factor(n: int, dtype: str, params: Dict[str, Any],
                       seed: int, reps: int,
                       prune_s: Optional[float]) -> Optional[float]:
    """Best-of-``reps`` seconds for one blocked factor+solve at ``params``
    (panel/chunk; refine_steps rides through the solve). None = pruned."""
    import jax
    import jax.numpy as jnp

    from gauss_tpu.core import blocked
    from gauss_tpu.utils.timing import timed

    a64, b64 = _seeded_system(n, seed)
    a = jnp.asarray(a64, dtype=jnp.dtype(dtype))
    b = jnp.asarray(b64, dtype=jnp.dtype(dtype))
    panel = params.get("panel")
    chunk = params.get("chunk")
    use_chunked = (chunk is not None and chunk != 1
                   and n > (panel or blocked.DEFAULT_PANEL))

    def run_once():
        if use_chunked:
            fac = blocked.lu_factor_blocked_chunked(a, panel=panel,
                                                    chunk=int(chunk))
        else:
            fac = blocked.lu_factor_blocked(a, panel=panel)
        return blocked.lu_solve(fac, b)

    with obs.compile_span("tune_candidate", op="lu_factor", n=n,
                          **{k: v for k, v in params.items()
                             if v is not None}):
        jax.block_until_ready(run_once())  # compile outside the timing
    best = None
    for r in range(max(1, reps)):
        t, _ = timed(run_once, warmup=0, reps=1)
        best = t if best is None else min(best, t)
        if r == 0 and prune_s is not None and t > prune_s:
            obs.emit("tune_sweep", event="pruned", op="lu_factor", n=n,
                     params=params, first_rep_s=round(t, 6),
                     prune_s=round(prune_s, 6))
            return None
    return best


def _measure_matmul(n: int, dtype: str, params: Dict[str, Any], seed: int,
                    reps: int, prune_s: Optional[float]) -> Optional[float]:
    import jax
    import jax.numpy as jnp

    from gauss_tpu.kernels.matmul_pallas import matmul_pallas
    from gauss_tpu.utils.timing import timed

    rng = np.random.default_rng(seed + n)
    a = jnp.asarray(rng.standard_normal((n, n)), dtype=jnp.dtype(dtype))
    b = jnp.asarray(rng.standard_normal((n, n)), dtype=jnp.dtype(dtype))
    kw = {k: int(v) for k, v in params.items()
          if k in ("bm", "bn", "bk") and v is not None}

    def run_once():
        return matmul_pallas(a, b, **kw)

    with obs.compile_span("tune_candidate", op="matmul", n=n, **kw):
        jax.block_until_ready(run_once())
    best = None
    for r in range(max(1, reps)):
        t, _ = timed(run_once, warmup=0, reps=1)
        best = t if best is None else min(best, t)
        if r == 0 and prune_s is not None and t > prune_s:
            obs.emit("tune_sweep", event="pruned", op="matmul", n=n,
                     params=params, first_rep_s=round(t, 6),
                     prune_s=round(prune_s, 6))
            return None
    return best


def _measure_panel_fused(n: int, dtype: str, params: Dict[str, Any],
                         seed: int, reps: int,
                         prune_s: Optional[float]) -> Optional[float]:
    """Best-of-``reps`` seconds for ONE fused panel+trailing launch
    (kernels.panel_fused_pallas) at the candidate (ct, seg, fseg) tiles —
    the first (tallest) panel step of an (n, n) block, the step whose
    shape dominates the factorization. Interpret-mode on non-TPU
    backends: sweepable anywhere, honest only on real hardware (like the
    panel kernel itself)."""
    import jax
    import jax.numpy as jnp

    from gauss_tpu.core import blocked
    from gauss_tpu.kernels.panel_fused_pallas import \
        panel_trailing_fused_pallas
    from gauss_tpu.utils.timing import timed

    a64, _ = _seeded_system(n, seed)
    a = jnp.asarray(a64, dtype=jnp.dtype(dtype))
    panel = min(blocked.auto_panel(n, np.dtype(dtype).itemsize), n)
    kw = {k: int(v) for k, v in params.items()
          if k in ("ct", "seg", "fseg") and v is not None}

    def run_once():
        return panel_trailing_fused_pallas(a, 0, 0, panel=panel, **kw)[4]

    with obs.compile_span("tune_candidate", op="panel_fused", n=n, **kw):
        jax.block_until_ready(run_once())
    best = None
    for r in range(max(1, reps)):
        t, _ = timed(run_once, warmup=0, reps=1)
        best = t if best is None else min(best, t)
        if r == 0 and prune_s is not None and t > prune_s:
            obs.emit("tune_sweep", event="pruned", op="panel_fused", n=n,
                     params=params, first_rep_s=round(t, 6),
                     prune_s=round(prune_s, 6))
            return None
    return best


def _measure_outofcore(n: int, dtype: str, params: Dict[str, Any],
                       seed: int, reps: int,
                       prune_s: Optional[float]) -> Optional[float]:
    """Best-of-``reps`` seconds for one host-streamed factor+solve
    (gauss_tpu.outofcore) at the candidate (ct, chunk) window — the
    streamed engine's window/group-size axis. The streamed path is
    host-stepped (per-group jits), so the compile span wraps a full first
    solve; timed reps then rerun the cached steps."""
    from gauss_tpu import outofcore
    from gauss_tpu.utils.timing import timed

    a64, b64 = _seeded_system(n, seed)
    ct = params.get("ct")
    chunk = params.get("chunk")
    kw = dict(ct=None if ct is None else int(ct),
              chunk=None if chunk is None else int(chunk), iters=1)

    def run_once():
        return outofcore.solve_outofcore(a64, b64, **kw)

    with obs.compile_span("tune_candidate", op="outofcore", n=n,
                          **{k: v for k, v in params.items()
                             if v is not None}):
        run_once()  # per-group jit compiles land outside the timing
    best = None
    for r in range(max(1, reps)):
        t, _ = timed(run_once, warmup=0, reps=1)
        best = t if best is None else min(best, t)
        if r == 0 and prune_s is not None and t > prune_s:
            obs.emit("tune_sweep", event="pruned", op="outofcore", n=n,
                     params=params, first_rep_s=round(t, 6),
                     prune_s=round(prune_s, 6))
            return None
    return best


#: the most recent converged refine count per (n, dtype-name) measured by
#: _measure_lowered — read back by the concretizer so the store pins the
#: MEASURED minimal budget, not the swept cap.
_LOWERED_USED_STEPS: Dict[Tuple[int, str], int] = {}


def _measure_lowered(n: int, dtype: str, params: Dict[str, Any],
                     seed: int, reps: int,
                     prune_s: Optional[float]) -> Optional[float]:
    """Best-of-``reps`` seconds for one LOWERED solve (core.lowered) at
    the candidate (dtype, refine_steps) pair — the refine-steps-vs-dtype
    axis. A candidate that cannot reach the 1e-4 gate at its budget is
    DISQUALIFIED (recorded like a pruned candidate), so the store can
    only ever pin a converging pair; the converged run's SURFACED
    iteration count (dsfloat.refine_ds) is stashed for the concretizer.
    None = pruned or disqualified."""
    from gauss_tpu.core import lowered
    from gauss_tpu.utils.timing import timed

    a64, b64 = _seeded_system(n, seed)
    ldt = str(params.get("dtype") or "float32")
    steps = params.get("refine_steps")
    steps = int(steps) if steps else None

    def run_once():
        return lowered.solve_lowered(a64, b64, dtype=ldt,
                                     refine_steps=steps)

    try:
        with obs.compile_span("tune_candidate", op="lowered", n=n,
                              dtype=ldt, refine_steps=steps):
            _, _, info = run_once()  # compile outside the timing
    except lowered.PrecisionNotConvergedError as e:
        obs.emit("tune_sweep", event="disqualified", op="lowered", n=n,
                 params=params, rel_residual=float(f"{e.rel_residual:.3e}"))
        return None
    _LOWERED_USED_STEPS[(n, ldt)] = int(info["refine_steps"])
    best = None
    for r in range(max(1, reps)):
        t, _ = timed(run_once, warmup=0, reps=1)
        best = t if best is None else min(best, t)
        if r == 0 and prune_s is not None and t > prune_s:
            obs.emit("tune_sweep", event="pruned", op="lowered", n=n,
                     params=params, first_rep_s=round(t, 6),
                     prune_s=round(prune_s, 6))
            return None
    return best


_MEASURERS = {"lu_factor": _measure_lu_factor, "matmul": _measure_matmul,
              "panel_fused": _measure_panel_fused,
              "lowered": _measure_lowered,
              "outofcore": _measure_outofcore}


def _concrete_lu_factor(n: int, dtype: str,
                        params: Dict[str, Any]) -> Dict[str, Any]:
    """Resolve a winning lu_factor config's auto values to what they
    concretely resolved to DURING the measurement, so every store entry
    pins concrete params (a store entry exists to short-circuit the auto
    heuristics; recording "auto" would pin nothing)."""
    out = dict(params)
    if "panel" in out and out["panel"] is None:
        from gauss_tpu.core import blocked

        out["panel"] = blocked.auto_panel(n, np.dtype(dtype).itemsize)
    if "chunk" in out and out["chunk"] is None:
        out["chunk"] = _space.CHUNK_SEED
    return out


def _concrete_lowered(n: int, dtype: str,
                      params: Dict[str, Any]) -> Dict[str, Any]:
    """Pin the winning lowered pair's refine budget to the MEASURED
    converged iteration count (the refine_ds surfaced count stashed by
    _measure_lowered, plus one step of margin for operands the sweep
    system did not sample) — the store entry then records what the gate
    actually needed, not the swept cap."""
    out = dict(params)
    used = _LOWERED_USED_STEPS.get((n, str(out.get("dtype") or "float32")))
    if used is not None and out.get("refine_steps"):
        out["refine_steps"] = min(int(out["refine_steps"]),
                                  max(1, used + 1))
    return out


_CONCRETIZERS = {"lu_factor": _concrete_lu_factor,
                 "lowered": _concrete_lowered}


def sweep_point(op: str, n: int, dtype: str = "float32",
                engine: str = "blocked", seed: int = 258458,
                reps: int = DEFAULT_REPS,
                prune_ratio: float = DEFAULT_PRUNE_RATIO,
                axes: Optional[Dict[str, Iterable]] = None,
                sweep_all: bool = False) -> Dict[str, Any]:
    """Sweep one (op, n, dtype, engine) point; returns the point record
    (seed/best params + seconds, candidates tried/pruned). The declared
    seed config is always measured fully (it is the fallback the store
    must never be worse than)."""
    measure = _MEASURERS.get(op)
    if measure is None:
        raise ValueError(f"op {op!r} has no sweep measurer; options: "
                         f"{sorted(_MEASURERS)}")
    from gauss_tpu.tune import apply as _apply

    grid = _candidate_grid(op, axes=axes, sweep_all=sweep_all)
    results: List[Tuple[Dict[str, Any], Optional[float]]] = []
    best_s: Optional[float] = None
    with _apply.suspended(), obs.span("tune_sweep_point", op=op, n=n,
                                      dtype=dtype, candidates=len(grid)):
        for i, params in enumerate(grid):
            prune_s = (None if best_s is None or i == 0
                       else prune_ratio * best_s)
            t = measure(n, dtype, params, seed, reps, prune_s)
            results.append((params, t))
            if t is not None and (best_s is None or t < best_s):
                best_s = t
        seed_params, seed_s = results[0]
        best_params, best_sec = min(
            ((p, t) for p, t in results if t is not None),
            key=lambda pt: pt[1])
        concretize = _CONCRETIZERS.get(op)
        if concretize is not None:
            best_params = concretize(n, dtype, best_params)
    point = {
        "op": op, "n": n, "n_bucket": _space.n_bucket(n), "dtype": dtype,
        "engine": engine, "key": _space.config_key(op, n, dtype, engine),
        "seed_params": seed_params,
        "seed_s": round(seed_s, 6) if seed_s is not None else None,
        "best_params": best_params, "best_s": round(best_sec, 6),
        "improvement": (round(seed_s / best_sec, 4)
                        if seed_s and best_sec else None),
        "candidates": len(grid),
        "pruned": sum(1 for _, t in results if t is None),
    }
    obs.emit("tune_sweep", event="point", **point)
    return point


def run_sweep(ops: List[str], ns: List[int], dtype: str = "float32",
              engine: str = "blocked", seed: int = 258458,
              reps: int = DEFAULT_REPS,
              prune_ratio: float = DEFAULT_PRUNE_RATIO,
              axes: Optional[Dict[str, Iterable]] = None,
              sweep_all: bool = False,
              run_id: Optional[str] = None) -> Dict[str, Any]:
    """Sweep the (ops x ns) grid; returns the ``tune_sweep`` summary."""
    t0 = time.monotonic()
    from gauss_tpu.tune import apply as _apply

    # A pre-existing store must not leak into the measurements (the seed
    # baseline would silently become "previously tuned"): the sweep runs
    # with consults suspended — deterministic in the store's content.
    with _apply.suspended():
        points = [sweep_point(op, n, dtype=dtype, engine=engine, seed=seed,
                              reps=reps, prune_ratio=prune_ratio, axes=axes,
                              sweep_all=sweep_all)
                  for op in ops for n in ns]
    return {"kind": "tune_sweep", "ops": ops, "ns": ns, "dtype": dtype,
            "engine": engine, "seed": seed, "reps": reps,
            "prune_ratio": prune_ratio, "points": points,
            "fingerprint": _store.store_fingerprint(),
            "run_id": run_id, "wall_s": round(time.monotonic() - t0, 3)}


def write_store(summary: Dict[str, Any], path,
                keep_seed_winners: bool = True) -> str:
    """Persist a sweep summary's winners as a store at ``path``. An
    existing same-fingerprint store is UPDATED (other points survive); a
    foreign or unusable one is replaced wholesale. ``keep_seed_winners``:
    also record points whose winner IS the seed config — the entry then
    documents "swept, seed confirmed" and pins the auto heuristics to the
    measured value."""
    st: Optional[_store.TuneStore] = None
    if os.path.exists(os.fspath(path)):
        try:
            prev = _store.TuneStore.load(path)
            if _store.fingerprint_matches(prev.fingerprint,
                                          summary["fingerprint"]):
                st = prev
        except _store.TuneStoreError:
            st = None
    if st is None:
        st = _store.TuneStore(fingerprint=summary["fingerprint"])
    for point in summary["points"]:
        if not keep_seed_winners \
                and point["best_params"] == point["seed_params"]:
            continue
        st.put(point["op"], point["n"],
               {k: v for k, v in point["best_params"].items()
                if v is not None},
               dtype=point["dtype"], engine=point["engine"],
               seconds=point["best_s"], seed_seconds=point["seed_s"],
               source=summary.get("run_id"))
    return st.save(path)


def history_records(summary: Dict[str, Any]) -> List[Tuple[str, float, str]]:
    """(metric, value, unit) records a sweep contributes to the regression
    history — both slow-side gated: tuned seconds growing means the hot
    path got slower; win_ratio (tuned/seed) drifting toward 1+ means
    tuning stopped paying."""
    out = []
    for p in summary.get("points", []):
        stem = f"tune:{p['op']}/n{p['n_bucket']}/{p['dtype']}"
        if isinstance(p.get("best_s"), (int, float)) and p["best_s"] > 0:
            out.append((f"{stem}:s_per_solve", p["best_s"], "s"))
        if p.get("seed_s") and p.get("best_s"):
            out.append((f"{stem}:win_ratio",
                        round(p["best_s"] / p["seed_s"], 4), "ratio"))
    return out


def format_summary(summary: Dict[str, Any]) -> str:
    lines = [f"gauss-tune sweep [{summary['dtype']}/{summary['engine']}] "
             f"ops={','.join(summary['ops'])} "
             f"ns={','.join(str(n) for n in summary['ns'])} "
             f"({summary['wall_s']:.1f} s)"]
    for p in summary["points"]:
        imp = (f"{p['improvement']:.2f}x vs seed" if p["improvement"]
               else "no seed time")
        lines.append(
            f"  {p['key']}: best={p['best_params']} "
            f"{p['best_s'] * 1e3:.3f} ms ({imp}; seed={p['seed_params']} "
            f"{(p['seed_s'] or 0) * 1e3:.3f} ms; "
            f"{p['candidates']} candidates, {p['pruned']} pruned)")
    return "\n".join(lines)


def _parse_axes(specs: List[str]) -> Dict[str, List[Any]]:
    """``panel=64,128 chunk=1,2`` -> {"panel": [64, 128], "chunk": [1, 2]}
    (values parse as int, then float, then bare string)."""
    def _val(s: str):
        for cast in (int, float):
            try:
                return cast(s)
            except ValueError:
                continue
        return None if s in ("none", "None", "auto") else s

    out: Dict[str, List[Any]] = {}
    for spec in specs:
        if "=" not in spec:
            raise ValueError(f"bad --axes spec {spec!r} (want name=v1,v2)")
        name, _, vals = spec.partition("=")
        out[name.strip()] = [_val(v) for v in vals.split(",") if v != ""]
    return out


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="gauss-tune",
        description="Offline autotuner: sweep the declared config space "
                    "per (op, n-bucket, dtype, engine) on THIS hardware "
                    "and persist the winners to the tuned store that "
                    "bench, serve warmup, and the fleet consult.")
    p.add_argument("--ops", default="lu_factor",
                   help="comma-separated ops to sweep (default lu_factor; "
                        f"known: {','.join(sorted(_MEASURERS))})")
    p.add_argument("--ns", default="512,2048",
                   help="comma-separated sizes (one store point per "
                        "n-bucket; default 512,2048)")
    p.add_argument("--dtype", default="float32")
    p.add_argument("--engine", default="blocked")
    p.add_argument("--seed", type=int, default=258458)
    p.add_argument("--reps", type=int, default=DEFAULT_REPS,
                   help=f"timed repetitions per candidate (best-of; "
                        f"default {DEFAULT_REPS})")
    p.add_argument("--prune-ratio", type=float, default=DEFAULT_PRUNE_RATIO,
                   help="abandon a candidate whose first rep exceeds this "
                        "x the best-so-far (default "
                        f"{DEFAULT_PRUNE_RATIO})")
    p.add_argument("--axes", nargs="*", default=None, metavar="NAME=V1,V2",
                   help="override candidate values per axis (e.g. "
                        "panel=64,128 chunk=1,2); also admits axes that "
                        "are declared but not swept by default")
    p.add_argument("--sweep-all", action="store_true",
                   help="include non-default axes (refine depth, vmem "
                        "budget) in the grid")
    p.add_argument("--store", default=None, metavar="PATH",
                   help="store file to write (default: "
                        "$GAUSS_TUNE_STORE or ~/.cache/gauss_tpu/"
                        "tune_store.json)")
    p.add_argument("--dry-run", action="store_true",
                   help="sweep and report, write nothing")
    p.add_argument("--compile-cache", default=None, metavar="DIR",
                   help="persistent XLA compile cache for the sweep's own "
                        "compiles (gauss_tpu.tune.compilecache)")
    p.add_argument("--metrics-out", default=None, metavar="PATH")
    p.add_argument("--summary-json", default=None, metavar="PATH",
                   help="write the sweep summary (regress-ingestable: "
                        "kind=tune_sweep)")
    p.add_argument("--history", nargs="?", const="", default=None,
                   metavar="PATH",
                   help="append tuned s_per_solve / win_ratio records to "
                        "the regression history (default "
                        "reports/history.jsonl)")
    p.add_argument("--regress-check", action="store_true",
                   help="gate the sweep against the history baselines "
                        "(exit 1 when out of band)")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from gauss_tpu.utils.env import honor_jax_platforms

    honor_jax_platforms()
    if args.compile_cache:
        from gauss_tpu.tune import compilecache

        compilecache.enable(args.compile_cache, export_env=False)
    ops = [o for o in args.ops.split(",") if o]
    ns = [int(n) for n in args.ns.split(",") if n]
    axes = _parse_axes(args.axes) if args.axes else None

    with obs.run(metrics_out=args.metrics_out, tool="gauss_tune",
                 ops=args.ops, ns=args.ns) as rec:
        summary = run_sweep(ops, ns, dtype=args.dtype, engine=args.engine,
                            seed=args.seed, reps=args.reps,
                            prune_ratio=args.prune_ratio, axes=axes,
                            sweep_all=args.sweep_all, run_id=rec.run_id)
    print(format_summary(summary))

    if not args.dry_run:
        store_path = args.store or _store.default_store_path()
        write_store(summary, store_path)
        print(f"store: {store_path} "
              f"({len(summary['points'])} point(s) recorded)")
        from gauss_tpu.tune import apply as _apply

        _apply.reset_cache()  # this process may consult what it just wrote

    if args.summary_json:
        parent = os.path.dirname(args.summary_json)
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(args.summary_json, "w") as f:
            json.dump(summary, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"summary: {args.summary_json}")

    rc = 0
    from gauss_tpu.obs import regress

    records = [{"metric": m, "value": v, "unit": u,
                "source": f"tune:{summary.get('run_id')}", "kind": "tune"}
               for m, v, u in history_records(summary)]
    if args.regress_check and records:
        history_path = args.history or regress.default_history_path()
        verdicts = regress.check_records(records,
                                         regress.load_history(history_path))
        print(regress.format_verdicts(verdicts))
        if any(v["status"] == "out-of-band" for v in verdicts):
            rc = 1
    if args.history is not None and records and rc == 0:
        history_path = args.history or regress.default_history_path()
        added = regress.append_history(records, history_path)
        print(f"history: {added} record(s) appended to {history_path}")
    return rc


if __name__ == "__main__":
    sys.exit(main())
