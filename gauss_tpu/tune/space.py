"""The tunable parameter space, with the hand constants as seed defaults.

Every number here used to be a frozen constant somewhere else in the tree,
each picked by ONE sweep on ONE machine (the reference repo does the same:
CUDA ``BLOCK_SIZE``, Pthreads ``block_size=16`` cache tiling). This module
is now their single source: the code imports its defaults FROM here, the
tuner sweeps candidate values AROUND them, and the store persists per-
hardware winners — so the seed defaults and the tuner's search space can
never drift apart.

Structure:

- **Seed constants** — the historical hand-picked values, re-exported by
  their original homes (``core.blocked.CHUNK_DEFAULT`` is now this
  module's :data:`CHUNK_SEED`, etc.). Changing a seed here changes the
  code default everywhere, which is the point.
- **Axes** — per operation, the named tunable parameters with their seed
  and the candidate values an offline sweep tries. Candidates are small
  curated sets (the measured-plausible region), not open ranges: the
  sweep's job is picking per-hardware among known-sane configs, not
  exploring configs that are known to OOM or miscompile.

This module is stdlib-only (no jax, no numpy) so it can be imported by
anything, including kernel modules at load time and the CLI before the
platform is pinned.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Tuple

# -- seed constants (single source; original homes re-export) ---------------

#: panels per chunked group (core.blocked.CHUNK_DEFAULT; picked by a single
#: n=8192 sweep on v5e: 4 < 2 < 8 < 16).
CHUNK_SEED = 4

#: Pallas panel-kernel scoped-VMEM budget in bytes (core.blocked
#: .PANEL_VMEM_BUDGET; calibrated from round-5 compile probes on v5e —
#: a different chip generation gets a different usable scoped limit, which
#: is exactly why it is a declared axis).
PANEL_VMEM_BUDGET_SEED = 15_500_000

#: narrow-panel per-row VMEM overhead floor: widths below the narrowest
#: measured rung extrapolate conservatively as ``max(FLOOR, SCALE//panel)``
#: (core.blocked.panel_fits_vmem; ADVICE r5 — the ~1/panel growth seen in
#: the round-4 data).
NARROW_PANEL_OVERHEAD_FLOOR = 220
NARROW_PANEL_OVERHEAD_SCALE = 55_000

#: panel sub-segment width for the Pallas panel kernel
#: (kernels.panel_pallas.DEFAULT_SEG; 64 measured best on v5e).
PANEL_SEG_SEED = 64

#: fused panel+trailing kernel (kernels.panel_fused_pallas): trailing
#: column-tile width. The fused kernel streams the trailing block through
#: VMEM in (h, ct) tiles while the factored panel's multipliers stay
#: resident; ct trades per-tile MXU occupancy against the tile's VMEM
#: slice. Seeded at one 256-column tile (two MXU tiles wide — the same
#: traffic argument as the 512-wide matmul output tiles, halved because
#: the multiplier scratch shares the budget).
FUSED_CT_SEED = 256

#: fused kernel trailing-apply segment width: the rank at which the
#: recorded multiplier rows are applied to each trailing tile (one
#: Neumann-series chain per segment — the deferred-update scheme of
#: kernels.panel_pallas, applied across the whole trailing block).
#: 32 is the deferred form's measured saddle on v5e (panel_pallas
#: defer_seg); the fused kernel inherits it as its seed.
FUSED_FSEG_SEED = 32

#: fused-kernel VMEM working-set model: bytes-per-row multiplier on the
#: column footprint (pipeline-buffered trailing tiles + the aliased
#: transposed panel + the (panel, h) multiplier/pivot scratch pair), plus
#: the per-row bookkeeping overhead shared with the classic panel kernel.
FUSED_WORKSET_TILES = 3   # trailing-tile copies the pipeline keeps live
FUSED_WORKSET_PANELS = 3  # aliased panel block + mult + pt scratch

#: Pallas matmul tile grid (bm, bn, bk)
#: (kernels.matmul_pallas defaults; sweep_mm_tiles r4 on v5e).
MM_TILE_SEED = (512, 512, 1024)

#: row-elimination kernel tile (bm, bn) (kernels.rowelim_pallas defaults).
ROWELIM_TILE_SEED = (256, 256)

#: lowered-precision solve path (core.lowered): the storage/GEMM dtype
#: the factorization runs at and the double-single refinement budget that
#: brings it back to the 1e-4 gate. The dtype SEED is float32 — an
#: untuned checkout keeps today's path exactly; only an offline
#: ``gauss-tune --ops lowered`` sweep that MEASURED a converging cheaper
#: (dtype, refine_steps) pair on this hardware moves the start down the
#: ladder (bfloat16 storage / the bf16x3 split-GEMM middle rung). The
#: refine seed is the dsfloat default (clears saylr4, cond ~1e6);
#: candidates bracket the measured needs of the lowered dtypes (bf16
#: ~4e-3/step contraction wants headroom, bf16x3 ~1e-5 needs almost
#: none). The sweep runner DISQUALIFIES candidates that miss the gate,
#: so the store can only ever pin a converging pair.
LOWERED_DTYPE_SEED = "float32"
LOWERED_REFINE_SEED = 6

#: out-of-core streamed factorization (gauss_tpu.outofcore): trailing
#: tile width (columns per streamed H2D/D2H tile — trades per-tile MXU
#: occupancy and transfer granularity against the device window), panels
#: per streamed group (wider groups amortize the host round-trip per
#: group but grow the device-resident group block), and the fraction of
#: the device budget the streamed working set may claim (declared for
#: operator recalibration, not swept — it encodes the headroom left for
#: XLA's in-update transients).
OUTOFCORE_CT_SEED = 4096
OUTOFCORE_CHUNK_SEED = 16
OUTOFCORE_DEVICE_FRAC_SEED = 0.25

#: sparse Krylov plane (gauss_tpu.sparse; docs/STRUCTURE.md sparse
#: section): GMRES restart length — the resident Krylov basis, i.e. the
#: O(nnz + n*restart) peak-memory bound the acceptance gate asserts —
#: and the block size the block-Jacobi / blocked incomplete (ILU0/IC0)
#: preconditioners partition on.
SPARSE_RESTART_SEED = 32
SPARSE_BLOCK_SEED = 16

#: density at or below which the structure tagger classifies "sparse"
#: (structure.detect.SPARSE_MAX_DENSITY re-exports it). A routing-policy
#: bound, not a timing knob: declared so operators can recalibrate the
#: sparse/dense boundary, never swept by default.
SPARSE_DENSITY_SEED = 1.0 / 32.0

#: host-f64 refinement rounds per batched serve dispatch
#: (serve.admission.ServeConfig.refine_steps).
SERVE_REFINE_SEED = 1

#: bucket ladder growth factor (serve.buckets pads to the power-of-two
#: ladder; declared here so a future sweep can trade padding waste against
#: executable count — growth 2.0 IS the pow2 policy).
BUCKET_GROWTH_SEED = 2.0


def narrow_panel_overhead(panel: int) -> int:
    """Conservative per-row VMEM overhead for unmeasured narrow panel
    widths (single source of the ``max(220, 55000//panel)`` floor)."""
    return max(NARROW_PANEL_OVERHEAD_FLOOR,
               NARROW_PANEL_OVERHEAD_SCALE // max(1, panel))


# -- the declared space ------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Axis:
    """One tunable parameter: its name, hand-picked seed, and the candidate
    values an offline sweep tries (seed always included, tried first)."""

    name: str
    seed: Any
    candidates: Tuple[Any, ...] = ()
    #: swept by default by ``gauss-tune``? Axes that change numerics
    #: (refine depth) or that encode hardware limits (vmem budget) are
    #: declared — so the store can carry operator-set overrides — but only
    #: swept when asked for explicitly.
    sweep_default: bool = True

    def values(self) -> Tuple[Any, ...]:
        vals = [self.seed]
        for c in self.candidates:
            if c not in vals:
                vals.append(c)
        return tuple(vals)


#: op name -> axes. ``None`` seeds mean "auto-resolved by the code"
#: (e.g. panel=None routes through core.blocked.auto_panel); the sweep
#: still tries the concrete candidates and the store records a concrete
#: winner, which then SHORT-CIRCUITS the auto resolution.
SPACES: Dict[str, Tuple[Axis, ...]] = {
    # the blocked LU factorization — the headline hot path
    "lu_factor": (
        Axis("panel", None, (128, 256, 64)),
        Axis("chunk", CHUNK_SEED, (2, 8, 16)),
        Axis("refine_steps", 2, (1, 3), sweep_default=False),
    ),
    # the VMEM-resident panel kernel (TPU-only; CPU sweeps skip it)
    "panel_kernel": (
        Axis("seg", PANEL_SEG_SEED, (32, 128)),
        Axis("vmem_budget", PANEL_VMEM_BUDGET_SEED, (), sweep_default=False),
    ),
    # the fused panel+trailing kernel (kernels.panel_fused_pallas): the
    # trailing tile and apply-segment widths the sweep tries per
    # (n-bucket, dtype, device kind); the budget axis is declared for
    # operator-set per-hardware recalibration, like panel_kernel's.
    "panel_fused": (
        Axis("ct", FUSED_CT_SEED, (128, 512)),
        Axis("fseg", FUSED_FSEG_SEED, (16, 64)),
        Axis("seg", PANEL_SEG_SEED, (32, 128)),
        Axis("vmem_budget", PANEL_VMEM_BUDGET_SEED, (), sweep_default=False),
    ),
    # the Pallas matmul tile grid
    "matmul": (
        Axis("bm", MM_TILE_SEED[0], (256, 1024)),
        Axis("bn", MM_TILE_SEED[1], (256, 1024)),
        Axis("bk", MM_TILE_SEED[2], (512, 2048)),
    ),
    # the mixed-precision solve ladder (core.lowered.solve_lowered_auto):
    # which dtype rung a solve STARTS at and its refinement budget —
    # refine-steps-vs-dtype as one swept pair, per (n-bucket, device).
    # The winner concretizes refine_steps to the MEASURED converged count
    # (dsfloat.refine_ds surfaces it), so the store pins the minimal
    # budget that actually met the gate.
    "lowered": (
        Axis("dtype", LOWERED_DTYPE_SEED, ("bfloat16", "bf16x3")),
        Axis("refine_steps", LOWERED_REFINE_SEED, (2, 4, 8, 12)),
    ),
    # the host-streamed out-of-core engine (gauss_tpu.outofcore): window
    # and group-size per (n-bucket, dtype, device) — consulted by
    # outofcore_window / lu_factor_outofcore exactly like the kernel
    # tiles; the device fraction is declared for operator recalibration.
    "outofcore": (
        Axis("ct", OUTOFCORE_CT_SEED, (2048, 8192)),
        Axis("chunk", OUTOFCORE_CHUNK_SEED, (8, 32)),
        Axis("device_frac", OUTOFCORE_DEVICE_FRAC_SEED, (),
             sweep_default=False),
    ),
    # the sparse Krylov plane (gauss_tpu.sparse): restart length trades
    # convergence per cycle against the resident-basis memory bound;
    # block sizes the incomplete-factor partitions; the density threshold
    # is the declared routing boundary (structure.detect), operator-set
    # only.
    "sparse": (
        Axis("restart", SPARSE_RESTART_SEED, (16, 64)),
        Axis("block", SPARSE_BLOCK_SEED, (8, 32)),
        Axis("density", SPARSE_DENSITY_SEED, (), sweep_default=False),
    ),
    # serve-layer knobs consulted at warmup (bucket growth is declared for
    # operators; the pow2 ladder stays the only implemented policy)
    "serve": (
        Axis("refine_steps", SERVE_REFINE_SEED, (), sweep_default=False),
        Axis("bucket_growth", BUCKET_GROWTH_SEED, (), sweep_default=False),
    ),
}


def space_for(op: str) -> Tuple[Axis, ...]:
    try:
        return SPACES[op]
    except KeyError:
        raise KeyError(f"unknown tunable op {op!r}; options: "
                       f"{sorted(SPACES)}") from None


def seed_params(op: str) -> Dict[str, Any]:
    """The hand-tuned defaults for ``op`` — what runs when no store
    exists, and the reference point every sweep measures against."""
    return {ax.name: ax.seed for ax in space_for(op)}


def n_bucket(n: int) -> int:
    """The size bucket a tuned config is keyed by: the next power of two
    at or above ``n`` (mirrors serve.buckets so a tuned config and the
    serving bucket that consults it agree on the boundary)."""
    b = 1
    while b < max(1, int(n)):
        b <<= 1
    return b


def config_key(op: str, n: int, dtype: str = "float32",
               engine: str = "blocked") -> str:
    """The store key for (op, n-bucket, dtype, engine). Device kind is NOT
    in the key — it lives in the store's environment fingerprint: one
    store file describes one hardware epoch."""
    return f"{op}/n{n_bucket(n)}/{dtype}/{engine}"
