"""The consult path: every entry point asks here for its tuned config.

Contract (the tentpole's integration rule):

- **Zero behavior change without a store.** When no store file exists,
  :func:`param` returns the seed (or the caller's own default) after one
  cached ``os.stat`` — the hot paths pay a dict hit, nothing else.
- **Typed fallback.** A corrupt/stale/foreign store is a
  :class:`~gauss_tpu.tune.store.TuneStoreError` internally; here it
  degrades to seeds with an obs ``tune`` event naming the reason —
  a broken store file must never break a solve.
- **Process-stable.** The store is read ONCE per process (first consult)
  and the resolution is frozen: jitted entry points bake the resolved
  values into compiled programs at trace time, so re-reading a changed
  file mid-process would make the lookup disagree with the executables
  already compiled from it. Tests use :func:`reset_cache`.
- **Observable.** Each distinct (run, key, outcome) consult emits one obs
  ``tune`` event (source=store|seed, reason on fallbacks) plus
  ``tune.store_hits`` / ``tune.store_misses`` counters — the summarizer's
  "tuning" section and the tune-check gate read these.
"""

from __future__ import annotations

import contextlib
import os
import threading
from typing import Any, Dict, Optional, Tuple

from gauss_tpu import obs
from gauss_tpu.tune import space as _space
from gauss_tpu.tune import store as _store

_lock = threading.Lock()
#: (path, store-or-None, reason) — resolved once per process.
_resolved: Optional[Tuple[str, Optional[_store.TuneStore], str]] = None
#: (run_id, key, outcome) tuples already announced, so per-solve consults
#: do not flood a long-running recorder stream.
_announced: set = set()


def reset_cache() -> None:
    """Forget the cached store resolution (tests; or after writing a new
    store in-process, e.g. the tune-check gate)."""
    global _resolved
    with _lock:
        _resolved = None
        _announced.clear()


_suspended = False


@contextlib.contextmanager
def suspended():
    """Temporarily behave as if no store exists. The sweep runner wraps
    its measurements in this so a PRE-EXISTING store can never leak into
    the seed-config baseline it measures candidates against (re-sweeps
    must be deterministic in the store's content)."""
    global _suspended
    prev = _suspended
    _suspended = True
    try:
        yield
    finally:
        _suspended = prev


def _resolve() -> Tuple[str, Optional[_store.TuneStore], str]:
    """(path, usable store or None, reason). Cached for process lifetime —
    with one exception: a store whose fingerprint cannot be judged yet
    because no jax backend is initialized (the current fingerprint is
    missing the fields the store is stamped with) is NOT cached; the next
    consult — by which point the surrounding solve has initialized the
    backend — retries. A confirmed hardware CONFLICT is cached: it cannot
    heal within this process."""
    global _resolved
    with _lock:
        if _resolved is not None:
            return _resolved
        path = _store.default_store_path()
        st: Optional[_store.TuneStore] = None
        cache = True
        if not os.path.exists(path):
            reason = "absent"
        else:
            try:
                st = _store.TuneStore.load(path)
            except _store.TuneStoreError as e:
                st, reason = None, f"store_error: {e}"
            else:
                current = _store.store_fingerprint()
                stamped = st.fingerprint
                conflict = any(k in stamped and k in current
                               and stamped[k] != current[k]
                               for k in _store.FINGERPRINT_KEYS)
                unknown = any(k in stamped and k not in current
                              for k in _store.FINGERPRINT_KEYS)
                if conflict:
                    st, reason = None, "fingerprint_mismatch"
                elif unknown:
                    st, reason = None, "backend_uninitialized"
                    cache = False
                else:
                    reason = "ok"
        resolved = (path, st, reason)
        if cache:
            _resolved = resolved
        return resolved


def store_status() -> Dict[str, Any]:
    """The resolved store state (path / usable / reason) — diagnostics and
    the bench/grid ``--tuned`` banners."""
    path, st, reason = _resolve()
    return {"path": path, "usable": st is not None, "reason": reason,
            "configs": len(st.configs) if st is not None else 0}


def _announce(key: str, outcome: str, **fields) -> None:
    rec = obs.active()
    run_id = rec.run_id if rec is not None else None
    tag = (run_id, key, outcome)
    with _lock:
        if tag in _announced:
            return
        _announced.add(tag)
    obs.counter("tune.store_hits" if outcome == "store"
                else "tune.store_misses")
    obs.emit("tune", key=key, source=outcome, **fields)


def params_for(op: str, n: int, dtype: str = "float32",
               engine: str = "blocked") -> Dict[str, Any]:
    """Seed defaults overlaid with this hardware's stored winners for the
    (op, n-bucket, dtype, engine) point. Never raises; never returns None.
    """
    key = _space.config_key(op, n, dtype, engine)
    seeds = _space.seed_params(op)
    if _suspended:
        return seeds
    path, st, reason = _resolve()
    if st is None:
        # "absent" is the permanent steady state of an untuned checkout —
        # not worth an event per run; real degradations are.
        if reason != "absent":
            _announce(key, "seed", reason=reason)
        return seeds
    entry = st.configs.get(key)
    if not entry:
        _announce(key, "seed", reason="no_entry")
        return seeds
    seeds.update(entry["params"])
    _announce(key, "store", params=entry["params"],
              swept=entry.get("swept_unix"),
              sweep_run=entry.get("source"))
    return seeds


def param(op: str, n: int, name: str, default: Any = None,
          dtype: str = "float32", engine: str = "blocked") -> Any:
    """One tuned parameter for the (op, n) point; ``default`` (then the
    declared seed) when the store has nothing to say. The single-value
    form the auto-resolvers use (core.blocked.auto_panel / resolve_factor,
    kernel tile pickers, serve warmup)."""
    value = params_for(op, n, dtype, engine).get(name)
    return default if value is None else value


def override(op: str, n: int, name: str, dtype: str = "float32",
             engine: str = "blocked") -> Any:
    """STORE-provided value only — None unless a usable store carries an
    explicit winner for this (op, n-bucket, dtype, engine, param) point.
    For code whose fallback is its own live module constant (e.g.
    ``core.blocked.PANEL_VMEM_BUDGET``, which tests monkeypatch): the
    declared seed must not shadow the caller's default there."""
    if _suspended:
        return None
    path, st, reason = _resolve()
    if st is None:
        # Degradations are data (summarize "tuning" section); the absent /
        # not-yet-judgeable states are steady noise, not degradations.
        if reason not in ("absent", "backend_uninitialized"):
            _announce(_space.config_key(op, n, dtype, engine), "seed",
                      reason=reason)
        return None
    key = _space.config_key(op, n, dtype, engine)
    entry = st.configs.get(key)
    if not entry or name not in entry["params"]:
        return None
    value = entry["params"][name]
    _announce(key, "store", params=entry["params"],
              swept=entry.get("swept_unix"), sweep_run=entry.get("source"))
    return value
