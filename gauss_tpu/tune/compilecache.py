"""JAX persistent compilation cache behind one helper + one env channel.

Every serve process and every fleet worker used to re-jit its whole bucket
ladder from scratch on start — the dominant term in serve cold-start p99
and in the fleet-restart resume latency PR 5 measures. XLA can already
persist compiled executables across processes (``jax_compilation_cache_dir``);
this module is the single switch that turns it on consistently:

- :func:`enable` points jax at an on-disk cache directory and drops the
  default minimum-compile-time/entry-size thresholds (our executables are
  many and individually small — the default 1 s floor would cache almost
  none of them), then registers the obs XLA-cache accounting listener so
  hits/misses are data in the run stream.
- ``GAUSS_COMPILE_CACHE`` is the env channel (same pattern as
  ``GAUSS_FAULTS``): :func:`enable` exports it, so worker subprocesses a
  supervisor spawns (resilience.fleet) and any child driver inherit the
  warm cache automatically; :func:`enable_from_env` is the receiving end.

Config consistency matters: the cache key covers the compile options, so
every participating process must enable the cache the same way (this
helper IS that way). Processes that never call :func:`enable` are
untouched — the cache is strictly opt-in.
"""

from __future__ import annotations

import os
from typing import Optional

from gauss_tpu import obs

ENV_CACHE_DIR = "GAUSS_COMPILE_CACHE"

_enabled_dir: Optional[str] = None


def cache_dir() -> Optional[str]:
    """The directory this process's persistent cache writes to (None when
    not enabled)."""
    return _enabled_dir


def enabled() -> bool:
    return _enabled_dir is not None


def enable(path: Optional[str] = None, export_env: bool = True,
           ) -> Optional[str]:
    """Enable the persistent compilation cache at ``path`` (or the
    ``GAUSS_COMPILE_CACHE`` env value when ``path`` is None). Returns the
    directory in effect, or None when there is nothing to enable.
    Idempotent; re-enabling with a different path re-points the cache.

    ``export_env``: also export the dir into this process's environment so
    spawned subprocesses (fleet workers, loadgen children) join the same
    cache — the GAUSS_* env channel.
    """
    global _enabled_dir
    path = path or os.environ.get(ENV_CACHE_DIR)
    if not path:
        return None
    path = os.path.abspath(os.fspath(path))
    os.makedirs(path, exist_ok=True)

    import jax

    jax.config.update("jax_compilation_cache_dir", path)
    # Cache EVERYTHING: the serve/fleet workload is dozens of small
    # executables, each well under the default 1 s / min-entry-size
    # thresholds that were designed for giant training steps.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)

    from gauss_tpu.obs import compile as _obs_compile

    _obs_compile.track_xla_cache()
    if export_env:
        os.environ[ENV_CACHE_DIR] = path
    _enabled_dir = path
    obs.emit("tune", key="compile_cache", source="enabled", dir=path)
    return path


def enable_from_env() -> Optional[str]:
    """The subprocess receiving end: enable the cache iff the env channel
    names a directory (fleet workers call this right after
    honor_jax_platforms). No-op — and no jax import — otherwise."""
    if not os.environ.get(ENV_CACHE_DIR):
        return None
    return enable()
