"""gauss_tpu.tune — offline autotuner + persistent compile cache.

The repo's hot-path constants (panel width, chunk group size, kernel tile
shapes, VMEM sizing floors, refine depth) were each hand-picked from one
sweep on one machine; ROADMAP's "[perf+scale] Autotuner + persistent
compile cache" item exists because those numbers cannot be right across
CPU, v5e, and v5p at every (n, dtype, engine) point. This package closes
the loop:

- :mod:`gauss_tpu.tune.space` — the declared tunable space per operation,
  with the historical hand constants as SEED DEFAULTS (single-sourced: the
  code imports its defaults from here, so tuner output and code defaults
  cannot drift).
- :mod:`gauss_tpu.tune.runner` — the offline sweep (``gauss-tune``):
  per (op, n-bucket, dtype, engine) it measures every candidate with the
  existing bench timers, prunes losers early, and records the winner.
- :mod:`gauss_tpu.tune.store` — the versioned on-disk JSON store of
  winning configs, keyed by an environment fingerprint; corrupt / stale /
  foreign stores fall back to the seeds with a typed
  :class:`~gauss_tpu.tune.store.TuneStoreError` available to strict
  callers.
- :mod:`gauss_tpu.tune.apply` — the read side every entry point consults
  (core.blocked auto-resolution, kernels, serve warmup, fleet workers,
  bench): one stat + dict hit per lookup, zero behavior change when no
  store exists.
- :mod:`gauss_tpu.tune.compilecache` — JAX's persistent compilation cache
  behind one helper + the ``GAUSS_COMPILE_CACHE`` env channel, so serve
  restarts and fleet worker respawns resume with a warm cache instead of
  re-jitting their whole bucket ladder.
- :mod:`gauss_tpu.tune.check` — the ``make tune-check`` CI gate:
  micro-sweep -> store -> tuned solve verified at 1e-4 -> second-process
  warm-cache rerun asserted to perform strictly fewer XLA compiles.

Nothing here imports jax at module load; device-touching helpers import
it lazily (same rule as gauss_tpu.obs).
"""

from gauss_tpu.tune.store import TuneStore, TuneStoreError  # noqa: F401
