"""Versioned on-disk store of tuned configs, keyed by hardware fingerprint.

One JSON file holds the winning configs an offline sweep (``gauss-tune``)
measured on THIS hardware::

    {"version": 1,
     "fingerprint": {"backend": "tpu", "device_kind": "TPU v5e",
                     "device_count": 8, "jax": "0.4.37"},
     "created_unix": 1754300000.0,
     "configs": {
        "lu_factor/n2048/float32/blocked": {
            "params": {"panel": 256, "chunk": 4},
            "seconds": 0.00148, "seed_seconds": 0.00165,
            "source": "3f9a2c...", "swept_unix": 1754300000.0}}}

Failure policy (the satellite contract): a corrupt / truncated / wrong-
version / foreign-fingerprint store NEVER changes behavior — readers fall
back to the seed defaults in :mod:`gauss_tpu.tune.space`. The typed
:class:`TuneStoreError` is raised by the strict loader (:meth:`TuneStore
.load`); the consult path (:mod:`gauss_tpu.tune.apply`) catches it, emits
an obs ``tune`` event naming the reason, and proceeds on seeds.

The fingerprint reuses the obs ``run_start`` environment fingerprint from
PR 2 (:func:`gauss_tpu.obs.registry.environment_fingerprint`), reduced to
the fields that change which config wins: backend, device kind/count, and
the jax version (a jax upgrade can move compile behavior enough to retune).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Optional

from gauss_tpu.tune import space as _space

STORE_VERSION = 1

#: env channel naming the store file (same GAUSS_* pattern as GAUSS_FAULTS /
#: GAUSS_COMPILE_CACHE — how serve processes and fleet worker subprocesses
#: inherit a store they cannot be handed through an API).
ENV_STORE = "GAUSS_TUNE_STORE"

#: fingerprint fields that key a store to a hardware epoch.
FINGERPRINT_KEYS = ("backend", "device_kind", "device_count", "jax")


class TuneStoreError(RuntimeError):
    """The store file on disk cannot be used: unreadable, corrupt JSON,
    missing required fields, a future/unknown schema version, or a
    fingerprint from different hardware. Consult paths catch this and
    fall back to the seed defaults; strict tools (``gauss-tune ...``
    operating ON a store) let it propagate."""


def default_store_path() -> str:
    """The store location: ``$GAUSS_TUNE_STORE`` when set, else a per-user
    cache path (NOT inside the repo — a checkout must behave identically
    on every machine until a sweep is run on it)."""
    env = os.environ.get(ENV_STORE)
    if env:
        return env
    return os.path.join(os.path.expanduser("~"), ".cache", "gauss_tpu",
                        "tune_store.json")


def store_fingerprint() -> Dict[str, Any]:
    """The reduced hardware fingerprint for store stamping/matching.
    Reuses the obs environment fingerprint (never initializes a backend);
    fields the current process cannot know yet are simply absent."""
    from gauss_tpu.obs.registry import environment_fingerprint

    fp = environment_fingerprint()
    return {k: fp[k] for k in FINGERPRINT_KEYS if fp.get(k) is not None}


def fingerprint_matches(stamped: Dict[str, Any],
                        current: Optional[Dict[str, Any]] = None) -> bool:
    """Does a store stamped with ``stamped`` apply to this process?
    Strict on the fields BOTH sides know; a reader that has not
    initialized a backend yet (no ``backend`` key) cannot prove a match,
    so a backend-stamped store conservatively mismatches there."""
    current = store_fingerprint() if current is None else current
    for k in FINGERPRINT_KEYS:
        if k in stamped and stamped[k] != current.get(k):
            return False
    return True


class TuneStore:
    """In-memory image of one store file (load -> mutate -> save)."""

    def __init__(self, fingerprint: Optional[Dict[str, Any]] = None,
                 configs: Optional[Dict[str, Dict[str, Any]]] = None,
                 created_unix: Optional[float] = None):
        self.version = STORE_VERSION
        self.fingerprint = dict(fingerprint or {})
        self.configs: Dict[str, Dict[str, Any]] = dict(configs or {})
        self.created_unix = (time.time() if created_unix is None
                             else created_unix)

    # -- (de)serialization -------------------------------------------------

    def to_doc(self) -> Dict[str, Any]:
        return {"version": self.version, "fingerprint": self.fingerprint,
                "created_unix": self.created_unix, "configs": self.configs}

    @classmethod
    def from_doc(cls, doc: Any, path: str = "<doc>") -> "TuneStore":
        if not isinstance(doc, dict):
            raise TuneStoreError(f"tune store {path!r}: expected a JSON "
                                 f"object, got {type(doc).__name__}")
        version = doc.get("version")
        if version != STORE_VERSION:
            raise TuneStoreError(
                f"tune store {path!r}: schema version {version!r} is not "
                f"the supported version {STORE_VERSION} — re-run the sweep "
                f"(gauss-tune) to regenerate it")
        configs = doc.get("configs")
        fingerprint = doc.get("fingerprint")
        if not isinstance(configs, dict) or not isinstance(fingerprint,
                                                           dict):
            raise TuneStoreError(
                f"tune store {path!r}: missing/invalid 'configs' or "
                f"'fingerprint' field")
        for key, entry in configs.items():
            if (not isinstance(entry, dict)
                    or not isinstance(entry.get("params"), dict)):
                raise TuneStoreError(
                    f"tune store {path!r}: config {key!r} has no valid "
                    f"'params' dict")
        store = cls(fingerprint=fingerprint, configs=configs,
                    created_unix=doc.get("created_unix"))
        return store

    @classmethod
    def load(cls, path) -> "TuneStore":
        """Strict load: every failure shape is the typed
        :class:`TuneStoreError` (original error chained), so callers hold
        one except clause instead of OSError/ValueError/KeyError soup."""
        path = os.fspath(path)
        try:
            with open(path) as f:
                text = f.read()
        except OSError as e:
            raise TuneStoreError(f"tune store {path!r}: cannot read: "
                                 f"{e}") from e
        try:
            doc = json.loads(text)
        except ValueError as e:
            raise TuneStoreError(
                f"tune store {path!r}: corrupt/truncated JSON ({e}) — "
                f"falling back to seed defaults is safe; re-run "
                f"gauss-tune to regenerate") from e
        return cls.from_doc(doc, path)

    def save(self, path) -> str:
        """Atomic write (tmp + rename), stable key order — byte-identical
        for identical content, so a re-run that finds the same winners
        produces the same file (roundtrip determinism, tested)."""
        path = os.fspath(path)
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump(self.to_doc(), f, indent=1, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)
        return path

    # -- config access -----------------------------------------------------

    def put(self, op: str, n: int, params: Dict[str, Any],
            dtype: str = "float32", engine: str = "blocked",
            seconds: Optional[float] = None,
            seed_seconds: Optional[float] = None,
            source: Optional[str] = None) -> str:
        key = _space.config_key(op, n, dtype, engine)
        entry: Dict[str, Any] = {"params": dict(params),
                                 "swept_unix": time.time()}
        if seconds is not None:
            entry["seconds"] = float(seconds)
        if seed_seconds is not None:
            entry["seed_seconds"] = float(seed_seconds)
        if source:
            entry["source"] = source
        self.configs[key] = entry
        return key

    def get(self, op: str, n: int, dtype: str = "float32",
            engine: str = "blocked") -> Optional[Dict[str, Any]]:
        """The stored entry for the (op, n-bucket, dtype, engine) point,
        or None."""
        return self.configs.get(_space.config_key(op, n, dtype, engine))

    def params(self, op: str, n: int, dtype: str = "float32",
               engine: str = "blocked") -> Dict[str, Any]:
        """Seed defaults overlaid with the stored winners for this point
        (missing point -> pure seeds)."""
        out = _space.seed_params(op)
        entry = self.get(op, n, dtype, engine)
        if entry:
            out.update(entry["params"])
        return out
