"""``make tune-check`` — the autotuner + compile-cache CI gate.

One bounded CPU smoke proving the whole tune loop end to end:

1. **Micro-sweep** (2 points per axis) through the real runner -> a store
   file written with this environment's fingerprint.
2. **Tuned solve**: with the store installed (``GAUSS_TUNE_STORE``), the
   auto-resolving entry points must consult it (asserted via obs ``tune``
   events), produce a solution inside the 1e-4 relative-residual gate, and
   factor BIT-IDENTICALLY to an explicit call with the winning params —
   tuning picks among configs, it must never change the math of any one.
3. **Serve warmup**: a batched executable built with ``panel=None`` must
   pick up the tuned panel (same cache key as untuned — tuning changes how
   an entry is built, not which entry it is).
4. **Warm-start**: two child processes run the same workload against one
   persistent compile-cache dir; the second must perform STRICTLY FEWER
   XLA compiles (obs ``xla.cache_misses`` accounting — a miss IS a real
   backend compile) and report its warmup accordingly.

Exit codes: 2 on any correctness/consult/warm-start assertion failure,
1 when ``--regress-check`` finds the sweep out of the history band, 0
green. The summary is the runner's regress-ingestable ``tune_sweep`` doc
extended with a ``warm_start`` section.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time
from typing import Dict, List, Optional

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _fail(msg: str) -> int:
    print(f"tune-check: FAILED: {msg}", file=sys.stderr)
    return 2


def _counter(events: List[dict], name: str) -> float:
    for ev in events:
        if (ev.get("type") == "metric" and ev.get("kind") == "counter"
                and ev.get("name") == name):
            return float(ev.get("value") or 0)
    return 0.0


def _child_main(args) -> int:
    """One warm-start probe process: enable the compile cache from the env
    channel, run the seeded solve + serve-executable build, record the
    stream. Spawned twice against one cache dir; the streams' XLA cache
    counters are the gate's evidence."""
    from gauss_tpu.utils.env import honor_jax_platforms

    honor_jax_platforms()
    from gauss_tpu import obs
    from gauss_tpu.tune import compilecache, runner

    compilecache.enable_from_env()
    t0 = time.perf_counter()
    with obs.run(metrics_out=args.metrics_out, tool="tune_check_child"):
        from gauss_tpu.core import blocked
        from gauss_tpu.serve.cache import CacheKey, ExecutableCache

        a64, b64 = runner._seeded_system(args.n, args.seed)
        x, _ = blocked.solve_refined(a64, b64)
        rel = (np.linalg.norm(a64 @ x - b64)
               / max(np.linalg.norm(b64), 1e-30))
        # The serve warmup shapes join the cache too (they dominate a real
        # cold start).
        cache = ExecutableCache(capacity=4)
        bucket = 1 << (args.n - 1).bit_length()
        cache.get(CacheKey(bucket_n=bucket, nrhs=1, batch=2,
                           dtype="float32", engine="blocked",
                           refine_steps=1))
        obs.emit("tune_check", child=True, rel_residual=float(rel),
                 wall_s=round(time.perf_counter() - t0, 4))
    return 0 if rel <= 1e-4 else 2


def run_check(args) -> int:
    from gauss_tpu.utils.env import honor_jax_platforms

    honor_jax_platforms()
    from gauss_tpu import obs
    from gauss_tpu.core import blocked
    from gauss_tpu.serve.cache import CacheKey, ExecutableCache
    from gauss_tpu.tune import apply as _apply
    from gauss_tpu.tune import runner
    from gauss_tpu.tune import store as _tstore

    own_tmp = args.tmpdir is None
    tmpdir = args.tmpdir or tempfile.mkdtemp(prefix="gauss_tune_check_")
    os.makedirs(tmpdir, exist_ok=True)
    store_path = os.path.join(tmpdir, "tune_store.json")
    cache_dir = os.path.join(tmpdir, "xla_cache")
    summary: Dict = {}
    rc = 0
    try:
        with obs.run(metrics_out=args.metrics_out,
                     tool="tune_check", n=args.n) as rec:
            # -- 1. micro-sweep: 2 points per swept axis ------------------
            axes = {"panel": [64, 128], "chunk": [1, 2]}
            summary = runner.run_sweep(["lu_factor"], [args.n],
                                       seed=args.seed, reps=args.reps,
                                       axes=axes, run_id=rec.run_id)
            runner.write_store(summary, store_path)
            print(runner.format_summary(summary))
            point = summary["points"][0]
            winner = {k: v for k, v in point["best_params"].items()
                      if v is not None}

            # -- 2. tuned solve: consulted + verified + bit-identical -----
            os.environ[_tstore.ENV_STORE] = store_path
            _apply.reset_cache()
            import jax

            # The sweep already traced these shapes with the seed configs;
            # the jit cache would replay those programs and the store
            # consult (trace-time) would never run. A fresh process has no
            # such cache — clearing reproduces that state.
            jax.clear_caches()
            a64, b64 = runner._seeded_system(args.n, args.seed)
            x, _ = blocked.solve_refined(a64, b64)
            rel = (np.linalg.norm(a64 @ x - b64)
                   / max(np.linalg.norm(b64), 1e-30))
            if not rel <= 1e-4:
                return _fail(f"tuned solve missed the 1e-4 gate "
                             f"(rel residual {rel:.3e})")
            consults = [ev for ev in rec.events if ev.get("type") == "tune"
                        and ev.get("source") == "store"]
            if not consults:
                return _fail("tuned solve emitted no store-consult event "
                             "(the store was not consulted)")
            if "panel" in winner:
                import jax.numpy as jnp

                a32 = jnp.asarray(a64, jnp.float32)
                fac_auto = blocked.lu_factor_blocked(a32, panel=None)
                fac_explicit = blocked.lu_factor_blocked(
                    a32, panel=int(winner["panel"]))
                if not np.array_equal(np.asarray(fac_auto.m),
                                      np.asarray(fac_explicit.m)):
                    return _fail("store-resolved factorization is not "
                                 "bit-identical to the explicit winning "
                                 "config")
                print(f"tune-check: tuned solve ok (rel {rel:.3e}, "
                      f"bit-identical to explicit {winner})")

            # -- 3. serve warmup picks up the tuned panel -----------------
            cache = ExecutableCache(capacity=4)
            bucket = 1 << (args.n - 1).bit_length()
            key = CacheKey(bucket_n=bucket, nrhs=1, batch=1,
                           dtype="float32", engine="blocked",
                           refine_steps=1)
            exe = cache.get(key)
            want_panel = winner.get("panel")
            if want_panel is not None and exe.panel != int(want_panel):
                return _fail(f"serve warmup built with panel={exe.panel}, "
                             f"store says {want_panel}")
            if exe.key != key:
                return _fail("tuning changed the executable cache key")
            print(f"tune-check: serve warmup consulted the store "
                  f"(panel={exe.panel}, cache key unchanged)")

        # -- 4. warm-start: strictly fewer XLA compiles in process 2 ------
        env = dict(os.environ)
        env["GAUSS_COMPILE_CACHE"] = cache_dir
        env[_tstore.ENV_STORE] = store_path
        env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
        streams, walls = [], []
        for tag in ("cold", "warm"):
            stream = os.path.join(tmpdir, f"child_{tag}.jsonl")
            cmd = [sys.executable, "-m", "gauss_tpu.tune.check", "--child",
                   "--n", str(args.n), "--seed", str(args.seed),
                   "--metrics-out", stream]
            t0 = time.perf_counter()
            proc = subprocess.run(cmd, cwd=_REPO, env=env,
                                  capture_output=True, text=True,
                                  timeout=args.child_timeout)
            walls.append(round(time.perf_counter() - t0, 3))
            if proc.returncode != 0:
                return _fail(f"{tag} child exited {proc.returncode}:\n"
                             f"{proc.stdout}\n{proc.stderr}")
            streams.append(stream)
        from gauss_tpu.obs.registry import read_events

        cold_ev, warm_ev = (read_events(s) for s in streams)
        cold_misses = _counter(cold_ev, "xla.cache_misses")
        warm_misses = _counter(warm_ev, "xla.cache_misses")
        warm_hits = _counter(warm_ev, "xla.cache_hits")
        if not cold_misses > 0:
            return _fail("cold child recorded no XLA compiles — the "
                         "persistent-cache accounting is broken")
        if not warm_misses < cold_misses:
            return _fail(f"warm-start did not reduce XLA compiles "
                         f"(cold {cold_misses:.0f} vs warm "
                         f"{warm_misses:.0f} misses)")
        summary["warm_start"] = {
            "cache_dir": cache_dir, "cold_compiles": int(cold_misses),
            "warm_compiles": int(warm_misses),
            "warm_cache_hits": int(warm_hits),
            "cold_wall_s": walls[0], "warm_wall_s": walls[1]}
        print(f"tune-check: warm start ok — XLA compiles "
              f"{int(cold_misses)} cold -> {int(warm_misses)} warm "
              f"({int(warm_hits)} cache hits; wall {walls[0]:.1f} s -> "
              f"{walls[1]:.1f} s)")

        # -- outputs / gates ---------------------------------------------
        if args.summary_json:
            parent = os.path.dirname(args.summary_json)
            if parent:
                os.makedirs(parent, exist_ok=True)
            with open(args.summary_json, "w") as f:
                json.dump(summary, f, indent=1, sort_keys=True)
                f.write("\n")
            print(f"summary: {args.summary_json}")

        from gauss_tpu.obs import regress

        records = [{"metric": m, "value": v, "unit": u,
                    "source": f"tune:{summary.get('run_id')}",
                    "kind": "tune"}
                   for m, v, u in runner.history_records(summary)]
        if args.regress_check and records:
            history_path = args.history or regress.default_history_path()
            verdicts = regress.check_records(
                records, regress.load_history(history_path))
            print(regress.format_verdicts(verdicts))
            if any(v["status"] == "out-of-band" for v in verdicts):
                rc = 1
        if args.history is not None and records and rc == 0:
            history_path = args.history or regress.default_history_path()
            added = regress.append_history(records, history_path)
            print(f"history: {added} record(s) appended to {history_path}")
    finally:
        if own_tmp:
            shutil.rmtree(tmpdir, ignore_errors=True)
    return rc


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m gauss_tpu.tune.check",
        description="Autotuner + compile-cache smoke gate: micro-sweep -> "
                    "store -> tuned solve (verified, bit-identical, "
                    "consult-asserted) -> serve warmup consult -> "
                    "second-process warm start with strictly fewer XLA "
                    "compiles.")
    p.add_argument("--n", type=int, default=96,
                   help="system size for the micro-sweep (default 96)")
    p.add_argument("--seed", type=int, default=258458)
    p.add_argument("--reps", type=int, default=2,
                   help="timed reps per candidate (default 2)")
    p.add_argument("--tmpdir", default=None,
                   help="working dir (store, cache, child streams); a "
                        "temp dir removed at exit by default")
    p.add_argument("--child-timeout", type=float, default=180.0)
    p.add_argument("--metrics-out", default=None, metavar="PATH")
    p.add_argument("--summary-json", default=None, metavar="PATH")
    p.add_argument("--history", nargs="?", const="", default=None,
                   metavar="PATH",
                   help="append the sweep's records to the regression "
                        "history (default reports/history.jsonl)")
    p.add_argument("--regress-check", action="store_true")
    p.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.child:
        return _child_main(args)
    return run_check(args)


if __name__ == "__main__":
    sys.exit(main())
