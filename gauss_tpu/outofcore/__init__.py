"""gauss_tpu.outofcore — host-streamed solves for n beyond device memory.

The full matrix lives in host memory; only the active panel group plus a
bounded window of trailing column tiles are device-resident, with
H2D/D2H transfers double-buffered against MXU work. The per-group step
is the SHARED ``core.blocked._factor_group`` (the checkpointed and ABFT
paths step the same function), so the streamed factor cannot drift from
the in-core forms. See stream.py's module docstring for the full design;
``python -m gauss_tpu.outofcore.check`` is the CI gate.

Quick tour::

    from gauss_tpu import outofcore

    x = outofcore.solve_outofcore(a, b)          # float64, 1e-4-refinable
    stats = outofcore.last_stream_stats()        # transfers/stalls/peak
    outofcore.outofcore_fits(65536)              # admission (HBM-shaped)

``solve_handoff(engine="outofcore")`` forces this route;
oversized single-device requests stream here automatically.
"""

from gauss_tpu.outofcore.stream import (  # noqa: F401
    OUTOFCORE_DEVICE_FRAC,
    PIPELINE_TILE_BUFFERS,
    OutOfCoreLU,
    SDCDetectedError,
    StreamStats,
    host_memory_budget,
    last_stream_stats,
    lu_factor_outofcore,
    lu_solve_outofcore,
    outofcore_fits,
    outofcore_window,
    solve_outofcore,
)
