"""Out-of-core streamed-solve gate: ``python -m gauss_tpu.outofcore.check``.

Runs the host-streamed blocked LU end to end on the CPU proxy and asserts
the subsystem's three contracts:

- **correctness** — the streamed solve passes the 1e-4 relative-residual
  gate (verified here, independently of any ladder);
- **boundedness** — the measured peak of the device-byte ledger stays
  under half of the full in-core working set (``3 n^2 itemsize`` — the
  whole point of streaming), and the trailing region really was tiled
  (``tiles >= 2``);
- **routing** — an oversized request (budget forced below the working
  set) reaches the streamed engine through ``solve_handoff`` without an
  explicit engine request, emitting the ``route`` obs event with
  ``lane=outofcore``.

The summary (``--summary-json``) is regress-ingestable
(``kind: outofcore_bench``): seconds per streamed solve, the stall
fraction (1 - transfer/compute overlap — the double-buffering pipeline
breaking shows up as this jumping toward 1), and the peak device fraction
(deterministic; a window-sizing regression moves it). ``make
outofcore-check`` runs the CPU configuration CI gates on.

``--giant N`` additionally runs the acceptance-scale leg (n=32768 class:
auto window from the device budget, checkpointless) with the same
correctness + boundedness assertions — minutes of wall clock, not part of
the default CI gate.

Exit status: 2 when any assertion fails, 1 when ``--regress-check`` finds
an out-of-band metric, 0 otherwise.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from gauss_tpu.utils.env import honor_jax_platforms


def _seeded_system(n: int, seed: int) -> Tuple[np.ndarray, np.ndarray]:
    """Deterministic diagonally-dominant dense system (float32 operand —
    the streamed engine's native storage; residuals verify in f64)."""
    rng = np.random.default_rng(np.random.SeedSequence((seed, n)))
    a = rng.standard_normal((n, n)).astype(np.float32)
    a[np.arange(n), np.arange(n)] += np.float32(n)
    b = rng.standard_normal(n).astype(np.float32)
    return a, b


def _rel_residual(a: np.ndarray, x: np.ndarray, b: np.ndarray) -> float:
    """Chunked f64 relative residual — no full f64 operand copy, so the
    giant leg verifies without doubling its host footprint."""
    from gauss_tpu.outofcore.stream import _residual_chunked

    b64 = np.asarray(b, dtype=np.float64)
    r = _residual_chunked(a, np.asarray(x, dtype=np.float64)[:, None],
                          b64[:, None])
    return float(np.linalg.norm(r) / max(np.linalg.norm(b64), 1e-300))


def run_streamed(n: int, seed: int, gate: float, panel: Optional[int],
                 chunk: Optional[int], ct: Optional[int],
                 reps: int = 1) -> Dict:
    """One streamed solve (best-of-``reps``); returns its summary row with
    the StreamStats accounting folded in."""
    from gauss_tpu import outofcore

    a, b = _seeded_system(n, seed)
    workset = 3 * n * n * a.dtype.itemsize
    best = None
    stats = x = None
    for _ in range(max(1, reps)):
        t0 = time.perf_counter()
        x = outofcore.solve_outofcore(a, b, panel=panel, chunk=chunk, ct=ct)
        dt = time.perf_counter() - t0
        if best is None or dt < best:
            best = dt
            stats = outofcore.last_stream_stats()
    rel = _rel_residual(a, x, b)
    peak_frac = stats.peak_device_bytes / workset
    return {
        "n": n, "panel": stats.panel, "chunk": stats.chunk, "ct": stats.ct,
        "s_per_solve": round(best, 6),
        "rel_residual": rel,
        "verified": bool(np.isfinite(rel) and rel <= gate),
        "workset_bytes": int(workset),
        "peak_device_frac": round(peak_frac, 6),
        "bounded": bool(peak_frac < 0.5),
        "streamed": bool(stats.tiles >= 2),
        **stats.to_dict(),
    }


def run_routing(n: int, seed: int, gate: float) -> Dict:
    """The handoff leg: a request whose working set exceeds a forced
    budget, submitted WITHOUT an engine request, must stream (no
    multi-device mesh in the gate configuration) and verify."""
    from gauss_tpu.core import blocked
    from gauss_tpu.dist.mesh import make_mesh

    a, b = _seeded_system(n, seed + 1)
    budget = 3 * n * n * a.dtype.itemsize - 1  # one byte short: oversized
    t0 = time.perf_counter()
    # A single-device mesh, explicitly: the no-mesh fallback branch under
    # test, independent of how many virtual devices the host exposes.
    x = blocked.solve_handoff(a, b, budget=budget, mesh=make_mesh(1))
    dt = time.perf_counter() - t0
    rel = _rel_residual(a, x, b)
    return {"n": n, "budget": budget, "s_per_solve": round(dt, 6),
            "rel_residual": rel,
            "verified": bool(np.isfinite(rel) and rel <= gate)}


def history_records(summary: Dict) -> List[Tuple[str, float, str]]:
    """(metric, value, unit) records an out-of-core run contributes to the
    regression history — all slow-side-gated: the streamed solve getting
    slower shows in s_per_solve, the double-buffering pipeline breaking in
    stall_fraction, a window-sizing regression in peak_device_frac."""
    out: List[Tuple[str, float, str]] = []
    smoke = summary.get("smoke") or {}
    if isinstance(smoke.get("s_per_solve"), (int, float)):
        out.append(("outofcore:s_per_solve", smoke["s_per_solve"], "s"))
    if isinstance(smoke.get("stall_fraction"), (int, float)):
        out.append(("outofcore:stall_fraction",
                    round(smoke["stall_fraction"], 4), "ratio"))
    if isinstance(smoke.get("peak_device_frac"), (int, float)):
        out.append(("outofcore:peak_device_frac",
                    smoke["peak_device_frac"], "ratio"))
    giant = summary.get("giant") or {}
    if isinstance(giant.get("s_per_solve"), (int, float)):
        out.append((f"outofcore:n{giant['n']}/s_per_solve",
                    giant["s_per_solve"], "s"))
    return out


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m gauss_tpu.outofcore.check",
        description="Out-of-core streamed-solve gate: correctness at the "
                    "1e-4 bar, measured peak device bytes bounded under "
                    "half the in-core working set, transfer/compute "
                    "overlap reported from obs spans, and solve_handoff "
                    "routing oversized no-mesh requests to the streamed "
                    "engine (the make outofcore-check CI configuration).")
    p.add_argument("--n", type=int, default=2048,
                   help="smoke-leg system size (default 2048)")
    p.add_argument("--panel", type=int, default=None)
    p.add_argument("--chunk", type=int, default=4,
                   help="panels per streamed group for the smoke leg")
    p.add_argument("--ct", type=int, default=256,
                   help="trailing tile width for the smoke leg (small, so "
                        "the pipeline demonstrably streams)")
    p.add_argument("--routing-n", type=int, default=192,
                   help="size of the forced-oversized routing leg")
    p.add_argument("--reps", type=int, default=1)
    p.add_argument("--seed", type=int, default=258458)
    p.add_argument("--gate", type=float, default=1e-4)
    p.add_argument("--giant", type=int, default=0, metavar="N",
                   help="also run the acceptance-scale leg at this n "
                        "(e.g. 32768; auto window, minutes of wall clock)")
    p.add_argument("--giant-ct", type=int, default=None,
                   help="explicit tile width for the giant leg "
                        "(default: outofcore_window from the budget)")
    p.add_argument("--metrics-out", default=None, metavar="PATH",
                   help="append the run's obs JSONL stream here")
    p.add_argument("--summary-json", default=None, metavar="PATH",
                   help="write the regress-ingestable summary "
                        "(kind=outofcore_bench)")
    p.add_argument("--history", nargs="?", const="", default=None,
                   metavar="PATH",
                   help="append this run's records to the regression "
                        "history (default reports/history.jsonl)")
    p.add_argument("--regress-check", action="store_true",
                   help="gate against the history baselines (exit 1 when "
                        "out of band)")
    p.add_argument("--band", type=float, default=1.5,
                   help="slow-side noise band for --regress-check (the "
                        "smoke timing is seconds-scale CPU wall — "
                        "jittery; the regressions this gate exists for "
                        "move it by integer factors)")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    honor_jax_platforms()

    from gauss_tpu import obs
    from gauss_tpu.obs import regress

    t0 = time.perf_counter()
    with obs.run(metrics_out=args.metrics_out, tool="outofcore_check",
                 seed=args.seed) as rec:
        with obs.span("outofcore_check_smoke", n=args.n):
            smoke = run_streamed(args.n, args.seed, args.gate, args.panel,
                                 args.chunk, args.ct, reps=args.reps)
        with obs.span("outofcore_check_routing", n=args.routing_n):
            routing = run_routing(args.routing_n, args.seed, args.gate)
        giant = None
        if args.giant:
            with obs.span("outofcore_check_giant", n=args.giant):
                giant = run_streamed(args.giant, args.seed, args.gate,
                                     None, None, args.giant_ct, reps=1)
    wall = round(time.perf_counter() - t0, 3)

    failures: List[str] = []
    for name, row, need_stream in (("smoke", smoke, True),
                                   ("routing", routing, False),
                                   ("giant", giant, True)):
        if row is None:
            continue
        if not row["verified"]:
            failures.append(f"{name}: rel_residual {row['rel_residual']:.2e}"
                            f" missed the {args.gate:.0e} gate")
        if need_stream and not row.get("bounded", True):
            failures.append(
                f"{name}: peak device bytes "
                f"{row['peak_device_frac']:.1%} of the in-core working set "
                f"(must be < 50%)")
        if need_stream and not row.get("streamed", True):
            failures.append(f"{name}: trailing region was not tiled "
                            f"(tiles={row.get('tiles')})")
    # The routing decision as data: the handoff leg must have emitted
    # lane=outofcore (checked on the recorded stream when one exists).
    if args.metrics_out and os.path.exists(args.metrics_out):
        events = obs.read_events(args.metrics_out)
        lanes = [e.get("lane") for e in events
                 if e.get("type") == "route"
                 and e.get("tool") == "solve_handoff"]
        if "outofcore" not in lanes:
            failures.append(f"routing: no route event with lane=outofcore "
                            f"on the recorded stream (saw {lanes})")

    summary = {"kind": "outofcore_bench", "seed": args.seed,
               "gate": args.gate, "smoke": smoke, "routing": routing,
               "giant": giant, "wall_s": wall, "ok": not failures}

    for name, row in (("smoke", smoke), ("routing", routing),
                      ("giant", giant)):
        if row is None:
            continue
        extra = (f" peak={row['peak_device_frac']:.1%} "
                 f"overlap={row['overlap_fraction']:.2f} "
                 f"tiles={row['tiles']}" if "tiles" in row else "")
        print(f"outofcore-check [{name:7s}] n={row['n']:6d} "
              f"s_per_solve={row['s_per_solve']:.3f} "
              f"rel_residual={row['rel_residual']:.2e}{extra} "
              f"{'OK' if row['verified'] else 'FAIL'}")
    print(f"outofcore-check: done in {wall} s"
          + (f"; FAILED: {failures}" if failures
             else f"; all legs verified at the {args.gate:.0e} gate"))

    if args.summary_json:
        parent = os.path.dirname(args.summary_json)
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(args.summary_json, "w") as f:
            json.dump(summary, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"summary: {args.summary_json}")

    rc = 0
    # Run-id-tagged sources (cf. structure/fleet records): identical
    # values from distinct epochs — peak_device_frac is deterministic —
    # must accumulate as separate baseline samples, not dedup into one.
    records = [{"metric": m, "value": v, "unit": u,
                "source": f"outofcore-{rec.run_id}",
                "kind": "outofcore"}
               for m, v, u in history_records(summary)]
    if args.regress_check and records:
        history_path = args.history or regress.default_history_path()
        verdicts = regress.check_records(
            records, regress.load_history(history_path), band=args.band)
        print(regress.format_verdicts(verdicts))
        if any(v["status"] == "out-of-band" for v in verdicts):
            rc = 1
    if args.history is not None and records and rc == 0 and not failures:
        history_path = args.history or regress.default_history_path()
        added = regress.append_history(records, history_path)
        print(f"history: {added} record(s) appended to {history_path}")

    if failures:
        return 2
    return rc


if __name__ == "__main__":
    sys.exit(main())
