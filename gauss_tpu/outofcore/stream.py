"""Host-streamed blocked LU — solves for n beyond one device's memory.

The single-chip blocked path holds ~3 matrix copies on device
(core.blocked.fits_single_chip); past ~34k at f32 on a v5e that is a hard
wall, and without a multi-device mesh ``solve_handoff`` used to raise an
explicit error there. This module opens the giant-system workload on ONE
device: the full matrix lives (and is updated) in HOST memory, and only

- the active panel GROUP's (gh, w) column block, and
- a bounded WINDOW of trailing (gh, ct) column tiles (a small fixed number
  of pipeline buffers, sized by :func:`outofcore_window` from
  ``device_memory_budget()``)

are ever device-resident. H2D/D2H transfers are double-buffered against
MXU work: tile t+1 is ``jax.device_put`` while tile t's compiled update
runs, and tile t-1's result is copied back while tile t computes. Every
transfer and every exposed device stall is an obs SPAN
(``outofcore.h2d`` / ``outofcore.d2h`` / ``outofcore.compute_wait``) so
``obs.doctor`` can attribute stream-vs-compute time, and the engine keeps
a byte LEDGER of every device buffer it holds — ``peak_device_bytes`` is
measured, not modeled (XLA's in-kernel transients ride on top; the gate
asserts the ledger peak far enough under the full working set that they
cannot close the gap).

**Shared math, cannot drift.** The per-group step IS
:func:`gauss_tpu.core.blocked._factor_group` — the same function the
one-shot chunked form traces, the checkpointed path steps, and the ABFT
runner replays — called on a RECTANGULAR (gh, w) group-only buffer
(``gs=0``, trailing width 0: the in-core last-group trace). The windowed
trailing update mirrors ``_factor_group``'s right-of-group branch
operation for operation (the same ``_gdot`` blockwise L-solve scan and
rank-w GEMM, restricted to one (gh, ct) tile), so the streamed factor
matches the in-core chunked factor to GEMM-tiling rounding.

**Riders.** ``abft=True`` carries the Huang-Abraham checksum row on the
host and verifies (a) the group-column identity inside the shared group
step and (b) the trailing column-sum identity per streamed tile; a
mismatch raises a typed :class:`SDCDetectedError` localized to (group,
column). Retired columns leave the device permanently, so the in-core
final whole-factor identity is unnecessary: every column is checked at
the moment it retires. ``checkpoint_path`` serializes the host carry —
the exact ``(m, perm, min_piv, linvs, uinvs, next_group)`` signature of
gauss_tpu.resilience.checkpoint, through its own ``save_state`` /
``_load_resume_state`` — every K groups, so a killed giant solve resumes
instead of restarting.

Fault hooks: ``outofcore.group`` (kill between groups — preemption),
``outofcore.tile`` (corrupt a trailing tile on its way to the device —
what the ABFT rider detects).
"""

from __future__ import annotations

import dataclasses
import functools
import os
import time
from contextlib import contextmanager
from functools import partial
from typing import NamedTuple, Optional

import numpy as np

from gauss_tpu import obs
from gauss_tpu.resilience import inject as _inject
from gauss_tpu.tune import space as _tspace

#: device buffers the tile pipeline keeps live at once: the in-flight
#: input tile, its output, plus the prefetched next input and the
#: previous output draining back to host.
PIPELINE_TILE_BUFFERS = 4

#: fraction of the device budget the streamed working set (group block +
#: window tiles) may claim. Kept well under the 50%-of-full-working-set
#: acceptance bar so XLA's in-update transients (~1 tile copy) can never
#: close the gap. Seeded in tune.space so a sweep can recalibrate it per
#: hardware epoch alongside the window itself.
OUTOFCORE_DEVICE_FRAC = _tspace.OUTOFCORE_DEVICE_FRAC_SEED

#: host working set ~ the factor copy being updated in place + the
#: caller's original operand + refinement/transfer transients.
OUTOFCORE_HOST_FACTOR = 2.25

#: conservative usable host RAM when the OS cannot report it.
DEFAULT_HOST_BYTES = 32 * 2**30

#: row-block size for the chunked host-f64 residual matvec (refinement
#: never materializes a full f64 copy of a giant operand).
RESIDUAL_ROW_BLOCK = 4096


class SDCDetectedError(RuntimeError):
    """The ABFT checksum rider detected silent data corruption in the
    streamed factorization — localized to the panel group (and global
    column) that produced it. With checkpointing enabled the natural
    recovery is a resume from the last verified checkpoint; under the
    recovery ladder (resilience.recover) the rung simply escalates."""

    def __init__(self, msg: str, group: int = -1, col: int = -1,
                 err: float = float("inf")):
        super().__init__(msg)
        self.group = group
        self.col = col
        self.err = err


class OutOfCoreLU(NamedTuple):
    """Host-resident factorization state — the streamed analog of
    core.blocked.BlockedLU (same getrf layout, same permuted-row
    convention, numpy instead of device arrays)."""

    m: np.ndarray           # (npad, npad) factored; rows permuted
    perm: np.ndarray        # (npad,) gather indices
    min_abs_pivot: float
    linv: np.ndarray        # (nb, panel, panel) accumulate-dtype inverses
    uinv: np.ndarray
    n: int
    panel: int
    abft_err: Optional[np.ndarray] = None  # per-group max mismatch


@dataclasses.dataclass
class StreamStats:
    """Measured accounting for one streamed factor/solve: transfer and
    stall walls (mirrored by the obs spans), streamed bytes, and the
    device-byte ledger's measured peak."""

    n: int = 0
    npad: int = 0
    panel: int = 0
    chunk: int = 0
    ct: int = 0
    groups: int = 0
    tiles: int = 0
    solves: int = 0
    h2d_s: float = 0.0
    d2h_s: float = 0.0
    compute_wait_s: float = 0.0
    wall_s: float = 0.0
    bytes_h2d: int = 0
    bytes_d2h: int = 0
    live_device_bytes: int = 0
    peak_device_bytes: int = 0

    # -- device ledger -----------------------------------------------------
    def add_dev(self, nbytes: int) -> None:
        self.live_device_bytes += int(nbytes)
        if self.live_device_bytes > self.peak_device_bytes:
            self.peak_device_bytes = self.live_device_bytes

    def sub_dev(self, nbytes: int) -> None:
        self.live_device_bytes -= int(nbytes)

    # -- derived -----------------------------------------------------------
    @property
    def transfer_s(self) -> float:
        return self.h2d_s + self.d2h_s

    @property
    def overlap_fraction(self) -> float:
        """Of the stream engine's blocking+streaming time, the fraction the
        host spent MOVING TILES while dispatched device work was in flight
        (transfers are issued strictly after the compute they shadow), vs
        stalled on the device with nothing left to stream
        (``compute_wait``). 1.0 = the pipeline fully hid the device behind
        the stream; a collapse toward 0 means async dispatch broke and
        every transfer ran against an idle device."""
        denom = self.transfer_s + self.compute_wait_s
        return (self.transfer_s / denom) if denom > 0 else 0.0

    @property
    def stall_fraction(self) -> float:
        """1 - overlap_fraction (the regress-gated, smaller-is-better
        form)."""
        return 1.0 - self.overlap_fraction

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d.pop("live_device_bytes", None)
        d["overlap_fraction"] = round(self.overlap_fraction, 4)
        d["stall_fraction"] = round(self.stall_fraction, 4)
        for k in ("h2d_s", "d2h_s", "compute_wait_s", "wall_s"):
            d[k] = round(d[k], 6)
        return d


#: the stats scope: solve_outofcore opens one so the factor and every
#: triangular sweep accumulate into a single record; bare factor/solve
#: calls open their own. The finished record is kept for callers
#: (last_stream_stats) and emitted as an ``outofcore`` obs event.
_ACTIVE: Optional[StreamStats] = None
_LAST: Optional[StreamStats] = None


def last_stream_stats() -> Optional[StreamStats]:
    """The most recent completed streamed operation's accounting."""
    return _LAST


@contextmanager
def _stats_scope(**fields):
    """Enter (or join) the active StreamStats scope."""
    global _ACTIVE, _LAST
    if _ACTIVE is not None:
        yield _ACTIVE
        return
    stats = StreamStats(**fields)
    _ACTIVE = stats
    t0 = time.perf_counter()
    try:
        yield stats
    finally:
        stats.wall_s += time.perf_counter() - t0
        _ACTIVE = None
        _LAST = stats
        # Feed the installed attribution plane (if any): the ledger's
        # overlap/stall accounting generalizes into the roofline's
        # ``outofcore`` engine row. No-op (one is-None read) when the
        # plane is off, and never allowed to break the solve.
        try:
            from gauss_tpu.obs import attr as _attr

            matrix = _attr.active()
            if matrix is not None:
                matrix.observe(
                    "outofcore_stream",
                    f"outofcore/n{stats.n}/p{stats.panel}",
                    stats.wall_s,
                    engine="outofcore",
                    requests=max(1, stats.solves),
                    bytes_accessed=stats.bytes_h2d + stats.bytes_d2h,
                    stall_frac=stats.stall_fraction,
                )
        except Exception:  # pragma: no cover — observability must not raise
            pass


@contextmanager
def _timed(stats: StreamStats, key: str, name: str, **attrs):
    """One accounted obs span: wall accumulates into ``stats.<key>`` AND
    lands on the recorder as a ``span`` event (zero-cost there when no
    recorder is active — the stats still measure)."""
    t0 = time.perf_counter()
    try:
        with obs.span(name, **attrs):
            yield
    finally:
        setattr(stats, key, getattr(stats, key) + time.perf_counter() - t0)


# -- admission + window sizing ----------------------------------------------


def host_memory_budget() -> int:
    """Usable host bytes (OS-reported physical memory with headroom, a
    conservative constant when unreadable). Monkeypatchable seam for the
    admission tests."""
    try:
        pages = os.sysconf("SC_PHYS_PAGES")
        psz = os.sysconf("SC_PAGE_SIZE")
        if pages > 0 and psz > 0:
            return int(0.8 * pages * psz)
    except (AttributeError, OSError, ValueError):
        pass
    return DEFAULT_HOST_BYTES


def _group_width(n: int, panel: Optional[int], chunk: Optional[int],
                 itemsize: int):
    from gauss_tpu.core import blocked

    panel = blocked._resolve_panel(n, panel, itemsize)
    if chunk is None:
        from gauss_tpu.tune import apply as _tune

        chunk = int(_tune.override("outofcore", n, "chunk")
                    or _tspace.OUTOFCORE_CHUNK_SEED)
    return panel, int(chunk)


def outofcore_window(n: int, panel: Optional[int] = None,
                     chunk: Optional[int] = None, itemsize: int = 4,
                     budget: Optional[int] = None) -> int:
    """The trailing tile width ``ct`` (a panel multiple): what fits the
    device-budget fraction next to the tallest (first) group block, with
    ``PIPELINE_TILE_BUFFERS`` copies live for the double-buffered
    pipeline. A tuned store (op ``outofcore``) short-circuits the formula
    per (n-bucket, dtype), exactly like the kernel tile widths."""
    from gauss_tpu.core import blocked
    from gauss_tpu.tune import apply as _tune

    panel, chunk = _group_width(n, panel, chunk, itemsize)
    npad = -(-n // panel) * panel
    tuned = _tune.override("outofcore", n, "ct")
    if tuned:
        ct = max(panel, (int(tuned) // panel) * panel)
    else:
        budget = (blocked.device_memory_budget() if budget is None
                  else int(budget))
        group_bytes = npad * chunk * panel * itemsize
        avail = OUTOFCORE_DEVICE_FRAC * budget - group_bytes
        ct = int(avail // (PIPELINE_TILE_BUFFERS * npad * itemsize))
        ct = max(panel, (ct // panel) * panel)
    ct = min(ct, npad)
    obs.record_vmem_estimate(
        "outofcore_window", n=n, panel=panel, chunk=chunk, ct=ct,
        itemsize=itemsize,
        bytes=npad * (chunk * panel + PIPELINE_TILE_BUFFERS * ct) * itemsize)
    return ct


def outofcore_fits(n: int, itemsize: int = 4,
                   host_budget: Optional[int] = None,
                   budget: Optional[int] = None,
                   panel: Optional[int] = None,
                   chunk: Optional[int] = None) -> bool:
    """Whether a host-streamed solve can ADMIT an (n, n) system: the host
    must hold ~``OUTOFCORE_HOST_FACTOR`` matrix copies (the in-place
    factor + the caller's original + transients), and the device-budget
    fraction must fit the first group block next to at least a
    minimum-width (one-panel) tile window. The HBM-shaped sibling of
    ``fused_fits_vmem`` — emitted as a ``vmem_estimate`` obs event like
    every other admission check."""
    from gauss_tpu.core import blocked

    panel, chunk = _group_width(n, panel, chunk, itemsize)
    npad = -(-n // panel) * panel
    host_budget = (host_memory_budget() if host_budget is None
                   else int(host_budget))
    dev_budget = (blocked.device_memory_budget() if budget is None
                  else int(budget))
    host_est = int(OUTOFCORE_HOST_FACTOR * npad * npad * itemsize)
    dev_est = npad * (chunk * panel
                      + PIPELINE_TILE_BUFFERS * panel) * itemsize
    fits = (host_est <= host_budget
            and dev_est <= OUTOFCORE_DEVICE_FRAC * dev_budget)
    obs.record_vmem_estimate(
        "outofcore_hbm", n=n, panel=panel, chunk=chunk, itemsize=itemsize,
        bytes=dev_est, budget=dev_budget, host_bytes=host_est,
        host_budget=host_budget, fits=fits)
    return fits


# -- compiled steps (cached on their statics) --------------------------------


@functools.lru_cache(maxsize=None)
def _group_step(panel: int, gpanels: int, panel_impl: str,
                gemm_precision: str, abft: bool):
    """The compiled per-group step — the checkpoint module's donated
    ``_factor_group`` jit verbatim for the plain form; the same function
    with the checksum rider threaded for ``abft=True``. Rectangular
    (gh, w) carry, ``g0=0``: the shared-step contract."""
    from gauss_tpu.resilience.checkpoint import _group_step_jit

    if not abft:
        return _group_step_jit(panel, gpanels, panel_impl, gemm_precision)
    import jax

    from gauss_tpu.core import blocked
    from gauss_tpu.core.matmul import resolve_precision

    @partial(jax.jit, donate_argnums=(0, 1, 2, 3))
    def step(m, perm, min_piv, crow):
        return blocked._factor_group(
            m, perm, min_piv, 0, panel, gpanels, panel_impl,
            resolve_precision(gemm_precision), crow=crow)

    return step


@functools.lru_cache(maxsize=None)
def _tile_step(panel: int, gpanels: int, gemm_precision: str, abft: bool):
    """The compiled trailing-tile update: the EXACT right-of-group math of
    ``_factor_group`` (permute rows by the group permutation, blockwise
    ``U12 = L_g^-1 top`` through the stored diagonal-block inverses, then
    ``A22_tile -= L21 @ U12``) restricted to one (gh, ct) column tile.
    The tile buffer is donated — the pipeline's in-place update."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from gauss_tpu.core import blocked
    from gauss_tpu.core.matmul import resolve_precision

    prec = resolve_precision(gemm_precision)
    w = gpanels * panel

    def _update(grp, linvs, gperm, tile):
        dtype = tile.dtype
        ct = tile.shape[1]
        tp = tile[gperm]
        top = tp[:w]

        def usolve(x, i):
            rows = lax.dynamic_slice(grp, (i * panel, 0), (panel, w))
            r = lax.dynamic_slice(top, (i * panel, 0), (panel, ct))
            r = r - blocked._gdot(rows, x, prec, dtype)
            xi = blocked._gdot(linvs[i], r, prec, dtype)
            return lax.dynamic_update_slice(x, xi, (i * panel, 0)), i

        u12, _ = lax.scan(usolve, jnp.zeros((w, ct), dtype),
                          jnp.arange(gpanels))
        fresh = tp[w:] - blocked._gdot(grp[w:], u12, prec, dtype)
        return u12, fresh

    if not abft:
        @partial(jax.jit, donate_argnums=(3,))
        def step(grp, linvs, gperm, tile):
            u12, fresh = _update(grp, linvs, gperm, tile)
            return jnp.concatenate([u12, fresh], axis=0)

        return step

    @partial(jax.jit, donate_argnums=(3, 4))
    def step_abft(grp, linvs, gperm, tile, ctile, lc):
        u12, fresh = _update(grp, linvs, gperm, tile)
        # The checksum row's exact rider of the tile GEMM (cf.
        # _factor_group's crow update), then the trailing column-sum
        # identity over this tile's live rows.
        cnew = ctile - jnp.dot(lc, u12, precision=prec)
        diff = jnp.sum(fresh, axis=0) - cnew[0]
        diff = jnp.where(jnp.isnan(diff), jnp.inf, jnp.abs(diff))
        return (jnp.concatenate([u12, fresh], axis=0), cnew,
                jnp.max(diff), jnp.argmax(diff))

    return step_abft


@functools.lru_cache(maxsize=None)
def _lc_step(panel: int, gpanels: int, gemm_precision: str):
    """``Lc = c1 @ Ugroup^-1`` for the group's checksum slice — shared
    checksum math (core.blocked._csum_group_solve), jitted once per group
    shape."""
    import jax

    from gauss_tpu.core import blocked
    from gauss_tpu.core.matmul import resolve_precision

    prec = resolve_precision(gemm_precision)

    @jax.jit
    def f(crow_grp, grp, uinvs):
        return blocked._csum_group_solve(crow_grp, grp, uinvs, gpanels,
                                         panel, prec)

    return f


@functools.lru_cache(maxsize=None)
def _subst_step(lower: bool):
    """One streamed block-row substitution step — the body of
    ``core.blocked._blockwise_substitution_scan`` with the factor's block
    row ``strip`` streamed in instead of sliced from a device-resident
    matrix. ``x`` is donated (rebound every step)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    prec = lax.Precision.HIGHEST

    @partial(jax.jit, donate_argnums=(3,))
    def step(strip, inv_i, rhs, x, i):
        panel = strip.shape[0]
        zero = i * 0  # index literal in i's dtype (x64-safe)
        r = lax.dynamic_slice(rhs, (i * panel, zero),
                              (panel, rhs.shape[1]))
        r = r - jnp.dot(strip, x, precision=prec)
        xi = jnp.dot(inv_i, r, precision=prec)
        return lax.dynamic_update_slice(x, xi, (i * panel, zero))

    return step


# -- the streamed factorization ----------------------------------------------


def _stage_host(a_np: np.ndarray, npad: int, np_dtype) -> np.ndarray:
    """The host working copy: _pad_to_panel's identity-padded layout,
    built with numpy so the full matrix never touches the device."""
    n = a_np.shape[0]
    m = np.zeros((npad, npad), dtype=np_dtype)
    m[:n, :n] = a_np
    if npad > n:
        idx = np.arange(n, npad)
        m[idx, idx] = 1.0
    return m


def lu_factor_outofcore(a, *, panel: Optional[int] = None,
                        chunk: Optional[int] = None,
                        ct: Optional[int] = None,
                        panel_impl: str = "auto",
                        gemm_precision: str = "highest",
                        dtype=None, abft: bool = False,
                        checkpoint_path=None,
                        checkpoint_every_groups: int = 1,
                        resume: bool = True,
                        keep: bool = False) -> OutOfCoreLU:
    """Host-streamed blocked LU with partial pivoting.

    Same math as ``lu_factor_blocked_chunked`` — the per-group step is the
    shared ``_factor_group`` — with the matrix held and updated in host
    memory and only the active group + a ``ct``-wide tile window device-
    resident. ``ct`` defaults to :func:`outofcore_window`; ``chunk``
    (panels per group) consults the tuned store (op ``outofcore``).

    ``abft=True`` verifies the checksum identities per group and per tile
    (typed :class:`SDCDetectedError` on mismatch, ``abft_err`` on the
    result otherwise). ``checkpoint_path`` saves the host carry every
    ``checkpoint_every_groups`` groups through the resilience.checkpoint
    idiom (atomic, previous generation kept, digest-guarded resume).
    """
    import jax
    import jax.numpy as jnp

    from gauss_tpu.core import blocked
    from gauss_tpu.core.matmul import resolve_precision

    a_np = np.asarray(a)
    n = a_np.shape[0]
    if a_np.shape != (n, n):
        raise ValueError(f"expected square matrix, got {a_np.shape}")
    dtype = jnp.dtype(jnp.float32 if dtype is None else dtype)
    itemsize = dtype.itemsize
    blocked._check_lowered_support(dtype, resolve_precision(gemm_precision),
                                   abft)
    panel, chunk = _group_width(n, panel, chunk, itemsize)
    if chunk < 1:
        raise ValueError(f"chunk must be >= 1, got {chunk}")
    npad = -(-n // panel) * panel
    nb = npad // panel
    if ct is None:
        ct = outofcore_window(n, panel, chunk, itemsize)
    ct = max(panel, (int(ct) // panel) * panel)
    np_dtype = np.dtype(dtype)

    with _stats_scope(n=n, panel=panel, chunk=chunk, ct=ct) as stats:
        stats.n, stats.npad = n, npad
        stats.panel, stats.chunk, stats.ct = panel, chunk, ct
        m_host = _stage_host(a_np, npad, np_dtype)
        perm_host = np.arange(npad, dtype=np.int64)
        min_piv = jnp.asarray(jnp.inf, dtype)
        stats.add_dev(min_piv.nbytes)
        linv_parts, uinv_parts = [], []
        abft_errs: list = []
        crow_host = tol = None
        if abft:
            from gauss_tpu.resilience import abft as _abft

            crow_host = m_host.sum(axis=0, dtype=np_dtype, keepdims=True)
            tol = _abft.default_tol(npad, np_dtype,
                                    float(np.abs(crow_host).max()))

        # -- checkpoint/resume (the resilience.checkpoint carry) ----------
        start_group = 0
        ckpt = None
        if checkpoint_path is not None:
            from gauss_tpu.resilience import checkpoint as ckpt

            meta = {"schema": ckpt.SCHEMA, "n": n, "panel": panel,
                    "chunk": chunk, "panel_impl": panel_impl,
                    "gemm_precision": gemm_precision, "dtype": str(dtype),
                    "digest": ckpt._digest(a_np), "outofcore": True,
                    "abft": bool(abft)}
            state = (ckpt._load_resume_state(os.fspath(checkpoint_path),
                                             meta) if resume else None)
            if state is not None:
                m_host = np.array(state["m"], dtype=np_dtype)
                perm_host = np.array(state["perm"], dtype=np.int64)
                min_piv = jnp.asarray(state["min_piv"].item(), dtype)
                if state["linvs"].size:
                    linv_parts = [state["linvs"]]
                    uinv_parts = [state["uinvs"]]
                start_group = int(state["meta"]["next_group"])
                if abft:
                    # The checksum row is reconstructible from the carry:
                    # retired/updated columns' sums are invariants of the
                    # data actually on disk — recompute from scratch over
                    # the RESUMED matrix region still to be factored.
                    crow_host = _resume_crow(m_host, perm_host, a_np,
                                             np_dtype, start_group * panel)
                obs.counter("outofcore.resumes")
                obs.emit("outofcore", event="resume",
                         next_group=start_group)

        groups_done = 0
        for g0 in range(start_group, nb, chunk):
            _inject.maybe_kill("outofcore.group")
            gs = g0 * panel
            gh = npad - gs
            gpanels = min(chunk, nb - g0)
            w = gpanels * panel

            # H2D the group's own column block (+ the checksum slice).
            with _timed(stats, "h2d_s", "outofcore.h2d", what="group",
                        group=g0, bytes=gh * w * itemsize):
                grp_dev = jax.device_put(
                    np.ascontiguousarray(m_host[gs:, gs:gs + w]))
                gperm_dev = jax.device_put(np.arange(gh, dtype=np.int32))
                jax.block_until_ready(grp_dev)
                stats.add_dev(grp_dev.nbytes + gperm_dev.nbytes)
                stats.bytes_h2d += grp_dev.nbytes
                crow_dev = None
                if abft:
                    crow_dev = jax.device_put(
                        np.ascontiguousarray(crow_host[:, gs:gs + w]))
                    stats.add_dev(crow_dev.nbytes)
                    stats.bytes_h2d += crow_dev.nbytes

            # The shared per-group step (async dispatch: the tile
            # pipeline's first prefetches overlap the factor itself).
            step = _group_step(panel, gpanels, panel_impl, gemm_precision,
                               abft)
            in_bytes = (grp_dev.nbytes + gperm_dev.nbytes + min_piv.nbytes
                        + (crow_dev.nbytes if crow_dev is not None else 0))
            gerr = None
            if abft:
                (grp_dev, gperm_dev, min_piv, linvs_dev, uinvs_dev,
                 crow_dev, gerr, _gcol) = step(grp_dev, gperm_dev, min_piv,
                                               crow_dev)
            else:
                grp_dev, gperm_dev, min_piv, linvs_dev, uinvs_dev = step(
                    grp_dev, gperm_dev, min_piv, g0=0)
            stats.sub_dev(in_bytes)
            stats.add_dev(grp_dev.nbytes + gperm_dev.nbytes + min_piv.nbytes
                          + linvs_dev.nbytes + uinvs_dev.nbytes
                          + (crow_dev.nbytes if crow_dev is not None else 0))

            # -- the double-buffered trailing-tile pipeline ----------------
            tile_errs = _stream_group_tiles(
                stats, m_host, crow_host, gs, gh, w, ct, panel, gpanels,
                gemm_precision, abft, grp_dev, linvs_dev, uinvs_dev,
                gperm_dev, crow_dev, itemsize)

            # Drain the group's own results back to host.
            with _timed(stats, "compute_wait_s", "outofcore.compute_wait",
                        what="group", group=g0):
                jax.block_until_ready(grp_dev)
            with _timed(stats, "d2h_s", "outofcore.d2h", what="group",
                        group=g0, bytes=grp_dev.nbytes):
                gperm_host = np.asarray(gperm_dev)
                m_host[gs:, gs:gs + w] = np.asarray(grp_dev)
                linv_parts.append(np.asarray(linvs_dev))
                uinv_parts.append(np.asarray(uinvs_dev))
                stats.bytes_d2h += grp_dev.nbytes
            # Realign the already-factored L columns (left of the group)
            # with the group's composed permutation — the host-side half
            # of _factor_group's realignment (right columns were permuted
            # on device inside each tile update).
            if gs:
                m_host[gs:, :gs] = np.take(m_host[gs:, :gs], gperm_host,
                                           axis=0)
            perm_host[gs:] = perm_host[gs:][gperm_host]

            if abft:
                gerr_v = float(np.asarray(gerr))
                abft_errs.append(max(gerr_v, max(tile_errs, default=0.0)))
                if abft_errs[-1] > tol:
                    obs.counter("outofcore.sdc_detected")
                    obs.emit("outofcore", event="sdc_detected", group=g0,
                             err=abft_errs[-1], tol=tol)
                    raise SDCDetectedError(
                        f"ABFT checksum mismatch {abft_errs[-1]:.3e} "
                        f"(tol {tol:.3e}) in panel group {g0} of the "
                        f"streamed factorization", group=g0,
                        err=abft_errs[-1])
                crow_host[:, gs:gs + w] = np.asarray(crow_dev)

            for buf in (grp_dev, gperm_dev, linvs_dev, uinvs_dev,
                        crow_dev):
                if buf is not None:
                    stats.sub_dev(buf.nbytes)
                    buf.delete()
            groups_done += 1
            stats.groups += 1
            obs.counter("outofcore.groups")

            if (ckpt is not None and groups_done % checkpoint_every_groups
                    == 0 and g0 + chunk < nb):
                mp_host = np.asarray(min_piv)
                nbytes = ckpt.save_state(
                    checkpoint_path,
                    meta={**meta, "next_group": g0 + chunk,
                          "panels_done": g0 + chunk},
                    m=m_host, perm=perm_host, min_piv=mp_host,
                    linvs=np.concatenate(linv_parts),
                    uinvs=np.concatenate(uinv_parts))
                obs.counter("outofcore.checkpoint_saves")
                obs.emit("outofcore", event="checkpoint",
                         next_group=g0 + chunk, bytes=int(nbytes))

        if ckpt is not None and not keep:
            for stale in (os.fspath(checkpoint_path),
                          ckpt.prev_path(checkpoint_path)):
                try:
                    os.unlink(stale)
                except OSError:
                    pass

        mp = float(np.asarray(min_piv))
        stats.sub_dev(min_piv.nbytes)
        obs.emit("outofcore", event="factor_complete", **stats.to_dict())
        return OutOfCoreLU(
            m=m_host, perm=perm_host, min_abs_pivot=mp,
            linv=np.concatenate(linv_parts),
            uinv=np.concatenate(uinv_parts), n=n, panel=panel,
            abft_err=(np.asarray(abft_errs, dtype=np.float64)
                      if abft else None))


def _resume_crow(m_host, perm_host, a_np, np_dtype, gs):
    """Rebuild the checksum row after a checkpoint resume: retired columns
    keep their ORIGINAL sums (only used for provenance), active trailing
    columns carry the sums of the current (partially updated) trailing
    block — exactly what the per-tile identity checks verify against."""
    npad = m_host.shape[0]
    crow = np.zeros((1, npad), dtype=np_dtype)
    n = a_np.shape[0]
    crow[0, :n] = np.asarray(a_np, dtype=np_dtype).sum(axis=0)
    crow[0, n:] = 1.0
    if gs:
        crow[0, gs:] = m_host[gs:, gs:].sum(axis=0, dtype=np_dtype)
    return crow


def _stream_group_tiles(stats, m_host, crow_host, gs, gh, w, ct, panel,
                        gpanels, gemm_precision, abft, grp_dev, linvs_dev,
                        uinvs_dev, gperm_dev, crow_dev, itemsize):
    """The per-group tile pipeline: prefetch tile t+1 while tile t's
    compiled update runs, drain tile t-1's result while tile t computes.
    Returns the per-tile checksum mismatches (empty without abft)."""
    import jax

    npad = m_host.shape[0]
    cols = [(c0, min(c0 + ct, npad))
            for c0 in range(gs + w, npad, ct)]
    if not cols:
        return []
    tstep = _tile_step(panel, gpanels, gemm_precision, abft)
    lc_dev = None
    if abft:
        lc_dev = _lc_step(panel, gpanels, gemm_precision)(
            crow_dev, grp_dev, uinvs_dev)
        stats.add_dev(lc_dev.nbytes)
    errs: list = []

    def _h2d(c0, c1):
        with _timed(stats, "h2d_s", "outofcore.h2d", what="tile",
                    bytes=gh * (c1 - c0) * itemsize):
            blk = np.ascontiguousarray(m_host[gs:, c0:c1])
            # Fault hook "outofcore.tile": corrupt the tile on its way to
            # the device — the data-corruption surface the ABFT rider's
            # per-tile identity is there to catch.
            if _inject.enabled():
                blk = np.asarray(_inject.corrupt_operand("outofcore.tile",
                                                         blk))
            tdev = jax.device_put(blk)
            cdev = None
            if abft:
                cdev = jax.device_put(
                    np.ascontiguousarray(crow_host[:, c0:c1]))
                stats.add_dev(cdev.nbytes)
                stats.bytes_h2d += cdev.nbytes
            jax.block_until_ready(tdev)
            stats.add_dev(tdev.nbytes)
            stats.bytes_h2d += tdev.nbytes
        return tdev, cdev

    pending = _h2d(*cols[0])
    prev = None  # (out_dev, cout_dev, err_dev, (c0, c1))
    for idx, (c0, c1) in enumerate(cols):
        tdev, cdev = pending
        # Dispatch this tile's update (async), donating the input buffers.
        in_bytes = tdev.nbytes + (cdev.nbytes if cdev is not None else 0)
        if abft:
            out, cout, err, _col = tstep(grp_dev, linvs_dev, gperm_dev,
                                         tdev, cdev, lc_dev)
        else:
            out = tstep(grp_dev, linvs_dev, gperm_dev, tdev)
            cout = err = None
        stats.sub_dev(in_bytes)
        stats.add_dev(out.nbytes
                      + (cout.nbytes if cout is not None else 0))
        # Prefetch the NEXT tile while this one computes.
        pending = _h2d(*cols[idx + 1]) if idx + 1 < len(cols) else None
        # Drain the PREVIOUS tile's result while this one computes.
        if prev is not None:
            _drain_tile(stats, m_host, crow_host, gs, prev, errs)
        prev = (out, cout, err, (c0, c1))
        stats.tiles += 1
        obs.counter("outofcore.tiles")
    _drain_tile(stats, m_host, crow_host, gs, prev, errs)
    if lc_dev is not None:
        stats.sub_dev(lc_dev.nbytes)
        lc_dev.delete()
    return errs


def _drain_tile(stats, m_host, crow_host, gs, prev, errs):
    import jax

    out, cout, err, (c0, c1) = prev
    with _timed(stats, "compute_wait_s", "outofcore.compute_wait",
                what="tile"):
        jax.block_until_ready(out)
    with _timed(stats, "d2h_s", "outofcore.d2h", what="tile",
                bytes=out.nbytes):
        m_host[gs:, c0:c1] = np.asarray(out)
        stats.bytes_d2h += out.nbytes
        if cout is not None:
            crow_host[:, c0:c1] = np.asarray(cout)
            errs.append(float(np.asarray(err)))
    stats.sub_dev(out.nbytes + (cout.nbytes if cout is not None else 0))
    out.delete()
    if cout is not None:
        cout.delete()


# -- streamed triangular solves ---------------------------------------------


def lu_solve_outofcore(fac: OutOfCoreLU, b) -> np.ndarray:
    """Solve against a host-resident streamed factor: permute, then the
    two blockwise substitutions of ``core.blocked
    ._blockwise_substitution_scan`` with the factor's (panel, npad) block
    rows STREAMED through the same double-buffered h2d pipeline (the
    solution and diagonal-block inverses stay device-resident — they are
    O(n * k) and O(nb * panel^2)). Returns float64, shaped like ``b``."""
    import jax

    from gauss_tpu.core import blocked

    m_host, perm = fac.m, fac.perm
    npad = m_host.shape[0]
    nb, panel = fac.linv.shape[0], fac.panel
    cdt = np.dtype(blocked.accum_dtype(m_host.dtype))
    b = np.asarray(b)
    was_vector = b.ndim == 1
    b2 = b[:, None] if was_vector else b
    n, k = b2.shape
    bp = np.zeros((npad, k), dtype=cdt)
    bp[:n] = b2
    bp = bp[perm]

    with _stats_scope(n=fac.n, panel=panel) as stats:
        stats.solves += 1
        rhs = jax.device_put(bp)
        linv_dev = jax.device_put(fac.linv)
        uinv_dev = jax.device_put(fac.uinv)
        x = jax.device_put(np.zeros((npad, k), dtype=cdt))
        for buf in (rhs, linv_dev, uinv_dev, x):
            stats.add_dev(buf.nbytes)
        x = _stream_substitution(stats, m_host, linv_dev, rhs, x, panel,
                                 nb, lower=True)
        # Backward sweep: the forward result becomes the rhs.
        stats.sub_dev(rhs.nbytes)
        rhs.delete()
        rhs = x
        x = jax.device_put(np.zeros((npad, k), dtype=cdt))
        stats.add_dev(x.nbytes)
        x = _stream_substitution(stats, m_host, uinv_dev, rhs, x, panel,
                                 nb, lower=False)
        with _timed(stats, "d2h_s", "outofcore.d2h", what="solution",
                    bytes=x.nbytes):
            out = np.asarray(x, dtype=np.float64)[:n]
            stats.bytes_d2h += x.nbytes
        for buf in (rhs, linv_dev, uinv_dev, x):
            stats.sub_dev(buf.nbytes)
            buf.delete()
    return out[:, 0] if was_vector else out


def _stream_substitution(stats, m_host, invs_dev, rhs, x, panel, nb,
                         lower: bool):
    """One streamed substitution sweep (same per-block math as the in-core
    scan; block rows arrive from host, prefetched one ahead)."""
    import jax
    import jax.numpy as jnp

    step = _subst_step(lower)
    order = list(range(nb)) if lower else list(range(nb - 1, -1, -1))
    itemsize = m_host.dtype.itemsize
    npad = m_host.shape[0]

    def _h2d(i):
        with _timed(stats, "h2d_s", "outofcore.h2d", what="strip",
                    bytes=panel * npad * itemsize):
            s = jax.device_put(
                np.ascontiguousarray(m_host[i * panel:(i + 1) * panel]))
            jax.block_until_ready(s)
            stats.add_dev(s.nbytes)
            stats.bytes_h2d += s.nbytes
        return s

    pending = _h2d(order[0])
    prev_strip = None
    for pos, i in enumerate(order):
        strip = pending
        x = step(strip, invs_dev[i], rhs, x, jnp.int32(i))
        pending = _h2d(order[pos + 1]) if pos + 1 < len(order) else None
        if prev_strip is not None:
            stats.sub_dev(prev_strip.nbytes)
            prev_strip.delete()
        prev_strip = strip
    with _timed(stats, "compute_wait_s", "outofcore.compute_wait",
                what="substitution"):
        jax.block_until_ready(x)
    if prev_strip is not None:
        stats.sub_dev(prev_strip.nbytes)
        prev_strip.delete()
    return x


# -- the refined giant solve -------------------------------------------------


def _residual_chunked(a_np: np.ndarray, x: np.ndarray,
                      b64: np.ndarray) -> np.ndarray:
    """``b - A @ x`` in f64 without materializing a full f64 copy of a
    giant operand: row blocks are upcast on the fly."""
    r = np.empty_like(b64)
    for r0 in range(0, a_np.shape[0], RESIDUAL_ROW_BLOCK):
        r1 = min(r0 + RESIDUAL_ROW_BLOCK, a_np.shape[0])
        blk = a_np[r0:r1]
        if blk.dtype != np.float64:
            blk = blk.astype(np.float64)
        r[r0:r1] = b64[r0:r1] - blk @ x
    return r


def solve_outofcore(a, b, *, panel: Optional[int] = None,
                    chunk: Optional[int] = None, ct: Optional[int] = None,
                    iters: int = 3, tol: float = 0.0, dtype=None,
                    abft: bool = False, checkpoint_path=None,
                    checkpoint_every_groups: int = 1,
                    gemm_precision: str = "highest") -> np.ndarray:
    """Solve ``a @ x = b`` for systems beyond device memory: streamed
    factorization + streamed triangular solves + host-f64 iterative
    refinement (chunked residuals — no full f64 operand copy). Returns x
    float64, shaped like ``b``. One :class:`StreamStats` record covers the
    whole solve (``last_stream_stats()``; also emitted as an
    ``outofcore`` obs event)."""
    a_np = np.asarray(a)
    n = a_np.shape[0]
    b64 = np.asarray(b, dtype=np.float64)
    with _stats_scope(n=n) as stats:
        with obs.span("outofcore.solve", n=n):
            fac = lu_factor_outofcore(
                a_np, panel=panel, chunk=chunk, ct=ct, dtype=dtype,
                abft=abft, checkpoint_path=checkpoint_path,
                checkpoint_every_groups=checkpoint_every_groups,
                gemm_precision=gemm_precision)
            x = lu_solve_outofcore(fac, b64)
            x2 = x[:, None] if x.ndim == 1 else x
            b2 = b64[:, None] if b64.ndim == 1 else b64
            tol_eff = (tol * min(1.0, float(np.linalg.norm(b64)))
                       if tol > 0.0 else 0.0)
            for _ in range(iters):
                r = _residual_chunked(a_np, x2, b2)
                if tol > 0.0 and float(np.linalg.norm(r)) <= tol_eff:
                    break
                d = lu_solve_outofcore(fac, r)
                x2 = x2 + (d[:, None] if d.ndim == 1 else d)
            x = x2[:, 0] if b64.ndim == 1 else x2
        obs.emit("outofcore", event="solve_complete", **stats.to_dict())
    return x
