"""Correctness checks, unified from the reference's three verification ideas.

1. Manufactured-solution max relative error — the external programs' always-on
   oracle (reference gauss_external_input.c:304-315): ``max |x - x_true| / |x_true|``.
2. VERIFY pattern check — the internal programs' compile-time-gated check that
   the solution is (-0.5, 0, ..., 0, 0.5) (gauss_internal_input.c:17,54-57).
   Here it is a runtime function, not a recompile.
3. Elementwise epsilon comparison — the CUDA ``verify()`` with EPSILON=1e-4
   (cuda_matmul.cu:13,61-72), which the reference defines but never calls;
   we actually wire it into tests and the CLI.

Plus the residual norm ``||Ax - b||`` used as the BASELINE.json acceptance bar.
All checks compute in float64 on host so they are meaningful for f32 device
results.
"""

from __future__ import annotations

import numpy as np

EPSILON = 1e-4  # reference cuda_matmul.cu:13


def max_rel_error(x, x_true) -> float:
    """max_i |x_i - x_true_i| / |x_true_i| (external-input 'Error:' line)."""
    x = np.asarray(x, dtype=np.float64)
    x_true = np.asarray(x_true, dtype=np.float64)
    denom = np.abs(x_true)
    denom = np.where(denom == 0.0, 1.0, denom)
    return float(np.max(np.abs(x - x_true) / denom))


def residual_norm(a, x, b, relative: bool = False) -> float:
    """||A x - b||_2, optionally scaled by ||b||_2."""
    a = np.asarray(a, dtype=np.float64)
    x = np.asarray(x, dtype=np.float64)
    b = np.asarray(b, dtype=np.float64)
    r = float(np.linalg.norm(a @ x - b))
    if relative:
        nb = float(np.linalg.norm(b))
        return r / nb if nb else r
    return r


def elementwise_match(x, y, epsilon: float = EPSILON) -> bool:
    """CUDA verify() semantics: no element differs by more than epsilon."""
    x = np.asarray(x, dtype=np.float64)
    y = np.asarray(y, dtype=np.float64)
    return bool(np.all(np.abs(x - y) <= epsilon))


def internal_pattern_ok(x, atol: float = 1e-6) -> bool:
    """The internal-input VERIFY oracle: x == (-0.5, 0, ..., 0, 0.5)."""
    x = np.asarray(x, dtype=np.float64)
    n = x.shape[0]
    expected = np.zeros(n)
    expected[0], expected[-1] = -0.5, 0.5
    return bool(np.all(np.abs(x - expected) <= atol))
