"""Verification machinery (reference component C12, SURVEY.md §2)."""

from gauss_tpu.verify.checks import (  # noqa: F401
    max_rel_error,
    residual_norm,
    elementwise_match,
    internal_pattern_ok,
)
