"""Shared utilities: timing spans, padding helpers."""

from gauss_tpu.utils.timing import timed, timed_fetch  # noqa: F401
