"""Shared utilities: timing spans, padding helpers."""

from gauss_tpu.utils.timing import Timer, timed, timed_fetch  # noqa: F401
