"""Environment helpers usable BEFORE any jax import (no jax dependency)."""

from __future__ import annotations

import os

FORCE_FLAG = "xla_force_host_platform_device_count"


def force_host_device_count(n: int) -> bool:
    """Ensure XLA_FLAGS forces >= n virtual host devices.

    Returns True if the flag was set (or already requested >= n); False if a
    pre-existing flag requests FEWER devices — callers should surface that,
    because the earlier value wins once the backend initializes. Must run
    before the first jax backend initialization to have any effect.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    if FORCE_FLAG in flags:
        try:
            current = int(flags.split(FORCE_FLAG + "=")[1].split()[0])
        except (IndexError, ValueError):
            return False
        return current >= n
    os.environ["XLA_FLAGS"] = (flags + f" --{FORCE_FLAG}={n}").strip()
    return True


def honor_jax_platforms() -> bool:
    """Make JAX_PLATFORMS effective even where a sitecustomize re-pins a
    device platform AFTER env processing (this image's tunneled-TPU setup
    does): the jax.config update takes precedence over the pin. No-op (and
    no jax import) when the variable is unset. Call before any
    jax.devices() use; returns True if a platform was applied. Single
    source for tests/conftest.py-style pinning in scripts and examples."""
    plat = os.environ.get("JAX_PLATFORMS")
    if not plat:
        return False
    import jax

    jax.config.update("jax_platforms", plat)
    return True
