"""Tracing / profiling subsystem (SURVEY.md §5).

The reference instruments with ``gettimeofday`` spans around each phase
(reference Pthreads/Version-1/gauss_internal_input.c:278-290) and analyses
hotspots offline with gprof (Pthreads/report.pdf "Profiling of the
Algorithm": computeGauss/subtractElim at 99.93-100%). The TPU-native
equivalents here:

- :class:`PhaseTimer` — named wall-clock spans with a gprof-style percentage
  report, device-completion bounded when given JAX values;
- :func:`trace` — a ``jax.profiler.trace`` context manager producing XLA/TPU
  traces viewable in TensorBoard/Perfetto (the gprof analog for compiled
  device code), no-op when given no directory so CLI flags can pass None
  straight through.
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, Optional


class PhaseTimer:
    """Accumulates named wall-clock spans; renders a gprof-like table.

    Every closed phase also reports into the telemetry subsystem
    (``gauss_tpu.obs``) as a span event when a recorder is active — the
    table stays the interactive surface, the JSONL stream the persistent
    one. Pass ``emit=False`` to keep a timer table-only (e.g. a timer
    replaying durations that were already recorded as spans).

    >>> pt = PhaseTimer()
    >>> with pt.phase("init"): ...
    >>> with pt.phase("computeGauss"): ...
    >>> print(pt.report())
    """

    def __init__(self, emit: bool = True) -> None:
        self.seconds: Dict[str, float] = {}
        self.emit = emit

    @contextlib.contextmanager
    def phase(self, name: str, block_on=None):
        """Time a phase. ``block_on``: optional JAX value (or pytree) to
        ``block_until_ready`` before closing the span, so asynchronous
        dispatch does not leak one phase's device time into the next."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            if block_on is not None:
                import jax

                jax.block_until_ready(block_on)
            dur = time.perf_counter() - t0
            self.seconds[name] = self.seconds.get(name, 0.0) + dur
            if self.emit:
                from gauss_tpu.obs import spans as _obs_spans

                _obs_spans.record_span(name, dur)

    @property
    def total(self) -> float:
        return sum(self.seconds.values())

    def report(self) -> str:
        """gprof-flavoured flat profile: % time, seconds, phase."""
        total = self.total or 1.0
        lines = ["  %time   seconds  phase"]
        for name, s in sorted(self.seconds.items(), key=lambda kv: -kv[1]):
            lines.append(f"  {100.0 * s / total:5.1f}  {s:9.6f}  {name}")
        return "\n".join(lines)


@contextlib.contextmanager
def trace(logdir: Optional[str]):
    """Capture a device trace into ``logdir`` (None -> no-op).

    Wraps ``jax.profiler.trace``; the output is the compiled-code hotspot
    view (XLA fusions, Pallas kernels, collectives) that gprof provided for
    the reference's C hot loops.
    """
    if not logdir:
        yield
        return
    import jax

    with jax.profiler.trace(str(logdir)):
        yield
