"""JAX version compatibility shims (single source; no jax import at load).

The engines target the modern public APIs; some images pin older jax
releases where the same functionality lives under ``jax.experimental`` or
takes different keyword names. Every shim resolves at call time so the repo
imports cleanly regardless of which jax is installed.
"""

from __future__ import annotations


def shard_map(f, *, mesh, in_specs, out_specs):
    """``jax.shard_map`` where it exists (jax >= 0.6), else the
    ``jax.experimental.shard_map`` form.

    On the experimental form, replication checking is disabled: the engines
    lean on varying-manual-axes inference (see core.blocked._panel_factor_jax
    carry inits), which the old ``check_rep`` analysis predates — it rejects
    valid scan carries whose replication type is refined inside the loop
    ("Scan carry input and output got mismatched replication types"). The
    modern path keeps full checking.
    """
    import jax

    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_rep=False)


def pcast_varying(x, axes):
    """Mark a replicated value as varying over ``axes`` inside shard_map.

    ``lax.pcast`` (newest) > ``lax.pvary`` (jax >= 0.6) > identity: on jax
    releases that predate varying-manual-axes tracking the shim's
    ``check_rep=False`` path performs no replication analysis, so the cast
    has nothing to record and the value passes through unchanged.
    """
    from jax import lax

    if hasattr(lax, "pcast"):
        return lax.pcast(x, axes, to="varying")
    if hasattr(lax, "pvary"):
        return lax.pvary(x, axes)
    return x
