"""Wall-clock timing spans with device-completion semantics.

The reference times with ``gettimeofday`` around the compute phase
(reference Pthreads/Version-1/gauss_internal_input.c:278-290) and
``clock_gettime`` per engine in CUDA (cuda_matmul.cu:135-180). On TPU,
dispatch is asynchronous, so an honest equivalent span must end with
``jax.block_until_ready`` on the results — every timer here does.
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List

import jax


@dataclass
class Timer:
    """Accumulates named wall-clock spans; used by the CLI and bench harness."""

    spans: Dict[str, List[float]] = field(default_factory=dict)

    @contextmanager
    def span(self, name: str, block_on: Any = None):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            if block_on is not None:
                jax.block_until_ready(block_on)
            self.spans.setdefault(name, []).append(time.perf_counter() - t0)

    def total(self, name: str) -> float:
        return sum(self.spans.get(name, []))

    def best(self, name: str) -> float:
        return min(self.spans[name])


def timed(fn: Callable, *args, warmup: int = 1, iters: int = 1, **kwargs):
    """Run ``fn`` with compile warmup; return (best_seconds, last_result).

    ``block_until_ready`` bounds every span so the number is device wall-clock,
    not dispatch time.
    """
    result = None
    for _ in range(max(warmup, 0)):
        result = jax.block_until_ready(fn(*args, **kwargs))
    best = float("inf")
    for _ in range(max(iters, 1)):
        t0 = time.perf_counter()
        result = jax.block_until_ready(fn(*args, **kwargs))
        best = min(best, time.perf_counter() - t0)
    return best, result
