"""Wall-clock timing with device-completion semantics.

The reference times with ``gettimeofday`` around the compute phase
(reference Pthreads/Version-1/gauss_internal_input.c:278-290) and
``clock_gettime`` per engine in CUDA (cuda_matmul.cu:135-180). On TPU,
dispatch is asynchronous, so an honest equivalent span must end with device
completion: :func:`timed` uses ``jax.block_until_ready``; :func:`timed_fetch`
(used by the CLI drivers and bench.py) forces a host fetch, which is the only
completion signal that holds on tunneled platforms.
"""

from __future__ import annotations

import time
from typing import Callable

import jax


def timed(fn: Callable, *args, warmup: int = 1, reps: int = 1, **kwargs):
    """Run ``fn`` with compile warmup; return (best_seconds, last_result).

    ``block_until_ready`` bounds every span so the number is device wall-clock,
    not dispatch time. Caveat: on tunneled device platforms (e.g. 'axon')
    block_until_ready has been observed to return early — use
    :func:`timed_fetch` there, which forces a device-to-host transfer.
    """
    result = None
    for _ in range(max(warmup, 0)):
        result = jax.block_until_ready(fn(*args, **kwargs))
    best = float("inf")
    for _ in range(max(reps, 1)):
        t0 = time.perf_counter()
        result = jax.block_until_ready(fn(*args, **kwargs))
        best = min(best, time.perf_counter() - t0)
    return best, result


def fetch_staged(*arrays):
    """Completion-bound already-staged device arrays by fetching ONE element
    of each: on tunneled platforms ``block_until_ready`` can return while
    uploads are still in flight, and the unfinished H2D then bills to
    whatever timed span opens next — the memplus external host-span cell
    measured 86-100 s of leaked staging around a 0.4 s solve until every
    stage point was bounded this way. A buffer cannot serve any read before
    it is fully materialized, so a scalar fetch is a true completion signal
    at ~1 RTT cost. Returns the arrays unchanged (pytrees welcome)."""
    import numpy as np

    for a in arrays:
        for leaf in jax.tree.leaves(a):
            np.asarray(leaf[(0,) * leaf.ndim])
    return arrays


def timed_fetch(fn: Callable, *args, warmup: int = 1, reps: int = 1, **kwargs):
    """Like :func:`timed`, but bounds each span with an actual host fetch of
    the result (``np.asarray``), which is the only completion signal that
    cannot lie. Prefer for benchmarks; the fetched bytes should be small
    (return a scalar/vector from ``fn``, not the whole matrix, or the span
    measures tunnel bandwidth instead of compute)."""
    import numpy as np

    result = None
    for _ in range(max(warmup, 0)):
        result = jax.tree.map(np.asarray, fn(*args, **kwargs))
    best = float("inf")
    for _ in range(max(reps, 1)):
        t0 = time.perf_counter()
        result = jax.tree.map(np.asarray, fn(*args, **kwargs))
        best = min(best, time.perf_counter() - t0)
    return best, result
