"""Wall-clock timing spans with device-completion semantics.

The reference times with ``gettimeofday`` around the compute phase
(reference Pthreads/Version-1/gauss_internal_input.c:278-290) and
``clock_gettime`` per engine in CUDA (cuda_matmul.cu:135-180). On TPU,
dispatch is asynchronous, so an honest equivalent span must end with
``jax.block_until_ready`` on the results — every timer here does.
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List

import jax


@dataclass
class _Span:
    """Handle yielded by Timer.span; the body registers what to block on."""

    block: Any = None


@dataclass
class Timer:
    """Accumulates named wall-clock spans; used by the CLI and bench harness."""

    spans: Dict[str, List[float]] = field(default_factory=dict)

    @contextmanager
    def span(self, name: str):
        """Usage::

            with timer.span("solve") as s:
                s.block = gauss_solve(a, b)   # blocked on at span exit

        The handle is mutable so the value to block on can be produced inside
        the span body (a plain argument would be bound before the body runs).
        """
        handle = _Span()
        t0 = time.perf_counter()
        try:
            yield handle
        finally:
            if handle.block is not None:
                jax.block_until_ready(handle.block)
            self.spans.setdefault(name, []).append(time.perf_counter() - t0)

    def total(self, name: str) -> float:
        return sum(self.spans.get(name, []))

    def best(self, name: str) -> float:
        return min(self.spans[name])


def timed(fn: Callable, *args, warmup: int = 1, iters: int = 1, **kwargs):
    """Run ``fn`` with compile warmup; return (best_seconds, last_result).

    ``block_until_ready`` bounds every span so the number is device wall-clock,
    not dispatch time. Caveat: on tunneled device platforms (e.g. 'axon')
    block_until_ready has been observed to return early — use
    :func:`timed_fetch` there, which forces a device-to-host transfer.
    """
    result = None
    for _ in range(max(warmup, 0)):
        result = jax.block_until_ready(fn(*args, **kwargs))
    best = float("inf")
    for _ in range(max(iters, 1)):
        t0 = time.perf_counter()
        result = jax.block_until_ready(fn(*args, **kwargs))
        best = min(best, time.perf_counter() - t0)
    return best, result


def timed_fetch(fn: Callable, *args, warmup: int = 1, iters: int = 1, **kwargs):
    """Like :func:`timed`, but bounds each span with an actual host fetch of
    the result (``np.asarray``), which is the only completion signal that
    cannot lie. Prefer for benchmarks; the fetched bytes should be small
    (return a scalar/vector from ``fn``, not the whole matrix, or the span
    measures tunnel bandwidth instead of compute)."""
    import numpy as np

    result = None
    for _ in range(max(warmup, 0)):
        result = jax.tree.map(np.asarray, fn(*args, **kwargs))
    best = float("inf")
    for _ in range(max(iters, 1)):
        t0 = time.perf_counter()
        result = jax.tree.map(np.asarray, fn(*args, **kwargs))
        best = min(best, time.perf_counter() - t0)
    return best, result
