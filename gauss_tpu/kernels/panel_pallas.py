"""VMEM-resident panel factorization kernel for the blocked LU.

The blocked factorization (core.blocked) spends most of its time in the
unblocked panel factor: `panel` dependent pivot steps, each a rank-1 update of
the (h, panel) column block. Done in stock JAX, every step round-trips the
panel through HBM. This kernel runs *all* panel steps inside one Pallas
program with the panel held in VMEM (h * panel * 4 bytes — 1 MB at
n=2048/panel=128, comfortably under the ~16 MB budget), so the per-step
traffic never leaves the chip. This is the TPU analog of the reference
Version-2's block_size=16 cache tiling of the same loop
(reference Pthreads/Version-2/gauss_internal_input.c:162-173), at VMEM scale.

Layout is everything here. The panel is held TRANSPOSED in VMEM, shape
(panel, h): matrix rows live on the lane (minor) dimension. Then

- column j of the panel is sublane row j — one dynamically-indexed O(1) load
  per step instead of a lane-masked full-tile reduction;
- every per-column vector (candidates, multipliers, the done mask) is a
  (1, h) lane vector occupying h/1024 vregs, where the natural (h, 1)
  sublane layout would occupy h/8 vregs — a 128x difference that made
  "cheap vector ops" cost as much as full-tile passes in an earlier
  untransposed version of this kernel;
- the pivot row is lane p_idx — one masked full-tile reduction.

Pivoting is partial (masked argmax over the live column) with NO physical row
swaps: a `done` lane mask retires each chosen pivot row, and the permutation
is emitted as an inverse-position vector (`inv`: old row -> new position,
chosen pivots at kb+j in choice order, unchosen rows following in original
order). Any consistent permutation yields the same P A = L U — and the values
computed are identical to a swapping implementation because elimination math
never depends on storage order. The wrapper scatters `inv` into gather
indices (perm_local) and returns the factored panel already row-permuted,
getrf layout (multipliers below the diagonal, U on/above).

Per step only TWO full-tile passes touch the (panel, h) block: the pivot-row
extraction (lane-masked reduction) and the fused rank-1-update + column-j
store. Measured on v5e at h=2048, panel=128 this is ~3x faster than the
untransposed masked-select kernel it replaces.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from gauss_tpu.kernels.matmul_pallas import _auto_interpret


def _factor_body(kb, t_ref, out_ref, ipiv_ref, inv_ref, minpiv_ref,
                 chosen_ref, done_ref, mult_ref, pt_ref, *, h, panel, seg,
                 defer, record=False):
    """The panel-factor step loop, shared VERBATIM by :func:`_panel_kernel`
    and the fused panel+trailing kernel (kernels.panel_fused_pallas) — one
    op sequence, so the two kernels' factor outputs are bit-identical at
    matching (seg, defer) configs. ``kb`` is the already-read scalar row
    offset of the diagonal.

    ``record=True`` (the fused kernel's mode, classic segments only)
    additionally stores every step's multiplier lane vector and pivot
    one-hot into the (panel, h) ``mult_ref``/``pt_ref`` scratch — pure
    extra stores, the factor arithmetic is untouched — which the fused
    kernel's trailing phase then applies as rank-``fseg`` MXU updates
    without the factored panel ever leaving VMEM."""
    assert not (defer and record)
    out_ref[:] = t_ref[:]
    lanes = lax.broadcasted_iota(jnp.int32, (1, h), 1)
    inv_ref[:] = lax.broadcasted_iota(jnp.int32, (h, 1), 0)
    chosen_ref[:] = jnp.zeros((h, 1), jnp.int32)
    # Rows above the diagonal block are finished U rows: not pivotable.
    done_ref[:] = (lanes < kb).astype(jnp.int32)
    minpiv_ref[0] = jnp.asarray(jnp.inf, out_ref.dtype)
    dtype = out_ref.dtype
    zero = jnp.zeros((), dtype)
    neg_inf = jnp.asarray(-jnp.inf, dtype)

    # The per-step tile passes only need the LIVE columns j..panel — columns
    # left of j hold finished L multipliers and receive no further updates.
    # pl.ds sizes must be static, so the step loop is segmented at trace time.
    # Two forms (static `defer` flag):
    #  - defer=False: within segment [s0, s1) every pass touches the static
    #    slice [s0, panel), shrinking the touched tile from (panel, h) to an
    #    average of ~(panel/2 + seg/2, h) across the chain.
    #  - defer=True (the two-level scheme): per-step passes touch ONLY the
    #    (seg, h) sub-panel slice [s0, s1) — the serial VPU rank-1 work drops
    #    from O(panel^2/2 * h) to O(panel * seg * h) per panel — and the
    #    columns right of the sub-panel receive one deferred rank-seg MXU
    #    update per segment (see _deferred_update). This is the blocked-LU
    #    idea applied INSIDE the panel factorization: the decomposed n=2048
    #    budget showed the panel chain at 1.29 ms of a 2.0 ms factor, almost
    #    all of it these VPU passes (VERDICT r4 weak #5).
    def make_step(s0: int, s1: int):
        w = (s1 if defer else panel) - s0  # static live width this segment
        subs = s0 + lax.broadcasted_iota(jnp.int32, (w, 1), 0)

        def step(j, _):
            j = j.astype(jnp.int32)  # fori index is int64 under x64
            c = kb + j

            # Column j of the panel = sublane row j of the transposed block.
            col = out_ref[pl.ds(j, 1), :]  # (1, h)
            cand = jnp.where(done_ref[:] != 0, neg_inf, jnp.abs(col))
            p_idx = jnp.argmax(cand).astype(jnp.int32)
            ipiv_ref[j] = p_idx
            # inv/chosen are reconstructible from ipiv at the XLA level
            # (rows never move), but reconstructing them outside costs more
            # than these stores: scatter- and argsort-based wrappers measured
            # +0.4 ms per solve (round 2), and a one-hot-reduction rebuild
            # measured +19 us per call at h=2048 (round 5) vs keeping the
            # bookkeeping in-kernel.
            inv_ref[pl.ds(p_idx, 1), :] = jnp.full((1, 1), c, jnp.int32)
            chosen_ref[pl.ds(p_idx, 1), :] = jnp.ones((1, 1), jnp.int32)

            lane_p = lanes == p_idx
            T = out_ref[pl.ds(s0, w), :]
            # Pivot row = lane p_idx (live pass 1: lane-masked reduction).
            u = jnp.sum(jnp.where(lane_p, T, zero), axis=1, keepdims=True)
            # The pivot VALUE is row j of the extracted pivot row — a (w, 1)
            # sublane select instead of a second (1, h) lane reduction
            # (measured 16 us/call at h=2048).
            piv = jnp.sum(jnp.where(subs == j, u, zero))
            apiv = jnp.abs(piv)
            # A NaN pivot means a zero pivot already poisoned the trailing
            # rows; report it as singular (0), not NaN.
            minpiv_ref[0] = jnp.minimum(
                minpiv_ref[0], jnp.where(jnp.isnan(apiv), zero, apiv))
            done = (done_ref[:] != 0) | lane_p
            done_ref[:] = done.astype(jnp.int32)

            mult = jnp.where(done, zero, col / piv)  # (1, h); 0 on pivot+done
            if defer:
                # Per-step bookkeeping for the segment-end rank-seg update:
                # multiplier lane vector and the one-hot pivot lane, both at
                # the sub-panel-local row. (Lane p_idx of LATER trailing
                # columns still needs updates from steps < its choice; mult
                # is zero exactly on done lanes, so the deferred GEMM
                # reproduces the sequential updates bit-for-bit in exact
                # arithmetic.)
                jl = j - s0
                mult_ref[pl.ds(jl, 1), :] = mult
                pt_ref[pl.ds(jl, 1), :] = lane_p.astype(dtype)
            elif record:
                # Full-panel bookkeeping for the fused kernel's trailing
                # phase — stores only; the factor values are unchanged.
                mult_ref[pl.ds(j, 1), :] = mult
                pt_ref[pl.ds(j, 1), :] = lane_p.astype(dtype)
            upd = jnp.where(subs > j, u, zero)  # only original columns > j
            # Column-j store: done lanes (U above the diagonal) and the pivot
            # lane (the diagonal) keep their values; live lanes take
            # multipliers.
            row_j_new = jnp.where(done, col, col / piv)
            # Live pass 2: rank-1 update fused with the column-j store.
            out_ref[pl.ds(s0, w), :] = jnp.where(
                subs == j, row_j_new, T - upd * mult)
            return 0

        return step

    def deferred_update(s0: int, s1: int):
        """Apply the segment's seg accumulated rank-1 eliminations to the
        panel columns RIGHT of the sub-panel as MXU dots.

        With T0 the trailing slice at segment start, M (w, h) the stored
        multiplier vectors and PT (w, h) the stored one-hot pivot lanes:
        U0[c, i] = T0[c, p_i] (one-hot extraction — exact at HIGHEST, the
        6-pass split reconstructs each f32 exactly against a 1.0 operand),
        Lp[i, j] = M[i, p_j] (strictly upper: a pivot lane is done for every
        later step), and the sequential pivot-row values satisfy
        U = U0 - U @ Lp, i.e. U = U0 @ (I + Lp)^-1. The unit-triangular
        inverse is applied via the factored Neumann series
        (I + Lp)^-1 = (I - Lp)(I + Lp^2)(I + Lp^4)... — log2(seg) tiny
        (seg, seg) dots, no data-dependent loop. Then the rank-seg update
        lands as ONE (wt, w) x (w, h) MXU dot."""
        w = s1 - s0
        wt = panel - s1
        hi = lax.Precision.HIGHEST
        t0 = out_ref[pl.ds(s1, wt), :]             # (wt, h)
        m_blk = mult_ref[pl.ds(0, w), :]           # (w, h)
        pt = pt_ref[pl.ds(0, w), :]                # (w, h)
        dn = (((1,), (1,)), ((), ()))              # contract on the h axis
        u = lax.dot_general(t0, pt, dn, precision=hi,
                            preferred_element_type=dtype)       # U0 (wt, w)
        lp = lax.dot_general(m_blk, pt, dn, precision=hi,
                             preferred_element_type=dtype)      # (w, w)
        p2 = None
        e = 1
        while e < w:
            term = lp if e == 1 else p2
            corr = jnp.dot(u, term, precision=hi, preferred_element_type=dtype)
            u = u - corr if e == 1 else u + corr
            if e * 2 < w:
                p2 = jnp.dot(term, term, precision=hi,
                             preferred_element_type=dtype)
            e *= 2
        out_ref[pl.ds(s1, wt), :] = t0 - jnp.dot(
            u, m_blk, precision=hi, preferred_element_type=dtype)

    for s0 in range(0, panel, seg):
        s1 = min(s0 + seg, panel)
        lax.fori_loop(s0, s1, make_step(s0, s1), 0)
        if defer and s1 < panel:
            deferred_update(s0, s1)


def _panel_kernel(kb_ref, t_ref, out_ref, ipiv_ref, inv_ref, minpiv_ref,
                  chosen_ref, done_ref, *refs, h, panel, seg, defer):
    mult_ref, pt_ref = refs if defer else (None, None)
    _factor_body(kb_ref[0], t_ref, out_ref, ipiv_ref, inv_ref, minpiv_ref,
                 chosen_ref, done_ref, mult_ref, pt_ref, h=h, panel=panel,
                 seg=seg, defer=defer)


# Sub-panel segment width; see _panel_kernel (64 best on v5e). The value
# is the autotuner seed in tune.space (single source); a tuned store
# overrides it per (h-bucket, dtype) in panel_factor_pallas.
from gauss_tpu.tune.space import PANEL_SEG_SEED as DEFAULT_SEG


DEFER_WORKSET_FACTOR = 5  # empirical VMEM multiple of the block bytes for
# the deferred form: its segment-boundary dot_generals materialize
# transposed copies of the (wt, h) trailing slice whose size the simple
# block+scratch model misses entirely — (256-wide, h=4096, seg=32)
# reported 18.1 M scoped bytes against a 5.2 M block+scratch estimate and
# failed to compile on the chip. 5x the block admits every config that
# measured fast (h <= 2048 at panel 256) and excludes every observed OOM.


def defer_seg(h: int, panel: int, itemsize: int = 4) -> int:
    """Sub-panel width for the two-level (deferred-update) kernel form, or 0
    when only the classic form fits VMEM. The deferred form adds (seg, h)
    multiplier/pivot scratch AND large Mosaic transposition transients in
    its boundary dots (see DEFER_WORKSET_FACTOR), so its reach is far
    shorter than the classic form's; past it the classic segmented kernel
    — whose input is aliased into its output — runs to the HBM ceiling."""
    from gauss_tpu.core.blocked import DEFER_VMEM_BUDGET, panel_fits_vmem

    if not panel_fits_vmem(h, panel, itemsize):
        return 0
    if h * panel * itemsize * DEFER_WORKSET_FACTOR > DEFER_VMEM_BUDGET:
        return 0
    # 32 measured best on v5e at h=2048/panel=256 (170 us vs 220 at 64 and
    # 225 at 16: the per-step tile passes shrink with seg, the per-boundary
    # deferred-update dot chain grows as panel/seg — 32 is the saddle);
    # narrower panels take the widest seg that still leaves a sub-panel.
    return 32 if panel > 32 else 16 if panel > 16 else 0


@partial(jax.jit, static_argnames=("interpret", "seg", "defer"))
def panel_factor_pallas(p: jax.Array, kb: jax.Array,
                        interpret: bool | None = None,
                        seg: int | None = None,
                        defer: bool | None = None):
    """Factor one (h, panel) column block whose diagonal lives at global row
    offset ``kb``. Returns (factored_panel, ipiv, perm_local, min_abs_pivot):
    the panel comes back already row-permuted (getrf layout), ipiv holds the
    chosen pivot row (pre-permutation index) per step, perm_local (h,) is the
    permutation as gather indices, and min_abs_pivot is 0 for singular input.

    ``defer`` selects the two-level kernel form (per-step VPU passes confined
    to the seg-wide sub-panel, deferred rank-seg MXU updates to the rest of
    the panel — see _panel_kernel); None auto-resolves via :func:`defer_seg`.
    """
    interpret = _auto_interpret(interpret)
    h, panel = p.shape
    kb = jnp.asarray(kb, jnp.int32).reshape(1)
    itemsize = jnp.dtype(p.dtype).itemsize
    if defer is None:
        # Auto-resolve only in fully-auto mode: an EXPLICIT seg keeps the
        # classic form, whose segmented loop is bit-identical to the
        # single-segment kernel (a property tests rely on and the deferred
        # reordering intentionally gives up).
        if seg is None:
            auto_seg = defer_seg(h, panel, itemsize)
            defer = auto_seg > 0
            if defer:
                seg = auto_seg
        else:
            defer = False
    if seg is None:
        # Tuned store override for the classic form's segment width (the
        # deferred auto path above already picked its own seg); seed
        # default otherwise — zero behavior change without a store.
        from gauss_tpu.tune import apply as _tune

        seg = int(_tune.override("panel_kernel", h, "seg",
                                 dtype=str(jnp.dtype(p.dtype)))
                  or DEFAULT_SEG)
    if seg < 1:
        raise ValueError(f"seg must be >= 1, got {seg}")
    seg = min(seg, panel)
    if defer and seg >= panel:
        defer = False  # a single segment has no trailing columns to defer
    scratch = [pltpu.VMEM((1, h), jnp.int32)]
    if defer:
        scratch += [pltpu.VMEM((seg, h), p.dtype),
                    pltpu.VMEM((seg, h), p.dtype)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(1,),
        in_specs=[pl.BlockSpec((panel, h), lambda i, kb_ref: (0, 0))],
        out_specs=[
            pl.BlockSpec((panel, h), lambda i, kb_ref: (0, 0)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((h, 1), lambda i, kb_ref: (0, 0)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((h, 1), lambda i, kb_ref: (0, 0)),
        ],
        scratch_shapes=scratch,
    )
    out_t, ipiv, inv, minpiv, chosen = pl.pallas_call(
        partial(_panel_kernel, h=h, panel=panel, seg=seg, defer=defer),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((panel, h), p.dtype),
            jax.ShapeDtypeStruct((panel,), jnp.int32),
            jax.ShapeDtypeStruct((h, 1), jnp.int32),
            jax.ShapeDtypeStruct((1,), p.dtype),
            jax.ShapeDtypeStruct((h, 1), jnp.int32),
        ],
        # The transposed input IS the factored output's buffer: the kernel
        # copies t_ref into out_ref up front and never reads t_ref again,
        # so aliasing them (index 1 counts the scalar-prefetch operand)
        # removes one full (panel, h) block from the scoped-VMEM working
        # set — the h-ceiling roughly doubles for free (VERDICT r4 next
        # #5: in-kernel pivoting to the HBM ceiling). The barrier keeps the
        # operand a standalone buffer: when the factor loops' dynamic-slice
        # + transpose fused INTO the custom call, the operand materialized
        # in scoped VMEM alongside the output and the aliasing won nothing
        # (25.5 M for a 12.6 M block at (128, 24576) — both copies).
        input_output_aliases={1: 0},
        interpret=interpret,
    )(kb, lax.optimization_barrier(p.T))
    # Unchosen rows keep their original relative order after the pivots
    # (cumsum is not lowerable inside Mosaic, so the rank fill lives here).
    rows = jnp.arange(h, dtype=jnp.int32)
    unch = (rows >= kb[0]) & (chosen[:, 0] == 0)
    rank = jnp.cumsum(unch.astype(jnp.int32))  # 1-based at unchosen rows
    inv = jnp.where(unch, kb[0] + panel - 1 + rank, inv[:, 0])
    perm_local = jnp.zeros((h,), jnp.int32).at[inv].set(rows)
    return out_t.T[perm_local], ipiv, perm_local, minpiv[0]
