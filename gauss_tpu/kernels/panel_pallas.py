"""VMEM-resident panel factorization kernel for the blocked LU.

The blocked factorization (core.blocked) spends most of its time in the
unblocked panel factor: `panel` dependent pivot steps, each a rank-1 update of
the (h, panel) column block. Done in stock JAX, every step round-trips the
panel through HBM. This kernel runs *all* panel steps inside one Pallas
program with the panel held in VMEM (h * panel * 4 bytes — 1 MB at
n=2048/panel=128, comfortably under the ~16 MB budget), so the per-step
traffic never leaves the chip. This is the TPU analog of the reference
Version-2's block_size=16 cache tiling of the same loop
(reference Pthreads/Version-2/gauss_internal_input.c:162-173), at VMEM scale.

Layout is everything here. The panel is held TRANSPOSED in VMEM, shape
(panel, h): matrix rows live on the lane (minor) dimension. Then

- column j of the panel is sublane row j — one dynamically-indexed O(1) load
  per step instead of a lane-masked full-tile reduction;
- every per-column vector (candidates, multipliers, the done mask) is a
  (1, h) lane vector occupying h/1024 vregs, where the natural (h, 1)
  sublane layout would occupy h/8 vregs — a 128x difference that made
  "cheap vector ops" cost as much as full-tile passes in an earlier
  untransposed version of this kernel;
- the pivot row is lane p_idx — one masked full-tile reduction.

Pivoting is partial (masked argmax over the live column) with NO physical row
swaps: a `done` lane mask retires each chosen pivot row, and the permutation
is emitted as an inverse-position vector (`inv`: old row -> new position,
chosen pivots at kb+j in choice order, unchosen rows following in original
order). Any consistent permutation yields the same P A = L U — and the values
computed are identical to a swapping implementation because elimination math
never depends on storage order. The wrapper scatters `inv` into gather
indices (perm_local) and returns the factored panel already row-permuted,
getrf layout (multipliers below the diagonal, U on/above).

Per step only TWO full-tile passes touch the (panel, h) block: the pivot-row
extraction (lane-masked reduction) and the fused rank-1-update + column-j
store. Measured on v5e at h=2048, panel=128 this is ~3x faster than the
untransposed masked-select kernel it replaces.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from gauss_tpu.kernels.matmul_pallas import _auto_interpret


def _panel_kernel(kb_ref, t_ref, out_ref, ipiv_ref, inv_ref, minpiv_ref,
                  chosen_ref, done_ref, *, h, panel, seg):
    kb = kb_ref[0]
    out_ref[:] = t_ref[:]
    lanes = lax.broadcasted_iota(jnp.int32, (1, h), 1)
    inv_ref[:] = lax.broadcasted_iota(jnp.int32, (h, 1), 0)
    chosen_ref[:] = jnp.zeros((h, 1), jnp.int32)
    # Rows above the diagonal block are finished U rows: not pivotable.
    done_ref[:] = (lanes < kb).astype(jnp.int32)
    minpiv_ref[0] = jnp.asarray(jnp.inf, out_ref.dtype)
    dtype = out_ref.dtype
    zero = jnp.zeros((), dtype)
    neg_inf = jnp.asarray(-jnp.inf, dtype)

    # The per-step tile passes only need the LIVE columns j..panel — columns
    # left of j hold finished L multipliers and receive no further updates.
    # pl.ds sizes must be static, so the step loop is segmented at trace time:
    # within segment [s0, s1) every pass touches the static slice [s0, panel)
    # of the sublane (column) axis, shrinking the touched tile from
    # (panel, h) to an average of ~(panel/2 + seg/2, h) across the chain.
    def make_step(s0: int):
        w = panel - s0  # static live width for this segment
        subs = s0 + lax.broadcasted_iota(jnp.int32, (w, 1), 0)

        def step(j, _):
            j = j.astype(jnp.int32)  # fori index is int64 under x64
            c = kb + j

            # Column j of the panel = sublane row j of the transposed block.
            col = out_ref[pl.ds(j, 1), :]  # (1, h)
            cand = jnp.where(done_ref[:] != 0, neg_inf, jnp.abs(col))
            p_idx = jnp.argmax(cand).astype(jnp.int32)
            ipiv_ref[j] = p_idx
            # inv/chosen are reconstructible from ipiv at the XLA level
            # (rows never move), but reconstructing them outside costs more
            # than these stores: measured on v5e at n=2048, scatter- or
            # onehot+argsort-based wrappers were +0.4 ms per solve vs
            # keeping the bookkeeping in-kernel.
            inv_ref[pl.ds(p_idx, 1), :] = jnp.full((1, 1), c, jnp.int32)
            chosen_ref[pl.ds(p_idx, 1), :] = jnp.ones((1, 1), jnp.int32)

            lane_p = lanes == p_idx
            piv = jnp.sum(jnp.where(lane_p, col, zero))
            apiv = jnp.abs(piv)
            # A NaN pivot means a zero pivot already poisoned the trailing
            # rows; report it as singular (0), not NaN.
            minpiv_ref[0] = jnp.minimum(
                minpiv_ref[0], jnp.where(jnp.isnan(apiv), zero, apiv))
            done = (done_ref[:] != 0) | lane_p
            done_ref[:] = done.astype(jnp.int32)

            mult = jnp.where(done, zero, col / piv)  # (1, h); 0 on pivot+done
            T = out_ref[pl.ds(s0, w), :]
            # Pivot row = lane p_idx (live pass 1: lane-masked reduction).
            u = jnp.sum(jnp.where(lane_p, T, zero), axis=1, keepdims=True)
            upd = jnp.where(subs > j, u, zero)  # only original columns > j
            # Column-j store: done lanes (U above the diagonal) and the pivot
            # lane (the diagonal) keep their values; live lanes take
            # multipliers.
            row_j_new = jnp.where(done, col, col / piv)
            # Live pass 2: rank-1 update fused with the column-j store.
            out_ref[pl.ds(s0, w), :] = jnp.where(
                subs == j, row_j_new, T - upd * mult)
            return 0

        return step

    for s0 in range(0, panel, seg):
        lax.fori_loop(s0, min(s0 + seg, panel), make_step(s0), 0)


DEFAULT_SEG = 64  # sub-panel segment width; see _panel_kernel (64 best on v5e)


@partial(jax.jit, static_argnames=("interpret", "seg"))
def panel_factor_pallas(p: jax.Array, kb: jax.Array,
                        interpret: bool | None = None,
                        seg: int | None = None):
    """Factor one (h, panel) column block whose diagonal lives at global row
    offset ``kb``. Returns (factored_panel, ipiv, perm_local, min_abs_pivot):
    the panel comes back already row-permuted (getrf layout), ipiv holds the
    chosen pivot row (pre-permutation index) per step, perm_local (h,) is the
    permutation as gather indices, and min_abs_pivot is 0 for singular input.
    """
    interpret = _auto_interpret(interpret)
    h, panel = p.shape
    kb = jnp.asarray(kb, jnp.int32).reshape(1)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(1,),
        in_specs=[pl.BlockSpec((panel, h), lambda i, kb_ref: (0, 0))],
        out_specs=[
            pl.BlockSpec((panel, h), lambda i, kb_ref: (0, 0)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((h, 1), lambda i, kb_ref: (0, 0)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((h, 1), lambda i, kb_ref: (0, 0)),
        ],
        scratch_shapes=[pltpu.VMEM((1, h), jnp.int32)],
    )
    seg = DEFAULT_SEG if seg is None else seg
    if seg < 1:
        raise ValueError(f"seg must be >= 1, got {seg}")
    seg = min(seg, panel)
    out_t, ipiv, inv, minpiv, chosen = pl.pallas_call(
        partial(_panel_kernel, h=h, panel=panel, seg=seg),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((panel, h), p.dtype),
            jax.ShapeDtypeStruct((panel,), jnp.int32),
            jax.ShapeDtypeStruct((h, 1), jnp.int32),
            jax.ShapeDtypeStruct((1,), p.dtype),
            jax.ShapeDtypeStruct((h, 1), jnp.int32),
        ],
        interpret=interpret,
    )(kb, p.T)
    # Unchosen rows keep their original relative order after the pivots
    # (cumsum is not lowerable inside Mosaic, so the rank fill lives here).
    rows = jnp.arange(h, dtype=jnp.int32)
    unch = (rows >= kb[0]) & (chosen[:, 0] == 0)
    rank = jnp.cumsum(unch.astype(jnp.int32))  # 1-based at unchosen rows
    inv = jnp.where(unch, kb[0] + panel - 1 + rank, inv[:, 0])
    perm_local = jnp.zeros((h,), jnp.int32).at[inv].set(rows)
    return out_t.T[perm_local], ipiv, perm_local, minpiv[0]
