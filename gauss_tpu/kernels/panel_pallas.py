"""VMEM-resident panel factorization kernel for the blocked LU.

The blocked factorization (core.blocked) spends most of its time in the
unblocked panel factor: `panel` dependent pivot steps, each a rank-1 update of
the (npad, panel) column block. Done in stock JAX, every step round-trips the
panel through HBM. This kernel runs *all* panel steps inside one Pallas
program with the panel held in VMEM (npad * panel * 4 bytes — 1 MB at
n=2048/panel=128, comfortably under the ~16 MB budget), so the per-step
traffic never leaves the chip. This is the TPU analog of the reference
Version-2's block_size=16 cache tiling of the same loop
(reference Pthreads/Version-2/gauss_internal_input.c:162-173), at VMEM scale.

Outputs: the factored panel (getrf layout: multipliers below the diagonal,
U on/above), the per-step pivot-row indices (ipiv, int32, in SMEM), and the
*folded* local permutation (perm_local, int32): the composition of the panel's
``panel`` sequential row swaps as gather indices, computed in VMEM alongside
the factorization. Folding here matters: done at the XLA level it is a
``panel``-step fori_loop of tiny scatters per panel — measured 6.3 ms of an
11 ms n=2048 factorization on v5e, more than the panel math itself — whereas
in-kernel it is two extra (npad, 1) selects per already-running step.
Partial pivoting happens inside the kernel: masked argmax over the live
column, then a two-row swap via dynamically-indexed sublane loads/stores.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from gauss_tpu.kernels.matmul_pallas import _auto_interpret


def _panel_kernel(kb_ref, p_ref, out_ref, ipiv_ref, pfold_ref, *, npad, panel):
    # Mosaic cannot lower dynamically-positioned single-row/column slices
    # (lane-dim indices must be static multiples of 128), so every per-step
    # extraction and update below is a masked full-tile VPU op: column j via a
    # lane-masked row-sum, rows c/p via sublane-masked column-sums, the swap
    # and multiplier store via selects. Each step is a handful of full-tile
    # passes over VMEM — that traffic never touches HBM, which is the point.
    kb = kb_ref[0]
    out_ref[:] = p_ref[:]
    rows = lax.broadcasted_iota(jnp.int32, (npad, 1), 0)
    pfold_ref[:] = rows
    cols = lax.broadcasted_iota(jnp.int32, (1, panel), 1)
    dtype = out_ref.dtype
    zero = jnp.zeros((), dtype)
    neg_inf = jnp.asarray(-jnp.inf, dtype)

    def step(j, _):
        j = j.astype(jnp.int32)  # fori index is int64 under x64
        c = kb + j
        P = out_ref[:]
        lane_j = cols == j  # (1, panel)

        # Pivot selection on column j.
        col = jnp.sum(jnp.where(lane_j, P, zero), axis=1, keepdims=True)
        cand = jnp.where(rows >= c, jnp.abs(col), neg_inf)
        p_idx = jnp.argmax(cand[:, 0]).astype(jnp.int32)
        ipiv_ref[j] = p_idx

        # Two-row swap via masked selects (no-op when p_idx == c).
        mask_c = rows == c      # (npad, 1)
        mask_p = rows == p_idx
        row_c = jnp.sum(jnp.where(mask_c, P, zero), axis=0, keepdims=True)
        row_p = jnp.sum(jnp.where(mask_p, P, zero), axis=0, keepdims=True)
        P = jnp.where(mask_c, row_p, jnp.where(mask_p, row_c, P))

        # Mirror the swap into the folded permutation vector.
        pv = pfold_ref[:]
        v_c = jnp.sum(jnp.where(mask_c, pv, 0), axis=0, keepdims=True)
        v_p = jnp.sum(jnp.where(mask_p, pv, 0), axis=0, keepdims=True)
        pfold_ref[:] = jnp.where(mask_c, v_p, jnp.where(mask_p, v_c, pv))

        piv = jnp.sum(jnp.where(lane_j, row_p, zero))
        col2 = jnp.sum(jnp.where(lane_j, P, zero), axis=1, keepdims=True)
        mult = jnp.where(rows > c, col2 / piv, zero)

        # Rank-1 update right of column j, then store the multipliers into
        # column j itself (getrf layout).
        urow = jnp.where(cols > j, row_p, zero)
        P = P - mult * urow
        P = jnp.where(lane_j, jnp.where(rows > c, mult, col2), P)
        out_ref[:] = P
        return 0

    lax.fori_loop(0, panel, step, 0)


@partial(jax.jit, static_argnames=("interpret",))
def panel_factor_pallas(p: jax.Array, kb: jax.Array,
                        interpret: bool | None = None):
    """Factor one (npad, panel) column block whose diagonal lives at global
    row offset ``kb``. Returns (factored_panel, ipiv, perm_local) where
    perm_local (npad,) is the panel's swaps folded into gather indices."""
    interpret = _auto_interpret(interpret)
    npad, panel = p.shape
    kb = jnp.asarray(kb, jnp.int32).reshape(1)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(1,),
        in_specs=[pl.BlockSpec((npad, panel), lambda i, kb_ref: (0, 0))],
        out_specs=[
            pl.BlockSpec((npad, panel), lambda i, kb_ref: (0, 0)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((npad, 1), lambda i, kb_ref: (0, 0)),
        ],
    )
    out, ipiv, pfold = pl.pallas_call(
        partial(_panel_kernel, npad=npad, panel=panel),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((npad, panel), p.dtype),
            jax.ShapeDtypeStruct((panel,), jnp.int32),
            jax.ShapeDtypeStruct((npad, 1), jnp.int32),
        ],
        interpret=interpret,
    )(kb, p)
    return out, ipiv, pfold[:, 0]
