"""Hand-written Pallas TPU kernels (reference CUDA engines, re-tiled for MXU/VPU).

- matmul_pallas: output-tile-per-program tiled matmul — the MXU re-expression
  of CUDA Version-2's one-thread-per-cell grid (reference
  CUDA_and_OpenMP/Version-2/cuda_matmul.cu:89-101).
- rowelim_pallas: one pivot step (pivot-row broadcast + masked per-row SAXPY)
  over an HBM-resident matrix, tiled to VMEM — the BASELINE.json north-star
  kernel and the analog of the reference's subtractElim hot loop.
- panel_pallas: VMEM-resident panel factorization driving the blocked LU's
  inner loop without per-step HBM round trips.

All kernels accept ``interpret=`` for CPU-interpreter execution (how the test
suite runs them without a TPU); ``None`` auto-selects based on the backend.
"""

from gauss_tpu.kernels.matmul_pallas import matmul_pallas, matmul_pallas_stripe  # noqa: F401
from gauss_tpu.kernels.panel_pallas import panel_factor_pallas  # noqa: F401
from gauss_tpu.kernels.rowelim_pallas import eliminate_step_pallas, gauss_solve_rowelim  # noqa: F401
